"""Regenerate the committed client-size histograms under benchmarks/.

Default mode: benchmarks/northstar_client_sizes.json — the per-client
sample histogram of the north-star bench partition, consumed by the
PERF003 padding-waste lint (fedml_tpu/analysis/perf) so `fedml lint
--perf` can audit the size-bucket policy without touching the dataset.

``--hyperscale [N]`` mode: benchmarks/hyperscale_client_sizes.json — a
heavy-tailed (bounded-Pareto, Zipf-ish) population of N clients
(default 100k) for the hyper-scale streaming bench and its PERF003
audit.  The bucket-cap policy of record is asserted to hold ≥99% slot
utilization on the scaled histogram before the file is written.

Deterministic: the default histogram depends only on the committed
synthetic-CIFAR generator (gen_northstar_cifar.py, DATA_VERSION) and the
seeded Dirichlet(0.5) partition; the hyperscale histogram only on the
counter-based `zipf_sizes` generator — re-running after a data-version
or generator change is the only time these files change.
"""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

NPZ = os.path.join(ROOT, ".data_cache", "northstar", "cifar10.npz")
OUT = os.path.join(HERE, "northstar_client_sizes.json")
OUT_HYPER = os.path.join(HERE, "hyperscale_client_sizes.json")

# the hyperscale bench's policy of record (bench.py --hyperscale and the
# streaming entrypoint's PERF003 audit read exactly these knobs)
HYPER_POLICY = {
    "client_num_per_round": 1024,
    "batch_size": 32,
    "hetero_buckets": 32,
    "hetero_bucket_cap": 0.6,
    "zipf_exponent": 1.2,
    "min_size": 64,
    "max_size": 4096,
}


def main() -> None:
    import numpy as np

    if not os.path.exists(NPZ):
        subprocess.run([sys.executable,
                        os.path.join(HERE, "gen_northstar_cifar.py")],
                       check=True)
    with np.load(NPZ) as z:
        y = z["y_train"]
        meta = str(z["meta"][0])
    from fedml_tpu.data.partition import partition

    m = partition(y if y.ndim == 1 else y[:, 0], 100, "hetero", 0.5, 0)
    sizes = [int(len(m[c])) for c in range(100)]
    payload = {
        "description": "Per-client sample counts of the north-star bench "
                       "partition (benchmarks/gen_northstar_cifar.py npz, "
                       "Dirichlet(0.5), 100 clients, seed 0) — consumed "
                       "by the PERF003 padding-waste lint and regenerable "
                       "with benchmarks/gen_northstar_client_sizes.py",
        "dataset": "cifar10_northstar",
        "data_version": meta,
        "partition_method": "hetero",
        "partition_alpha": 0.5,
        "random_seed": 0,
        "client_num_in_total": 100,
        "client_num_per_round": 10,
        "batch_size": 32,
        "hetero_buckets": 10,
        # the bench's bucket-cap policy of record (bench.py
        # hetero_bucket_cap) — PERF003 audits bucket_plan under exactly
        # this policy, so a bench-side change must be mirrored here
        "hetero_bucket_cap": 0.8,
        "sizes": sizes,
    }
    with open(OUT, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps({"out": OUT, "n": sum(sizes)}))


def main_hyperscale(n_clients: int) -> None:
    import numpy as np

    from fedml_tpu.data.population import size_hist, zipf_sizes
    from fedml_tpu.simulation.parrot.parrot_api import bucket_plan

    p = HYPER_POLICY
    sizes = zipf_sizes(n_clients, seed=0, exponent=p["zipf_exponent"],
                       min_size=p["min_size"], max_size=p["max_size"])
    plan = bucket_plan(np.asarray(sizes), p["client_num_per_round"],
                       p["batch_size"], p["hetero_buckets"],
                       p["hetero_bucket_cap"])
    padded = sum(b["padded"] for b in plan)
    real = sum(b["real"] for b in plan)
    util = real / padded
    assert util >= 0.99, (
        f"bucket-cap policy holds only {util:.4f} slot utilization on the "
        f"scaled histogram (need >=0.99) — retune HYPER_POLICY")
    payload = {
        "description": "Heavy-tailed (bounded-Pareto) per-client sample "
                       "counts for the hyper-scale streaming bench "
                       "(bench.py --hyperscale) and its PERF003 padding "
                       "audit, histogram-encoded as ascending "
                       "[size, count] pairs (decode with "
                       "fedml_tpu.data.population.expand_size_hist; "
                       "bucket stats are a function of the multiset, so "
                       "they match the dense form exactly) — regenerable "
                       "with gen_northstar_client_sizes.py --hyperscale",
        "generator": "fedml_tpu.data.population.zipf_sizes",
        "random_seed": 0,
        "client_num_in_total": n_clients,
        **p,
        "slot_utilization": round(util, 4),
        "size_hist": size_hist(sizes),
    }
    with open(OUT_HYPER, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps({"out": OUT_HYPER, "n_clients": n_clients,
                      "total_samples": int(sizes.sum()),
                      "slot_utilization": round(util, 4)}))


if __name__ == "__main__":
    if "--hyperscale" in sys.argv:
        i = sys.argv.index("--hyperscale")
        n = (int(sys.argv[i + 1]) if len(sys.argv) > i + 1
             and sys.argv[i + 1].isdigit() else 100_000)
        main_hyperscale(n)
    else:
        main()
