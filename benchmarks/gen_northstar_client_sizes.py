"""Regenerate benchmarks/northstar_client_sizes.json — the per-client
sample histogram of the north-star bench partition, consumed by the
PERF003 padding-waste lint (fedml_tpu/analysis/perf) so `fedml lint
--perf` can audit the size-bucket policy without touching the dataset.

Deterministic: the histogram depends only on the committed synthetic-CIFAR
generator (gen_northstar_cifar.py, DATA_VERSION) and the seeded
Dirichlet(0.5) partition, so re-running after a data-version bump is the
only time this file changes.
"""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

NPZ = os.path.join(ROOT, ".data_cache", "northstar", "cifar10.npz")
OUT = os.path.join(HERE, "northstar_client_sizes.json")


def main() -> None:
    import numpy as np

    if not os.path.exists(NPZ):
        subprocess.run([sys.executable,
                        os.path.join(HERE, "gen_northstar_cifar.py")],
                       check=True)
    with np.load(NPZ) as z:
        y = z["y_train"]
        meta = str(z["meta"][0])
    from fedml_tpu.data.partition import partition

    m = partition(y if y.ndim == 1 else y[:, 0], 100, "hetero", 0.5, 0)
    sizes = [int(len(m[c])) for c in range(100)]
    payload = {
        "description": "Per-client sample counts of the north-star bench "
                       "partition (benchmarks/gen_northstar_cifar.py npz, "
                       "Dirichlet(0.5), 100 clients, seed 0) — consumed "
                       "by the PERF003 padding-waste lint and regenerable "
                       "with benchmarks/gen_northstar_client_sizes.py",
        "dataset": "cifar10_northstar",
        "data_version": meta,
        "partition_method": "hetero",
        "partition_alpha": 0.5,
        "random_seed": 0,
        "client_num_in_total": 100,
        "client_num_per_round": 10,
        "batch_size": 32,
        "hetero_buckets": 10,
        # the bench's bucket-cap policy of record (bench.py
        # hetero_bucket_cap) — PERF003 audits bucket_plan under exactly
        # this policy, so a bench-side change must be mirrored here
        "hetero_bucket_cap": 0.8,
        "sizes": sizes,
    }
    with open(OUT, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps({"out": OUT, "n": sum(sizes)}))


if __name__ == "__main__":
    main()
