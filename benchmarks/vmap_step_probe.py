"""Cost of ONE vmapped local-SGD step vs client count (the real hot path).

Uses the production build_local_update on ResNet-56 with a single padded
batch (nb=1) and measures wall per jitted call for K in {1,2,5,10}
vmapped clients.  If per-call cost grows faster than K, the vmapped
(grouped-conv) lowering is the bottleneck and fewer clients per bucket
win; if it grows slower than K, bigger buckets win.

Prints one JSON line per K.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import fedml_tpu
from fedml_tpu.ml.engine.local_update import build_local_update

BS = 32
ITERS = 30


def main():
    args = fedml_tpu.Config(model="resnet56", dataset="cifar10",
                            compute_dtype="bfloat16", learning_rate=0.05,
                            epochs=1)
    bundle = fedml_tpu.model.create(args, 10)
    variables = bundle.init_variables(jax.random.PRNGKey(0), batch_size=8)
    local_update = build_local_update(bundle, args)
    rng = np.random.RandomState(0)

    for k in (1, 2, 5, 10):
        batches = {
            "x": jnp.asarray(rng.randn(k, 1, BS, 32, 32, 3), jnp.bfloat16),
            "y": jnp.asarray(rng.randint(0, 10, (k, 1, BS)), jnp.int32),
            "mask": jnp.ones((k, 1, BS), jnp.float32),
        }
        rngs = jax.random.split(jax.random.PRNGKey(1), k)
        step = jax.jit(jax.vmap(local_update, in_axes=(None, 0, 0, None)))
        out = step(variables, batches, rngs, None)
        float(out[2]["train_loss"][0])   # axon: force scalar transfer
        t0 = time.time()
        for _ in range(ITERS):
            out = step(variables, batches, rngs, None)
            float(out[2]["train_loss"][0])
        ms = (time.time() - t0) / ITERS * 1e3
        print(json.dumps({"k_clients": k, "ms_per_step": round(ms, 2),
                          "ms_per_client_step": round(ms / k, 3),
                          "samples_per_sec": round(k * BS / ms * 1e3, 1)}))


if __name__ == "__main__":
    main()


def probe_nb(k=5, nb=8):
    """Does per-batch cost stay flat as the in-client scan lengthens?"""
    args = fedml_tpu.Config(model="resnet56", dataset="cifar10",
                            compute_dtype="bfloat16", learning_rate=0.05,
                            epochs=1)
    bundle = fedml_tpu.model.create(args, 10)
    variables = bundle.init_variables(jax.random.PRNGKey(0), batch_size=8)
    local_update = build_local_update(bundle, args)
    rng = np.random.RandomState(0)
    batches = {
        "x": jnp.asarray(rng.randn(k, nb, BS, 32, 32, 3), jnp.bfloat16),
        "y": jnp.asarray(rng.randint(0, 10, (k, nb, BS)), jnp.int32),
        "mask": jnp.ones((k, nb, BS), jnp.float32),
    }
    rngs = jax.random.split(jax.random.PRNGKey(1), k)
    step = jax.jit(jax.vmap(local_update, in_axes=(None, 0, 0, None)))
    out = step(variables, batches, rngs, None)
    float(out[2]["train_loss"][0])       # axon: force scalar transfer
    t0 = time.time()
    iters = max(4, ITERS // nb)
    for _ in range(iters):
        out = step(variables, batches, rngs, None)
        float(out[2]["train_loss"][0])
    ms = (time.time() - t0) / iters * 1e3
    print(json.dumps({"k_clients": k, "nb": nb,
                      "ms_per_step": round(ms, 2),
                      "ms_per_batch_step": round(ms / nb, 3),
                      "samples_per_sec": round(k * nb * BS / ms * 1e3, 1)}))
