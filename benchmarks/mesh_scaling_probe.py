"""Bucketed-Parrot mesh scaling probe (VERDICT r3 item 1 evidence).

Runs the SAME total work (bucketed hetero rounds, fused chunk) on a
1-device mesh and an N-device virtual CPU mesh and reports steady-state
round times.  On this box the virtual devices share ONE physical core, so
wall-clock parity (not speedup) is the expected outcome; the point of the
probe is (a) the sharded program partitions and executes, (b) the numbers
land in BENCH_NOTES so a multi-core/multi-chip host can re-run it and see
the scaling.  The HARD multi-chip evidence is
tests/test_parrot.py::test_bucketed_mesh_compiles_collectives (compiled
HLO carries all-reduce) and the driver dryrun.

Usage:  python benchmarks/mesh_scaling_probe.py [n_devices]
"""

import json
import os
import sys
import time

N = int(sys.argv[1]) if len(sys.argv) > 1 else 8
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + f" --xla_force_host_platform_device_count={N}"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import fedml_tpu  # noqa: E402
from fedml_tpu.simulation.parrot.parrot_api import ParrotAPI  # noqa: E402

ROUNDS = 12


def build(mesh_clients, use_mesh):
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="cifar10", model="cnn", backend="mesh",
        partition_method="hetero", partition_alpha=0.5,
        hetero_buckets=2, mesh_shape={"clients": mesh_clients},
        client_num_in_total=8, client_num_per_round=4, comm_round=ROUNDS,
        epochs=1, batch_size=8, data_scale=0.05, frequency_of_the_test=100,
        enable_tracking=False, compute_dtype="float32"))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return ParrotAPI(args, device, dataset, bundle, use_mesh=use_mesh)


def steady_rate(api):
    # time the per-round jitted step directly (the fused 64-round scan is
    # the TPU fast path; its CPU compile dominates wall-clock on this
    # 1-core box and would swamp the comparison)
    rng = jax.random.PRNGKey(0)
    step = api.bucketed_round_step
    gv, st = api.global_vars, api.server_state
    for _ in range(2):                       # compile + warm
        rng, sub = jax.random.split(rng)
        gv, st, rm = step(api.device_data, gv, st, sub)
    jax.block_until_ready(rm["train_loss"])
    t0 = time.time()
    for _ in range(ROUNDS):
        rng, sub = jax.random.split(rng)
        gv, st, rm = step(api.device_data, gv, st, sub)
    jax.block_until_ready(rm["train_loss"])
    return ROUNDS / (time.time() - t0)


if __name__ == "__main__":
    r1 = steady_rate(build(1, use_mesh=False))
    rN = steady_rate(build(N, use_mesh=True))
    out = {"metric": "bucketed_parrot_rounds_per_sec",
           "devices_1_unsharded": round(r1, 3),
           f"devices_{N}_sharded": round(rN, 3),
           "ratio": round(rN / r1, 3),
           "host_cores": os.cpu_count(),
           "note": ("virtual CPU devices share the physical cores; "
                    "expect ~parity on a 1-core host — partitioning "
                    "correctness is asserted by the HLO-collective tests")}
    print(json.dumps(out))
