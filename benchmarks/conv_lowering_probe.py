"""How should per-client convs lower on the MXU? (VERDICT r3 item 2)

The Parrot hot path vmaps local SGD over clients; after the first step
every client has its OWN weights, so jax's conv batching rule lowers
vmapped convs to feature_group_count=K grouped convolutions.  The mfu
probe showed grouped lowering is SLOWER per sample than running clients
one at a time — this microbench quantifies the alternatives on the three
ResNet-56 stage shapes:

  seq      — K sequential plain convs, batch 32 (what 10 buckets of 1 do)
  grouped  — one vmapped conv, per-client weights (XLA grouped lowering)
  patches  — im2col (conv_general_dilated_patches) + einsum: under vmap
             this is a BATCHED MATMUL, the MXU-native form
  shared   — one conv at batch K*32 with shared weights (upper bound)

Prints one JSON line per (stage, variant): {stage, variant, us_per_step,
samples_per_sec}.
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

K = 10       # clients
BS = 32
STAGES = [   # (H, W, Cin, Cout) — ResNet-56 stage conv shapes
    (32, 32, 16, 16),
    (16, 16, 32, 32),
    (8, 8, 64, 64),
]
DT = jnp.bfloat16


def conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def patches_conv(x, w):
    """im2col + matmul: identical math to conv(), but under vmap the
    contraction stays a plain (batched) matmul instead of a grouped conv."""
    kh, kw, cin, cout = w.shape
    p = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))    # [N,H,W,cin*kh*kw]
    return jnp.einsum("nhwp,pc->nhwc", p,
                      w.transpose(2, 0, 1, 3).reshape(-1, cout))


def bench(fn, *args, iters=50):
    out = fn(*args)
    float(jnp.sum(out))   # axon: force a scalar transfer (BENCH_NOTES r3)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    float(jnp.sum(out))
    return (time.time() - t0) / iters


def main():
    rng = np.random.RandomState(0)
    for (h, w_, cin, cout) in STAGES:
        x1 = jnp.asarray(rng.randn(BS, h, w_, cin), DT)
        xk = jnp.asarray(rng.randn(K, BS, h, w_, cin), DT)
        wk = jnp.asarray(rng.randn(K, 3, 3, cin, cout) * 0.1, DT)
        w1 = wk[0]
        xs = jnp.asarray(rng.randn(K * BS, h, w_, cin), DT)

        @jax.jit
        def seq(xk, wk):
            outs = [conv(xk[i], wk[i]) for i in range(K)]
            return jnp.stack(outs)

        grouped = jax.jit(jax.vmap(conv))
        patches_v = jax.jit(jax.vmap(patches_conv))
        shared = jax.jit(conv)
        patches_1 = jax.jit(patches_conv)

        stage = f"{h}x{w_}x{cin}->{cout}"
        for name, f, a in [
            ("seq", seq, (xk, wk)),
            ("grouped", grouped, (xk, wk)),
            ("patches", patches_v, (xk, wk)),
            ("shared", shared, (xs, w1)),
            ("patches_1client", patches_1, (x1, w1)),
            ("conv_1client", shared, (x1, w1)),
        ]:
            us = bench(f, *a) * 1e6
            n = K * BS if name not in ("patches_1client",
                                       "conv_1client") else BS
            print(json.dumps({"stage": stage, "variant": name,
                              "us_per_step": round(us, 1),
                              "msamples_per_sec": round(n / us, 3)}))


if __name__ == "__main__":
    main()
