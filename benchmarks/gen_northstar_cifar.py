"""Generate the canonical full-size synthetic CIFAR-10 for the north-star
benchmark (50k train / 10k test, uint8 npz) shared byte-identically by the
reference CPU anchor run and fedml_tpu's bench.py.

Zero-egress stand-in for real CIFAR-10 (no download possible); same
class-template+noise construction as fedml_tpu's synthetic fallback
(`fedml_tpu/data/datasets.py:_synthetic_images`) but written once to disk so
both frameworks consume identical bytes.
"""

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, ".data_cache", "northstar")
#: bumped whenever the construction changes; bench.py regenerates any
#: cached npz whose meta marker doesn't match (a stale pre-hard cache
#: would silently run the bench on saturating data)
DATA_VERSION = "hard_v2"


def main(seed: int = 0, n_train: int = 50_000, n_test: int = 10_000) -> None:
    sys.path.insert(0, REPO)
    from fedml_tpu.data.datasets import _synthetic_images

    # hard=True: class mixing + affine/intensity jitter + train label
    # noise, so the ResNet-56 plateau lands below 1.0 (real-CIFAR-like)
    # and the bench accuracy guard is real evidence (VERDICT r3 item 4)
    xt, yt, xe, ye = _synthetic_images((32, 32, 3), 10, n_train, n_test,
                                       seed, hard=True)
    os.makedirs(OUT_DIR, exist_ok=True)
    np.savez(os.path.join(OUT_DIR, "cifar10.npz"),
             x_train=(xt * 255).astype(np.uint8), y_train=yt.astype(np.int64),
             x_test=(xe * 255).astype(np.uint8), y_test=ye.astype(np.int64),
             meta=np.array([DATA_VERSION]))
    print(json.dumps({"out": os.path.join(OUT_DIR, "cifar10.npz"),
                      "n_train": n_train, "n_test": n_test, "seed": seed}))


if __name__ == "__main__":
    main()
