"""MFU probe on the real TPU (VERDICT r3 item 2 evidence).

One invocation = one north-star config variant (bucket count via argv),
so the persistent compilation cache's cross-process behavior is measured
for free: the first run of a config pays the compile, a re-run should
hit the cache (if the axon PJRT plugin supports it).

Prints one JSON line: {buckets, compile_s, rounds_per_sec,
padded_samples_per_round, samples_per_sec, est_mfu}.

Usage:  python benchmarks/mfu_probe.py <n_buckets> [--no-cache]
"""

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

_pos = [a for a in sys.argv[1:] if not a.startswith("--")]
N_BUCKETS = int(_pos[0]) if _pos else 4
USE_CACHE = "--no-cache" not in sys.argv
CONV_IMPL = "patches" if "--patches" in sys.argv else "lax"
NPZ_DIR = os.path.join(REPO, ".data_cache", "northstar")

import jax  # noqa: E402

if USE_CACHE:
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

import numpy as np  # noqa: E402

import fedml_tpu  # noqa: E402
from fedml_tpu.constants import (  # noqa: E402
    TPU_PEAK_BF16_DEFAULT,
    TPU_PEAK_BF16_FLOPS,
)
from fedml_tpu.runner import FedMLRunner  # noqa: E402


def _peak() -> float:
    kind = jax.devices()[0].device_kind
    return TPU_PEAK_BF16_FLOPS.get(kind, TPU_PEAK_BF16_DEFAULT)

RESNET56_FWD_FLOPS = 2 * 126.5e6
TRAIN_MULT = 3.0


def main() -> None:
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="cifar10", data_cache_dir=NPZ_DIR, model="resnet56",
        backend="parrot", partition_method="hetero", partition_alpha=0.5,
        client_num_in_total=100, client_num_per_round=10, comm_round=512,
        epochs=1, batch_size=32, learning_rate=0.05,
        frequency_of_the_test=1000, enable_tracking=False,
        compute_dtype="bfloat16", hetero_buckets=N_BUCKETS,
        conv_impl=CONV_IMPL))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    api = FedMLRunner(args, device, dataset, bundle).runner

    chunk = api.FUSED_CHUNK_ROUNDS
    rng = jax.random.PRNGKey(7)

    t0 = time.time()
    rng, sub = jax.random.split(rng)
    rms = api.run_rounds_fused(chunk, rng=sub)
    jax.block_until_ready(rms["train_loss"])
    compile_s = time.time() - t0

    n_meas = 2 * chunk
    t0 = time.time()
    rng, sub = jax.random.split(rng)
    rms = api.run_rounds_fused(n_meas, rng=sub)
    jax.block_until_ready(rms["train_loss"])
    dt = time.time() - t0
    rps = n_meas / dt

    if api.buckets is not None:
        padded = sum(b["k"] * b["nb"] for b in api.buckets) * api.bs
        eff_b = [b["k"] for b in api.buckets]
    else:
        padded = api.k * api.nb * api.bs
        eff_b = [api.k]
    flops_round = padded * RESNET56_FWD_FLOPS * TRAIN_MULT
    print(json.dumps({
        "buckets_requested": N_BUCKETS,
        "conv_impl": CONV_IMPL,
        "buckets_effective": len(eff_b),
        "clients_per_bucket": eff_b,
        "cache": USE_CACHE,
        "compile_s": round(compile_s, 1),
        "rounds_per_sec": round(rps, 4),
        "padded_samples_per_round": int(padded),
        "samples_per_sec": round(
            float(np.sum(np.asarray(rms["samples"]))) / dt, 1),
        "padded_samples_per_sec": round(padded * rps, 1),
        "est_mfu": round(flops_round * rps / _peak(), 4),
    }))


if __name__ == "__main__":
    main()
