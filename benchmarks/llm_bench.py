"""Absolute LLM-plane performance on the real TPU (VERDICT r3 item 1).

Two measurements, both on a GPT-2-small-class transformer (dim 768,
12 layers, 12 heads, vocab 50257 — the size class the reference's HF
trainer fine-tunes, `train/llm/hf_trainer.py`, and its scalellm wrapper
serves, `scalellm/__init__.py`):

* **SFT train step** — the functional LM (`parallel/seq_parallel.py`)
  under one jitted AdamW step, bf16 matmuls / fp32 optimizer, seq 1024.
  Reports tokens/s and analytic MFU against the chip's bf16 peak.
  FLOP accounting counts what the program EXECUTES (full T x T attention
  scores -- the einsum materializes both triangles), so MFU is never
  flattered by a causal discount the hardware doesn't take:
      fwd/token  = L*(24*D^2 + 4*T*D) + 2*D*V
      train/token = 3x fwd (no remat) or 4x fwd (remat re-runs the fwd)
* **Serving** — `KVCacheLM` prefill/decode at the same size, bf16:
  TTFT (prefill + first decode dispatch, batch 1) and steady-state decode
  tokens/s vs batch size via the on-device multi-token sampler
  (`decode_multi`), replacing round 3's relative "15.7x" with absolute
  numbers.

MEASUREMENT NOTE (axon tunnel): `jax.block_until_ready` is a NO-OP on
the remote-TPU plugin (verified: an 8-matmul chain "completes" in 0.1 ms
by block_until_ready but takes real time to fetch), so every timed window
here syncs by fetching a SCALAR to the host (~90 ms round-trip, measured
and subtracted).  The tunneled chip also sees BURSTY INTERFERENCE from
other tenants — long windows absorb multi-second stalls (observed: the
same decode step measuring 3.9 ms and 57 ms minutes apart) — so every
metric is the BEST of N short windows, which converges on the
uncontended rate.

Prints ONE JSON line and writes `benchmarks/llm_bench_results.json`.
Regression guard: if `benchmarks/llm_bench_floor.json` exists (committed
after the first accepted run), the script exits 1 when any guarded metric
falls below floor * 0.8 — same contract as the north-star accuracy guard.

Usage: python benchmarks/llm_bench.py [--quick] [--bs N] [--remat]
  --quick  skip the batch-size sweeps (used from bench.py: train bs 4
           only, decode batches 8/128 only; results go to
           llm_bench_results_quick.json)

FEDERATED MODE (--federated, CPU-feasible — this is what CI runs):
  measures the fed-LLM plane (docs/FED_LLM.md) instead of the raw TPU
  step: an INPROC 2-silo LoRA federation on shakespeare/transformer —
  per-silo SFT tokens/s, uplink/downlink bytes-on-wire per round, the
  adapter-vs-full-model bytes reduction, and the quality-vs-central
  curve (same model trained centrally on the union stream with an equal
  round budget).  Results go to llm_bench_federated[_quick].json;
  --guard enforces benchmarks/llm_bench_federated_floor.json (exit 1
  when the bytes reduction falls below 0.8x floor or the 20x hard
  minimum).
"""

import json
import os
import sys
import time
from functools import partial

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

QUICK = "--quick" in sys.argv
REMAT = "--remat" in sys.argv
FEDERATED = "--federated" in sys.argv
GUARD = "--guard" in sys.argv
_bs = [a for i, a in enumerate(sys.argv) if sys.argv[i - 1] == "--bs"]
FORCE_BS = int(_bs[0]) if _bs else 0

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(REPO, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from fedml_tpu.parallel.ring_attention import reference_attention  # noqa: E402
from fedml_tpu.parallel.seq_parallel import (  # noqa: E402
    init_lm_params,
    lm_loss,
)
from fedml_tpu.serving.kv_cache_lm import KVCacheLM  # noqa: E402

from fedml_tpu.constants import (  # noqa: E402
    TPU_PEAK_BF16_DEFAULT,
    TPU_PEAK_BF16_FLOPS,
)

# GPT-2 small class
VOCAB, DIM, LAYERS, HEADS, SEQ = 50257, 768, 12, 12, 1024

#: quick mode writes its (reduced-sweep) results to a separate file so it
#: never clobbers the committed full-sweep artifact that bench.py's
#: fallback and BENCH_NOTES.md reference
RESULTS_PATH = os.path.join(
    HERE, "llm_bench_results_quick.json" if QUICK
    else "llm_bench_results.json")
FLOOR_PATH = os.path.join(HERE, "llm_bench_floor.json")


def tree_size(tree) -> int:
    return sum(int(np.prod(x.shape))
               for x in jax.tree_util.tree_leaves(tree))


def sync(x) -> float:
    """Real device sync: fetch a scalar to the host (block_until_ready is
    a no-op on the axon remote platform — see module docstring)."""
    return float(jnp.sum(jnp.ravel(x)[:1]))


def measure_rtt() -> float:
    one = jnp.ones(())
    sync(one)
    ts = []
    for _ in range(5):
        t0 = time.time()
        sync(one + 0.0)
        ts.append(time.time() - t0)
    return min(ts)


def train_flops_per_token(remat: bool) -> float:
    fwd = LAYERS * (24 * DIM * DIM + 4 * SEQ * DIM) + 2 * DIM * VOCAB
    return fwd * (4.0 if remat else 3.0)


def bench_train(peak: float, remat: bool, rtt: float):
    """One jitted AdamW SFT step; returns best (bs, tokens/s, mfu)."""
    rng = jax.random.PRNGKey(0)
    params = init_lm_params(rng, VOCAB, dim=DIM, layers=LAYERS,
                            heads=HEADS, max_len=SEQ)
    n_params = tree_size(params)
    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)
    attn = partial(reference_attention, causal=True)

    def loss_fn(p, t):
        p16 = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), p)
        return lm_loss(p16, t, HEADS, attn, remat=remat)

    def make_step(accum: int):
        @jax.jit
        def step(params, opt_state, tokens):
            if accum == 1:
                loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
            else:
                # scan-accumulated microbatches: activation memory = ONE
                # microbatch → less HBM pressure than the single-shot
                # batch (measured best config, BENCH_NOTES round 5)
                mb = tokens.reshape(accum, -1, SEQ)

                def body(g_acc, t):
                    l, g = jax.value_and_grad(loss_fn)(params, t)
                    return jax.tree_util.tree_map(jnp.add, g_acc, g), l

                g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
                grads, losses = jax.lax.scan(body, g0, mb)
                grads = jax.tree_util.tree_map(
                    lambda g: g / accum, grads)
                loss = jnp.mean(losses)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
        return step

    #: (batch, accum) sweep — 16x4 = 4 scan-accumulated bs4 microbatches
    candidates = ([(FORCE_BS, 1)] if FORCE_BS
                  else ([(4, 1)] if QUICK
                        else [(4, 1), (8, 1), (16, 1), (16, 4)]))
    per_bs = {}
    for bs, accum in candidates:
        key = f"{bs}x{accum}" if accum > 1 else str(bs)
        step = make_step(accum)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, VOCAB, (bs, SEQ)),
            jnp.int32)
        try:
            t0 = time.time()
            p, o, loss = step(params, opt_state, tokens)
            sync(loss)
            compile_s = time.time() - t0
            for _ in range(2):                       # warmup steady state
                p, o, loss = step(p, o, tokens)
            sync(loss)
            # best-of-N 2-step windows (see module docstring: the tunnel
            # sees bursty interference; min converges on the true rate)
            n_win, spw = (4, 2) if QUICK else (8, 2)
            dt = float("inf")
            windows_ms = []
            for _ in range(n_win):
                t0 = time.time()
                for _ in range(spw):
                    p, o, loss = step(p, o, tokens)
                sync(loss)               # ONE host fetch syncs the window
                w = (time.time() - t0 - rtt) / spw
                windows_ms.append(round(w * 1e3, 1))
                dt = min(dt, w)
        except Exception as e:                       # OOM at this bs
            per_bs[key] = {"error": str(e)[:200]}
            continue
        tok_s = bs * SEQ / dt
        per_bs[key] = {
            "step_ms": round(dt * 1e3, 1),
            "tokens_per_sec": round(tok_s, 0),
            "mfu": round(tok_s * train_flops_per_token(remat) / peak, 4),
            "compile_s": round(compile_s, 1),
            # every window, not just the best: makes shared-chip
            # interference VISIBLE in the committed artifact (a floor
            # trip can be diagnosed as variance vs regression)
            "windows_ms": windows_ms,
        }
        del p, o
    ok = {b: r for b, r in per_bs.items() if "error" not in r}
    if not ok:
        raise RuntimeError(f"all train batch sizes failed: {per_bs}")
    best = max(ok, key=lambda b: ok[b]["tokens_per_sec"])
    # typed best-config fields: per_bs keys are strings ("16x4"), so keep
    # numeric consumers working via best_bs (int batch) + best_accum
    b_bs, _, b_acc = best.partition("x")
    return {"model": f"gpt2-small-class d{DIM} L{LAYERS} T{SEQ}",
            "n_params": n_params, "remat": remat,
            "best_bs": int(b_bs), "best_accum": int(b_acc or 1),
            **ok[best], "per_bs": per_bs}


def bench_serving(peak: float, rtt: float):
    """KVCacheLM in bf16: TTFT (bs1) + decode tokens/s vs batch."""
    rng = jax.random.PRNGKey(2)
    params = init_lm_params(rng, VOCAB, dim=DIM, layers=LAYERS,
                            heads=HEADS, max_len=SEQ)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), params)
    lm = KVCacheLM(params, HEADS, SEQ)
    gen = np.random.default_rng(3)

    def mk_prompts(bs, width):
        toks = jnp.asarray(gen.integers(0, VOCAB, (bs, width)), jnp.int32)
        return toks, jnp.full((bs,), width, jnp.int32)

    # ---- TTFT: prompt 512, batch 1 — prefill + argmax of last logits ----
    toks, length = mk_prompts(1, 512)
    cache, last = lm.prefill(toks, length)           # compile
    sync(last)
    ttfts = []
    for _ in range(5 if QUICK else 8):
        t0 = time.time()
        cache, last = lm.prefill(toks, length)
        first_tok = jnp.argmax(last, -1)
        sync(first_tok)                  # the fetch IS the "token arrives"
        ttfts.append(time.time() - t0)
    # raw wall includes one ~90ms tunnel round-trip (a local host would
    # not pay it).  The device-side prefill cost is too small to recover
    # from a single dispatch minus noisy RTT, so measure it by chaining N
    # back-to-back prefill dispatches under one sync (in-order execution)
    ttft_ms = 1e3 * min(ttfts)
    n_chain = 8
    best_pref = float("inf")
    for _ in range(3):
        t0 = time.time()
        for _ in range(n_chain):
            cache, last = lm.prefill(toks, length)
        sync(last)
        best_pref = min(best_pref, (time.time() - t0 - rtt) / n_chain)
    prefill_ms = 1e3 * best_pref
    prefill_tok_s = 512 / best_pref

    # ---- steady-state decode tokens/s vs batch ----
    # decode FLOPs/token ~ 2*n_params + cache attention reads; the engine
    # is HBM-bound here (reads all params per k-chunk step), so we also
    # report the bandwidth-model ceiling for context.
    decode = {}
    batches = [8, 128] if QUICK else [1, 8, 32, 64, 128]
    K = 64                                           # tokens per dispatch
    n_win = 4 if QUICK else 8
    for bs in batches:
        toks, length = mk_prompts(bs, 128)
        cache, last = lm.prefill(toks, length)
        first = jnp.argmax(last, -1)
        prompt_buf = jnp.zeros((bs, K), jnp.int32).at[:, 0].set(first)
        prompt_n = jnp.ones((bs,), jnp.int32)
        temps = jnp.zeros((bs,), jnp.float32)        # greedy
        top_k = jnp.zeros((bs,), jnp.int32)
        top_p = jnp.ones((bs,), jnp.float32)
        key = jax.random.PRNGKey(4)
        pos = length
        # compile + warm
        cache, emitted = lm.decode_multi(cache, prompt_buf, prompt_n, pos,
                                         temps, top_k, top_p, key, K)
        sync(emitted)
        pos = pos + K
        # best-of-N one-chunk windows, each chained on-device through
        # emitted[:, -1] and synced by one scalar fetch
        assert 128 + K * (2 + n_win) <= lm.max_len, \
            "decode windows overrun the cache; lower K or n_win"
        best = float("inf")
        for _ in range(n_win):
            nxt = emitted[:, -1]
            prompt_buf = prompt_buf.at[:, 0].set(nxt)
            t0 = time.time()
            cache, emitted = lm.decode_multi(cache, prompt_buf, prompt_n,
                                             pos, temps, top_k, top_p,
                                             key, K)
            sync(emitted)
            best = min(best, time.time() - t0 - rtt)
            pos = pos + K
        decode[bs] = {
            "tokens_per_sec": round(bs * K / best, 0),
            "ms_per_token_per_seq": round(1e3 * best / K, 3),
        }
        del cache, emitted
    best_bs = max(decode, key=lambda b: decode[b]["tokens_per_sec"])
    return {"ttft_ms_b1_p512": round(ttft_ms, 1),
            "prefill_ms_device_b1_p512": round(prefill_ms, 1),
            "prefill_tokens_per_sec": round(prefill_tok_s, 0),
            "decode": decode,
            "best_decode_bs": best_bs,
            "best_decode_tokens_per_sec":
                decode[best_bs]["tokens_per_sec"]}


FED_RESULTS_PATH = os.path.join(
    HERE, "llm_bench_federated_quick.json" if QUICK
    else "llm_bench_federated.json")
FED_FLOOR_PATH = os.path.join(HERE, "llm_bench_federated_floor.json")

#: ISSUE acceptance: adapter uploads must beat full-model transfer by at
#: least this factor, regardless of what the committed floor says
FED_MIN_REDUCTION = 20.0


def main_federated() -> None:
    import fedml_tpu
    from fedml_tpu.ml.engine.local_update import build_eval_step
    from fedml_tpu.ml.trainer.default_trainer import batches_for
    from fedml_tpu.runner import FedMLRunner
    from fedml_tpu.train.fed_llm.trainer import (
        FED_LLM_TOKENS,
        FED_LLM_TRAIN_SECONDS,
    )
    from fedml_tpu.train.llm.lora import apply_lora
    from fedml_tpu.utils.compression import WIRE_BYTES
    from fedml_tpu.utils.serialization import estimate_nbytes

    run_id = "llm-bench-fed"
    n_silos, rounds = 2, (3 if QUICK else 5)
    lora_rank, seq_len, bs = 4, 32, 4
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="shakespeare", model="transformer",
        training_type="cross_silo", backend="INPROC", role="simulated",
        client_num_in_total=n_silos, client_num_per_round=n_silos,
        comm_round=rounds, epochs=1, batch_size=bs, learning_rate=3e-3,
        data_scale=0.5 if QUICK else 1.0, frequency_of_the_test=1,
        random_seed=0, run_id=run_id, enable_tracking=False,
        compute_dtype="float32", fed_llm=True, lora_rank=lora_rank,
        fed_llm_seq_len=seq_len))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])

    t0 = time.time()
    metrics = FedMLRunner(args, device, dataset, bundle).run()
    fed_wall = time.time() - t0
    fed_hist = metrics["server_loss_history"]

    # -- bytes on the wire (measured at the transport, not estimated) ----
    codecs = ("raw", "bf16", "int8", "topk", "topk8")
    up = sum(WIRE_BYTES.labels(run_id=run_id, direction="up",
                               codec=c).value for c in codecs)
    down = sum(WIRE_BYTES.labels(run_id=run_id, direction="down",
                                 codec=c).value for c in codecs)
    full_model = estimate_nbytes(
        bundle.init_variables(jax.random.PRNGKey(0)))
    n_uploads = n_silos * rounds
    reduction = full_model / (up / n_uploads)

    per_silo = {}
    for silo in range(n_silos):
        tok = FED_LLM_TOKENS.labels(run_id=run_id, silo=str(silo)).value
        sec = FED_LLM_TRAIN_SECONDS.labels(run_id=run_id,
                                           silo=str(silo)).value
        per_silo[str(silo)] = {
            "train_tokens": tok,
            # counter includes the round-1 compile; steady-state rate is
            # higher (the per-round logs show it)
            "tokens_per_sec": round(tok / max(sec, 1e-9), 0),
        }

    # -- quality vs central: same model + token budget, no federation ----
    from fedml_tpu.train.fed_llm.config import llm_config_from_args
    from fedml_tpu.train.llm.trainer import LLMTrainer

    import numpy as _np

    union = _np.concatenate(
        [_np.asarray(dataset[5][c][0]).reshape(-1)
         for c in range(n_silos)]).astype(_np.int64)
    central = LLMTrainer(bundle, llm_config_from_args(args),
                         rng=jax.random.PRNGKey(0))
    eval_step = jax.jit(build_eval_step(bundle))
    test_global = dataset[3]
    nb = max(1, -(-len(test_global[1]) // bs))
    batches = jax.device_get(  # host-side once; reused every eval
        batches_for(test_global, bs, nb, bundle.input_dtype))
    central_hist = []
    for _ in range(rounds):
        central.train(union)  # fresh opt state per call == per-round SGD
        merged = apply_lora(central.variables["params"], central.lora,
                            central.cfg.lora_alpha)
        out = jax.device_get(eval_step(
            dict(central.variables, params=merged), batches))
        central_hist.append(float(out["loss_sum"]) / max(
            float(out["n"]), 1.0))

    out = {
        "mode": "federated", "quick": QUICK,
        "model": "tiny-transformer d128 L2 (shakespeare char-LM)",
        "silos": n_silos, "rounds": rounds, "lora_rank": lora_rank,
        "seq_len": seq_len, "batch_size": bs,
        "full_model_bytes": full_model,
        "uplink_bytes_total": up,
        "uplink_bytes_per_round": round(up / rounds, 0),
        "downlink_bytes_per_round": round(down / rounds, 0),
        "mean_upload_bytes": round(up / n_uploads, 0),
        "uplink_bytes_reduction": round(reduction, 1),
        "per_silo": per_silo,
        "federated_loss_history": [round(x, 4) for x in fed_hist],
        "central_loss_history": [round(x, 4) for x in central_hist],
        "quality_gap_final": round(fed_hist[-1] - central_hist[-1], 4),
        "federated_wall_s": round(fed_wall, 1),
    }
    with open(FED_RESULTS_PATH, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "fed_llm_uplink_reduction": out["uplink_bytes_reduction"],
        "fed_llm_final_loss": out["federated_loss_history"][-1],
        "fed_llm_quality_gap": out["quality_gap_final"],
        "fed_llm_tokens_per_sec_per_silo":
            [v["tokens_per_sec"] for v in per_silo.values()],
        "detail": FED_RESULTS_PATH,
    }))

    if GUARD:
        bad = {}
        if reduction < FED_MIN_REDUCTION:
            bad["uplink_bytes_reduction(min)"] = (round(reduction, 1),
                                                  FED_MIN_REDUCTION)
        if os.path.exists(FED_FLOOR_PATH):
            with open(FED_FLOOR_PATH) as f:
                floor = json.load(f)
            k = "uplink_bytes_reduction"
            if k in floor and reduction < 0.8 * floor[k]:
                bad[k] = (round(reduction, 1), floor[k])
        if bad:
            print(f"FED LLM GUARD FAILED: {bad}", file=sys.stderr)
            sys.exit(1)


def main() -> None:
    kind = jax.devices()[0].device_kind
    peak = TPU_PEAK_BF16_FLOPS.get(kind, TPU_PEAK_BF16_DEFAULT)
    rtt = measure_rtt()
    out = {"device": kind, "peak_bf16_flops": peak, "quick": QUICK,
           "host_rtt_ms": round(1e3 * rtt, 1)}
    t0 = time.time()
    out["train"] = bench_train(peak, REMAT, rtt)
    out["serving"] = bench_serving(peak, rtt)
    out["wall_s"] = round(time.time() - t0, 1)

    with open(RESULTS_PATH, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({
        "llm_sft_mfu": out["train"]["mfu"],
        "llm_sft_tokens_per_sec": out["train"]["tokens_per_sec"],
        "llm_ttft_ms": out["serving"]["ttft_ms_b1_p512"],
        "llm_decode_tokens_per_sec":
            out["serving"]["best_decode_tokens_per_sec"],
        "detail": RESULTS_PATH,
    }))

    if os.path.exists(FLOOR_PATH):
        with open(FLOOR_PATH) as f:
            floor = json.load(f)
        checks = {
            "llm_sft_mfu": out["train"]["mfu"],
            "llm_sft_tokens_per_sec": out["train"]["tokens_per_sec"],
            "llm_decode_tokens_per_sec":
                out["serving"]["best_decode_tokens_per_sec"],
        }
        bad = {k: (v, floor[k]) for k, v in checks.items()
               if k in floor and v < 0.8 * floor[k]}
        if bad:
            print(f"LLM PERF GUARD FAILED: {bad}", file=sys.stderr)
            sys.exit(1)


if __name__ == "__main__":
    main_federated() if FEDERATED else main()
