class InferenceServerClient:
    def __init__(self, *a, **k):
        raise RuntimeError("triton stub")
def __getattr__(name):
    def _fail(*a, **k):
        raise RuntimeError("triton stub")
    return _fail
