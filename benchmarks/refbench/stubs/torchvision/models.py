def __getattr__(name):
    raise RuntimeError("torchvision.models stub: not available")
