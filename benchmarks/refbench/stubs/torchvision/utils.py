def make_grid(*a, **k):
    raise RuntimeError("torchvision.utils stub")
def save_image(*a, **k):
    raise RuntimeError("torchvision.utils stub")
