class VisionDataset:
    def __init__(self, root, *a, **k):
        self.root = root
class MNIST(VisionDataset):
    pass
class CIFAR10(VisionDataset):
    pass
class CIFAR100(VisionDataset):
    pass
class ImageFolder(VisionDataset):
    pass
class DatasetFolder(VisionDataset):
    def __init__(self, root, *a, **k):
        self.root = root
        self.samples = []
class EMNIST(VisionDataset):
    pass
class SVHN(VisionDataset):
    pass
def __getattr__(name):
    return VisionDataset
