class Compose:
    def __init__(self, ts):
        self.ts = ts
    def __call__(self, x):
        for t in self.ts:
            x = t(x)
        return x
class ToTensor:
    def __call__(self, x):
        import torch, numpy as np
        return torch.as_tensor(np.asarray(x))
class Normalize:
    def __init__(self, mean, std, inplace=False):
        self.mean, self.std = mean, std
    def __call__(self, x):
        return x
class ToPILImage:
    def __call__(self, x):
        return x
class RandomCrop:
    def __init__(self, *a, **k):
        pass
    def __call__(self, x):
        return x
class RandomHorizontalFlip:
    def __init__(self, *a, **k):
        pass
    def __call__(self, x):
        return x
class CenterCrop:
    def __init__(self, *a, **k):
        pass
    def __call__(self, x):
        return x
class Resize:
    def __init__(self, *a, **k):
        pass
    def __call__(self, x):
        return x
class Lambda:
    def __init__(self, f):
        self.f = f
    def __call__(self, x):
        return self.f(x)
