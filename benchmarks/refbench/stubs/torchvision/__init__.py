"""Stub torchvision: enough surface for import-time use on the SP MNIST path."""
from . import transforms, datasets, models, utils
