from ..orm import declarative_base
