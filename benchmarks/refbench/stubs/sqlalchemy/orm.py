from . import _Placeholder
def sessionmaker(*a, **k):
    return _Placeholder()
def declarative_base(*a, **k):
    class Base:
        metadata = _Placeholder()
        def __init_subclass__(cls, **kw):
            pass
    return Base
def __getattr__(name):
    return _Placeholder
