"""Stub sqlalchemy: import-time surface only (reference uses it for run DBs)."""
class _Placeholder:
    def __init__(self, *a, **k):
        pass
    def __call__(self, *a, **k):
        return _Placeholder()
    def __getattr__(self, name):
        return _Placeholder()
Column = String = TEXT = Integer = Float = Boolean = DateTime = BigInteger = _Placeholder
def create_engine(*a, **k):
    return _Placeholder()
def and_(*a, **k):
    return None
def or_(*a, **k):
    return None
def __getattr__(name):
    return _Placeholder
