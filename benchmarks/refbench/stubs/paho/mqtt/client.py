"""Stub paho.mqtt.client: import-time only; connecting raises."""
MQTTv311 = 4
MQTTv5 = 5


class MQTTMessage:
    def __init__(self, topic=b"", payload=b""):
        self.topic = topic
        self.payload = payload


class Client:
    def __init__(self, *a, **k):
        self.on_connect = None
        self.on_disconnect = None
        self.on_message = None
        self.on_publish = None
        self.on_subscribe = None

    def username_pw_set(self, *a, **k):
        pass

    def will_set(self, *a, **k):
        pass

    def connect(self, *a, **k):
        raise RuntimeError("paho stub: no broker in this environment")

    def loop_start(self, *a, **k):
        pass

    def loop_stop(self, *a, **k):
        pass

    def loop_forever(self, *a, **k):
        raise RuntimeError("paho stub: no broker in this environment")

    def publish(self, *a, **k):
        raise RuntimeError("paho stub: no broker in this environment")

    def subscribe(self, *a, **k):
        pass

    def disconnect(self, *a, **k):
        pass
