def single(*a, **k):
    raise RuntimeError("paho stub: no broker")
def multiple(*a, **k):
    raise RuntimeError("paho stub: no broker")
