"""Stub: alias the stdlib multiprocessing as the 'multiprocess' package."""
import multiprocessing as _mp
import sys
sys.modules[__name__] = _mp
