def detect(b):
    return {"encoding": "utf-8", "confidence": 1.0}
