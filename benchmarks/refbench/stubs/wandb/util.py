def apple_gpu_stats_binary():
    raise RuntimeError("wandb stub")
def __getattr__(name):
    def _fail(*a, **k):
        raise RuntimeError("wandb stub")
    return _fail
