class TelemetryRecord:
    pass
def context(*a, **k):
    class _Ctx:
        def __enter__(self):
            return TelemetryRecord()
        def __exit__(self, *a):
            return False
    return _Ctx()
def __getattr__(name):
    return None
