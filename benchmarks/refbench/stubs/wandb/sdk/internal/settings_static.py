class SettingsStatic:
    def __init__(self, d=None):
        self.__dict__.update(d or {})
