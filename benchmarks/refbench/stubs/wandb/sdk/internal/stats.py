class SystemStats:
    def __init__(self, *a, **k):
        pass
