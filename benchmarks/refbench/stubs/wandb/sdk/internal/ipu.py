class IPUProfiler:
    pass
def is_ipu_available():
    return False
def __getattr__(name):
    return None
