class TPUProfiler:
    pass
def is_tpu_available():
    return False
def get_profiler(*a, **k):
    return None
def __getattr__(name):
    return None
