class InterfaceQueue:
    def __init__(self, *a, **k):
        pass
