"""Stub wandb."""
run = None
class _Run:
    def watch(self, *a, **k):
        pass


def init(*a, **k):
    return _Run()


def watch(*a, **k):
    pass
def log(*a, **k):
    pass
