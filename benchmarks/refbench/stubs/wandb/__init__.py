"""Stub wandb."""
run = None
def init(*a, **k):
    raise RuntimeError("wandb stub")
def log(*a, **k):
    pass
