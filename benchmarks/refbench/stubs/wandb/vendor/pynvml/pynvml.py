class NVMLError(Exception):
    pass
def nvmlInit():
    raise NVMLError("no nvml")
def nvmlDeviceGetCount():
    return 0
def __getattr__(name):
    def _fail(*a, **k):
        raise NVMLError("no nvml")
    return _fail
