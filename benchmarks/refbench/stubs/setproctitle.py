def setproctitle(title):
    pass
def getproctitle():
    return ""
