class PrettyTable:
    def __init__(self, *a, **k):
        self.rows = []
        self.field_names = []
    def add_row(self, row):
        self.rows.append(row)
    def __str__(self):
        return "\n".join(str(r) for r in self.rows)
