"""Stub docker SDK: import-time only."""
class errors:
    class DockerException(Exception):
        pass
    class APIError(Exception):
        pass
    class NotFound(Exception):
        pass
def from_env(*a, **k):
    raise errors.DockerException("docker stub: no daemon")
class DockerClient:
    def __init__(self, *a, **k):
        raise errors.DockerException("docker stub: no daemon")
