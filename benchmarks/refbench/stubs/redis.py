"""Stub redis client: import-time only."""
class ConnectionError(Exception):
    pass
class Redis:
    def __init__(self, *a, **k):
        pass
    def ping(self):
        raise ConnectionError("redis stub")
    def __getattr__(self, name):
        def _fail(*a, **k):
            raise ConnectionError("redis stub")
        return _fail
class ConnectionPool:
    def __init__(self, *a, **k):
        pass
