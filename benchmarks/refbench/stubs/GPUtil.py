"""Stub GPUtil: no GPUs on this host."""
def getGPUs():
    return []
def getAvailable(*a, **k):
    return []
