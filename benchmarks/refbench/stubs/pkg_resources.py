"""Stub pkg_resources (setuptools>=78 removed it)."""
def parse_version(v):
    import re
    return tuple(int(x) if x.isdigit() else x for x in re.split(r"[.\-+]", str(v)))
class DistributionNotFound(Exception):
    pass
def get_distribution(name):
    raise DistributionNotFound(name)
def iter_entry_points(*a, **k):
    return []
