class Request:
    pass
