"""Stub FastAPI: import-time surface only."""
class FastAPI:
    def __init__(self, *a, **k):
        pass
    def _deco(self, *a, **k):
        def wrap(fn):
            return fn
        return wrap
    get = post = put = delete = api_route = middleware = on_event = _deco
    def mount(self, *a, **k):
        pass
    def add_middleware(self, *a, **k):
        pass
class Request:
    pass
class Response:
    def __init__(self, *a, **k):
        pass
class HTTPException(Exception):
    def __init__(self, status_code=500, detail=""):
        self.status_code = status_code
        self.detail = detail
class APIRouter(FastAPI):
    pass
def Depends(x=None):
    return x
def Body(*a, **k):
    return None
def Query(*a, **k):
    return None
def Header(*a, **k):
    return None
def File(*a, **k):
    return None
def Form(*a, **k):
    return None
class UploadFile:
    pass
class BackgroundTasks:
    def add_task(self, *a, **k):
        pass
class status:
    HTTP_200_OK = 200
    HTTP_404_NOT_FOUND = 404
    HTTP_500_INTERNAL_SERVER_ERROR = 500
