class Response:
    def __init__(self, *a, **k):
        pass
class JSONResponse(Response):
    pass
class StreamingResponse(Response):
    pass
class FileResponse(Response):
    pass
class PlainTextResponse(Response):
    pass
class RedirectResponse(Response):
    pass
class HTMLResponse(Response):
    pass
