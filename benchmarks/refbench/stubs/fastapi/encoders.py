def jsonable_encoder(x, *a, **k):
    return x
