"""Stub boto3: import-time only."""
def client(*a, **k):
    raise RuntimeError("boto3 stub: no S3 in this environment")
def resource(*a, **k):
    raise RuntimeError("boto3 stub: no S3 in this environment")
def session(*a, **k):
    raise RuntimeError("boto3 stub")
class Session:
    def __init__(self, *a, **k):
        raise RuntimeError("boto3 stub")
