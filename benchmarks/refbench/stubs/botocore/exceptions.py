class ClientError(Exception):
    pass
class BotoCoreError(Exception):
    pass
class NoCredentialsError(BotoCoreError):
    pass
