class Config:
    def __init__(self, *a, **k):
        pass
