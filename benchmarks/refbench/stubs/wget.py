def download(url, out=None, bar=None):
    raise RuntimeError("zero-egress environment: wget stub; pre-seed the cache dir")
