"""Run the reference's SP FedAvg MNIST-LR smoke config and measure it.

Mirrors `/root/reference/python/examples/federate/quick_start/parrot/`
(config at fedml_config.yaml:1-44) but on the zero-egress synthetic LEAF
MNIST produced by gen_leaf_mnist.py, CPU-only. Prints one JSON line with
measured wall-clock, rounds/sec, and final accuracy; this is the measured
anchor BASELINE.md requires.

Usage: PYTHONPATH=<stubs>:<reference/python> python run_reference_sp.py
"""

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))
CACHE = os.path.join(REPO, ".data_cache", "refbench")

CONFIG = {
    "common_args": {"training_type": "simulation", "random_seed": 0},
    "data_args": {
        "dataset": "mnist",
        "data_cache_dir": CACHE,
        "partition_method": "hetero",
        "partition_alpha": 0.5,
    },
    "model_args": {"model": "lr"},
    "train_args": {
        "federated_optimizer": "FedAvg",
        "client_id_list": "[]",
        "client_num_in_total": 2,
        "client_num_per_round": 2,
        "comm_round": 10,
        "epochs": 1,
        "batch_size": 10,
        "client_optimizer": "sgd",
        "learning_rate": 0.03,
        "weight_decay": 0.001,
    },
    "validation_args": {"frequency_of_the_test": 1},
    "device_args": {"using_gpu": False, "gpu_id": 0},
    "comm_args": {"backend": "sp"},
    "tracking_args": {"enable_tracking": False, "enable_wandb": False,
                      "log_file_dir": os.path.join(CACHE, "log")},
}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--optimizer", default="FedAvg",
                   choices=["FedAvg", "FedProx", "SCAFFOLD",
                            "FedNova", "FedDyn", "Mime"])
    p.add_argument("--rounds", type=int, default=10)
    p.add_argument("--model", default="lr", choices=["lr", "cnn"],
                   help="cnn = the reference CNN_DropOut conv model "
                        "(model_hub.py:32-37) for the conv parity plane")
    cli, _ = p.parse_known_args()
    CONFIG["train_args"]["federated_optimizer"] = cli.optimizer
    CONFIG["train_args"]["comm_round"] = cli.rounds
    CONFIG["model_args"]["model"] = cli.model
    # optimizer-specific keys (reference ml/trainer/fedprox_trainer.py:50
    # args.fedprox_mu; sp/scaffold/scaffold_trainer.py:132 args.server_lr)
    CONFIG["train_args"]["fedprox_mu"] = 0.1
    CONFIG["train_args"]["server_lr"] = 1.0
    # scaffold_trainer.py:62 requires this flag (no default in Arguments)
    CONFIG["train_args"]["initialize_all_clients"] = False
    # FedNova (sp/fednova/client.py:84-93 custom optimizer knobs): plain
    # SGD semantics for the parity run
    CONFIG["train_args"]["gmf"] = 0
    CONFIG["train_args"]["mu"] = 0
    CONFIG["train_args"]["momentum"] = 0.0
    CONFIG["train_args"]["dampening"] = 0.0
    CONFIG["train_args"]["wd"] = 0.0
    CONFIG["train_args"]["nesterov"] = False
    # FedDyn (ml/trainer/feddyn_trainer.py alpha)
    CONFIG["train_args"]["feddyn_alpha"] = 0.01
    # Mime (sp/mime/mime_trainer.py server opt + mimelite flag)
    CONFIG["train_args"]["server_optimizer"] = "sgd"
    CONFIG["train_args"]["server_momentum"] = 0.9
    CONFIG["train_args"]["mimelite"] = True
    if cli.optimizer in ("FedNova", "Mime"):
        # fednova_trainer.py / mime_trainer.py log Test/Acc ONLY through
        # wandb (no mlops.log); enable it against the refbench stub
        CONFIG["tracking_args"]["enable_wandb"] = True
        CONFIG["tracking_args"]["wandb_project"] = "refbench"
        CONFIG["tracking_args"]["wandb_name"] = "refbench"
        CONFIG["tracking_args"]["wandb_key"] = "stub"
        CONFIG["tracking_args"]["run_name"] = "refbench"
        CONFIG["tracking_args"]["ci"] = False
        CONFIG["tracking_args"]["wandb_entity"] = None
        CONFIG["tracking_args"]["wandb_group"] = None
        CONFIG["tracking_args"]["wandb_offline"] = True

    os.makedirs(CACHE, exist_ok=True)
    if not os.path.exists(os.path.join(CACHE, "MNIST", "train")):
        sys.path.insert(0, HERE)
        from gen_leaf_mnist import gen
        print("generating LEAF mnist...", file=sys.stderr)
        gen(CACHE, users=100, seed=42)
    # Satisfy download_mnist's existence checks (zero-egress: no real zip).
    zip_marker = os.path.join(CACHE, "MNIST.zip")
    if not os.path.exists(zip_marker):
        open(zip_marker, "wb").close()

    cfg_path = os.path.join(CACHE, "fedml_config.yaml")
    import yaml
    with open(cfg_path, "w") as f:
        yaml.safe_dump(CONFIG, f)
    sys.argv = ["run_reference_sp.py", "--cf", cfg_path, "--rank", "0",
                "--role", "server"]

    import fedml  # noqa: E402  (resolved from /root/reference/python)

    # capture the per-round eval stream the APIs emit via mlops.log
    # (Test/Acc, Test/Loss with a round index) — enable_tracking is off so
    # the hook is otherwise a no-op
    per_round = {}
    from fedml.core import mlops as _mlops

    _orig_log = _mlops.log

    def _capture(metrics, *a, **k):
        if isinstance(metrics, dict) and "round" in metrics:
            r = int(metrics["round"])
            rec = per_round.setdefault(r, {})
            for key, v in metrics.items():
                if key != "round":
                    rec[key] = float(v)
        return _orig_log(metrics, *a, **k)

    _mlops.log = _capture
    fedml.mlops.log = _capture
    import wandb as _wandb  # the refbench stub

    _wandb.log = lambda metrics, *a, **k: _capture(metrics)

    t_setup = time.time()
    args = fedml.init()
    device = fedml.device.get_device(args)
    dataset, output_dim = fedml.data.load(args)
    if cli.model == "cnn":
        # dropout RNG is framework-specific (torch vs jax), so the parity
        # run zeroes it on BOTH sides: patch nn.Dropout to Identity before
        # model creation (CNN_DropOut builds its Dropout modules in
        # __init__, cnn.py:118-123); documented in docs/PARITY.md
        import torch.nn as _nn
        _nn.Dropout = lambda *a, **k: _nn.Identity()
    model = fedml.model.create(args, output_dim)
    setup_s = time.time() - t_setup

    # export the exact initial weights so the fedml_tpu side can start from
    # the SAME point (cross-framework init transfer for the parity audit)
    import numpy as np
    sd = model.state_dict()
    np.savez(os.path.join(CACHE, f"ref_init_{cli.model}.npz"),
             **{k: v.cpu().numpy() for k, v in sd.items()})

    from fedml.simulation.simulator import SimulatorSingleProcess

    sim = SimulatorSingleProcess(args, device, dataset, model)
    t0 = time.time()
    sim.run()
    train_s = time.time() - t0

    last = per_round[max(per_round)] if per_round else {}
    out = {
        "what": f"reference_sp_{cli.optimizer.lower()}_mnist_"
                f"{cli.model}_smoke",
        "host": "cpu",
        "users": args.client_num_in_total,
        "comm_round": args.comm_round,
        "setup_s": round(setup_s, 3),
        "train_wall_s": round(train_s, 3),
        "rounds_per_sec": round(args.comm_round / train_s, 4),
        "test_acc": last.get("Test/Acc"),
        "test_loss": last.get("Test/Loss"),
        "train_acc": last.get("Train/Acc"),
        "per_round": {str(r): per_round[r] for r in sorted(per_round)},
    }
    print("PARITY_JSON " + json.dumps(out))


if __name__ == "__main__":
    main()
