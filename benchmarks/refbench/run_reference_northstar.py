"""Measure the reference at the north-star shape on the hardware it can use
here (CPU torch, 1 core): SP FedAvg, ResNet-56, CIFAR-10 (50k synthetic,
shared npz), 100 clients / 10 per round, bs 32, 1 local epoch.

Runs the reference's own FedAvgAPI / ModelTrainerCLS / resnet56
(`/root/reference/python/fedml/simulation/sp/fedavg/fedavg_api.py:66`,
`model/cv/resnet.py:297`) on the identical data + Dirichlet(0.5) partition
fedml_tpu's bench.py uses, with eval disabled inside the measured window.
Prints one JSON line: sec/round, rounds/sec, samples/sec.

Usage:
  python benchmarks/gen_northstar_cifar.py   # once
  PYTHONPATH=stubs:/root/reference/python python run_reference_northstar.py \
      [--rounds 2]
"""

import argparse
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))
CACHE = os.path.join(REPO, ".data_cache", "northstar")


def build_args():
    import yaml
    cfg = {
        "common_args": {"training_type": "simulation", "random_seed": 0},
        "data_args": {"dataset": "cifar10", "data_cache_dir": CACHE,
                      "partition_method": "hetero", "partition_alpha": 0.5},
        "model_args": {"model": "resnet56"},
        "train_args": {
            "federated_optimizer": "FedAvg", "client_id_list": "[]",
            "client_num_in_total": 100, "client_num_per_round": 10,
            "comm_round": 2, "epochs": 1, "batch_size": 32,
            "client_optimizer": "sgd", "learning_rate": 0.05,
            "weight_decay": 0.0,
        },
        "validation_args": {"frequency_of_the_test": 100},
        "device_args": {"using_gpu": False, "gpu_id": 0},
        "comm_args": {"backend": "sp"},
        "tracking_args": {"enable_tracking": False, "enable_wandb": False,
                          "log_file_dir": os.path.join(CACHE, "log")},
    }
    cfg_path = os.path.join(CACHE, "ref_northstar_config.yaml")
    with open(cfg_path, "w") as f:
        yaml.safe_dump(cfg, f)
    sys.argv = [sys.argv[0], "--cf", cfg_path, "--rank", "0",
                "--role", "server"]
    import fedml
    return fedml, fedml.init()


def build_dataset(args):
    """Identical bytes + identical partition to fedml_tpu's loader
    (fedml_tpu/data/data_loader.py:load) for dataset=cifar10 with the
    north-star npz in cache."""
    import numpy as np
    import torch
    sys.path.insert(0, REPO)
    from fedml_tpu.data.partition import partition

    z = np.load(os.path.join(CACHE, "cifar10.npz"))
    xt = z["x_train"].astype(np.float32) / 255.0
    yt = z["y_train"].astype(np.int64)
    xe = z["x_test"].astype(np.float32) / 255.0
    ye = z["y_test"].astype(np.int64)

    net_map = partition(yt, args.client_num_in_total, "hetero",
                        args.partition_alpha, args.random_seed)
    test_map = partition(ye, args.client_num_in_total, "homo",
                         args.partition_alpha, args.random_seed + 1)

    def to_batches(x, y, bs):
        out = []
        for i in range(0, len(x), bs):
            xb = torch.from_numpy(x[i:i + bs].transpose(0, 3, 1, 2)).float()
            yb = torch.from_numpy(y[i:i + bs]).long()
            out.append((xb, yb))
        return out

    train_local, test_local, local_num = {}, {}, {}
    for cid in range(args.client_num_in_total):
        idx = net_map[cid]
        train_local[cid] = to_batches(xt[idx], yt[idx], args.batch_size)
        local_num[cid] = int(len(idx))
        tidx = test_map[cid]
        test_local[cid] = to_batches(xe[tidx], ye[tidx], args.batch_size)

    dataset = [len(yt), len(ye), None, None, local_num, train_local,
               test_local, 10]
    return dataset, local_num, net_map


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=2)
    a, _ = p.parse_known_args()
    sys.argv = [sys.argv[0]]

    fedml, args = build_args()
    args.comm_round = a.rounds
    device = fedml.device.get_device(args)
    dataset, local_num, net_map = build_dataset(args)
    model = fedml.model.create(args, dataset[-1])

    from fedml.simulation.sp.fedavg.fedavg_api import FedAvgAPI

    api = FedAvgAPI(args, device, dataset, model)

    # Time the API's own train() loop (fedavg_api.py:66-123) with eval
    # patched out: the last round unconditionally runs
    # _local_test_on_all_clients (100 clients × full data on 1 CPU core —
    # hours), and we are measuring training throughput here, exactly as
    # bench.py's measured window excludes eval.
    api._local_test_on_all_clients = lambda round_idx: None
    import numpy as np
    t0 = time.time()
    api.train()
    wall = time.time() - t0

    # samples actually trained across the measured rounds (same sampler:
    # np.random.seed(round_idx) choice, fedavg_api.py:127-136)
    total_samples = 0
    for r in range(args.comm_round):
        np.random.seed(r)
        picked = np.random.choice(range(args.client_num_in_total),
                                  args.client_num_per_round, replace=False)
        total_samples += sum(local_num[int(c)] for c in picked)

    print(json.dumps({
        "what": "reference_sp_fedavg_resnet56_cifar10_northstar",
        "host": "cpu_torch_1core",
        "clients_total": args.client_num_in_total,
        "clients_per_round": args.client_num_per_round,
        "rounds": args.comm_round,
        "wall_s": round(wall, 2),
        "sec_per_round": round(wall / args.comm_round, 2),
        "rounds_per_sec": round(args.comm_round / wall, 5),
        "samples_per_sec": round(total_samples / wall, 1),
    }))


if __name__ == "__main__":
    main()
