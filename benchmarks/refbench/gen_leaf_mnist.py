"""Generate a deterministic synthetic MNIST in LEAF JSON format.

Produces the exact on-disk layout the reference's MNIST loader expects
(`/root/reference/python/fedml/data/MNIST/data_loader.py:33-66` `read_data`:
train/ and test/ dirs of .json files with keys "users", "num_samples",
"user_data" -> {user: {"x": [[784 floats]], "y": [ints]}}), plus an .npz
mirror consumed by fedml_tpu's natural-partition loader so BOTH frameworks
train on byte-identical data.

Zero-egress substitution for the real FedML MNIST.zip (1000 LEAF users):
we emit --users users (default 100) with power-law sample counts, 10 gaussian
class clusters in 784-dim, pixel range [0, 1]. Deterministic under --seed.
"""

import argparse
import json
import os

import numpy as np


def make_class_means(rng: np.random.Generator, n_classes: int = 10,
                     dim: int = 784, support: int = 150,
                     pool: int = 260) -> np.ndarray:
    """Sparse class means: `support` active pixels per class, like digit
    strokes. Supports are drawn from a shared `pool` of pixels so classes
    overlap and the problem is not linearly trivial."""
    means = np.zeros((n_classes, dim), dtype=np.float64)
    shared = rng.choice(dim, size=pool, replace=False)
    for c in range(n_classes):
        idx = rng.choice(shared, size=support, replace=False)
        means[c, idx] = rng.uniform(0.3, 0.8, size=support)
    return means


def gen(out_dir: str, users: int = 100, seed: int = 42,
        mean_train: int = 60, test_frac: float = 0.2) -> dict:
    rng = np.random.default_rng(seed)
    means = make_class_means(rng)
    n_classes = means.shape[0]

    # Power-law-ish user sizes, and per-user label distribution (2 dominant
    # classes per user -> natural non-IID, like LEAF's writer split).
    sizes = np.clip(rng.pareto(2.5, size=users) * mean_train * 0.6 + 20,
                    20, mean_train * 3).astype(int)

    user_names = [f"f_{i:05d}" for i in range(users)]
    train_data, test_data = {}, {}
    num_train, num_test = [], []
    for u, n in zip(user_names, sizes):
        n_test = max(2, int(n * test_frac))
        dom = rng.choice(n_classes, size=2, replace=False)
        probs = np.full(n_classes, 0.1 / (n_classes - 2))
        probs[dom] = 0.45
        probs /= probs.sum()
        ys = rng.choice(n_classes, size=n + n_test, p=probs)
        noise = rng.normal(0.0, 0.55, size=(n + n_test, means.shape[1]))
        active = (means[ys] > 0) | (rng.random(noise.shape) < 0.08)
        xs = np.clip(means[ys] + noise * active, 0.0, 1.0)
        xs = np.round(xs, 4)
        train_data[u] = {"x": xs[:n].tolist(), "y": ys[:n].tolist()}
        test_data[u] = {"x": xs[n:].tolist(), "y": ys[n:].tolist()}
        num_train.append(int(n))
        num_test.append(int(n_test))

    for split, data, nums in (("train", train_data, num_train),
                              ("test", test_data, num_test)):
        d = os.path.join(out_dir, "MNIST", split)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "all_data_0_niid_0_keep_10_%s_9.json" % split),
                  "w") as f:
            json.dump({"users": user_names, "num_samples": nums,
                       "user_data": data}, f)

    # npz mirror for fedml_tpu's natural-partition loader: one array pair per
    # user, keys "x_<user>" / "y_<user>" per split.
    npz_train = {}
    npz_test = {}
    for u in user_names:
        npz_train["x_" + u] = np.asarray(train_data[u]["x"], dtype=np.float32)
        npz_train["y_" + u] = np.asarray(train_data[u]["y"], dtype=np.int32)
        npz_test["x_" + u] = np.asarray(test_data[u]["x"], dtype=np.float32)
        npz_test["y_" + u] = np.asarray(test_data[u]["y"], dtype=np.int32)
    np.savez_compressed(os.path.join(out_dir, "leaf_mnist_train.npz"), **npz_train)
    np.savez_compressed(os.path.join(out_dir, "leaf_mnist_test.npz"), **npz_test)
    return {"users": users, "train_samples": int(sum(num_train)),
            "test_samples": int(sum(num_test))}


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=os.path.expanduser("~/.cache/fedml_data"))
    p.add_argument("--users", type=int, default=100)
    p.add_argument("--seed", type=int, default=42)
    a = p.parse_args()
    info = gen(a.out, users=a.users, seed=a.seed)
    print(json.dumps(info))
