#!/usr/bin/env python
"""Transport benchmark matrix for the cross-silo plane.

{inproc, grpc, mqtt} × {sync, async} × {none, quantize, sparsify} × WAN
profile → round time, bytes-on-wire, accuracy-at-round — the measurement
ROADMAP item 5 calls for (transport choice + payload size dominate WAN
round time; until this file neither had ever been measured here).

Codecs map to ``--wire-compression`` specs:

* ``none``      — raw f32 pytrees both directions;
* ``quantize``  — ``int8`` blocked delta quantization (+ int8 downlink).
  NOTE: int8's reduction ceiling is 4.0x by construction (8 of 32 bits);
  with scale/framing overhead it lands ≈3.9x;
* ``sparsify``  — ``topk8:0.1`` (top-10% delta coords, int8-quantized,
  error feedback) — the fused quantize+sparsify delta codec, ≥4x
  end-to-end including the int8 downlink.

The WAN-straggler soak (acceptance): 5 silos, one on ``wan-lossy`` at
10x latency; async (buffer_k=3, flush 2 s) must sustain ≥3x the sync
round-completion rate at equal final accuracy, and the sparsify codec
must cut total bytes-on-wire ≥4x at equal accuracy — both checked by
``--guard`` (exit 2 on regression; the CI async-soak step runs
``--quick --guard``).

The hierarchy soak (acceptance for the geo-distributed tier): 3 regions
× 5 silos vs a flat 15-silo federation, both over ``wan-lossy``.  The
hierarchy ships one pre-reduced int8 delta per region per segment, so
its bytes-on-WAN (``fedml_wan_bytes_total``) must land ≤ 1/3 of the
flat run's total wire bytes at equal accuracy (same ``--guard``), and
the result lands as a provenance-stamped ``perf_history.jsonl`` row.

Usage:
    python benchmarks/bench_transports.py --quick --guard \
        --out benchmarks/bench_transports_quick.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import fedml_tpu  # noqa: E402
from fedml_tpu.arguments import Config
from fedml_tpu.core.distributed.communication.chaos import (
    ChaosProfile,
    chaos_from_profile,
)
from fedml_tpu.core.distributed.fedml_comm_manager import (
    register_comm_backend,
)
from fedml_tpu.core.mlops import metrics
from fedml_tpu.cross_silo.runner import init_client, init_server

CODECS = {"none": None, "quantize": "int8", "sparsify": "topk8:0.1"}

#: an unimpaired counting profile — the chaos wrapper still accounts
#: bytes, so every transport's payload traffic is measured the same way
LAN = ChaosProfile("lan")

PROFILES: Dict[str, Any] = {"lan": LAN, "wan-good": "wan-good",
                            "wan-lossy": "wan-lossy"}

_GRPC_PORT = [21000]  # unique port block per grpc cell


def _base_args(run_id: str, **kw) -> Any:
    # mnist-shaped synthetic data + lr → a 7.8k-param model (~31 KB/f32
    # payload): big enough that codec framing is noise, small enough that
    # every cell trains in seconds on CPU
    base = dict(
        training_type="cross_silo", dataset="mnist", model="lr",
        client_num_in_total=3, client_num_per_round=3, comm_round=3,
        epochs=1, batch_size=16, learning_rate=0.1, data_scale=0.1,
        frequency_of_the_test=1, enable_tracking=False,
        compute_dtype="float32", run_id=run_id)
    base.update(kw)
    return fedml_tpu.init(Config(**base))


def _register_profile_backend(name: str, transport: str, profile: Any,
                              straggler_rank: Optional[int] = None,
                              straggler_scale: float = 1.0) -> None:
    def factory(args, rank=0, size=0):
        if transport == "inproc":
            from fedml_tpu.core.distributed.communication.inprocess import (
                InProcCommManager,
            )

            inner = InProcCommManager(rank, size, str(args.run_id))
        elif transport == "grpc":
            from fedml_tpu.core.distributed.communication.grpc import (
                GRPCCommManager,
            )

            inner = GRPCCommManager(args=args, rank=rank, size=size)
        elif transport == "mqtt":
            from fedml_tpu.core.distributed.communication.mqtt_s3 import (
                MqttS3CommManager,
            )

            inner = MqttS3CommManager(args=args, rank=rank, size=size)
        else:
            raise ValueError(transport)
        prof = profile
        scale = 1.0
        if straggler_rank is not None and rank == straggler_rank:
            prof, scale = "wan-lossy", straggler_scale
        return chaos_from_profile(inner, prof, seed=1000 + rank,
                                  latency_scale=scale)

    register_comm_backend(name, factory)


def _wire_bytes(run_id: str) -> Dict[str, float]:
    m = metrics.REGISTRY.collect().get("fedml_wire_bytes_total")
    out: Dict[str, float] = {"up": 0.0, "down": 0.0}
    if m is None:
        return out
    for key, child in list(m._children.items()):
        rid, direction, _codec = key
        if rid == run_id and direction in out:
            out[direction] += child.value
    out["total"] = out["up"] + out["down"]
    return out


def _federate(args: Any, backend: str, n_clients: int,
              join_timeout: float = 60.0) -> Dict[str, Any]:
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle, backend=backend)
    clients = [init_client(args, dataset, bundle, rank, backend=backend)
               for rank in range(1, n_clients + 1)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    server.run()
    wall = time.monotonic() - t0
    for t in threads:
        t.join(timeout=join_timeout)
    hist = server.aggregator.metrics_history
    return {"wall_s": round(wall, 3),
            "final": hist[-1] if hist else {},
            "acc_at_round": [
                {"round": h.get("round"), "test_acc": h.get("test_acc")}
                for h in hist]}


def run_cell(transport: str, mode: str, codec: str, profile: str,
             rounds: int, cell_timeout_s: float = 180.0) -> Dict[str, Any]:
    run_id = f"bt_{transport}_{mode}_{codec}_{profile}"
    backend = f"BENCH_{run_id}".upper()
    _register_profile_backend(backend, transport, PROFILES[profile])
    kw: Dict[str, Any] = {"comm_round": rounds}
    if CODECS[codec]:
        kw["wire_compression"] = CODECS[codec]
    if mode == "async":
        kw.update(async_agg=True, async_buffer_k=2)
    if profile != "lan":
        # lossy profiles DROP messages: without the reliability plane (and
        # a round-timer backstop for what outlives its retransmit
        # deadline) a sync cell would stall forever on one lost upload
        kw.update(reliable=True, reliable_retx_initial_s=0.2,
                  reliable_retx_max_s=1.0, round_timeout_s=15.0,
                  min_clients_per_round=2)
    if transport == "grpc":
        _GRPC_PORT[0] += 20
        kw["grpc_base_port"] = _GRPC_PORT[0]
    if transport == "mqtt":
        kw["mqtt_broker"] = "inproc"
    args = _base_args(run_id, **kw)
    cell = {"transport": transport, "mode": mode, "codec": codec,
            "profile": profile, "rounds": rounds}
    box: Dict[str, Any] = {}

    def _worker():
        try:
            box["res"] = _federate(args, backend, n_clients=3)
        except Exception as e:  # noqa: BLE001 — a transport missing from
            # the environment (no grpc wheel, no broker) skips its cells,
            # it does not kill the matrix
            box["err"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_worker, daemon=True)
    t.start()
    t.join(timeout=cell_timeout_s)
    if t.is_alive():
        cell["skipped"] = f"timeout after {cell_timeout_s:.0f}s"
        return cell
    if "err" in box:
        cell["skipped"] = box["err"]
        return cell
    res = box["res"]
    bytes_on_wire = _wire_bytes(run_id)
    cell.update(
        wall_s=res["wall_s"],
        rounds_per_s=round(rounds / max(res["wall_s"], 1e-9), 3),
        bytes_up=bytes_on_wire["up"], bytes_down=bytes_on_wire["down"],
        bytes_total=bytes_on_wire["total"],
        test_acc=res["final"].get("test_acc"),
        test_loss=res["final"].get("test_loss"),
        acc_at_round=res["acc_at_round"])
    return cell


def run_straggler_soak(rounds: int = 12) -> Dict[str, Any]:
    """The acceptance soak: one wan-lossy silo at 10x latency among 5.
    Sync pays the straggler every round (bounded by its round timer);
    async force-starts on the fast four, flushes on the 3 fastest, and
    folds the straggler's stale uploads with decayed weight.  Server-side
    eval runs once at the end (it is identical work in both modes and
    would otherwise mask the round-time contrast being measured)."""
    n = 5
    common = dict(client_num_in_total=n, client_num_per_round=n,
                  comm_round=rounds, reliable=True,
                  reliable_retx_initial_s=0.2, reliable_retx_max_s=1.0,
                  frequency_of_the_test=rounds)
    clean = _federate(_base_args("bt_soak_clean", **common), "INPROC", n)

    _register_profile_backend("BT_SOAK_SYNC", "inproc", "wan-good",
                              straggler_rank=n, straggler_scale=10.0)
    sync = _federate(_base_args(
        "bt_soak_sync", round_timeout_s=8.0, min_clients_per_round=3,
        **common), "BT_SOAK_SYNC", n)

    _register_profile_backend("BT_SOAK_ASYNC", "inproc", "wan-good",
                              straggler_rank=n, straggler_scale=10.0)
    asn = _federate(_base_args(
        "bt_soak_async", async_agg=True, async_buffer_k=3, async_flush_s=2.0,
        async_staleness="poly:0.5", wire_compression="int8",
        round_timeout_s=1.0, min_clients_per_round=3,
        **common), "BT_SOAK_ASYNC", n)

    sync_rate = rounds / max(sync["wall_s"], 1e-9)
    async_rate = rounds / max(asn["wall_s"], 1e-9)
    return {
        "silos": n, "rounds": rounds, "straggler": "wan-lossy @ 10x latency",
        "clean_acc": clean["final"].get("test_acc"),
        "sync": {"wall_s": sync["wall_s"],
                 "rounds_per_s": round(sync_rate, 3),
                 "test_acc": sync["final"].get("test_acc")},
        "async": {"wall_s": asn["wall_s"],
                  "rounds_per_s": round(async_rate, 3),
                  "test_acc": asn["final"].get("test_acc"),
                  "bytes_total": _wire_bytes("bt_soak_async")["total"]},
        "sync_bytes_total": _wire_bytes("bt_soak_sync")["total"],
        "round_rate_ratio": round(async_rate / max(sync_rate, 1e-9), 2),
    }


def _wan_bytes(run_id: str) -> Dict[str, float]:
    """Bytes that crossed the WAN tier of the aggregation hierarchy
    (``fedml_wan_bytes_total`` — regional folds up, segment broadcasts
    down; LAN silo traffic excluded by construction)."""
    m = metrics.REGISTRY.collect().get("fedml_wan_bytes_total")
    out: Dict[str, float] = {"up": 0.0, "down": 0.0}
    if m is None:
        return out
    for key, child in list(m._children.items()):
        rid, direction = key
        if rid == run_id and direction in out:
            out[direction] += child.value
    out["total"] = out["up"] + out["down"]
    return out


def run_hierarchy_soak(rounds: int = 3,
                       timeout_s: float = 300.0) -> Dict[str, Any]:
    """Hierarchy acceptance: 3 regions x 5 silos vs a flat 15-silo
    federation, both crossing a wan-lossy WAN.

    Flat pays the WAN for every silo (15 uploads + 15 broadcasts per
    round); the hierarchy folds each region's silos on its clean LAN and
    ships ONE pre-reduced int8 delta per region per segment, so its
    bytes-on-WAN must land at <= 1/3 of the flat run's at equal accuracy
    (``--guard``; fan-in alone gives ~5x, the delta codec ~4x more)."""
    from fedml_tpu.cross_silo.hierarchical.message_define import HierMessage
    from fedml_tpu.cross_silo.runner import build_cross_silo_runner

    n, n_regions = 15, 3
    common = dict(client_num_in_total=n, client_num_per_round=n,
                  comm_round=rounds, data_scale=0.1,
                  frequency_of_the_test=rounds, reliable=True,
                  reliable_retx_initial_s=0.2, reliable_retx_max_s=1.0)

    # -- flat: every silo crosses the lossy WAN ------------------------------
    _register_profile_backend("BT_HIER_FLAT", "inproc", "wan-lossy")
    flat_args = _base_args("bt_hier_flat", round_timeout_s=20.0,
                           min_clients_per_round=n - 3, **common)
    box: Dict[str, Any] = {}

    def _flat_worker():
        try:
            box["flat"] = _federate(flat_args, "BT_HIER_FLAT", n)
        except Exception as e:  # noqa: BLE001 — report, don't kill the bench
            box["err"] = f"flat: {type(e).__name__}: {e}"

    t = threading.Thread(target=_flat_worker, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive() or "err" in box:
        return {"skipped": box.get(
            "err", f"flat cell timeout after {timeout_s:.0f}s")}
    flat = box["flat"]
    flat_wan = _wire_bytes("bt_hier_flat")  # flat: ALL wire bytes are WAN

    # -- hierarchy: only the 3 regional uplinks cross that same WAN ----------
    def hier_wan_factory(args, rank=0, size=0):
        from fedml_tpu.core.distributed.communication.inprocess import (
            InProcCommManager,
        )

        return chaos_from_profile(
            InProcCommManager(rank, size, str(args.run_id)), "wan-lossy",
            seed=2000 + rank,
            protect_types={HierMessage.MSG_TYPE_G2R_FINISH})

    register_comm_backend("BT_HIER_WAN", hier_wan_factory)
    hier_args = _base_args(
        "bt_hier_tree", backend="INPROC", hier_regions=n_regions,
        hier_wan_backend="BT_HIER_WAN", hier_wan_reliable=True,
        hier_wan_compression="int8", min_regions=2,
        hier_round_deadline_s=30.0, **common)
    dataset = fedml_tpu.data.load(hier_args)
    bundle = fedml_tpu.model.create(hier_args, dataset[-1])
    runner = build_cross_silo_runner(hier_args, None, dataset, bundle)
    t0 = time.monotonic()
    runner.launch()
    final = runner.wait(timeout=timeout_s)
    hier_wall = time.monotonic() - t0
    if runner._global_thread.is_alive():
        return {"skipped": f"hier run timeout after {timeout_s:.0f}s"}
    hier_wan = _wan_bytes("bt_hier_tree")
    hist = runner.global_manager.aggregator.metrics_history

    flat_rate = rounds / max(flat["wall_s"], 1e-9)
    hier_rate = rounds / max(hier_wall, 1e-9)
    ratio = flat_wan["total"] / max(hier_wan["total"], 1e-9)
    return {
        "silos": n, "regions": n_regions, "rounds": rounds,
        "profile": "wan-lossy",
        "flat": {"wall_s": flat["wall_s"],
                 "rounds_per_s": round(flat_rate, 3),
                 "wan_bytes": flat_wan["total"],
                 "test_acc": flat["final"].get("test_acc"),
                 "acc_at_round": flat["acc_at_round"]},
        "hier": {"wall_s": round(hier_wall, 3),
                 "rounds_per_s": round(hier_rate, 3),
                 "wan_bytes": hier_wan["total"],
                 "wan_bytes_up": hier_wan["up"],
                 "wan_bytes_down": hier_wan["down"],
                 "test_acc": final.get("test_acc"),
                 "acc_at_round": [
                     {"round": h.get("round"), "test_acc": h.get("test_acc")}
                     for h in hist]},
        "wan_bytes_ratio": round(ratio, 2),
    }


def check_guard(cells: List[Dict], soak: Dict,
                hier: Optional[Dict] = None) -> List[str]:
    """Bytes-on-wire + straggler regression guard (CI async-soak step).
    Returns a list of violations (empty = pass)."""
    bad: List[str] = []
    by_key = {(c["transport"], c["mode"], c["profile"], c["codec"]): c
              for c in cells if "skipped" not in c}
    for (tr, mode, prof, codec), c in by_key.items():
        if codec != "sparsify":
            continue
        base = by_key.get((tr, mode, prof, "none"))
        if base is None or not base.get("bytes_total"):
            continue
        ratio = base["bytes_total"] / max(c["bytes_total"], 1e-9)
        if ratio < 4.0:
            bad.append(f"{tr}/{mode}/{prof}: sparsify bytes reduction "
                       f"{ratio:.2f}x < 4x")
        if (base.get("test_acc") is not None
                and c.get("test_acc") is not None
                and abs(base["test_acc"] - c["test_acc"]) > 0.15):
            bad.append(f"{tr}/{mode}/{prof}: sparsify accuracy "
                       f"{c['test_acc']:.3f} vs {base['test_acc']:.3f} "
                       f"(> 0.15 apart)")
    if soak:
        if soak["round_rate_ratio"] < 3.0:
            bad.append(f"soak: async/sync round-completion ratio "
                       f"{soak['round_rate_ratio']}x < 3x")
        ca, aa = soak.get("clean_acc"), soak["async"].get("test_acc")
        if ca is not None and aa is not None and abs(ca - aa) > 0.15:
            bad.append(f"soak: async acc {aa:.3f} vs clean {ca:.3f}")
    if hier and "skipped" not in hier:
        if hier["wan_bytes_ratio"] < 3.0:
            bad.append(f"hierarchy: WAN bytes flat/hier ratio "
                       f"{hier['wan_bytes_ratio']}x < 3x — the pre-reduced "
                       f"regional fold is not earning its tier")
        fa = hier["flat"].get("test_acc")
        ha = hier["hier"].get("test_acc")
        if fa is not None and ha is not None and abs(fa - ha) > 0.15:
            bad.append(f"hierarchy: hier acc {ha:.3f} vs flat {fa:.3f} "
                       f"(> 0.15 apart)")
    return bad


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--quick", action="store_true",
                   help="inproc only, lan profile, + the straggler soak")
    p.add_argument("--guard", action="store_true",
                   help="exit 2 when the bytes/straggler guard fails")
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--no-soak", action="store_true")
    p.add_argument("--no-hier", action="store_true",
                   help="skip the 3x5-vs-flat-15 hierarchy soak")
    p.add_argument("--out", default=None, help="write JSON here")
    a = p.parse_args(argv)

    transports = ["inproc"] if a.quick else ["inproc", "grpc", "mqtt"]
    profiles = ["lan"] if a.quick else ["lan", "wan-good", "wan-lossy"]
    cells: List[Dict] = []
    for transport in transports:
        for profile in profiles:
            if transport != "inproc" and profile != "lan":
                # WAN emulation wraps the transport identically — the
                # non-lan rows only vary payload timing, measured once on
                # the in-process transport to keep the matrix affordable
                continue
            for mode in ("sync", "async"):
                for codec in ("none", "quantize", "sparsify"):
                    print(f"[bench_transports] {transport}/{mode}/{codec}"
                          f"/{profile} ...", flush=True)
                    cells.append(run_cell(transport, mode, codec, profile,
                                          a.rounds))

    soak = {} if a.no_soak else run_straggler_soak()
    if a.no_hier:
        hier = {}
    else:
        print("[bench_transports] hierarchy 3x5-vs-flat-15 / wan-lossy ...",
              flush=True)
        hier = run_hierarchy_soak(rounds=a.rounds)
    violations = check_guard(cells, soak, hier)
    if hier and "skipped" not in hier:
        # provenance-stamped headline so `fedml perf history` carries the
        # hierarchy's WAN-byte win and round rate forward (hier_* keys are
        # deliberately NOT in HEADLINE_METRICS — they must not be compared
        # against the flat-plane rounds_per_s series)
        try:
            import jax

            from fedml_tpu.core.mlops import perf_history

            perf_history.append_entry(
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "perf_history.jsonl"),
                platform=jax.default_backend(),
                source="bench_transports.py",
                label="hier_3x5_vs_flat15_wanlossy", measured=True,
                notes=(f"WAN bytes flat/hier {hier['wan_bytes_ratio']}x, "
                       f"hier acc {hier['hier'].get('test_acc')}"),
                metrics={
                    "hier_wan_bytes_ratio": hier["wan_bytes_ratio"],
                    "hier_rounds_per_s": hier["hier"]["rounds_per_s"]})
        except Exception:  # noqa: BLE001 — bookkeeping never fails the bench
            pass
    report = {
        "bench": "transports",
        "quick": bool(a.quick),
        "matrix": {"transports": transports, "profiles": profiles,
                   "modes": ["sync", "async"],
                   "codecs": {k: v or "raw" for k, v in CODECS.items()}},
        "cells": cells,
        "straggler_soak": soak,
        "hierarchy_soak": hier,
        "guard_violations": violations,
    }
    out = json.dumps(report, indent=2, default=float)
    if a.out:
        with open(a.out, "w") as f:
            f.write(out + "\n")
        print(f"[bench_transports] wrote {a.out}")
    else:
        print(out)
    if violations:
        print("[bench_transports] GUARD FAILED:", *violations, sep="\n  ")
        return 2 if a.guard else 0
    print("[bench_transports] guard clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
