"""Convergence-parity audit: reference SP vs fedml_tpu SP on identical
bytes, identical sampling, identical initial weights.

For each optimizer (FedAvg / FedProx / SCAFFOLD) this script:
1. runs the reference's own SP trainer on CPU
   (refbench/run_reference_sp.py, stubs on PYTHONPATH) — which also exports
   its exact initial weights;
2. runs fedml_tpu's SP plane on the same LEAF-MNIST natural partition
   starting FROM those weights (parity_fedml_tpu_sp.py);
3. diffs the per-round test accuracy/loss curves.

Writes benchmarks/parity_results.json and docs/PARITY.md (curve table +
the documented deviations), and exits non-zero if any per-round |Δacc|
exceeds the tolerance.
"""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
STUBS = os.path.join(HERE, "refbench", "stubs")
#: where parity_results.json / PARITY.md land — tests override this to a
#: tmp dir so a shortened-horizon CI run never clobbers the committed
#: full-horizon artifacts
OUT_DIR = os.environ.get("PARITY_OUT_DIR", HERE)
DOC_DIR = os.environ.get("PARITY_OUT_DIR",
                         os.path.join(REPO, "docs"))
ROUNDS = int(os.environ.get("PARITY_ROUNDS", "30"))
#: three-tier criterion: the early window must match numerically (identical
#: init + identical batches + identical math ⇒ identical evals before
#: float-accumulation chaos kicks in); mid-curve may wobble in the steep
#: region; the plateau must agree.
TOL_EARLY = 0.005       # rounds 0..EARLY_ROUNDS: numerical-parity window
TOL_EARLY_LOSS = 0.003  # |Δ test_loss| in the early window (catches loss-
                        # math/semantic drift that acc quantization hides —
                        # round-3 lesson: early acc matched while a round-0
                        # training deviation sat in the loss)
EARLY_ROUNDS = 4
TOL_ROUND = 0.12        # any round: gross-divergence bound
TOL_FINAL = 0.02        # final-round |Δ test_acc|
OPTIMIZERS = ["FedAvg", "FedProx", "SCAFFOLD", "FedNova", "FedDyn",
              "Mime"]
#: conv-plane rounds: the reference CNN_DropOut on CPU costs ~50 s/round
#: (100-client eval each round), so the conv audit runs a shorter window
#: by default; PARITY_CNN_ROUNDS=30 reproduces the full lr-plane window.
CNN_ROUNDS = int(os.environ.get("PARITY_CNN_ROUNDS", "8"))
#: (optimizer, model) planes: every optimizer on lr, plus the conv plane
#: (reference CNN_DropOut, model_hub.py:32-37) on FedAvg.  The conv plane
#: MUST run with the same round-0 chain-compat flag as lr-FedAvg — without
#: it the round-0 sequential-chaining deviation (docs/PARITY.md item 1)
#: shows up as a ~0.1 early-window loss drift that decays by round 3 while
#: accuracy stays identical (root-caused round 5: that drift is the
#: chain-compat flag missing, not conv semantics; with the flag the curves
#: match to 1e-4).
PLANES = [(opt, "lr", ROUNDS) for opt in OPTIMIZERS] + [
    ("FedAvg", "cnn", CNN_ROUNDS)]


def _run(cmd, env=None, timeout=900):
    e = dict(os.environ)
    if env:
        e.update(env)
    out = subprocess.run(cmd, capture_output=True, text=True, env=e,
                         timeout=timeout)
    for line in (out.stdout + out.stderr).splitlines():
        if line.startswith("PARITY_JSON ") or " PARITY_JSON " in line:
            return json.loads(line.split("PARITY_JSON ", 1)[1])
    raise RuntimeError(f"no PARITY_JSON from {cmd}:\n{out.stderr[-2000:]}")


def main() -> None:
    results = {}
    failures = []
    for opt, model, rounds in PLANES:
        plane = opt if model == "lr" else f"{opt}_{model}"
        ref = _run([sys.executable,
                    os.path.join(HERE, "refbench", "run_reference_sp.py"),
                    "--optimizer", opt, "--rounds", str(rounds),
                    "--model", model],
                   env={"PYTHONPATH":
                        f"{STUBS}:/root/reference/python"},
                   # the reference CNN costs ~50 s/round on CPU
                   timeout=(900 if model == "lr" else 120 * rounds))
        mine_cmd = [sys.executable,
                    os.path.join(HERE, "parity_fedml_tpu_sp.py"),
                    "--optimizer", opt, "--rounds", str(rounds),
                    "--model", model]
        # per-optimizer reference-bug compat flags (each reproduces the
        # reference's OWN implementation exactly; docs/PARITY.md lists
        # what each flag stands in for)
        if opt == "SCAFFOLD":
            mine_cmd.append("--scaffold-ref-bug-compat")
        elif opt == "FedDyn":
            mine_cmd += ["--feddyn-ref-bug-compat",
                         "--fedavg-ref-chain-compat"]
        elif opt == "Mime":
            mine_cmd.append("--mime-ref-compat")
        elif opt == "FedNova":
            pass   # the reference FedNova trainer is clean: no compat
        else:
            # reproduce the reference's round-0 sequential-client chaining
            # (state_dict aliasing — root-caused in parity_round0_oracle.py)
            mine_cmd.append("--fedavg-ref-chain-compat")
        mine = _run(mine_cmd, env={"JAX_PLATFORMS": "cpu",
                                   "PYTHONPATH": REPO},
                    timeout=(900 if model == "lr" else 120 * rounds))
        rows = []
        max_d = 0.0
        for r in range(rounds):
            ra = ref["per_round"].get(str(r), {})
            ma = mine["per_round"].get(str(r), {})
            if "Test/Acc" not in ra or "Test/Acc" not in ma:
                continue
            d = abs(ra["Test/Acc"] - ma["Test/Acc"])
            max_d = max(max_d, d)
            rows.append({"round": r, "ref_acc": ra["Test/Acc"],
                         "tpu_acc": ma["Test/Acc"], "abs_diff": d,
                         "ref_loss": ra.get("Test/Loss"),
                         "tpu_loss": ma.get("Test/Loss")})
        early_d = max((r["abs_diff"] for r in rows
                       if r["round"] <= EARLY_ROUNDS), default=0.0)
        early_loss_d = max(
            (abs(r["ref_loss"] - r["tpu_loss"]) for r in rows
             if r["round"] <= EARLY_ROUNDS
             and r.get("ref_loss") is not None
             and r.get("tpu_loss") is not None), default=0.0)
        final_d = abs(ref.get("test_acc", 0) - mine.get("test_acc", 0))
        results[plane] = {"rounds": rows, "max_abs_acc_diff": max_d,
                        "early_window_diff": early_d,
                        "early_window_loss_diff": early_loss_d,
                        "final_abs_diff": final_d,
                        "final_ref_acc": ref.get("test_acc"),
                        "final_tpu_acc": mine.get("test_acc")}
        if early_d > TOL_EARLY:
            failures.append(f"{plane}: early-window diff {early_d:.4f}")
        if early_loss_d > TOL_EARLY_LOSS:
            failures.append(
                f"{plane}: early-window LOSS diff {early_loss_d:.4f}")
        if max_d > TOL_ROUND:
            failures.append(f"{plane}: per-round diff {max_d:.4f}")
        if final_d > TOL_FINAL:
            failures.append(f"{plane}: final diff {final_d:.4f}")
        print(f"{plane}: early |d| = {early_d:.4f} "
              f"(loss {early_loss_d:.4f}), max |d| = {max_d:.4f}, "
              f"final ref={ref.get('test_acc'):.4f} "
              f"tpu={mine.get('test_acc'):.4f}")

    os.makedirs(OUT_DIR, exist_ok=True)
    with open(os.path.join(OUT_DIR, "parity_results.json"), "w") as f:
        json.dump({"rounds": ROUNDS,
                   "cnn_rounds": CNN_ROUNDS,
                   "tolerances": {"early": TOL_EARLY,
                                  "early_rounds": EARLY_ROUNDS,
                                  "per_round": TOL_ROUND,
                                  "final": TOL_FINAL},
                   "results": {o: {k: v for k, v in r.items()
                                   if k != "rounds"}
                               for o, r in results.items()},
                   "curves": {o: r["rounds"] for o, r in results.items()},
                   }, f, indent=1)

    _write_doc(results)
    if failures:
        print("PARITY FAIL: " + "; ".join(failures))
        sys.exit(1)
    print("PARITY OK")


def _write_doc(results) -> None:
    lines = [
        "# Convergence parity: fedml_tpu vs reference FedML (SP plane)",
        "",
        "Same bytes (LEAF-MNIST, 100 synthetic users, "
        "`benchmarks/refbench/gen_leaf_mnist.py`), same natural per-user "
        "partition, same `np.random.seed(round)` client sampling, same "
        "config (2 clients/round, bs 10, lr 0.03, 1 epoch), and the SAME "
        "initial weights (the reference run exports its torch init; the "
        "fedml_tpu run loads it). Reference runs its own code from "
        "`/root/reference/python` on CPU. Regenerate: "
        "`python benchmarks/parity_audit.py`.",
        "",
    ]
    for opt, r in results.items():
        lines += [f"## {opt}",
                  "",
                  "| round | reference acc | fedml_tpu acc | abs diff |",
                  "|---|---|---|---|"]
        last_round = max((row["round"] for row in r["rounds"]), default=0)
        for row in r["rounds"]:
            if row["round"] % 3 == 0 or row["round"] == last_round:
                lines.append(
                    f"| {row['round']} | {row['ref_acc']:.4f} | "
                    f"{row['tpu_acc']:.4f} | {row['abs_diff']:.4f} |")
        lines += [
            "",
            f"Early window (rounds 0-{EARLY_ROUNDS}) max |acc diff|: "
            f"**{r['early_window_diff']:.4f}** — identical init + "
            "identical batches reproduce the reference numerics exactly "
            "until float accumulation diverges; max per-round diff "
            f"**{r['max_abs_acc_diff']:.4f}** (steep mid-curve wobble); "
            f"final diff **{r['final_abs_diff']:.4f}**.", ""]
    lines += [
        "## Documented deviations (SURVEY §7 hard part f)",
        "",
        "1. **Round-0 sequential-client chaining in the reference** "
        "(root-caused round 3, `benchmarks/parity_round0_oracle.py`): "
        "`simulation/sp/fedavg/fedavg_api.py:75` takes `w_global = "
        "get_model_params()`, a state_dict ALIASING the live model "
        "tensors; the per-client `copy.deepcopy(w_global)` therefore "
        "snapshots the PREVIOUS client's trained weights, so round-0 "
        "clients chain sequentially (extra optimization steps — a "
        "permanent head start in the curve). Rounds >= 1 aggregate into "
        "a fresh dict, so only round 0 chains. fedml_tpu's default "
        "implements true FedAvg (every client starts from the round's "
        "global model); the audit runs `fedavg_ref_chain_compat: true` "
        "to reproduce the reference bit-for-bit — the 0.0000 per-round "
        "diffs above are WITH that flag. Before root-causing this, the "
        "audit showed a constant +0.008 loss offset from round 0 and a "
        "one-sided 3-5pp late-curve accuracy gap.",
        "2. **SCAFFOLD aggregation bugs in the reference** — "
        "`ml/aggregator/agg_operator.py:100-118` computes the weighted "
        "sum of client deltas, then overwrites it with the LAST client's "
        "delta (`total_weights_delta[k] = weights_delta[k]` after the "
        "loop), and applies only the last client's c-delta/N. "
        "Additionally `sp/scaffold/client.py:44-56` never writes "
        "c_model_local back (it rebinds state_dict slots, not module "
        "params), so client control variates stay ZERO; and the "
        "c-correction `param.data = param.data - ...` "
        "(`ml/trainer/scaffold_trainer.py:166-170`) REBINDS param.data, "
        "freezing the aliased w_global at w0 + the first client's first "
        "plain-SGD step — later round-0 clients start there. fedml_tpu's "
        "default implements the published algorithm (true weighted "
        "average, summed c-deltas, live c_locals). The audit runs "
        "`scaffold_ref_bug_compat: true`, which reproduces ALL of the "
        "above bit-for-bit (0.0000 per-round diffs); production configs "
        "get the fix.",
        "3. **SGD ignores weight_decay in the reference** — "
        "`ml/trainer/my_model_trainer_classification.py:29-33` passes "
        "only lr to torch.optim.SGD even though configs carry "
        "weight_decay. fedml_tpu applies weight decay when configured; "
        "parity runs set `weight_decay: 0` to match the reference's "
        "effective behavior.",
        "4. **The reference `lr` model applies sigmoid before "
        "CrossEntropyLoss** (`model/linear/lr.py:11`), bounding logits to "
        "[0,1] (slower convergence, loss floor ~2.0). fedml_tpu defaults "
        "to plain logits; `lr_sigmoid_outputs: true` (used here) "
        "reproduces the reference model exactly.",
        "5. **Batch order within a client** — the reference shuffles each "
        "user's samples once with `np.random.seed(100)` at load "
        "(`data/MNIST/data_loader.py:batch_data`); fedml_tpu batches in "
        "stored order. Different order, same set; the curves above show "
        "the residual effect.",
        "6. **Fused Parrot rounds sample on-device** "
        "(`simulation/parrot/parrot_api.py` run_rounds_fused): same "
        "distribution, different draws than the host "
        "`np.random.seed(round)` stream. The per-round (non-fused) path "
        "keeps reference-identical sampling and is what this audit runs.",
        "7. **FedDyn's reference regularization is gradient-dead** — "
        "`ml/trainer/feddyn_trainer.py:45-60` computes the linear and "
        "quadratic penalties on `param.data` (detached), so they alter "
        "the REPORTED loss but contribute zero gradient; its aggregation "
        "is an unweighted sum divided by K, and the h-state delta is "
        "measured against the LAST client's trained weights (aliased "
        "model), not the round start. fedml_tpu's default implements the "
        "published FedDyn; `feddyn_ref_bug_compat: true` (used here) "
        "reproduces the reference exactly.",
        "8. **Mime's reference deviates from published MimeLite** — "
        "client steps use torch-SGD semantics with the server momentum "
        "state re-loaded every batch (`ml/trainer/mime_trainer.py:40-75`),"
        " the full-dataset gradient is accumulated at the TRAINED params "
        "(sum of batch means, clipped to norm 1) rather than at w_global, "
        "the server applies a torch-SGD momentum step on top of the "
        "average, w_global re-aliases the live model every round "
        "(sequential clients chain in EVERY round), and evaluation covers "
        "ONLY client 0's test split (the all-clients loop is commented "
        "out). `mime_ref_compat: true` (used here) reproduces all of it; "
        "the default implements the published MimeLite.",
        "9. **FedNova parity needs no compat flags** — the reference's "
        "FedNova trainer (`sp/fednova/fednova_trainer.py`) deep-copies "
        "the model per client (no aliasing) and its normalized-gradient "
        "aggregation is algebraically identical to fedml_tpu's "
        "(the learning rate cancels); measured equality to float noise.",
        "10. **Conv plane (FedAvg_cnn, reference CNN_DropOut) needs the "
        "same round-0 chain-compat flag as lr-FedAvg** — running "
        "`parity_fedml_tpu_sp.py --model cnn` WITHOUT "
        "`--fedavg-ref-chain-compat` reproduces deviation 1's signature "
        "on the conv plane: early-window test-loss drift ~0.105 at round "
        "0 decaying to ~0.009 by round 3 while per-round accuracy stays "
        "identical (the chained extra SGD steps barely move argmax on a "
        "62-class head whose 52 non-digit logits dominate the loss). "
        "With the flag (what this audit runs), curves match to 1e-4: "
        "conv/pool/dropout/flatten semantics, the OIHW→HWIO / NCHW-flat "
        "weight transfer, and the eval loss reduction are all exact "
        "(bisected by `benchmarks/conv_parity_probe.py`: forward "
        "|Δlogits| ≤ 3e-4, one-SGD-step |ΔW| ≤ 7e-5; dropout is zeroed "
        "on both sides — torch patches Dropout→Identity, flax rates "
        "(0,0) — because dropout RNG is framework-specific).",
        "",
    ]
    os.makedirs(DOC_DIR, exist_ok=True)
    with open(os.path.join(DOC_DIR, "PARITY.md"), "w") as f:
        f.write("\n".join(lines))


if __name__ == "__main__":
    main()
