"""Bisect the real Parrot round: why does a vmapped k=10 step cost ~26x
its isolated cost?  Variants, all on the real 50k north-star data:

  A  full uniform round step (gather + vmap(scan) + aggregate), jitted
     standalone (fixed client ids, no 64-round fusion)
  B  same but batches PRE-GATHERED outside the jit (gather exonerated?)
  C  vmap(scan) alone on the pre-gathered batches (aggregation exonerated?)

Prints ms per variant; compile each once, then 8 timed calls.
"""

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

import fedml_tpu
from fedml_tpu.runner import FedMLRunner

NPZ_DIR = os.path.join(REPO, ".data_cache", "northstar")
ITERS = 8


def _sync(out):
    # axon: block_until_ready alone under-reports by up to 100x through
    # the tunnel — force a scalar transfer (BENCH_NOTES round 3)
    import jax.numpy as jnp

    return float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))


def timed(name, fn, *args):
    out = fn(*args)
    _sync(out)
    t0 = time.time()
    for _ in range(ITERS):
        out = fn(*args)
        _sync(out)
    ms = (time.time() - t0) / ITERS * 1e3
    print(json.dumps({"variant": name, "ms": round(ms, 1)}))
    return out


def main():
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="cifar10", data_cache_dir=NPZ_DIR, model="resnet56",
        backend="parrot", partition_method="hetero", partition_alpha=0.5,
        client_num_in_total=100, client_num_per_round=10, comm_round=512,
        epochs=1, batch_size=32, learning_rate=0.05,
        frequency_of_the_test=1000, enable_tracking=False,
        compute_dtype="bfloat16", hetero_buckets=1))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    api = FedMLRunner(args, device, dataset, bundle).runner

    ids = jnp.asarray(np.arange(10, dtype=np.int32) * 7)
    rng = jax.random.PRNGKey(3)

    # A: the production uniform round step (jit with donation disabled so
    # repeated timing calls can reuse inputs)
    step_a = jax.jit(api._build_round_step())
    gv = api.global_vars
    st = api.server_state
    timed("A_full_round_step", step_a, api.device_data, gv, st, ids, rng)

    # B: gather once OUTSIDE, jit only vmap(scan)+aggregate
    batches = jax.jit(
        lambda data: api._gather_batches(data, ids, data["idx"], api.nb)
    )(api.device_data)
    jax.block_until_ready(batches["x"])
    in_axes_algo = api._in_axes_algo()
    aggregate = api._build_aggregate()
    weights = api.device_data["w"][ids]

    def body_b(gv2, st2, batches, rng2):
        rngs = jax.random.split(rng2, 10)
        new_vars, algo_out, metrics = jax.vmap(
            api.local_update, in_axes=(None, 0, 0, in_axes_algo))(
                gv2, batches, rngs, None)
        return aggregate(gv2, st2, ids, new_vars, algo_out, metrics,
                         weights)

    step_b = jax.jit(body_b)
    timed("B_pregathered_step", step_b, gv, st, batches, rng)

    # C: vmap(scan) only
    def body_c(gv2, batches, rng2):
        rngs = jax.random.split(rng2, 10)
        return jax.vmap(api.local_update, in_axes=(None, 0, 0, None))(
            gv2, batches, rngs, None)

    step_c = jax.jit(body_c)
    timed("C_vmap_scan_only", step_c, gv, batches, rng)

    # D: C but batches cast to bf16 first (storage-dtype effect)
    b16 = dict(batches, x=batches["x"].astype(jnp.bfloat16))
    timed("D_vmap_scan_bf16_batches", step_c, gv, b16, rng)


if __name__ == "__main__":
    main()
