"""Multi-chip collective cost model (VERDICT r4 item 3 / r3 #6).

Extracts the per-round collective structure (op counts + payload bytes)
from the COMPILED HLO of every multi-chip path on the virtual 8-device
mesh, then projects round cost to a v5e-64 slice under the documented
ICI/DCN bandwidth model (`fedml_tpu/utils/hlo_costs.py`).  The point:
a reviewer can see what an 8- or 64-chip round moves over the wire
without 64 real chips, and CI can catch collective-structure regressions
(`tests/test_hlo_costs.py`).

Paths measured (mirroring `__graft_entry__.dryrun_multichip`):
* buckets×mesh, batch-axis mode — per-client SGD data-parallel over mesh
* buckets×mesh, client-axis mode — clients sharded over mesh
* cross-cloud fsdp — transformer train step, params/grads sharded

Reference bar: `simulation/nccl/base_framework/common.py:180-228` proves
the reference's collective plane only by running it; here the compiled
program IS the evidence.

Usage: python benchmarks/collective_cost_model.py   (CPU, ~1 min)
Writes benchmarks/collective_costs.json.
"""

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
# the axon TPU-tunnel sitecustomize force-sets jax_platforms="axon,cpu";
# override it the way tests/conftest.py does
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

N = 8


def _bucket_mesh_costs(batch_axis: bool):
    """Compile one bucketed mesh round and summarize its collectives."""
    import jax

    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner
    from fedml_tpu.utils.hlo_costs import summarize_compiled

    # batch-axis: quota k/B < mesh → per-client batch shards
    # client-axis: quota divides the mesh → clients shard
    cfg = dict(dataset="mnist", model="lr", backend="mesh",
               hetero_buckets=2, partition_alpha=0.3,
               client_num_in_total=8, comm_round=1, epochs=1,
               data_scale=0.05, frequency_of_the_test=1,
               enable_tracking=False, compute_dtype="float32")
    if batch_axis:
        cfg.update(mesh_shape={"clients": N}, client_num_per_round=4,
                   batch_size=8)
    else:
        cfg.update(mesh_shape={"clients": 2}, client_num_per_round=4,
                   batch_size=8)
    args = fedml_tpu.init(fedml_tpu.Config(**cfg))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    api = FedMLRunner(args, None, dataset, bundle).runner
    compiled = api.bucketed_round_step.lower(
        api.device_data, api.global_vars, api.server_state,
        jax.random.PRNGKey(0)).compile()
    return summarize_compiled(compiled)


def _fsdp_step_costs():
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.ml.engine.mesh import build_mesh
    from fedml_tpu.parallel.sharding import (
        batch_sharding,
        build_sharded_train_step,
    )
    from fedml_tpu.utils.hlo_costs import summarize_compiled

    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            batch_size=8, compute_dtype="float32",
                            learning_rate=0.01)
    bundle = fedml_tpu.model.create(args, 90)
    variables = bundle.init_variables(jax.random.PRNGKey(0))
    mesh = build_mesh({"data": N})
    step, init_sh, tx = build_sharded_train_step(bundle, args, mesh, "fsdp")
    v = jax.device_put(variables, init_sh(variables))
    opt_state = tx.init(v["params"])
    batch = {"x": jax.device_put(
                 jnp.zeros((8, 32), jnp.int32), batch_sharding(mesh)),
             "y": jax.device_put(
                 jnp.zeros((8, 32), jnp.int32), batch_sharding(mesh)),
             "mask": None}
    with mesh:
        compiled = jax.jit(step).lower(v, opt_state, batch,
                                       jax.random.PRNGKey(1)).compile()
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(v["params"]))
    return summarize_compiled(compiled), int(n_params)


def _projection():
    """v5e-64 round-cost projection under the documented BW model."""
    from fedml_tpu.utils.hlo_costs import (
        DCN_BW,
        ICI_BW_V5E,
        dcn_seconds,
        ici_seconds,
    )

    out = {"assumptions": {
        "ici_bw_one_way_B_per_s": ICI_BW_V5E,
        "dcn_bw_B_per_s": DCN_BW,
        "model": "ring collectives, 2(N-1)/N allreduce factor",
    }}
    # north star: ResNet-56 CIFAR (855,770 params bf16) on a 64-chip
    # clients mesh, 10 clients/round: ONE weighted param allreduce per
    # round + scalar metric reductions
    p_bytes = 855_770 * 2
    t_ar = ici_seconds(p_bytes, 64, "all-reduce")
    out["northstar_v5e64"] = {
        "param_allreduce_bytes": p_bytes,
        "allreduce_s": t_ar,
        "measured_round_s_single_chip": 0.295,   # 3.39 rounds/s, r4 bench
        "collective_share_at_64": t_ar / (0.295 / 64 + t_ar),
    }
    # LLM fsdp: GPT-2-small 124M params bf16; per step all-gather params
    # + reduce-scatter grads
    g_bytes = 124e6 * 2
    out["gpt2_small_fsdp_v5e64"] = {
        "allgather_s": ici_seconds(g_bytes, 64, "all-gather"),
        "reduce_scatter_s": ici_seconds(g_bytes, 64, "reduce-scatter"),
        "note": "vs ~0.05 s/step measured compute at bs4 (MFU 0.49): "
                "collectives ~0.2x compute; overlap hides most of it",
    }
    # cross-cloud: one full-model exchange per round over DCN
    out["cross_cloud_round_dcn"] = {
        "gpt2_small_param_exchange_s": dcn_seconds(g_bytes) * 2,
        "resnet56_param_exchange_s": dcn_seconds(p_bytes) * 2,
    }
    return out


def main() -> None:
    res = {
        "n_devices": N,
        "bucket_mesh_batch_axis": _bucket_mesh_costs(batch_axis=True),
        "bucket_mesh_client_axis": _bucket_mesh_costs(batch_axis=False),
    }
    fsdp, n_params = _fsdp_step_costs()
    res["cross_cloud_fsdp_step"] = fsdp
    res["cross_cloud_fsdp_params"] = n_params
    res["projection"] = _projection()
    path = os.path.join(HERE, "collective_costs.json")
    with open(path, "w") as f:
        json.dump(res, f, indent=1)
    print("COLLECTIVE_COSTS " + json.dumps(res))


if __name__ == "__main__":
    main()
