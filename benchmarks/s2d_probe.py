"""Space-to-depth stage-1 conv reparam probe (VERDICT r4 weak #5 / item 8).

BENCH_NOTES round 4 named one remaining conv-plane lever: reparametrize
the north star's stage-1 convs (3x3 SAME, 16ch, 32x32) over
space-to-depth blocks so the MXU contraction stops padding C=16 lanes.
The reparam is EXACT and the kernel transform is weight-dependent but
TINY (9 KB per conv vs the banded-Toeplitz probe's 5 MB bands, so it can
run inside the step): w' is a fixed sparse embedding of w into a 3x3
conv over [B, 16, 16, 64].

This probe (a) verifies exact equivalence on random data, (b) times the
original vs s2d conv forward and fwd+bwd on the chip at the bucketed
north-star shape, and (c) reports the projected round-level impact.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python benchmarks/s2d_probe.py
"""

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))

import jax
import jax.numpy as jnp
import numpy as np

B, H, W, C, CO = 32, 32, 32, 16, 16


def s2d(x):
    """[B, H, W, C] -> [B, H/2, W/2, 4C]; channel = qi*2C + qj*C + c."""
    b, h, w, c = x.shape
    return (x.reshape(b, h // 2, 2, w // 2, 2, c)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(b, h // 2, w // 2, 4 * c))


def s2d_kernel(w):
    """Embed a 3x3 [kh, kw, C, CO] SAME-conv kernel into the equivalent
    3x3 conv over s2d space: [3, 3, 4C, 4CO], structural zeros where a
    (phase, tap) pair falls outside the block window."""
    kh, kw, c, co = w.shape
    wp = np.zeros((3, 3, 4 * c, 4 * co), w.dtype)
    for pi in range(2):
        for pj in range(2):
            for di in range(kh):
                for dj in range(kw):
                    posi, posj = pi + di - 1, pj + dj - 1
                    ti, qi = posi // 2 + 1, posi % 2
                    tj, qj = posj // 2 + 1, posj % 2
                    wp[ti, tj,
                       qi * 2 * c + qj * c:qi * 2 * c + qj * c + c,
                       pi * 2 * co + pj * co:pi * 2 * co + pj * co + co] \
                        = w[di, dj]
    return wp


def conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bench(fn, *args, n_inner=1):
    """Best-of-8 of ONE dispatch; divide by n_inner (the op is chained
    n_inner times INSIDE the jitted fn — a single stage-1 conv is ~10 us
    of compute vs ~100 ms of tunnel dispatch, so per-op cost is only
    measurable amortized inside one dispatch)."""
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.tree_util.tree_map(lambda a: np.asarray(a), out)   # compile+sync
    best = float("inf")
    for _ in range(8):
        t0 = time.time()
        out = fn_j(*args)
        jax.tree_util.tree_map(lambda a: np.asarray(a), out)
        best = min(best, time.time() - t0)
    # subtract the measured empty-dispatch RTT
    e = jax.jit(lambda a: a)
    x0 = args[0]
    np.asarray(e(x0))
    rtt = min(_t(lambda: np.asarray(e(x0))) for _ in range(8))
    return max(best - rtt, 1e-9) / n_inner


def _t(f):
    t0 = time.time()
    f()
    return time.time() - t0


def main() -> None:
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, H, W, C)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 3, C, CO)) * 0.1, jnp.float32)

    # ---- exactness ------------------------------------------------------
    y = conv(x, w)
    y2 = conv(s2d(x), jnp.asarray(s2d_kernel(np.asarray(w))))
    err = float(jnp.abs(s2d(y) - y2).max())
    print(f"exactness: max|d| = {err:.2e}", file=sys.stderr)
    assert err < 1e-4

    xb = x.astype(jnp.bfloat16)
    wb = w.astype(jnp.bfloat16)
    xs = s2d(xb)
    N_FWD, N_FB = 8192, 64

    def chain(a, k):
        # conv keeps the activation shape (C == CO per grid), so the op
        # chains inside one dispatch; *0.5 keeps magnitudes bounded
        return jax.lax.fori_loop(
            0, N_FWD, lambda i, v: conv(v, k) * 0.5, a)

    # forward: original vs s2d (kernel transform OUTSIDE: cached across
    # uses within a step) vs s2d with the transform INSIDE (the honest
    # per-SGD-step cost: weights change every step)
    t_orig = bench(chain, xb, wb, n_inner=N_FWD)
    ws = jnp.asarray(s2d_kernel(np.asarray(w)), jnp.bfloat16)
    t_s2d = bench(chain, xs, ws, n_inner=N_FWD)

    # in-step kernel transform: one gather through precomputed indices
    # (kp[t,u,a,b] = w_flat[IDX[t,u,a,b]] * MASK) — exact, and cheap
    # enough to run every SGD step (147k-element gather)
    # recover (index, mask) by embedding an index-valued kernel: the
    # embedded value IS the flat source index; the ones-kernel embedding
    # distinguishes "maps to w_flat[0]" from "structural zero"
    probe_w = np.arange(9 * C * CO, dtype=np.float32).reshape(3, 3, C, CO)
    idx = s2d_kernel(probe_w).astype(np.int32)
    mask = (s2d_kernel(np.ones((3, 3, C, CO), np.float32)) > 0
            ).astype(np.float32)
    idx_j = jnp.asarray(idx)
    mask_j = jnp.asarray(mask, jnp.bfloat16)

    def build_kp(k):
        return jnp.take(k.reshape(-1), idx_j) * mask_j

    # exactness of the in-step transform itself
    np.testing.assert_allclose(
        np.asarray(build_kp(w.astype(jnp.float32))),
        s2d_kernel(np.asarray(w)), rtol=1e-6)

    def s2d_inside(a, k):
        # the transform must RE-RUN per iteration (like it would per SGD
        # step, where weights change): carry the kernel and decay it each
        # step — a loop-variant operand XLA cannot hoist (`k + i*0` gets
        # folded to loop-invariant `k` and the gather hoisted out)
        def body(i, carry):
            v, kv = carry
            kv = kv * 0.9999
            return conv(v, build_kp(kv)) * 0.5, kv

        return jax.lax.fori_loop(0, N_FWD, body, (a, k))[0]

    t_s2d_in = bench(s2d_inside, xs, wb, n_inner=N_FWD)

    # fwd+bwd per conv: grad of a 64-conv chain wrt (x, w) — cost is
    # N_FB x (one conv forward + backward) in ONE dispatch
    def fb(a, k):
        def loss(a, k):
            def body(v, _):
                return conv(v, k) * 0.5, ()
            out, _ = jax.lax.scan(body, a, None, length=N_FB)
            return jnp.sum(out ** 2)
        return jax.grad(loss, argnums=(0, 1))(a, k)

    def fb_s2d(a, k):
        def loss(a, k):
            kp = build_kp(k)

            def body(v, _):
                return conv(v, kp) * 0.5, ()
            out, _ = jax.lax.scan(body, a, None, length=N_FB)
            return jnp.sum(out ** 2)
        return jax.grad(loss, argnums=(0, 1))(a, k)

    t_fb = bench(fb, xb, wb, n_inner=N_FB)
    t_fb_s2d = bench(fb_s2d, xs, wb, n_inner=N_FB)

    out = {
        "shape": f"[{B},{H},{W},{C}]->{CO} 3x3 SAME bf16",
        "exact_err": err,
        "fwd_orig_us": round(t_orig * 1e6, 2),
        "fwd_s2d_us": round(t_s2d * 1e6, 2),
        "fwd_s2d_transform_inside_us": round(t_s2d_in * 1e6, 2),
        "fwdbwd_orig_us": round(t_fb * 1e6, 2),
        "fwdbwd_s2d_us": round(t_fb_s2d * 1e6, 2),
        "fwd_speedup": round(t_orig / t_s2d_in, 2),
        "fwdbwd_speedup": round(t_fb / t_fb_s2d, 2),
    }
    print("S2D_PROBE " + json.dumps(out))


if __name__ == "__main__":
    main()
