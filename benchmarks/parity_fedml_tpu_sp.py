"""fedml_tpu side of the convergence-parity audit.

Runs the SP plane on the SAME LEAF-MNIST bytes and config as
refbench/run_reference_sp.py (natural per-user partition, 2 clients/round,
bs 10, lr 0.03, eval every round) and prints the same
``PARITY_JSON {...per_round...}`` line for the audit to diff.

Usage: python benchmarks/parity_fedml_tpu_sp.py --optimizer FedAvg
       [--rounds 30] [--scaffold-ref-bug-compat]
"""

import argparse
import json
import os
import sys

import numpy as np
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
CACHE = os.path.join(REPO, ".data_cache", "refbench")
sys.path.insert(0, REPO)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--optimizer", default="FedAvg")
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--model", default="lr", choices=["lr", "cnn"],
                   help="cnn = conv parity plane (reference CNN_DropOut; "
                        "my cnn_dropout module, dropout zeroed both "
                        "sides)")
    p.add_argument("--scaffold-ref-bug-compat", action="store_true")
    p.add_argument("--fedavg-ref-chain-compat", action="store_true",
                   help="reproduce the reference's round-0 state_dict "
                        "aliasing (sequential clients chain; see "
                        "parity_round0_oracle.py)")
    p.add_argument("--feddyn-ref-bug-compat", action="store_true",
                   help="reproduce the reference FedDyn trainer's dead "
                        "penalties + unweighted-sum server math "
                        "(fed_api._server_update compat branch)")
    p.add_argument("--mime-ref-compat", action="store_true",
                   help="reproduce the reference Mime trainer: full-grad "
                        "at trained params clipped to norm 1, torch-SGD "
                        "server step, every-round client chaining")
    cli = p.parse_args()

    if not os.path.exists(os.path.join(CACHE, "leaf_mnist_train.npz")):
        sys.path.insert(0, os.path.join(HERE, "refbench"))
        from gen_leaf_mnist import gen
        os.makedirs(CACHE, exist_ok=True)
        gen(CACHE, users=100, seed=42)

    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    # the FedDyn reference's regularization penalties are gradient-dead
    # (param.data), so its LOCAL update is plain FedAvg SGD; the server
    # math runs in the fed_api compat branch
    local_opt = ("FedAvg" if cli.feddyn_ref_bug_compat
                 else cli.optimizer)
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="mnist",
        data_cache_dir=CACHE,
        partition_method="natural",      # LEAF users, like the reference
        model=("cnn_dropout" if cli.model == "cnn" else "lr"),
        cnn_dropout_rates=(0.0, 0.0),    # parity: dropout zeroed both sides
        backend="sp",
        federated_optimizer=local_opt,
        client_num_in_total=2,           # overridden by natural user count
        client_num_per_round=2,
        comm_round=cli.rounds,
        epochs=1,
        batch_size=10,
        client_optimizer="sgd",
        learning_rate=0.03,
        # the reference's FedAvg-family SGD branch IGNORES weight_decay
        # (ml/trainer/my_model_trainer_classification.py:29-33 passes only
        # lr) — but its FedDyn trainer DOES pass it (feddyn_trainer.py:
        # 58-62), so the compat run matches the config's 0.001
        weight_decay=(0.001 if (cli.feddyn_ref_bug_compat
                                or cli.mime_ref_compat) else 0.0),
        # match the reference lr model exactly: sigmoid before CE
        # (`model/linear/lr.py:11`) — deviation docs in docs/PARITY.md
        lr_sigmoid_outputs=True,
        fedprox_mu=0.1,
        server_lr=1.0,
        scaffold_ref_bug_compat=cli.scaffold_ref_bug_compat,
        fedavg_ref_chain_compat=cli.fedavg_ref_chain_compat,
        feddyn_ref_bug_compat=cli.feddyn_ref_bug_compat,
        mime_ref_compat=cli.mime_ref_compat,
        frequency_of_the_test=1,
        enable_tracking=False,
        compute_dtype="float32",
    ))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)

    # replicate the reference's per-client shuffle-once-at-load
    # (`data/MNIST/data_loader.py:batch_data` — np.random.seed(100), same
    # state for x and y) so minibatch ORDER matches too
    train_local = dataset[5]
    for cid, (x, y) in list(train_local.items()):
        x = np.array(x, copy=True)
        y = np.array(y, copy=True)
        np.random.seed(100)
        st = np.random.get_state()
        np.random.shuffle(x)
        np.random.set_state(st)
        np.random.shuffle(y)
        train_local[cid] = (x, y)

    if cli.mime_ref_compat:
        # the reference Mime trainer evaluates ONLY client 0's local test
        # split (its all-clients loop is commented out,
        # `sp/mime/mime_trainer.py:_local_test_on_all_clients`)
        ds = list(dataset)
        ds[3] = ds[6][0]
        dataset = tuple(ds)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, device, dataset, bundle)

    # start from the reference's exact initial weights when its runner has
    # exported them (torch Linear [out,in] → flax Dense kernel [in,out];
    # torch Conv OIHW → flax HWIO; the cnn_dropout module flattens
    # channel-major so torch Linear weights transfer as a plain .T)
    init_path = os.path.join(CACHE, f"ref_init_{cli.model}.npz")
    if os.path.exists(init_path):
        import jax.numpy as jnp
        z = np.load(init_path)
        api = runner.runner
        params = dict(api.global_vars["params"])
        if cli.model == "cnn":
            mapping = {
                "Conv_0": ("conv2d_1", True), "Conv_1": ("conv2d_2", True),
                "Dense_0": ("linear_1", False),
                "Dense_1": ("linear_2", False),
            }
            for mine, (ref, is_conv) in mapping.items():
                w = z[f"{ref}.weight"]
                layer = dict(params[mine])
                layer["kernel"] = jnp.asarray(
                    w.transpose(2, 3, 1, 0) if is_conv else w.T)
                layer["bias"] = jnp.asarray(z[f"{ref}.bias"])
                params[mine] = layer
        else:
            dense = dict(params["Dense_0"])
            dense["kernel"] = jnp.asarray(z["linear.weight"].T)
            dense["bias"] = jnp.asarray(z["linear.bias"])
            params["Dense_0"] = dense
        api.global_vars = dict(api.global_vars, params=params)
        print("loaded reference init", file=sys.stderr)

    t0 = time.time()
    runner.run()
    wall = time.time() - t0

    api = runner.runner
    per_round = {}
    for m in api.metrics_history:
        per_round[str(int(m["round"]))] = {
            "Test/Acc": float(m["test_acc"]),
            "Test/Loss": float(m["test_loss"]),
        }
    last = per_round[str(cli.rounds - 1)] if per_round else {}
    print("PARITY_JSON " + json.dumps({
        "what": f"fedml_tpu_sp_{cli.optimizer.lower()}_mnist_"
                f"{cli.model}_smoke",
        "users": int(args.client_num_in_total),
        "comm_round": cli.rounds,
        "train_wall_s": round(wall, 3),
        "rounds_per_sec": round(cli.rounds / wall, 4),
        "test_acc": last.get("Test/Acc"),
        "test_loss": last.get("Test/Loss"),
        "per_round": per_round,
    }))


if __name__ == "__main__":
    main()
