"""Bisect the conv-parity loss drift: same init, same bytes, both forwards.

Loads `.data_cache/refbench/ref_init_cnn.npz` into (a) the reference's
torch CNN_DropOut (`/root/reference/python/fedml/model/cv/cnn.py:101-150`,
dropout zeroed) and (b) fedml_tpu's flax CNNDropOut with the parity
weight-transfer mapping, runs both on the same LEAF-MNIST test batch, and
prints max |Δlogits|, per-side CE loss, and per-side one-SGD-step weight
delta so the drift can be attributed to forward / loss / training math.

Usage: PYTHONPATH=/root/repo python benchmarks/conv_parity_probe.py
"""

import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
CACHE = os.path.join(REPO, ".data_cache", "refbench")
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def torch_model(z):
    import torch
    import torch.nn as nn

    class RefCNN(nn.Module):
        """Op-for-op copy of the reference forward (cnn.py:126-142),
        dropout omitted (the parity run patches Dropout -> Identity)."""

        def __init__(self):
            super().__init__()
            self.conv2d_1 = nn.Conv2d(1, 32, kernel_size=3)
            self.max_pooling = nn.MaxPool2d(2, stride=2)
            self.conv2d_2 = nn.Conv2d(32, 64, kernel_size=3)
            self.flatten = nn.Flatten()
            self.linear_1 = nn.Linear(9216, 128)
            self.linear_2 = nn.Linear(128, 62)
            self.relu = nn.ReLU()

        def forward(self, x):
            x = torch.unsqueeze(x, 1)
            x = self.relu(self.conv2d_1(x))
            x = self.relu(self.conv2d_2(x))
            x = self.max_pooling(x)
            x = self.flatten(x)
            x = self.relu(self.linear_1(x))
            return self.linear_2(x)

    m = RefCNN()
    sd = {k: torch.from_numpy(np.asarray(v)) for k, v in z.items()}
    m.load_state_dict(sd)
    return m


def flax_model(z):
    import jax.numpy as jnp
    from fedml_tpu.models.cv import CNNDropOut

    module = CNNDropOut(num_classes=62, rate1=0.0, rate2=0.0)
    import jax
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 28, 28)))["params"]
    mapping = {"Conv_0": ("conv2d_1", True), "Conv_1": ("conv2d_2", True),
               "Dense_0": ("linear_1", False),
               "Dense_1": ("linear_2", False)}
    params = dict(params)
    for mine, (ref, is_conv) in mapping.items():
        w = np.asarray(z[f"{ref}.weight"])
        layer = dict(params[mine])
        layer["kernel"] = jnp.asarray(
            w.transpose(2, 3, 1, 0) if is_conv else w.T)
        layer["bias"] = jnp.asarray(np.asarray(z[f"{ref}.bias"]))
        params[mine] = layer
    return module, params


def main() -> None:
    import torch
    import torch.nn as nn
    import jax
    import jax.numpy as jnp
    import optax

    z = np.load(os.path.join(CACHE, "ref_init_cnn.npz"))
    test = np.load(os.path.join(CACHE, "leaf_mnist_test.npz"),
                   allow_pickle=True)
    users = sorted(k[2:] for k in test.files if k.startswith("x_"))
    x = np.concatenate([test[f"x_{u}"] for u in users[:5]])[:64]
    y = np.concatenate([test[f"y_{u}"] for u in users[:5]])[:64]
    print(f"batch: x{x.shape} y{y.shape}", file=sys.stderr)

    tm = torch_model(z)
    tm.eval()
    tx = torch.from_numpy(x).float().reshape(-1, 28, 28)
    ty = torch.from_numpy(y).long()
    with torch.no_grad():
        tlogits = tm(tx).numpy()
        tloss = float(nn.CrossEntropyLoss()(torch.from_numpy(tlogits),
                                            ty))

    module, params = flax_model(z)
    jx = jnp.asarray(x, jnp.float32)
    jlogits = np.asarray(module.apply({"params": params}, jx))
    jloss = float(optax.softmax_cross_entropy_with_integer_labels(
        jnp.asarray(jlogits), jnp.asarray(y, jnp.int32)).mean())

    dlog = np.abs(tlogits - jlogits).max()
    print(f"FORWARD  max|dlogits|={dlog:.3e}  "
          f"torch_loss={tloss:.6f} jax_loss={jloss:.6f} "
          f"dloss={abs(tloss - jloss):.3e}")

    # one SGD step on one batch, then diff the updated weights
    crit = nn.CrossEntropyLoss()
    tm.train()
    opt = torch.optim.SGD(tm.parameters(), lr=0.03)
    opt.zero_grad()
    crit(tm(tx[:10]), ty[:10]).backward()
    opt.step()
    sd_after = {k: v.detach().numpy() for k, v in tm.state_dict().items()}

    def loss_fn(p):
        lg = module.apply({"params": p}, jx[:10])
        return optax.softmax_cross_entropy_with_integer_labels(
            lg, jnp.asarray(y[:10], jnp.int32)).mean()

    g = jax.grad(loss_fn)(params)
    jparams = jax.tree.map(lambda p, gg: p - 0.03 * gg, params, g)

    mapping = {"Conv_0": ("conv2d_1", True), "Conv_1": ("conv2d_2", True),
               "Dense_0": ("linear_1", False),
               "Dense_1": ("linear_2", False)}
    worst = 0.0
    for mine, (ref, is_conv) in mapping.items():
        tw = sd_after[f"{ref}.weight"]
        tw = tw.transpose(2, 3, 1, 0) if is_conv else tw.T
        dw = np.abs(tw - np.asarray(jparams[mine]["kernel"])).max()
        db = np.abs(sd_after[f"{ref}.bias"]
                    - np.asarray(jparams[mine]["bias"])).max()
        print(f"STEP     {mine}: max|dW|={dw:.3e} max|db|={db:.3e}")
        worst = max(worst, dw, db)
    print(f"RESULT   forward_dlogits={dlog:.3e} step_dw={worst:.3e}")


if __name__ == "__main__":
    main()
