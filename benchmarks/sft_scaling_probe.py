"""SFT batch-size MFU inversion probe + large-model datum (VERDICT r4
item 6 / weak #3).

Round 4 measured MFU 0.4925 at bs4 but 0.4258/0.4394 at bs8/16 on
GPT-2-small — bigger batches should not be slower per token.  Hypothesis:
the bench's `reference_attention` materializes [B, H, T, T] score
matrices (bs16: 12 GB of bf16 score traffic per layer fwd+bwd at T=1024),
so the step goes HBM-bound as B grows.  This probe measures every (bs,
attention-impl) pair, plus remat and a ~350M-class (GPT-2-medium
geometry) config, on the real chip.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python
       benchmarks/sft_scaling_probe.py
Prints one PROBE_JSON line; results go into BENCH_NOTES round 5.
"""

import json
import os
import subprocess
import sys
import time
from functools import partial

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

SEQ = 1024

#: (key, dim, layers, heads, bs, attn, remat, accum)
CONFIGS = [
    ("small_ref_bs4", 768, 12, 12, 4, "ref", False, 1),
    ("small_ref_bs8", 768, 12, 12, 8, "ref", False, 1),
    ("small_ref_bs16", 768, 12, 12, 16, "ref", False, 1),
    ("small_flash_bs4", 768, 12, 12, 4, "flash", False, 1),
    ("small_flash_bs8", 768, 12, 12, 8, "flash", False, 1),
    ("small_flash_bs16", 768, 12, 12, 16, "flash", False, 1),
    ("small_ref_bs8_remat", 768, 12, 12, 8, "ref", True, 1),
    ("small_ref_bs16_accum4", 768, 12, 12, 16, "ref", False, 4),
    ("medium_flash_bs4_remat", 1024, 24, 16, 4, "flash", True, 1),
    ("medium_ref_bs4_remat", 1024, 24, 16, 4, "ref", True, 1),
    ("medium_ref_bs8_remat", 1024, 24, 16, 8, "ref", True, 1),
]


def flops_per_token(dim, layers, vocab, remat):
    fwd = layers * (24 * dim * dim + 4 * SEQ * dim) + 2 * dim * vocab
    return fwd * (4.0 if remat else 3.0)


def measure_rtt():
    """Dispatch latency of a trivial op through the (possibly tunneled)
    runtime — subtracted from step windows like llm_bench does."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    x = jnp.zeros((8,), jnp.float32)
    f = jax.jit(lambda a: a + 1)
    np.asarray(f(x))
    best = float("inf")
    for _ in range(8):
        t0 = time.time()
        np.asarray(f(x))
        best = min(best, time.time() - t0)
    return best


def measure(dim, layers, heads, vocab, bs, attn, remat, accum=1):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from fedml_tpu.constants import (
        TPU_PEAK_BF16_DEFAULT,
        TPU_PEAK_BF16_FLOPS,
    )
    from fedml_tpu.ops.pallas_attention import flash_attention
    from fedml_tpu.parallel.ring_attention import reference_attention
    from fedml_tpu.parallel.seq_parallel import init_lm_params, lm_loss

    rtt = measure_rtt()
    params = init_lm_params(jax.random.PRNGKey(0), vocab, dim=dim,
                            layers=layers, heads=heads, max_len=SEQ)
    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)
    attn_fn = (partial(reference_attention, causal=True)
               if attn == "ref" else partial(flash_attention, causal=True))

    def loss_fn(p, t):
        p16 = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16), p)
        return lm_loss(p16, t, heads, attn_fn, remat=remat)

    @jax.jit
    def step(params, opt_state, tokens):
        if accum == 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        else:
            # true gradient accumulation: per-microbatch backward inside
            # a scan (activation memory = ONE microbatch), summed grads,
            # one optimizer update
            mb = tokens.reshape(accum, bs // accum, SEQ)

            def body(g_acc, t):
                l, g = jax.value_and_grad(loss_fn)(params, t)
                return jax.tree_util.tree_map(jnp.add, g_acc, g), l

            g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
            grads, losses = jax.lax.scan(body, g0, mb)
            grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
            loss = jnp.mean(losses)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, vocab, (bs, SEQ)), jnp.int32)
    t0 = time.time()
    try:
        p, o, loss = step(params, opt_state, tokens)
        float(loss)
    except Exception as e:  # noqa: BLE001 — OOM is a result
        return {"error": str(e)[:160]}
    compile_s = time.time() - t0
    for _ in range(2):
        p, o, loss = step(p, o, tokens)
    float(loss)
    dt = float("inf")
    for _ in range(8):
        t0 = time.time()
        for _ in range(2):
            p, o, loss = step(p, o, tokens)
        float(loss)
        dt = min(dt, (time.time() - t0 - rtt) / 2)
    kind = jax.devices()[0].device_kind
    peak = TPU_PEAK_BF16_FLOPS.get(kind, TPU_PEAK_BF16_DEFAULT)
    tok_s = bs * SEQ / dt
    return {"step_ms": round(dt * 1e3, 1),
            "tokens_per_sec": round(tok_s, 0),
            "mfu": round(tok_s * flops_per_token(dim, layers, vocab,
                                                 remat) / peak, 4),
            "compile_s": round(compile_s, 1),
            "rtt_ms": round(rtt * 1e3, 1)}


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--one":
        key = sys.argv[2]
        cfg = next(c for c in CONFIGS if c[0] == key)
        _, dim, layers, heads, bs, attn, remat, accum = cfg
        res = measure(dim, layers, heads, 50257, bs, attn, remat, accum)
        print("ONE_JSON " + json.dumps(res))
        return
    # one SUBPROCESS per config: a prior config's OOM must not poison the
    # allocator for later ones (observed: post-OOM RESOURCE_EXHAUSTED on
    # an init that fits a clean chip)
    out = {}
    for cfg in CONFIGS:
        key = cfg[0]
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one", key],
                capture_output=True, text=True, timeout=900)
        except subprocess.TimeoutExpired:
            # one hung config must not discard the whole sweep
            out[key] = {"error": "timeout (900s)"}
            print(key, out[key], file=sys.stderr)
            continue
        res = {"error": proc.stderr.strip()[-200:] or "no output"}
        for line in reversed(proc.stdout.splitlines()):
            if line.startswith("ONE_JSON "):
                res = json.loads(line[len("ONE_JSON "):])
                break
        out[key] = res
        print(key, res, file=sys.stderr)
    print("PROBE_JSON " + json.dumps(out))


if __name__ == "__main__":
    main()
