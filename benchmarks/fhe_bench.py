"""FHE-at-model-scale benchmark (VERDICT round-1 item 9): weighted
encrypted aggregation of a >=1M-parameter model, RLWE vs Paillier.

Paillier timing is measured on a sample of ciphertexts and extrapolated
(the full run is ~10 min/side — the point of this benchmark); RLWE runs the
full 1M parameters for real.  Prints one JSON line; results recorded in
docs/FHE_PRACTICALITY.md.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PARAMS = 1_000_000
N_CLIENTS = 10


def bench_rlwe() -> dict:
    from fedml_tpu.core.fhe.rlwe import RlweCodec, keygen

    key = keygen(1234)
    codec = RlweCodec(key)
    rng = np.random.RandomState(0)
    vec = rng.randn(N_PARAMS).astype(np.float32) * 0.1

    t0 = time.time()
    enc = codec.encrypt(vec)
    t_enc = time.time() - t0

    encs = [enc] + [codec.encrypt(vec) for _ in range(2)]
    weights = [codec.quantize_weight(1.0 / 3)] * 3
    t0 = time.time()
    agg = codec.weighted_sum(list(zip(weights, encs)))
    t_agg_3 = time.time() - t0
    t_agg = t_agg_3 / 3 * N_CLIENTS

    t0 = time.time()
    out = codec.decrypt(key, agg)
    t_dec = time.time() - t0
    err = float(np.abs(out - vec).max())
    assert err < 1e-3, err
    return {"enc_s_per_client": round(t_enc, 2),
            "agg_s_10_clients": round(t_agg, 2),
            "dec_s": round(t_dec, 2),
            "round_total_s": round(t_enc + t_agg + t_dec, 2),
            "max_abs_err": err}


def bench_paillier(sample_cts: int = 40) -> dict:
    from fedml_tpu.core.fhe.paillier import PaillierCodec, keygen

    pub, priv = keygen(bits=1024, seed=7)
    codec = PaillierCodec(pub)
    vec = np.random.RandomState(0).randn(
        codec.slots_per_ct * sample_cts).astype(np.float32) * 0.1
    n_ct_full = -(-N_PARAMS // codec.slots_per_ct)
    scale = n_ct_full / sample_cts

    t0 = time.time()
    e1 = codec.encrypt(vec)
    t_enc = (time.time() - t0) * scale
    e2 = codec.encrypt(vec)
    w = codec.quantize_weight(0.5)
    t0 = time.time()
    agg = codec.weighted_sum([(w, e1), (w, e2)])
    t_agg = (time.time() - t0) / 2 * N_CLIENTS * scale
    t0 = time.time()
    out = codec.decrypt(priv, agg)
    t_dec = (time.time() - t0) * scale
    err = float(np.abs(out - vec).max())
    return {"enc_s_per_client_extrapolated": round(t_enc, 1),
            "agg_s_10_clients_extrapolated": round(t_agg, 1),
            "dec_s_extrapolated": round(t_dec, 1),
            "round_total_s_extrapolated": round(t_enc + t_agg + t_dec, 1),
            "max_abs_err": err,
            "sampled_cts": sample_cts, "full_cts": n_ct_full}


if __name__ == "__main__":
    r = bench_rlwe()
    p = bench_paillier()
    speedup = p["round_total_s_extrapolated"] / max(r["round_total_s"],
                                                    1e-9)
    print(json.dumps({"params": N_PARAMS, "clients": N_CLIENTS,
                      "rlwe": r, "paillier": p,
                      "rlwe_speedup": round(speedup, 1)}))
