"""Served (HTTP-level) LLM throughput: the product-visible numbers.

VERDICT r4 item 4: round 4's decode numbers were device-side engine
measurements; this script measures the SAME engine through the real
serving stack — `KVCacheLLMEngine` → `LLMEnginePredictor` →
`OpenAIServer` (/v1/chat/completions, streaming + non-streaming) — under
concurrent HTTP clients, and reports:

* ``served_tokens_per_sec``  — aggregate completion tokens/s across N
  concurrent non-streaming clients;
* ``ttft_ms_idle`` / ``ttft_ms_loaded`` — streaming time-to-first-token
  (POST → first SSE content chunk), alone and under load;
* ``device_tokens_per_sec`` — the same engine driven directly (no HTTP),
  same batch shape, so ``serving_overhead_pct`` is an honest apples-to-
  apples delta.

Reference bar: `serving/templates/hf_template/main_openai.py` (the
reference serves through FastAPI but publishes no numbers).  Model:
GPT-2-small geometry (vocab 50257, d768 L12 H12) with random weights —
serving throughput does not depend on the weights' values.

Floors: benchmarks/serve_bench_floor.json (0.75x of the committed best,
same shared-chip variance policy as llm_bench_floor.json); exits 1 on a
floor breach so CI catches regressions.

Usage: python benchmarks/serve_bench.py [--quick] [--update-floor]
"""

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

RESULTS = os.path.join(HERE, "serve_bench_results.json")
FLOOR = os.path.join(HERE, "serve_bench_floor.json")


def _post(port, body, timeout=600):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _messages_prompt():
    """The exact prompt string the HTTP path produces from _chat_body."""
    return ("user: benchmark prompt: tell me a story\nassistant:")


def _chat_body(max_tokens, stream=False):
    return {"model": "bench", "max_tokens": max_tokens,
            "temperature": 1.0, "top_p": 0.9, "stream": stream,
            "messages": [{"role": "user",
                          "content": "benchmark prompt: tell me a story"}]}


def _ttft_stream(port, max_tokens):
    """POST a streaming request; return (ttft_s, total_s, n_chunks)."""
    t0 = time.time()
    resp = _post(port, _chat_body(max_tokens, stream=True))
    ttft = None
    n = 0
    for raw in resp:
        line = raw.decode("utf-8", "replace").strip()
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        try:
            chunk = json.loads(line[len("data: "):])
        except json.JSONDecodeError:
            continue
        delta = chunk["choices"][0]["delta"]
        if delta.get("content"):
            if ttft is None:
                ttft = time.time() - t0
            n += 1
    return ttft, time.time() - t0, n


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="tiny model + short run (CI smoke; no floors)")
    p.add_argument("--update-floor", action="store_true")
    cli = p.parse_args()

    import jax

    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import (
        KVCacheLLMEngine,
        LLMEnginePredictor,
    )
    from fedml_tpu.serving.openai_api import OpenAIServer

    if cli.quick:
        vocab, dim, layers, heads, max_len = 256, 64, 2, 4, 96
        max_batch, k, clients, max_tokens = 8, 4, 6, 16
    else:
        vocab, dim, layers, heads, max_len = 50257, 768, 12, 12, 640
        max_batch, k, clients, max_tokens = 64, 16, 48, 64
        # dispatch-length sweep knob (latency/throughput tradeoff: shorter
        # dispatches admit new requests sooner → lower loaded TTFT)
        default_k = k
        k = int(os.environ.get("SERVE_BENCH_K", k))

    lm = KVCacheLM.create(jax.random.PRNGKey(0), vocab=vocab, dim=dim,
                          layers=layers, heads=heads, max_len=max_len)
    engine = KVCacheLLMEngine(lm, max_batch=max_batch,
                              tokens_per_dispatch=k)
    # id-mod codec: perf only depends on token COUNTS, not values
    predictor = LLMEnginePredictor(
        engine,
        encode=lambda s: [ord(c) % vocab for c in s] or [0],
        decode=lambda ids: "".join(chr(32 + (int(i) % 90)) for i in ids))
    server = OpenAIServer(predictor, model_name="bench", port=0)
    server.run(block=False)
    port = server.port

    try:
        # ---- warmup: compile both jit variants (prefill + decode) --------
        _post(port, _chat_body(4)).read()

        # ---- device-side anchor: same engine, no HTTP --------------------
        # IDENTICAL prompt to the HTTP clients (same prefill bucket — a
        # different bucket would eat a fresh compile inside the timed
        # window) and one warmup submit first
        dev_prompt = predictor.encode(_messages_prompt())
        engine.submit(dev_prompt, max_new=4, temperature=1.0,
                      top_p=0.9).result(600)
        t0 = time.time()
        futs = [engine.submit(dev_prompt, max_new=max_tokens,
                              temperature=1.0, top_p=0.9)
                for _ in range(clients)]
        dev_tokens = sum(len(f.result(600)) - len(dev_prompt)
                         for f in futs)
        dev_s = time.time() - t0
        device_tps = dev_tokens / dev_s

        # ---- served throughput: N concurrent non-streaming clients ------
        done = []
        lock = threading.Lock()

        errors = []

        def client():
            try:
                r = json.loads(_post(port, _chat_body(max_tokens)).read())
                n = len(r["choices"][0]["message"]["content"])
            except Exception as e:  # noqa: BLE001 — a dropped request is
                with lock:          # a RESULT, not a crash
                    errors.append(repr(e))
                return
            with lock:
                done.append(n)

        threads = [threading.Thread(target=client) for _ in range(clients)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        served_s = time.time() - t0
        served_tokens = sum(done)
        served_tps = served_tokens / served_s

        # ---- TTFT: idle, then under load ---------------------------------
        ttft_idle, _, _ = _ttft_stream(port, max_tokens=8)
        bg = [threading.Thread(target=client)
              for _ in range(max(clients - 1, 1))]
        for t in bg:
            t.start()
        time.sleep(0.3)            # let the load actually occupy slots
        ttft_loaded, _, n_chunks = _ttft_stream(port, max_tokens=8)
        for t in bg:
            t.join()
    finally:
        server.stop()
        engine.stop()

    result = {
        "what": "openai_api over KVCacheLLMEngine, GPT-2-small geometry"
                if not cli.quick else "quick (tiny model)",
        "clients": clients,
        "max_tokens": max_tokens,
        "max_batch": max_batch,
        "tokens_per_dispatch": k,
        "served_tokens_per_sec": round(served_tps, 1),
        "served_wall_s": round(served_s, 2),
        "device_tokens_per_sec": round(device_tps, 1),
        "serving_overhead_pct": round(100 * (1 - served_tps / device_tps),
                                      1),
        "ttft_ms_idle": round(ttft_idle * 1e3, 1),
        "ttft_ms_loaded": round(ttft_loaded * 1e3, 1),
        "stream_chunks_seen": n_chunks,
        "dropped_requests": len(errors),
        "drop_examples": errors[:3],
    }

    guard_fail = None
    if errors:
        guard_fail = f"{len(errors)} dropped requests: {errors[:3]}"
    # sweep runs (SERVE_BENCH_K != default 16) must not overwrite the
    # canonical k=16 headline artifact bench.py reads, nor its floor
    is_sweep = not cli.quick and k != default_k
    if is_sweep:
        result["note"] = (f"k={k} sweep run: results NOT written to the "
                          "canonical artifact")
    if not cli.quick and not is_sweep:
        with open(RESULTS, "w") as f:
            json.dump(result, f, indent=1)
        if cli.update_floor or not os.path.exists(FLOOR):
            floor = {
                "served_tokens_per_sec_min":
                    round(0.75 * served_tps, 1),
                "ttft_ms_idle_max": round(2.0 * ttft_idle * 1e3, 1),
                "note": "0.75x/2x of the committed best — shared-chip "
                        "variance policy of llm_bench_floor.json",
            }
            with open(FLOOR, "w") as f:
                json.dump(floor, f, indent=1)
        else:
            with open(FLOOR) as f:
                floor = json.load(f)
            if served_tps < floor["served_tokens_per_sec_min"]:
                guard_fail = (f"served {served_tps:.1f} tok/s < floor "
                              f"{floor['served_tokens_per_sec_min']}")
            if ttft_idle * 1e3 > floor["ttft_ms_idle_max"]:
                guard_fail = (f"ttft {ttft_idle*1e3:.1f} ms > floor "
                              f"{floor['ttft_ms_idle_max']}")
    result["guard"] = guard_fail or "ok"
    print("SERVE_BENCH " + json.dumps(result))
    if guard_fail:
        print("SERVE GUARD FAILED: " + guard_fail, file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
