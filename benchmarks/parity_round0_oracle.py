"""Round-0 parity oracle: pin down WHERE the reference-vs-fedml_tpu loss
offset enters (VERDICT r3 item 3).

Replays round 0 of the parity config (LEAF-MNIST LR, 2 clients, bs 10,
lr 0.03, sigmoid-before-CE) three ways on IDENTICAL bytes and init:

  torch  — the reference trainer semantics verbatim (Linear + sigmoid +
           CrossEntropyLoss + SGD per batch, partial batch included;
           `ml/trainer/my_model_trainer_classification.py:21-70`)
  jax    — fedml_tpu's build_local_update on mask-padded batches
  fp64   — a numpy float64 re-derivation (ground truth for float error)

and compares the per-batch parameter trajectories and the post-aggregation
test loss/acc.  Run on CPU:  JAX_PLATFORMS=cpu python benchmarks/parity_round0_oracle.py
"""

import json
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
CACHE = os.path.join(REPO, ".data_cache", "refbench")
sys.path.insert(0, REPO)

LR = 0.03
BS = 10


def leaf_clients():
    """Same bytes + same shuffle as both parity runners."""
    import fedml_tpu

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="mnist", data_cache_dir=CACHE, partition_method="natural",
        model="lr", backend="sp", client_num_in_total=2,
        client_num_per_round=2, comm_round=1, epochs=1, batch_size=BS,
        client_optimizer="sgd", learning_rate=LR, weight_decay=0.0,
        lr_sigmoid_outputs=True, frequency_of_the_test=1,
        enable_tracking=False, compute_dtype="float32"))
    dataset = fedml_tpu.data.load(args)
    train_local = dataset[5]
    for cid, (x, y) in list(train_local.items()):
        x = np.array(x, copy=True)
        y = np.array(y, copy=True)
        np.random.seed(100)
        st = np.random.get_state()
        np.random.shuffle(x)
        np.random.set_state(st)
        np.random.shuffle(y)
        train_local[cid] = (x, y)
    return args, dataset, train_local


def sampled_round0(n_total):
    np.random.seed(0)
    return np.random.choice(n_total, 2, replace=False)


def batches_of(x, y):
    return [(x[i:i + BS], y[i:i + BS]) for i in range(0, len(y), BS)]


# ---------------------------------------------------------------- torch
def torch_round(W0, b0, clients_data):
    import torch

    outs = []
    for x, y in clients_data:
        model = torch.nn.Linear(784, 10)
        with torch.no_grad():
            model.weight.copy_(torch.from_numpy(W0))
            model.bias.copy_(torch.from_numpy(b0))
        opt = torch.optim.SGD(model.parameters(), lr=LR)
        crit = torch.nn.CrossEntropyLoss()
        traj = []
        for bx, by in batches_of(x, y):
            model.zero_grad()
            out = torch.sigmoid(model(torch.from_numpy(
                np.asarray(bx, np.float32))))
            loss = crit(out, torch.from_numpy(np.asarray(by)).long())
            loss.backward()
            opt.step()
            traj.append(model.weight.detach().numpy().copy())
        outs.append((model.weight.detach().numpy().copy(),
                     model.bias.detach().numpy().copy(), traj))
    return outs


# ---------------------------------------------------------------- fp64
def _softmax64(z):
    z = z - z.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def fp64_round(W0, b0, clients_data):
    outs = []
    for x, y in clients_data:
        W = W0.astype(np.float64).copy()
        b = b0.astype(np.float64).copy()
        traj = []
        for bx, by in batches_of(x, y):
            bx = np.asarray(bx, np.float64)
            by = np.asarray(by, np.int64)
            m = len(by)
            z = bx @ W.T + b
            s = 1.0 / (1.0 + np.exp(-z))          # sigmoid outputs
            p = _softmax64(s)
            g = p.copy()
            g[np.arange(m), by] -= 1.0            # dCE/ds · m
            g /= m
            gz = g * s * (1.0 - s)                # through sigmoid
            gW = gz.T @ bx
            gb = gz.sum(axis=0)
            W -= LR * gW
            b -= LR * gb
            traj.append(W.copy())
        outs.append((W, b, traj))
    return outs


# ---------------------------------------------------------------- jax
def jax_round(args, W0, b0, clients_data):
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.ml.engine.local_update import (
        build_local_update,
        make_batches,
    )

    bundle = fedml_tpu.model.create(args, 10)
    variables = bundle.init_variables(jax.random.PRNGKey(0))
    local_update = build_local_update(bundle, args)
    step = jax.jit(local_update)
    outs = []
    for x, y in clients_data:
        params = {"Dense_0": {"kernel": jnp.asarray(W0.T),
                              "bias": jnp.asarray(b0)}}
        v = dict(variables, params=params)
        traj = []
        # one batch per call → per-batch trajectory comparable to torch
        for bx, by in batches_of(x, y):
            batches = make_batches(np.asarray(bx, np.float32),
                                   np.asarray(by), BS, 1)
            v, _, _ = step(v, batches, jax.random.PRNGKey(0), None)
            traj.append(np.asarray(v["params"]["Dense_0"]["kernel"]).T)
        outs.append((np.asarray(v["params"]["Dense_0"]["kernel"]).T,
                     np.asarray(v["params"]["Dense_0"]["bias"]), traj))
    return outs


def agg(outs, weights):
    ws = np.asarray(weights, np.float64)
    ws = ws / ws.sum()
    W = sum(w * o[0].astype(np.float64) for w, o in zip(ws, outs))
    b = sum(w * o[1].astype(np.float64) for w, o in zip(ws, outs))
    return W, b


def test_metrics(W, b, x_te, y_te):
    z = np.asarray(x_te, np.float64) @ W.T + b
    s = 1.0 / (1.0 + np.exp(-z))
    p = _softmax64(s)
    y = np.asarray(y_te, np.int64)
    loss = -np.log(p[np.arange(len(y)), y]).mean()
    acc = (p.argmax(axis=-1) == y).mean()
    return float(loss), float(acc)


def main():
    z = np.load(os.path.join(CACHE, "ref_init_lr.npz"))
    W0 = z["linear.weight"].astype(np.float32)
    b0 = z["linear.bias"].astype(np.float32)

    args, dataset, train_local = leaf_clients()
    n_total = int(args.client_num_in_total)
    cids = sampled_round0(n_total)
    data = [train_local[int(c)] for c in cids]
    weights = [len(d[1]) for d in data]
    x_te, y_te = dataset[3]

    t = torch_round(W0, b0, data)
    f = fp64_round(W0, b0, data)
    j = jax_round(args, W0, b0, data)

    report = {"clients": [int(c) for c in cids], "weights": weights,
              "per_batch_divergence": []}
    for ci in range(len(data)):
        for bi, (tw, fw, jw) in enumerate(zip(t[ci][2], f[ci][2],
                                              j[ci][2])):
            report["per_batch_divergence"].append({
                "client": ci, "batch": bi,
                "torch_vs_fp64": float(np.abs(tw - fw).max()),
                "jax_vs_fp64": float(np.abs(jw - fw).max()),
                "torch_vs_jax": float(np.abs(tw - jw).max()),
            })
            if bi > 3 and ci == 0:
                break

    for name, outs in (("torch", t), ("fp64", f), ("jax", j)):
        W, b = agg(outs, weights)
        loss, acc = test_metrics(W, b, x_te, y_te)
        report[f"{name}_round0"] = {"test_loss": loss, "test_acc": acc}
    d_tj = max(r["torch_vs_jax"] for r in report["per_batch_divergence"])
    report["max_torch_vs_jax_param_diff"] = d_tj
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
