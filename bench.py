"""Benchmark: Parrot FedAvg ResNet-56 / CIFAR-10, 100 clients / 10 per round
(the BASELINE.json north-star config) on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline: the reference publishes no numbers (BASELINE.md); the recorded
H100-NCCL anchor used by the driver is wall-clock to target accuracy.  Until
a measured reference anchor exists we report rounds/sec against a NOMINAL
anchor of 1.0 round/sec for this config (documented placeholder), so the
ratio tracks our own progress across rounds.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NOMINAL_BASELINE_ROUNDS_PER_SEC = 1.0


def main() -> None:
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="cifar10",
        model="resnet56",
        backend="parrot",
        client_num_in_total=100,
        client_num_per_round=10,
        comm_round=8,            # 1 warmup/compile + 7 measured
        epochs=1,
        batch_size=32,
        learning_rate=0.05,
        data_scale=0.2,          # synthetic-fallback CIFAR size control
        frequency_of_the_test=100,  # eval only at the end
        enable_tracking=False,
        compute_dtype="bfloat16",
    ))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, device, dataset, bundle)
    api = runner.runner

    import jax

    # Fused scan-over-rounds path: a fixed 8-round chunk is compiled once
    # and re-dispatched, amortizing per-call dispatch/transfer overhead
    # (~7x over per-round dispatch through the remote-TPU tunnel).
    chunk = api.FUSED_CHUNK_ROUNDS
    jax.block_until_ready(api.run_rounds_fused(chunk))  # warmup/compile

    n_rounds = 16 * chunk
    t0 = time.time()
    rms = api.run_rounds_fused(n_rounds)
    jax.block_until_ready(rms)
    dt = time.time() - t0
    rounds_per_sec = n_rounds / dt

    print(json.dumps({
        "metric": "parrot_fedavg_resnet56_cifar10_rounds_per_sec",
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec (100 clients, 10/round, bs32, 1 local epoch)",
        "vs_baseline": round(rounds_per_sec / NOMINAL_BASELINE_ROUNDS_PER_SEC,
                             4),
    }))


if __name__ == "__main__":
    main()
