"""Benchmark: Parrot FedAvg ResNet-56 / CIFAR-10, 100 clients / 10 per round
(the BASELINE.json north-star config) on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline: the reference publishes no numbers (BASELINE.md); the recorded
H100-NCCL anchor used by the driver is wall-clock to target accuracy.  Until
a measured reference anchor exists we report rounds/sec against a NOMINAL
anchor of 1.0 round/sec for this config (documented placeholder), so the
ratio tracks our own progress across rounds.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NOMINAL_BASELINE_ROUNDS_PER_SEC = 1.0


def main() -> None:
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="cifar10",
        model="resnet56",
        backend="parrot",
        client_num_in_total=100,
        client_num_per_round=10,
        comm_round=8,            # 1 warmup/compile + 7 measured
        epochs=1,
        batch_size=32,
        learning_rate=0.05,
        data_scale=0.2,          # synthetic-fallback CIFAR size control
        frequency_of_the_test=100,  # eval only at the end
        enable_tracking=False,
        compute_dtype="bfloat16",
    ))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, device, dataset, bundle)
    api = runner.runner

    import jax
    import jax.numpy as jnp

    # Per-round dispatch path.  (The fused lax.scan-over-rounds path,
    # `api.run_rounds_fused`, amortizes dispatch latency further but its
    # compile doesn't fit the remote-compile tunnel's budget on this driver;
    # it is exercised in tests on CPU.)
    rng = jax.random.PRNGKey(0)
    ids = jnp.asarray(api._client_sampling(0))
    gv, st, _ = api.round_step(api.global_vars, api.server_state, ids, rng)
    jax.block_until_ready(gv)  # warmup/compile

    n_rounds = 10
    t0 = time.time()
    for r in range(1, n_rounds + 1):
        ids = jnp.asarray(api._client_sampling(r))
        rng, sub = jax.random.split(rng)
        gv, st, _ = api.round_step(gv, st, ids, sub)
    jax.block_until_ready(gv)
    dt = time.time() - t0
    rounds_per_sec = n_rounds / dt

    print(json.dumps({
        "metric": "parrot_fedavg_resnet56_cifar10_rounds_per_sec",
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec (100 clients, 10/round, bs32, 1 local epoch)",
        "vs_baseline": round(rounds_per_sec / NOMINAL_BASELINE_ROUNDS_PER_SEC,
                             4),
    }))


if __name__ == "__main__":
    main()
