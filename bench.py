"""North-star benchmark: Parrot FedAvg ResNet-56 / CIFAR-10 (50k samples),
100 clients Dirichlet(0.5), 10 per round, bs 32, 1 local epoch — the
BASELINE.json headline config at FULL dataset scale, with an accuracy guard.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.

vs_baseline is a MEASURED ratio: this framework on the available TPU vs the
reference's own FedAvgAPI/ResNet-56 run on the hardware the reference can use
in this image (1-core CPU torch; `benchmarks/measured_baseline.json`,
recorded by benchmarks/refbench/run_reference_northstar.py). Both sides
consume byte-identical data (benchmarks/gen_northstar_cifar.py npz) and the
identical Dirichlet(0.5) partition.

Beyond rounds/sec the line reports samples/sec, estimated MFU (executed
FLOPs from XLA's compiled cost analysis ÷ wall ÷ chip peak), and
wall-clock-to-target-accuracy — and FAILS (exit 1) if the model does not
reach TARGET_TEST_ACC, so a perf win can never silently regress convergence.
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

ANCHOR_PATH = os.path.join(HERE, "benchmarks", "measured_baseline.json")
NPZ_DIR = os.path.join(HERE, ".data_cache", "northstar")

#: accuracy the run must reach on the HARD synthetic CIFAR (class mixing
#: lam in [0.6,1], +-3px roll jitter, intensity scaling, 2% train label
#: noise — gen_northstar_cifar hard_v2; round 3 replaced the saturating
#: template data that hit acc 1.0): measured plateau 0.92-0.94 over
#: rounds 128-512, real-CIFAR-like; the guard sits below the
#: post-crossing oscillation band; tests/test_bench_guard.py
#: demonstrates guard-style discrimination (healthy clears, sabotaged
#: aggregation stays under) on a small proxy config
TARGET_TEST_ACC = 0.85
MAX_ROUNDS = 512

# bf16 peak FLOP/s table lives in fedml_tpu.constants (single source of
# truth with benchmarks/llm_bench.py); imported in main() after jax init


def _npz_is_current() -> bool:
    path = os.path.join(NPZ_DIR, "cifar10.npz")
    if not os.path.exists(path):
        return False
    sys.path.insert(0, os.path.join(HERE, "benchmarks"))
    from gen_northstar_cifar import DATA_VERSION

    try:
        import numpy as _np

        with _np.load(path) as z:
            return ("meta" in z.files
                    and str(z["meta"][0]) == DATA_VERSION)
    except Exception:
        return False


def _record_perf_history(label: str, metrics: dict) -> None:
    """Append this run's headline to benchmarks/perf_history.jsonl so
    `fedml perf regress` can flag regressions and stale carried numbers;
    bookkeeping must never fail the bench."""
    try:
        import jax

        from fedml_tpu.core.mlops import perf_history

        perf_history.append_entry(
            os.path.join(HERE, *perf_history.DEFAULT_HISTORY.split(os.sep)),
            platform=jax.default_backend(), source="bench.py",
            label=label, measured=True,
            metrics={k: v for k, v in metrics.items() if v is not None})
    except Exception as e:  # noqa: BLE001
        print(f"perf-history append failed: {e}", file=sys.stderr)


def main() -> None:
    if not _npz_is_current():
        # regenerate on version drift too: a stale pre-hard cache would
        # silently run the bench on saturating (easy) data
        subprocess.run([sys.executable,
                        os.path.join(HERE, "benchmarks",
                                     "gen_northstar_cifar.py")], check=True)

    with open(ANCHOR_PATH) as f:
        anchor = json.load(f)["northstar_fedavg_resnet56_cifar10"]

    import jax

    # persistent compilation cache: kills ~40s of the ~130s first compile
    # on re-runs (the rest is client-side tracing; measured in
    # benchmarks/BENCH_NOTES.md round 3)
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(HERE, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

    import fedml_tpu
    from fedml_tpu.core.mlops import flight_recorder
    from fedml_tpu.runner import FedMLRunner

    # fresh flight-log dir per invocation so `fedml perf diff` can compare
    # bench runs without records bleeding across appends
    flight_dir = os.path.join(HERE, ".bench_flight",
                              time.strftime("%Y%m%d-%H%M%S"))
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="cifar10",
        data_cache_dir=NPZ_DIR,          # 50k-sample shared npz
        model="resnet56",
        backend="parrot",
        partition_method="hetero",
        partition_alpha=0.5,
        client_num_in_total=100,
        client_num_per_round=10,
        comm_round=MAX_ROUNDS,
        epochs=1,
        batch_size=32,
        learning_rate=0.05,
        frequency_of_the_test=1000,      # eval handled manually below
        enable_tracking=False,
        flight_recorder=True,            # phase attribution + measured MFU
        log_file_dir=flight_dir,
        compute_dtype="bfloat16",
        hetero_buckets=10,               # 1 client per stratum: minimal
                                         # padding AND no grouped-conv
                                         # vmap lowering (measured optimal,
                                         # benchmarks/mfu_probe.py sweep)
        hetero_bucket_cap=0.8,           # cap each stratum's batch
                                         # capacity at 0.8x its mean size
                                         # with per-round rotating windows
                                         # for over-cap clients: padded
                                         # samples/round 5664 -> 4128 at
                                         # 99.9% slot utilization (PERF003
                                         # perf-lint audit; coverage
                                         # preserved across rounds)
    ))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, device, dataset, bundle)
    api = runner.runner

    import jax.numpy as jnp
    import numpy as np

    chunk = api.FUSED_CHUNK_ROUNDS
    # fresh rng per fused call — with rng=None every call would replay the
    # identical PRNGKey(seed+23) sampling stream (same clients, same noise)
    rng = jax.random.PRNGKey(int(args.random_seed) + 1001)

    def fused(n):
        nonlocal rng
        rng, sub = jax.random.split(rng)
        return api.run_rounds_fused(n, rng=sub)

    # program readiness — parrot_api._ensure_multi_round_step compiles
    # eagerly on EVERY path (AOT-cache load, or trace+lower+compile), so
    # this is the honest "compile_s" regardless of parrot_aot_cache; the
    # first chunk's 64 REAL training rounds are timed separately (they
    # used to be conflated, overstating compile by ~19 s)
    t_c0 = time.time()
    api._ensure_multi_round_step()
    compile_s = time.time() - t_c0
    t_c0 = time.time()
    rms = fused(chunk)                   # warmup chunk (execution only)
    _ = float(np.asarray(rms["train_loss"])[0])   # real sync (host fetch)
    first_chunk_s = time.time() - t_c0
    rounds_done = chunk

    # ---- measured perf window --------------------------------------------
    n_meas = 4 * chunk
    t0 = time.time()
    rms = fused(n_meas)
    jax.block_until_ready(rms["train_loss"])
    dt = time.time() - t0
    rounds_per_sec = n_meas / dt
    samples = float(np.sum(np.asarray(rms["samples"])))
    samples_per_sec = samples / dt
    rounds_done += n_meas

    # ---- measured MFU (XLA cost analysis x flight-recorder device time) --
    # The compiled chunk's executed FLOPs come from XLA's own
    # cost_analysis, captured by flight_recorder.note_program when
    # _ensure_multi_round_step compiled (or cache-loaded) the fused scan;
    # device seconds come from the recorder's block_until_ready-synced
    # device_compute phase.  The hand-derived ResNet-56 figure stays as a
    # CROSS-CHECK: the remote-TPU plugin once reported cost_analysis ~16x
    # low, and a silent factor like that must fail the bench, not ship in
    # a headline MFU.  Analytic: ResNet-56 on 32x32 CIFAR = 126.5
    # MMACs/sample forward (well-known figure; 2 FLOPs/MAC), x3 for
    # fwd+bwd, times the PADDED samples each round actually executes
    # (Σ_buckets k_b·nb_b·bs, or k·nb·bs uniform).
    RESNET56_FWD_FLOPS = 2 * 126.5e6
    TRAIN_MULT = 3.0
    if api.buckets is not None:
        padded_per_round = sum(b["k"] * b["nb"] for b in api.buckets) * api.bs
    else:
        padded_per_round = api.k * api.nb * api.bs
    flops_analytic = padded_per_round * RESNET56_FWD_FLOPS * TRAIN_MULT
    chunk_flops = (api.program_costs or {}).get("flops")
    flops_cost = chunk_flops / chunk if chunk_flops else None
    from fedml_tpu.constants import (
        TPU_PEAK_BF16_DEFAULT,
        TPU_PEAK_BF16_FLOPS,
    )

    kind = jax.devices()[0].device_kind
    peak = TPU_PEAK_BF16_FLOPS.get(kind, TPU_PEAK_BF16_DEFAULT)

    # measured device seconds per round over the perf window's fused
    # chunks (warmup + measured window are all kind="parrot_fused")
    fl = flight_recorder.summarize(
        flight_recorder.load_flight_log(flight_dir))
    pf = fl["kinds"].get("parrot_fused", {})
    dev_s = pf.get("phases_s", {}).get("device_compute", 0.0)
    dev_s_per_round = dev_s / max(1, pf.get("rounds", 0))

    flops_per_round = flops_cost if flops_cost else flops_analytic
    flops_source = ("xla_cost_analysis(compiled fused chunk)/chunk_rounds"
                    if flops_cost else
                    "analytic 2*126.5e6 FLOPs/sample x3 (cost_analysis "
                    "unavailable on this backend)")
    if dev_s_per_round > 0:
        mfu = flops_per_round / dev_s_per_round / peak
        mfu_source = (f"{flops_source} / flight-recorder device_compute "
                      "seconds / chip peak")
    else:
        mfu = flops_per_round * rounds_per_sec / peak
        mfu_source = f"{flops_source} x rounds_per_sec / chip peak (wall)"
    mfu_guard_msg = None
    if flops_cost:
        ratio = flops_cost / flops_analytic
        if not (0.5 <= ratio <= 2.0):
            mfu_guard_msg = (
                f"MFU FLOPS GUARD FAILED: cost_analysis/analytic ratio "
                f"{ratio:.3f} outside [0.5, 2] — XLA's reported FLOPs and "
                f"the hand-derived ResNet-56 figure disagree >2x; one of "
                f"them is wrong (remote-TPU plugin has reported ~16x low)")

    # ---- train to the accuracy target (wall-clock-to-accuracy) ------------
    test_batches = api._make_test_batches()

    def test_acc():
        out = api.eval_step(api.global_vars, test_batches)
        return float(out["correct"]) / max(float(out["n"]), 1.0)

    t_train0 = time.time()
    acc = test_acc()
    wall_to_target = None
    while acc < TARGET_TEST_ACC and rounds_done < MAX_ROUNDS:
        rms = fused(chunk)
        jax.block_until_ready(rms["train_loss"])
        rounds_done += chunk
        acc = test_acc()
    if acc >= TARGET_TEST_ACC:
        # perf window + remaining training + the warmup chunk's TRAINING
        # share (its wall time is compile-dominated; its 64 rounds of real
        # training are charged at the measured steady-state rate so
        # time-to-accuracy is not understated), excluding compile itself
        wall_to_target = ((time.time() - t_train0) + dt
                          + chunk / rounds_per_sec)

    result = {
        "metric": "parrot_fedavg_resnet56_cifar10_50k_rounds_per_sec",
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec (100 clients, 10/round, bs32, 1 epoch, 50k "
                "CIFAR, hetero a=0.5, bf16, 10 size buckets)",
        "vs_baseline": round(rounds_per_sec
                             / float(anchor["rounds_per_sec"]), 2),
        "baseline": {"rounds_per_sec": anchor["rounds_per_sec"],
                     "host": "reference torch on 1-core CPU (only hardware "
                             "the reference runs on here)"},
        "samples_per_sec": round(samples_per_sec, 1),
        "samples_per_sec_vs_baseline": round(
            samples_per_sec / float(anchor["samples_per_sec"]), 2),
        "compile_s": round(compile_s, 1),
        # True ⇒ compile_s is the WARM path (executable deserialized from
        # the AOT cache, no trace/lower/compile) — the driver-visible
        # warm-start datum VERDICT r4 item 2 asked for; cross-process
        # correctness proof lives in tests/test_aot_cache.py
        "aot_cache_hit": bool(getattr(api, "aot_cache_hit", False)),
        "first_chunk_s": round(first_chunk_s, 1),
        "rounds_to_report": rounds_done,
        "final_test_acc": round(acc, 4),
        "target_test_acc": TARGET_TEST_ACC,
        "wall_to_target_acc_s": (None if wall_to_target is None
                                 else round(wall_to_target, 2)),
    }
    result["est_mfu"] = round(mfu, 4)
    result["mfu_source"] = mfu_source
    result["flops_per_round"] = round(flops_per_round, 1)
    result["flops_per_round_analytic"] = round(flops_analytic, 1)
    if flops_cost:
        result["flops_cost_vs_analytic_ratio"] = round(
            flops_cost / flops_analytic, 3)
    result["padded_samples_per_round"] = int(padded_per_round)
    # measured round-phase decomposition from the flight recorder (whole
    # run so far: compile + warmup + perf window), plus log provenance so
    # `fedml perf report/diff` can re-render it
    fl_final = flight_recorder.summarize(
        flight_recorder.load_flight_log(flight_dir))
    result["round_phase_seconds"] = fl_final["phases_s"]
    result["flight_coverage"] = fl_final["coverage"]
    result["flight_overhead_frac"] = fl_final["overhead_frac"]
    result["flight_log"] = os.path.relpath(
        os.path.join(flight_dir, "flight.jsonl"), HERE)
    # per-bucket padded-vs-real so the padding-waste trend stays visible
    # round over round (same accounting as the PERF003 perf-lint rule)
    waste = api.bucket_waste_stats() if hasattr(api, "bucket_waste_stats") \
        else None
    if waste:
        result["bucket_cap_ratio"] = waste["cap_ratio"]
        result["expected_real_samples_per_round"] = \
            waste["expected_real_per_round"]
        result["bucket_waste"] = [
            {"q": b["q"], "nb": b["nb"], "nb_full": b["nb_full"],
             "padded": b["padded"], "real": b["real"]}
            for b in waste["buckets"]]

    # ---- LLM plane (VERDICT r3 item 1): SFT MFU + absolute serving ------
    # run in a subprocess so its device state can't perturb the main
    # bench; on any failure fall back to the committed last-good results
    llm = None
    try:
        proc = subprocess.run(
            [sys.executable,
             os.path.join(HERE, "benchmarks", "llm_bench.py"), "--quick"],
            capture_output=True, text=True, timeout=900)
        if proc.returncode == 0:
            for line in reversed(proc.stdout.strip().splitlines()):
                try:
                    llm = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            result["llm_guard"] = "ok"
        else:
            # a guard-tripped run may still print its summary JSON; do NOT
            # merge its metrics under the good-run keys — fall through to
            # the committed last-good results (marked stale below)
            result["llm_guard"] = "failed"
    except Exception as e:
        result["llm_guard"] = f"error: {e}"
    if llm is None:
        try:
            with open(os.path.join(HERE, "benchmarks",
                                   "llm_bench_results.json")) as f:
                d = json.load(f)
            llm = {"llm_sft_mfu": d["train"]["mfu"],
                   "llm_sft_tokens_per_sec": d["train"]["tokens_per_sec"],
                   "llm_ttft_ms": d["serving"]["ttft_ms_b1_p512"],
                   "llm_decode_tokens_per_sec":
                       d["serving"]["best_decode_tokens_per_sec"]}
            # keep the failure signal visible: a guard-tripped run must not
            # masquerade as a benign skip just because last-good metrics
            # exist to show
            if result.get("llm_guard") == "failed":
                result["llm_guard"] = \
                    "failed (showing committed last-good metrics)"
            else:
                result["llm_guard"] = "stale (committed results)"
        except Exception:
            llm = {}
    for k in ("llm_sft_mfu", "llm_sft_tokens_per_sec", "llm_ttft_ms",
              "llm_decode_tokens_per_sec"):
        if k in llm:
            result[k] = llm[k]

    # served (HTTP-level) numbers from the committed serve_bench artifact
    # (benchmarks/serve_bench.py measures them on-chip; re-running the
    # 48-client load inside bench would double the chip time, so the
    # driver-visible line carries the committed values, source-marked)
    try:
        with open(os.path.join(HERE, "benchmarks",
                               "serve_bench_results.json")) as f:
            served = json.load(f)
        # read all keys BEFORE mutating result: a partial schema must not
        # leave an unsourced served number in the output
        tps, ttft = (served["served_tokens_per_sec"],
                     served["ttft_ms_idle"])
        result["llm_served_tokens_per_sec"] = tps
        result["llm_served_ttft_ms"] = ttft
        result["llm_served_source"] = "committed serve_bench_results.json"
    except Exception:  # noqa: BLE001 — optional artifact
        pass

    _record_perf_history(
        label=result["metric"],
        metrics={"rounds_per_s": rounds_per_sec,
                 "measured_mfu": mfu,
                 "tokens_per_s": result.get("llm_sft_tokens_per_sec")})

    print(json.dumps(result))
    if acc < TARGET_TEST_ACC:
        print(f"ACCURACY GUARD FAILED: {acc:.4f} < {TARGET_TEST_ACC}",
              file=sys.stderr)
        sys.exit(1)
    if mfu_guard_msg is not None:
        print(mfu_guard_msg, file=sys.stderr)
        sys.exit(1)


def main_hyperscale(n_clients: int, rounds: int) -> None:
    """Hyper-scale streaming bench: clients-simulated/sec over a virtual
    population of ``n_clients`` (default 100k, the committed heavy-tailed
    histogram), double-buffered cohort streaming vs sequential staging on
    the SAME config, with the flight-recorder phase breakdown.

    Prints ONE JSON line and exits 1 if double-buffering does not put the
    h2d-blocked share strictly below the sequential-staging share — the
    overlap claim is enforced, not assumed.  On a CPU-only container the
    absolute clients/sec is a CPU proxy (provenance-marked); the overlap
    and phase decomposition are the portable deliverable.
    """
    # 8 virtual host devices so the sharded client axis is exercised on
    # the CPU proxy; --xla_force_host_platform_device_count only affects
    # the host platform, so a TPU run is untouched by this
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(HERE, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

    import fedml_tpu
    from fedml_tpu.core.mlops import flight_recorder
    from fedml_tpu.runner import FedMLRunner

    sys.path.insert(0, os.path.join(HERE, "benchmarks"))
    from gen_northstar_client_sizes import HYPER_POLICY, OUT_HYPER

    ts = time.strftime("%Y%m%d-%H%M%S")
    pol = HYPER_POLICY
    sizes_path = OUT_HYPER
    slot_util = None
    try:
        with open(OUT_HYPER) as f:
            committed = json.load(f)
        slot_util = committed.get("slot_utilization")
        committed_n = int(committed["client_num_in_total"])
    except FileNotFoundError:
        committed_n = -1
    if n_clients != committed_n:
        # ad-hoc population size: same generator + policy knobs, written
        # next to the flight logs so the committed artifact stays pinned
        from fedml_tpu.data.population import zipf_sizes

        sizes = zipf_sizes(n_clients, seed=0,
                           exponent=pol["zipf_exponent"],
                           min_size=pol["min_size"],
                           max_size=pol["max_size"])
        sizes_path = os.path.join(HERE, ".bench_flight",
                                  f"hyper_sizes_{n_clients}.json")
        os.makedirs(os.path.dirname(sizes_path), exist_ok=True)
        with open(sizes_path, "w") as f:
            json.dump({"sizes": [int(s) for s in sizes]}, f)
        slot_util = None

    def run(prefetch: int):
        flight_dir = os.path.join(HERE, ".bench_flight",
                                  f"{ts}-hyper-p{prefetch}")
        args = fedml_tpu.init(fedml_tpu.Config(
            dataset="synthetic",
            model="lr",
            backend="hyperscale",
            # loader-side client count only — population_sizes_path
            # overrides N with the heavy-tailed histogram; the loader
            # just provides the shared base arrays + test set
            client_num_in_total=64,
            client_num_per_round=pol["client_num_per_round"],
            comm_round=rounds,
            epochs=1,
            batch_size=pol["batch_size"],
            learning_rate=0.05,
            data_scale=0.1,
            frequency_of_the_test=max(rounds, 1),
            enable_tracking=False,
            flight_recorder=True,
            log_file_dir=flight_dir,
            hetero_buckets=pol["hetero_buckets"],
            hetero_bucket_cap=pol["hetero_bucket_cap"],
            cohort_sampling="hierarchical",
            population_sizes_path=sizes_path,
            stream_prefetch=prefetch,
        ))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        api = FedMLRunner(args, device, dataset, bundle).runner

        # warm the jit caches OUTSIDE the measured window (train() resets
        # its stream stats on entry): one eval + one manual round step,
        # so clients/sec measures steady-state streaming, not compile.
        # Must run under the same mesh context as train() — the jit cache
        # keys on the ambient resource env, so a bare warmup would leave
        # the in-mesh call to recompile inside the measured window.
        import contextlib

        t0 = time.time()
        with api.mesh if api.mesh is not None else contextlib.nullcontext():
            jax.block_until_ready(
                api.eval_step(api.global_vars, api._make_test_batches()))
            # two steps, not one: step 1's inputs carry the init-time
            # (single-device) shardings, its outputs the compiled mesh
            # shardings — only step 2 compiles the steady-state signature
            # every train() round actually hits
            for _ in range(2):
                staged = api._stage(0)
                gv, ss, rm = api.round_step(
                    staged.grids, staged.weights, staged.ids,
                    api.global_vars, api.server_state, jax.random.PRNGKey(0))
                jax.block_until_ready(rm)
                api.global_vars, api.server_state = gv, ss
        compile_s = time.time() - t0

        metrics = api.train()
        st = api.stream_stats()
        fl = flight_recorder.summarize(
            flight_recorder.load_flight_log(flight_dir))
        return api, st, fl, flight_dir, compile_s, metrics

    _, st_seq, _, _, _, _ = run(prefetch=1)
    api, st, fl, flight_dir, compile_s, metrics = run(prefetch=2)

    result = {
        "metric": "hyperscale_parrot_clients_per_sec",
        "value": st["clients_per_sec"],
        "unit": (f"clients-simulated/sec ({n_clients} heavy-tailed "
                 f"virtual clients, {pol['client_num_per_round']}/round, "
                 f"bs{pol['batch_size']}, {pol['hetero_buckets']} strata, "
                 f"cap {pol['hetero_bucket_cap']}, hierarchical sampling, "
                 f"double-buffered streaming)"),
        "n_clients": n_clients,
        "rounds": rounds,
        "clients_simulated": st["clients_simulated"],
        "policy": pol,
        "slot_utilization": slot_util,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": len(jax.devices()),
        "provenance": (
            "MEASURED on this host; CPU proxy unless platform == 'tpu' — "
            "absolute clients/sec is then relative, the h2d/compute "
            "overlap + phase decomposition is the portable deliverable"),
        "compile_s": round(compile_s, 1),
        "final_test_acc": round(float(metrics.get("test_acc", 0.0)), 4),
        "stream": st,
        "sequential": st_seq,
        "h2d_share_stream": st["h2d_share"],
        "h2d_share_sequential": st_seq["h2d_share"],
        "overlap_frac": st["overlap_frac"],
        "round_phase_seconds": fl["phases_s"],
        "flight_coverage": fl["coverage"],
        "flight_overhead_frac": fl["overhead_frac"],
        "flight_log": os.path.relpath(
            os.path.join(flight_dir, "flight.jsonl"), HERE),
    }
    _record_perf_history(
        label=result["metric"],
        metrics={"clients_per_s": float(st["clients_per_sec"])})

    print(json.dumps(result))
    if not st["h2d_share"] < st_seq["h2d_share"]:
        print(f"OVERLAP GUARD FAILED: streamed h2d share "
              f"{st['h2d_share']} not below sequential "
              f"{st_seq['h2d_share']} — the double buffer is not hiding "
              f"the upload behind device compute", file=sys.stderr)
        sys.exit(1)


def main_epilogue(rounds: int, clients: int, mode: str) -> None:
    """Fused round-epilogue A/B (mirrors the --hyperscale overlap guard):
    the SAME model-shaped stacked-update reduce + FedOpt-adam server step
    run (a) through ``ops.epilogue.fused_epilogue`` — one device program,
    one HBM pass per leaf — and (b) through the legacy chain — the
    weighted reduce materialized by one jit, then a second jit for the
    pseudo-gradient + optax adam + apply — with the flight recorder
    attributing each mode's wall to an ``aggregation`` phase.

    Prints ONE JSON line with the dominant phase per mode and exits 1 if
    the fused aggregation-phase seconds are not STRICTLY lower than the
    unfused ones.  A compile-ahead probe rides along: three small Parrot
    runs (no warm pool / cold warm pool / warm-pool cache hit) showing
    the round-1 ``compile`` phase leaving the flight log.  On a CPU-only
    container everything is a provenance-marked CPU proxy; the phase
    structure and the fused-vs-unfused contrast are the portable
    deliverable."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    import fedml_tpu
    from fedml_tpu.core.mlops import flight_recorder
    from fedml_tpu.ml.aggregator.agg_operator import weighted_average
    from fedml_tpu.ops.epilogue import EpilogueSpec, fused_epilogue

    ts = time.strftime("%Y%m%d-%H%M%S")
    rng = np.random.default_rng(0)

    # model-shaped stacked client updates: bf16 conv stack + f32 dense
    # head, the wire dtypes of the north-star path (~4.3 MB/client)
    def leaf(*shape, dt=np.float32):
        return jnp.asarray(rng.standard_normal((clients, *shape)) * 1e-2,
                           dt)

    stacked = {
        "conv": [leaf(3, 3, 64, 64, dt=jnp.bfloat16) for _ in range(8)],
        "dense": {"kernel": leaf(1024, 512), "bias": leaf(512)},
        "head": {"kernel": leaf(512, 10), "bias": leaf(10)},
    }
    global_tree = jax.tree_util.tree_map(lambda s: s[0], stacked)
    weights = jnp.asarray(rng.uniform(0.5, 2.0, clients), jnp.float32)
    # the legacy funnel consumes a per-client (weight, tree) list — the
    # same updates, unstacked (FedMLAggOperator.agg's wire shape)
    grad_list = [(float(weights[i]),
                  jax.tree_util.tree_map(lambda s: s[i], stacked))
                 for i in range(clients)]
    spec = EpilogueSpec(opt="adam", lr=1e-3)
    f32 = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda a: jnp.zeros(a.shape, jnp.float32), t)

    fused_step = jax.jit(
        lambda g, s, w, st: fused_epilogue(g, s, w, 1.0, spec, st),
        donate_argnums=(0, 3))

    tx = optax.adam(spec.lr, b1=spec.b1, b2=spec.b2, eps=spec.eps)

    @jax.jit
    def unfused_opt(g, agg, st):
        grad = jax.tree_util.tree_map(
            lambda a, b: (a.astype(jnp.float32)
                          - b.astype(jnp.float32)), g, agg)
        updates, st2 = tx.update(grad, st, g)
        return optax.apply_updates(g, updates), st2

    def run(kind):
        flight_dir = os.path.join(HERE, ".bench_flight",
                                  f"{ts}-epilogue-{kind}")
        flight_recorder.enable(True, log_dir=flight_dir)
        # fused_step donates the global — each mode folds its own copy
        g = jax.tree_util.tree_map(lambda a: a.copy(), global_tree)
        st = ({"m": f32(global_tree), "v": f32(global_tree),
               "t": jnp.zeros((), jnp.int32)} if kind == "fused"
              else tx.init(f32(global_tree)))
        # warm the jits outside the measured window
        if kind == "fused":
            g, st = fused_step(g, stacked, weights, st)
        else:
            g, st = unfused_opt(g, weighted_average(grad_list), st)
        jax.block_until_ready(g)
        t0 = time.perf_counter()
        for _ in range(rounds):
            with flight_recorder.record_round(
                    f"epilogue_{kind}",
                    program=f"agg/{kind}_epilogue"):
                with flight_recorder.phase("aggregation"):
                    if kind == "fused":
                        g, st = fused_step(g, stacked, weights, st)
                        jax.block_until_ready(g)
                    else:
                        # the pre-fusion host funnel: eager per-leaf
                        # weighted_average materializes the aggregate,
                        # then a second program steps the server opt
                        agg = weighted_average(grad_list)
                        jax.block_until_ready(agg)
                        g, st = unfused_opt(g, agg, st)
                        jax.block_until_ready(g)
        wall = time.perf_counter() - t0
        fl = flight_recorder.summarize(
            flight_recorder.load_flight_log(flight_dir))
        flight_recorder.reset()
        k = fl["kinds"][f"epilogue_{kind}"]
        phases = k["phases_s"]
        return {"agg_phase_s": round(phases.get("aggregation", 0.0), 4),
                "wall_s": round(wall, 4),
                "dominant_phase": next(iter(phases), None),
                "phases_s": phases,
                "flight_log": os.path.relpath(
                    os.path.join(flight_dir, "flight.jsonl"), HERE)}

    modes = ["fused", "unfused"] if mode == "both" else [mode]
    results = {k: run(k) for k in modes}

    out = {
        "metric": "epilogue_aggregation_phase_seconds",
        "unit": (f"seconds in the 'aggregation' flight phase over "
                 f"{rounds} folds of {clients} stacked client updates "
                 f"(bf16 conv + f32 dense, FedOpt adam server step)"),
        "rounds": rounds,
        "clients": clients,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        "provenance": ("MEASURED on this host; CPU proxy unless "
                       "platform == 'tpu'.  unfused = the pre-fusion "
                       "host funnel (eager per-leaf weighted_average + "
                       "separately jitted optax adam); fused = the "
                       "one-program epilogue (stacked reduce + server "
                       "opt in one jit — the pallas one-HBM-pass kernels "
                       "engage only on TPU, off-TPU the jnp fallback "
                       "still collapses the program count)"),
        **{k: v for k, v in results.items()},
    }
    if mode == "both":
        out["speedup"] = round(results["unfused"]["agg_phase_s"]
                               / max(results["fused"]["agg_phase_s"],
                                     1e-9), 3)
        out["compile_ahead"] = _epilogue_compile_ahead_probe(ts)
    print(json.dumps(out))
    if mode == "both" and not (results["fused"]["agg_phase_s"]
                               < results["unfused"]["agg_phase_s"]):
        print(f"EPILOGUE GUARD FAILED: fused aggregation phase "
              f"{results['fused']['agg_phase_s']}s not strictly below "
              f"unfused {results['unfused']['agg_phase_s']}s — the "
              f"fused epilogue is not paying for itself",
              file=sys.stderr)
        sys.exit(1)


def _epilogue_compile_ahead_probe(ts: str) -> dict:
    """Three small Parrot runs against one shared AOT cache: (1) no warm
    pool — round 1 pays the ``compile`` phase in the flight log; (2) cold
    warm pool — the same wall moves to the standalone ``compile_ahead``
    phase and the executables land in the cache; (3) a second API with a
    warm pool — every executable is a cache HIT."""
    import tempfile

    import numpy as np

    import fedml_tpu
    from fedml_tpu.core.mlops import flight_recorder
    from fedml_tpu.runner import FedMLRunner

    cache = tempfile.mkdtemp(prefix="epilogue_aot_")

    def mk(tagname, compile_ahead):
        flight_dir = os.path.join(HERE, ".bench_flight",
                                  f"{ts}-epilogue-aot-{tagname}")
        args = fedml_tpu.init(fedml_tpu.Config(
            dataset="synthetic", model="lr", backend="parrot",
            client_num_in_total=8, client_num_per_round=8, comm_round=2,
            epochs=1, batch_size=16, learning_rate=0.1, data_scale=0.1,
            frequency_of_the_test=2, enable_tracking=False,
            flight_recorder=True, log_file_dir=flight_dir,
            aot_cache_dir=cache, parrot_compile_ahead=compile_ahead))
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        api = FedMLRunner(args, None, dataset, bundle).runner
        if compile_ahead:
            api.start_compile_ahead(wait=True)
        rms = api.run_rounds_fused(2)
        assert np.isfinite(np.asarray(rms["train_loss"])).all()
        fl = flight_recorder.summarize(
            flight_recorder.load_flight_log(flight_dir))
        flight_recorder.reset()
        return {
            "compile_s_in_rounds": round(
                fl["phases_s"].get("compile", 0.0), 3),
            "compile_ahead_s": round(
                fl["phases_s"].get("compile_ahead", 0.0), 3),
            "aot_cache_hit": bool(api.aot_cache_hit),
            "report": getattr(api, "compile_ahead_report", {}),
        }

    return {"no_warm_pool": mk("baseline", False),
            "cold_warm_pool": mk("cold", True),
            "warm_warm_pool": mk("warm", True)}


if __name__ == "__main__":
    if "--epilogue" in sys.argv:
        import argparse

        ap = argparse.ArgumentParser(
            description="fused round-epilogue A/B (aggregation phase)")
        ap.add_argument("--epilogue", nargs="?", const="both",
                        choices=("both", "fused", "unfused"),
                        help="run the fused-vs-unfused epilogue A/B "
                             "(default: both + guard), or one mode alone")
        ap.add_argument("--rounds", type=int, default=30,
                        help="measured folds per mode (after a warmup "
                             "fold excluded from the window)")
        ap.add_argument("--clients", type=int, default=32,
                        help="stacked client updates per fold")
        opts = ap.parse_args()
        main_epilogue(opts.rounds, opts.clients, opts.epilogue or "both")
    elif "--hyperscale" in sys.argv or "--n-clients" in sys.argv:
        import argparse

        ap = argparse.ArgumentParser(
            description="hyper-scale streaming bench (clients/sec)")
        ap.add_argument("--hyperscale", action="store_true",
                        help="run the hyper-scale streaming bench instead "
                             "of the north-star ResNet-56 bench")
        ap.add_argument("--n-clients", type=int, default=100_000,
                        help="virtual population size (default: the "
                             "committed 100k heavy-tailed histogram)")
        ap.add_argument("--rounds", type=int, default=8,
                        help="measured rounds per mode (after a warmup "
                             "round excluded from the window)")
        opts = ap.parse_args()
        main_hyperscale(opts.n_clients, opts.rounds)
    else:
        main()
