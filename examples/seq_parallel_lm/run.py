"""Long-context LM training with sequence (context) parallelism.

Shards the token axis over a `seq` mesh (ring attention: K/V blocks rotate
on ICI via ppermute, flash-kernel partials merged exactly) so no device ever
holds the full [B, T] context — the capability SURVEY §2.14 lists as absent
in the reference.  Runs on any device count:

    # 8 virtual CPU devices
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/seq_parallel_lm/run.py

    # real TPU(s): just run it; the mesh sizes to the available chips
    python examples/seq_parallel_lm/run.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    # honor the virtual-CPU-mesh invocation even when a TPU plugin's
    # sitecustomize pre-selects its platform
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

from fedml_tpu.ml.engine.mesh import build_mesh
from fedml_tpu.parallel.seq_parallel import (
    build_seq_parallel_train_step,
    init_lm_params,
)


def main() -> None:
    n = len(jax.devices())
    seq_shards = max(
        [s for s in (1, 2, 4, 8) if s <= n and 256 % s == 0])
    mesh = build_mesh({"seq": seq_shards})
    vocab, heads, t, b = 256, 8, 256, 4

    params = init_lm_params(jax.random.PRNGKey(0), vocab, dim=128,
                            layers=4, heads=heads, max_len=t)
    step, tok_sharding = build_seq_parallel_train_step(
        mesh, heads, strategy="ring", learning_rate=0.3)

    # byte-level "corpus": learn to continue a repeating pattern
    rng = np.random.RandomState(0)
    pattern = rng.randint(0, vocab, size=64)
    stream = np.tile(pattern, 64)

    n_iters = 80
    with mesh:
        for it in range(n_iters):
            start = rng.randint(0, len(stream) - t - 1, size=b)
            tokens = jnp.asarray(np.stack([stream[s:s + t] for s in start]))
            tokens = jax.device_put(tokens, tok_sharding)
            params, loss = step(params, tokens)
            if it % 10 == 0 or it == n_iters - 1:
                print(f"iter {it:3d}  seq_shards={seq_shards}  "
                      f"loss {float(loss):.4f}")
    assert float(loss) < 2.0, "pattern should be learnable"
    print("OK")


if __name__ == "__main__":
    main()
