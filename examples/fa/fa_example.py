"""Federated analytics: run every FA task over the cross-silo message plane.

Usage: python examples/fa/fa_example.py
"""

import fedml_tpu
from fedml_tpu.fa.cross_silo import run_cross_silo_fa

client_data = {0: [1, 2, 5], 1: [2, 3, 5], 2: [2, 5, 9]}

for task in ("avg", "intersection", "union", "cardinality", "frequency",
             "k_percentile"):
    args = fedml_tpu.Config(fa_task=task, run_id=f"fa_demo_{task}")
    print(task, "→", run_cross_silo_fa(args, client_data))

words = {i: ["the", "the", "then", "cat", "car"] for i in range(3)}
args = fedml_tpu.Config(fa_task="heavy_hitter_triehh", comm_round=3,
                        triehh_theta=3, run_id="fa_demo_hh")
print("heavy_hitter_triehh →", run_cross_silo_fa(args, words))
