"""KV-cache LLM serving: prefill/decode engine + int8 quantization + the
OpenAI-compatible chat API.

    python examples/kv_serving/main.py           # serves one request and exits
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import json
import urllib.request

import jax
import numpy as np

from fedml_tpu.serving.kv_cache_lm import KVCacheLM
from fedml_tpu.serving.llm_engine import KVCacheLLMEngine, LLMEnginePredictor
from fedml_tpu.serving.openai_api import OpenAIServer
from fedml_tpu.serving.quantization import QuantizedKVCacheLM


def main() -> None:
    # char-level demo model (fine-tune one with train/llm first for real use)
    lm = KVCacheLM.create(jax.random.PRNGKey(0), vocab=90, dim=64,
                          layers=2, heads=4, max_len=128)
    lm = QuantizedKVCacheLM.from_lm(lm)        # int8 weights, same API
    engine = KVCacheLLMEngine(lm, max_batch=4)
    server = OpenAIServer(LLMEnginePredictor(engine), model_name="kv-demo",
                          port=0)
    try:
        server.run(block=False)
        body = json.dumps({
            "model": "kv-demo", "max_tokens": 16, "temperature": 0.7,
            "top_p": 0.9,
            "messages": [{"role": "user", "content": "to be or not"}],
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/chat/completions",
            data=body, headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=300).read())
        print("completion:", repr(resp["choices"][0]["message"]["content"]))

        # raw engine path: concurrent requests, continuous batching
        futs = [engine.submit(list(np.random.randint(0, 90, size=n)),
                              max_new=8) for n in (3, 11, 6)]
        for i, f in enumerate(futs):
            print(f"request {i}: {len(f.result(300))} tokens")
    finally:
        server.stop()
        engine.stop()
    print("OK")


if __name__ == "__main__":
    main()
