"""KV-cache LLM serving: prefill/decode engine + int8 quantization + the
OpenAI-compatible chat API.

    python examples/kv_serving/main.py           # serves one request and exits
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import json
import urllib.request

import jax
import numpy as np

from fedml_tpu.serving.kv_cache_lm import KVCacheLM
from fedml_tpu.serving.llm_engine import KVCacheLLMEngine, LLMEnginePredictor
from fedml_tpu.serving.openai_api import OpenAIServer
from fedml_tpu.serving.quantization import QuantizedKVCacheLM


def main() -> None:
    # 1) LoRA fine-tune the functional LM on a char corpus (the SAME pytree
    #    the KV engine serves — no export/conversion step)
    import fedml_tpu
    from fedml_tpu.data.datasets import shakespeare_sequences
    from fedml_tpu.train.llm import LLMTrainConfig, LLMTrainer, apply_lora

    args = fedml_tpu.Config(model="functional_lm", dataset="shakespeare",
                            compute_dtype="float32", lm_dim=64, lm_layers=2,
                            lm_heads=4, lm_max_len=128)
    bundle = fedml_tpu.model.create(args, 90)
    xt, _, _, _ = shakespeare_sequences(seq_len=64, n_train=128, n_test=8)
    stream = np.concatenate(list(xt))
    cfg = LLMTrainConfig(seq_len=64, batch_size=8, epochs=2,
                         learning_rate=3e-3, lora_rank=8)
    trainer = LLMTrainer(bundle, cfg)
    metrics = trainer.train(stream)
    print("fine-tune loss history:",
          [round(x, 3) for x in metrics["loss_history"]])

    # 2) merge LoRA, quantize to int8, serve through the KV-cache engine
    merged = apply_lora(trainer.variables["params"], trainer.lora,
                        cfg.lora_alpha)
    lm = QuantizedKVCacheLM.from_lm(KVCacheLM(merged, heads=4, max_len=128))
    engine = KVCacheLLMEngine(lm, max_batch=4)
    server = OpenAIServer(LLMEnginePredictor(engine), model_name="kv-demo",
                          port=0)
    try:
        server.run(block=False)
        body = json.dumps({
            "model": "kv-demo", "max_tokens": 16, "temperature": 0.7,
            "top_p": 0.9,
            "messages": [{"role": "user", "content": "to be or not"}],
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/chat/completions",
            data=body, headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=300).read())
        print("completion:", repr(resp["choices"][0]["message"]["content"]))

        # raw engine path: concurrent requests, continuous batching
        futs = [engine.submit(list(np.random.randint(0, 90, size=n)),
                              max_new=8) for n in (3, 11, 6)]
        for i, f in enumerate(futs):
            print(f"request {i}: {len(f.result(300))} tokens")
    finally:
        server.stop()
        engine.stop()
    print("OK")


if __name__ == "__main__":
    main()
