"""Chatbot serving template (reference `serving/templates/hf_template/
main_openai.py`): fine-tune a small LM with LoRA, then serve it behind the
OpenAI-compatible chat API via the continuous-batching engine.

Usage: PYTHONPATH=. python examples/serving_chatbot/main.py [--port 8000]
Then point any OpenAI SDK client at http://127.0.0.1:<port>/v1 .
"""

import sys

import jax
import numpy as np

import fedml_tpu
from fedml_tpu.data.datasets import shakespeare_sequences
from fedml_tpu.models import model_hub
from fedml_tpu.serving.llm_engine import BatchedLLMEngine, LLMEnginePredictor
from fedml_tpu.serving.openai_api import OpenAIServer
from fedml_tpu.train.llm.trainer import LLMTrainConfig, LLMTrainer


def main(port: int = 8000) -> None:
    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            compute_dtype="float32")
    bundle = model_hub.create(args, 90)

    # 1) brief LoRA fine-tune on ONE contiguous char stream (concatenating
    # randomly-sampled windows would corrupt targets at every seam)
    cfg = LLMTrainConfig(seq_len=32, batch_size=8, epochs=1, use_lora=True,
                         lora_rank=4, learning_rate=1e-3)
    trainer = LLMTrainer(bundle, cfg, rng=jax.random.PRNGKey(0))
    stream, _, _, _ = shakespeare_sequences(seq_len=512 * 33, n_train=1,
                                            n_test=1)
    metrics = trainer.train(np.asarray(stream).reshape(-1))
    print("fine-tune:", metrics)

    # 2) serve the (LoRA-merged) model
    from fedml_tpu.train.llm.lora import merge_lora

    variables = dict(trainer.variables,
                     params=merge_lora(trainer.variables["params"],
                                       trainer.lora, cfg.lora_alpha))
    engine = BatchedLLMEngine(bundle, variables, max_batch=8, window=32)
    server = OpenAIServer(LLMEnginePredictor(engine),
                          model_name="shakespeare-tiny", port=port)
    print(f"serving on http://127.0.0.1:{port}/v1/chat/completions")
    try:
        server.run(block=True)
    finally:
        engine.stop()


if __name__ == "__main__":
    port = 8000
    if "--port" in sys.argv:
        try:
            port = int(sys.argv[sys.argv.index("--port") + 1])
        except (IndexError, ValueError):
            sys.exit("usage: main.py [--port <int>]")
    main(port)
