"""Cross-cloud federation example: every party is a TPU-slice mesh.

2 clouds x 4-device fsdp on the virtual CPU mesh (or real slices on a pod):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/cross_cloud/main.py

Each cloud trains the transformer LM fsdp-sharded over its own 4 devices
(ZeRO-equivalent, XLA collectives on ICI); rounds between the clouds ride
the cross-silo message protocol — the reference needs DeepSpeed + NCCL +
its Cheetah managers for this shape (`cross_cloud/`,
`train/llm/distributed.py:20-58`).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def main() -> None:
    args = fedml_tpu.init(fedml_tpu.Config(
        training_type="cross_cloud",
        backend="INPROC",
        dataset="shakespeare",
        model="transformer",
        cloud_slices=True,
        cloud_strategy="fsdp",
        client_num_in_total=2,
        client_num_per_round=2,
        comm_round=5,
        epochs=1,
        batch_size=8,
        learning_rate=0.01,
        client_optimizer="adam",
        data_scale=0.3,
        frequency_of_the_test=1,
        compute_dtype="float32",
        enable_tracking=False,
    ))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    metrics = FedMLRunner(args, device, dataset, bundle).run()
    print("final:", metrics)


if __name__ == "__main__":
    main()
