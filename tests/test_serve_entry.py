"""Deploy-runtime depth (VERDICT r3 item 5): the `fedml serve` gateway —
per-request metrics feeding the autoscaler, versioned endpoints with
rollback, and the container entrypoint as a tested code path whose flags
the devops/ manifests must match."""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from fedml_tpu.scheduler.model_cards import EndpointDB, ModelCardRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_card(tmp_path, w_scale: float, name="lin"):
    rng = np.random.RandomState(0)
    model_dir = tmp_path / f"model_{w_scale}"
    model_dir.mkdir(exist_ok=True)
    np.savez(model_dir / "model.npz",
             w2=(rng.randn(6, 3) * w_scale).astype(np.float32),
             b2=np.zeros(3, np.float32))
    reg = ModelCardRegistry(root=str(tmp_path / "registry"))
    card = reg.create(name, str(model_dir))
    return reg, card


def _post(url, body, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def test_autoscaler_driven_from_metrics_store(tmp_path):
    """The scaling decision consumes the REQUEST-METRICS STORE: slow
    requests recorded in EndpointDB push the observed latency over the
    policy target and the autoscaler scales up via apply_fn."""
    from fedml_tpu.scheduler.autoscaler import (
        AutoscalePolicy,
        ReplicaAutoscaler,
    )

    db = EndpointDB(path=str(tmp_path / "endpoints.db"))
    for _ in range(30):
        db.record("lin", latency_ms=2500.0, ok=True)    # 2.5s > 1s target

    applied = []
    policy = AutoscalePolicy(min_replicas=1, max_replicas=4,
                             target_latency_s=1.0)
    scaler = ReplicaAutoscaler(policy, apply_fn=applied.append)
    w = db.window("lin", window_s=30.0)
    assert w["requests"] == 30 and w["avg_latency_s"] > 2.0
    n = scaler.observe(w["qps"], w["avg_latency_s"])
    assert n > 1 and applied and applied[-1] == n

    # and an idle window scales back down (after the idle-tick hysteresis)
    db2 = EndpointDB(path=str(tmp_path / "idle.db"))
    w0 = db2.window("lin", window_s=30.0)
    scaler._last_scale_t = -1e18                       # bypass cooldown
    for _ in range(policy.scale_down_idle_ticks + 1):
        n = scaler.observe(w0["qps"], w0["avg_latency_s"])
        scaler._last_scale_t = -1e18
    assert n < applied[0] or n == policy.min_replicas


@pytest.mark.slow
def test_gateway_serves_records_metrics_and_rolls_back(tmp_path):
    """End to end in-process: deploy v1, predict through the gateway
    (metrics recorded), publish v2 (different weights), rolling update,
    then ROLLBACK — the endpoint must serve v1's exact outputs again."""
    from fedml_tpu.serving.serve_entry import ServeGateway

    reg, card_v1 = _make_card(tmp_path, w_scale=1.0)
    gw = ServeGateway("lin", registry_root=reg.root, replicas=1,
                      db_path=str(tmp_path / "metrics.db"),
                      autoscale_interval_s=3600.0).start()
    try:
        x = np.arange(12, dtype=np.float32).reshape(2, 6).tolist()
        out_v1 = _post(f"{gw.url}/predict", {"inputs": x})
        assert "predictions" in out_v1

        # metrics landed in the store
        stats = _get(f"{gw.url}/stats")
        assert stats["endpoint"]["requests"] >= 1
        assert stats["version"] == card_v1["version"]

        # v2 with different weights → rolling update → different outputs
        reg2, card_v2 = _make_card(tmp_path, w_scale=-2.0)
        assert card_v2["version"] != card_v1["version"]
        gw.manager.rolling_restart()
        out_v2 = _post(f"{gw.url}/predict", {"inputs": x})
        assert not np.allclose(out_v2["predictions"],
                               out_v1["predictions"])

        # rollback over HTTP → v1 bytes serve again
        rb = _post(f"{gw.url}/rollback", {})
        assert rb["version"] == card_v1["version"]
        out_rb = _post(f"{gw.url}/predict", {"inputs": x})
        np.testing.assert_allclose(out_rb["predictions"],
                                   out_v1["predictions"], atol=1e-6)
        # a second rollback has nowhere to go → clean 409
        try:
            _post(f"{gw.url}/rollback", {})
            raise AssertionError("expected 409")
        except urllib.error.HTTPError as e:
            assert e.code == 409
    finally:
        gw.stop()


@pytest.mark.slow
def test_serve_entrypoint_module_runs_as_container_would(tmp_path):
    """The EXACT devops entrypoint: `fedml serve --card ... --registry-root
    ... --host ... --port ... --replicas ...` as its own OS process."""
    import subprocess
    import sys

    reg, _ = _make_card(tmp_path, w_scale=1.0)
    proc = subprocess.Popen(
        [sys.executable, "-m", "fedml_tpu.serving.serve_entry",
         "--card", "lin", "--registry-root", reg.root,
         "--host", "127.0.0.1", "--port", "0", "--replicas", "1",
         "--db", str(tmp_path / "m.db")],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE, text=True)
    try:
        url = json.loads(proc.stdout.readline())["serving"]
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if _get(f"{url}/ready")["ready"]:
                    break
            except Exception:  # noqa: BLE001
                time.sleep(0.3)
        x = np.zeros((1, 6), np.float32).tolist()
        out = _post(f"{url}/predict", {"inputs": x})
        assert "predictions" in out
        assert _get(f"{url}/stats")["endpoint"]["requests"] >= 1
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_devops_manifests_reference_tested_entrypoints():
    """Schema/consistency validation of the container assets (docker is
    absent in this image): every yaml parses; every fedml CLI command the
    containers run EXISTS with EXACTLY those options; every `python -m`
    module is importable (VERDICT r3 item 5 'manifests reference only
    tested entrypoints/flags')."""
    import importlib
    import re

    import click
    import yaml as pyyaml

    from fedml_tpu.cli.cli import cli as click_cli

    def assert_cli_command(argv):
        name, args = argv[0], argv[1:]
        cmd = click_cli.commands.get(name)
        assert cmd is not None, f"manifest references unknown command "\
            f"`fedml {name}`"
        known = set()
        for param in cmd.params:
            known.update(o for o in param.opts if o.startswith("--"))
        for a in args:
            if a.startswith("--"):
                assert a in known, (
                    f"`fedml {name}` has no option {a} (manifest drift); "
                    f"known: {sorted(known)}")

    def check_command(argv):
        argv = list(argv)
        if argv[:2] == ["/bin/sh", "-c"]:
            return          # free-form shell; checked via regex below
        if argv[0] == "python" and argv[1] == "-m":
            importlib.import_module(argv[2])
            return
        if argv[0] in ("fedml",):
            return assert_cli_command(argv[1:])
        # bare ENTRYPOINT["fedml"] images: command IS the cli args
        return assert_cli_command(argv)

    roots = [os.path.join(REPO, "devops", "docker-compose.yaml")] + [
        os.path.join(REPO, "devops", "k8s", f)
        for f in sorted(os.listdir(os.path.join(REPO, "devops", "k8s")))]
    shell_cmds = []
    for path in roots:
        with open(path) as f:
            docs = list(pyyaml.safe_load_all(f))
        for doc in docs:
            if not doc:
                continue
            if "services" in doc:      # compose
                for svc in doc["services"].values():
                    if "command" in svc:
                        check_command(svc["command"])
            else:                      # k8s
                tpl = (doc.get("spec", {}).get("template", {})
                       .get("spec", {}))
                for c in tpl.get("containers", []):
                    argv = list(c.get("command", [])) + list(
                        c.get("args", []))
                    if argv[:2] == ["/bin/sh", "-c"]:
                        shell_cmds.extend(argv[2:])
                        continue
                    if argv:
                        check_command(argv)
    # shell-form commands: the `fedml <cmd>` they invoke must exist
    for sh in shell_cmds:
        for m in re.finditer(r"fedml (\w+)", sh):
            assert m.group(1) in click_cli.commands, sh
