"""Transport backends: gRPC rank-to-rank round trip and MQTT+ObjectStore
control/bulk split (mirrors the reference's grpc/mqtt_s3 backends)."""

import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.distributed.communication.message import Message


class _Collector:
    def __init__(self):
        self.got = []
        self.event = threading.Event()

    def receive_message(self, msg_type, msg):
        self.got.append((msg_type, msg))
        self.event.set()


def test_grpc_round_trip(args_factory):
    from fedml_tpu.core.distributed.communication.grpc import GRPCCommManager

    args = args_factory(grpc_base_port=18890)
    m0 = GRPCCommManager(args=args, rank=0, size=2)
    m1 = GRPCCommManager(args=args, rank=1, size=2)
    c0, c1 = _Collector(), _Collector()
    m0.add_observer(c0)
    m1.add_observer(c1)
    t0 = threading.Thread(target=m0.handle_receive_message, daemon=True)
    t1 = threading.Thread(target=m1.handle_receive_message, daemon=True)
    t0.start()
    t1.start()

    msg = Message("TEST_MSG", 0, 1)
    payload = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, payload)
    msg.add_params("round_idx", 3)
    m0.send_message(msg)
    assert c1.event.wait(10), "rank1 never received"
    mtype, received = c1.got[0]
    assert mtype == "TEST_MSG"
    assert received.get("round_idx") == 3
    np.testing.assert_array_equal(
        received.get(Message.MSG_ARG_KEY_MODEL_PARAMS)["w"], payload["w"])

    # reply path
    reply = Message("REPLY", 1, 0)
    m1.send_message(reply)
    assert c0.event.wait(10), "rank0 never received reply"
    m0.stop_receive_message()
    m1.stop_receive_message()


def test_mqtt_objectstore_split(args_factory, tmp_path):
    from fedml_tpu.core.distributed.communication.mqtt_s3 import (
        LocalFSStore,
        MqttS3CommManager,
    )

    args = args_factory(run_id="mq1")
    store = LocalFSStore(str(tmp_path))
    m0 = MqttS3CommManager(args=args, rank=0, size=2, store=store)
    m1 = MqttS3CommManager(args=args, rank=1, size=2, store=store)
    c1 = _Collector()
    m1.add_observer(c1)
    t1 = threading.Thread(target=m1.handle_receive_message, daemon=True)
    t1.start()

    big = {"w": np.random.RandomState(0).randn(64, 64).astype(np.float32)}
    msg = Message("MODEL_UP", 0, 1)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, big)
    msg.add_params("num_samples", 10)
    m0.send_message(msg)
    assert c1.event.wait(10)
    mtype, received = c1.got[0]
    assert mtype == "MODEL_UP"
    # bulk payload went out-of-band: a key was attached
    assert received.get(Message.MSG_ARG_KEY_MODEL_PARAMS_KEY)
    np.testing.assert_array_equal(
        received.get(Message.MSG_ARG_KEY_MODEL_PARAMS)["w"], big["w"])
    m1.stop_receive_message()
    m0.stop_receive_message()


def test_cross_silo_over_grpc(args_factory):
    """Full cross-silo protocol over real gRPC on localhost."""
    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=2,
        client_num_per_round=2, comm_round=2, data_scale=0.2,
        grpc_base_port=19890, run_id="gcs1"))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])

    server = init_server(args, dataset, bundle, backend="GRPC")
    clients = [init_client(args, dataset, bundle, rank, backend="GRPC")
               for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    assert server.aggregator.metrics_history
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])


def test_web3_content_addressed_store(tmp_path):
    from fedml_tpu.core.distributed.communication.distributed_storage import (
        ThetaStore,
        Web3Store,
    )

    store = Web3Store(root=str(tmp_path / "w3"))
    model = {"w": np.arange(6, dtype=np.float32)}
    cid = store.write_model("run1", 0, model)
    assert cid.startswith("bafy")
    np.testing.assert_array_equal(store.read_model(cid)["w"], model["w"])
    # identical content → identical cid (idempotent write)
    assert store.write_model("run1", 0, model) == cid
    # corrupted content fails the integrity check
    with open(store._path(cid), "r+b") as f:
        f.write(b"\x00\x01")
    with pytest.raises(IOError):
        store.read(cid)

    ts = ThetaStore(root=str(tmp_path / "theta"))
    cid2 = ts.write_model("run1", 1, model)
    np.testing.assert_array_equal(ts.read_model(cid2)["w"], model["w"])


def test_aes_encrypted_store(tmp_path):
    from fedml_tpu.core.distributed.communication.mqtt_s3.remote_storage import (
        EncryptedStore,
        LocalFSStore,
    )
    from fedml_tpu.core.distributed.crypto import aes_decrypt, aes_encrypt

    # raw AES round trip + tamper detection
    blob = aes_encrypt(b"secret weights", "pw")
    assert aes_decrypt(blob, "pw") == b"secret weights"
    with pytest.raises(Exception):
        aes_decrypt(blob, "wrong-pw")

    store = EncryptedStore(LocalFSStore(str(tmp_path / "enc")), "pw")
    model = {"w": np.arange(4, dtype=np.float32)}
    key = store.write_model("run1", 0, model)
    np.testing.assert_array_equal(store.read_model(key)["w"], model["w"])
    # at rest it is ciphertext: the inner store must NOT parse as a pytree
    raw = store.inner.read(key)
    from fedml_tpu.utils.serialization import loads_pytree

    with pytest.raises(Exception):
        loads_pytree(raw)


def test_encrypted_cas_store_addresses_ciphertext(tmp_path):
    from fedml_tpu.core.distributed.communication.distributed_storage import (
        Web3Store,
    )
    from fedml_tpu.core.distributed.communication.mqtt_s3.remote_storage import (
        EncryptedStore,
    )

    store = EncryptedStore(Web3Store(root=str(tmp_path)), "pw")
    model = {"w": np.arange(4, dtype=np.float32)}
    cid = store.write_model("run1", 0, model)
    assert cid.startswith("bafy")  # cid of the CIPHERTEXT
    np.testing.assert_array_equal(store.read_model(cid)["w"], model["w"])


def test_mqtt_web3_backend_round_trip(args_factory, tmp_path):
    """MQTT_WEB3: broker control plane + content-addressed bulk payload."""
    from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager

    args = args_factory(run_id="w3rt", mqtt_broker="inproc",
                        object_store_dir=str(tmp_path))
    m0 = FedMLCommManager(args, rank=0, size=2, backend="MQTT_WEB3")
    m1 = FedMLCommManager(args, rank=1, size=2, backend="MQTT_WEB3")
    c1 = _Collector()
    m1.com_manager.add_observer(c1)
    t1 = threading.Thread(target=m1.com_manager.handle_receive_message,
                          daemon=True)
    t1.start()
    time.sleep(0.1)
    msg = Message("SYNC", 0, 1)
    big = {"w": np.arange(4096, dtype=np.float32)}
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, big)
    m0.send_message(msg)
    assert c1.event.wait(10)
    _, received = c1.got[0]
    key = received.get(Message.MSG_ARG_KEY_MODEL_PARAMS_KEY)
    assert key and key.startswith("bafy")
    np.testing.assert_array_equal(
        received.get(Message.MSG_ARG_KEY_MODEL_PARAMS)["w"], big["w"])
    m1.com_manager.stop_receive_message()
    m0.com_manager.stop_receive_message()


def test_mpi_backend_gated():
    from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager

    try:
        import mpi4py  # noqa: F401

        pytest.skip("mpi4py present; gating path not exercised")
    except ImportError:
        pass
    with pytest.raises(NotImplementedError, match="mpi4py"):
        FedMLCommManager(object(), rank=0, size=2, backend="MPI")


def test_mqtt_backend_carries_compressed_updates(args_factory, tmp_path):
    """The compressed_update bulk param must survive the MQTT+store wire
    (offloaded or inline), not fall into the JSON control record."""
    import jax.numpy as jnp

    from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager

    args = args_factory(run_id="mq_comp", object_store_dir=str(tmp_path))
    m0 = FedMLCommManager(args, rank=0, size=2, backend="MQTT_S3")
    m1 = FedMLCommManager(args, rank=1, size=2, backend="MQTT_S3")
    c1 = _Collector()
    m1.com_manager.add_observer(c1)
    t1 = threading.Thread(target=m1.com_manager.handle_receive_message,
                          daemon=True)
    t1.start()
    time.sleep(0.1)
    payload = {"values": jnp.arange(4096, dtype=jnp.float32),
               "indices": jnp.arange(4096, dtype=jnp.int32),
               "size": 100000}
    msg = Message("UPLOAD", 0, 1)
    msg.add_params("compressed_update", payload)
    msg.add_params("num_samples", 7)
    m0.send_message(msg)
    assert c1.event.wait(10)
    _, received = c1.got[0]
    got = received.get("compressed_update")
    assert got is not None and int(np.asarray(got["size"])) == 100000
    np.testing.assert_array_equal(np.asarray(got["values"]),
                                  np.arange(4096, dtype=np.float32))
    assert received.get("num_samples") == 7
    m1.com_manager.stop_receive_message()
    m0.com_manager.stop_receive_message()


def test_chaos_transport_elastic_cross_silo_survives(args_factory):
    """Fault injection: with 15% message drops and duplicates on every
    link, the elastic cross-silo protocol still completes all rounds
    (dropped syncs/uploads are absorbed by the round timeout; duplicate
    uploads dedup via the per-round received set)."""
    import threading

    import fedml_tpu
    from fedml_tpu.core.distributed.communication.chaos import (
        ChaosCommManager,
    )
    from fedml_tpu.core.distributed.communication.inprocess import (
        InProcCommManager,
    )
    from fedml_tpu.core.distributed.fedml_comm_manager import (
        register_comm_backend,
    )
    from fedml_tpu.cross_silo.runner import init_client, init_server

    chaos_instances = []

    def chaos_factory(args, rank=0, size=0):
        mgr = ChaosCommManager(
            InProcCommManager(rank, size, str(args.run_id)),
            drop_p=0.15, dup_p=0.15, delay_p=0.2, max_delay_s=0.05,
            seed=100 + rank, protect_types=("S2C_FINISH",))
        chaos_instances.append(mgr)
        return mgr

    register_comm_backend("CHAOS_INPROC", chaos_factory)

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=3,
        client_num_per_round=3, comm_round=4, data_scale=0.3,
        learning_rate=0.1, run_id="cs_chaos", round_timeout_s=1.5,
        min_clients_per_round=1))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle, backend="CHAOS_INPROC")
    clients = [init_client(args, dataset, bundle, rank,
                           backend="CHAOS_INPROC") for rank in (1, 2, 3)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
    total_chaos = sum(c.stats["dropped"] + c.stats["duplicated"]
                      for c in chaos_instances)
    assert total_chaos > 0, "chaos never fired — test proves nothing"


def test_grpc_stub_cached_and_channels_closed_on_stop(args_factory):
    """send_message must reuse one cached stub per channel (not rebuild it
    every send), and stop_receive_message must close every client channel
    so the sockets are released."""
    import grpc

    from fedml_tpu.core.distributed.communication.grpc import GRPCCommManager

    args = args_factory(grpc_base_port=18930)
    m0 = GRPCCommManager(args=args, rank=0, size=2)
    m1 = GRPCCommManager(args=args, rank=1, size=2)
    c1 = _Collector()
    m1.add_observer(c1)
    t1 = threading.Thread(target=m1.handle_receive_message, daemon=True)
    t1.start()

    m0.send_message(Message("A", 0, 1))
    stub_after_first = m0._stubs[1]
    channel_after_first = m0._channels[1]
    m0.send_message(Message("B", 0, 1))
    assert m0._stubs[1] is stub_after_first, "stub rebuilt on second send"
    assert m0._channels[1] is channel_after_first
    deadline = time.time() + 10
    while time.time() < deadline and len(c1.got) < 2:
        time.sleep(0.05)
    assert len(c1.got) == 2

    m1.stop_receive_message()
    m0.stop_receive_message()
    assert m0._channels == {} and m0._stubs == {}, \
        "client channels not released on stop"
    # the closed channel object rejects further use
    with pytest.raises(Exception):
        channel_after_first.unary_unary("/x/y")(b"", timeout=1)
    del grpc  # imported for documentation of the dependency


def test_grpc_send_retries_transient_failures_with_backoff(args_factory):
    """A send to an unreachable peer is retried grpc_send_retries times
    with backoff before the RpcError surfaces (transient channel errors
    must not instantly kill the handler thread that sends replies)."""
    import grpc

    from fedml_tpu.core.distributed.communication.grpc import GRPCCommManager

    args = args_factory(grpc_base_port=18950, grpc_send_retries=2,
                        grpc_retry_backoff_s=0.05, grpc_send_timeout_s=1.0)
    m0 = GRPCCommManager(args=args, rank=0, size=2)
    start = time.time()
    with pytest.raises(grpc.RpcError):
        m0.send_message(Message("DOOMED", 0, 1))   # nobody at rank 1's port
    # 2 retries × ≥0.025s jittered backoff happened before surfacing
    assert time.time() - start > 0.05
    m0.stop_receive_message()
    assert m0._channels == {} and m0._stubs == {}
