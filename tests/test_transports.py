"""Transport backends: gRPC rank-to-rank round trip and MQTT+ObjectStore
control/bulk split (mirrors the reference's grpc/mqtt_s3 backends)."""

import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.distributed.communication.message import Message


class _Collector:
    def __init__(self):
        self.got = []
        self.event = threading.Event()

    def receive_message(self, msg_type, msg):
        self.got.append((msg_type, msg))
        self.event.set()


def test_grpc_round_trip(args_factory):
    from fedml_tpu.core.distributed.communication.grpc import GRPCCommManager

    args = args_factory(grpc_base_port=18890)
    m0 = GRPCCommManager(args=args, rank=0, size=2)
    m1 = GRPCCommManager(args=args, rank=1, size=2)
    c0, c1 = _Collector(), _Collector()
    m0.add_observer(c0)
    m1.add_observer(c1)
    t0 = threading.Thread(target=m0.handle_receive_message, daemon=True)
    t1 = threading.Thread(target=m1.handle_receive_message, daemon=True)
    t0.start()
    t1.start()

    msg = Message("TEST_MSG", 0, 1)
    payload = {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, payload)
    msg.add_params("round_idx", 3)
    m0.send_message(msg)
    assert c1.event.wait(10), "rank1 never received"
    mtype, received = c1.got[0]
    assert mtype == "TEST_MSG"
    assert received.get("round_idx") == 3
    np.testing.assert_array_equal(
        received.get(Message.MSG_ARG_KEY_MODEL_PARAMS)["w"], payload["w"])

    # reply path
    reply = Message("REPLY", 1, 0)
    m1.send_message(reply)
    assert c0.event.wait(10), "rank0 never received reply"
    m0.stop_receive_message()
    m1.stop_receive_message()


def test_mqtt_objectstore_split(args_factory, tmp_path):
    from fedml_tpu.core.distributed.communication.mqtt_s3 import (
        LocalFSStore,
        MqttS3CommManager,
    )

    args = args_factory(run_id="mq1")
    store = LocalFSStore(str(tmp_path))
    m0 = MqttS3CommManager(args=args, rank=0, size=2, store=store)
    m1 = MqttS3CommManager(args=args, rank=1, size=2, store=store)
    c1 = _Collector()
    m1.add_observer(c1)
    t1 = threading.Thread(target=m1.handle_receive_message, daemon=True)
    t1.start()

    big = {"w": np.random.RandomState(0).randn(64, 64).astype(np.float32)}
    msg = Message("MODEL_UP", 0, 1)
    msg.add_params(Message.MSG_ARG_KEY_MODEL_PARAMS, big)
    msg.add_params("num_samples", 10)
    m0.send_message(msg)
    assert c1.event.wait(10)
    mtype, received = c1.got[0]
    assert mtype == "MODEL_UP"
    # bulk payload went out-of-band: a key was attached
    assert received.get(Message.MSG_ARG_KEY_MODEL_PARAMS_KEY)
    np.testing.assert_array_equal(
        received.get(Message.MSG_ARG_KEY_MODEL_PARAMS)["w"], big["w"])
    m1.stop_receive_message()
    m0.stop_receive_message()


def test_cross_silo_over_grpc(args_factory):
    """Full cross-silo protocol over real gRPC on localhost."""
    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=2,
        client_num_per_round=2, comm_round=2, data_scale=0.2,
        grpc_base_port=19890, run_id="gcs1"))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])

    server = init_server(args, dataset, bundle, backend="GRPC")
    clients = [init_client(args, dataset, bundle, rank, backend="GRPC")
               for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    assert server.aggregator.metrics_history
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
