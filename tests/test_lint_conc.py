"""fedml lint --conc: the concurrency tier (CONC002-CONC006), its
noqa/fingerprint/baseline integration, and the lock-order ratchet."""

from __future__ import annotations

import json
import textwrap

from fedml_tpu.analysis import run_cli, run_lint
from fedml_tpu.analysis.baseline import load_baseline
from fedml_tpu.analysis.conc.lockorder import (collect_edges, load_order,
                                               order_path, write_order)
from fedml_tpu.analysis.findings import fingerprints


def _write(tmp_path, relpath: str, source: str):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def _lint(tmp_path, rules):
    return run_lint(root=tmp_path, rule_ids=rules)


def _ids(result):
    return [f.rule_id for f in result.findings]


# -- CONC002: lockset inference ----------------------------------------------

CONC002_RACY = """\
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                with self._lock:
                    self.count += 1

        def bump(self):
            with self._lock:
                self.count += 1

        def peek(self):
            return self.count
"""


def test_conc002_fires_on_unguarded_access(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", CONC002_RACY)
    res = _lint(tmp_path, ["CONC002"])
    assert _ids(res) == ["CONC002"]
    msg = res.findings[0].message
    assert "Counter._lock" in msg and "peek" in msg


def test_conc002_silent_when_every_access_locked(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", CONC002_RACY.replace(
        "        def peek(self):\n            return self.count",
        "        def peek(self):\n            with self._lock:\n"
        "                return self.count"))
    assert _ids(_lint(tmp_path, ["CONC002"])) == []


def test_conc002_silent_for_init_only_fields(tmp_path):
    # a field only ever STORED in __init__ (config knob) cannot race —
    # concurrent reads of construction-time state are safe
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import threading

        class Svc:
            def __init__(self, rank):
                self._lock = threading.Lock()
                self.rank = rank
                self._t = threading.Thread(target=self._loop)

            def _loop(self):
                with self._lock:
                    a = self.rank
                with self._lock:
                    b = self.rank
                return a + b

            def who(self):
                return self.rank
    """)
    assert _ids(_lint(tmp_path, ["CONC002"])) == []


# -- CONC003: lock-order graph + ratchet -------------------------------------

NESTED = """\
    import threading

    class Pair:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()

        def both(self):
            with self.a:
                with self.b:
                    pass
"""


def test_conc003_new_edge_flagged_until_committed(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", NESTED)
    res = _lint(tmp_path, ["CONC003"])
    assert _ids(res) == ["CONC003"]
    assert "Pair.a' -> 'Pair.b" in res.findings[0].message
    assert any("no committed lock-order DAG" in n for n in res.notes)
    # commit the reviewed edge: the ratchet file silences it
    write_order(tmp_path, collect_edges(tmp_path))
    assert order_path(tmp_path).is_file()
    assert load_order(tmp_path) == {"Pair.a -> Pair.b": {
        "site": "fedml_tpu/mod.py", "via": ["Pair.both"]}}
    assert _ids(_lint(tmp_path, ["CONC003"])) == []


def test_conc003_stale_committed_edge_noted(tmp_path):
    f = _write(tmp_path, "fedml_tpu/mod.py", NESTED)
    write_order(tmp_path, collect_edges(tmp_path))
    # drop the nesting: the committed edge goes stale and the ratchet
    # asks to be tightened (a note, not a finding)
    f.write_text(textwrap.dedent(NESTED).replace(
        "            with self.b:\n                pass", "            pass"))
    res = _lint(tmp_path, ["CONC003"])
    assert _ids(res) == []
    assert any("no longer observed" in n for n in res.notes)


def test_conc003_cycle_is_error_even_when_committed(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", NESTED + """\

        def reverse(self):
            with self.b:
                with self.a:
                    pass
""")
    write_order(tmp_path, collect_edges(tmp_path))
    res = _lint(tmp_path, ["CONC003"])
    assert res.findings, res.notes
    assert all(f.rule_id == "CONC003" and f.severity == "error"
               for f in res.findings)
    assert "deadlock" in res.findings[0].message


# -- CONC004: blocking call under a lock -------------------------------------

def test_conc004_fires_on_sleep_under_lock(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import threading
        import time

        class Writer:
            def __init__(self):
                self._lock = threading.Lock()

            def put(self, x):
                with self._lock:
                    time.sleep(0.1)
    """)
    res = _lint(tmp_path, ["CONC004"])
    assert _ids(res) == ["CONC004"]
    assert "time.sleep()" in res.findings[0].message


def test_conc004_dedicated_serializer_exempt(tmp_path):
    # a lock whose critical sections are ALL the same sqlite calls IS
    # that connection's serializer — not a smell
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import threading

        class DB:
            def __init__(self, conn):
                self._lock = threading.Lock()
                self.conn = conn

            def put(self, x):
                with self._lock:
                    self.conn.execute("insert", (x,))

            def drop(self, x):
                with self._lock:
                    self.conn.execute("delete", (x,))

            def flush(self):
                with self._lock:
                    self.conn.commit()
    """)
    assert _ids(_lint(tmp_path, ["CONC004"])) == []


# -- CONC005: condition-variable misuse --------------------------------------

def test_conc005_wait_outside_while_and_naked_notify(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self.ready = False

            def bad_wait(self):
                with self._cv:
                    self._cv.wait()

            def good_wait(self):
                with self._cv:
                    while not self.ready:
                        self._cv.wait()

            def bad_notify(self):
                self._cv.notify()

            def good_notify(self):
                with self._cv:
                    self._cv.notify_all()
    """)
    res = _lint(tmp_path, ["CONC005"])
    assert _ids(res) == ["CONC005", "CONC005"]
    msgs = "\n".join(f.message for f in res.findings)
    assert "while-predicate" in msgs and "without holding" in msgs


# -- CONC006: timeout-less shutdown wait -------------------------------------

CONC006_HANG = """\
    import threading

    class Svc:
        def __init__(self):
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            pass

        def stop(self):
            self._t.join(){noqa}
"""


def test_conc006_fires_and_timeout_fixes(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", CONC006_HANG.format(noqa=""))
    res = _lint(tmp_path, ["CONC006"])
    assert _ids(res) == ["CONC006"]
    assert "Svc.stop" in res.findings[0].message
    _write(tmp_path, "fedml_tpu/mod.py", CONC006_HANG.format(
        noqa="").replace(".join()", ".join(timeout=5.0)"))
    assert _ids(_lint(tmp_path, ["CONC006"])) == []


def test_conc006_noqa_suppresses(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", CONC006_HANG.format(
        noqa="  # fedml: noqa[CONC006] — joined at exit, wedge impossible"))
    res = _lint(tmp_path, ["CONC006"])
    assert _ids(res) == []
    assert res.suppressed == 1


# -- engine integration -------------------------------------------------------

def test_conc_fingerprints_stable_under_line_drift(tmp_path):
    f = _write(tmp_path, "fedml_tpu/mod.py", CONC002_RACY)
    before = {fp: fi.rule_id for fi, fp in
              fingerprints(_lint(tmp_path, ["CONC002"]).findings)}
    assert before
    f.write_text("# a new header comment\n\n" + f.read_text())
    after = {fp: fi.rule_id for fi, fp in
             fingerprints(_lint(tmp_path, ["CONC002"]).findings)}
    assert before == after


def test_update_baseline_covers_all_six_tiers(tmp_path):
    # --update-baseline must sweep EVERY tier (file + whole-program +
    # perf + mesh + conc + taint): a baseline written from a partial
    # scan would let the missing tier's findings land as "new" on main
    _write(tmp_path, "fedml_tpu/mod.py", CONC006_HANG.format(noqa=""))
    _write(tmp_path, "fedml_tpu/jaxy.py", """\
        import jax

        def train(fn, xs):
            for x in xs:
                f = jax.jit(fn)
                f(x)
    """)
    assert run_cli(root=str(tmp_path), update_baseline=True,
                   echo=lambda *_: None) == 0
    entries = load_baseline(tmp_path / ".fedml-lint-baseline.json")
    rules = {e["rule"] for e in entries.values()}
    assert {"JAX001", "CONC006"} <= rules
    # the ratcheted run is clean, and the conc tier stays covered
    assert run_cli(root=str(tmp_path), conc=True,
                   echo=lambda *_: None) == 0


def test_conc_rule_id_filter_enables_the_pass(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", CONC006_HANG.format(noqa=""))
    lines = []
    code = run_cli(root=str(tmp_path), rule_ids=["CONC006"], fmt="json",
                   echo=lines.append)
    assert code == 1
    report = json.loads("\n".join(lines))
    assert [f["rule"] for f in report["findings"]] == ["CONC006"]


def test_list_rules_prints_six_tier_catalog(tmp_path):
    lines = []
    assert run_cli(root=str(tmp_path), list_rules=True, fmt="json",
                   echo=lines.append) == 0
    catalog = json.loads("\n".join(lines))
    tiers = [t["tier"] for t in catalog["tiers"]]
    assert tiers == ["file", "program", "perf", "mesh", "conc", "taint"]
    assert all(t["doc"] for t in catalog["tiers"])
    ids = {r["id"] for t in catalog["tiers"] for r in t["rules"]}
    assert {"JAX001", "PROTO002", "PERF001", "SHARD002",
            "CONC002", "CONC003", "CONC006", "PRIV001", "PRIV006"} <= ids
