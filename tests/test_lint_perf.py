"""Perf-lint tier: entrypoint registry, PERF001-PERF005 IR rules, noqa,
fingerprint stability, baseline ratchet, and the repo-clean smoke over the
real registered entrypoints (CPU, <60s)."""

from __future__ import annotations

import importlib.util
import itertools
import json
import textwrap
import time

import pytest

from fedml_tpu.analysis import run_cli, run_lint
from fedml_tpu.analysis.baseline import write_baseline
from fedml_tpu.analysis.engine import default_root
from fedml_tpu.analysis.findings import fingerprints
from fedml_tpu.analysis.perf import EntrypointRegistry

_seq = itertools.count()


def _write(tmp_path, relpath: str, source: str):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def _load(tmp_path, relpath: str = "fedml_tpu/hot.py"):
    """Import a fixture module from the tmp lint root so jaxpr source
    frames (and noqa lookups) resolve inside that root."""
    name = f"_perf_fixture_{next(_seq)}"
    spec = importlib.util.spec_from_file_location(name,
                                                  tmp_path / relpath)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lint(tmp_path, reg, rules=None):
    return run_lint(root=tmp_path, rule_ids=rules, perf=True,
                    perf_registry=reg).findings


def _ids(findings):
    return [f.rule_id for f in findings]


#: fixture-module prelude: a private registry the test pulls out as REG
_PRELUDE = """\
    import jax
    import jax.numpy as jnp

    from fedml_tpu.analysis.perf import (
        EntrypointRegistry,
        register_jit_entrypoint,
    )

    REG = EntrypointRegistry()
"""


# -- PERF001: donation audit --------------------------------------------------

def test_perf001_fires_on_dropped_donation(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(state):
            return state.astype(jnp.bfloat16)   # dtype change drops it
        return (jax.jit(step, donate_argnums=(0,)),
                (jax.ShapeDtypeStruct((128, 128), jnp.float32),))

    register_jit_entrypoint("fx/step", _factory, donate_argnums=(0,),
                            registry=REG)
    """)
    found = _lint(tmp_path, _load(tmp_path).REG)
    assert _ids(found) == ["PERF001"]
    assert "donation is silently dropped" in found[0].message
    assert found[0].path == "fedml_tpu/hot.py"


def test_perf001_silent_when_donation_aliases(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(state):
            return state * 2.0                  # same shape/dtype: aliases
        return (jax.jit(step, donate_argnums=(0,)),
                (jax.ShapeDtypeStruct((128, 128), jnp.float32),))

    register_jit_entrypoint("fx/step", _factory, donate_argnums=(0,),
                            registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG) == []


def test_perf001_fires_on_missing_donation(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(params, batch):
            return params + jnp.sum(batch), jnp.sum(batch)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((256, 256), jnp.float32),
                 jax.ShapeDtypeStruct((8, 8), jnp.float32)))

    register_jit_entrypoint("fx/step", _factory, registry=REG)
    """)
    found = _lint(tmp_path, _load(tmp_path).REG)
    assert _ids(found) == ["PERF001"]
    assert "declares no donate_argnums" in found[0].message


def test_perf001_optout_with_empty_donate_argnums(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(params, batch):
            return params + jnp.sum(batch), jnp.sum(batch)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((256, 256), jnp.float32),
                 jax.ShapeDtypeStruct((8, 8), jnp.float32)))

    # inputs are reused by the caller — audited, donation declined
    register_jit_entrypoint("fx/step", _factory, donate_argnums=(),
                            registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG) == []


def test_perf001_ignores_unused_eliminated_args(tmp_path):
    # an arg the program never reads is ELIMINATED from the lowered
    # module; donating it frees the buffer — that is not a dropped
    # donation (regression: positional alias mapping must survive
    # eliminated args sitting between kept ones)
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(unused, state):
            return state * 2.0
        return (jax.jit(step, donate_argnums=(0, 1)),
                (jax.ShapeDtypeStruct((64, 64), jnp.float32),
                 jax.ShapeDtypeStruct((128, 128), jnp.float32)))

    register_jit_entrypoint("fx/step", _factory, donate_argnums=(0, 1),
                            registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG) == []


def test_perf001_dropped_donation_shadowed_by_eliminated_twin(tmp_path):
    # an UNUSED (eliminated) arg with the same shape/dtype as a later
    # donated-but-dropped arg must not shadow the real finding: the
    # dropped set comes from jax's lower-time warning, which fires
    # exactly for mismatches and never for eliminated args
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(unused, state):
            return state.astype(jnp.bfloat16)
        return (jax.jit(step, donate_argnums=(1,)),
                (jax.ShapeDtypeStruct((128, 128), jnp.float32),
                 jax.ShapeDtypeStruct((128, 128), jnp.float32)))

    register_jit_entrypoint("fx/step", _factory, donate_argnums=(1,),
                            registry=REG)
    """)
    found = _lint(tmp_path, _load(tmp_path).REG)
    assert _ids(found) == ["PERF001"]
    assert "float32[128,128]" in found[0].message


def test_perf001_lost_donation_guard_silent_on_eliminated_type_twin(tmp_path):
    # a donated-but-UNUSED arg sharing a tensor type with a kept arg
    # makes the leaf alignment ambiguous; the lost-donation guard must
    # stay silent (the eliminated donation just freed a buffer)
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(unused, state):
            return state * 2.0
        return (jax.jit(step, donate_argnums=(0,)),
                (jax.ShapeDtypeStruct((128, 128), jnp.float32),
                 jax.ShapeDtypeStruct((128, 128), jnp.float32)))

    register_jit_entrypoint("fx/step", _factory, donate_argnums=(0,),
                            registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG) == []


def test_perf001_fires_when_jit_lost_its_donation(tmp_path):
    # the registration declares donate_argnums but the factory's jit has
    # none: no warning fires (nothing was declared to jax) and nothing
    # aliases — the audit must not pass vacuously
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(state):
            return state * 2.0
        return (jax.jit(step),      # <- donation forgotten here
                (jax.ShapeDtypeStruct((128, 128), jnp.float32),))

    register_jit_entrypoint("fx/step", _factory, donate_argnums=(0,),
                            registry=REG)
    """)
    found = _lint(tmp_path, _load(tmp_path).REG)
    assert _ids(found) == ["PERF001"]
    assert "lost its" in found[0].message


# -- PERF002: dtype widening --------------------------------------------------

_WIDEN = """\

    def _factory():
        def step(x):
            y = x.astype(jnp.float32) * 2.0     {noqa}
            return jnp.sum(y)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((64, 128), jnp.bfloat16),))

    register_jit_entrypoint("fx/widen", _factory, registry=REG{extra})
"""


def _widen_module(noqa: str = "", extra: str = "") -> str:
    return _PRELUDE + _WIDEN.format(noqa=noqa, extra=extra)


def test_perf002_fires_on_bf16_widening(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _widen_module())
    found = _lint(tmp_path, _load(tmp_path).REG)
    assert _ids(found) == ["PERF002"]
    assert "widens to float32" in found[0].message
    # the finding lands on the widening SOURCE LINE, so noqa works there
    assert found[0].path == "fedml_tpu/hot.py"
    assert "astype" in (tmp_path / "fedml_tpu/hot.py").read_text() \
        .splitlines()[found[0].line - 1]


def test_perf002_silent_when_chain_stays_bf16(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(x):
            # NB: jnp.sum would widen (f32 accumulator) — and PERF002
            # would be right to say so
            return x * jnp.bfloat16(2.0)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((64, 128), jnp.bfloat16),))

    register_jit_entrypoint("fx/widen", _factory, registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG) == []


def test_perf002_widen_allow_sanctions_path(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _widen_module(
        extra=",\n        meta={'widen_allow': ('fedml_tpu/hot.py',)}"))
    assert _lint(tmp_path, _load(tmp_path).REG) == []


def test_perf002_noqa_suppresses_on_source_line(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py",
           _widen_module(noqa="# fedml: noqa[PERF002] — f32 on purpose"))
    res = run_lint(root=tmp_path, perf=True,
                   perf_registry=_load(tmp_path).REG)
    assert res.findings == [] and res.suppressed == 1


def test_perf002_small_tensors_ignored(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(x):
            return jnp.sum(x.astype(jnp.float32))
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((4, 4), jnp.bfloat16),))

    register_jit_entrypoint("fx/widen", _factory, registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG) == []


# -- PERF003: padding waste ---------------------------------------------------

def _bucket_reg(stats):
    import jax
    import jax.numpy as jnp

    from fedml_tpu.analysis.perf import register_jit_entrypoint

    reg = EntrypointRegistry()
    register_jit_entrypoint(
        "fx/buckets",
        lambda: (jax.jit(lambda x: x + 1),
                 (jax.ShapeDtypeStruct((8,), jnp.float32),)),
        path="fedml_tpu/hot.py",
        meta={"bucket_stats": stats}, registry=reg)
    return reg


def test_perf003_fires_on_wasteful_bucket(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", "x = 1\n")
    reg = _bucket_reg({"buckets": [{"padded": 1088, "real": 790.0},
                                   {"padded": 512, "real": 500.0},
                                   {"padded": 512, "real": 500.0},
                                   {"padded": 512, "real": 500.0},
                                   {"padded": 512, "real": 500.0}]})
    found = _lint(tmp_path, reg, rules=["PERF003"])
    assert _ids(found) == ["PERF003"]
    assert "bucket 0" in found[0].message


def test_perf003_fires_on_round_level_waste(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", "x = 1\n")
    # every bucket just under the per-bucket bar, total over the round bar
    reg = _bucket_reg({"buckets": [{"padded": 620, "real": 500.0}
                                   for _ in range(4)]})
    found = _lint(tmp_path, reg, rules=["PERF003"])
    assert _ids(found) == ["PERF003"]
    assert "round-level" in found[0].message


def test_perf003_silent_on_tight_policy(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", "x = 1\n")
    reg = _bucket_reg({"buckets": [{"padded": 512, "real": 500.0},
                                   {"padded": 416, "real": 410.0}]})
    assert _lint(tmp_path, reg, rules=["PERF003"]) == []


def test_perf003_northstar_policy_of_record_is_tight():
    """The committed histogram + the live bucket_plan under the bench's
    cap must stay under the waste thresholds (the satellite fix), and the
    padded total must hold the <= 4250 acceptance line."""
    import numpy as np

    from fedml_tpu.simulation.parrot.parrot_api import bucket_plan

    d = json.loads((default_root() / "benchmarks" /
                    "northstar_client_sizes.json").read_text())
    plan = bucket_plan(np.asarray(d["sizes"]), d["client_num_per_round"],
                       d["batch_size"], d["hetero_buckets"],
                       d["hetero_bucket_cap"])
    padded = sum(b["padded"] for b in plan)
    real = sum(b["real"] for b in plan)
    assert padded <= 4250, padded
    assert padded / real - 1.0 <= 0.08, (padded, real)
    # and the UNCAPPED policy is what PERF003 exists to catch
    plan0 = bucket_plan(np.asarray(d["sizes"]), d["client_num_per_round"],
                        d["batch_size"], d["hetero_buckets"], 0.0)
    padded0 = sum(b["padded"] for b in plan0)
    assert padded0 >= 5600, padded0
    assert any(b["padded"] / b["real"] - 1.0 > 0.25 for b in plan0)


# -- PERF004: layout-changing transpose in scan bodies ------------------------

def test_perf004_fires_on_explicit_transpose_in_scan(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def body(c, x):
            z = jnp.transpose(x, (1, 0))
            return c + jnp.sum(z), z
        def step(xs):
            return jax.lax.scan(body, jnp.float32(0), xs)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((4, 128, 64), jnp.float32),))

    register_jit_entrypoint("fx/scan", _factory, registry=REG)
    """)
    found = _lint(tmp_path, _load(tmp_path).REG)
    assert _ids(found) == ["PERF004"]
    assert "inside a scan body" in found[0].message


def test_perf004_silent_when_hoisted_out_of_scan(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def body(c, z):
            return c + jnp.sum(z), z
        def step(xs):
            zs = jnp.transpose(xs, (0, 2, 1))   # once, outside the loop
            return jax.lax.scan(body, jnp.float32(0), zs)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((4, 128, 64), jnp.float32),))

    register_jit_entrypoint("fx/scan", _factory, registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG) == []


def test_perf004_autodiff_transposes_filtered(tmp_path):
    # grad-of-matmul inserts transposes attributed to the forward line;
    # the source-text check keeps them out
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def loss(w, x):
            return jnp.sum(jnp.tanh(x @ w))
        def body(w, x):
            return w - 0.1 * jax.grad(loss)(w, x), jnp.float32(0)
        def step(w, xs):
            return jax.lax.scan(body, w, xs)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((128, 128), jnp.float32),
                 jax.ShapeDtypeStruct((4, 64, 128), jnp.float32)))

    register_jit_entrypoint("fx/scan", _factory, registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG, rules=["PERF004"]) == []


# -- PERF005: host callbacks --------------------------------------------------

def test_perf005_fires_on_debug_print_in_jit(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def body(c, x):
            jax.debug.print("c={c}", c=c)
            return c + jnp.sum(x), c
        def step(xs):
            return jax.lax.scan(body, jnp.float32(0), xs)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((4, 8, 8), jnp.float32),))

    register_jit_entrypoint("fx/cb", _factory, registry=REG)
    """)
    found = _lint(tmp_path, _load(tmp_path).REG)
    assert _ids(found) == ["PERF005"]
    assert found[0].severity == "error"
    assert "scan body" in found[0].message


def test_perf005_silent_without_callbacks(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        def step(xs):
            return jnp.sum(xs)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((4, 8, 8), jnp.float32),))

    register_jit_entrypoint("fx/cb", _factory, registry=REG)
    """)
    assert _lint(tmp_path, _load(tmp_path).REG) == []


# -- PERF000: broken registrations fail loudly --------------------------------

def test_perf000_trace_failure_is_an_error_finding(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    def _factory():
        raise RuntimeError("model import exploded")

    register_jit_entrypoint("fx/broken", _factory, registry=REG)
    """)
    res = run_lint(root=tmp_path, perf=True,
                   perf_registry=_load(tmp_path).REG)
    assert _ids(res.findings) == ["PERF000"]
    assert res.findings[0].severity == "error"
    assert "model import exploded" in res.findings[0].message
    assert any("failed to trace" in n for n in res.notes)


# -- engine integration -------------------------------------------------------

def test_perf_rules_imply_perf_pass(tmp_path):
    """--rules PERF00x auto-enables the perf pass (like whole-program),
    and the per-file tiers do NOT run a second time."""
    _write(tmp_path, "fedml_tpu/hot.py", _widen_module())
    mod = _load(tmp_path)
    res = run_lint(root=tmp_path, rule_ids=["PERF002"],
                   perf_registry=mod.REG)      # no perf=True
    assert _ids(res.findings) == ["PERF002"]
    # a JAX001-triggering file proves AST rules were filtered out
    _write(tmp_path, "fedml_tpu/loopy.py", """\
        import jax

        def train(fn, xs):
            for x in xs:
                jax.jit(fn)(x)
    """)
    res = run_lint(root=tmp_path, rule_ids=["PERF002"],
                   perf_registry=mod.REG)
    assert _ids(res.findings) == ["PERF002"]


def test_unknown_perf_rule_rejected(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", "x = 1\n")
    with pytest.raises(ValueError, match="unknown rule id"):
        run_lint(root=tmp_path, rule_ids=["PERF999"])


# -- fingerprints + baseline ratchet ------------------------------------------

def test_perf_fingerprints_stable_under_unrelated_churn(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _widen_module())
    f1 = _lint(tmp_path, _load(tmp_path).REG)
    fp1 = [fp for _, fp in fingerprints(f1)]
    # unrelated edits above the finding move its line; fingerprint holds
    _write(tmp_path, "fedml_tpu/hot.py",
           "    # a new header comment\n    X_UNRELATED = 42\n"
           + _widen_module())
    f2 = _lint(tmp_path, _load(tmp_path).REG)
    fp2 = [fp for _, fp in fingerprints(f2)]
    assert fp1 == fp2
    assert f1[0].line != f2[0].line


def test_perf_baseline_ratchet_roundtrip(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _widen_module())
    mod = _load(tmp_path)
    findings = _lint(tmp_path, mod.REG)
    assert findings
    baseline = tmp_path / ".fedml-lint-baseline.json"
    write_baseline(baseline, findings)
    # baselined → clean exit
    assert run_cli(root=str(tmp_path), perf=True, perf_registry=mod.REG,
                   baseline=str(baseline), echo=lambda *a, **k: None) == 0
    # a NEW finding (second widening entrypoint) → exit 1
    _write(tmp_path, "fedml_tpu/hot2.py", _widen_module().replace(
        "fx/widen", "fx/widen2"))
    mod2 = _load(tmp_path, "fedml_tpu/hot2.py")
    reg = EntrypointRegistry()
    for e in mod.REG.entries() + mod2.REG.entries():
        reg.register(e)
    assert run_cli(root=str(tmp_path), perf=True, perf_registry=reg,
                   baseline=str(baseline), echo=lambda *a, **k: None) == 1


# -- repo-clean smoke over the real registry ----------------------------------

def test_repo_perf_lint_clean_and_fast():
    """The real registered entrypoints (parrot round + fused scan, robust
    agg, wire codecs, LLM train step) trace on CPU inside the smoke
    budget and raise no new findings over the committed baseline."""
    t0 = time.monotonic()
    root = default_root()
    res = run_lint(root=root, rule_ids=[
        "PERF000", "PERF001", "PERF002", "PERF003", "PERF004", "PERF005"])
    took = time.monotonic() - t0
    from fedml_tpu.analysis.baseline import (
        DEFAULT_BASELINE_NAME,
        load_baseline,
        partition,
    )

    baseline_p = root / DEFAULT_BASELINE_NAME
    known = load_baseline(baseline_p) if baseline_p.is_file() else {}
    new, _old = partition(res.findings, known)
    assert new == [], [f.render() for f, _ in new]
    assert not res.notes, res.notes
    assert took < 60.0, f"perf pass took {took:.1f}s (budget 60s)"
    # the registry actually covered the hot programs
    from fedml_tpu.analysis.perf import load_default_entrypoints

    names = set(load_default_entrypoints().names())
    for expected in ("parrot/fused_round_scan", "parrot/bucketed_round_step",
                     "agg/robust_trimmed_mean", "wire/decode_int8_delta",
                     "llm/train_epoch"):
        assert expected in names, sorted(names)
