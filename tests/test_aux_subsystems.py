"""Aux subsystems: RDP accountant, compression, flow engine, checkpointing,
federated analytics, DP end-to-end."""

import json
import threading
import time

import numpy as np
import pytest


def test_rdp_accountant_monotone_and_sane():
    from fedml_tpu.core.dp.accountant.rdp_accountant import RDPAccountant

    acc = RDPAccountant()
    acc.step(noise_multiplier=1.1, sample_rate=0.01, num_steps=100)
    e1 = acc.get_epsilon(1e-5)
    acc.step(noise_multiplier=1.1, sample_rate=0.01, num_steps=900)
    e2 = acc.get_epsilon(1e-5)
    assert 0 < e1 < e2 < 100
    acc2 = RDPAccountant()
    acc2.step(1.1, 0.01, 10000)
    assert 0.5 < acc2.get_epsilon(1e-5) < 10.0
    # closed-form check (q=1): eps = min_a [a/(2σ²) + log(1/δ)/(a−1)]
    # σ=10, 1 step, δ=1e-5 → optimum a≈1+√(2σ²·log(1e5)) ≈ 49, eps ≈ 0.48
    acc3 = RDPAccountant()
    acc3.step(10.0, 1.0, 1)
    assert 0.4 < acc3.get_epsilon(1e-5) < 0.6


def test_topk_and_ef_compression():
    import jax.numpy as jnp

    from fedml_tpu.utils.compression import EFTopKCompressor, TopKCompressor

    tree = {"a": jnp.asarray(np.random.RandomState(0).randn(100),
                             jnp.float32),
            "b": jnp.asarray(np.random.RandomState(1).randn(10, 10),
                             jnp.float32)}
    c = TopKCompressor(0.1)
    payload, spec = c.compress(tree)
    assert len(payload["values"]) == 20
    back = c.decompress(payload, spec)
    assert back["a"].shape == (100,) and back["b"].shape == (10, 10)
    # EF: residual accumulates what wasn't sent
    ef = EFTopKCompressor(0.1)
    p1, spec = ef.compress(tree)
    assert ef.residual is not None
    dense = np.concatenate([np.ravel(np.asarray(tree["a"]))
                            , np.ravel(np.asarray(tree["b"]))])
    sent = np.zeros_like(dense)
    sent[np.asarray(p1["indices"])] = np.asarray(p1["values"])
    np.testing.assert_allclose(np.asarray(ef.residual), dense - sent,
                               atol=1e-6)


def test_flow_engine_three_nodes(args_factory):
    from fedml_tpu.core.alg_frame.params import Params
    from fedml_tpu.core.distributed.flow.fedml_flow import (
        FedMLAlgorithmFlow,
        FedMLExecutor,
    )

    log = []

    class Server(FedMLExecutor):
        def init_global(self):
            log.append(("server_init", self.id))
            return Params(value=1)

        def aggregate(self):
            v = self.get_params().get("value")
            log.append(("server_agg", v))
            return Params(value=v + 1)

    class Client(FedMLExecutor):
        def local_train(self):
            v = self.get_params().get("value")
            log.append(("client_train", self.id, v))
            return Params(value=v * 10)

    args_s = args_factory(rank=0, comm_round=2, flow_world_size=2,
                          run_id="flow1")
    args_c = args_factory(rank=1, comm_round=2, flow_world_size=2,
                          run_id="flow1")
    server_exec = Server(id=0)
    client_exec = Client(id=1)

    def build(args, my_exec):
        flow = FedMLAlgorithmFlow(args, my_exec)
        flow.add_flow("init_global", server_exec)
        flow.add_flow("local_train", client_exec)
        flow.add_flow("aggregate", server_exec)
        flow.build()
        return flow

    f_server = build(args_s, server_exec)
    f_client = build(args_c, client_exec)
    t = threading.Thread(target=f_client.run_flow, daemon=True)
    t.start()
    f_server.run_flow()
    t.join(timeout=10)
    assert ("server_init", 0) in log
    assert any(e[0] == "client_train" for e in log)
    assert any(e[0] == "server_agg" for e in log)


def test_checkpoint_resume_round_trip(tmp_path):
    import jax.numpy as jnp

    from fedml_tpu.utils.checkpoint import RoundCheckpointer

    ck = RoundCheckpointer(str(tmp_path / "ck"))
    state = {"round_idx": 4,
             "global_vars": {"params": {"w": jnp.ones((3, 2))}},
             "server_state": {}}
    ck.save(4, state)
    assert ck.latest_round() == 4
    back = ck.restore()
    np.testing.assert_array_equal(
        np.asarray(back["global_vars"]["params"]["w"]), np.ones((3, 2)))


def test_parrot_resumes_from_checkpoint(args_factory, tmp_path):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    def run(rounds):
        args = fedml_tpu.init(args_factory(
            backend="parrot", comm_round=rounds, data_scale=0.2,
            checkpoint_dir=str(tmp_path / "ck2"), checkpoint_frequency=1))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        runner = FedMLRunner(args, device, dataset, bundle)
        out = runner.run()
        return out, runner.runner

    _, api1 = run(2)          # rounds 0..1 + checkpoints
    out2, api2 = run(4)       # must resume at round 2
    assert out2["round"] == 3
    assert len(api2.metrics_history) <= 2  # only rounds 2..3 ran


@pytest.mark.parametrize("task,expect", [
    ("avg", 2.0),
    ("intersection", {2}),
    ("union", {1, 2, 3}),
    ("cardinality", 3),
    ("k_percentile", None),
    ("frequency", None),
])
def test_fa_tasks(args_factory, task, expect):
    from fedml_tpu.fa.fa_frame import FASimulator

    data = {0: [1, 2], 1: [2, 3], 2: [2]}
    sim = FASimulator(args_factory(fa_task=task), data)
    result = sim.run()
    if expect is not None:
        assert result == expect


def test_fa_heavy_hitter(args_factory):
    from fedml_tpu.fa.fa_frame import FASimulator

    words = ["the", "the", "then", "cat"]
    data = {i: words for i in range(3)}
    sim = FASimulator(args_factory(fa_task="heavy_hitter_triehh",
                                   comm_round=3, triehh_theta=3), data)
    result = sim.run()
    assert "the" in result


def test_local_dp_changes_upload(args_factory):
    """enable_dp local: client upload must differ from noiseless params."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    def run(dp):
        kw = dict(comm_round=1, data_scale=0.2, run_id=f"dp{dp}")
        if dp:
            kw.update(enable_dp=True, dp_solution_type="local", sigma=0.05)
        args = fedml_tpu.init(args_factory(**kw))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        return FedMLRunner(args, device, dataset, bundle).run()

    base = run(False)
    noised = run(True)
    assert np.isfinite(noised["test_loss"])
    assert abs(base["test_loss"] - noised["test_loss"]) > 1e-9


def test_perf_stats_daemon(tmp_path, args_factory):
    from fedml_tpu.core import mlops
    from fedml_tpu.core.mlops.perf_stats import (
        MLOpsJobPerfStats,
        system_snapshot,
    )

    snap = system_snapshot()
    assert snap["cpu_percent"] >= 0
    assert snap["mem_total_gb"] > 0
    assert isinstance(snap.get("devices"), list) and snap["devices"]

    mlops.init(args_factory(enable_tracking=True, run_id="perfrun",
                            log_file_dir=str(tmp_path)))
    d = MLOpsJobPerfStats(run_id="perfrun", interval_s=0.05).start()
    time.sleep(0.3)
    d.stop()
    assert d.samples, "no samples collected"
    assert all(s["role"] == "job" for s in d.samples)
    with open(tmp_path / "sysperf.jsonl") as f:
        records = [json.loads(line) for line in f]
    assert records and records[0]["job_run_id"] == "perfrun"


def test_log_upload_daemon_resumes_cursor(tmp_path):
    from fedml_tpu.core.mlops.log_daemon import MLOpsRuntimeLogDaemon

    src = tmp_path / "run.log"
    src.write_text("".join(f"line {i}\n" for i in range(10)))
    d = MLOpsRuntimeLogDaemon("r1", str(src), interval_s=0.05,
                              chunk_lines=4)
    assert d.ship_once() == 10
    uploaded = tmp_path / "uploaded" / "r1.log"
    assert uploaded.read_text().count("\n") == 10

    # append more; a NEW daemon instance resumes from the persisted cursor
    with open(src, "a") as f:
        f.write("line 10\nline 11\n")
    d2 = MLOpsRuntimeLogDaemon("r1", str(src), interval_s=0.05)
    assert d2.ship_once() == 2
    assert uploaded.read_text().count("\n") == 12
    # partial trailing line is held back until complete
    with open(src, "a") as f:
        f.write("partial")
    assert d2.ship_once() == 0
    with open(src, "a") as f:
        f.write(" done\n")
    assert d2.ship_once() == 1


def test_fa_cross_silo_runtime(args_factory):
    """FA over the message plane matches the SP simulator's results."""
    from fedml_tpu.fa.cross_silo import run_cross_silo_fa
    from fedml_tpu.fa.fa_frame import FASimulator

    data = {0: [1, 2], 1: [2, 3], 2: [2]}
    for task in ("intersection", "union", "cardinality", "avg"):
        args = args_factory(fa_task=task, run_id=f"fa_{task}")
        got = run_cross_silo_fa(args, data)
        want = FASimulator(args_factory(fa_task=task), data).run()
        assert got == want, (task, got, want)


def test_fa_cross_silo_triehh(args_factory):
    from fedml_tpu.fa.cross_silo import run_cross_silo_fa

    words = ["the", "the", "then", "cat"]
    data = {i: words for i in range(3)}
    result = run_cross_silo_fa(
        args_factory(fa_task="heavy_hitter_triehh", comm_round=3,
                     triehh_theta=3, run_id="fa_hh"), data)
    assert "the" in result


def test_log_upload_daemon_invalid_utf8_cursor(tmp_path):
    """Byte-exact cursor even when the partial tail has invalid UTF-8."""
    from fedml_tpu.core.mlops.log_daemon import MLOpsRuntimeLogDaemon

    src = tmp_path / "bin.log"
    with open(src, "wb") as f:
        f.write(b"good line\n")
        f.write(b"partial \xff\xfe")  # invalid utf-8, no newline yet
    d = MLOpsRuntimeLogDaemon("rb1", str(src))
    assert d.ship_once() == 1
    with open(src, "ab") as f:
        f.write(b" rest\n")
    assert d.ship_once() == 1  # exactly the completed line, no re-reads
    uploaded = (tmp_path / "uploaded" / "rb1.log").read_text()
    assert uploaded.startswith("good line\n")
    assert uploaded.count("\n") == 2
    assert "partial" in uploaded and "rest" in uploaded


def test_maybe_init_distributed_noop_without_coordinator(monkeypatch):
    """No coordinator configured → init() must not touch jax.distributed."""
    import fedml_tpu

    for var in ("FEDML_COORDINATOR_ADDRESS", "MASTER_ADDR", "WORLD_SIZE",
                "RANK"):
        monkeypatch.delenv(var, raising=False)
    called = {}
    import jax

    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.setdefault("kw", kw))
    fedml_tpu._maybe_init_distributed(fedml_tpu.Config())
    assert not called


def test_maybe_init_distributed_reads_torchrun_env(monkeypatch):
    """MASTER_ADDR/WORLD_SIZE/RANK (the reference's torchrun contract,
    `__init__.py:339-389`) map onto jax.distributed.initialize."""
    import fedml_tpu
    import jax

    monkeypatch.setattr(fedml_tpu, "_distributed_initialized", False)
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
    monkeypatch.setenv("MASTER_PORT", "4321")
    monkeypatch.setenv("WORLD_SIZE", "4")
    monkeypatch.setenv("RANK", "2")
    called = {}
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: called.update(kw))
    # process_index/count are read for the log line after "joining"
    monkeypatch.setattr(jax, "process_index", lambda: 2)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    fedml_tpu._maybe_init_distributed(fedml_tpu.Config())
    assert called == {"coordinator_address": "10.0.0.1:4321",
                      "num_processes": 4, "process_id": 2}
    monkeypatch.setattr(fedml_tpu, "_distributed_initialized", False)
