"""Performance flight recorder: phase attribution with the residual
host_gap bucket, bounded JSONL flight log, report/diff rendering,
cost-analysis FLOPs + measured-MFU helpers, the device-phase spans it
shares with `fedml trace summarize`, and the instrumented Parrot fused
path's end-to-end coverage + overhead budget."""

import json
import os
import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.core.mlops import flight_recorder as fr
from fedml_tpu.core.mlops import metrics as metrics_mod
from fedml_tpu.core.mlops import tracing
from fedml_tpu.runner import FedMLRunner

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


@pytest.fixture
def armed(tmp_path):
    fr.enable(True, log_dir=str(tmp_path), run_id="fr-test")
    yield str(tmp_path)
    fr.reset()


# -- phase / record primitives -----------------------------------------------

def test_round_decomposition_covers_wall(armed):
    with fr.record_round("unit_round", rounds=2, program="test/prog") as rec:
        with rec.phase("device_compute"):
            time.sleep(0.02)
        with rec.phase("h2d"):
            time.sleep(0.005)
        time.sleep(0.01)               # unattributed host work
    records = fr.load_flight_log(armed)
    assert len(records) == 1
    r = records[0]
    assert r["kind"] == "unit_round"
    assert r["rounds"] == 2
    assert r["program"] == "test/prog"
    phases = r["phases_s"]
    assert phases["device_compute"] >= 0.02
    assert phases["h2d"] >= 0.005
    # host_gap is the residual: decomposition sums to the wall by
    # construction, and here it must carry the un-phased sleep
    assert phases["host_gap"] >= 0.008
    assert sum(phases.values()) == pytest.approx(r["wall_s"], rel=1e-3)


def test_nested_phase_attributes_to_innermost_record(armed):
    with fr.record_round("outer") as outer:
        with fr.record_round("inner") as inner:
            with fr.phase("device_compute"):   # module-level helper
                time.sleep(0.01)
        assert inner.phase_seconds("device_compute") >= 0.01
        assert outer.phase_seconds("device_compute") == 0.0


def test_standalone_phase_has_no_residual(armed):
    with fr.phase("compile", program="test/prog"):
        time.sleep(0.01)
    records = fr.load_flight_log(armed)
    assert len(records) == 1
    r = records[0]
    assert r["kind"] == "phase"
    assert r["phases_s"]["compile"] >= 0.01
    # a standalone phase IS its record's wall — no residual bucket
    assert "host_gap" not in r["phases_s"]


def test_flight_log_is_bounded(tmp_path):
    fr.enable(True, log_dir=str(tmp_path), run_id="b", max_records=3)
    try:
        for _ in range(5):
            with fr.record_round("r"):
                pass
        with open(os.path.join(str(tmp_path), "flight.jsonl")) as f:
            assert len(f.readlines()) == 3
    finally:
        fr.reset()


def test_disarmed_is_noop(tmp_path):
    fr.reset()
    with fr.record_round("r") as rec:
        with rec.phase("device_compute"):
            pass
        rec.note(mfu=0.5)
        assert rec.phase_seconds("device_compute") == 0.0
    with fr.phase("compile"):
        pass
    fr.observe_phase("device_compute", 0.1)
    fr.note_transfer("h2d", 100)
    assert not os.path.exists(os.path.join(str(tmp_path), "flight.jsonl"))


def test_phase_histogram_and_transfer_counter(armed):
    with fr.record_round("r", rounds=4) as rec:
        with rec.phase("device_compute"):
            time.sleep(0.004)
    fr.note_transfer("h2d", 1024)
    fr.note_transfer("h2d", 1024)
    text = metrics_mod.render_prometheus()
    assert "fedml_round_phase_seconds" in text
    assert 'phase="device_compute"' in text
    assert 'phase="host_gap"' in text
    assert ('fedml_transfer_bytes_total{direction="h2d"} 2048' in text)


def test_tree_nbytes():
    tree = {"a": np.zeros((4, 4), np.float32), "b": [np.zeros(8, np.int8)]}
    assert fr.tree_nbytes(tree) == 4 * 4 * 4 + 8
    assert fr.tree_nbytes({"x": 3}) == 0   # scalar leaves have no nbytes


# -- cost analysis / measured MFU ---------------------------------------------

def test_program_cost_memory_and_mfu(armed):
    import jax
    import jax.numpy as jnp

    n = 64
    compiled = jax.jit(lambda a, b: a @ b).trace(
        jnp.zeros((n, n), jnp.float32),
        jnp.zeros((n, n), jnp.float32)).lower().compile()
    cost = fr.program_cost(compiled)
    assert cost is not None
    # XLA counts 2*n^3 (+/- fusion noise) for a matmul on CPU
    assert cost["flops"] == pytest.approx(2 * n ** 3, rel=0.2)
    mem = fr.program_memory(compiled)
    assert mem is not None and mem["argument"] >= 2 * n * n * 4

    info = fr.note_program("test/matmul", compiled, chunk_rounds=1)
    assert info is not None and info["flops"] == cost["flops"]
    assert fr.programs()["test/matmul"]["hbm_bytes"] == mem
    # a kind="program" flight record lands in the log
    kinds = [r.get("kind") for r in fr.load_flight_log(armed)]
    assert "program" in kinds

    mfu = fr.measured_mfu("test/matmul", flops=cost["flops"],
                          device_seconds=0.001)
    assert 0.0 < mfu == pytest.approx(
        cost["flops"] / 0.001 / fr.chip_peak_flops())
    assert fr.measured_mfu("test/matmul", 1e9, 0.0) == 0.0
    text = metrics_mod.render_prometheus()
    assert 'fedml_measured_mfu{program="test/matmul"}' in text


# -- summarize / report / diff ------------------------------------------------

def _fake_log(dev=0.8, gap=0.2, rounds=10):
    return [{"kind": "fused", "rounds": rounds, "wall_s": dev + gap,
             "phases_s": {"device_compute": dev, "host_gap": gap},
             "overhead_s": 0.001, "program": "p",
             "meta": {"mfu": 0.41}},
            {"kind": "program", "program": "p", "flops": 1e12,
             "hbm_bytes": {"temp": 1 << 20}}]


def test_summarize_schema_and_report():
    s = fr.summarize(_fake_log())
    assert s["records"] == 1 and s["rounds"] == 10
    assert s["coverage"] == pytest.approx(1.0)
    assert s["measured_share"] == pytest.approx(0.8)
    assert s["overhead_frac"] == pytest.approx(0.001)
    assert s["kinds"]["fused"]["phases_s"]["device_compute"] == 0.8
    assert s["programs"]["p"]["last_mfu"] == 0.41
    assert s["programs"]["p"]["flops"] == 1e12
    text = fr.report(_fake_log())
    assert "device_compute" in text and "host_gap" in text
    assert "coverage: 100.0%" in text
    assert "mfu=0.4100" in text
    assert fr.report([]) == "(no flight records)"


def test_diff_renders_per_round_delta():
    a = _fake_log(dev=1.0, gap=0.2, rounds=10)     # 0.10 s/round device
    b = _fake_log(dev=0.5, gap=0.2, rounds=10)     # 0.05 s/round device
    text = fr.diff(a, b, label_a="before", label_b="after")
    assert "before" in text and "after" in text
    assert "device_compute" in text
    assert "0.50" in text      # device ratio after/before
    assert fr.diff([], b) == "(one of the flight logs is empty)"


# -- device-phase spans in the trace timeline ---------------------------------

def test_trace_summarize_renders_flight_spans():
    """Regression on a recorded fixture: `fedml trace summarize` must show
    the flight parent with its device phases nested under it."""
    records = tracing.load_spans(os.path.join(FIXTURES, "flight_trace"))
    assert records, "fixture flight_trace/spans.jsonl missing"
    text = tracing.summarize(records)
    lines = text.splitlines()
    parent = next(i for i, ln in enumerate(lines)
                  if "flight.parrot_fused" in ln)
    child_dc = next(i for i, ln in enumerate(lines)
                    if "phase.device_compute" in ln)
    child_h2d = next(i for i, ln in enumerate(lines)
                     if "phase.h2d" in ln)
    assert child_dc > parent and child_h2d > parent
    # children render INDENTED under the flight parent
    parent_indent = len(lines[parent]) - len(lines[parent].lstrip())
    for i in (child_dc, child_h2d):
        assert (len(lines[i]) - len(lines[i].lstrip())) > parent_indent
    assert "rounds=64" in lines[parent]


def test_live_run_emits_flight_spans(args_factory, tmp_path):
    """The recorder's spans reach the run's spans.jsonl alongside the
    host-side ones, so one timeline shows both."""
    args = fedml_tpu.init(args_factory(
        backend="parrot", comm_round=2, fused_rounds=True,
        frequency_of_the_test=2, flight_recorder=True,
        enable_tracking=True, log_file_dir=str(tmp_path)))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    FedMLRunner(args, device, dataset, bundle).run()
    names = {r.get("name") for r in tracing.load_spans(str(tmp_path))}
    assert "flight.parrot_fused" in names
    assert "phase.device_compute" in names


# -- end-to-end: instrumented parrot path --------------------------------------

def test_parrot_fused_coverage_and_overhead(args_factory, tmp_path):
    """Acceptance: the flight log decomposes >=95% of round wall time into
    named phases, the recorder's self-measured bookkeeping stays under the
    2% CI budget, and the compiled fused scan's cost analysis + MFU are
    captured."""
    args = fedml_tpu.init(args_factory(
        backend="parrot", comm_round=4, fused_rounds=True,
        frequency_of_the_test=4, flight_recorder=True,
        log_file_dir=str(tmp_path)))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])

    s = fr.summarize(fr.load_flight_log(str(tmp_path)))
    assert s["records"] > 0
    assert s["coverage"] >= 0.95
    assert s["overhead_frac"] < 0.02
    assert "compile" in s["phases_s"]
    assert s["kinds"]["parrot_fused"]["phases_s"]["device_compute"] > 0
    prog = s["programs"].get("parrot/fused_round_scan")
    assert prog is not None and prog.get("flops", 0) > 0
    assert prog.get("last_mfu", 0) > 0


def test_unfused_parrot_round_records(args_factory, tmp_path):
    args = fedml_tpu.init(args_factory(
        backend="parrot", comm_round=2, frequency_of_the_test=2,
        flight_recorder=True, log_file_dir=str(tmp_path)))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    FedMLRunner(args, device, dataset, bundle).run()
    s = fr.summarize(fr.load_flight_log(str(tmp_path)))
    assert s["kinds"]["parrot_round"]["records"] == 2
    assert s["coverage"] >= 0.95


def test_perf_cli_report_and_diff(args_factory, tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    args = fedml_tpu.init(args_factory(
        backend="parrot", comm_round=2, fused_rounds=True,
        frequency_of_the_test=2, flight_recorder=True,
        log_file_dir=str(tmp_path)))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    FedMLRunner(args, device, dataset, bundle).run()

    runner = CliRunner()
    r = runner.invoke(cli, ["perf", "report", str(tmp_path)])
    assert r.exit_code == 0, r.output
    assert "device_compute" in r.output and "coverage" in r.output
    r = runner.invoke(cli, ["perf", "report", str(tmp_path), "--json"])
    assert r.exit_code == 0
    s = json.loads(r.output)
    assert s["coverage"] >= 0.95
    r = runner.invoke(cli, ["perf", "diff", str(tmp_path), str(tmp_path)])
    assert r.exit_code == 0 and "ratio" in r.output
    r = runner.invoke(cli, ["perf", "report", str(tmp_path / "missing")])
    assert r.exit_code != 0
