"""Native C++ component: build, CLI main, codec interop with the Python
LightSecAgg implementation, and the native trainer in a real FL round."""

import os
import subprocess

import numpy as np
import pytest

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "fedml_tpu",
                          "native")


@pytest.fixture(scope="module")
def native_lib():
    from fedml_tpu.native import bindings

    bindings.build_native()
    return bindings


def test_cli_main_passes(native_lib):
    main = os.path.join(NATIVE_DIR, "build", "main_train")
    out = subprocess.run([main], capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "secagg round-trip OK" in out.stdout


def test_cpp_lcc_matches_python(native_lib):
    """The C++ codec must speak the exact protocol of core/mpc/secagg.py."""
    from fedml_tpu.core.mpc.secagg import (
        FIELD_PRIME,
        LCC_decoding_with_points,
        LCC_encoding_with_points,
    )

    rng = np.random.RandomState(0)
    X = rng.randint(0, int(FIELD_PRIME), size=(3, 11)).astype(np.int64)
    beta, alpha = [1, 2, 3], [4, 5, 6, 7]
    enc_py = LCC_encoding_with_points(X, beta, alpha)
    enc_cpp = native_lib.lcc_encode(X, beta, alpha)
    np.testing.assert_array_equal(enc_py, enc_cpp)
    dec_py = LCC_decoding_with_points(enc_py[:3], alpha[:3], beta)
    dec_cpp = native_lib.lcc_decode(enc_cpp[:3], alpha[:3], beta)
    np.testing.assert_array_equal(dec_py, dec_cpp)
    np.testing.assert_array_equal(dec_cpp, X)


def test_native_trainer_learns(native_lib):
    from fedml_tpu.data.datasets import synthetic_classification

    xt, yt, xe, ye = synthetic_classification(n_features=20, n_classes=4,
                                              n_train=800, n_test=200)
    w = native_lib.train_classifier(xt, yt, classes=4, epochs=6, batch=32,
                                    lr=0.1, momentum=0.9, seed=1)
    acc, loss = native_lib.eval_classifier(xe, ye, 4, w)
    assert acc > 0.6


def test_native_trainer_in_federated_round(args_factory):
    """The C++ trainer drives a full SP FedAvg federation: params are numpy
    dicts, aggregation is the same weighted average."""
    import fedml_tpu
    from fedml_tpu.core.alg_frame.server_aggregator import ServerAggregator
    from fedml_tpu.native.native_trainer import NativeClientTrainer
    from fedml_tpu.runner import FedMLRunner

    class NativeServerAggregator(ServerAggregator):
        def __init__(self, bundle, args):
            super().__init__(bundle, args)
            self.bundle = bundle
            self._trainer = NativeClientTrainer(bundle, args)

        def test(self, test_data, device=None, args=None):
            self._trainer.params = {k: v for k, v in self.params.items()
                                    if k != "loss"}
            return self._trainer.test(test_data)

    args = fedml_tpu.init(args_factory(comm_round=4, data_scale=0.4,
                                       learning_rate=0.1, momentum=0.9))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    trainer = NativeClientTrainer(bundle, args)
    # seed initial global params so round 0 has something to distribute
    trainer.train(dataset[5][0])
    init_params = {k: np.zeros_like(v) if hasattr(v, "shape") else v
                   for k, v in trainer.params.items() if k != "loss"}
    aggregator = NativeServerAggregator(bundle, args)
    aggregator.set_model_params(init_params)

    runner = FedMLRunner(args, device, dataset, bundle,
                         client_trainer=trainer,
                         server_aggregator=aggregator)
    api = runner.runner
    api.global_vars = init_params
    m = api.train()
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.3


def test_native_csv_loader_trains(native_lib, tmp_path):
    """C++ CSV loader feeds the native trainer end to end (reference
    MobileNN tabular DataLoader capability)."""
    from fedml_tpu.data.datasets import synthetic_classification

    xt, yt, xe, ye = synthetic_classification(n_features=8, n_classes=3,
                                              n_train=300, n_test=60, seed=1)
    csv = tmp_path / "train.csv"
    with open(csv, "w") as f:
        f.write("# features...,label\n")
        for row, label in zip(xt, yt):
            f.write(",".join(f"{v:.6f}" for v in row) + f",{label}\n")
    x, y = native_lib.load_csv(str(csv))
    assert x.shape == (300, 8) and y.shape == (300,)
    np.testing.assert_allclose(x, xt, atol=1e-5)
    np.testing.assert_array_equal(y, yt)

    rng = np.random.RandomState(0)
    weights = {"w1": np.zeros((0,)), "b1": np.zeros((0,)),
               "w2": 0.01 * rng.randn(8, 3).astype(np.float32),
               "b2": np.zeros(3, np.float32)}
    out = native_lib.train_classifier(x, y, 3, hidden=0, epochs=20,
                                      batch=32, lr=0.2, weights=weights)
    acc, _ = native_lib.eval_classifier(xe, ye, 3, out, hidden=0)
    assert acc > 0.6


def test_native_idx_loader(native_lib, tmp_path):
    """C++ MNIST-idx loader parses the big-endian idx3/idx1 pair."""
    import struct

    rng = np.random.RandomState(0)
    n, rows, cols = 12, 4, 5
    imgs = rng.randint(0, 256, size=(n, rows, cols)).astype(np.uint8)
    labels = rng.randint(0, 10, size=n).astype(np.uint8)
    with open(tmp_path / "imgs.idx3", "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, rows, cols))
        f.write(imgs.tobytes())
    with open(tmp_path / "labels.idx1", "wb") as f:
        f.write(struct.pack(">II", 0x801, n))
        f.write(labels.tobytes())
    x, y = native_lib.load_idx(str(tmp_path / "imgs.idx3"),
                               str(tmp_path / "labels.idx1"))
    assert x.shape == (n, rows * cols)
    np.testing.assert_allclose(x, imgs.reshape(n, -1) / 255.0, atol=1e-6)
    np.testing.assert_array_equal(y, labels)


def test_native_loaders_reject_corrupt_inputs(native_lib, tmp_path):
    import struct

    # unparseable CSV cell (uncommented header) is a hard error, not 0.0s
    bad = tmp_path / "bad.csv"
    bad.write_text("f0,f1,label\n1.0,2.0,0\n")
    with pytest.raises(IOError, match="code 4"):
        native_lib.load_csv(str(bad))

    # truncated idx image data is a hard error, not silent duplication
    n, rows, cols = 10, 4, 4
    imgs = np.zeros((n, rows, cols), np.uint8)
    with open(tmp_path / "trunc.idx3", "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, rows, cols))
        f.write(imgs.tobytes()[: n * rows * cols // 2])  # half the data
    with open(tmp_path / "l.idx1", "wb") as f:
        f.write(struct.pack(">II", 0x801, n))
        f.write(np.zeros(n, np.uint8).tobytes())
    with pytest.raises(IOError, match="code 5"):
        native_lib.load_idx(str(tmp_path / "trunc.idx3"),
                            str(tmp_path / "l.idx1"))


def test_native_lenet_trains_conv_on_device(native_lib):
    """Conv-capable edge trainer (reference FedMLMNNTrainer.cpp CNN
    capability): the C++ LeNet reaches >80% of the JAX CNN's accuracy on
    synthetic MNIST at equal epochs."""
    from fedml_tpu.data.datasets import _synthetic_images
    from fedml_tpu.native import bindings

    xt, yt, xe, ye = _synthetic_images((28, 28, 1), 10, 600, 150, seed=3)

    # native C++ LeNet, 2 epochs
    w = bindings.train_lenet(xt, yt, classes=10, epochs=2, batch=32,
                             lr=0.05, momentum=0.9, seed=0)
    acc_native, loss_native = bindings.eval_lenet(xe, ye, 10, w)
    assert np.isfinite(loss_native)

    # JAX CNN trainer at equal epochs on the same data
    import jax

    import fedml_tpu
    from fedml_tpu.ml.engine.local_update import (
        build_eval_step,
        build_local_update,
        make_batches,
    )

    args = fedml_tpu.Config(model="cnn", dataset="mnist", epochs=2,
                            learning_rate=0.05, client_optimizer="sgd",
                            momentum=0.9, compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 10)
    variables = bundle.init_variables(jax.random.PRNGKey(0))
    step = jax.jit(build_local_update(bundle, args))
    batches = make_batches(xt, yt, 32, -(-len(yt) // 32),
                           bundle.input_dtype)
    new_vars, _, _ = step(variables, batches, jax.random.PRNGKey(1), None)
    ev = jax.jit(build_eval_step(bundle))
    test_batches = make_batches(xe, ye, 32, -(-len(ye) // 32),
                                bundle.input_dtype)
    out = ev(new_vars, test_batches)
    acc_jax = float(out["correct"]) / max(float(out["n"]), 1.0)

    assert acc_native >= 0.8 * acc_jax, (acc_native, acc_jax)
    # and it must actually use the convs: kernels moved from init
    init = bindings.init_lenet_weights(784, 10, seed=0)
    assert float(np.abs(w["k1"] - init["k1"]).max()) > 0


def test_native_lenet_federated_round_carries_weights(native_lib,
                                                      args_factory):
    """The conv trainer plugs into the same federated plane: weights carry
    across rounds (in-place update contract) and accuracy improves."""
    from fedml_tpu.data.datasets import _synthetic_images
    from fedml_tpu.native.native_trainer import NativeClientTrainer

    import fedml_tpu

    xt, yt, xe, ye = _synthetic_images((28, 28, 1), 10, 600, 150, seed=4)
    args = args_factory(native_model="lenet", epochs=1, batch_size=32,
                        learning_rate=0.03, momentum=0.9)
    bundle = fedml_tpu.model.create(args, 10)
    t = NativeClientTrainer(bundle, args)
    t.update_dataset((xt, yt), (xe, ye), len(yt))
    t.train((xt, yt))
    m1 = t.test((xe, ye))
    for _ in range(3):          # more federated rounds, carried weights
        t.train((xt, yt))
    m4 = t.test((xe, ye))
    assert m4["test_acc"] > max(0.5, m1["test_acc"])   # keeps learning
    assert m4["test_loss"] < m1["test_loss"]
    assert set(t.params) >= {"k1", "bk1", "k2", "bk2", "fw", "fb"}
