"""Security-stack tests mirroring the reference's per-attack/per-defense unit
tests (`python/tests/security/attack/test_*.py`, `defense/test_*.py`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import make_args
from fedml_tpu.core.security.attack import ATTACK_REGISTRY, create_attacker
from fedml_tpu.core.security.defense import DEFENSE_REGISTRY, create_defender
from fedml_tpu.core.security.utils import (
    fabricate_fake_client_grads,
    tree_to_vector,
)
from fedml_tpu.ml.aggregator.agg_operator import FedMLAggOperator


def _grads_with_outlier(n=6, dim=12, outlier_scale=50.0, seed=0):
    """Honest updates ~N(0,0.1) around a shared direction + one huge outlier."""
    rng = np.random.RandomState(seed)
    base = rng.randn(dim) * 0.5
    grads = []
    for i in range(n):
        vec = base + rng.randn(dim) * 0.1
        if i == 0:
            vec = vec * 0 + outlier_scale
        tree = {"w": jnp.asarray(vec[: dim // 2], dtype=jnp.float32),
                "b": jnp.asarray(vec[dim // 2:], dtype=jnp.float32)}
        grads.append((10.0, tree))
    return grads, base


@pytest.mark.parametrize("name", sorted(DEFENSE_REGISTRY))
def test_every_defense_runs(name):
    """Every registered defense consumes a grad list and yields either a
    filtered list (before-hook) or an aggregate pytree (on-hook)."""
    args = make_args(enable_defense=True, defense_type=name,
                     byzantine_client_num=1, trim_param_k=1,
                     robust_threshold=2.0)
    d = create_defender(name, args)
    grads, _ = _grads_with_outlier()

    filtered = d.defend_before_aggregation(grads)
    assert len(filtered) >= 1
    agg = d.defend_on_aggregation(
        filtered, base_aggregation_func=FedMLAggOperator.agg)
    vec = tree_to_vector(agg)
    assert vec.shape == (12,)
    assert bool(jnp.all(jnp.isfinite(vec)))
    out = d.defend_after_aggregation(agg)
    assert bool(jnp.all(jnp.isfinite(tree_to_vector(out))))


@pytest.mark.parametrize("name", ["krum", "multikrum", "three_sigma",
                                  "outlier_detection", "wbc"])
def test_filter_defenses_remove_large_outlier(name):
    args = make_args(byzantine_client_num=1)
    d = create_defender(name, args)
    grads, base = _grads_with_outlier()
    filtered = d.defend_before_aggregation(grads)
    agg = d.defend_on_aggregation(
        filtered, base_aggregation_func=FedMLAggOperator.agg)
    vec = np.asarray(tree_to_vector(agg))
    # aggregate should sit near the honest direction, far from the 50s
    assert np.linalg.norm(vec - base) < np.linalg.norm(vec - 50.0)


def test_robust_learning_rate_flips_minority_coords():
    args = make_args(robust_threshold=4.0)
    d = create_defender("robust_learning_rate", args)
    grads, _ = _grads_with_outlier(n=5, outlier_scale=3.0)
    agg = d.defend_on_aggregation(
        grads, base_aggregation_func=FedMLAggOperator.agg)
    assert bool(jnp.all(jnp.isfinite(tree_to_vector(agg))))


def test_crfl_clips_and_noises_global_model():
    args = make_args(crfl_clip_threshold=1.0, crfl_sigma=0.0)
    d = create_defender("crfl", args)
    big = {"w": jnp.ones((8,), jnp.float32) * 100.0}
    out = d.defend_after_aggregation(big)
    norm = float(jnp.linalg.norm(out["w"]))
    assert norm <= 1.0 + 1e-4


def test_soteria_prunes_representation_layer():
    args = make_args(soteria_prune_ratio=0.5)
    d = create_defender("soteria", args)
    grads, _ = _grads_with_outlier()
    out = d.defend_before_aggregation(grads)
    for (_, tree), (_, orig) in zip(out, grads):
        # exactly one leaf (the representation layer) gets ~half zeroed;
        # the other stays untouched
        zeros = {k: int(jnp.sum(tree[k] == 0)) for k in ("w", "b")}
        pruned = max(zeros, key=zeros.get)
        other = "w" if pruned == "b" else "b"
        assert zeros[pruned] >= 2
        assert bool(jnp.all(tree[other] == orig[other]))


@pytest.mark.parametrize("name", sorted(ATTACK_REGISTRY))
def test_every_model_attack_runs(name):
    args = make_args(enable_attack=True, attack_type=name,
                     byzantine_client_num=1, poison_frac=0.3)
    a = create_attacker(name, args)
    grads, _ = _grads_with_outlier()
    gm = grads[1][1]
    out = a.attack_model(grads, extra_auxiliary_info=gm)
    assert len(out) == len(grads)

    x = np.random.RandomState(0).rand(20, 8, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 3, size=20)
    x2, y2 = a.poison_data((x, y))
    assert x2.shape == x.shape and y2.shape == y.shape


def test_label_flipping_flips():
    args = make_args(original_class_list=[1], target_class_list=[7])
    a = create_attacker("label_flipping", args)
    y = np.array([0, 1, 1, 2])
    _, y2 = a.poison_data((np.zeros((4, 4)), y))
    assert set(y2[y == 1]) <= {7}


def test_edge_case_backdoor_targets_tail_samples():
    args = make_args(backdoor_target_label=9, poison_frac=0.2,
                     trigger_size=2)
    a = create_attacker("edge_case_backdoor", args)
    rng = np.random.RandomState(0)
    x = rng.rand(50, 6, 6).astype(np.float32)
    y = np.zeros(50, dtype=np.int64)
    x[0] = 10.0  # an extreme edge-case sample
    x2, y2 = a.poison_data((x, y))
    assert y2[0] == 9  # the tail sample got poisoned
    assert int(np.sum(y2 == 9)) == 10  # exactly poison_frac * n


def test_revealing_labels_from_gradients():
    from fedml_tpu.core.security.attack.gradient_inversion import (
        infer_labels_from_gradients,
    )
    # classic cross-entropy bias-grad sign structure: present classes negative
    g = jnp.asarray([0.2, -0.9, 0.1, -0.4, 0.3])
    labels = set(np.asarray(infer_labels_from_gradients(g, 2)).tolist())
    assert labels == {1, 3}


def test_dp_frames_registry_and_nbafl():
    from fedml_tpu.core.dp.fedml_differential_privacy import (
        FedMLDifferentialPrivacy,
    )
    args = make_args(enable_dp=True, dp_solution_type="NbAFL",
                     mechanism_type="gaussian", epsilon=5.0, delta=1e-5,
                     max_grad_norm=1.0)
    dp = FedMLDifferentialPrivacy.get_instance()
    dp.init(args)
    assert dp.is_local_dp_enabled() and dp.is_global_dp_enabled()
    tree = {"w": jnp.ones((16,), jnp.float32) * 10.0}
    noised = dp.add_local_noise(tree)
    # NbAFL clips to max_grad_norm then noises: norm near 1, not 40
    assert float(jnp.linalg.norm(noised["w"])) < 5.0
    clipped = dp.global_clip([(1.0, tree)])
    assert float(jnp.linalg.norm(clipped[0][1]["w"])) <= 1.0 + 1e-4
    assert bool(jnp.all(jnp.isfinite(dp.add_global_noise(tree)["w"])))


# ---------------------------------------------------------------- three-sigma
def _tree12(vec):
    return {"w": jnp.asarray(vec[:6], jnp.float32),
            "b": jnp.asarray(vec[6:], jnp.float32)}


def test_three_sigma_foolsgold_drops_sybils_after_pretraining():
    """Reference `three_sigma_defense_foolsgold.py`: honest pretraining
    round fits the score Gaussian; a sybil pair joining later scores far
    below mu-2sigma (raw FoolsGold logit) and is removed, survivors are
    bucketized."""
    rng = np.random.RandomState(0)
    base = rng.randn(12) * 0.5
    honest = [(10.0, _tree12(base + rng.randn(12) * 0.3)) for _ in range(8)]
    d = create_defender("three_sigma_foolsgold",
                        make_args(pretraining_round_num=2,
                                  bucketing_batch_size=1))
    assert len(d.defend_before_aggregation(list(honest))) == 8
    assert d.dist.lower_bound < d.dist.upper_bound  # Gaussian got fit
    syb = rng.randn(12)
    sybils = [(10.0, _tree12(syb)), (10.0, _tree12(syb))]
    kept = d.defend_before_aggregation(list(honest) + sybils)
    assert len(kept) == 8  # both sybils removed, no honest client lost


def test_three_sigma_foolsgold_bucketization():
    """Survivors are grouped into sample-weighted buckets of
    bucketing_batch_size (reference `common/bucket.py`)."""
    rng = np.random.RandomState(1)
    grads = [(float(10 + i), _tree12(rng.randn(12))) for i in range(8)]
    d = create_defender("three_sigma_foolsgold",
                        make_args(bucketing_batch_size=3))
    out = d.defend_before_aggregation(list(grads))
    assert [n for n, _ in out] == [10 + 11 + 12, 13 + 14 + 15, 16 + 17]
    # first bucket = sample-weighted mean of the first three updates
    n0, n1, n2 = 10.0, 11.0, 12.0
    tot = n0 + n1 + n2
    want = (tree_to_vector(grads[0][1]) * n0 + tree_to_vector(grads[1][1])
            * n1 + tree_to_vector(grads[2][1]) * n2) / tot
    np.testing.assert_allclose(np.asarray(tree_to_vector(out[0][1])),
                               np.asarray(want), rtol=1e-5)


def test_three_sigma_geomedian_freezes_median_and_drops_outlier():
    """Reference `three_sigma_geomedian_defense.py`: the geometric median
    of the first round's features is FROZEN; a later far-away update
    scores above mu+sigma and is removed."""
    rng = np.random.RandomState(2)
    base = rng.randn(12) * 0.5
    honest = [(10.0, _tree12(base + rng.randn(12) * 0.1)) for _ in range(8)]
    d = create_defender("three_sigma_geomedian",
                        make_args(pretraining_round_num=2))
    d.defend_before_aggregation(list(honest))
    frozen = np.asarray(d.geo_median).copy()
    outlier = [(10.0, _tree12(base * 0 + 50.0))]
    kept = d.defend_before_aggregation(list(honest) + outlier)
    assert not any(float(jnp.max(g["w"])) > 40 for _, g in kept)
    np.testing.assert_array_equal(np.asarray(d.geo_median), frozen)


def test_defense_registry_covers_every_reference_defense_file():
    """Audit: every concrete defense file in the reference maps to a
    registered defense name — the table has no holes (VERDICT r3 #5)."""
    import os

    ref_dir = "/root/reference/python/fedml/core/security/defense"
    if not os.path.isdir(ref_dir):
        pytest.skip("reference tree not available")
    file_to_name = {
        "RFA_defense": "rfa",
        "bulyan_defense": "bulyan",
        "cclip_defense": "cclip",
        "coordinate_wise_median_defense": "coordinate_wise_median",
        "coordinate_wise_trimmed_mean_defense":
            "coordinate_wise_trimmed_mean",
        "crfl_defense": "crfl",
        "cross_round_defense": "crossround",
        "foolsgold_defense": "foolsgold",
        "geometric_median_defense": "geometric_median",
        "krum_defense": "krum",
        "norm_diff_clipping_defense": "norm_diff_clipping",
        "outlier_detection": "outlier_detection",
        "residual_based_reweighting_defense": "residual_based_reweighting",
        "robust_learning_rate_defense": "robust_learning_rate",
        "slsgd_defense": "slsgd",
        "soteria_defense": "soteria",
        "three_sigma_defense": "three_sigma",
        "three_sigma_defense_foolsgold": "three_sigma_foolsgold",
        "three_sigma_geomedian_defense": "three_sigma_geomedian",
        "wbc_defense": "wbc",
        "weak_dp_defense": "weak_dp",
    }
    ref_files = sorted(
        f[:-3] for f in os.listdir(ref_dir)
        if f.endswith(".py") and f not in ("__init__.py", "defense_base.py"))
    unmapped = [f for f in ref_files if f not in file_to_name]
    assert not unmapped, f"reference defense files without a mapping: {unmapped}"
    missing = [n for n in file_to_name.values() if n not in DEFENSE_REGISTRY]
    assert not missing, f"mapped names absent from DEFENSE_REGISTRY: {missing}"


# ------------------------------------------------------- gradient inversion
def _tiny_conv_model(seed=1):
    """Tiny LeNet-style conv net (conv3x3x6 → relu → 2x2 mean pool →
    dense 10) in NHWC, pure-jax — the reconstruction target."""
    k1, k2, _ = jax.random.split(jax.random.PRNGKey(seed), 3)
    params = {"conv": jax.random.normal(k1, (3, 3, 1, 6)) * 0.3,
              "w": jax.random.normal(k2, (6 * 7 * 7, 10)) * 0.1,
              "b": jnp.zeros((10,))}

    def fwd(p, x):
        h = jax.lax.conv_general_dilated(
            x, p["conv"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h = jax.nn.relu(h)
        h = jax.lax.reduce_window(h, 0.0, jax.lax.add, (1, 2, 2, 1),
                                  (1, 2, 2, 1), "SAME") / 4.0
        return h.reshape(h.shape[0], -1) @ p["w"] + p["b"]

    def loss(p, x, y_onehot):
        return -jnp.mean(jnp.sum(
            y_onehot * jax.nn.log_softmax(fwd(p, x)), axis=-1))

    return params, fwd, loss


def _blob_batch():
    """Smooth structured images (gaussian blobs) — something a PSNR can
    recognizably recover, unlike white noise."""
    def blob(cx, cy):
        yy, xx = np.mgrid[0:14, 0:14]
        return np.exp(-((xx - cx) ** 2 + (yy - cy) ** 2) / 8.0)
    x = np.stack([blob(4, 5)[..., None],
                  (blob(9, 8) + 0.6 * blob(3, 10))[..., None]])
    return x.astype(np.float32), jnp.asarray([3, 7])


@pytest.mark.slow
def test_invert_gradient_reconstructs_recognizable_images():
    """The attack recovers the client's batch from one gradient: exact
    iDLG label recovery + affine-fit PSNR well above the ~10 dB noise
    floor (reference `invert_gradient_attack.py` capability: cosine
    matching + TV prior + multi-restart)."""
    from fedml_tpu.core.security.attack.gradient_inversion import psnr

    params, fwd, loss = _tiny_conv_model()
    x_true, y_true = _blob_batch()
    tgt = jax.grad(loss)(params, jnp.asarray(x_true),
                         jax.nn.one_hot(y_true, 10))
    atk = create_attacker("invert_gradient", make_args(
        inversion_iters=1200, inversion_lr=0.1, inversion_restarts=3,
        inversion_tv_weight=1e-4, random_seed=0))
    x, labels, score = atk.reconstruct_with_score(tgt, {
        "loss_grad_fn": lambda x, y: jax.grad(loss)(params, x, y),
        "x_shape": x_true.shape, "num_classes": 10,
        "bias_grad": tgt["b"], "x_bounds": (0.0, 1.5)})
    assert list(np.asarray(labels)) == [3, 7]      # iDLG exact
    assert score < 0.05                             # gradients matched
    for i in range(2):
        assert psnr(x[i], x_true[i]) > 18.0, f"image {i} unrecognizable"


@pytest.mark.slow
def test_invert_gradient_feature_stats_prior_runs():
    """Deep-inversion style statistic prior: matching hidden-feature
    moments of a population batch keeps quality while exercising the
    BN-prior path (reference BN-loss hooks)."""
    from fedml_tpu.core.security.attack.gradient_inversion import psnr

    params, fwd, loss = _tiny_conv_model()
    x_true, y_true = _blob_batch()
    tgt = jax.grad(loss)(params, jnp.asarray(x_true),
                         jax.nn.one_hot(y_true, 10))

    def features(x):
        h = jax.lax.conv_general_dilated(
            x, params["conv"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jax.nn.relu(h).reshape(-1, 6)

    pop = features(jnp.asarray(x_true))
    atk = create_attacker("invert_gradient", make_args(
        inversion_iters=800, inversion_restarts=2,
        inversion_bn_weight=1e-2, random_seed=1))
    x, labels, _ = atk.reconstruct_with_score(tgt, {
        "loss_grad_fn": lambda x, y: jax.grad(loss)(params, x, y),
        "x_shape": x_true.shape, "num_classes": 10,
        "bias_grad": tgt["b"],
        "feature_fn": features, "feat_mean": jnp.mean(pop, axis=0),
        "feat_var": jnp.var(pop, axis=0)})
    assert list(np.asarray(labels)) == [3, 7]
    assert psnr(x[0], x_true[0]) > 12.0


def test_dlg_attack_l2_path_runs():
    params, fwd, loss = _tiny_conv_model()
    x_true, y_true = _blob_batch()
    tgt = jax.grad(loss)(params, jnp.asarray(x_true),
                         jax.nn.one_hot(y_true, 10))
    atk = create_attacker("dlg", make_args(inversion_iters=50,
                                           inversion_restarts=2))
    x, labels = atk.reconstruct_data(
        tgt, (lambda x, y: jax.grad(loss)(params, x, y),
              x_true.shape, 10))
    assert x.shape == x_true.shape
    assert labels.shape == (2,)
