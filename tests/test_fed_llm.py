"""Fed-LLM plane: cross-silo LoRA SFT where ONLY adapter deltas cross the
wire — e2e convergence (sync + buffered-async), bytes-on-wire reduction,
codec round-trips on LoRA-shaped pytrees, delta-space robust aggregation,
and startup flag validation (docs/FED_LLM.md)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner
from fedml_tpu.utils.serialization import estimate_nbytes

VOCAB = 90  # shakespeare char vocab


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run(), bundle


def _fed_args(args_factory, **kw):
    base = dict(
        dataset="shakespeare", model="transformer",
        training_type="cross_silo", backend="INPROC", role="simulated",
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        epochs=1, batch_size=4, learning_rate=3e-3, data_scale=0.5,
        frequency_of_the_test=1, random_seed=0,
        fed_llm=True, lora_rank=4, fed_llm_seq_len=32)
    base.update(kw)
    return args_factory(**base)


def _uplink_reduction(bundle, run_id, n_uploads):
    """full-model bytes ÷ measured mean bytes-on-wire per upload."""
    from fedml_tpu.utils.compression import WIRE_BYTES

    full = estimate_nbytes(bundle.init_variables(jax.random.PRNGKey(0)))
    up = sum(WIRE_BYTES.labels(run_id=str(run_id), direction="up",
                               codec=c).value
             for c in ("raw", "bf16", "int8", "topk", "topk8"))
    assert up > 0, "no uplink bytes recorded"
    return full / (up / n_uploads)


# -- e2e: the ISSUE acceptance gate ----------------------------------------
def test_fed_llm_e2e_sync_converges_and_ships_only_adapters(args_factory):
    m, bundle = _run(_fed_args(args_factory, run_id="fedllm-sync"))
    hist = m["server_loss_history"]
    # one eval per round: monotone-ish improvement is too strict for 3
    # SGD rounds, but the endpoint must beat the start and the
    # uniform-over-vocab ceiling
    assert len(hist) == 3
    assert all(math.isfinite(x) for x in hist)
    assert hist[-1] < hist[0]
    assert hist[-1] < math.log(VOCAB)
    assert m["adapter_params"] > 0
    # only adapter trees crossed the wire: 2 silos x 3 rounds of uploads
    red = _uplink_reduction(bundle, "fedllm-sync", n_uploads=6)
    assert red >= 20.0, f"uplink reduction {red:.1f}x below 20x floor"


def test_fed_llm_e2e_async_buffered_int8_wire(args_factory):
    # buffered-async AND the negotiated int8 delta codec in one loop:
    # adapter trees flow encode_delta → decode_delta with client-side
    # error feedback, then fold through the async buffer
    m, bundle = _run(_fed_args(args_factory, run_id="fedllm-async",
                               async_agg=True, comm_round=3,
                               wire_compression="int8"))
    hist = m["server_loss_history"]
    assert all(math.isfinite(x) for x in hist)
    # async mixes adapter trees post-aggregate (mix_global) — the lazy
    # re-merge must still produce an improving merged model
    assert hist[-1] < hist[0]
    assert hist[-1] < math.log(VOCAB)
    # int8 quantizes the already-tiny adapter deltas: reduction well past
    # the raw-adapter 20x floor
    assert _uplink_reduction(bundle, "fedllm-async", n_uploads=6) >= 20.0


def test_fed_llm_sync_parity_with_central_adapter_average(args_factory):
    """One round of the federation == centrally averaging the silos'
    locally-trained adapters (FedAvg in delta space is exact for equal
    participation)."""
    from fedml_tpu.train.fed_llm import FedLLMAggregator, FedLLMTrainer

    args = fedml_tpu.init(_fed_args(args_factory, run_id="fedllm-parity"))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    ag = FedLLMAggregator(bundle, args)
    gl = ag.get_model_params()
    # server and silo bases are bit-identical by construction (same seed)
    tr = FedLLMTrainer(bundle, args)
    for a, b in zip(jax.tree_util.tree_leaves(ag._ref.variables["params"]),
                    jax.tree_util.tree_leaves(tr.llm.variables["params"])):
        assert jnp.array_equal(a, b)

    ups = []
    for cid in (0, 1):
        t = FedLLMTrainer(bundle, args)
        t.set_model_params(gl)
        t.train(dataset[5][cid])
        ups.append(t.get_model_params())
    new = ag.aggregate([(1.0, ups[0]), (3.0, ups[1])])
    exp = jax.tree_util.tree_map(
        lambda g, a, b: g + (1.0 * (a - g) + 3.0 * (b - g)) / 4.0,
        gl, ups[0], ups[1])
    for a, b in zip(jax.tree_util.tree_leaves(new),
                    jax.tree_util.tree_leaves(exp)):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5
    # the cached merge is exactly apply_lora(base, new, alpha)
    from fedml_tpu.train.llm.lora import apply_lora

    ag.set_model_params(new)
    merged = ag._merged_params()
    ref = apply_lora(ag._ref.variables["params"], new, ag.cfg.lora_alpha)
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(ref)):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5


# -- codec round-trips on LoRA-shaped pytrees ------------------------------
def _lora_tree(rng, dtype):
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    return {
        "encoder/mlp/Dense_0": {
            "a": jax.random.normal(k1, (16, 4)).astype(dtype) * 0.02,
            "b": jax.random.normal(k2, (4, 32)).astype(dtype) * 0.02,
        },
        "head": {
            "a": jax.random.normal(k3, (32, 4)).astype(dtype) * 0.02,
            "b": jax.random.normal(k4, (4, 90)).astype(dtype) * 0.02,
        },
    }


@pytest.mark.parametrize("spec", ["int8", "topk8:0.25"])
@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_fed_llm_codec_roundtrip_adapter_tree(spec, dtype):
    from fedml_tpu.utils import compression as C

    ref = _lora_tree(jax.random.PRNGKey(0), dtype)
    upd = jax.tree_util.tree_map(
        lambda a, d: a + jnp.asarray(d, a.dtype),
        ref, _lora_tree(jax.random.PRNGKey(1), jnp.float32))
    codec = C.WireCodec(spec)
    payload = codec.encode_delta(upd, ref)

    # residual IS delta − decoded, exactly (f32): nothing the quantizer
    # dropped is lost — it rides into the next round's encode
    flat_u, _ = C._flatten(upd)
    flat_r, _ = C._flatten(ref)
    delta = flat_u - flat_r
    decoded_flat = C.decode_delta_flat(payload)
    assert jnp.array_equal(codec._residual, delta - decoded_flat)

    # decode preserves structure + per-leaf dtype (bf16 stays bf16), and
    # is deterministic against the shared per-version reference
    out1 = C.decode_delta(payload, ref)
    out2 = C.decode_delta(payload, ref)
    assert (jax.tree_util.tree_structure(out1)
            == jax.tree_util.tree_structure(ref))
    for o1, o2, r in zip(jax.tree_util.tree_leaves(out1),
                         jax.tree_util.tree_leaves(out2),
                         jax.tree_util.tree_leaves(ref)):
        assert o1.dtype == r.dtype and o1.shape == r.shape
        assert jnp.array_equal(o1, o2)

    # error feedback: re-sending the SAME update flushes the residual, so
    # two EF rounds reconstruct the cumulative delta better than 2x one
    # lossy round
    payload2 = codec.encode_delta(upd, ref)
    recon = C.decode_delta_flat(payload) + C.decode_delta_flat(payload2)
    err_ef = float(jnp.max(jnp.abs(recon - 2.0 * delta)))
    err_naive = 2.0 * float(jnp.max(jnp.abs(decoded_flat - delta)))
    assert err_ef <= err_naive + 1e-7


# -- delta-space robust aggregation ----------------------------------------
def test_fed_llm_trimmed_mean_quarantines_sign_flipped_silo(args_factory):
    from fedml_tpu.train.fed_llm import FedLLMAggregator

    args = fedml_tpu.init(_fed_args(args_factory, run_id="fedllm-robust",
                                    client_num_in_total=3,
                                    client_num_per_round=3,
                                    robust_agg="trimmed_mean:0.34"))
    bundle = fedml_tpu.model.create(args, VOCAB)
    ag = FedLLMAggregator(bundle, args)
    gl = ag.get_model_params()
    d = jax.tree_util.tree_map(lambda a: jnp.full_like(a, 0.01), gl)
    honest = jax.tree_util.tree_map(jnp.add, gl, d)
    # sign-flipped and amplified: an untrimmed mean would be dragged to
    # gl − 2.6⋅d; per-coordinate trimming drops the outlier instead
    attacker = jax.tree_util.tree_map(
        lambda g, x: g - 10.0 * x, gl, d)
    new = ag.aggregate([(1.0, honest), (1.0, honest), (1.0, attacker)])
    exp = jax.tree_util.tree_map(jnp.add, gl, d)
    for a, b in zip(jax.tree_util.tree_leaves(new),
                    jax.tree_util.tree_leaves(exp)):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5


# -- serving probe ---------------------------------------------------------
def test_fed_llm_serve_eval_probe(args_factory):
    from fedml_tpu.train.fed_llm import FedLLMAggregator

    args = fedml_tpu.init(_fed_args(args_factory, run_id="fedllm-serve",
                                    fed_llm_serve_eval=True))
    bundle = fedml_tpu.model.create(args, VOCAB)
    ag = FedLLMAggregator(bundle, args)
    x = np.random.default_rng(0).integers(0, VOCAB, size=(8, 80))
    m = ag.test((x, x))
    assert m["served_tokens"] == 8
    assert math.isfinite(m["test_loss"])


# -- startup validation (the parse_wire_compression idiom) -----------------
@pytest.mark.parametrize("bad", [
    {"lora_rank": 0}, {"lora_rank": "four"},
    {"lora_alpha": 0.0}, {"lora_alpha": -2.0},
    {"fed_llm_seq_len": 1},
    {"fed_llm_strategy": "tp"},
    {"lora_targets": "(unclosed"},
])
def test_fed_llm_bad_flags_fail_at_startup(args_factory, bad):
    from fedml_tpu.train.fed_llm import validate_fed_llm_args

    args = _fed_args(args_factory, **bad)
    with pytest.raises(ValueError):
        validate_fed_llm_args(args)
    # fedml_tpu.init is the funnel every launcher goes through
    with pytest.raises(ValueError):
        fedml_tpu.init(args)


def test_fed_llm_lora_targets_parsing():
    from fedml_tpu.train.fed_llm import parse_lora_targets

    assert parse_lora_targets(None) is None
    assert parse_lora_targets("") is None
    assert parse_lora_targets("  ,  ") is None
    assert parse_lora_targets("mlp, head$") == ("mlp", "head$")


def test_fed_llm_silo_rejects_undersized_partition(args_factory):
    from fedml_tpu.train.fed_llm import FedLLMTrainer

    args = fedml_tpu.init(_fed_args(args_factory, run_id="fedllm-tiny"))
    bundle = fedml_tpu.model.create(args, VOCAB)
    tr = FedLLMTrainer(bundle, args)
    x = np.zeros((1, 80), np.int64)  # 80 tokens < 32*4 + 1
    with pytest.raises(ValueError, match="too small"):
        tr.train((x, x))
