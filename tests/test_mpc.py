"""MPC primitives: Shamir, LCC, LightSecAgg round-trips, uint32 masking."""

import numpy as np

from fedml_tpu.core.mpc.lightsecagg import (
    aggregate_encoded_masks,
    decode_aggregate_mask,
    mask_encoding,
)
from fedml_tpu.core.mpc.secagg import (
    FIELD_PRIME,
    LCC_decoding_with_points,
    LCC_encoding_with_points,
    dequantize,
    mask_model,
    modular_inv,
    prg_mask_like,
    quantize,
    shamir_reconstruct,
    shamir_share,
    unmask_sum,
)


def test_modular_inv():
    rng = np.random.RandomState(0)
    a = rng.randint(1, int(FIELD_PRIME), size=10).astype(np.int64)
    inv = modular_inv(a)
    assert np.all((a * inv) % FIELD_PRIME == 1)


def test_shamir_round_trip():
    rng = np.random.RandomState(1)
    secret = rng.randint(0, int(FIELD_PRIME), size=20).astype(np.int64)
    shares = shamir_share(secret, n=5, t=2, rng=rng)
    # any t+1=3 shares reconstruct
    sub = {k: shares[k] for k in [0, 2, 4]}
    np.testing.assert_array_equal(shamir_reconstruct(sub), secret)
    sub2 = {k: shares[k] for k in [1, 2, 3]}
    np.testing.assert_array_equal(shamir_reconstruct(sub2), secret)


def test_lcc_encode_decode_round_trip():
    rng = np.random.RandomState(2)
    X = rng.randint(0, int(FIELD_PRIME), size=(3, 7)).astype(np.int64)
    beta = [1, 2, 3]
    alpha = [4, 5, 6, 7, 8]
    enc = LCC_encoding_with_points(X, beta, alpha)
    dec = LCC_decoding_with_points(enc[:4], alpha[:4], beta)
    np.testing.assert_array_equal(dec % FIELD_PRIME, X % FIELD_PRIME)


def test_lightsecagg_dropout_tolerant_sum():
    """3 clients, 1 drops out after sharing; aggregate mask of the SURVIVING
    set is reconstructed from u survivors' aggregated shares."""
    d, n, u, t = 11, 3, 2, 1
    rng = np.random.RandomState(3)
    masks = [rng.randint(0, 2**16, size=d).astype(np.int64) for _ in range(n)]
    shares = [mask_encoding(d, n, u, t, masks[i], rng) for i in range(n)]
    survivors = [0, 2]  # client 1 dropped
    # each survivor j sums the shares it HOLDS from the surviving clients
    agg_shares = {
        j: aggregate_encoded_masks([shares[i][j] for i in survivors])
        for j in survivors
    }
    agg_mask = decode_aggregate_mask(agg_shares, d, n, u, t)
    expect = (masks[0] + masks[2]) % FIELD_PRIME
    np.testing.assert_array_equal(agg_mask % FIELD_PRIME, expect)


def test_uint32_mask_roundtrip():
    import jax.numpy as jnp

    tree = {"w": jnp.asarray(np.random.RandomState(4).randn(8, 3),
                             jnp.float32)}
    q = quantize(tree)
    m1 = prg_mask_like(q, seed=101)
    m2 = prg_mask_like(q, seed=202)
    masked1 = mask_model(q, m1)
    masked2 = mask_model(q, m2)
    # server sums masked models, subtracts aggregate mask
    qsum = {"w": masked1["w"] + masked2["w"]}
    agg_mask = {"w": m1["w"] + m2["w"]}
    unmasked = unmask_sum(qsum, agg_mask)
    recovered = dequantize(unmasked)
    np.testing.assert_allclose(recovered["w"], 2 * np.asarray(tree["w"]),
                               atol=1e-3)
