"""Always-on native edge daemon (reference EdgeService/ClientAgentManager,
closing the round-1 partial on component #27): devices bind once over REAL
TCP MQTT, heartbeat, join a federated run when start_train is dispatched,
and outlive the run."""

import json
import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.distributed.communication.mqtt_s3.mini_mqtt import (
    MiniMqttBroker,
)


@pytest.mark.slow
def test_edge_service_full_dispatch_cycle(tmp_path, monkeypatch):
    import fedml_tpu
    from fedml_tpu.core.alg_frame.server_aggregator import ServerAggregator
    from fedml_tpu.cross_device.edge_service import EdgeService
    from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator
    from fedml_tpu.cross_silo.server.fedml_server_manager import (
        FedMLServerManager,
    )
    from fedml_tpu.native.native_trainer import NativeClientTrainer
    from fedml_tpu.scheduler.agents import _topic_start, _topic_status

    broker = MiniMqttBroker()
    monkeypatch.setenv("FEDML_MQTT_HOST", broker.host)
    monkeypatch.setenv("FEDML_MQTT_PORT", str(broker.port))

    run_id = "edgesvc1"
    cfg = dict(
        training_type="cross_device", dataset="synthetic", model="lr",
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        data_scale=0.2, batch_size=16, epochs=1, learning_rate=0.1,
        momentum=0.9, frequency_of_the_test=1, run_id=run_id,
        random_seed=0, enable_tracking=False, compute_dtype="float32",
        mqtt_host=broker.host, mqtt_port=broker.port,
        object_store_dir=str(tmp_path))

    # control-plane status collector (the MLOps role)
    from fedml_tpu.scheduler.agents import _make_broker

    ctl = _make_broker("edges", "mlops")
    statuses = []
    ctl.subscribe(_topic_status(run_id),
                  lambda t, p: statuses.append(json.loads(p.decode())))

    # two always-on edge daemons come online BEFORE any run exists
    services = [EdgeService(f"e{i}", channel="edges",
                            heartbeat_s=1.0).start()
                for i in (1, 2)]
    try:
        # server side (native weight layout, same wire as edge_client)
        args = fedml_tpu.init(fedml_tpu.Config(**cfg))
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])

        class EdgeServerAggregator(ServerAggregator):
            def __init__(self, bundle, args):
                super().__init__(bundle, args)
                self._t = NativeClientTrainer(bundle, args)

            def test(self, test_data, device=None, args=None):
                self._t.params = {k: np.asarray(v)
                                  for k, v in self.params.items()}
                return self._t.test(test_data)

        d = int(np.prod(dataset[2][0].shape[1:]))
        agg_impl = EdgeServerAggregator(bundle, args)
        agg_impl.set_model_params({
            "w1": np.zeros(0, np.float32), "b1": np.zeros(0, np.float32),
            "w2": np.zeros((d, dataset[-1]), np.float32),
            "b2": np.zeros(dataset[-1], np.float32)})
        aggregator = FedMLAggregator(args, agg_impl, dataset[3])
        server = FedMLServerManager(args, aggregator, rank=0,
                                    client_num=2, backend="MQTT_S3")

        # MLOps dispatches start_train to the bound edges
        for rank, svc in enumerate(services, start=1):
            ctl.publish(_topic_start(svc.edge_id), json.dumps(
                {"run_id": run_id, "rank": rank, "size": 3,
                 "backend": "MQTT_S3", "config": cfg}).encode())

        server.run()        # blocks until rounds complete + FINISH

        deadline = time.time() + 60
        while time.time() < deadline and not all(
                s.completed.get(run_id) == "FINISHED" for s in services):
            time.sleep(0.1)
        assert all(s.completed.get(run_id) == "FINISHED"
                   for s in services), [s.completed for s in services]
        m = aggregator.metrics_history[-1]
        assert np.isfinite(m["test_loss"])
        assert m["test_acc"] > 0.3

        # the daemons outlive the run (heartbeats still flowing)
        assert all(not s._stop.is_set() for s in services)
        # status stream saw TRAINING then FINISHED per edge
        got = {(s["edge_id"], s["status"]) for s in statuses}
        for i in (1, 2):
            assert (f"e{i}", "TRAINING") in got
            assert (f"e{i}", "FINISHED") in got
    finally:
        for s in services:
            s.stop()
        broker.stop()


@pytest.mark.slow
def test_edge_service_stop_during_setup_kills_run(tmp_path, monkeypatch):
    """A stop_train landing in the setup window (before the client joins)
    must kill the run, not let it train to completion."""
    import fedml_tpu
    from fedml_tpu.cross_device.edge_service import EdgeService

    broker = MiniMqttBroker()
    monkeypatch.setenv("FEDML_MQTT_HOST", broker.host)
    monkeypatch.setenv("FEDML_MQTT_PORT", str(broker.port))
    run_id = "edgesvc-cancel"

    slow_gate = threading.Event()

    def slow_provider(args):
        slow_gate.wait(30)          # hold setup until stop_train lands
        return fedml_tpu.data.load(args)

    svc = EdgeService("e9", channel="edges",
                      dataset_provider=slow_provider).start()
    try:
        from fedml_tpu.scheduler.agents import _make_broker, _topic_start

        ctl = _make_broker("edges", "mlops2")
        cfg = dict(dataset="synthetic", model="lr", data_scale=0.1,
                   run_id=run_id, mqtt_host=broker.host,
                   mqtt_port=broker.port, object_store_dir=str(tmp_path),
                   enable_tracking=False)
        ctl.publish(_topic_start("e9"), json.dumps(
            {"run_id": run_id, "rank": 1, "size": 2,
             "config": cfg}).encode())
        deadline = time.time() + 20
        while run_id not in svc._threads and time.time() < deadline:
            time.sleep(0.05)
        svc._on_stop("", json.dumps({"run_id": run_id}).encode())
        slow_gate.set()             # setup resumes AFTER the stop
        deadline = time.time() + 30
        while svc.completed.get(run_id) != "KILLED" \
                and time.time() < deadline:
            time.sleep(0.05)
        assert svc.completed.get(run_id) == "KILLED", svc.completed
        assert run_id not in svc._runs
    finally:
        svc.stop()
        broker.stop()


def test_redelivered_start_train_after_finish_replays_status(monkeypatch):
    """At-least-once delivery can replay start_train AFTER the run ended
    and its thread entry was reaped; the daemon must re-publish the
    recorded terminal status, not silently re-run the whole job."""
    from fedml_tpu.cross_device import edge_service as es_mod

    class _FakeBroker:
        def __init__(self):
            self.published = []

        def publish(self, topic, payload):
            self.published.append((topic, json.loads(payload.decode())))

        def subscribe(self, *a):
            pass

        def unsubscribe(self, *a):
            pass

    monkeypatch.setattr(es_mod, "_make_broker",
                        lambda channel, name: _FakeBroker())
    svc = es_mod.EdgeService("e-dup", heartbeat_s=999.0)
    svc.completed["r9"] = "FINISHED"

    started = []
    monkeypatch.setattr(svc, "_run_round_loop",
                        lambda run_id, req: started.append(run_id))
    svc._on_start("t", json.dumps({"run_id": "r9"}).encode())
    time.sleep(0.2)
    assert started == []                       # job NOT re-run
    statuses = [p for t, p in svc.broker.published
                if p.get("run_id") == "r9"]
    assert statuses and statuses[-1]["status"] == "FINISHED"
