"""Buffered-async aggregation plane: staleness weighting, buffer flush
triggers, wire-compression round-trips, the reliable×async interaction
(expired_stale), (sender, client_round) dedup, and the WAN-straggler
chaos soak (slow tier)."""

import threading
import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


# -- staleness-weight catalog -------------------------------------------------

def test_staleness_catalog():
    from fedml_tpu.ml.aggregator.staleness import (
        parse_staleness,
        staleness_fn,
        staleness_weight,
    )

    # every function maps s=0 → 1 and is monotone non-increasing
    for spec in ("constant", "poly", "poly:1.0", "exp:0.5", "hinge:3:1.0"):
        parsed = parse_staleness(spec)
        assert staleness_weight(parsed, 0) == pytest.approx(1.0)
        ws = [staleness_weight(parsed, s) for s in range(8)]
        assert all(a >= b for a, b in zip(ws, ws[1:])), (spec, ws)

    # exact values
    assert staleness_weight(parse_staleness("poly:0.5"), 3) == \
        pytest.approx(0.5)          # (1+3)^-0.5
    assert staleness_weight(parse_staleness("exp:1.0"), 1) == \
        pytest.approx(np.exp(-1.0))
    hinge = parse_staleness("hinge:3:1.0")
    assert staleness_weight(hinge, 3) == pytest.approx(1.0)  # grace window
    assert staleness_weight(hinge, 5) == pytest.approx(1.0 / 3.0)
    assert staleness_weight(parse_staleness("constant"), 100) == 1.0
    # default is the FedBuff poly:0.5
    assert parse_staleness(None).name == "poly"
    # negatives clamp (an update can't be fresher than the frontier)
    assert staleness_fn("poly:0.5")(-2) == pytest.approx(1.0)

    for bad in ("frobnicate", "poly:-1", "exp:0", "hinge:-1"):
        with pytest.raises(ValueError):
            parse_staleness(bad)


# -- wire codec ---------------------------------------------------------------

def test_parse_wire_compression():
    from fedml_tpu.utils.compression import (
        parse_wire_compression,
        required_caps,
    )

    assert parse_wire_compression(None) is None
    assert parse_wire_compression("none") is None
    assert parse_wire_compression("int8").kind == "int8"
    spec = parse_wire_compression("topk8:0.05")
    assert spec.kind == "topk8" and spec.ratio == pytest.approx(0.05)
    assert set(required_caps(spec)) == {"delta", "int8", "topk"}
    assert set(required_caps(parse_wire_compression("bf16"))) == \
        {"delta", "bf16"}
    for bad in ("zstd", "topk:0", "topk:2", "int8:0.5", "topk:x"):
        with pytest.raises(ValueError):
            parse_wire_compression(bad)


def _toy_trees():
    import jax.numpy as jnp

    ref = {"a": jnp.arange(700, dtype=jnp.float32).reshape(7, 100) / 9.0,
           "b": {"w": jnp.linspace(-1, 1, 300).astype(jnp.float32)}}
    upd = {"a": ref["a"] * 1.01 + 0.05,
           "b": {"w": ref["b"]["w"] * 0.9 - 0.02}}
    return ref, upd


@pytest.mark.parametrize("spec", ["bf16", "int8", "topk:0.2", "topk8:0.2"])
def test_wire_codec_delta_roundtrip(spec):
    import jax

    from fedml_tpu.utils.compression import WireCodec, decode_delta

    ref, upd = _toy_trees()
    codec = WireCodec(spec)
    payload = codec.encode_delta(upd, ref)
    back = decode_delta(payload, ref)
    # dtype and structure preserved
    assert (jax.tree_util.tree_structure(back)
            == jax.tree_util.tree_structure(upd))
    # quantization error is bounded by a scale quantum; top-k drops
    # coordinates (recovered by error feedback below)
    err = max(float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
              for a, b in zip(jax.tree_util.tree_leaves(back),
                              jax.tree_util.tree_leaves(upd)))
    assert err < (2.0 if spec.startswith("topk") else 0.05)


def test_decode_delta_bf16_reconstruction_is_bit_exact():
    """The per-leaf decode must keep the f32-add-then-cast contract: with
    a lossless payload (topk k=all), a bf16 update reconstructs
    BIT-EXACTLY against a bf16 reference — the EF residual and the async
    per-version reference both model an exact server-side apply, so a
    double-rounded add would drift every round."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.utils.compression import WireCodec, decode_delta

    rng = np.random.RandomState(3)
    ref = {"w": jnp.asarray(rng.randn(64, 64), jnp.bfloat16),
           "b": jnp.asarray(rng.randn(4096), jnp.bfloat16)}
    upd = {"w": (ref["w"].astype(jnp.float32) * 1.01 + 0.03).astype(
        jnp.bfloat16), "b": (ref["b"].astype(jnp.float32) - 0.5).astype(
        jnp.bfloat16)}
    payload = WireCodec("topk:1.0").encode_delta(upd, ref)
    back = decode_delta(payload, ref)
    for a, b in zip(jax.tree_util.tree_leaves(back),
                    jax.tree_util.tree_leaves(upd)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_wire_codec_error_feedback_recovers_dropped_mass():
    """What top-k drops one round, the EF residual re-sends later: the
    cumulative decoded delta converges to the true cumulative delta."""
    import jax

    from fedml_tpu.utils.compression import (
        WireCodec,
        _flatten,
        decode_delta_flat,
    )

    ref, upd = _toy_trees()
    true_delta = np.asarray(_flatten(upd)[0] - _flatten(ref)[0])
    codec = WireCodec("topk8:0.1")
    sent = np.zeros_like(true_delta)
    rels = {}
    for i in range(1, 31):
        payload = codec.encode_delta(upd, ref)
        sent = sent + np.asarray(decode_delta_flat(payload))
        if i in (5, 30):
            rels[i] = (np.linalg.norm(sent - i * true_delta)
                       / np.linalg.norm(i * true_delta))
    # the residual is BOUNDED, so the relative shortfall of the
    # cumulative sent mass decays ~1/n — without EF it would be the
    # constant fraction top-k drops every round
    no_ef = WireCodec("topk8:0.1")
    no_ef._residual = None
    one_shot = np.asarray(decode_delta_flat(no_ef._encode_flat(
        _flatten(upd)[0] - _flatten(ref)[0])))
    rel_no_ef = (np.linalg.norm(one_shot - true_delta)
                 / np.linalg.norm(true_delta))
    assert rels[30] < rels[5] * 0.4, rels       # decays with rounds
    assert rels[30] < rel_no_ef * 0.5, (rels, rel_no_ef)  # beats no-EF


def test_wire_codec_decode_runs_inside_jit():
    """The decompress path must be jit-traceable so the server can fold
    it into the aggregation program (and the pallas kernel's interpret
    mode must agree with the jnp fallback)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.wire_compression import (
        dequantize_int8_blocked,
        quantize_int8_blocked,
        scatter_flat,
    )

    flat = jnp.linspace(-3, 3, 2000).astype(jnp.float32)
    q, s = quantize_int8_blocked(flat)
    qi, si = quantize_int8_blocked(flat, interpret=True)  # pallas path
    np.testing.assert_array_equal(np.asarray(q), np.asarray(qi))
    np.testing.assert_allclose(np.asarray(s), np.asarray(si), rtol=1e-6)

    deq = jax.jit(lambda a, b: dequantize_int8_blocked(a, b, 2000))(q, s)
    np.testing.assert_allclose(np.asarray(deq), np.asarray(flat),
                               atol=float(np.max(np.asarray(s))) + 1e-6)
    sc = jax.jit(lambda v, i: scatter_flat(v, i, 10))(
        jnp.ones(3), jnp.array([1, 5, 7]))
    np.testing.assert_array_equal(
        np.asarray(sc), np.array([0, 1, 0, 0, 0, 1, 0, 1, 0, 0], np.float32))


def test_encoded_model_broadcast_roundtrip_is_shared_reference():
    """decode(encode_model(g)) is deterministic — both ends of the link
    derive bit-identical delta references from the same payload."""
    import jax

    from fedml_tpu.utils.compression import WireCodec

    ref, _ = _toy_trees()
    enc = WireCodec.encode_model(ref, "int8")
    assert WireCodec.is_encoded_model(enc)
    a = WireCodec.decode_model(enc)
    b = WireCodec.decode_model(enc)
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert not WireCodec.is_encoded_model(a)


# -- async manager unit tier (stub aggregator, no training) -------------------

class _StubServerAggregator:
    """Minimal FedMLAggregator stand-in recording buffer folds."""

    admission_control = False
    metrics_history: list

    def __init__(self, reject_reason=None):
        import jax.numpy as jnp

        self.global_params = {"w": jnp.zeros(8, jnp.float32)}
        self.folds = []
        self.metrics_history = []
        self.quarantined_this_round = {}
        self._reject = reject_reason

    def get_global_model_params(self):
        return self.global_params

    def set_global_model_params(self, p):
        self.global_params = p

    def admission_check(self, params):
        return self._reject

    def aggregate_buffer(self, entries, server_lr=1.0):
        self.folds.append(list(entries))
        return self.global_params

    def test_on_server_for_all_clients(self, round_idx):
        self.metrics_history.append({"round": round_idx})
        return {"round": round_idx}

    def client_sampling(self, r, total, k):
        return list(range(k))

    def data_silo_selection(self, r, total, k):
        return list(range(k))


def _mk_async_server(args_factory, run_id, n_clients=3, **kw):
    from fedml_tpu.cross_silo.server.async_server_manager import (
        AsyncFedMLServerManager,
    )

    args = args_factory(training_type="cross_silo",
                        client_num_in_total=n_clients,
                        client_num_per_round=n_clients, run_id=run_id, **kw)
    agg = _StubServerAggregator()
    if kw.get("admission_control"):
        agg.admission_control = True
    mgr = AsyncFedMLServerManager(args, agg, rank=0, client_num=n_clients,
                                  backend="INPROC")
    mgr.is_initialized = True
    mgr.client_id_list_in_this_round = list(range(n_clients))
    for rank in range(1, n_clients + 1):
        mgr.client_online_status[rank] = True
        mgr._dispatched_version[rank] = 0
    return mgr, agg


def _upload(mgr, sender, client_round, n_samples=10.0, params=None):
    import jax.numpy as jnp

    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.cross_silo.message_define import MyMessage

    msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, sender, 0)
    msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, client_round)
    msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, n_samples)
    msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS,
                   params if params is not None
                   else {"w": jnp.ones(8, jnp.float32) * sender})
    mgr.handle_message_receive_model_from_client(msg)


def test_async_count_flush_applies_staleness_weights(args_factory):
    mgr, agg = _mk_async_server(args_factory, "as_unit1",
                                async_agg=True, async_buffer_k=2,
                                async_staleness="poly:0.5", comm_round=50)
    mgr.args.round_idx = 4  # pretend 4 flushes happened
    _upload(mgr, 1, client_round=4, n_samples=10)   # fresh: weight 10
    assert len(mgr._buffer) == 1 and not agg.folds
    _upload(mgr, 2, client_round=1, n_samples=10)   # staleness 3: 10·(4)^-½=5
    # count trigger at k=2 → one flush, buffer drained, version advanced
    assert len(agg.folds) == 1 and not mgr._buffer
    assert int(mgr.args.round_idx) == 5
    weights = [w for w, _ in agg.folds[0]]
    assert weights[0] == pytest.approx(10.0)
    assert weights[1] == pytest.approx(10.0 / np.sqrt(4.0))


def test_async_expired_stale_is_dropped_not_quarantined(args_factory):
    """Satellite: a retransmitted update arriving past its staleness
    cutoff is counted expired_stale and dropped — never quarantined, and
    it cannot re-open a flushed buffer (the fold list stays empty)."""
    from fedml_tpu.core.mlops import metrics

    mgr, agg = _mk_async_server(args_factory, "as_unit2",
                                async_agg=True, async_buffer_k=4,
                                async_staleness_cutoff=3, comm_round=50,
                                admission_control=True)
    agg.admission_control = True
    mgr.args.round_idx = 10
    _upload(mgr, 1, client_round=2)   # staleness 8 > cutoff 3
    assert not mgr._buffer and not agg.folds
    assert mgr.aggregator.quarantined_this_round == {}
    m = metrics.REGISTRY.collect()["fedml_async_updates_total"]
    assert m.labels(run_id="as_unit2", outcome="expired_stale").value == 1
    # the duplicate retransmit of the SAME expired upload is dedup-suppressed
    _upload(mgr, 1, client_round=2)
    assert m.labels(run_id="as_unit2", outcome="expired_stale").value == 1
    assert m.labels(run_id="as_unit2", outcome="duplicate").value == 1
    assert not mgr._buffer and not agg.folds


def test_async_dedup_key_is_sender_and_client_round(args_factory):
    """Satellite: keep-first dedup on (sender, client_round) — the same
    client uploading in two DIFFERENT rounds is legitimate, the same
    (sender, round) pair twice is a transport duplicate."""
    from fedml_tpu.core.mlops import metrics

    mgr, agg = _mk_async_server(args_factory, "as_unit3",
                                async_agg=True, async_buffer_k=10,
                                comm_round=50)
    mgr.args.round_idx = 2
    _upload(mgr, 1, client_round=1)
    _upload(mgr, 1, client_round=1)   # transport duplicate → suppressed
    _upload(mgr, 1, client_round=2)   # different round → legitimate
    assert len(mgr._buffer) == 2
    m = metrics.REGISTRY.collect()["fedml_async_updates_total"]
    assert m.labels(run_id="as_unit3", outcome="duplicate").value == 1
    assert m.labels(run_id="as_unit3", outcome="folded").value == 2


def test_async_quarantine_before_buffer(args_factory):
    """Admission control screens async uploads BEFORE the buffer: poison
    is rejected outright, not staleness-down-weighted."""
    mgr, agg = _mk_async_server(args_factory, "as_unit4",
                                async_agg=True, async_buffer_k=4,
                                comm_round=50, admission_control=True)
    agg.admission_control = True
    agg._reject = "non_finite"
    _upload(mgr, 1, client_round=0)
    assert not mgr._buffer
    assert mgr.aggregator.quarantined_this_round.get(0) == "non_finite"
    # a corrected retry for the SAME round is re-screened, not dedup-dropped
    agg._reject = None
    _upload(mgr, 1, client_round=0)
    assert len(mgr._buffer) == 1


def test_async_timer_flush(args_factory):
    mgr, agg = _mk_async_server(args_factory, "as_unit5",
                                async_agg=True, async_buffer_k=99,
                                async_flush_s=0.2, comm_round=50)
    t = threading.Thread(target=mgr._flush_loop, daemon=True)
    t.start()
    _upload(mgr, 1, client_round=0)
    deadline = time.time() + 5
    while time.time() < deadline and not agg.folds:
        time.sleep(0.02)
    mgr._flush_stop.set()
    assert agg.folds, "timer never flushed the buffer"
    assert int(mgr.args.round_idx) == 1


def test_async_drain_flush_when_everyone_parked(args_factory):
    """All online participants at the frontier → flush immediately
    instead of idling (or deadlocking when buffer_k > cohort)."""
    mgr, agg = _mk_async_server(args_factory, "as_unit6", n_clients=2,
                                async_agg=True, async_buffer_k=99,
                                comm_round=50)
    _upload(mgr, 1, client_round=0)
    assert not agg.folds          # rank 2 still active
    _upload(mgr, 2, client_round=0)
    assert len(agg.folds) == 1    # both parked → drain flush
    assert int(mgr.args.round_idx) == 1


def test_async_dead_silo_triggers_drain_flush(args_factory):
    """A heartbeat-dead declaration shrinks the online set — the drain
    trigger must re-fire so survivors parked at the frontier are not
    gated forever on the dead silo's never-coming upload."""
    mgr, agg = _mk_async_server(args_factory, "as_unit8", n_clients=3,
                                async_agg=True, async_buffer_k=3,
                                comm_round=50)
    _upload(mgr, 1, client_round=0)
    _upload(mgr, 2, client_round=0)
    assert not agg.folds              # rank 3 still online and active
    with mgr._round_lock:
        mgr.client_online_status[3] = False   # hb monitor declares dead
        mgr._maybe_complete_early()
    assert len(agg.folds) == 1        # drain flushed without rank 3
    assert int(mgr.args.round_idx) == 1


def test_async_missing_delta_ref_is_expired_not_corrupted(args_factory):
    """A compressed upload whose trained-against reference is no longer
    held (version predates a crash-resume) cannot be reconstructed —
    it must be dropped as expired_stale, never decoded against a
    different version's reference (silent corruption) and never
    quarantined."""
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.core.mlops import metrics
    from fedml_tpu.cross_silo.message_define import MyMessage

    mgr, agg = _mk_async_server(args_factory, "as_unit9",
                                async_agg=True, async_buffer_k=4,
                                comm_round=50)
    mgr.args.round_idx = 3            # resumed: no refs for versions < 3
    msg = Message(MyMessage.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, 1, 0)
    msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, 2)
    msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 10.0)
    msg.add_params(MyMessage.MSG_ARG_KEY_WIRE_UPDATE, {"pre_crash": True})
    mgr.handle_message_receive_model_from_client(msg)
    assert not mgr._buffer and not agg.folds
    assert mgr.aggregator.quarantined_this_round == {}
    m = metrics.REGISTRY.collect()["fedml_async_updates_total"]
    assert m.labels(run_id="as_unit9", outcome="expired_stale").value == 1


def test_async_quarantine_exhaustion_aborts_instead_of_hanging(args_factory):
    """When every online silo is parked with an EMPTY buffer and the
    quarantine re-solicit budgets are spent, no admissible upload can
    ever arrive and no flush will release the fleet — the server must
    abort the run cleanly, not hang forever."""
    mgr, agg = _mk_async_server(args_factory, "as_unit7", n_clients=1,
                                async_agg=True, async_buffer_k=99,
                                comm_round=50, admission_control=True,
                                admission_resolicit_max=1)
    agg.admission_control = True
    agg._reject = "non_finite"
    _upload(mgr, 1, client_round=0)       # quarantined → re-solicited
    assert not mgr._finishing
    _upload(mgr, 1, client_round=0)       # budget spent → parked
    assert mgr._finishing, (
        "server parked its only silo with an empty buffer and kept "
        "waiting for a flush that can never come")
    assert not agg.folds and mgr.aggregator.quarantined_this_round


# -- integration: full protocol over INPROC -----------------------------------

def test_async_full_protocol_converges(args_factory):
    m = _run(args_factory(training_type="cross_silo", backend="INPROC",
                          role="simulated", client_num_in_total=3,
                          client_num_per_round=3, comm_round=4,
                          data_scale=0.3, learning_rate=0.1,
                          run_id="as_e2e", async_agg=True,
                          async_buffer_k=2))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


def test_async_with_wire_compression_matches_sync(args_factory):
    """int8 delta compression under async folding: equal-accuracy check
    against the plain sync run (quantize+delta+EF loses ~nothing on this
    workload), plus the ≥4x uplink byte reduction."""
    from fedml_tpu.core.mlops import metrics

    common = dict(training_type="cross_silo", backend="INPROC",
                  role="simulated", client_num_in_total=3,
                  client_num_per_round=3, comm_round=3, data_scale=0.3,
                  learning_rate=0.1)
    sync = _run(args_factory(run_id="as_wc_sync", **common))
    comp = _run(args_factory(run_id="as_wc_async", async_agg=True,
                             async_buffer_k=3, wire_compression="int8",
                             **common))
    assert np.isfinite(comp["test_loss"])
    assert abs(sync["test_acc"] - comp["test_acc"]) < 0.15
    wb = metrics.REGISTRY.collect()["fedml_wire_bytes_total"]
    raw_up = wb.labels(run_id="as_wc_sync", direction="up",
                       codec="raw").value
    int8_up = wb.labels(run_id="as_wc_async", direction="up",
                        codec="int8").value
    assert int8_up > 0 and raw_up > 0
    # int8 payload ≈ ¼ of f32 (+ scales); both runs ship 9 uploads
    assert raw_up / int8_up > 3.0, (raw_up, int8_up)


# -- chaos soak: WAN straggler (slow tier, runs in CI async-soak step) --------

def _register_wan_backend(name, straggler_rank, latency_scale):
    from fedml_tpu.core.distributed.communication.chaos import (
        chaos_from_profile,
    )
    from fedml_tpu.core.distributed.communication.inprocess import (
        InProcCommManager,
    )
    from fedml_tpu.core.distributed.fedml_comm_manager import (
        register_comm_backend,
    )

    def factory(args, rank=0, size=0):
        inner = InProcCommManager(rank, size, str(args.run_id))
        scale = latency_scale if rank == straggler_rank else 1.0
        return chaos_from_profile(
            inner, "wan-lossy" if rank == straggler_rank else "wan-good",
            seed=100 + rank, latency_scale=scale)

    register_comm_backend(name, factory)


@pytest.mark.slow
def test_async_wan_straggler_soak(args_factory):
    """5 silos, one on wan-lossy at 10x latency: async round progress
    must not be gated by the straggler (wall-clock beats sync under the
    SAME chaos), and the final model must match sync FedAvg within
    tolerance."""
    import threading as _t

    from fedml_tpu.cross_silo.runner import init_client, init_server

    def federate(run_id, backend, **kw):
        args = fedml_tpu.init(args_factory(
            training_type="cross_silo", client_num_in_total=5,
            client_num_per_round=5, comm_round=4, data_scale=0.3,
            learning_rate=0.1, run_id=run_id, reliable=True,
            reliable_retx_initial_s=0.2, reliable_retx_max_s=1.0,
            frequency_of_the_test=1, **kw))
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        server = init_server(args, dataset, bundle, backend=backend)
        clients = [init_client(args, dataset, bundle, rank, backend=backend)
                   for rank in range(1, 6)]
        threads = [_t.Thread(target=c.run, daemon=True) for c in clients]
        t0 = time.monotonic()
        for th in threads:
            th.start()
        server.run()
        wall = time.monotonic() - t0
        for th in threads:
            th.join(timeout=30)
        return server.aggregator.metrics_history[-1], wall

    _register_wan_backend("WAN_SOAK_SYNC", straggler_rank=5,
                          latency_scale=10.0)
    _register_wan_backend("WAN_SOAK_ASYNC", straggler_rank=5,
                          latency_scale=10.0)
    # clean sync baseline for the accuracy bar (no chaos, plain INPROC)
    clean, _ = federate("soak_clean", "INPROC")
    sync_m, sync_wall = federate("soak_sync", "WAN_SOAK_SYNC",
                                 round_timeout_s=8.0,
                                 min_clients_per_round=3)
    async_m, async_wall = federate(
        "soak_async", "WAN_SOAK_ASYNC", async_agg=True, async_buffer_k=3,
        async_flush_s=2.0, async_staleness="poly:0.5",
        wire_compression="int8")
    assert np.isfinite(async_m["test_loss"])
    # round progress is not gated by the slowest link: the async run's
    # rounds complete faster than the sync run's under identical chaos
    assert async_wall < sync_wall, (async_wall, sync_wall)
    # equal final accuracy within tolerance (both vs the clean baseline)
    assert abs(async_m["test_acc"] - clean["test_acc"]) < 0.15, \
        (async_m["test_acc"], clean["test_acc"])
    assert np.isfinite(sync_m["test_loss"])
