"""Whole-program lint: PROTO002/FLOW001/SHARD001/RES001, the package
index, the send/handle graph export, baseline/fingerprint integration."""

from __future__ import annotations

import json
import textwrap

from fedml_tpu.analysis import run_cli, run_lint
from fedml_tpu.analysis.engine import default_root
from fedml_tpu.analysis.findings import fingerprints
from fedml_tpu.analysis.wholeprogram import (
    build_graph,
    index_package,
    to_dot,
    to_json,
)


def _write(tmp_path, relpath: str, source: str):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def _lint(tmp_path, rules):
    return run_lint(root=tmp_path, rule_ids=rules,
                    whole_program=True).findings


def _ids(findings):
    return [f.rule_id for f in findings]


# -- fixture mini-package: a clean two-role protocol --------------------------

BASE_GUARDED = """\
    class Message:
        def __init__(self, mtype, sender, receiver):
            self.mtype = mtype

    class BaseCommManager:
        def __init__(self):
            self.handlers = {}

        def register_message_receive_handler(self, mtype, handler):
            self.handlers[str(mtype)] = handler

        def receive_message(self, mtype, msg):
            handler = self.handlers.get(str(mtype))
            try:
                handler(msg)
            except Exception:
                self.finish()
                raise

        def send_message(self, msg):
            pass

        def finish(self):
            pass
"""

BASE_UNGUARDED = """\
    class Message:
        def __init__(self, mtype, sender, receiver):
            self.mtype = mtype

    class BaseCommManager:
        def __init__(self):
            self.handlers = {}

        def register_message_receive_handler(self, mtype, handler):
            self.handlers[str(mtype)] = handler

        def receive_message(self, mtype, msg):
            self.handlers[str(mtype)](msg)

        def send_message(self, msg):
            pass

        def finish(self):
            pass
"""

DEFINE = """\
    class MyMessage:
        MSG_TYPE_C2S_HELLO = "C2S_HELLO"
        MSG_TYPE_S2C_INIT = "S2C_INIT"
        MSG_TYPE_C2S_UPLOAD = "C2S_UPLOAD"
        MSG_TYPE_S2C_FINISH = "S2C_FINISH"
"""

SERVER = """\
    from .base import BaseCommManager, Message
    from .message_define import MyMessage

    class ServerManager(BaseCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_C2S_HELLO, self.handle_hello)
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_C2S_UPLOAD, self.handle_upload)

        def run(self):
            self.register_message_receive_handlers()

        def handle_hello(self, msg):
            self._send_round(MyMessage.MSG_TYPE_S2C_INIT)

        def _send_round(self, mtype):
            self.send_message(Message(mtype, 0, 1))

        def handle_upload(self, msg):
            self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, 1))
            self.finish()
"""

CLIENT = """\
    from .base import BaseCommManager, Message
    from .message_define import MyMessage

    class ClientManager(BaseCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_S2C_INIT, self.handle_init)
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)

        def run(self):
            self.register_message_receive_handlers()
            self.send_message(Message(MyMessage.MSG_TYPE_C2S_HELLO, 1, 0))

        def handle_init(self, msg):
            self.send_message(Message(MyMessage.MSG_TYPE_C2S_UPLOAD, 1, 0))

        def handle_finish(self, msg):
            self.finish()
"""


def _write_protocol(tmp_path, base=BASE_GUARDED, server=SERVER,
                    client=CLIENT, define=DEFINE):
    _write(tmp_path, "fedml_tpu/proto/__init__.py", "")
    _write(tmp_path, "fedml_tpu/proto/base.py", base)
    _write(tmp_path, "fedml_tpu/proto/message_define.py", define)
    _write(tmp_path, "fedml_tpu/proto/server.py", server)
    _write(tmp_path, "fedml_tpu/proto/client.py", client)


# -- PROTO002: orphan wire traffic --------------------------------------------

def test_proto002_clean_protocol_is_silent(tmp_path):
    _write_protocol(tmp_path)
    assert _lint(tmp_path, ["PROTO002", "FLOW001", "RES001"]) == []


def test_proto002_flags_orphan_send(tmp_path):
    server = SERVER.replace(
        "self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, 1))",
        "self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, 1))\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))")
    _write_protocol(tmp_path, server=server)
    found = _lint(tmp_path, ["PROTO002"])
    assert _ids(found) == ["PROTO002"]
    assert "'S2C_EXTRA'" in found[0].message
    assert "dropped on arrival" in found[0].message
    assert found[0].path == "fedml_tpu/proto/server.py"


def test_proto002_flags_orphan_handler(tmp_path):
    client = CLIENT.replace(
        "self.register_message_receive_handler(\n"
        "                MyMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)",
        "self.register_message_receive_handler(\n"
        "                MyMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)\n"
        "            self.register_message_receive_handler(\n"
        "                'S2C_NEVER_SENT', self.handle_finish)")
    _write_protocol(tmp_path, client=client)
    found = _lint(tmp_path, ["PROTO002"])
    assert _ids(found) == ["PROTO002"]
    assert "'S2C_NEVER_SENT'" in found[0].message
    assert "no code path ever sends" in found[0].message


def test_proto002_noqa_on_send_line(tmp_path):
    server = SERVER.replace(
        "self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, 1))",
        "self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, 1))\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))"
        "  # fedml: noqa[PROTO002] — consumed by an external native client")
    _write_protocol(tmp_path, server=server)
    res = run_lint(root=tmp_path, rule_ids=["PROTO002"], whole_program=True)
    assert res.findings == [] and res.suppressed == 1


def test_proto002_dynamic_registration_withholds_orphan_send(tmp_path):
    # a handler registered with an unresolvable type could accept anything:
    # the orphan-send verdict must be withheld, not guessed
    client = CLIENT.replace(
        "def run(self):",
        "def register_dynamic(self, mtype):\n"
        "            self.register_message_receive_handler(mtype, "
        "self.handle_finish)\n\n"
        "        def run(self):")
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_MYSTERY', 0, 1))")
    _write_protocol(tmp_path, server=server, client=client)
    assert _lint(tmp_path, ["PROTO002"]) == []


def test_proto002_counts_sends_from_pure_sender_code(tmp_path):
    # a helper class with no registrations and a top-level driver function
    # both feed handlers — neither may leave the handler "dead"
    _write(tmp_path, "fedml_tpu/proto/driver.py", """\
        from .base import Message

        class Announcer:
            def announce(self, mgr):
                mgr.send_message(Message("S2C_INIT", 0, 1))

        def kick_off(mgr):
            mgr.send_message(Message("S2C_FINISH", 0, 1))
    """)
    server = SERVER.replace(
        "self._send_round(MyMessage.MSG_TYPE_S2C_INIT)", "pass").replace(
        "self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, 1))",
        "pass")
    _write_protocol(tmp_path, server=server)
    # the client's S2C_INIT/S2C_FINISH handlers are fed only by the
    # pure-sender module — no orphan-handler (or liveness) false positive
    assert _lint(tmp_path, ["PROTO002", "FLOW001"]) == []


def test_param_bound_sends_are_not_duplicated(tmp_path):
    # two Message(<param>) sites in one helper, one call site: the bound
    # emission must appear once, not once per site
    server = SERVER.replace(
        "def _send_round(self, mtype):\n"
        "            self.send_message(Message(mtype, 0, 1))",
        "def _send_round(self, mtype):\n"
        "            self.send_message(Message(mtype, 0, 1))\n"
        "            self.send_message(Message(mtype, 0, 2))")
    orphan = server.replace(
        "self._send_round(MyMessage.MSG_TYPE_S2C_INIT)",
        "self._send_round('S2C_ORPHANED')")
    _write_protocol(tmp_path, server=orphan)
    found = [f for f in _lint(tmp_path, ["PROTO002"])
             if "S2C_ORPHANED" in f.message]
    assert len(found) == 1


def test_bound_helper_in_pure_sender_class_keeps_verdicts(tmp_path):
    # a NON-manager helper class using the bound Message(<param>) idiom is
    # fully resolvable — it must not count as a dynamic send and disable
    # orphan-handler verdicts package-wide
    _write(tmp_path, "fedml_tpu/proto/helper.py", """\
        from .base import Message

        class Pinger:
            def start(self, mgr):
                self._send(mgr, "C2S_HELLO")

            def _send(self, mgr, mtype):
                mgr.send_message(Message(mtype, 0, 1))
    """)
    client = CLIENT.replace(
        "self.register_message_receive_handler(\n"
        "                MyMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)",
        "self.register_message_receive_handler(\n"
        "                MyMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)\n"
        "            self.register_message_receive_handler(\n"
        "                'S2C_DEAD', self.handle_finish)")
    _write_protocol(tmp_path, client=client)
    found = _lint(tmp_path, ["PROTO002"])
    assert _ids(found) == ["PROTO002"]
    assert "'S2C_DEAD'" in found[0].message


def test_paths_subset_uses_full_package_index(tmp_path):
    # cross-file verdicts need the whole program: linting ONE role of a
    # clean protocol must not call its counterpart's traffic orphaned
    _write_protocol(tmp_path)
    res = run_lint(root=tmp_path, paths=["fedml_tpu/proto/server.py"],
                   rule_ids=["PROTO002", "FLOW001"])
    assert res.findings == []
    assert res.files_scanned == 1
    # and findings elsewhere in the package are filtered to the subset
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))")
    _write(tmp_path, "fedml_tpu/proto/server.py", textwrap.dedent(server))
    hit = run_lint(root=tmp_path, paths=["fedml_tpu/proto/server.py"],
                   rule_ids=["PROTO002"]).findings
    assert _ids(hit) == ["PROTO002"]
    quiet = run_lint(root=tmp_path, paths=["fedml_tpu/proto/client.py"],
                     rule_ids=["PROTO002"]).findings
    assert quiet == []


def test_full_scan_skips_crossfile_verdicts_when_a_file_breaks(tmp_path):
    # full scan with a syntax-broken counterpart: the LINT001 fails the
    # run, but NO false cross-file verdicts may appear (they would even
    # poison the baseline via --update-baseline), and the skip is said
    _write_protocol(tmp_path)
    _write(tmp_path, "fedml_tpu/proto/client.py", "def broken(:\n")
    res = run_lint(root=tmp_path, whole_program=True,
                   rule_ids=["PROTO002", "FLOW001", "RES001"])
    assert _ids(res.findings) == ["LINT001"]
    assert any("cross-file rules skipped" in n for n in res.notes)
    lines = []
    assert run_cli(root=str(tmp_path), whole_program=True, fmt="json",
                   echo=lines.append) == 1
    report = json.loads("\n".join(lines))
    assert any("cross-file rules skipped" in n for n in report["notes"])
    assert not any(f["rule"].startswith(("PROTO002", "FLOW001"))
                   for f in report["findings"])


def test_update_baseline_refused_when_scan_is_incomplete(tmp_path):
    # rewriting the SHARED baseline from a scan whose cross-file pass was
    # skipped would silently drop every cross-file entry
    _write_protocol(tmp_path)
    _write(tmp_path, "fedml_tpu/proto/broken.py", "def broken(:\n")
    lines = []
    assert run_cli(root=str(tmp_path), whole_program=True,
                   update_baseline=True, echo=lines.append) == 2
    assert not (tmp_path / ".fedml-lint-baseline.json").exists()
    assert any("incomplete" in line for line in lines)


def test_graph_goes_conservative_on_unparsable_files(tmp_path):
    # a broken file hides its handlers; the graph must not paint the
    # now-unmatched traffic red (PROTO002 withholds those verdicts too)
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))")
    _write_protocol(tmp_path, server=server)
    _write(tmp_path, "fedml_tpu/proto/broken.py", "def broken(:\n")
    g = build_graph(index_package(tmp_path))
    assert g["orphan_sends"] == [] and g["orphan_handlers"] == []
    assert any("could not be parsed" in n for n in g["notes"])
    assert "// 1 file(s) could not be parsed" in to_dot(g)


def test_paths_subset_stays_silent_when_counterpart_is_unparsable(tmp_path):
    # a syntax-broken counterpart file hides its handlers from the index;
    # emitting orphan verdicts for the subset would be guessing — the
    # full scan reports the LINT001 and the cross-file findings together
    _write_protocol(tmp_path)
    _write(tmp_path, "fedml_tpu/proto/client.py", "def broken(:\n")
    res = run_lint(root=tmp_path, paths=["fedml_tpu/proto/server.py"],
                   rule_ids=["PROTO002", "FLOW001"])
    assert res.findings == []


def test_wp_rule_id_auto_enables_whole_program(tmp_path):
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))")
    _write_protocol(tmp_path, server=server)
    found = run_lint(root=tmp_path, rule_ids=["PROTO002"]).findings
    assert _ids(found) == ["PROTO002"]


def test_default_run_skips_whole_program_rules(tmp_path):
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))")
    _write_protocol(tmp_path, server=server)
    found = run_lint(root=tmp_path).findings
    assert "PROTO002" not in _ids(found)


# -- FLOW001: protocol liveness -----------------------------------------------

def test_flow001_clean_handshake_through_param_binding(tmp_path):
    # S2C_INIT is only ever sent as Message(<param>) inside _send_round;
    # liveness must bind it at the handle_hello call site, or the clean
    # protocol would be a false positive
    _write_protocol(tmp_path)
    assert _lint(tmp_path, ["FLOW001"]) == []


STALLED_SERVER = """\
    from .base import BaseCommManager, Message

    class ServerManager(BaseCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler("C2S_DONE", self.on_done)

        def run(self):
            self.register_message_receive_handlers()

        def on_done(self, msg):
            self.send_message(Message("S2C_GO", 0, 1))
            self.finish()
"""

STALLED_CLIENT = """\
    from .base import BaseCommManager, Message

    class ClientManager(BaseCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler("S2C_GO", self.on_go)

        def run(self):
            self.register_message_receive_handlers()

        def on_go(self, msg):
            self.send_message(Message("C2S_DONE", 1, 0))
            self.finish()
"""


def test_flow001_flags_deadlocked_init(tmp_path):
    # each side waits for the other to move first: every send site exists,
    # none is reachable from run() — the classic stalled handshake
    _write_protocol(tmp_path, server=STALLED_SERVER, client=STALLED_CLIENT)
    found = _lint(tmp_path, ["FLOW001"])
    assert _ids(found) == ["FLOW001", "FLOW001"]
    assert all("unreachable from the init handshake" in f.message
               for f in found)


def test_flow001_finish_unreachable_gets_termination_message(tmp_path):
    # nothing ever sends S2C_INIT, so the client's upload (and with it the
    # server's FINISH broadcast) can never happen
    client = CLIENT.replace(
        'self.send_message(Message(MyMessage.MSG_TYPE_C2S_HELLO, 1, 0))',
        "pass")
    server = SERVER.replace(
        "self._send_round(MyMessage.MSG_TYPE_S2C_INIT)", "pass")
    _write_protocol(tmp_path, server=server, client=client)
    found = _lint(tmp_path, ["FLOW001"])
    msgs = " | ".join(f.message for f in found)
    assert "rounds can never finish" in msgs
    assert any(f.rule_id == "FLOW001" for f in found)


def test_flow001_inherited_handler_is_not_a_stall(tmp_path):
    # the FINISH handler method lives on the BASE class, so it never
    # appears in the subclass's method table; the verdict must key on the
    # wire value being reachably sent, not on handler activation
    base = BASE_GUARDED.replace(
        "        def finish(self):\n            pass",
        "        def finish(self):\n            pass\n\n"
        "        def on_finish_msg(self, msg):\n            self.finish()")
    client = CLIENT.replace(
        "MyMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)",
        "MyMessage.MSG_TYPE_S2C_FINISH, self.on_finish_msg)")
    _write_protocol(tmp_path, base=base, client=client)
    assert _lint(tmp_path, ["FLOW001"]) == []


def test_keyword_bound_handler_registration_counts(tmp_path):
    client = CLIENT.replace(
        "self.register_message_receive_handler(\n"
        "                MyMessage.MSG_TYPE_S2C_INIT, self.handle_init)",
        "self.register_message_receive_handler(\n"
        "                MyMessage.MSG_TYPE_S2C_INIT, "
        "handler=self.handle_init)")
    _write_protocol(tmp_path, client=client)
    # S2C_INIT is handled (keyword-bound) — no orphan send, no stall, and
    # the class still counts as a manager for the lifecycle checks
    assert _lint(tmp_path, ["PROTO002", "FLOW001", "RES001"]) == []


def test_keyword_message_construction_counts_as_send(tmp_path):
    # Message(type=X, ...) is legal against the runtime ctor — it must
    # feed the handler, not leave it "dead"
    client = CLIENT.replace(
        "self.send_message(Message(MyMessage.MSG_TYPE_C2S_UPLOAD, 1, 0))",
        "self.send_message(Message(type=MyMessage.MSG_TYPE_C2S_UPLOAD, "
        "sender_id=1, receiver_id=0))")
    _write_protocol(tmp_path, client=client)
    assert _lint(tmp_path, ["PROTO002", "FLOW001"]) == []


def test_fully_keyword_bound_registration_counts(tmp_path):
    client = CLIENT.replace(
        "self.register_message_receive_handler(\n"
        "                MyMessage.MSG_TYPE_S2C_INIT, self.handle_init)",
        "self.register_message_receive_handler(\n"
        "                msg_type=MyMessage.MSG_TYPE_S2C_INIT, "
        "handler=self.handle_init)")
    _write_protocol(tmp_path, client=client)
    assert _lint(tmp_path, ["PROTO002", "FLOW001", "RES001"]) == []


ASYNC_DEFINE = """\
    class MyMessage:
        MSG_TYPE_C2S_HELLO = "C2S_HELLO"
        MSG_TYPE_S2C_INIT = "S2C_INIT"
        MSG_TYPE_S2C_SYNC = "S2C_SYNC"
        MSG_TYPE_C2S_UPLOAD = "C2S_UPLOAD"
        MSG_TYPE_S2C_FINISH = "S2C_FINISH"
"""

ASYNC_SERVER = """\
    from .base import BaseCommManager, Message
    from .message_define import MyMessage

    class AsyncServerManager(BaseCommManager):
        def __init__(self):
            super().__init__()
            self.buffer = []
            self.version = 0

        def register_message_receive_handlers(self):
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_C2S_HELLO, self.handle_hello)
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_C2S_UPLOAD, self.handle_upload)

        def run(self):
            self.register_message_receive_handlers()

        def handle_hello(self, msg):
            self.send_message(Message(MyMessage.MSG_TYPE_S2C_INIT, 0, 1))

        def handle_upload(self, msg):
            self.buffer.append(msg)
            if len(self.buffer) >= 2:
                self._flush()
            else:
                self.send_message(Message(MyMessage.MSG_TYPE_S2C_SYNC, 0, 1))

        def _flush(self):
            self.buffer = []
            self.version += 1
            self.send_message(Message(MyMessage.MSG_TYPE_S2C_FINISH, 0, 1))
            self.finish()
"""

ASYNC_CLIENT = """\
    from .base import BaseCommManager, Message
    from .message_define import MyMessage

    class AsyncClientManager(BaseCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_S2C_INIT, self.handle_dispatch)
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_S2C_SYNC, self.handle_dispatch)
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_S2C_FINISH, self.handle_finish)

        def run(self):
            self.register_message_receive_handlers()
            self.send_message(Message(MyMessage.MSG_TYPE_C2S_HELLO, 1, 0))

        def handle_dispatch(self, msg):
            self.send_message(Message(MyMessage.MSG_TYPE_C2S_UPLOAD, 1, 0))

        def handle_finish(self, msg):
            self.finish()
"""


def test_flow001_buffered_async_rounds_reach_finish(tmp_path):
    # the buffered-async message shape: the server answers each upload
    # with the next dispatch (no per-round barrier) and only the flush
    # path emits FINISH — the liveness FSM must see FINISH as reachable
    # through the fold → flush chain, not flag the buffered loop as a
    # stall
    _write_protocol(tmp_path, base=BASE_GUARDED, server=ASYNC_SERVER,
                    client=ASYNC_CLIENT, define=ASYNC_DEFINE)
    assert _lint(tmp_path, ["PROTO002", "FLOW001", "RES001"]) == []


def test_flow001_flags_async_flush_that_never_finishes(tmp_path):
    # regression guard for the FSM: a buffered server that re-dispatches
    # forever and never reaches its flush (the only FINISH emitter) is a
    # liveness bug, buffered or not — the flush method EXISTS, so this is
    # FLOW001's unreachable-send verdict, not PROTO002's orphan verdict
    server = ASYNC_SERVER.replace(
        "            if len(self.buffer) >= 2:\n"
        "                self._flush()\n"
        "            else:\n"
        "                self.send_message("
        "Message(MyMessage.MSG_TYPE_S2C_SYNC, 0, 1))",
        "            self.send_message("
        "Message(MyMessage.MSG_TYPE_S2C_SYNC, 0, 1))")
    _write_protocol(tmp_path, base=BASE_GUARDED, server=server,
                    client=ASYNC_CLIENT, define=ASYNC_DEFINE)
    found = _lint(tmp_path, ["FLOW001"])
    msgs = " | ".join(f.message for f in found)
    assert "rounds can never finish" in msgs


def test_flow001_noqa(tmp_path):
    _write_protocol(tmp_path, server=STALLED_SERVER,
                    client=STALLED_CLIENT.replace(
                        'self.register_message_receive_handler('
                        '"S2C_GO", self.on_go)',
                        'self.register_message_receive_handler('
                        '"S2C_GO", self.on_go)'
                        '  # fedml: noqa[FLOW001] — driven by an ops tool'))
    found = _lint(tmp_path, ["FLOW001"])
    # only the server-side registration is still flagged
    assert len(found) == 1 and found[0].path.endswith("server.py")


# -- SHARD001: PartitionSpec/mesh contracts -----------------------------------

SHARD_OK = """\
    from functools import partial

    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    AXIS_MODEL = "model"

    def build(devs):
        return Mesh(devs, axis_names=("data", "model"))

    def good_spec():
        return P(None, "model")

    def wrap(mesh):
        spec = P("data")

        @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                 out_specs=spec)
        def attn(q, k, v):
            return q
        return attn
"""


def test_shard001_clean_module_is_silent(tmp_path):
    _write(tmp_path, "fedml_tpu/parallel/mod.py", SHARD_OK)
    assert _lint(tmp_path, ["SHARD001"]) == []


def test_shard001_flags_undeclared_axis(tmp_path):
    _write(tmp_path, "fedml_tpu/parallel/mod.py",
           SHARD_OK.replace('P(None, "model")', 'P(None, "modle")'))
    found = _lint(tmp_path, ["SHARD001"])
    assert _ids(found) == ["SHARD001"]
    assert "'modle'" in found[0].message


def test_shard001_axis_check_scoped_to_sharded_layers(tmp_path):
    # the same typo outside parallel//train/llm//ml/engine is not scanned
    _write(tmp_path, "fedml_tpu/data/mod.py",
           SHARD_OK.replace('P(None, "model")', 'P(None, "modle")'))
    assert _lint(tmp_path, ["SHARD001"]) == []


def test_shard001_flags_in_specs_arity_mismatch(tmp_path):
    _write(tmp_path, "fedml_tpu/parallel/mod.py",
           SHARD_OK.replace("in_specs=(spec, spec, spec)",
                            "in_specs=(spec, spec)"))
    found = _lint(tmp_path, ["SHARD001"])
    assert _ids(found) == ["SHARD001"]
    assert "2 entries" in found[0].message and "3 positional" \
        in found[0].message


def test_shard001_single_spec_broadcast_is_legal(tmp_path):
    # in_specs=P(...) is a pytree PREFIX that broadcasts over all args —
    # no arity conclusion may be drawn from it
    _write(tmp_path, "fedml_tpu/parallel/mod.py",
           SHARD_OK.replace("in_specs=(spec, spec, spec)",
                            'in_specs=P("data")'))
    assert _lint(tmp_path, ["SHARD001"]) == []


def test_shard001_flags_donate_past_in_shardings(tmp_path):
    _write(tmp_path, "fedml_tpu/train/llm/mod.py", """\
        import jax

        def jit_it(fn, x_sh):
            return jax.jit(fn, donate_argnums=(2,),
                           in_shardings=(x_sh, x_sh))
    """)
    found = _lint(tmp_path, ["SHARD001"])
    assert _ids(found) == ["SHARD001"]
    assert "donate_argnums=2" in found[0].message


def test_shard001_noqa(tmp_path):
    _write(tmp_path, "fedml_tpu/parallel/mod.py",
           SHARD_OK.replace(
               'P(None, "model")',
               'P(None, "modle")  # fedml: noqa[SHARD001] — axis added '
               'by the caller\'s mesh'))
    res = run_lint(root=tmp_path, rule_ids=["SHARD001"], whole_program=True)
    assert res.findings == [] and res.suppressed == 1


# -- RES001: resource lifecycle -----------------------------------------------

def test_res001_flags_unjoined_nondaemon_thread(tmp_path):
    _write(tmp_path, "fedml_tpu/svc.py", """\
        import threading

        def leak():
            t = threading.Thread(target=print)
            t.start()
    """)
    found = _lint(tmp_path, ["RES001"])
    assert _ids(found) == ["RES001"]
    assert "neither daemonized nor joined" in found[0].message


def test_res001_silent_when_daemonized_or_joined(tmp_path):
    _write(tmp_path, "fedml_tpu/svc.py", """\
        import threading

        def ok_daemon():
            t = threading.Thread(target=print, daemon=True)
            t.start()

        def ok_joined():
            t2 = threading.Thread(target=print)
            t2.start()
            t2.join()

        def ok_attr():
            worker = threading.Thread(target=print)
            worker.daemon = True
            worker.start()
    """)
    assert _lint(tmp_path, ["RES001"]) == []


def test_res001_flags_manager_without_finish(tmp_path):
    _write(tmp_path, "fedml_tpu/mgr.py", """\
        class NoExitManager:
            def register_message_receive_handlers(self):
                self.register_message_receive_handler("GO", self.on_go)

            def on_go(self, msg):
                pass
    """)
    found = _lint(tmp_path, ["RES001"])
    assert _ids(found) == ["RES001"]
    assert "never calls finish()" in found[0].message


def test_res001_flags_handler_raise_with_unguarded_base(tmp_path):
    server = SERVER.replace(
        "def handle_upload(self, msg):",
        "def handle_upload(self, msg):\n"
        "            if msg is None:\n"
        "                raise RuntimeError('bad upload')")
    _write_protocol(tmp_path, base=BASE_UNGUARDED, server=server)
    found = _lint(tmp_path, ["RES001"])
    assert _ids(found) == ["RES001"]
    assert "receive_message" in found[0].message
    assert found[0].path == "fedml_tpu/proto/server.py"


def test_res001_guarded_base_silences_handler_raises(tmp_path):
    # with the comm base's dispatch wrapped in try→finish, a raising
    # handler no longer strands peers — the finding must disappear
    server = SERVER.replace(
        "def handle_upload(self, msg):",
        "def handle_upload(self, msg):\n"
        "            if msg is None:\n"
        "                raise RuntimeError('bad upload')")
    _write_protocol(tmp_path, base=BASE_GUARDED, server=server)
    assert _lint(tmp_path, ["RES001"]) == []


def test_res001_noqa(tmp_path):
    _write(tmp_path, "fedml_tpu/svc.py", """\
        import threading

        def leak():
            t = threading.Thread(target=print)  # fedml: noqa[RES001] — ref
            t.start()
    """)
    res = run_lint(root=tmp_path, rule_ids=["RES001"], whole_program=True)
    assert res.findings == [] and res.suppressed == 1


# -- baseline ratchet + fingerprint stability ---------------------------------

def test_whole_program_findings_share_the_baseline_ratchet(tmp_path):
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))")
    _write_protocol(tmp_path, server=server)
    quiet = lambda *_: None  # noqa: E731
    assert run_cli(root=str(tmp_path), whole_program=True,
                   update_baseline=True, echo=quiet) == 0
    assert run_cli(root=str(tmp_path), whole_program=True, echo=quiet) == 0
    # a NEW orphan fails the ratchet; the baselined one stays quiet
    client = CLIENT.replace(
        "def run(self):",
        "def run_extra(self):\n"
        "            self.send_message(Message('C2S_SURPRISE', 1, 0))\n\n"
        "        def run(self):")
    _write(tmp_path, "fedml_tpu/proto/client.py", client)
    out = []
    assert run_cli(root=str(tmp_path), whole_program=True,
                   echo=out.append) == 1
    rendered = "\n".join(out)
    assert "C2S_SURPRISE" in rendered and "S2C_EXTRA" not in rendered


def test_crossfile_fingerprints_stable_under_unrelated_churn(tmp_path):
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))")
    _write_protocol(tmp_path, server=server)
    _write(tmp_path, "fedml_tpu/parallel/mod.py",
           SHARD_OK.replace('P(None, "model")', 'P(None, "modle")'))
    rules = ["PROTO002", "FLOW001", "SHARD001"]
    before = {fp for _, fp in fingerprints(_lint(tmp_path, rules))}
    assert len(before) == 2  # the orphan send + the bad axis
    # line drift in the flagged file + a brand-new unrelated module (which
    # even declares a NEW mesh axis) must not churn a single fingerprint —
    # the committed baseline would break
    sf = tmp_path / "fedml_tpu/proto/server.py"
    sf.write_text("# an unrelated header comment\n\n" + sf.read_text())
    _write(tmp_path, "fedml_tpu/unrelated.py",
           "AXIS_EXTRA = \"extra_axis\"\n\n\ndef helper():\n    return 1\n")
    after = {fp for _, fp in fingerprints(_lint(tmp_path, rules))}
    assert before == after


# -- graph export --------------------------------------------------------------

def test_graph_dot_renders_cross_silo_topology():
    index = index_package(default_root())
    dot = to_dot(build_graph(index))
    assert dot.startswith("digraph send_handle {") and dot.endswith("}")
    assert '"FedMLServerManager"' in dot and '"ClientMasterManager"' in dot
    assert ('"FedMLServerManager" -> "ClientMasterManager" '
            '[label="S2C_INIT_CONFIG"]') in dot
    assert ('"ClientMasterManager" -> "FedMLServerManager" '
            '[label="C2S_SEND_MODEL_TO_SERVER"]') in dot
    # the repo protocol is orphan-free: no red dangling traffic
    assert "no handler" not in dot and "no sender" not in dot


def test_graph_json_schema_and_orphans(tmp_path):
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_EXTRA', 0, 1))")
    _write_protocol(tmp_path, server=server)
    g = build_graph(index_package(tmp_path))
    assert g["version"] == 1 and g["tool"] == "fedml-lint-graph"
    names = {n["name"] for n in g["nodes"]}
    assert {"ServerManager", "ClientManager"} <= names
    roles = {n["name"]: n["role"] for n in g["nodes"]}
    assert roles["ServerManager"] == "server"
    assert roles["ClientManager"] == "client"
    assert g["orphan_sends"] == ["S2C_EXTRA"]
    assert ("no handler" in to_dot(g))
    json.loads(to_json(g))  # round-trips


def test_graph_with_paths_still_indexes_whole_package(tmp_path):
    # --paths narrows what is DISPLAYED, not what is analyzed: the server
    # subset must still show resolved contracts and its counterpart
    _write_protocol(tmp_path)
    lines = []
    assert run_cli(root=str(tmp_path), graph="json",
                   paths=["fedml_tpu/proto/server.py"],
                   echo=lines.append) == 0
    g = json.loads("\n".join(lines))
    names = {n["name"] for n in g["nodes"]}
    assert "ServerManager" in names
    assert "ClientManager" in names  # counterpart of a displayed edge
    assert any(e["value"] == "C2S_HELLO" for e in g["edges"])
    assert g["orphan_sends"] == [] and g["orphan_handlers"] == []


def test_graph_orphans_mirror_proto002_conservatism(tmp_path):
    # one dynamic registration withholds PROTO002's orphan-send verdicts;
    # the graph must not render red traffic the rule will never flag
    client = CLIENT.replace(
        "def run(self):",
        "def register_dynamic(self, mtype):\n"
        "            self.register_message_receive_handler(mtype, "
        "self.handle_finish)\n\n"
        "        def run(self):")
    server = SERVER.replace(
        "self.finish()",
        "self.finish()\n"
        "            self.send_message(Message('S2C_MYSTERY', 0, 1))")
    _write_protocol(tmp_path, server=server, client=client)
    g = build_graph(index_package(tmp_path))
    assert g["orphan_sends"] == []  # matches the withheld PROTO002 verdict


# -- two-tier (hierarchical) message shape ------------------------------------
#
# The geo-distributed hierarchy's protocol tree: silo → region fold →
# WAN flush → global, FINISH flowing back down global → region → silo.
# The regional flush (send_fold) is only reachable through the
# ``set_fold_sink(self.send_fold)`` reference in ``__init__`` — exactly
# the shape the real RegionUplink uses.

HIER_DEFINE = """\
    class HierMsg:
        MSG_TYPE_G2R_SYNC = "G2R_SYNC"
        MSG_TYPE_G2R_FINISH = "G2R_FINISH"
        MSG_TYPE_R2G_FOLD = "R2G_FOLD"
        MSG_TYPE_S2C_SYNC = "S2C_SYNC"
        MSG_TYPE_S2C_FINISH = "S2C_FINISH"
        MSG_TYPE_C2S_UPLOAD = "C2S_UPLOAD"
"""

HIER_GLOBAL = """\
    from .base import BaseCommManager, Message
    from .hier_define import HierMsg

    class GlobalServer(BaseCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler(
                HierMsg.MSG_TYPE_R2G_FOLD, self.on_fold)

        def run(self):
            self.register_message_receive_handlers()
            self.send_message(Message(HierMsg.MSG_TYPE_G2R_SYNC, 0, 1))

        def on_fold(self, msg):
            self.send_message(Message(HierMsg.MSG_TYPE_G2R_FINISH, 0, 1))
            self.finish()
"""

HIER_REGION = """\
    from .base import BaseCommManager, Message
    from .hier_define import HierMsg

    class RegionNode(BaseCommManager):
        def __init__(self):
            super().__init__()
            self.set_fold_sink(self.send_fold)

        def set_fold_sink(self, sink):
            self._sink = sink

        def register_message_receive_handlers(self):
            self.register_message_receive_handler(
                HierMsg.MSG_TYPE_G2R_SYNC, self.on_sync)
            self.register_message_receive_handler(
                HierMsg.MSG_TYPE_C2S_UPLOAD, self.on_upload)
            self.register_message_receive_handler(
                HierMsg.MSG_TYPE_G2R_FINISH, self.on_finish)

        def run(self):
            self.register_message_receive_handlers()

        def on_sync(self, msg):
            self.send_message(Message(HierMsg.MSG_TYPE_S2C_SYNC, 0, 1))

        def on_upload(self, msg):
            self._sink(0)

        def send_fold(self, segment):
            self.send_message(Message(HierMsg.MSG_TYPE_R2G_FOLD, 1, 0))

        def on_finish(self, msg):
            self.send_message(Message(HierMsg.MSG_TYPE_S2C_FINISH, 0, 1))
            self.finish()
"""

HIER_SILO = """\
    from .base import BaseCommManager, Message
    from .hier_define import HierMsg

    class SiloClient(BaseCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler(
                HierMsg.MSG_TYPE_S2C_SYNC, self.on_sync)
            self.register_message_receive_handler(
                HierMsg.MSG_TYPE_S2C_FINISH, self.on_finish)

        def run(self):
            self.register_message_receive_handlers()

        def on_sync(self, msg):
            self.send_message(Message(HierMsg.MSG_TYPE_C2S_UPLOAD, 1, 0))

        def on_finish(self, msg):
            self.finish()
"""


def _write_hier(tmp_path, region=HIER_REGION):
    _write(tmp_path, "fedml_tpu/proto/__init__.py", "")
    _write(tmp_path, "fedml_tpu/proto/base.py", BASE_GUARDED)
    _write(tmp_path, "fedml_tpu/proto/hier_define.py", HIER_DEFINE)
    _write(tmp_path, "fedml_tpu/proto/hier_global.py", HIER_GLOBAL)
    _write(tmp_path, "fedml_tpu/proto/hier_region.py", region)
    _write(tmp_path, "fedml_tpu/proto/hier_silo.py", HIER_SILO)


def test_two_tier_fold_chain_is_live_and_orphan_free(tmp_path):
    # the clean tree reaches FINISH on every tier: G2R_SYNC → S2C_SYNC →
    # C2S_UPLOAD → regional fold → R2G_FOLD over the WAN → G2R_FINISH →
    # S2C_FINISH; every type sent has a handler and vice versa
    _write_hier(tmp_path)
    assert _lint(tmp_path, ["PROTO002", "FLOW001", "RES001"]) == []


def test_two_tier_unreachable_regional_flush_stalls_rounds(tmp_path):
    # sever the sink hookup: send_fold still exists textually (so no
    # PROTO002 orphan) but is unreachable from any init handshake — the
    # WAN fold can never flush and the terminal waits on both lower
    # tiers are dead
    region = HIER_REGION.replace(
        "self.set_fold_sink(self.send_fold)", "pass")
    _write_hier(tmp_path, region=region)
    found = _lint(tmp_path, ["FLOW001"])
    msgs = " | ".join(f.message for f in found)
    assert "rounds can never finish" in msgs
    assert _lint(tmp_path, ["PROTO002"]) == []


def test_graph_cli_modes(tmp_path):
    _write_protocol(tmp_path)
    lines = []
    assert run_cli(root=str(tmp_path), graph="dot",
                   echo=lines.append) == 0
    assert lines and lines[0].startswith("digraph send_handle")
    lines = []
    assert run_cli(root=str(tmp_path), graph="json",
                   echo=lines.append) == 0
    parsed = json.loads("\n".join(lines))
    assert parsed["tool"] == "fedml-lint-graph"
    # a typo'd --paths must error out, not render an empty digraph
    assert run_cli(root=str(tmp_path), graph="dot",
                   paths=["fedml_tpu/tpyo"], echo=lambda *_: None) == 2
    # a './'-prefixed path must match after normalization, not go empty
    lines = []
    assert run_cli(root=str(tmp_path), graph="json",
                   paths=["./fedml_tpu/proto/server.py"],
                   echo=lines.append) == 0
    assert "ServerManager" in {n["name"] for n in
                               json.loads("\n".join(lines))["nodes"]}
    # flags the graph mode would silently ignore are refused instead
    assert run_cli(root=str(tmp_path), graph="dot", update_baseline=True,
                   echo=lambda *_: None) == 2


# -- the repo itself: clean under the committed baseline, inside budget -------

def test_repo_whole_program_clean_under_budget():
    root = default_root()
    code = run_cli(root=str(root), whole_program=True,
                   echo=lambda *_: None)
    assert code == 0, "new unbaselined whole-program findings in the repo"
    res = run_lint(root=root, whole_program=True)
    assert res.duration_s < 60.0
    assert res.files_scanned > 150
