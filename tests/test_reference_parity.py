"""Live convergence-parity audit against the reference's own SP code
(VERDICT round-1 item 4): FedAvg / FedProx / SCAFFOLD on identical bytes,
identical sampling, identical initial weights. Runs
benchmarks/parity_audit.py end-to-end (reference subprocess + fedml_tpu
subprocess per optimizer) with a shortened horizon."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_three_optimizer_parity_vs_reference():
    if not os.path.isdir("/root/reference/python/fedml"):
        pytest.skip("reference checkout not available")
    tmp = os.path.join(REPO, ".data_cache", "parity_ci_out")
    env = dict(os.environ, PARITY_ROUNDS="12", PARITY_CNN_ROUNDS="4",
               PARITY_OUT_DIR=tmp)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "parity_audit.py")],
        capture_output=True, text=True, env=env, timeout=1200)
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-3000:]
    assert "PARITY OK" in out.stdout
    # the numerical-parity window must be exact for every optimizer
    for line in out.stdout.splitlines():
        if "early |d|" in line:
            assert "early |d| = 0.0000" in line, line
