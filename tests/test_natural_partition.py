"""Natural (per-user) federated partitions: LEAF JSON / h5 / npz ingestion,
`fedml_tpu data import`, and end-to-end training over real client keys
(reference `data/fed_shakespeare/data_loader.py:24-90`,
`data/MNIST/data_loader.py:33-66`, dispatch `data/data_loader.py:287-375`)."""

import json
import os

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _write_leaf(root, users, dim=32, classes=10, seq=False, seed=0):
    """Synthetic multi-user LEAF JSON fixture (writers/speakers/users)."""
    rng = np.random.RandomState(seed)
    for split, lo, hi in (("train", 12, 30), ("test", 4, 8)):
        d = os.path.join(root, split)
        os.makedirs(d, exist_ok=True)
        user_data = {}
        nums = []
        for u in users:
            n = rng.randint(lo, hi)
            if seq:
                x = rng.randint(0, classes, size=(n, 20)).tolist()
                y = rng.randint(0, classes, size=(n, 20)).tolist()
            else:
                x = rng.rand(n, dim).round(4).tolist()
                y = rng.randint(0, classes, size=n).tolist()
            user_data[u] = {"x": x, "y": y}
            nums.append(n)
        with open(os.path.join(d, "all_data_0.json"), "w") as f:
            json.dump({"users": list(users), "num_samples": nums,
                       "user_data": user_data}, f)


def test_import_cli_and_natural_load_femnist(tmp_path):
    """femnist-by-writer: LEAF JSON → npz cache → one client per writer."""
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    src = tmp_path / "leaf_femnist"
    users = [f"writer_{i:02d}" for i in range(7)]
    _write_leaf(str(src), users, dim=784, classes=62)
    cache = tmp_path / "cache"

    res = CliRunner().invoke(cli, [
        "data", "import", str(src), "--dataset", "femnist",
        "--cache-dir", str(cache)])
    assert res.exit_code == 0, res.output
    info = json.loads(res.output.strip().splitlines()[-1])
    assert info["users"] == 7 and info["format"] == "leaf"
    assert os.path.exists(info["out"])

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="femnist", model="lr", backend="sp",
        partition_method="natural", data_cache_dir=str(cache),
        client_num_in_total=999,     # must be overridden by user count
        client_num_per_round=3, comm_round=2, epochs=1, batch_size=8,
        learning_rate=0.05, frequency_of_the_test=1,
        enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 7          # natural override
    assert dataset[-1] == 62
    assert set(dataset[5].keys()) == set(range(7))
    sizes = [len(dataset[5][c][1]) for c in range(7)]
    assert min(sizes) >= 12 and len(set(sizes)) > 1  # real per-user skew
    device = fedml_tpu.device.get_device(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])


def _speaker_snippets(rng, n):
    corpus = ("to be or not to be that is the question\n"
              "all the worlds a stage and all the men and women "
              "merely players").split()
    out = []
    for _ in range(n):
        k = int(rng.randint(5, 30))
        words = [corpus[rng.randint(0, len(corpus))] for _ in range(k)]
        out.append(" ".join(words).encode("utf8"))
    return out


def test_natural_shakespeare_speakers_h5(tmp_path):
    """fed_shakespeare-by-speaker from the REFERENCE archive schema:
    `shakespeare_{train,test}.h5` with `examples/<speaker>/snippets` of
    BYTE STRINGS (`fed_shakespeare/data_loader.py:24-47` exactly),
    preprocessed with the TFF char vocab into length-80 next-char pairs."""
    import h5py

    cache = tmp_path
    rng = np.random.RandomState(1)
    speakers = [f"speaker_{i}" for i in range(5)]
    for split in ("train", "test"):
        # the reference's own file name, not a dataset-derived one
        with h5py.File(cache / f"shakespeare_{split}.h5", "w") as h:
            g = h.create_group("examples")
            for s in speakers:
                n = rng.randint(3, 7)
                g.create_group(s).create_dataset(
                    "snippets", data=_speaker_snippets(rng, n))

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="fed_shakespeare", model="rnn", backend="sp",
        data_cache_dir=str(cache), client_num_per_round=2,
        client_num_in_total=5, comm_round=2, epochs=1, batch_size=4,
        learning_rate=0.1, frequency_of_the_test=1,
        enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 5
    assert getattr(args, "natural_users") == speakers
    device = fedml_tpu.device.get_device(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])


def test_natural_stackoverflow_users_parrot(tmp_path):
    """stackoverflow_lr-by-user npz cache on the PARROT path: the
    device-resident gather consumes the natural row map."""
    cache = tmp_path
    rng = np.random.RandomState(2)
    arrs_tr, arrs_te = {}, {}
    for i in range(6):
        u = f"user_{i:03d}"
        n = int(rng.randint(10, 25))
        arrs_tr["x_" + u] = rng.rand(n, 10004).astype(np.float32)
        arrs_tr["y_" + u] = rng.randint(0, 500, size=n)
        arrs_te["x_" + u] = rng.rand(4, 10004).astype(np.float32)
        arrs_te["y_" + u] = rng.randint(0, 500, size=4)
    np.savez(cache / "stackoverflow_lr_train.npz", **arrs_tr)
    np.savez(cache / "stackoverflow_lr_test.npz", **arrs_te)

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="stackoverflow_lr", model="lr", backend="parrot",
        partition_method="natural", data_cache_dir=str(cache),
        client_num_in_total=6, client_num_per_round=3, comm_round=3,
        epochs=1, batch_size=8, learning_rate=0.05,
        frequency_of_the_test=1, enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 6
    # the row map must tile the concatenated global arrays exactly
    rows = np.concatenate([args.client_row_map[c] for c in range(6)])
    assert len(rows) == dataset[0] and len(np.unique(rows)) == dataset[0]
    device = fedml_tpu.device.get_device(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])


def test_natural_method_without_files_raises(tmp_path):
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="cifar10", model="lr", partition_method="natural",
        data_cache_dir=str(tmp_path), enable_tracking=False))
    with pytest.raises(FileNotFoundError, match="natural"):
        fedml_tpu.data.load(args)


def test_refbench_leaf_mnist_roundtrip():
    """The refbench generator's npz mirror loads as a natural partition —
    the byte-identical data both frameworks train on for the parity audit."""
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".data_cache", "refbench")
    if not os.path.exists(os.path.join(cache, "leaf_mnist_train.npz")):
        pytest.skip("refbench data not generated")
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="mnist", model="lr", partition_method="natural",
        data_cache_dir=cache, client_num_per_round=2, comm_round=1,
        batch_size=10, enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 100
    assert dataset[-1] == 10


def _load_ref_module(rel_path, name):
    """Load a reference utils module by FILE (they only import numpy/
    collections/os — no fedml package machinery needed)."""
    import importlib.util

    path = os.path.join("/root/reference/python/fedml", rel_path)
    if not os.path.exists(path):
        pytest.skip(f"reference module not present: {path}")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_shakespeare_preprocess_byte_exact_vs_reference():
    """Our TFF char preprocessing reproduces the reference's own
    `fed_shakespeare/utils.preprocess` + `split` BYTE-EXACTLY on real
    text (field-name drift or vocab drift would show here)."""
    from fedml_tpu.data.tff_text import (
        shakespeare_preprocess,
        split_next_token,
    )

    ref = _load_ref_module("data/fed_shakespeare/utils.py", "ref_shk_utils")
    snippets = [
        "Yonder comes my master, your brother.",
        "To be, or not to be: that is the question!\nWhether 'tis nobler",
        "x" * 200,          # forces multi-chunk padding
        "",                  # empty snippet: bos+eos only
    ]
    ref_seqs = np.asarray(ref.preprocess(list(snippets)))
    ref_x, ref_y = ref.split(ref_seqs)
    ours = shakespeare_preprocess([s.encode("utf8") for s in snippets])
    x, y = split_next_token(ours)
    np.testing.assert_array_equal(ours, ref_seqs)
    np.testing.assert_array_equal(x, ref_x)
    np.testing.assert_array_equal(y, ref_y)


def test_stackoverflow_tokenize_byte_exact_vs_reference(tmp_path):
    """Same for stackoverflow_nwp: word-count vocab + tokenizer match the
    reference's `stackoverflow_nwp/utils.tokenizer` byte-exactly."""
    from fedml_tpu.data.tff_text import (
        stackoverflow_tokenize,
        stackoverflow_word_dict,
    )

    ref = _load_ref_module("data/stackoverflow_nwp/utils.py",
                           "ref_so_utils")
    words = ["the", "to", "a", "how", "python", "error", "code", "use",
             "file", "data"]
    wc_path = tmp_path / "stackoverflow.word_count"
    wc_path.write_text("".join(f"{w} {1000 - i}\n"
                               for i, w in enumerate(words)))

    # point the reference's module-global vocab at the fixture
    ref.word_count_file_path = str(wc_path)
    ref.word_dict = None
    ref.word_list = None
    orig_most_frequent = ref.get_most_frequent_words

    def patched(data_dir, vocab_size=10000):
        return words                   # short fixture vocab

    ref.get_most_frequent_words = patched

    sentences = [
        "how to use python code",
        "the error in a file with data and more unknown words here",
        "a " * 40,                     # truncation past 20 words
        "",
    ]
    ref_rows = np.asarray([ref.tokenizer(s, str(tmp_path))
                           for s in sentences])
    ours = stackoverflow_tokenize(
        [s.encode("utf8") for s in sentences],
        stackoverflow_word_dict(str(wc_path)))
    np.testing.assert_array_equal(ours.reshape(ref_rows.shape), ref_rows)
    ref.get_most_frequent_words = orig_most_frequent


def test_natural_stackoverflow_reference_h5_schema(tmp_path):
    """End to end on the REFERENCE stackoverflow schema:
    stackoverflow_{train,test}.h5 with examples/<user>/tokens byte
    sentences + stackoverflow.word_count beside them → natural partition
    trains (`stackoverflow_nwp/dataset.py` + `utils.py` layout)."""
    import h5py

    cache = tmp_path
    words = ["the", "to", "a", "how", "python", "error", "code", "use"]
    (cache / "stackoverflow.word_count").write_text(
        "".join(f"{w} {100 - i}\n" for i, w in enumerate(words)))
    rng = np.random.RandomState(3)
    users = [f"user_{i}" for i in range(4)]
    for split in ("train", "test"):
        with h5py.File(cache / f"stackoverflow_{split}.h5", "w") as h:
            g = h.create_group("examples")
            for u in users:
                sents = [b" ".join(
                    words[rng.randint(0, len(words))].encode()
                    for _ in range(int(rng.randint(3, 12))))
                    for _ in range(int(rng.randint(4, 9)))]
                g.create_group(u).create_dataset("tokens", data=sents)

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="stackoverflow_nwp", model="rnn", backend="sp",
        partition_method="natural", data_cache_dir=str(cache),
        client_num_in_total=4, client_num_per_round=2, comm_round=2,
        epochs=1, batch_size=4, learning_rate=0.1,
        frequency_of_the_test=1, enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 4
    # x/y are [N, 20] next-token pairs in the 10004-id space
    x0, y0 = dataset[5][0]
    assert x0.shape[1] == 20 and y0.shape[1] == 20
    device = fedml_tpu.device.get_device(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])


def test_data_import_cli_on_reference_h5(tmp_path):
    """`fedml_tpu data import` must consume the reference-named h5 pair
    (shakespeare_train.h5) and emit the npz cache (VERDICT r3 item 8)."""
    import h5py

    from fedml_tpu.data.natural import import_to_cache, read_npz_users

    src = tmp_path / "download"
    src.mkdir()
    rng = np.random.RandomState(5)
    for split in ("train", "test"):
        with h5py.File(src / f"shakespeare_{split}.h5", "w") as h:
            g = h.create_group("examples")
            for s in ("romeo", "juliet", "hamlet"):
                g.create_group(s).create_dataset(
                    "snippets", data=_speaker_snippets(rng, 3))

    cache = tmp_path / "cache"
    out = import_to_cache(str(src), "fed_shakespeare", str(cache), "auto")
    assert out["users"] == 3 and out["format"] == "h5"
    users = read_npz_users(str(cache / "fed_shakespeare_train.npz"))
    assert sorted(users) == ["hamlet", "juliet", "romeo"]
    x, y = users["romeo"]
    assert x.shape[1] == 80 and y.shape[1] == 80          # TFF layout
    np.testing.assert_array_equal(x[:, 1:], y[:, :-1])    # next-char pairs
