"""Natural (per-user) federated partitions: LEAF JSON / h5 / npz ingestion,
`fedml_tpu data import`, and end-to-end training over real client keys
(reference `data/fed_shakespeare/data_loader.py:24-90`,
`data/MNIST/data_loader.py:33-66`, dispatch `data/data_loader.py:287-375`)."""

import json
import os

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _write_leaf(root, users, dim=32, classes=10, seq=False, seed=0):
    """Synthetic multi-user LEAF JSON fixture (writers/speakers/users)."""
    rng = np.random.RandomState(seed)
    for split, lo, hi in (("train", 12, 30), ("test", 4, 8)):
        d = os.path.join(root, split)
        os.makedirs(d, exist_ok=True)
        user_data = {}
        nums = []
        for u in users:
            n = rng.randint(lo, hi)
            if seq:
                x = rng.randint(0, classes, size=(n, 20)).tolist()
                y = rng.randint(0, classes, size=(n, 20)).tolist()
            else:
                x = rng.rand(n, dim).round(4).tolist()
                y = rng.randint(0, classes, size=n).tolist()
            user_data[u] = {"x": x, "y": y}
            nums.append(n)
        with open(os.path.join(d, "all_data_0.json"), "w") as f:
            json.dump({"users": list(users), "num_samples": nums,
                       "user_data": user_data}, f)


def test_import_cli_and_natural_load_femnist(tmp_path):
    """femnist-by-writer: LEAF JSON → npz cache → one client per writer."""
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    src = tmp_path / "leaf_femnist"
    users = [f"writer_{i:02d}" for i in range(7)]
    _write_leaf(str(src), users, dim=784, classes=62)
    cache = tmp_path / "cache"

    res = CliRunner().invoke(cli, [
        "data", "import", str(src), "--dataset", "femnist",
        "--cache-dir", str(cache)])
    assert res.exit_code == 0, res.output
    info = json.loads(res.output.strip().splitlines()[-1])
    assert info["users"] == 7 and info["format"] == "leaf"
    assert os.path.exists(info["out"])

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="femnist", model="lr", backend="sp",
        partition_method="natural", data_cache_dir=str(cache),
        client_num_in_total=999,     # must be overridden by user count
        client_num_per_round=3, comm_round=2, epochs=1, batch_size=8,
        learning_rate=0.05, frequency_of_the_test=1,
        enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 7          # natural override
    assert dataset[-1] == 62
    assert set(dataset[5].keys()) == set(range(7))
    sizes = [len(dataset[5][c][1]) for c in range(7)]
    assert min(sizes) >= 12 and len(set(sizes)) > 1  # real per-user skew
    device = fedml_tpu.device.get_device(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])


def test_natural_shakespeare_speakers_h5(tmp_path):
    """fed_shakespeare-by-speaker from client-keyed h5 (reference
    `fed_shakespeare/data_loader.py` reads examples/<speaker>/snippets)."""
    import h5py

    cache = tmp_path
    rng = np.random.RandomState(1)
    speakers = [f"speaker_{i}" for i in range(5)]
    for split in ("train", "test"):
        with h5py.File(cache / f"fed_shakespeare_{split}.h5", "w") as h:
            g = h.create_group("examples")
            for s in speakers:
                n = rng.randint(6, 14)
                g.create_group(s).create_dataset(
                    "snippets", data=rng.randint(0, 90, size=(n, 20)))

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="fed_shakespeare", model="rnn", backend="sp",
        data_cache_dir=str(cache), client_num_per_round=2,
        client_num_in_total=5, comm_round=2, epochs=1, batch_size=4,
        learning_rate=0.1, frequency_of_the_test=1,
        enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 5
    assert getattr(args, "natural_users") == speakers
    device = fedml_tpu.device.get_device(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])


def test_natural_stackoverflow_users_parrot(tmp_path):
    """stackoverflow_lr-by-user npz cache on the PARROT path: the
    device-resident gather consumes the natural row map."""
    cache = tmp_path
    rng = np.random.RandomState(2)
    arrs_tr, arrs_te = {}, {}
    for i in range(6):
        u = f"user_{i:03d}"
        n = int(rng.randint(10, 25))
        arrs_tr["x_" + u] = rng.rand(n, 10004).astype(np.float32)
        arrs_tr["y_" + u] = rng.randint(0, 500, size=n)
        arrs_te["x_" + u] = rng.rand(4, 10004).astype(np.float32)
        arrs_te["y_" + u] = rng.randint(0, 500, size=4)
    np.savez(cache / "stackoverflow_lr_train.npz", **arrs_tr)
    np.savez(cache / "stackoverflow_lr_test.npz", **arrs_te)

    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="stackoverflow_lr", model="lr", backend="parrot",
        partition_method="natural", data_cache_dir=str(cache),
        client_num_in_total=6, client_num_per_round=3, comm_round=3,
        epochs=1, batch_size=8, learning_rate=0.05,
        frequency_of_the_test=1, enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 6
    # the row map must tile the concatenated global arrays exactly
    rows = np.concatenate([args.client_row_map[c] for c in range(6)])
    assert len(rows) == dataset[0] and len(np.unique(rows)) == dataset[0]
    device = fedml_tpu.device.get_device(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])


def test_natural_method_without_files_raises(tmp_path):
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="cifar10", model="lr", partition_method="natural",
        data_cache_dir=str(tmp_path), enable_tracking=False))
    with pytest.raises(FileNotFoundError, match="natural"):
        fedml_tpu.data.load(args)


def test_refbench_leaf_mnist_roundtrip():
    """The refbench generator's npz mirror loads as a natural partition —
    the byte-identical data both frameworks train on for the parity audit."""
    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".data_cache", "refbench")
    if not os.path.exists(os.path.join(cache, "leaf_mnist_train.npz")):
        pytest.skip("refbench data not generated")
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="mnist", model="lr", partition_method="natural",
        data_cache_dir=cache, client_num_per_round=2, comm_round=1,
        batch_size=10, enable_tracking=False))
    dataset = fedml_tpu.data.load(args)
    assert args.client_num_in_total == 100
    assert dataset[-1] == 10
