"""Multi-client pallas conv kernels (ops/pallas_mc_conv.py) — interpret
mode off-TPU; the on-chip perf verdict lives in benchmarks/BENCH_NOTES.md
round 4 (negative result: XLA's grouped conv wins on v5e)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ops.pallas_mc_conv import conv_for_clients, mc_conv


def _ref(x, w, stride):
    return jax.vmap(lambda xk, wk: jax.lax.conv_general_dilated(
        xk, wk, window_strides=stride, padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC")))(x, w)


CASES = [
    (3, 4, 8, 8, 16, 16, 3, 3, (1, 1)),     # resnet stage-1 class
    (2, 4, 8, 8, 16, 32, 3, 3, (2, 2)),     # stage transition
    (2, 4, 8, 8, 16, 32, 1, 1, (2, 2)),     # 1x1 strided shortcut
    (2, 2, 5, 7, 8, 8, 3, 3, (1, 1)),       # odd spatial dims
    (2, 2, 6, 6, 8, 8, 2, 2, (1, 1)),       # even kernel -> XLA dx path
]


@pytest.mark.parametrize("k,b,h,w_,ci,co,kh,kw,stride", CASES)
def test_mc_conv_forward_matches_lax(k, b, h, w_, ci, co, kh, kw, stride):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((k, b, h, w_, ci)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, kh, kw, ci, co)) * 0.1,
                    jnp.float32)
    out = mc_conv(x, w, stride, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(
        _ref(x, w, stride)), atol=1e-4)


@pytest.mark.parametrize("k,b,h,w_,ci,co,kh,kw,stride", CASES)
def test_mc_conv_grads_match_lax(k, b, h, w_, ci, co, kh, kw, stride):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((k, b, h, w_, ci)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, kh, kw, ci, co)) * 0.1,
                    jnp.float32)
    oh, ow = -(-h // stride[0]), -(-w_ // stride[1])
    g = jnp.asarray(rng.standard_normal((k, b, oh, ow, co)), jnp.float32)
    dxp, dwp = jax.grad(
        lambda x, w: jnp.sum(mc_conv(x, w, stride, True) * g),
        argnums=(0, 1))(x, w)
    dxr, dwr = jax.grad(
        lambda x, w: jnp.sum(_ref(x, w, stride) * g), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dxp), np.asarray(dxr), atol=1e-3)
    np.testing.assert_allclose(np.asarray(dwp), np.asarray(dwr), atol=1e-3)


def test_dispatcher_xla_path_matches_interpret():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 2, 6, 6, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((2, 3, 3, 8, 8)) * 0.1,
                    jnp.float32)
    a = conv_for_clients(x, w, impl="xla")
    b = conv_for_clients(x, w, impl="interpret")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
