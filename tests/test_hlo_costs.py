"""HLO collective-cost extraction (VERDICT r4 item 3 / r3 #6): the parser
must find the collectives XLA inserts for known sharded programs, with
correct payload bytes, and the summaries must catch structure changes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ml.engine.mesh import build_mesh
from fedml_tpu.utils.hlo_costs import (
    ici_seconds,
    parse_collectives,
    summarize,
    summarize_compiled,
)


def _compile_psum(n):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = build_mesh({"data": n})
    sh = NamedSharding(mesh, P("data"))

    @jax.jit
    def f(x):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))

    x = jax.device_put(jnp.arange(8 * 1024, dtype=jnp.float32)
                       .reshape(8, 1024), sh)
    return jax.jit(lambda a: jnp.sum(a, axis=0)).lower(x).compile()


def test_parse_finds_allreduce_with_bytes():
    compiled = _compile_psum(8)
    s = summarize_compiled(compiled)
    assert s["counts"].get("all-reduce", 0) >= 1, s
    # the reduced row is [1024] f32 = 4096 bytes
    assert s["bytes"]["all-reduce"] >= 4096, s


def test_parse_collectives_from_text():
    txt = """
  %ar = f32[128,256]{1,0} all-reduce(f32[128,256]{1,0} %p), replica_groups={{0,1,2,3}}, to_apply=%add
  %ag.1 = bf16[64]{0} all-gather(bf16[16]{0} %q), replica_groups={{0,1,2,3}}, dimensions={0}
  %cp = f32[4]{0} collective-permute(f32[4]{0} %r), source_target_pairs={{0,1}}
  %add.5 = f32[4]{0} add(f32[4]{0} %a, f32[4]{0} %b)
"""
    recs = parse_collectives(txt)
    ops = sorted(r["op"] for r in recs)
    assert ops == ["all-gather", "all-reduce", "collective-permute"]
    ar = next(r for r in recs if r["op"] == "all-reduce")
    assert ar["bytes"] == 128 * 256 * 4
    assert ar["group_size"] == 4
    ag = next(r for r in recs if r["op"] == "all-gather")
    assert ag["bytes"] == 64 * 2
    s = summarize(txt)
    assert s["total_ops"] == 3
    assert s["total_bytes"] == 128 * 256 * 4 + 128 + 16


def test_sharded_train_step_carries_allreduce():
    """The dp train step's gradient sync must show up as all-reduce bytes
    on the order of the model size — the CI tripwire for collective-
    structure regressions."""
    import fedml_tpu
    from fedml_tpu.parallel.sharding import (
        batch_sharding,
        build_sharded_train_step,
    )

    args = fedml_tpu.Config(model="lr", dataset="mnist", batch_size=16,
                            compute_dtype="float32", learning_rate=0.05)
    bundle = fedml_tpu.model.create(args, 10)
    variables = bundle.init_variables(jax.random.PRNGKey(0))
    mesh = build_mesh({"data": 8})
    train_step, init_shardings, tx = build_sharded_train_step(
        bundle, args, mesh, "dp")
    v = jax.device_put(variables, init_shardings(variables))
    opt_state = tx.init(v["params"])
    batch = {"x": jax.device_put(jnp.zeros((16, 784)),
                                 batch_sharding(mesh)),
             "y": jax.device_put(jnp.zeros((16,), jnp.int32),
                                 batch_sharding(mesh)),
             "mask": None}
    with mesh:
        compiled = jax.jit(train_step).lower(
            v, opt_state, batch, jax.random.PRNGKey(1)).compile()
    s = summarize_compiled(compiled)
    assert s["counts"].get("all-reduce", 0) >= 1, s
    # lr model: 784*10 w + 10 b = 7850 f32 params → grad allreduce ≥ 31 KB
    assert s["bytes"]["all-reduce"] >= 7850 * 4, s


def test_ici_seconds_model():
    # 1 GB ring allreduce over 64 chips at 45 GB/s ≈ 2*(63/64)/45 s
    t = ici_seconds(1e9, 64, "all-reduce")
    assert t == pytest.approx(2 * (63 / 64) * 1e9 / 45e9, rel=1e-6)
    assert ici_seconds(1e9, 1) == 0.0
    assert ici_seconds(1e9, 64, "all-gather") < t
