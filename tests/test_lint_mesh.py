"""Mesh-lint tier: SHARD002-SHARD006 collective-flow rules over
mesh-lowered fixture programs (forced 8-device CPU mesh — conftest pins
it), replica-group host-span units, the SHARD004 budget-ratchet
roundtrip, the shared perf+mesh build cache, and the repo-clean smoke
over the real registered mesh variants (<60s)."""

from __future__ import annotations

import importlib.util
import itertools
import textwrap
import time

from fedml_tpu.analysis import run_lint
from fedml_tpu.analysis.engine import default_root
from fedml_tpu.analysis.mesh.budgets import (
    collect_registry_stats,
    load_budgets,
    write_budgets,
)
from fedml_tpu.analysis.mesh.lowering import (
    CollectiveInstr,
    expand_replica_groups,
)

_seq = itertools.count()


def _write(tmp_path, relpath: str, source: str):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def _load(tmp_path, relpath: str = "fedml_tpu/hot.py"):
    name = f"_mesh_fixture_{next(_seq)}"
    spec = importlib.util.spec_from_file_location(name,
                                                  tmp_path / relpath)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lint(tmp_path, reg, rules):
    """SHARD rule ids auto-enable the mesh pass (no mesh=True here —
    that IS the engine integration under test)."""
    return run_lint(root=tmp_path, rule_ids=rules, perf_registry=reg)


def _ids(findings):
    return [f.rule_id for f in findings]


#: fixture prelude: a private registry the test pulls out as REG.  Bare
#: PartitionSpec constraints resolve against the lowering's mesh context.
_PRELUDE = """\
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from fedml_tpu.analysis.mesh import OK_IN, OK_OUT, MeshVariant
    from fedml_tpu.analysis.perf import (
        EntrypointRegistry,
        register_jit_entrypoint,
    )

    REG = EntrypointRegistry()
"""


# -- SHARD002: boundary resharding --------------------------------------------

_RESHARD = """\

    def _factory():
        def step(x):
            return x * 2.0
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((64, 64), jnp.float32),))

    register_jit_entrypoint({noqa}
        "fx/reshard", _factory, donate_argnums=(),
        mesh_variants=(MeshVariant("m", {{"d": 8}}, in_specs=(("d",),),
                                   min_bytes=1024{vkw}),),
        registry=REG)
"""


def _reshard_module(noqa: str = "", vkw: str = "") -> str:
    return _PRELUDE + _RESHARD.format(noqa=noqa, vkw=vkw)


def test_shard002_fires_on_boundary_reshard(tmp_path):
    # sharded in, replicated out (the default): the partitioner must
    # all-gather the computed value right at the boundary
    _write(tmp_path, "fedml_tpu/hot.py", _reshard_module())
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD002"])
    assert _ids(res.findings) == ["SHARD002"]
    assert "all-gather" in res.findings[0].message
    assert "produces the program output" in res.findings[0].message
    assert res.findings[0].path == "fedml_tpu/hot.py"


def test_shard002_silent_when_specs_match(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py",
           _reshard_module(vkw=', out_specs=("d",)'))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD002"])
    assert res.findings == []


def test_shard002_reshard_ok_declares_design(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _reshard_module(
        vkw=", reshard_ok=(OK_OUT,), note='replicated result by design'"))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD002"])
    assert res.findings == []


def test_shard002_noqa_suppresses_at_registration(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _reshard_module(
        noqa="  # fedml: noqa[SHARD002] — boundary gather accepted"))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD002"])
    assert res.findings == [] and res.suppressed == 1


# -- SHARD003: idle-axis replication ------------------------------------------

_REPL = """\

    def _factory():
        def step(x):
            return jnp.sum(x)
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((64, 64), jnp.float32),))

    register_jit_entrypoint({noqa}
        "fx/repl", _factory, donate_argnums=(),
        mesh_variants=(MeshVariant("m", {{"d": 8}}{vkw}),),
        registry=REG)
"""


def _repl_module(noqa: str = "", vkw: str = ", min_bytes=1024") -> str:
    return _PRELUDE + _REPL.format(noqa=noqa, vkw=vkw)


def test_shard003_fires_on_idle_axis_replication(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _repl_module())
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD003"])
    assert _ids(res.findings) == ["SHARD003"]
    assert "fully replicated" in res.findings[0].message
    assert "mesh axis d" in res.findings[0].message


def test_shard003_silent_when_sharded(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _repl_module(
        vkw=', in_specs=(("d",),), min_bytes=1024'))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD003"])
    assert res.findings == []


def test_shard003_replicate_ok_declares_design(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _repl_module(
        vkw=", min_bytes=1024, replicate_ok=(0,),"
            " note='broadcast operand by design'"))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD003"])
    assert res.findings == []


def test_shard003_small_arrays_ignored(tmp_path):
    # 16KiB sits under the default 64KiB bar
    _write(tmp_path, "fedml_tpu/hot.py", _repl_module(vkw=""))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD003"])
    assert res.findings == []


def test_shard003_noqa_suppresses_at_registration(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _repl_module(
        noqa="  # fedml: noqa[SHARD003] — replicated on purpose"))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD003"])
    assert res.findings == [] and res.suppressed == 1


# -- SHARD005: cross-host all-gather in a round loop --------------------------

_LOOP = """\

    def _factory():
        def body(c, _):
            {body}
            return nxt, None
        def step(c):
            out, _ = jax.lax.scan(body, c, None, length=4)
            return out
        return (jax.jit(step),
                (jax.ShapeDtypeStruct((64, 64), jnp.float32),))

    register_jit_entrypoint({noqa}
        "fx/loop", _factory, donate_argnums=(),
        mesh_variants=(MeshVariant("m", {{"d": 8}}, in_specs=(("d",),),
                                   out_specs=("d",), min_bytes=1024),),
        registry=REG)
"""

#: the carry mutates every step, so the gather can NOT hoist out
_GATHERING_BODY = """\
full = jax.lax.with_sharding_constraint(c, P())
            nxt = jax.lax.with_sharding_constraint(full * 1.01, P("d"))"""

_SHARDED_BODY = "nxt = c * 1.01"


def test_shard005_fires_on_cross_host_loop_gather(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py",
           _PRELUDE + _LOOP.format(body=_GATHERING_BODY, noqa=""))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD005"])
    assert _ids(res.findings) == ["SHARD005"]
    assert res.findings[0].severity == "error"
    assert "cross-host all-gather" in res.findings[0].message
    assert "2 hosts" in res.findings[0].message
    assert "inside the round loop" in res.findings[0].message


def test_shard005_silent_when_loop_stays_sharded(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py",
           _PRELUDE + _LOOP.format(body=_SHARDED_BODY, noqa=""))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD005"])
    assert res.findings == []


def test_shard005_noqa_suppresses_at_registration(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py",
           _PRELUDE + _LOOP.format(
               body=_GATHERING_BODY,
               noqa="  # fedml: noqa[SHARD005] — tiny demo loop"))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD005"])
    assert res.findings == [] and res.suppressed == 1


# -- SHARD006: donation lost to sharding mismatch -----------------------------

_DONATE = """\

    def _factory():
        def step(x):
            return x + 1.0
        return (jax.jit(step, donate_argnums=(0,)),
                (jax.ShapeDtypeStruct((64, 64), jnp.float32),))

    register_jit_entrypoint({noqa}
        "fx/donate", _factory, donate_argnums=(0,),
        mesh_variants=(MeshVariant("m", {{"d": 8}}, in_specs=(("d",),),
                                   {outkw}min_bytes=1024),),
        registry=REG)
"""


def test_shard006_fires_on_donation_lost_to_sharding(tmp_path):
    # in-sharded, out-replicated: different per-device layouts, XLA
    # cannot alias, the donation silently buys nothing
    _write(tmp_path, "fedml_tpu/hot.py",
           _PRELUDE + _DONATE.format(noqa="", outkw=""))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD006"])
    assert _ids(res.findings) == ["SHARD006"]
    assert "lost its donation" in res.findings[0].message


def test_shard006_silent_when_out_sharding_matches(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py",
           _PRELUDE + _DONATE.format(noqa="", outkw='out_specs=("d",), '))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD006"])
    assert res.findings == []


def test_shard006_noqa_suppresses_at_registration(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py",
           _PRELUDE + _DONATE.format(
               noqa="  # fedml: noqa[SHARD006] — copy accepted", outkw=""))
    res = _lint(tmp_path, _load(tmp_path).REG, ["SHARD006"])
    assert res.findings == [] and res.suppressed == 1


# -- SHARD004: budget ratchet roundtrip ---------------------------------------

def test_shard004_budget_ratchet_roundtrip(tmp_path):
    _write(tmp_path, "fedml_tpu/hot.py", _reshard_module())
    reg = _load(tmp_path).REG
    # no committed file → missing-entry finding pointing at the generator
    res = _lint(tmp_path, reg, ["SHARD004"])
    assert _ids(res.findings) == ["SHARD004"]
    assert "no committed collective budget" in res.findings[0].message
    assert "fedml_tpu.analysis.mesh.budgets" in res.findings[0].message
    # generate-and-commit (what `python -m ...mesh.budgets` does) → clean
    stats = collect_registry_stats(tmp_path, registry=reg)
    assert set(stats) == {"fx/reshard@m"}
    assert stats["fx/reshard@m"]["total_ops"] >= 1
    write_budgets(tmp_path, stats)
    assert load_budgets(tmp_path) == stats
    res = _lint(tmp_path, reg, ["SHARD004"])
    assert res.findings == []
    # a ratchet below the compiled reality → over-budget finding
    tight = {k: dict(v, total_ops=0) for k, v in stats.items()}
    write_budgets(tmp_path, tight)
    res = _lint(tmp_path, reg, ["SHARD004"])
    assert _ids(res.findings) == ["SHARD004"]
    assert "exceed the committed budget" in res.findings[0].message


# -- replica-group expansion + host-span classification -----------------------

def test_expand_replica_groups_explicit():
    line = "all-gather(...), replica_groups={{0,1},{2,3}}, dims={0}"
    assert expand_replica_groups(line) == [[0, 1], [2, 3]]


def test_expand_replica_groups_iota():
    line = "all-reduce(...), replica_groups=[2,4]<=[8]"
    assert expand_replica_groups(line) == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_expand_replica_groups_iota_transposed():
    # arange(8).reshape(2,4).T.reshape(4,2): pairs spanning both halves
    line = "all-gather(...), replica_groups=[4,2]<=[2,4]T(1,0)"
    assert expand_replica_groups(line) == [[0, 4], [1, 5], [2, 6], [3, 7]]


def _coll(groups):
    return CollectiveInstr(op="all-gather", nbytes=0, groups=groups,
                           computation="c", in_loop=False, name="ag")


def test_hosts_spanned_classification():
    # 4 devices per modeled host: {0..3} host 0, {4..7} host 1
    assert _coll([[0, 1, 2, 3]]).hosts_spanned(4) == 1
    assert _coll([[4, 5, 6, 7]]).hosts_spanned(4) == 1
    assert _coll([[0, 4]]).hosts_spanned(4) == 2
    assert _coll([[0, 1], [2, 7]]).hosts_spanned(4) == 2
    # the whole 8-device mesh on one 8-device host stays intra-host
    assert _coll([[0, 1, 2, 3, 4, 5, 6, 7]]).hosts_spanned(8) == 1
    assert _coll([[0, 1, 2, 3, 4, 5, 6, 7]]).hosts_spanned(4) == 2


# -- engine integration: shared perf+mesh build cache -------------------------

def test_mixed_perf_and_mesh_rules_build_once(tmp_path):
    """A run mixing PERF and SHARD rule ids builds each registered
    factory ONCE (the shared EntrypointBuildCache), not once per tier."""
    _write(tmp_path, "fedml_tpu/hot.py", _PRELUDE + """\

    CALLS = []

    def _factory():
        CALLS.append(1)
        def step(x):
            return x.astype(jnp.bfloat16)       # dtype change: PERF001
        return (jax.jit(step, donate_argnums=(0,)),
                (jax.ShapeDtypeStruct((128, 128), jnp.float32),))

    register_jit_entrypoint(
        "fx/shared", _factory, donate_argnums=(0,),
        mesh_variants=(MeshVariant("m", {"d": 8}, in_specs=(("d",),),
                                   min_bytes=1024),),
        registry=REG)
    """)
    mod = _load(tmp_path)
    res = run_lint(root=tmp_path, rule_ids=["PERF001", "SHARD003"],
                   perf_registry=mod.REG)
    assert "PERF001" in _ids(res.findings)
    assert len(mod.CALLS) == 1, mod.CALLS


# -- repo-clean smoke over the real registry ----------------------------------

def test_repo_mesh_lint_clean_and_fast():
    """Every registered mesh variant (parrot client/batch axes, llm
    fsdp/tp_fsdp, robust agg, async fold, wire decode) lowers
    SPMD-partitioned on the forced 8-device CPU mesh inside the smoke
    budget, and the SHARD rules raise no new findings over the committed
    baseline + budgets."""
    t0 = time.monotonic()
    root = default_root()
    res = run_lint(root=root, rule_ids=[
        "SHARD002", "SHARD003", "SHARD004", "SHARD005", "SHARD006"])
    took = time.monotonic() - t0
    from fedml_tpu.analysis.baseline import (
        DEFAULT_BASELINE_NAME,
        load_baseline,
        partition,
    )

    baseline_p = root / DEFAULT_BASELINE_NAME
    known = load_baseline(baseline_p) if baseline_p.is_file() else {}
    new, _old = partition(res.findings, known)
    assert new == [], [f.render() for f, _ in new]
    assert not res.notes, res.notes
    assert took < 60.0, f"mesh pass took {took:.1f}s (budget 60s)"
    # the registry actually covers the programs the tier exists for
    from fedml_tpu.analysis.perf import load_default_entrypoints

    variants = {
        f"{spec.name}@{v.name}"
        for spec in load_default_entrypoints().entries()
        for v in (spec.mesh_variants or ())
    }
    for expected in ("parrot/fused_round_scan@client_axis",
                     "parrot/fused_round_scan@batch_axis",
                     "parrot/bucketed_round_step@client_axis",
                     "parrot/bucketed_round_step@batch_axis",
                     "llm/train_epoch@fsdp", "llm/train_epoch@tp_fsdp",
                     "agg/robust_trimmed_mean@clients8",
                     "async/aggregate_buffer@clients8",
                     "wire/decode_int8_delta@replicated8"):
        assert expected in variants, sorted(variants)
