"""Test config: run everything on a virtual 8-device CPU mesh so multi-chip
sharding paths are exercised without TPU hardware (SURVEY §4 implication)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# the axon TPU-tunnel sitecustomize force-sets jax_platforms="axon,cpu";
# override it so tests run on the virtual 8-device CPU mesh
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, jax.devices()

import pytest  # noqa: E402

#: the fast CI tier (`pytest -m smoke`, CI target ~3-4 min): one
#: representative file per major subsystem; everything in these files is
#: smoke unless explicitly marked slow.  Measured 3:09-3:37 on this box
#: (141 tests; varies with background load).
_SMOKE_FILES = {
    "test_algorithms.py", "test_sp_simulation.py", "test_parrot.py",
    "test_transports.py", "test_security.py", "test_mpc.py",
    "test_fhe.py", "test_aux_subsystems.py", "test_multiprocess.py",
    "test_lint.py", "test_lint_wholeprogram.py", "test_lint_perf.py",
    "test_lint_mesh.py",
    # test_reliability.py runs in its own dedicated smoke.yml step (like
    # test_observability.py) — listing it here would run the chaos soak
    # twice per CI job; test_aggregation.py likewise runs in the
    # byzantine-soak step (its slow-marked soaks only run there),
    # test_async_agg.py in the async-soak step (wan-lossy straggler
    # soak), and test_fed_llm.py in the fed-llm step (e2e federations +
    # the federated bench guard)
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if (item.fspath.basename in _SMOKE_FILES
                and "slow" not in item.keywords):
            item.add_marker(pytest.mark.smoke)


@pytest.fixture(autouse=True)
def _reset_singletons():
    """Each test gets fresh process-wide singletons."""
    yield
    from fedml_tpu.core.alg_frame.context import Context
    from fedml_tpu.core.dp.fedml_differential_privacy import (
        FedMLDifferentialPrivacy,
    )
    from fedml_tpu.core.security.fedml_attacker import FedMLAttacker
    from fedml_tpu.core.security.fedml_defender import FedMLDefender
    from fedml_tpu.ml.engine.mesh import MeshManager

    Context.reset()
    MeshManager.reset()
    FedMLAttacker._instance = None
    FedMLDefender._instance = None
    FedMLDifferentialPrivacy._instance = None


def make_args(**kw):
    from fedml_tpu.arguments import Config

    base = dict(
        dataset="synthetic",
        model="lr",
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=3,
        epochs=1,
        batch_size=16,
        learning_rate=0.1,
        frequency_of_the_test=1,
        data_scale=0.1,
        enable_tracking=False,
        compute_dtype="float32",
    )
    base.update(kw)
    return Config(**base)


@pytest.fixture
def args_factory():
    return make_args
