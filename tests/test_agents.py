"""Control-plane agent tests (reference `computing/scheduler`: slave/master
runners, launch manager, job monitor, model cards + deploy)."""

import json
import os
import textwrap
import time

import numpy as np
import pytest


def _write_job(tmp_path, name="testjob", job="echo JOB_RAN; echo done",
               bootstrap="echo BOOT"):
    ws = tmp_path / "ws"
    ws.mkdir(exist_ok=True)
    (ws / "hello.txt").write_text("payload")
    jy = tmp_path / "job.yaml"
    jy.write_text(textwrap.dedent(f"""
        workspace: ws
        job_name: {name}
        bootstrap: "{bootstrap}"
        job: "{job}"
    """))
    return str(jy)


def test_master_slave_agent_round_trip(tmp_path):
    """Master builds + uploads the package, dispatches start_train to two
    slave agents over the broker; agents unzip, run with live logs, report
    FINISHED."""
    from fedml_tpu.scheduler.agents import MasterAgent, SlaveAgent

    store = str(tmp_path / "store")
    agents = [SlaveAgent(f"e{i}", channel="t-agents", store_dir=store,
                         heartbeat_s=0.5).start() for i in (1, 2)]
    try:
        master = MasterAgent(channel="t-agents", store_dir=store)
        run_id = master.create_run(_write_job(tmp_path), ["e1", "e2"])
        result = master.wait(run_id, timeout=60)
        assert result["completed"] and result["success"], result
        for edge in ("e1", "e2"):
            st = result["edges"][edge]
            assert st["status"] == "FINISHED"
            log = open(st["log_path"]).read()
            assert "BOOT" in log and "JOB_RAN" in log
    finally:
        for a in agents:
            a.stop()


def test_agent_failed_job_reports_failed(tmp_path):
    from fedml_tpu.scheduler.agents import MasterAgent, SlaveAgent

    store = str(tmp_path / "store")
    agent = SlaveAgent("e9", channel="t-agents2", store_dir=store).start()
    try:
        master = MasterAgent(channel="t-agents2", store_dir=store)
        run_id = master.create_run(
            _write_job(tmp_path, job="exit 3"), ["e9"])
        result = master.wait(run_id, timeout=60)
        assert result["completed"]
        assert result["edges"]["e9"]["status"] == "FAILED"
        assert result["edges"]["e9"]["returncode"] == 3
    finally:
        agent.stop()


def test_agent_stop_train_kills_job(tmp_path):
    from fedml_tpu.scheduler.agents import MasterAgent, SlaveAgent

    store = str(tmp_path / "store")
    agent = SlaveAgent("e5", channel="t-agents3", store_dir=store).start()
    try:
        master = MasterAgent(channel="t-agents3", store_dir=store)
        run_id = master.create_run(
            _write_job(tmp_path, job="sleep 60"), ["e5"])
        time.sleep(1.0)  # let the job start
        master.stop_run(run_id)
        result = master.wait(run_id, timeout=30)
        assert result["completed"]
        assert result["edges"]["e5"]["status"] == "KILLED"
    finally:
        agent.stop()


def test_agent_config_rewrite(tmp_path):
    """start_train overrides rewrite the packaged fedml_config.yaml
    (reference `update_local_fedml_config:225`)."""
    import yaml

    from fedml_tpu.scheduler.agents import MasterAgent, SlaveAgent

    ws = tmp_path / "ws"
    ws.mkdir()
    (ws / "fedml_config.yaml").write_text(
        "data_args:\n  data_cache_dir: /old\ntrain_args:\n  batch_size: 4\n")
    jy = tmp_path / "job.yaml"
    jy.write_text("workspace: ws\njob_name: cfgjob\n"
                  "job: \"cat fedml_config.yaml\"\n")
    store = str(tmp_path / "store")
    agent = SlaveAgent("e7", channel="t-agents4", store_dir=store).start()
    try:
        master = MasterAgent(channel="t-agents4", store_dir=store)
        run_id = master.create_run(str(jy), ["e7"], config_overrides={
            "data_cache_dir": "/new/cache", "batch_size": 16})
        result = master.wait(run_id, timeout=60)
        assert result["success"], result
        log = open(result["edges"]["e7"]["log_path"]).read()
        cfg = yaml.safe_load(log.split("===== job =====")[1])
        assert cfg["data_args"]["data_cache_dir"] == "/new/cache"
        assert cfg["train_args"]["batch_size"] == 16
        assert cfg["agent_args"]["edge_id"] == "e7"
    finally:
        agent.stop()


def test_job_monitor_flips_dead_runs(tmp_path):
    from fedml_tpu.scheduler import local_launcher
    from fedml_tpu.scheduler.job_monitor import JobMonitor

    run_id = "dead_run_test"
    local_launcher.register_run(run_id, "dead", str(tmp_path / "x.log"),
                                pid=99999999)  # definitely not alive
    flipped = JobMonitor().check_once()
    assert any(r["run_id"] == run_id for r in flipped)
    assert local_launcher.get_run(run_id)["status"] == "FAILED"

    probe_calls = []
    mon = JobMonitor()
    mon.register_endpoint("ep1", probe=lambda: False,
                          reset=lambda: probe_calls.append(1))
    mon.check_once()
    assert probe_calls  # unhealthy endpoint got reset


def test_api_local_launch_stop_logs(tmp_path):
    from fedml_tpu import api

    out = api.launch_job(_write_job(tmp_path, name="apijob"))
    assert out["success"] and out["returncode"] == 0
    assert any(r["run_id"] == out["run_id"] for r in api.run_list(50))
    assert "JOB_RAN" in api.run_logs(out["run_id"])
    assert api.run_status(out["run_id"])["status"] == "FINISHED"


def test_api_clusters(tmp_path, monkeypatch):
    from fedml_tpu import api

    monkeypatch.setattr(api, "_CLUSTERS_PATH",
                        str(tmp_path / "clusters.json"))
    api.cluster_create("c1", ["e1", "e2"])
    assert api.cluster_list() == {"c1": ["e1", "e2"]}
    with pytest.raises(ValueError, match="unknown cluster"):
        api.launch_job_on_cluster(_write_job(tmp_path), "nope")
    assert api.cluster_remove("c1") and api.cluster_list() == {}


def test_model_cards_create_package_deploy(tmp_path):
    from fedml_tpu.scheduler.model_cards import ModelCardRegistry

    model_dir = tmp_path / "m"
    model_dir.mkdir()
    rng = np.random.RandomState(0)
    np.savez(model_dir / "model.npz",
             w2=rng.randn(8, 3).astype(np.float32),
             b2=np.zeros(3, np.float32))
    reg = ModelCardRegistry(root=str(tmp_path / "cards"))
    card = reg.create("lin", str(model_dir), metadata={"task": "cls"})
    assert card["name"] == "lin"
    assert [c["name"] for c in reg.list()] == ["lin"]

    zip_path = reg.package("lin", str(tmp_path))
    assert os.path.exists(zip_path)

    ep = reg.deploy("lin")
    try:
        assert ep.ready()
        x = rng.randn(4, 8).astype(np.float32)
        out = ep.predict({"inputs": x.tolist()})
        assert len(out["predictions"]) == 4
        stats = ep.stats()
        assert stats["requests"] >= 1 and stats["success"] >= 1
    finally:
        ep.stop()
    assert reg.delete("lin") and reg.list() == []


def test_model_card_recreate_from_own_file(tmp_path):
    """Re-registering a card from a file inside its own card dir must not
    destroy the file (regression: create() used to rmtree before copying)."""
    from fedml_tpu.scheduler.model_cards import ModelCardRegistry

    model = tmp_path / "model.npz"
    np.savez(model, w=np.eye(3, dtype=np.float32))
    reg = ModelCardRegistry(root=str(tmp_path / "cards"))
    card = reg.create("m", str(model))
    stored = os.path.join(card["path"], "model.npz")
    card2 = reg.create("m", stored)  # bump version from the stored file
    assert os.path.exists(os.path.join(card2["path"], "model.npz"))
    assert card2["version"] != card["version"]


def test_cli_job_cluster_model_groups(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    r = CliRunner().invoke(cli, ["job", "list", "--limit", "3"])
    assert r.exit_code == 0, r.output
    r = CliRunner().invoke(cli, ["model", "zoo"])
    assert r.exit_code == 0 and "resnet56" in r.output
    r = CliRunner().invoke(cli, ["cluster", "list"])
    assert r.exit_code == 0, r.output


def test_compute_resource_db(tmp_path):
    from fedml_tpu.scheduler.resource_db import ComputeResourceDB

    db = ComputeResourceDB(root=str(tmp_path), total_slots=4)
    assert db.report()["total"] == 4
    s1 = db.allocate("runA", 3)
    assert len(s1) == 3
    # not enough left → nothing allocated (atomic)
    assert db.allocate("runB", 2) == []
    assert db.report()["free"] == 1
    assert db.release("runA") == 3
    assert db.report()["free"] == 4
    # stale reclamation
    db.allocate("runC", 2)
    db.conn.execute("UPDATE devices SET allocated_ts = 1.0 "
                    "WHERE run_id='runC'")
    assert db.reclaim_stale(max_age_s=10.0) == 2
    assert db.report()["free"] == 4


def test_agent_rejects_job_when_no_slots(tmp_path):
    from fedml_tpu.scheduler.agents import MasterAgent, SlaveAgent
    from fedml_tpu.scheduler.resource_db import ComputeResourceDB

    import uuid

    edge = f"e11_{uuid.uuid4().hex[:6]}"
    store = str(tmp_path / "store")
    agent = SlaveAgent(edge, channel="t-agents-rs", store_dir=store).start()
    try:
        # exhaust this agent's slots up front
        db = ComputeResourceDB(root=agent.agent_dir)
        db.allocate("hog", len(db.available_slots()))
        master = MasterAgent(channel="t-agents-rs", store_dir=store)
        run_id = master.create_run(_write_job(tmp_path), [edge])
        result = master.wait(run_id, timeout=30)
        st = result["edges"][edge]
        assert st["status"] == "FAILED"
        assert "device slots" in st.get("error", "")
    finally:
        agent.stop()


def test_agent_ota_upgrade_and_replay(tmp_path):
    from fedml_tpu.scheduler.agents import (
        MasterAgent,
        SlaveAgent,
        _topic_start,
        _topic_upgrade,
    )

    import uuid

    edge = f"e12_{uuid.uuid4().hex[:6]}"  # fresh agent dir → fresh version
    store = str(tmp_path / "store")
    agent = SlaveAgent(edge, channel="t-agents-ota", store_dir=store).start()
    try:
        assert agent.version == "0.1.0"
        master = MasterAgent(channel="t-agents-ota", store_dir=store)

        # simulate a start_train arriving DURING an upgrade: set the flag,
        # publish the start, then publish the upgrade
        agent._upgrading = True
        run_id = master.create_run(_write_job(tmp_path), [edge])
        time.sleep(0.3)
        assert agent._replay_buffer, "start_train not buffered"
        agent.broker.publish(_topic_upgrade(edge),
                             json.dumps({"version": "0.2.0"}).encode())
        result = master.wait(run_id, timeout=60)
        assert result["completed"] and result["success"], result
        assert agent.version == "0.2.0"
        # persisted: a fresh agent object reads the upgraded version
        agent2 = SlaveAgent(edge, channel="t-agents-ota-2", store_dir=store)
        assert agent2.version == "0.2.0"
    finally:
        agent.stop()


def test_stop_during_upgrade_cancels_buffered_start(tmp_path):
    import uuid

    from fedml_tpu.scheduler.agents import (
        MasterAgent,
        SlaveAgent,
        _topic_stop,
        _topic_upgrade,
    )

    edge = f"e13_{uuid.uuid4().hex[:6]}"
    store = str(tmp_path / "store")
    agent = SlaveAgent(edge, channel="t-agents-ota2",
                       store_dir=store).start()
    try:
        master = MasterAgent(channel="t-agents-ota2", store_dir=store)
        agent._upgrading = True
        run_id = master.create_run(_write_job(tmp_path), [edge])
        time.sleep(0.2)
        assert agent._replay_buffer
        # cancel while the start is still buffered
        agent.broker.publish(_topic_stop(edge),
                             json.dumps({"run_id": run_id}).encode())
        agent.broker.publish(_topic_upgrade(edge),
                             json.dumps({"version": "9.9.9"}).encode())
        result = master.wait(run_id, timeout=30)
        assert result["edges"][edge]["status"] == "KILLED"
    finally:
        agent.stop()


def test_replica_autoscaler_scales_up_down_with_cooldown():
    from fedml_tpu.scheduler.autoscaler import (
        AutoscalePolicy,
        ReplicaAutoscaler,
    )

    t = [0.0]
    applied = []
    a = ReplicaAutoscaler(
        AutoscalePolicy(min_replicas=1, max_replicas=4,
                        target_latency_s=1.0, target_qps_per_replica=10.0,
                        scale_down_idle_ticks=2, cooldown_s=10.0),
        apply_fn=applied.append, clock=lambda: t[0])

    # overload by qps → jumps to the load-implied size
    assert a.observe(qps=35.0, latency_s=0.5) == 4
    assert applied == [4]
    # cooldown blocks an immediate scale-down
    for _ in range(5):
        a.observe(qps=0.5, latency_s=0.1)
    assert a.replicas == 4
    # after cooldown, sustained idle shrinks ONE step per window
    t[0] = 11.0
    for _ in range(2):
        a.observe(qps=0.5, latency_s=0.1)
    assert a.replicas == 3
    t[0] = 22.0
    a.observe(qps=0.5, latency_s=0.1)
    a.observe(qps=0.5, latency_s=0.1)
    assert a.replicas == 2
    # latency breach alone also scales up (bounded by max)
    t[0] = 40.0
    assert a.observe(qps=1.0, latency_s=5.0) == 3
    # scale-UP is exempt from the cooldown: a breach right after the
    # previous scale event still grows the fleet immediately
    t[0] = 40.5
    assert a.observe(qps=1.0, latency_s=5.0) == 4
    # bounds respected
    assert all(1 <= r <= 4 for r in a.history)


def test_master_matches_edges_by_advertised_resources(tmp_path):
    """Cross-host resource matching (reference launch_manager GPU match):
    the master picks dispatch targets from the fleet's advertised free
    slots instead of an explicit edge list."""
    import time as _time

    from fedml_tpu.scheduler.agents import MasterAgent, SlaveAgent

    channel = "match-test"
    agents = [SlaveAgent(f"m{i}", channel=channel,
                         store_dir=str(tmp_path), heartbeat_s=0.2).start()
              for i in (1, 2, 3)]
    master = MasterAgent(channel=channel, store_dir=str(tmp_path))
    try:
        deadline = _time.time() + 20
        while len(master._fleet) < 3 and _time.time() < deadline:
            _time.sleep(0.05)
        assert set(master._fleet) >= {"m1", "m2", "m3"}

        picked = master.match_edges(num_edges=2, min_free_slots=1)
        assert len(picked) == 2 and set(picked) <= {"m1", "m2", "m3"}

        # an impossible request fails loudly, naming the constraint
        with pytest.raises(RuntimeError, match="resource match failed"):
            master.match_edges(num_edges=2, min_free_slots=10_000)
        with pytest.raises(RuntimeError, match="kind"):
            master.match_edges(num_edges=1, device_kind="h100")

        # end-to-end: create_run with match= instead of edges=
        job = tmp_path / "job.yaml"
        job.write_text(
            "job_name: match-smoke\n"
            "workspace: .\n"
            "job: |\n  python -c \"print('hi from matched edge')\"\n")
        run_id = master.create_run(str(job),
                                   match={"num_edges": 2,
                                          "min_free_slots": 1})
        result = master.wait(run_id, timeout=60)
        done = [e for e, s in result["edges"].items()
                if s.get("status") == "FINISHED"]
        assert len(done) == 2
    finally:
        for a in agents:
            a.stop()


@pytest.mark.slow
def test_http_control_plane_two_process(tmp_path, monkeypatch):
    """VERDICT r3 item 7 end to end, across OS PROCESSES: the control
    plane (MasterAgent + HTTP server) runs in its own process over a real
    TCP MQTT broker; a slave agent joins the fleet in this process; the
    CLI submits via --remote (build package → HTTP upload → MQTT
    dispatch) and the run completes."""
    import subprocess
    import sys

    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli as cli_root
    from fedml_tpu.core.distributed.communication.mqtt_s3.mini_mqtt import (
        MiniMqttBroker,
    )
    from fedml_tpu.scheduler.agents import SlaveAgent

    broker = MiniMqttBroker()
    store = str(tmp_path / "store")
    env = dict(os.environ, FEDML_MQTT_HOST=broker.host,
               FEDML_MQTT_PORT=str(broker.port), JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "fedml_tpu.scheduler.control_plane",
         "--port", "0", "--channel", "cp-agents", "--store-dir", store,
         "--api-key", "sekrit"],
        env=env, stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline()
        url = json.loads(line)["control_plane"]

        monkeypatch.setenv("FEDML_MQTT_HOST", broker.host)
        monkeypatch.setenv("FEDML_MQTT_PORT", str(broker.port))
        agent = SlaveAgent("cp-e1", channel="cp-agents", store_dir=store,
                           heartbeat_s=0.5).start()
        try:
            from fedml_tpu.scheduler.control_plane import ControlPlaneClient

            client = ControlPlaneClient(url, api_key="sekrit")
            assert client.health()["ok"]
            # auth is enforced
            with pytest.raises(RuntimeError, match="401"):
                ControlPlaneClient(url, api_key="wrong").fleet()
            # the heartbeat reaches the control plane's fleet registry
            deadline = time.time() + 20
            while "cp-e1" not in client.fleet() and time.time() < deadline:
                time.sleep(0.3)
            assert "cp-e1" in client.fleet()
            assert client.match(1) == ["cp-e1"]

            res = CliRunner().invoke(cli_root, [
                "launch", _write_job(tmp_path), "--remote", url,
                "--api-key", "sekrit", "--num-edges", "1"])
            assert res.exit_code == 0, res.output
            lines = [json.loads(x) for x in
                     res.output.strip().splitlines()]
            assert lines[0]["run_id"]
            final = lines[1]
            assert final["completed"] and final["success"], final
            st = final["edges"]["cp-e1"]
            assert st["status"] == "FINISHED"
            assert "JOB_RAN" in open(st["log_path"]).read()

            # stop + status surface over HTTP too
            assert client.status(lines[0]["run_id"])["cp-e1"][
                "status"] == "FINISHED"
        finally:
            agent.stop()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        broker.stop()
