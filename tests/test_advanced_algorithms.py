"""FedGKT / FedGAN / TurboAggregate / FedAvg_seq / FedSeg + new zoo/datasets."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


def test_fedgkt(args_factory):
    m = _run(args_factory(federated_optimizer="FedGKT", dataset="mnist",
                          model="cnn", client_num_in_total=3,
                          client_num_per_round=3, comm_round=6, epochs=2,
                          batch_size=32, data_scale=0.05,
                          learning_rate=0.05))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.3  # synthetic-MNIST templates are learnable


def test_fedgan(args_factory):
    m = _run(args_factory(federated_optimizer="FedGAN", dataset="cifar10",
                          model="gan", client_num_in_total=2,
                          client_num_per_round=2, comm_round=2,
                          batch_size=16, data_scale=0.02,
                          learning_rate=2e-4))
    assert np.isfinite(m["d_loss"]) and np.isfinite(m["g_loss"])


def test_fedgan_generate(args_factory):
    args = fedml_tpu.init(args_factory(
        federated_optimizer="FedGAN", dataset="cifar10", model="gan",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        batch_size=16, data_scale=0.02))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, device, dataset, bundle)
    runner.run()
    imgs = runner.runner.generate(n=4)
    assert imgs.shape == (4, 32, 32, 3)
    assert np.all(np.abs(imgs) <= 1.0 + 1e-5)


def test_turbo_aggregate(args_factory):
    m = _run(args_factory(federated_optimizer="TurboAggregate",
                          client_num_in_total=4, client_num_per_round=4,
                          ta_group_num=2, comm_round=3, data_scale=0.3))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


def test_fedavg_seq_schedule(args_factory):
    m = _run(args_factory(federated_optimizer="FedAvg_seq",
                          client_num_in_total=6, client_num_per_round=6,
                          worker_num=2, comm_round=3, data_scale=0.2))
    assert np.isfinite(m["test_loss"])
    # every sampled client is assigned exactly once across workers
    assigned = sorted(c for w in m["schedule"] for c in w)
    assert assigned == list(range(6))
    assert m["est_makespan"] > 0


def test_fedseg_unet(args_factory):
    m = _run(args_factory(dataset="synthetic_seg", model="unet",
                          client_num_in_total=3, client_num_per_round=3,
                          comm_round=3, batch_size=16, learning_rate=0.05,
                          data_scale=0.5))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.5  # pixel accuracy; background majority ≈ 0.6+


def test_darts_search_trains(args_factory):
    m = _run(args_factory(dataset="cifar10", model="darts",
                          client_num_in_total=2, client_num_per_round=2,
                          comm_round=2, batch_size=16, data_scale=0.02))
    assert np.isfinite(m["test_loss"])


def test_darts_genotype_derivation():
    import numpy as np

    from fedml_tpu.models.darts import (
        PRIMITIVES,
        derive_genotype,
        num_edges,
    )

    alphas = np.zeros((num_edges(2), len(PRIMITIVES)), np.float32)
    alphas[:, PRIMITIVES.index("conv_3x3")] = 2.0
    alphas[:, PRIMITIVES.index("none")] = 5.0  # must be excluded
    g = derive_genotype(alphas)
    assert all(op == "conv_3x3" for op in g)


@pytest.mark.parametrize("name,dataset", [
    ("vgg11", "cifar10"), ("lenet", "mnist"), ("mlp", "adult"),
    ("darts_train", "cifar10"),
])
def test_new_models_forward(name, dataset):
    import jax
    import jax.numpy as jnp

    from fedml_tpu.models import model_hub
    from types import SimpleNamespace as NS

    b = model_hub.create(NS(model=name, dataset=dataset,
                            compute_dtype="float32"))
    x = jnp.zeros((2,) + b.input_shape, b.input_dtype)
    v = b.module.init(jax.random.PRNGKey(0), x)
    out = b.module.apply(v, x)
    assert out.shape[0] == 2 and np.all(np.isfinite(out))


@pytest.mark.parametrize("ds,classes,shape_tail", [
    ("stackoverflow_lr", 500, (10004,)),
    ("gld23k", 203, (96, 96, 3)),
    ("synthetic_seg", 4, (24, 24, 3)),
    ("synthetic_0.5_0.5", 10, (60,)),
    ("synthetic_1_1", 10, (60,)),
    ("nus_wide", 5, (1634,)),
    ("lending_club_loan", 2, (90,)),
    ("fednlp", 20, (5000,)),
    ("uci", 2, (105,)),
    ("reddit", 10000, (20,)),
    ("fets2021", 4, (32, 32, 3)),
])
def test_new_datasets(ds, classes, shape_tail):
    from fedml_tpu.data.datasets import load_arrays

    (xt, yt, xe, ye), c = load_arrays(ds, "", seed=0, scale=0.05)
    assert c == classes
    assert xt.shape[1:] == shape_tail
    assert len(xt) == len(yt) and len(xe) == len(ye)


def test_edge_case_poisoned_dataset():
    from fedml_tpu.data.datasets import load_arrays

    (xt, yt, _, _), c = load_arrays("cifar10", "", seed=0, scale=0.02)
    (xp, yp, _, _), cp = load_arrays("edge_case_cifar10", "", seed=0,
                                     scale=0.02)
    assert cp == c
    n_extra = len(xp) - len(xt)
    assert n_extra >= 8
    # poison tail carries the corner trigger and the target label
    assert np.all(xp[-n_extra:, :4, :4] == 1.0)
    assert len(set(yp[-n_extra:].tolist())) == 1
