"""Fault-tolerant round runtime: the reliability plane (ACK / retransmit /
dedup), heartbeat failure detection, and server crash-resume — proven
correct against the chaos plane (seeded drop/dup/delay injection)."""

import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.distributed.communication.chaos import ChaosCommManager
from fedml_tpu.core.distributed.communication.inprocess import (
    InProcCommManager,
    InProcHub,
)
from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.core.distributed.communication.reliable import (
    ARG_SEQ,
    ARG_VOLATILE,
    ReliableCommManager,
)


class _Collector:
    def __init__(self):
        self.got = []
        self.event = threading.Event()

    def receive_message(self, msg_type, msg):
        self.got.append((msg_type, msg))
        self.event.set()


class _Blackhole:
    """Inner transport that loses every send — exercises retransmit/expiry."""

    def __init__(self):
        self.sends = 0
        self._observers = []

    def send_message(self, msg):
        self.sends += 1

    def add_observer(self, obs):
        self._observers.append(obs)

    def remove_observer(self, obs):
        pass

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        pass


def _reliable_pair(channel, **kw):
    r0 = ReliableCommManager(InProcCommManager(0, 2, channel), rank=0, **kw)
    r1 = ReliableCommManager(InProcCommManager(1, 2, channel), rank=1, **kw)
    for r in (r0, r1):
        threading.Thread(target=r.handle_receive_message, daemon=True).start()
    return r0, r1


# --------------------------------------------------------------- unit tier
def test_reliable_stamps_acks_and_drains():
    r0, r1 = _reliable_pair("rel_ack", retx_initial_s=0.05)
    c1 = _Collector()
    r1.add_observer(c1)
    msg = Message("PING", 0, 1)
    msg.add_params("x", 7)
    r0.send_message(msg)
    assert c1.event.wait(5)
    assert c1.got[0][1].get("x") == 7
    assert c1.got[0][1].get(ARG_SEQ) == 1      # envelope stamped
    deadline = time.time() + 5
    while time.time() < deadline and r0._inflight:
        time.sleep(0.02)
    assert not r0._inflight, "ACK never cleared the in-flight window"
    assert r1.stats["acks_sent"] == 1
    r0.stop_receive_message()
    r1.stop_receive_message()


def test_reliable_dedup_suppresses_duplicate_delivery():
    r1 = ReliableCommManager(InProcCommManager(1, 2, "rel_dedup"), rank=1)
    c1 = _Collector()
    r1.add_observer(c1)
    msg = Message("UPLOAD", 0, 1)
    msg.add_params(ARG_SEQ, 5)
    msg.add_params("rel_epoch", 42)
    r1.receive_message("UPLOAD", msg)
    r1.receive_message("UPLOAD", msg)          # transport-level duplicate
    assert len(c1.got) == 1
    assert r1.stats["dup_suppressed"] == 1
    # both deliveries were ACKed — the first ACK may be the lost frame
    assert r1.stats["acks_sent"] == 2


def test_reliable_retransmits_then_expires():
    hole = _Blackhole()
    r = ReliableCommManager(hole, rank=0, retx_initial_s=0.02,
                            retx_max_s=0.04, retx_deadline_s=0.2)
    r.send_message(Message("DOOMED", 0, 1))
    deadline = time.time() + 3
    while time.time() < deadline and r._inflight:
        time.sleep(0.02)
    assert not r._inflight
    assert r.stats["retransmits"] >= 1
    assert r.stats["expired"] == 1
    assert hole.sends >= 2                      # original + retransmits


def test_reliable_volatile_and_unwrapped_passthrough():
    r1 = ReliableCommManager(InProcCommManager(1, 2, "rel_vol"), rank=1)
    c1 = _Collector()
    r1.add_observer(c1)
    # volatile send: no envelope, no in-flight tracking
    r0 = ReliableCommManager(InProcCommManager(0, 2, "rel_vol"), rank=0)
    hb = Message("HB", 0, 1)
    hb.add_params(ARG_VOLATILE, True)
    r0.send_message(hb)
    assert not r0._inflight
    # unwrapped-peer receive: no envelope → dispatched, never ACKed
    plain = Message("LEGACY", 0, 1)
    r1.receive_message("LEGACY", plain)
    assert [t for t, _ in c1.got] == ["LEGACY"]
    assert r1.stats["acks_sent"] == 0


def test_reliable_close_drains_inflight_before_stopping_inner():
    """stop_receive_message() must keep the inner loop alive until the
    in-flight window drains (the FINISH broadcast's ACKs), then stop it."""
    chan = "rel_drain"
    lossy0 = ChaosCommManager(InProcCommManager(0, 2, chan), drop_p=0.5,
                              seed=7)
    r0 = ReliableCommManager(lossy0, rank=0, retx_initial_s=0.03,
                             flush_timeout_s=5.0)
    r1 = ReliableCommManager(InProcCommManager(1, 2, chan), rank=1,
                             retx_initial_s=0.03)
    c1 = _Collector()
    r1.add_observer(c1)
    t0 = threading.Thread(target=r0.handle_receive_message, daemon=True)
    t1 = threading.Thread(target=r1.handle_receive_message, daemon=True)
    t0.start()
    t1.start()
    for i in range(10):
        r0.send_message(Message("FINAL", 0, 1))
    r0.stop_receive_message()                   # close while lossy
    t0.join(timeout=10)
    assert not t0.is_alive(), "receive loop never released after drain"
    assert len([1 for t, _ in c1.got if t == "FINAL"]) == 10
    assert not r0._inflight
    r1.stop_receive_message()


# ------------------------------------------------- chaos-plane satellites
def test_chaos_stats_exact_under_concurrent_senders():
    class _Sink:
        def send_message(self, msg):
            pass

        def add_observer(self, o):
            pass

        def remove_observer(self, o):
            pass

        def handle_receive_message(self):
            pass

        def stop_receive_message(self):
            pass

    chaos = ChaosCommManager(_Sink(), drop_p=0.3, dup_p=0.3, delay_p=0.3,
                             max_delay_s=0.0, seed=0)
    n_threads, n_msgs = 8, 200

    def _spam():
        for i in range(n_msgs):
            chaos.send_message(Message("SPAM", 0, 1))

    threads = [threading.Thread(target=_spam) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert chaos.stats["sent"] == n_threads * n_msgs


def test_chaos_duplicate_rolls_its_own_drop_and_delay():
    """The dup copy goes through the same drop/delay pipeline as the
    original — it is not an unconditional immediate echo."""
    hub_chan = "chaos_dup"
    chaos = ChaosCommManager(InProcCommManager(0, 2, hub_chan),
                             dup_p=1.0, delay_p=1.0, max_delay_s=0.05,
                             seed=3)
    q = InProcHub.get(hub_chan).queue_for(1)
    for _ in range(20):
        chaos.send_message(Message("D", 0, 1))
    deadline = time.time() + 5
    while time.time() < deadline and q.qsize() < 40:
        time.sleep(0.02)
    assert q.qsize() == 40                      # 20 originals + 20 dups
    assert chaos.stats["duplicated"] == 20
    assert chaos.stats["delayed"] == 40         # every copy rolled delay


def test_inproc_channel_release_is_identity_guarded(args_factory):
    from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager

    args = args_factory(run_id="rel_release")
    m1 = FedMLCommManager(args, rank=0, size=1, backend="INPROC")
    old_hub = m1.com_manager.hub
    old_hub.queue_for(0).put("stale-msg")
    m1.finish()                                 # releases the channel
    # a new same-process run with the same run_id gets a FRESH hub: the
    # stale message cannot leak forward
    m2 = FedMLCommManager(args, rank=0, size=1, backend="INPROC")
    assert m2.com_manager.hub is not old_hub
    assert m2.com_manager.hub.queue_for(0).qsize() == 0
    # finishing the OLD manager again must NOT drop the new run's channel
    m1.finish()
    assert InProcHub.get("rel_release") is m2.com_manager.hub
    m2.finish()


def test_round_checkpointer_force_overwrites_growing_round_state(tmp_path):
    from fedml_tpu.utils.checkpoint import RoundCheckpointer

    for use_fallback in (False, True):
        ck = RoundCheckpointer(str(tmp_path / f"ck{use_fallback}"))
        if use_fallback:
            ck._mgr = None                      # exercise the npz path too
        state = {"round_idx": 2,
                 "global_model": {"w": np.arange(4.0)},
                 "models": {"0": {"w": np.ones(4)}},
                 "num_samples": {"0": 5.0}}
        ck.save(2, state, force=True)
        state["models"]["1"] = {"w": np.zeros(4)}
        state["num_samples"]["1"] = 2.0
        ck.save(2, state, force=True)           # same step, grown set
        back = ck.restore(2)
        assert sorted(back["models"]) == ["0", "1"]
        assert int(np.asarray(back["round_idx"])) == 2


# --------------------------------------------------- protocol-level tier
def _register_chaos_reliable_backend(name, instances, *, drop_p=0.15,
                                     dup_p=0.1, delay_p=0.2,
                                     max_delay_s=0.05, seed0=100):
    """CHAOS backend factory; args.reliable=True makes the comm base wrap
    it in the reliability runtime (reliability ABOVE chaos, so ACKs and
    retransmits cross the lossy link too)."""
    from fedml_tpu.core.distributed.fedml_comm_manager import (
        register_comm_backend,
    )

    def factory(args, rank=0, size=0):
        mgr = ChaosCommManager(
            InProcCommManager(rank, size, str(args.run_id)),
            drop_p=drop_p, dup_p=dup_p, delay_p=delay_p,
            max_delay_s=max_delay_s, seed=seed0 + rank)
        instances.append(mgr)
        return mgr

    register_comm_backend(name, factory)


def test_chaos_soak_reliable_completes_all_rounds_exactly_once(args_factory):
    """Acceptance soak: 5 clients × 10 rounds under seeded chaos
    (drop_p=0.15, dup_p=0.1, delay_p=0.2) with the reliability runtime —
    every round completes with the full cohort, NO round timer needed, and
    zero duplicate-counted uploads (the dedup window absorbs every
    transport duplicate)."""
    import fedml_tpu
    from fedml_tpu.core.mlops import metrics
    from fedml_tpu.cross_silo.runner import init_client, init_server

    chaos_instances = []
    _register_chaos_reliable_backend("CHAOS_REL_SOAK", chaos_instances)
    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=5,
        client_num_per_round=5, comm_round=10, data_scale=0.2,
        learning_rate=0.1, frequency_of_the_test=5, run_id="rel_soak",
        reliable=True, reliable_retx_initial_s=0.05,
        reliable_retx_max_s=0.5))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle, backend="CHAOS_REL_SOAK")
    clients = [init_client(args, dataset, bundle, rank,
                           backend="CHAOS_REL_SOAK")
               for rank in range(1, 6)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30)

    assert int(args.round_idx) == 10, "not every round completed"
    assert np.isfinite(server.aggregator.metrics_history[-1]["test_loss"])
    # the adversary actually fired ...
    dropped = sum(c.stats["dropped"] for c in chaos_instances)
    duplicated = sum(c.stats["duplicated"] for c in chaos_instances)
    assert dropped > 0 and duplicated > 0, "chaos never fired"
    # ... and the reliability plane absorbed it: losses were retransmitted,
    # duplicates suppressed, and not one upload was double-counted
    rel = [m.com_manager for m in [server] + clients]
    retx = sum(r.stats["retransmits"] for r in rel)
    dups = sum(r.stats["dup_suppressed"] for r in rel)
    assert retx > 0, "drops happened but nothing was retransmitted"
    assert dups > 0, "duplicates happened but none were suppressed"
    assert all(r.stats["expired"] == 0 for r in rel)
    assert server.aggregator.duplicate_uploads == 0
    # counters are live on the Prometheus exposition surface
    exposition = metrics.render_prometheus()
    for name in ("fedml_reliable_retransmits_total",
                 "fedml_reliable_dup_suppressed_total",
                 "fedml_round_duplicate_uploads_total"):
        assert name in exposition


def test_server_crash_resume_mid_round(args_factory, tmp_path):
    """Kill the server mid-round: a restarted server resumes from
    RoundCheckpointer state at the SAME round index, re-solicits only the
    missing clients, and finishes without re-aggregating completed
    rounds."""
    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client
    from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator
    from fedml_tpu.cross_silo.server.fedml_server_manager import (
        FedMLServerManager,
    )
    from fedml_tpu.ml.trainer.default_trainer import DefaultServerAggregator

    CRASH_ROUND, TOTAL_ROUNDS, N = 3, 6, 3
    ckpt_dir = str(tmp_path / "rounds")

    class _CrashingAggregator(FedMLAggregator):
        crashed = False

        def add_local_trained_result(self, index, model_params, sample_num):
            if (not self.crashed
                    and int(self.args.round_idx) == CRASH_ROUND
                    and self.receive_count() >= 1):
                _CrashingAggregator.crashed = True
                raise RuntimeError("simulated server crash")
            super().add_local_trained_result(index, model_params, sample_num)

    def _build(args, aggregator_cls):
        import jax

        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        impl = DefaultServerAggregator(bundle, args)
        if impl.get_model_params() is None:
            impl.set_model_params(bundle.init_variables(
                jax.random.PRNGKey(0)))
        agg = aggregator_cls(args, impl, dataset[3])
        server = FedMLServerManager(args, agg, rank=0, client_num=N,
                                    backend="INPROC")
        clients = [init_client(args, dataset, bundle, rank,
                               backend="INPROC")
                   for rank in range(1, N + 1)]
        return server, clients

    common = dict(training_type="cross_silo", client_num_in_total=N,
                  client_num_per_round=N, comm_round=TOTAL_ROUNDS,
                  data_scale=0.3, frequency_of_the_test=1,
                  checkpoint_dir=ckpt_dir)

    # -- phase 1: crash mid-round CRASH_ROUND --------------------------------
    args1 = fedml_tpu.init(args_factory(run_id="crash_p1", **common))
    server1, clients1 = _build(args1, _CrashingAggregator)
    for c in clients1:
        threading.Thread(target=c.run, daemon=True).start()
    with pytest.raises(RuntimeError, match="simulated server crash"):
        server1.run()
    # completed rounds 0..CRASH_ROUND-1, each evaluated once
    assert len(server1.aggregator.metrics_history) == CRASH_ROUND
    assert int(args1.round_idx) == CRASH_ROUND

    # -- phase 2: restarted server resumes from the checkpoint ---------------
    args2 = fedml_tpu.init(args_factory(run_id="crash_p2",
                                        resume_from="latest", **common))
    server2, clients2 = _build(args2, FedMLAggregator)
    assert int(args2.round_idx) == CRASH_ROUND, "did not resume at round k"
    # the result accepted before the crash was restored — only the missing
    # clients get re-solicited
    assert server2.aggregator.receive_count() == 1
    threads2 = [threading.Thread(target=c.run, daemon=True)
                for c in clients2]
    for t in threads2:
        t.start()
    server2.run()
    for t in threads2:
        t.join(timeout=30)
    assert int(args2.round_idx) == TOTAL_ROUNDS
    # rounds CRASH_ROUND..TOTAL_ROUNDS-1 ran here — completed rounds were
    # NOT re-aggregated
    assert len(server2.aggregator.metrics_history) == \
        TOTAL_ROUNDS - CRASH_ROUND
    assert np.isfinite(server2.aggregator.metrics_history[-1]["test_loss"])


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_heartbeat_detector_drops_dead_client_immediately(args_factory):
    """A client that dies mid-run stops heartbeating; the failure detector
    declares it dead after miss_threshold intervals and the round
    completes with the survivors — WITHOUT waiting out the (long) round
    timer."""
    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    LONG_TIMEOUT = 60.0
    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=3,
        client_num_per_round=3, comm_round=3, data_scale=0.3,
        learning_rate=0.1, frequency_of_the_test=1, run_id="hb_drop",
        heartbeat_interval_s=0.15, heartbeat_miss_threshold=3,
        round_timeout_s=LONG_TIMEOUT, min_clients_per_round=2))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle, backend="INPROC")
    clients = [init_client(args, dataset, bundle, rank, backend="INPROC")
               for rank in range(1, 4)]

    # rank 3 "dies" when it receives the round-1 sync: its handler raises,
    # the comm base tears the node down, heartbeats stop
    doomed = clients[2]
    real_train = doomed.trainer_dist_adapter.train

    def _dying_train(round_idx):
        if int(round_idx) >= 1:
            raise RuntimeError("client 3 crashed")
        return real_train(round_idx)

    doomed.trainer_dist_adapter.train = _dying_train
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    start = time.monotonic()
    for t in threads:
        t.start()
    server.run()
    elapsed = time.monotonic() - start

    assert int(args.round_idx) == 3
    assert len(server.aggregator.metrics_history) == 3
    # the dead client was dropped by the failure detector, not the timer
    assert server.client_online_status[3] is False
    assert elapsed < LONG_TIMEOUT / 2, (
        f"run took {elapsed:.1f}s — the dead client was only dropped by "
        "the round timer, not the heartbeat detector")
