"""Runtime lock profiler: opt-in factory, edge recording, the
observed-vs-committed DAG gate, and the chaos soak's overhead budget."""

from __future__ import annotations

import json
import threading
import time

import pytest

from fedml_tpu.core.mlops import lock_profiler


@pytest.fixture
def armed():
    lock_profiler.arm(True)
    try:
        yield
    finally:
        lock_profiler.arm(False)
        lock_profiler._armed = None   # back to the env toggle


def test_disarmed_factory_returns_plain_primitives():
    lock_profiler.arm(False)
    try:
        lock = lock_profiler.named_lock("X._lock")
        rlock = lock_profiler.named_rlock("X._rlock")
        # the hot path carries ZERO wrapper frames when off
        assert type(lock) is type(threading.Lock())
        assert type(rlock) is type(threading.RLock())
        with lock:
            pass
        assert not lock_profiler.snapshot()["locks"]
    finally:
        lock_profiler._armed = None


def test_armed_records_acquisitions_and_order_edges(armed):
    a = lock_profiler.named_lock("A._lock")
    b = lock_profiler.named_lock("B._lock")
    with a:
        with b:
            pass
    with a:
        pass
    snap = lock_profiler.snapshot()
    assert snap["locks"]["A._lock"]["acquisitions"] == 2
    assert snap["locks"]["B._lock"]["acquisitions"] == 1
    assert lock_profiler.observed_edges(snap) == {("A._lock", "B._lock")}
    # the edge count rides along
    assert snap["edges"] == [["A._lock", "B._lock", 1]]


def test_rlock_records_outermost_acquire_only(armed):
    r = lock_profiler.named_rlock("R._lock")
    inner = lock_profiler.named_lock("R._inner")
    with r:
        with r:                      # reentrant — not a second acquisition
            with inner:
                pass
    snap = lock_profiler.snapshot()
    assert snap["locks"]["R._lock"]["acquisitions"] == 1
    # the edge comes from the OUTERMOST hold, never "R._lock -> R._lock"
    assert lock_profiler.observed_edges(snap) == {("R._lock", "R._inner")}


def test_contention_and_wait_accounting(armed):
    lock = lock_profiler.named_lock("C._lock")
    started = threading.Event()

    def holder():
        with lock:
            started.set()
            time.sleep(0.05)

    t = threading.Thread(target=holder)
    t.start()
    started.wait(timeout=5.0)
    with lock:                       # must wait out the holder
        pass
    t.join(timeout=5.0)
    rec = lock_profiler.snapshot()["locks"]["C._lock"]
    assert rec["acquisitions"] == 2
    assert rec["contended"] >= 1
    assert rec["wait_s"] > 0.0
    assert rec["hold_s"] > 0.04


def test_check_observed_edges_flags_extras(armed):
    observed = {("A", "B"), ("B", "C")}
    committed = {("A", "B")}
    assert lock_profiler.check_observed_edges(observed, committed) \
        == [("B", "C")]
    assert lock_profiler.check_observed_edges({("A", "B")}, committed) == []


def test_dump_roundtrip_and_report_render(armed, tmp_path):
    a = lock_profiler.named_lock("A._lock")
    b = lock_profiler.named_lock("B._lock")
    with a:
        with b:
            pass
    path = lock_profiler.dump(str(tmp_path / "lockprof.json"))
    snap = json.loads(open(path).read())
    assert lock_profiler.observed_edges(snap) == {("A._lock", "B._lock")}
    ok = lock_profiler.render_report(snap, extra_edges=[])
    assert "observed edges ⊆ committed static DAG: OK" in ok
    bad = lock_profiler.render_report(
        snap, extra_edges=[("A._lock", "B._lock")])
    assert "OUTSIDE THE COMMITTED STATIC DAG" in bad


def test_conc_report_cli_gates_on_dag_and_overhead(armed, tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    a = lock_profiler.named_lock(
        "ReplicaProcessManager._scale_lock")
    b = lock_profiler.named_lock("ReplicaProcessManager._lock")
    with a:
        with b:
            pass
    path = lock_profiler.dump(str(tmp_path / "lockprof.json"))
    # the committed repo DAG contains exactly this edge — the gate passes
    res = CliRunner().invoke(cli, ["conc", "report", "--snapshot", path,
                                   "--check-dag", "--max-overhead", "0.02"])
    assert res.exit_code == 0, res.output
    assert "OK" in res.output
    # an edge the static pass never saw fails the gate
    lock_profiler.reset()
    x = lock_profiler.named_lock("Rogue._x")
    y = lock_profiler.named_lock("Rogue._y")
    with x:
        with y:
            pass
    path = lock_profiler.dump(str(tmp_path / "rogue.json"))
    res = CliRunner().invoke(cli, ["conc", "report", "--snapshot", path,
                                   "--check-dag"])
    assert res.exit_code == 1, res.output
    assert "Rogue._x -> Rogue._y" in res.output


def test_chaos_soak_observed_subset_of_committed_under_budget(armed):
    """The CI soak in miniature: hammer the replica manager's two locks
    from scale/monitor/gateway-shaped threads in the committed order and
    assert (a) every observed edge is in the committed static DAG and
    (b) the profiler's self-measured bookkeeping stays under 2%."""
    from fedml_tpu.analysis.conc.lockorder import committed_pairs
    from fedml_tpu.analysis.engine import default_root

    committed = committed_pairs(default_root())
    assert committed, "benchmarks/lock_order.json must be committed"

    scale = lock_profiler.named_lock("ReplicaProcessManager._scale_lock")
    gateway = lock_profiler.named_lock("ReplicaProcessManager._lock")
    stop = threading.Event()

    sink = []

    def scaler():
        # lifecycle ticks: a lifecycle op nests the gateway lock, with
        # real (if tiny) work inside the critical section — the budget
        # is against a control-plane profile, not a lock-churn micro
        while not stop.is_set():
            with scale:
                with gateway:
                    sink.append(sum(range(200)))
            stop.wait(0.001)

    def monitor():
        while not stop.is_set():
            with gateway:
                sink.append(sum(range(200)))
            stop.wait(0.001)

    threads = [threading.Thread(target=scaler) for _ in range(2)] \
        + [threading.Thread(target=monitor) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)

    snap = lock_profiler.snapshot()
    extras = lock_profiler.check_observed_edges(
        lock_profiler.observed_edges(snap), committed)
    assert extras == [], extras
    total = sum(r["acquisitions"] for r in snap["locks"].values())
    assert total > 100, snap["locks"]
    assert snap["overhead_frac"] < 0.02, snap["overhead_frac"]
