"""The data-plane defense stack: aggregation arithmetic (weighted /
uniform / stacked), byzantine-robust operators, update admission control,
straggler-tolerant round pacing — proven end-to-end by a seeded
byzantine+straggler soak (slow tier; CI runs it in the dedicated
byzantine-soak step)."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.ml.aggregator.agg_operator import (
    FedMLAggOperator,
    agg_stacked,
    uniform_average,
    weighted_average,
)
from fedml_tpu.ml.aggregator.robust import (
    geo_median,
    krum,
    median,
    norm_clip,
    parse_robust_agg,
    robust_agg_stacked,
    stack_grad_list,
    trimmed_mean,
)


def _tree(val, shape=(4, 3), dtype=jnp.float32):
    return {"w": jnp.full(shape, val, dtype),
            "b": jnp.full((2,), val, dtype)}


def _honest_stack(n=5, base=1.0, jitter=0.05, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    trees = [jax.tree_util.tree_map(
        lambda x: x + jitter * jnp.asarray(
            rng.randn(*np.shape(x)).astype(np.float32)),
        _tree(base, dtype=dtype)) for _ in range(n)]
    return trees


# ------------------------------------------------------------- arithmetic
def test_weighted_average_weights_by_sample_count():
    out = weighted_average([(1.0, _tree(0.0)), (3.0, _tree(4.0))])
    np.testing.assert_allclose(np.asarray(out["w"]), 3.0, atol=1e-6)
    # nonpositive total falls back to uniform
    out = weighted_average([(0.0, _tree(2.0)), (0.0, _tree(4.0))])
    np.testing.assert_allclose(np.asarray(out["b"]), 3.0, atol=1e-6)


def test_uniform_average_custom_denominator():
    out = uniform_average([_tree(2.0), _tree(4.0)], denom=4.0)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.5, atol=1e-6)


def test_agg_stacked_mask_selects_clients():
    stacked = stack_grad_list([_tree(1.0), _tree(5.0), _tree(9.0)])
    # masked-out middle client must not contribute
    out = agg_stacked(stacked, jnp.asarray([1.0, 0.0, 3.0]))
    np.testing.assert_allclose(np.asarray(out["w"]), 7.0, atol=1e-5)


def test_agg_stacked_keeps_bf16_leaves_bf16():
    """f32 accumulation, but the reduced leaf comes back in the INPUT
    dtype — a bf16 model tree must not silently widen to f32."""
    stacked = stack_grad_list(
        [_tree(1.0, dtype=jnp.bfloat16), _tree(3.0, dtype=jnp.bfloat16)])
    out = agg_stacked(stacked, jnp.asarray([1.0, 1.0]))
    assert out["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out["w"], np.float32), 2.0, atol=1e-2)


def test_agg_operator_scaffold_and_mime_pair_paths(args_factory):
    pairs = [(2.0, (_tree(1.0), _tree(10.0))),
             (2.0, (_tree(3.0), _tree(30.0)))]
    args = args_factory(federated_optimizer="SCAFFOLD",
                        client_num_in_total=4)
    params_avg, c_avg = FedMLAggOperator.agg(args, pairs)
    np.testing.assert_allclose(np.asarray(params_avg["w"]), 2.0, atol=1e-6)
    # control variates average uniformly over client_num_in_total
    np.testing.assert_allclose(np.asarray(c_avg["w"]), 10.0, atol=1e-6)
    args = args_factory(federated_optimizer="Mime")
    params_avg, grads_avg = FedMLAggOperator.agg(args, pairs)
    np.testing.assert_allclose(np.asarray(grads_avg["w"]), 20.0, atol=1e-6)


# ------------------------------------------------- robust operator suite
def test_parse_robust_agg_specs():
    assert parse_robust_agg(None) is None
    assert parse_robust_agg("") is None
    s = parse_robust_agg("trimmed_mean:0.2")
    assert s.name == "trimmed_mean" and s.param == pytest.approx(0.2)
    assert parse_robust_agg("median").name == "median"
    assert parse_robust_agg("krum:1") == ("krum", 1.0, 1)
    assert parse_robust_agg("multi_krum:1:3").k == 3
    assert parse_robust_agg("geo_median:12").param == 12
    assert parse_robust_agg("norm_clip:5").param == 5.0
    for bad in ("bogus", "trimmed_mean:0.7", "krum", "norm_clip:-1",
                "norm_clip", "multi_krum:x"):
        with pytest.raises(ValueError):
            parse_robust_agg(bad)


def test_trimmed_mean_ignores_f_outliers():
    trees = _honest_stack(5)
    trees.append(_tree(1e6))          # one wild byzantine client
    stacked = stack_grad_list(trees)
    w = jnp.ones(6)
    out = trimmed_mean(stacked, w, trim_frac=0.2)   # k = floor(.2*6) = 1
    honest = np.mean([np.asarray(t["w"]) for t in trees[:5]])
    assert abs(float(np.asarray(out["w"]).mean()) - honest) < 0.2
    assert np.isfinite(np.asarray(out["w"])).all()


def test_median_bounded_by_honest_range():
    trees = _honest_stack(4)
    trees += [_tree(-1e5), _tree(jnp.nan)]          # < half byzantine
    stacked = stack_grad_list(trees)
    out = median(stacked, jnp.ones(6))
    vals = np.asarray(out["w"])
    honest = np.stack([np.asarray(t["w"]) for t in trees[:4]])
    assert np.isfinite(vals).all()
    assert (vals >= honest.min(axis=0) - 1e-5).all()
    assert (vals <= honest.max(axis=0) + 1e-5).all()


def test_krum_picks_an_honest_client():
    trees = _honest_stack(5)
    trees.append(_tree(50.0))
    stacked = stack_grad_list(trees)
    out = krum(stacked, jnp.ones(6), f=1, k=1)
    # the pick is exactly one of the honest updates, never the outlier
    picked = np.asarray(out["w"])
    honest = [np.asarray(t["w"]) for t in trees[:5]]
    assert any(np.allclose(picked, h, atol=1e-5) for h in honest)


def test_krum_degenerate_mask_falls_back_to_weighted_mean():
    """With n_valid <= f+2 every Krum score is +inf and top_k's arbitrary
    picks may all be masked — the fallback must return the valid clients'
    weighted mean, never a silent zero model."""
    trees = _honest_stack(4, jitter=0.0)
    stacked = stack_grad_list(trees)
    w = jnp.asarray([0.0, 0.0, 0.0, 2.0])   # lone survivor at index 3
    out = krum(stacked, w, f=1, k=1)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0, atol=1e-5)


def test_multi_krum_averages_honest_selection():
    trees = _honest_stack(5)
    trees.append(_tree(50.0))
    out = krum(stack_grad_list(trees), jnp.ones(6), f=1, k=3)
    assert abs(float(np.asarray(out["w"]).mean()) - 1.0) < 0.2


def test_geo_median_resists_outlier():
    trees = _honest_stack(5)
    trees.append(_tree(1e4))
    out = geo_median(stack_grad_list(trees), jnp.ones(6), iters=32)
    assert abs(float(np.asarray(out["w"]).mean()) - 1.0) < 0.2


def test_norm_clip_bounds_outlier_influence():
    trees = _honest_stack(5, jitter=0.0)
    trees.append(_tree(1e6))
    center = _tree(1.0)
    out = norm_clip(stack_grad_list(trees), jnp.ones(6), 1.0, center=center)
    # the outlier's delta is clipped to norm 1 → total shift ≤ 1/6
    assert abs(float(np.asarray(out["w"]).mean()) - 1.0) < 0.2


def test_robust_ops_respect_weight_mask():
    """Weight-0 clients are excluded exactly — a masked byzantine client
    must not shift any operator (the Parrot selective-aggregation
    contract)."""
    trees = _honest_stack(4, jitter=0.0)
    trees.append(_tree(1e6))
    stacked = stack_grad_list(trees)
    w = jnp.asarray([1.0, 1.0, 1.0, 1.0, 0.0])
    for spec in ("trimmed_mean:0.0", "median", "krum:0", "geo_median:8",
                 "norm_clip:100"):
        out = robust_agg_stacked(parse_robust_agg(spec), stacked, w,
                                 center=_tree(1.0))
        np.testing.assert_allclose(
            np.asarray(out["w"]), 1.0, atol=1e-3, err_msg=spec)


def test_robust_ops_jit_compatible_on_stacked_pytrees():
    """Acceptance: every operator traces under jit on a stacked pytree
    (leading client axis) with a TRACED weight mask — no per-leaf Python
    loop over clients in the hot path, one compiled program per
    participation pattern."""
    trees = _honest_stack(6)
    stacked = stack_grad_list(trees)
    for spec_str in ("trimmed_mean:0.2", "median", "krum:1",
                     "multi_krum:1:2", "geo_median:4", "norm_clip:2.0"):
        spec = parse_robust_agg(spec_str)
        fn = jax.jit(lambda s, w, sp=spec: robust_agg_stacked(sp, s, w))
        out = fn(stacked, jnp.ones(6))
        assert np.isfinite(np.asarray(out["w"])).all(), spec_str
        # same compiled fn, different mask → still correct (shapes static)
        out2 = fn(stacked, jnp.asarray([1., 1., 1., 0., 0., 0.]))
        assert np.isfinite(np.asarray(out2["w"])).all(), spec_str


def test_agg_operator_threads_robust_spec(args_factory):
    """--robust-agg reroutes FedMLAggOperator.agg (the SP + cross-silo
    funnel) through the stacked robust operator."""
    grad_list = [(10.0, t) for t in _honest_stack(4)]
    grad_list.append((10.0, _tree(1e6)))
    args = args_factory(robust_agg="median")
    out = FedMLAggOperator.agg(args, grad_list)
    assert abs(float(np.asarray(out["w"]).mean()) - 1.0) < 0.2
    # plain average for contrast is dragged away by the outlier
    plain = FedMLAggOperator.agg(args_factory(), grad_list)
    assert float(np.asarray(plain["w"]).mean()) > 1e4
    # pair payloads: robust on the params component, uniform variates
    pairs = [(1.0, (t, _tree(0.0))) for t in _honest_stack(4)]
    pairs.append((1.0, (_tree(1e6), _tree(0.0))))
    args = args_factory(robust_agg="median", federated_optimizer="SCAFFOLD",
                        client_num_in_total=5)
    params_avg, _ = FedMLAggOperator.agg(args, pairs)
    assert abs(float(np.asarray(params_avg["w"]).mean()) - 1.0) < 0.2


# --------------------------------------------------- admission control
class _StubImpl:
    """Minimal ServerAggregator stand-in: holds a params tree."""

    def __init__(self, params):
        self._p = params

    def get_model_params(self):
        return self._p

    def set_model_params(self, p):
        self._p = p


def _aggregator(args_factory, **kw):
    from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator

    args = args_factory(client_num_per_round=3, **kw)
    return FedMLAggregator(args, _StubImpl(_tree(1.0)), test_global=None)


def test_admission_quarantines_nan_structure_and_norm(args_factory):
    agg = _aggregator(args_factory, admission_control=True,
                      admission_norm_bound=10.0, run_id="adm")
    assert agg.add_local_trained_result(0, _tree(1.1), 5) is None
    assert agg.add_local_trained_result(1, _tree(jnp.nan), 5) == "non_finite"
    assert agg.add_local_trained_result(
        1, {"wrong": jnp.zeros(3)}, 5) == "structure_mismatch"
    bad_shape = {"w": jnp.ones((2, 2)), "b": jnp.ones(2)}
    assert agg.add_local_trained_result(
        1, bad_shape, 5) == "structure_mismatch"
    assert agg.add_local_trained_result(
        1, _tree(1e6), 5) == "norm_outlier"
    # the quarantined index never entered the received set...
    assert agg.receive_count() == 1 and not agg.has_received(1)
    assert agg.quarantined_total == 4
    # ...and the per-round ledger holds the LAST rejection reason
    assert agg.quarantined_this_round == {1: "norm_outlier"}
    # ...and a clean retry is admitted
    assert agg.add_local_trained_result(1, _tree(0.9), 5) is None
    assert agg.has_received(1)
    # pair payloads (params, variates): no structure/norm counterpart,
    # but the NaN/Inf scan still applies to the whole tuple tree
    assert agg.add_local_trained_result(
        2, (_tree(jnp.nan), _tree(0.0)), 5) == "non_finite"
    assert agg.add_local_trained_result(
        2, (_tree(1.0), _tree(0.0)), 5) is None
    from fedml_tpu.core.mlops import metrics
    assert "fedml_quarantined_updates_total" in metrics.render_prometheus()


def test_admission_off_accepts_everything(args_factory):
    agg = _aggregator(args_factory)
    assert agg.add_local_trained_result(0, _tree(jnp.nan), 5) is None
    assert agg.has_received(0)


def test_duplicate_upload_keeps_first_result(args_factory):
    """Keep-first: a late/forged duplicate must never replace the
    already-counted (and possibly checkpointed) update."""
    agg = _aggregator(args_factory, run_id="dupfirst")
    agg.add_local_trained_result(0, _tree(1.0), 5)
    assert agg.add_local_trained_result(0, _tree(999.0), 7) is None
    assert agg.duplicate_uploads == 1
    np.testing.assert_allclose(np.asarray(agg.model_dict[0]["w"]), 1.0)
    assert agg.sample_num_dict[0] == 5.0


def test_client_sampling_deterministic_and_isolated(args_factory):
    """Cohorts are a pure function of (run_id, random_seed, round_idx) —
    a crash-resumed server re-derives the SAME cohort — and sampling no
    longer touches the global np.random stream."""
    a1 = _aggregator(args_factory, run_id="det", client_num_in_total=20)
    a2 = _aggregator(args_factory, run_id="det", client_num_in_total=20)
    for r in (0, 1, 7):
        assert a1.client_sampling(r, 20, 5) == a2.client_sampling(r, 20, 5)
        assert a1.data_silo_selection(r, 30, 5) == \
            a2.data_silo_selection(r, 30, 5)
    assert a1.client_sampling(0, 20, 5) != a1.client_sampling(1, 20, 5)
    other = _aggregator(args_factory, run_id="other", client_num_in_total=20)
    assert other.client_sampling(0, 20, 5) != a1.client_sampling(0, 20, 5)
    # the global numpy stream is untouched
    np.random.seed(1234)
    expect = np.random.RandomState(1234).rand(3)
    a1.client_sampling(3, 20, 5)
    np.testing.assert_allclose(np.random.rand(3), expect)


# ------------------------------------------------------- chaos trainer
def test_chaos_trainer_modes():
    from fedml_tpu.core.distributed.communication.chaos import chaos_trainer

    class _T:
        params = _tree(2.0)

        def get_model_params(self):
            return self.params

        def train(self, data, device=None, args=None):
            return {"train_loss": 1.0}

    nan_t = chaos_trainer(_T(), "nan")
    assert not np.isfinite(np.asarray(nan_t.get_model_params()["w"])).any()
    flip = chaos_trainer(_T(), "sign_flip")
    np.testing.assert_allclose(np.asarray(flip.get_model_params()["w"]), -2.0)
    scale = chaos_trainer(_T(), "scale:10")
    np.testing.assert_allclose(np.asarray(scale.get_model_params()["w"]), 20.0)
    slow = chaos_trainer(_T(), "slow:0.05")
    t0 = time.monotonic()
    slow.train(None)
    assert time.monotonic() - t0 >= 0.05
    np.testing.assert_allclose(np.asarray(slow.get_model_params()["w"]), 2.0)
    with pytest.raises(ValueError):
        chaos_trainer(_T(), "explode")


def test_parrot_robust_aggregation_inside_round_jit(args_factory):
    """The Parrot vectorized plane swaps its fused weighted mean for the
    robust operator INSIDE the round jit (and the fused scan path)."""
    import fedml_tpu
    from fedml_tpu.simulation.parrot.parrot_api import ParrotAPI

    args = fedml_tpu.init(args_factory(
        comm_round=2, robust_agg="median", run_id="parrot_rob"))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    api = ParrotAPI(args, None, dataset, bundle)
    m = api.train()
    assert np.isfinite(m["test_loss"])


# ---------------------------------------------- end-to-end (slow tier)
def _run_federation(args_factory, run_id, adversaries=None, n=5,
                    comm_round=6, **kw):
    """One INPROC cross-silo federation; ``adversaries`` maps rank →
    chaos_trainer spec.  Returns (args, server, elapsed_s)."""
    import fedml_tpu
    from fedml_tpu.core.distributed.communication.chaos import chaos_trainer
    from fedml_tpu.cross_silo.runner import fleet_size, init_client, init_server
    from fedml_tpu.ml.trainer.default_trainer import DefaultClientTrainer

    cfg = dict(training_type="cross_silo", client_num_in_total=n,
               client_num_per_round=n, comm_round=comm_round, data_scale=0.2,
               learning_rate=0.1, frequency_of_the_test=1, run_id=run_id)
    cfg.update(kw)
    args = fedml_tpu.init(args_factory(**cfg))
    fleet = fleet_size(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle, backend="INPROC")
    clients = []
    for rank in range(1, fleet + 1):
        trainer = DefaultClientTrainer(bundle, args)
        if adversaries and rank in adversaries:
            trainer = chaos_trainer(trainer, adversaries[rank])
        clients.append(init_client(args, dataset, bundle, rank, trainer,
                                   backend="INPROC"))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    server.run()
    elapsed = time.monotonic() - t0
    for t in threads:
        t.join(timeout=15)
    return args, server, elapsed


def test_admission_without_pacer_completes_rounds(args_factory):
    """Regression: with admission control on but NO deadline/timeout
    pacer configured (the defaults), a persistently-byzantine client must
    not hang the round — once its quarantine re-solicit budget is spent,
    the round closes on the remaining participants."""
    _, server, _ = _run_federation(
        args_factory, "bz_nopacer", adversaries={3: "nan"}, n=3,
        comm_round=2, admission_control=True)
    assert len(server.aggregator.metrics_history) == 2
    assert server.aggregator.quarantined_total >= 2
    assert all(np.isfinite(m["test_loss"])
               for m in server.aggregator.metrics_history)


@pytest.mark.slow
def test_byzantine_soak_robust_converges_where_fedavg_diverges(args_factory):
    """Acceptance soak: 5 clients, 2 adversarial (sign-flip + NaN
    injector), seeded.  Trimmed-mean and median runs (admission control +
    deadline pacing on) reach a final loss within 10% of the clean-FedAvg
    baseline; plain FedAvg under the same faults does not.  NaN uploads
    land in fedml_quarantined_updates_total and NEVER in the global model
    (finite every round)."""
    from fedml_tpu.core.mlops import metrics
    from fedml_tpu.core.security.utils import tree_to_vector

    ADV = {4: "sign_flip", 5: "nan"}
    _, s_clean, _ = _run_federation(args_factory, "bz_clean")
    clean = s_clean.aggregator.metrics_history[-1]["test_loss"]
    assert np.isfinite(clean)

    _, s_bad, _ = _run_federation(args_factory, "bz_bad", adversaries=ADV)
    bad = s_bad.aggregator.metrics_history[-1]["test_loss"]
    # plain FedAvg is poisoned: NaN or far off the clean baseline
    assert not (np.isfinite(bad) and bad <= 1.1 * clean), (bad, clean)

    # floor 4 = every honest client + the sign-flipper: the NaN client is
    # always quarantined (never counted), so the deadline closes every
    # round with EXACTLY the same 4-member set on any machine speed —
    # below 4 it grace-extends, making the soak timing-independent
    robust_kw = dict(admission_control=True, round_deadline_s=2.0,
                     round_deadline_grace_s=1.0, min_aggregation_clients=4)
    for op, run_id in (("trimmed_mean:0.25", "bz_tm"), ("median", "bz_md")):
        _, server, _ = _run_federation(
            args_factory, run_id, adversaries=ADV, robust_agg=op,
            **robust_kw)
        hist = server.aggregator.metrics_history
        assert len(hist) == 6, f"{op}: not every round completed"
        # the NaN client never reached the global model: finite EVERY round
        assert all(np.isfinite(m["test_loss"]) for m in hist), op
        final_global = tree_to_vector(
            server.aggregator.get_global_model_params())
        assert np.isfinite(np.asarray(final_global)).all(), op
        robust_loss = hist[-1]["test_loss"]
        assert robust_loss <= 1.1 * clean, (op, robust_loss, clean)
        # NaN uploads were quarantined, never counted as received (on a
        # loaded machine a late NaN upload may be stale-dropped instead
        # of quarantined for some rounds, so this is a floor, not 1/round)
        assert server.aggregator.quarantined_total >= 2, op
    assert "fedml_quarantined_updates_total" in metrics.render_prometheus()


@pytest.mark.slow
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_deadline_paced_round_with_straggler(args_factory):
    """Acceptance: over-provisioned selection (K+m) completes each round
    with the first K results BEFORE the injected straggler finishes; the
    deadline pacer (no over-provision) drops the straggler like a
    heartbeat-dead client and the run still completes every round."""
    DELAY = 4.0
    # -- K of K+m: completion target stays K=3, fleet is 4 ----------------
    args, server, elapsed = _run_federation(
        args_factory, "straggle_op", adversaries={4: f"slow:{DELAY}"},
        n=4, comm_round=2, client_num_per_round=3, over_provision=1)
    assert int(args.round_idx) == 2
    assert len(server.aggregator.metrics_history) == 2
    # both rounds closed on the 3 fast arrivals, not the 4s straggler
    assert elapsed < 2 * DELAY * 0.9, (
        f"{elapsed:.1f}s — rounds waited for the straggler")

    # -- deadline drop: 3 of 3 with one straggler, deadline < delay -------
    args2, server2, elapsed2 = _run_federation(
        args_factory, "straggle_dl", adversaries={3: f"slow:{DELAY}"},
        n=3, comm_round=2, round_deadline_s=1.0,
        round_deadline_grace_s=0.5, min_aggregation_clients=2)
    assert int(args2.round_idx) == 2
    assert len(server2.aggregator.metrics_history) == 2
    assert elapsed2 < 2 * DELAY * 0.9
    # the straggler was dropped from the round exactly like a
    # heartbeat-dead client
    assert server2.client_online_status[3] is False
