"""Privacy of the observability artifacts: a chaos INPROC federation's
ledger.jsonl and metrics exposition must carry round anatomy — never
array payloads, raw examples or secrets.  The dynamic complement of the
taint tier's static PRIV001/PRIV003 verdicts, plus the wire-audit soak:
every key the run put on the wire is in the committed contract."""

import json
import threading
import time

import pytest

from fedml_tpu.core.distributed.communication.chaos import ChaosCommManager
from fedml_tpu.core.distributed.communication.inprocess import (
    InProcCommManager,
)
from fedml_tpu.core.mlops import ledger, metrics, wire_audit

#: longest numeric list a ledger attr may carry: round anatomy is
#: scalars and short id lists, a payload leaf is thousands of floats
MAX_NUMERIC_LIST = 8
MAX_STR_VALUE = 512


def _assert_value_free(value, where):
    if isinstance(value, dict):
        for k, v in value.items():
            _assert_value_free(v, f"{where}.{k}")
        return
    if isinstance(value, (list, tuple)):
        numeric = [v for v in value if isinstance(v, (int, float))]
        assert not (len(numeric) > MAX_NUMERIC_LIST
                    and len(numeric) == len(value)), (
            f"{where}: numeric array of {len(value)} elements looks like "
            f"a tensor payload")
        for i, v in enumerate(value):
            _assert_value_free(v, f"{where}[{i}]")
        return
    if isinstance(value, str):
        assert len(value) <= MAX_STR_VALUE, (
            f"{where}: {len(value)}-char string value looks like a "
            f"serialized payload")
        assert "array(" not in value, f"{where}: ndarray repr in artifact"


def test_label_cardinality_cap_under_racing_observes():
    """A hostile or unbounded label value (client-controlled strings) must
    not grow the exposition past MAX_LABEL_SETS per metric: overflow
    writes land in a never-exported child and are counted in
    fedml_metrics_dropped_labels_total."""
    reg = metrics.MetricsRegistry()
    ctr = reg.counter("fedml_test_cap_total", "cap test", labels=("who",))
    n_threads, per_thread = 8, 200   # 1600 distinct label sets >> 512
    barrier = threading.Barrier(n_threads)

    def worker(t):
        barrier.wait()
        for i in range(per_thread):
            ctr.labels(who=f"t{t}-v{i}").inc()

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    assert len(ctr.children()) == metrics.MAX_LABEL_SETS
    expected_dropped = n_threads * per_thread - metrics.MAX_LABEL_SETS
    dropped = reg.collect()[metrics.DROPPED_METRIC]
    (child,) = dropped.children().values()
    assert child.value == expected_dropped
    # overflow absorbed every dropped write but is never exported
    assert ctr._overflow is not None
    assert ctr._overflow.value == expected_dropped
    rendered = reg.render_prometheus()
    assert rendered.count("fedml_test_cap_total{") == metrics.MAX_LABEL_SETS


def test_chaos_run_artifacts_carry_no_payloads(args_factory, tmp_path):
    import fedml_tpu
    from fedml_tpu.core.distributed.fedml_comm_manager import (
        register_comm_backend,
    )
    from fedml_tpu.cross_silo.runner import init_client, init_server

    def factory(args, rank=0, size=0):
        return ChaosCommManager(
            InProcCommManager(rank, size, str(args.run_id)),
            drop_p=0.15, dup_p=0.1, delay_p=0.2, max_delay_s=0.03,
            seed=900 + rank)

    register_comm_backend("CHAOS_PRIV", factory)
    wire_audit.arm(True)
    try:
        args = fedml_tpu.init(args_factory(
            training_type="cross_silo", client_num_in_total=2,
            client_num_per_round=2, comm_round=2, data_scale=0.2,
            learning_rate=0.1, frequency_of_the_test=1,
            run_id="priv_artifacts", run_ledger=True,
            log_file_dir=str(tmp_path), reliable=True,
            reliable_retx_initial_s=0.05, reliable_retx_max_s=0.5))
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        server = init_server(args, dataset, bundle, backend="CHAOS_PRIV")
        clients = [init_client(args, dataset, bundle, rank,
                               backend="CHAOS_PRIV") for rank in (1, 2)]
        threads = [threading.Thread(target=c.run, daemon=True)
                   for c in clients]
        for t in threads:
            t.start()
        t0 = time.monotonic()
        server.run()
        elapsed = max(time.monotonic() - t0, 1e-9)
        for t in threads:
            t.join(timeout=15)
        assert int(args.round_idx) == 2
        snap = wire_audit.snapshot()
    finally:
        wire_audit.arm(False)
        wire_audit._armed = None
        ledger.reset()   # flush + close the jsonl

    # -- the wire-audit soak: observed keys ⊆ committed contract, and the
    # recorder's self-measured bookkeeping stays under the 2% CI budget
    assert snap["contract_loaded"], "benchmarks/wire_contract.json missing"
    assert snap["messages"] > 0
    assert snap["violations"] == [], snap["violations"]
    assert snap["overhead_s"] / elapsed < 0.02

    # -- ledger.jsonl: structured round anatomy, no tensor payloads
    ledger_file = tmp_path / "ledger.jsonl"
    assert ledger_file.is_file()
    raw = ledger_file.read_bytes()
    assert b"array(" not in raw
    records = [json.loads(line) for line in raw.splitlines() if line]
    assert records, "chaos run produced an empty ledger"
    for i, rec in enumerate(records):
        _assert_value_free(rec, f"ledger[{i}]")

    # -- metrics exposition: bounded label values, no payload-shaped text
    prom = metrics.render_prometheus()
    (tmp_path / "metrics.prom").write_text(prom)
    assert "array(" not in prom
    for line in prom.splitlines():
        if line.startswith("#"):
            continue
        assert len(line) <= 1024, f"metrics line too long: {line[:120]}"
        assert "[[" not in line, f"nested array in metrics line: {line}"
