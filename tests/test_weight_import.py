"""Pretrained-weight import (VERDICT r3 item 6): npz/safetensors →
functional-LM pytree with a shape/name report.  The gold test checks
logit equivalence against transformers' own GPT2LMHeadModel on an
imported GPT-2-format checkpoint — transposes, fused-qkv splits, biases,
LN epsilon and gelu flavor all have to be right for it to pass."""

import json
import struct

import numpy as np
import pytest

from fedml_tpu.parallel.seq_parallel import init_lm_params, lm_forward
from fedml_tpu.train.llm.weight_import import (
    export_lm_weights,
    import_lm_weights,
    read_checkpoint,
    save_lm_checkpoint,
)

import jax
import jax.numpy as jnp


def _full_attn(q, k, v):
    """Reference causal attention for equivalence tests: [B,H,T,Dh]."""
    dh = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(float(dh))
    t = q.shape[2]
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None], scores, -1e9)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)


def test_native_roundtrip(tmp_path):
    params = init_lm_params(jax.random.PRNGKey(0), vocab=50, dim=32,
                            layers=2, heads=4, max_len=16)
    path = str(tmp_path / "lm.npz")
    save_lm_checkpoint(params, path)
    loaded, report = import_lm_weights(path, schema="auto")
    assert not report["missing"] and not report["unused"]
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        params, loaded)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 50, (2, 16)))
    np.testing.assert_allclose(
        np.asarray(lm_forward(params, toks, 4, _full_attn)),
        np.asarray(lm_forward(loaded, toks, 4, _full_attn)), atol=1e-6)


def test_gpt2_import_matches_transformers_logits(tmp_path):
    """Build a tiny random GPT-2 with transformers, export its state dict
    to npz, import through the mapper, and require logit agreement."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    model = transformers.GPT2LMHeadModel(cfg).eval()

    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    path = str(tmp_path / "gpt2.npz")
    np.savez(path, **sd)

    params, report = import_lm_weights(path, schema="auto")
    assert not report["missing"], report["missing"]
    # everything in the file is either mapped or a structural mask buffer
    assert not report["unused"], report["unused"]

    toks_np = np.random.RandomState(0).randint(0, 64, (2, 16))
    with torch.no_grad():
        ref = model(torch.from_numpy(toks_np)).logits.numpy()
    ours = np.asarray(lm_forward(params, jnp.asarray(toks_np), 4,
                                 _full_attn))
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=1e-3)


def test_safetensors_stdlib_reader(tmp_path):
    """The dependency-free .safetensors parser reads what the format
    spec says: 8-byte header length + JSON header + raw little-endian
    buffer (bf16 widened to f32)."""
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b32 = np.asarray(jnp.asarray([[1.5, -2.0]], jnp.bfloat16))
    raw_a = a.tobytes()
    u16 = np.asarray(jnp.asarray(b32, jnp.bfloat16)).view(np.uint16)
    raw_b = u16.tobytes()
    header = {
        "a": {"dtype": "F32", "shape": [2, 3],
              "data_offsets": [0, len(raw_a)]},
        "b": {"dtype": "BF16", "shape": [1, 2],
              "data_offsets": [len(raw_a), len(raw_a) + len(raw_b)]},
    }
    hb = json.dumps(header).encode()
    path = tmp_path / "t.safetensors"
    path.write_bytes(struct.pack("<Q", len(hb)) + hb + raw_a + raw_b)

    # force the stdlib path even if the safetensors lib is installed
    from fedml_tpu.train.llm import weight_import as wi

    state = wi._read_safetensors(str(path))
    np.testing.assert_array_equal(state["a"], a)
    np.testing.assert_allclose(state["b"], np.asarray(b32, np.float32))


def test_trainer_finetunes_from_imported_weights(tmp_path):
    """finetune-from-imported-weights end to end: import → LLMTrainer →
    loss decreases from the pretrained starting point."""
    import fedml_tpu
    from fedml_tpu.train.llm.trainer import LLMTrainConfig, LLMTrainer

    params = init_lm_params(jax.random.PRNGKey(1), vocab=90, dim=32,
                            layers=1, heads=4, max_len=64)
    path = str(tmp_path / "pretrained.npz")
    save_lm_checkpoint(params, path)

    args = fedml_tpu.Config(model="functional_lm", dataset="shakespeare",
                            lm_dim=32, lm_layers=1, lm_heads=4,
                            lm_max_len=64, compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 90)
    cfg = LLMTrainConfig(seq_len=32, batch_size=4, learning_rate=3e-3,
                         epochs=2, use_lora=False,
                         pretrained_path=path)
    tr = LLMTrainer(bundle, cfg)
    assert tr.import_report and not tr.import_report["missing"]
    # the trainer actually starts FROM the imported weights
    np.testing.assert_array_equal(
        np.asarray(tr.variables["params"]["embed"]),
        np.asarray(params["embed"]))

    rng = np.random.RandomState(0)
    token_ids = rng.randint(0, 90, 8 * 4 * 33)
    out = tr.train(token_ids)
    hist = out["loss_history"]
    assert hist[-1] < hist[0]
    assert np.isfinite(out["train_loss"])


def test_kv_cache_serving_matches_forward_on_imported_gpt2(tmp_path):
    """The KV-cache serving path (prefill + decode_step) must reproduce
    lm_forward on an imported checkpoint WITH biases — it reimplements
    the block math, so missing bias support would silently serve wrong
    logits."""
    transformers = pytest.importorskip("transformers")

    cfg = transformers.GPT2Config(
        vocab_size=48, n_positions=24, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    model = transformers.GPT2LMHeadModel(cfg).eval()
    sd = {k: v.detach().cpu().numpy()
          for k, v in model.state_dict().items()}
    params, report = import_lm_weights(sd, schema="gpt2")
    assert not report["missing"]

    from fedml_tpu.serving.kv_cache_lm import decode_step, prefill

    toks_np = np.random.RandomState(1).randint(0, 48, (2, 10))
    toks = jnp.asarray(toks_np)
    full = np.asarray(lm_forward(params, toks, 4, _full_attn))

    length = jnp.asarray([10, 10])
    cache, last = prefill(params, toks, length, heads=4, max_len=16)
    np.testing.assert_allclose(np.asarray(last), full[:, -1], atol=1e-4,
                               rtol=1e-3)

    # one decode step == forward over the extended sequence's last logit
    nxt = jnp.asarray(np.random.RandomState(2).randint(0, 48, (2,)))
    cache, logits = decode_step(params, cache, nxt,
                                jnp.asarray([10, 10]), heads=4)
    ext = jnp.concatenate([toks, nxt[:, None]], axis=1)
    full_ext = np.asarray(lm_forward(params, ext, 4, _full_attn))
    np.testing.assert_allclose(np.asarray(logits), full_ext[:, -1],
                               atol=1e-4, rtol=1e-3)


def test_biasfree_gpt2_schema_passes_strict_and_mismatch_raises():
    """Biases are optional (strict must not fail on a bias-free gpt2-named
    checkpoint), while dim/vocab/head mismatches must raise loudly —
    JAX would otherwise clamp out-of-bounds gathers silently."""
    from fedml_tpu.train.llm.weight_import import validate_lm_shapes

    params = init_lm_params(jax.random.PRNGKey(2), vocab=32, dim=16,
                            layers=1, heads=4, max_len=8)
    # build a bias-free gpt2-style dict from our own params
    sd = {
        "wte.weight": np.asarray(params["embed"]),
        "wpe.weight": np.asarray(params["pos"]),
        "ln_f.weight": np.asarray(params["ln_f"]["scale"]),
        "ln_f.bias": np.asarray(params["ln_f"]["bias"]),
    }
    blk = params["blocks"][0]
    sd["h.0.ln_1.weight"] = np.asarray(blk["ln1"]["scale"])
    sd["h.0.ln_1.bias"] = np.asarray(blk["ln1"]["bias"])
    sd["h.0.ln_2.weight"] = np.asarray(blk["ln2"]["scale"])
    sd["h.0.ln_2.bias"] = np.asarray(blk["ln2"]["bias"])
    sd["h.0.attn.c_attn.weight"] = np.concatenate(
        [np.asarray(blk[k]) for k in ("wq", "wk", "wv")], axis=1)
    sd["h.0.attn.c_proj.weight"] = np.asarray(blk["wo"])
    sd["h.0.mlp.c_fc.weight"] = np.asarray(blk["w1"])
    sd["h.0.mlp.c_proj.weight"] = np.asarray(blk["w2"])

    loaded, report = import_lm_weights(sd, schema="gpt2", strict=True)
    assert not report["missing"]
    assert report["optional_absent"]          # the absent biases, recorded
    toks = jnp.asarray(np.random.RandomState(3).randint(0, 32, (1, 8)))
    np.testing.assert_allclose(
        np.asarray(lm_forward(params, toks, 4, _full_attn)),
        np.asarray(lm_forward(loaded, toks, 4, _full_attn)), atol=1e-6)

    validate_lm_shapes(loaded, vocab=32, dim=16, heads=4, min_len=8)
    with pytest.raises(ValueError, match="vocab"):
        validate_lm_shapes(loaded, vocab=64)
    with pytest.raises(ValueError, match="heads"):
        validate_lm_shapes(loaded, heads=3)
    with pytest.raises(ValueError, match="max_len"):
        validate_lm_shapes(loaded, min_len=999)


@pytest.mark.slow
def test_openai_serving_from_imported_gpt2_checkpoint(tmp_path):
    """Deploy half of the import loop: GPT-2-format checkpoint FILE →
    kv_lm_from_checkpoint → continuous-batching engine → OpenAI chat API.
    Greedy first token must equal transformers' own argmax next token."""
    import urllib.request

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from fedml_tpu.serving.kv_cache_lm import kv_lm_from_checkpoint
    from fedml_tpu.serving.llm_engine import (
        KVCacheLLMEngine,
        LLMEnginePredictor,
    )
    from fedml_tpu.serving.openai_api import OpenAIServer

    cfg = transformers.GPT2Config(
        vocab_size=90, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    model = transformers.GPT2LMHeadModel(cfg).eval()
    path = str(tmp_path / "gpt2_tiny.npz")
    np.savez(path, **{k: v.detach().cpu().numpy()
                      for k, v in model.state_dict().items()})

    lm = kv_lm_from_checkpoint(path, heads=4)
    assert lm.vocab == 90 and lm.max_len == 64
    engine = KVCacheLLMEngine(lm, max_batch=2)
    predictor = LLMEnginePredictor(engine)      # char codec, vocab 90
    server = OpenAIServer(predictor, model_name="gpt2-tiny", port=0)
    try:
        server.run(block=False)
        body = json.dumps({"model": "gpt2-tiny", "max_tokens": 4,
                            "temperature": 0,
                            "messages": [{"role": "user",
                                          "content": "hello"}]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/chat/completions",
            data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            out = json.loads(resp.read())
        text = out["choices"][0]["message"]["content"]
        assert len(text) == 4

        # greedy first token must sit at (or within float tolerance of)
        # transformers' argmax — random-init logits can tie to ~1e-4, so
        # exact-id equality would flake on tie-breaks
        # the server wraps messages in its chat template — compare on the
        # exact prompt the engine saw
        ids = predictor.encode("user: hello\nassistant:")
        with torch.no_grad():
            ref_logits = model(torch.tensor([ids])).logits[0, -1].numpy()
        ours = predictor.encode(text[0])[0]
        assert ref_logits[ours] >= ref_logits.max() - 1e-3, (
            text[0], float(ref_logits[ours]), float(ref_logits.max()))
    finally:
        server.stop()
        engine.stop()
