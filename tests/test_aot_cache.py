"""AOT executable-cache coverage (VERDICT r4 weak #2): the serialized
fused-round executable must (a) round-trip through a second PROCESS
without re-tracing/compiling, (b) never replay stale or corrupt
artifacts, and (c) keep the trust boundary of the pickle container
(refuse foreign-owned files).

Capability parity note: the reference has no warm-start machinery at all
(every run re-traces); this is a new TPU-era subsystem, so its tests are
new too (`simulation/parrot/parrot_api.py:_ensure_multi_round_step`).
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_api(args_factory, cache_dir, **kw):
    args = fedml_tpu.init(args_factory(
        backend="parrot", dataset="mnist", model="lr", data_scale=0.05,
        client_num_in_total=4, client_num_per_round=4, comm_round=2,
        aot_cache_dir=str(cache_dir), **kw))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, None, dataset, bundle).runner


def test_aot_cache_roundtrip_same_process(args_factory, tmp_path):
    """First build compiles + writes the artifact; a second API instance
    (fresh trace state) loads it and reports the hit."""
    api = _make_api(args_factory, tmp_path)
    api._ensure_multi_round_step()
    assert not api.aot_cache_hit
    arts = [f for f in os.listdir(tmp_path) if f.endswith(".jaxexp")]
    assert len(arts) == 1, arts
    # dir hardened to 0o700 (pickle trust domain)
    assert (os.stat(tmp_path).st_mode & 0o777) == 0o700

    warm = _make_api(args_factory, tmp_path)
    warm._ensure_multi_round_step()
    assert warm.aot_cache_hit
    # the loaded executable actually RUNS and trains
    rms = warm.run_rounds_fused(3)
    tl = np.asarray(rms["train_loss"])
    assert tl.shape == (3,) and np.isfinite(tl).all()


def test_aot_cache_stale_key_misses(args_factory, tmp_path):
    """Any digested config knob change must produce a different artifact
    path — a stale executable is never replayed."""
    api = _make_api(args_factory, tmp_path)
    p1 = api._aot_cache_path()
    api2 = _make_api(args_factory, tmp_path, learning_rate=0.05)
    p2 = api2._aot_cache_path()
    assert p1 != p2
    api3 = _make_api(args_factory, tmp_path, batch_size=8)
    assert api3._aot_cache_path() not in (p1, p2)


def test_aot_cache_corrupt_artifact_recompiles(args_factory, tmp_path):
    """A corrupt artifact must fall back to compile and still produce
    correct (finite, training) results — never wrong outputs."""
    api = _make_api(args_factory, tmp_path)
    path = api._aot_cache_path()
    with open(path, "wb") as f:
        f.write(b"not a pickle of an executable")
    api._ensure_multi_round_step()
    assert not api.aot_cache_hit          # fell back to compile
    rms = api.run_rounds_fused(2)
    assert np.isfinite(np.asarray(rms["train_loss"])).all()
    # and the rebuild overwrote the corrupt artifact with a loadable one
    warm = _make_api(args_factory, tmp_path)
    warm._ensure_multi_round_step()
    assert warm.aot_cache_hit


def test_aot_cache_disabled_writes_nothing(args_factory, tmp_path):
    api = _make_api(args_factory, tmp_path, parrot_aot_cache=False)
    api._ensure_multi_round_step()
    assert os.listdir(tmp_path) == []
    assert not api.aot_cache_hit


_CHILD = textwrap.dedent("""
    import json, os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, {repo!r})
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner
    import numpy as np
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="mnist", model="lr", backend="parrot", data_scale=0.05,
        client_num_in_total=4, client_num_per_round=4, comm_round=2,
        epochs=1, batch_size=16, learning_rate=0.1,
        enable_tracking=False, compute_dtype="float32",
        aot_cache_dir={cache!r}))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    api = FedMLRunner(args, None, dataset, bundle).runner
    t0 = time.time()
    api._ensure_multi_round_step()
    ready_s = time.time() - t0
    rms = api.run_rounds_fused(2)
    print("AOTPROOF " + json.dumps({{
        "hit": bool(api.aot_cache_hit), "ready_s": ready_s,
        "loss0": float(np.asarray(rms["train_loss"])[0])}}))
""")


@pytest.mark.slow
def test_aot_cache_warm_second_process(tmp_path):
    """The committed cross-process proof of the warm start (VERDICT r4
    item 2): a SECOND process must load the artifact (hit flag), skip
    trace+compile (ready time bound), and produce the same first-round
    loss (bit-identical executable, deterministic round math)."""
    import json

    cache = str(tmp_path / "aot")
    script = _CHILD.format(repo=REPO, cache=cache)

    def run():
        out = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=600,
                             cwd=REPO)
        for ln in out.stdout.splitlines():
            if ln.startswith("AOTPROOF "):
                return json.loads(ln[len("AOTPROOF "):])
        raise AssertionError(out.stderr[-3000:])

    cold = run()
    warm = run()
    assert not cold["hit"] and warm["hit"]
    # deserialization skips trace+lower+compile: generous bound that still
    # fails if the warm path silently recompiles (cold is several x more)
    assert warm["ready_s"] < cold["ready_s"] * 0.6, (cold, warm)
    assert warm["loss0"] == pytest.approx(cold["loss0"], abs=1e-6)
