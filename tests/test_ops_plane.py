"""Ops plane: CLI, local launcher, workflow DAG, serving endpoint."""

import json
import os
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest
from click.testing import CliRunner

from fedml_tpu.cli import cli


def test_cli_version_and_env():
    r = CliRunner().invoke(cli, ["version"])
    assert r.exit_code == 0 and "fedml_tpu" in r.output
    r = CliRunner().invoke(cli, ["env"])
    assert r.exit_code == 0
    info = json.loads(r.output)
    assert "python" in info and "jax" in info


def test_local_launcher_job_yaml(tmp_path):
    from fedml_tpu.scheduler.local_launcher import (
        build_job_package,
        launch_job_local,
        list_runs,
    )

    ws = tmp_path / "ws"
    ws.mkdir()
    (ws / "hello.py").write_text("print('hello from job')")
    job = tmp_path / "job.yaml"
    job.write_text(textwrap.dedent("""
        workspace: ws
        job_name: hello_job
        bootstrap: |
          echo bootstrap-ran
        job: |
          python hello.py
    """))
    result = launch_job_local(str(job))
    assert result.returncode == 0
    log = open(result.log_path).read()
    assert "bootstrap-ran" in log and "hello from job" in log
    assert any(r["job_name"] == "hello_job" for r in list_runs())
    # package build
    zip_path = build_job_package(str(job), str(tmp_path))
    import zipfile

    names = zipfile.ZipFile(zip_path).namelist()
    assert "job.yaml" in names and "workspace/hello.py" in names


def test_workflow_dag_chaining():
    from fedml_tpu.workflow.workflow import CallableJob, Workflow

    order = []

    def make(name, fn):
        def wrapped(inp):
            order.append(name)
            return fn(inp)
        return CallableJob(name, wrapped)

    a = make("a", lambda inp: {"x": 2})
    b = make("b", lambda inp: {"y": inp["x"] * 10})
    c = make("c", lambda inp: {"z": inp["y"] + 1})
    wf = Workflow("test")
    wf.add_job(a)
    wf.add_job(b, dependencies=[a])
    wf.add_job(c, dependencies=[b])
    out = wf.run()
    assert order == ["a", "b", "c"]
    assert out["c"]["z"] == 21


def test_workflow_detects_cycle():
    from fedml_tpu.workflow.workflow import CallableJob, Workflow

    a = CallableJob("a", lambda i: {})
    b = CallableJob("b", lambda i: {})
    wf = Workflow("cyc")
    wf.add_job(a, dependencies=[b])
    wf.add_job(b, dependencies=[a])
    with pytest.raises(ValueError, match="cycle"):
        wf.run()


def test_serving_endpoint_predict_ready_and_streaming():
    from fedml_tpu.serving import FedMLInferenceRunner, FedMLPredictor

    class Echo(FedMLPredictor):
        def predict(self, request):
            if request.get("stream"):
                return (f"tok{i} " for i in range(3))
            return {"echo": request.get("text", ""), "n": 1}

    port = 23451
    runner = FedMLInferenceRunner(Echo(), host="127.0.0.1", port=port)
    runner.run(block=False, prefer_fastapi=False)
    time.sleep(0.2)
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/ready") as r:
        assert json.loads(r.read())["ready"] is True
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"text": "hi"}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read()) == {"echo": "hi", "n": 1}
    req2 = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"stream": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req2) as r:
        assert b"tok0" in r.read()
    runner.stop()


def test_federated_serving_plane(args_factory):
    """FL-to-serving handoff: server ships the model to N serving nodes,
    endpoints come up, health checks report stats, fleet tears down."""
    import numpy as np
    from fedml_tpu.serving.federated_serving import deploy_federated

    rng = np.random.RandomState(0)
    params = {"w2": rng.randn(6, 3).astype(np.float32),
              "b2": np.zeros(3, np.float32)}
    args = args_factory(run_id="fs1", serving_oneshot=True)
    out = deploy_federated(args, "lin-model", params, n_nodes=2)
    assert len(out["endpoints"]) == 2 and not out["failed"]
    assert not out["timed_out"]
    assert all(h["healthy"] for h in out["health"].values()), out


def test_federated_serving_node_failure_no_hang(args_factory):
    """A node whose predictor factory raises must be reported as failed —
    not hang the deploy (regression: server waited on ENDPOINT_UP forever)."""
    import numpy as np
    from fedml_tpu.serving.fedml_predictor import LinearHeadPredictor
    from fedml_tpu.serving.federated_serving import deploy_federated

    calls = []

    def flaky_factory(params):
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("boom")
        return LinearHeadPredictor(params)

    rng = np.random.RandomState(0)
    params = {"w2": rng.randn(6, 3).astype(np.float32),
              "b2": np.zeros(3, np.float32)}
    args = args_factory(run_id="fs2", serving_oneshot=True,
                        serving_deploy_timeout=60.0)
    out = deploy_federated(args, "lin-model", params, n_nodes=2,
                           predictor_factory=flaky_factory)
    assert not out["timed_out"]
    assert len(out["failed"]) == 1 and len(out["endpoints"]) == 1
    failed_rank = out["failed"][0]
    assert out["health"][failed_rank]["healthy"] is False


def test_openai_compatible_api():
    import json
    import time
    import urllib.request

    from fedml_tpu.serving.fedml_predictor import FedMLPredictor
    from fedml_tpu.serving.openai_api import OpenAIServer

    class Chat(FedMLPredictor):
        def predict(self, request):
            assert "assistant:" in request["prompt"]
            if request.get("max_tokens", 0) >= 3:
                return iter(["hello ", "from ", "fedml"])
            return "short"

    srv = OpenAIServer(Chat(), model_name="test-model", host="127.0.0.1",
                       port=0)
    srv.run(block=False)
    time.sleep(0.2)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(
                f"{base}/v1/models") as r:
            models = json.loads(r.read())
        assert models["data"][0]["id"] == "test-model"

        body = {"model": "test-model", "max_tokens": 16,
                "messages": [{"role": "user", "content": "hi"}]}
        req = urllib.request.Request(
            f"{base}/v1/chat/completions",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert out["object"] == "chat.completion"
        assert out["choices"][0]["message"]["content"] == "hello from fedml"

        body["stream"] = True
        req2 = urllib.request.Request(
            f"{base}/v1/chat/completions",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req2) as r:
            raw = r.read().decode()
        assert "data: [DONE]" in raw
        assert '"chat.completion.chunk"' in raw
    finally:
        srv.stop()


def test_diagnosis_report(args_factory, tmp_path):
    from fedml_tpu.scheduler.diagnosis import diagnose

    args = args_factory(object_store_dir=str(tmp_path))
    report = diagnose(args)
    assert report["all_ok"], report
    assert set(report) >= {"broker", "object_store", "grpc_port",
                           "accelerator"}
    assert "inproc" in report["broker"]["detail"]


def test_diagnosis_cli(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    res = CliRunner().invoke(cli, ["diagnosis", "--check", "grpc_port",
                                   "--check", "accelerator"])
    assert res.exit_code == 0, res.output
    assert '"all_ok": true' in res.output


def test_diagnosis_unknown_check_rejected():
    import pytest as _pytest

    from fedml_tpu.scheduler.diagnosis import diagnose

    with _pytest.raises(ValueError, match="unknown checks"):
        diagnose(checks=["brokr"])


def test_cli_train_and_federate_aliases(tmp_path):
    import textwrap

    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    ws = tmp_path / "ws"
    ws.mkdir()
    jy = tmp_path / "job.yaml"
    jy.write_text(textwrap.dedent("""
        workspace: ws
        job_name: t1
        job: "echo TYPE=$FEDML_JOB_TYPE"
    """))
    for cmd in ("train", "federate"):
        res = CliRunner().invoke(cli, [cmd, "run", str(jy)])
        assert res.exit_code == 0, res.output
        import json as _json

        log_path = _json.loads(res.output.strip().splitlines()[-1])["log_path"]
        assert f"TYPE={cmd}" in open(log_path).read()
