"""Cross-device (BeeHive) and cross-cloud (Cheetah) plane tests: FedMLRunner
dispatch, native-edge federation via the runner, per-round edge artifacts,
and intra-cloud mesh training."""

import os

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


def test_cross_device_simulated_runner(args_factory, tmp_path):
    """FedMLRunner(training_type=cross_device) federates native edge clients
    and writes per-round edge artifacts + run config."""
    art = str(tmp_path / "edge_art")
    m = _run(args_factory(training_type="cross_device", role="simulated",
                          backend="MQTT_S3", client_num_in_total=2,
                          client_num_per_round=2, comm_round=2,
                          data_scale=0.4, learning_rate=0.1, momentum=0.9,
                          run_id="xd1", object_store_dir=str(tmp_path / "s3"),
                          edge_artifact_dir=art))
    assert np.isfinite(m["test_loss"])
    assert os.path.exists(os.path.join(art, "run_config.json"))
    # a round closed → artifact emitted in the native layout
    arts = [f for f in os.listdir(art) if f.startswith("global_model_r")]
    assert arts, os.listdir(art)
    from fedml_tpu.cross_device.server import read_edge_bundle

    bundle = read_edge_bundle(os.path.join(art, sorted(arts)[0]))
    assert "w2" in bundle and bundle["w2"].ndim == 2


def test_cross_device_rejects_client_role(args_factory):
    with pytest.raises(RuntimeError, match="server-only"):
        _run(args_factory(training_type="cross_device", role="client",
                          run_id="xd2"))


def test_cross_cloud_federation_with_intra_cloud_mesh(args_factory):
    """Cheetah: cross-silo protocol between clouds; each cloud trains
    data-parallel over the local device mesh."""
    m = _run(args_factory(training_type="cross_cloud", backend="INPROC",
                          role="simulated", client_num_in_total=2,
                          client_num_per_round=2, comm_round=2,
                          data_scale=0.3, run_id="xc1"))
    assert np.isfinite(m["test_loss"])


def test_cross_cloud_forces_hierarchical_scenario(args_factory):
    from fedml_tpu.cross_cloud.runner import _force_cloud_scenario

    args = fedml_tpu.init(args_factory(run_id="xc2"))
    args = _force_cloud_scenario(args)
    assert args.scenario == "hierarchical"
    assert int(args.n_proc_per_node) >= 1
