"""ONE test that runs the whole product lifecycle (VERDICT r4 item 5 /
r3 #8): import GPT-2 → LoRA SFT → quantize → versioned model card →
replica-process serving behind the gateway → OpenAI-API load →
EndpointDB-metrics-driven scale-up → POST /rollback.

Every stage already has its own unit tests; this is the stitched flow the
reference runs as card→push→deploy→infer→monitor
(`model_scheduler/device_model_cards.py`, `device_model_deployment.py:
89-928`, `comm_utils/job_monitor.py`) — exercised here as one chain with
real subprocess replicas and real HTTP at every hop.
"""

import json
import os
import textwrap
import urllib.request

import numpy as np
import pytest

#: the card's replica-side predictor: loads the card's checkpoint, int8-
#: quantizes it, and serves through the continuous-batching KV engine.
#: Written into each card version so replica PROCESSES (spawned by
#: ReplicaProcessManager) resolve it via predictor.py → class Predictor.
_PREDICTOR_PY = textwrap.dedent("""
    import os

    from fedml_tpu.serving.kv_cache_lm import kv_lm_from_checkpoint
    from fedml_tpu.serving.llm_engine import (
        KVCacheLLMEngine,
        LLMEnginePredictor,
    )
    from fedml_tpu.serving.quantization import QuantizedKVCacheLM


    class Predictor(LLMEnginePredictor):
        def __init__(self):
            lm = kv_lm_from_checkpoint(
                os.path.join(os.path.dirname(__file__), "model.npz"),
                heads=4)
            qlm = QuantizedKVCacheLM.from_lm(lm)   # int8 weights
            super().__init__(KVCacheLLMEngine(qlm, max_batch=4,
                                              tokens_per_dispatch=4))
""")


def _post(url, body, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _chat(port, text, max_tokens=6):
    out = _post(f"http://127.0.0.1:{port}/v1/chat/completions",
                {"model": "lifecycle", "max_tokens": max_tokens,
                 "temperature": 0,
                 "messages": [{"role": "user", "content": text}]})
    return out["choices"][0]["message"]["content"]


@pytest.mark.slow
def test_one_command_lifecycle(tmp_path):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    import fedml_tpu
    from fedml_tpu.scheduler.autoscaler import AutoscalePolicy
    from fedml_tpu.scheduler.model_cards import (
        ModelCardRegistry,
        _resolve_predictor,
    )
    from fedml_tpu.serving.quantization import QuantizedKVCacheLM
    from fedml_tpu.serving.serve_entry import ServeGateway
    from fedml_tpu.train.llm.lora import apply_lora
    from fedml_tpu.train.llm.trainer import LLMTrainConfig, LLMTrainer
    from fedml_tpu.train.llm.weight_import import save_lm_checkpoint

    # ---- 1. IMPORT: a real HF-format GPT-2 checkpoint file -------------
    cfg = transformers.GPT2Config(
        vocab_size=90, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    hf = transformers.GPT2LMHeadModel(cfg).eval()
    v1_dir = tmp_path / "v1"
    v1_dir.mkdir()
    np.savez(v1_dir / "model.npz",
             **{k: v.detach().cpu().numpy()
                for k, v in hf.state_dict().items()})
    (v1_dir / "predictor.py").write_text(_PREDICTOR_PY)

    # ---- 2. LoRA SFT from the imported checkpoint ----------------------
    args = fedml_tpu.Config(model="functional_lm", dataset="shakespeare",
                            lm_dim=32, lm_layers=2, lm_heads=4,
                            lm_max_len=64, compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 90)
    tcfg = LLMTrainConfig(seq_len=32, batch_size=4, epochs=2,
                          learning_rate=3e-3, use_lora=True, lora_rank=4,
                          pretrained_path=str(v1_dir / "model.npz"))
    trainer = LLMTrainer(bundle, tcfg)
    assert trainer.import_report and not trainer.import_report["missing"]
    rng = np.random.RandomState(0)
    out = trainer.train(rng.randint(0, 90, 4 * 4 * 33 * 2))
    assert out["loss_history"][-1] < out["loss_history"][0]

    # merged (base + LoRA) weights become version 2 of the SAME card
    merged = apply_lora(trainer.variables["params"], trainer.lora,
                        tcfg.lora_alpha)
    v2_dir = tmp_path / "v2"
    v2_dir.mkdir()
    save_lm_checkpoint(merged, str(v2_dir / "model.npz"))
    (v2_dir / "predictor.py").write_text(_PREDICTOR_PY)

    # ---- 3. QUANTIZE is part of the card's serving path; prove the
    # resolved predictor actually serves int8 weights -------------------
    reg = ModelCardRegistry(root=str(tmp_path / "registry"))
    card_v1 = reg.create("lifecycle", str(v1_dir))
    in_proc = _resolve_predictor(reg.get("lifecycle"))
    assert isinstance(in_proc.engine.lm, QuantizedKVCacheLM)
    in_proc.engine.stop()
    card_v2 = reg.create("lifecycle", str(v2_dir))
    assert card_v2["version"] != card_v1["version"]

    # ---- 4. SERVE: gateway + replica process on the v2 card ------------
    gw = ServeGateway(
        "lifecycle", registry_root=reg.root, replicas=1,
        db_path=str(tmp_path / "metrics.sqlite"),
        policy=AutoscalePolicy(min_replicas=1, max_replicas=2,
                               target_qps_per_replica=0.01,
                               cooldown_s=0.0),
        autoscale_interval_s=3600.0).start()
    try:
        from fedml_tpu.serving.openai_api import OpenAIServer

        # OpenAI-compatible front door FOR THE GATEWAY: chat requests
        # flatten to a prompt and flow through /predict → replica →
        # quantized KV engine, with per-request metrics into EndpointDB
        class GatewayPredictor:
            def predict(self, request):
                out = _post(f"{gw.url}/predict", dict(request),
                            timeout=300)
                # replicas return the predictor's value directly (a str);
                # dict-shaped predictors return {"text": ...}
                return out["text"] if isinstance(out, dict) else out

            def ready(self):
                return True

        api = OpenAIServer(GatewayPredictor(), model_name="lifecycle",
                           port=0)
        api.run(block=False)

        # ---- 5. OpenAI-API load (v2 = SFT'd weights serve) -------------
        sft_text = _chat(api.port, "hello there")
        assert isinstance(sft_text, str) and len(sft_text) == 6
        for _ in range(5):
            _chat(api.port, "hello there")

        # ---- 6. EndpointDB-driven scale-up -----------------------------
        w = gw.db.window("lifecycle", window_s=300.0)
        assert w["qps"] > 0                       # load was recorded
        n = gw.autoscale_tick()                   # metrics → autoscaler
        assert n == 2
        assert gw.manager.live_count() == 2

        # ---- 7. POST /rollback: v1 bytes serve again -------------------
        rb = _post(f"{gw.url}/rollback", {})
        assert rb["version"] == card_v1["version"]
        base_text = _chat(api.port, "hello there")
        assert len(base_text) == 6
        api.stop()
    finally:
        gw.stop()

    # the two versions are genuinely different FUNCTIONS (SFT moved the
    # weights): compare full-precision logits — greedy TEXT can coincide
    # on a tiny random model whose int8 serving flattens the LoRA delta
    import jax.numpy as jnp

    from fedml_tpu.serving.kv_cache_lm import kv_lm_from_checkpoint

    ids = jnp.asarray([[1, 2, 3, 4]])
    lg1 = kv_lm_from_checkpoint(str(v1_dir / "model.npz"),
                                heads=4).full_logits(ids)
    lg2 = kv_lm_from_checkpoint(str(v2_dir / "model.npz"),
                                heads=4).full_logits(ids)
    assert float(np.abs(np.asarray(lg1) - np.asarray(lg2)).max()) > 1e-4


def test_inference_runner_stop_releases_port():
    """stop() must release the listening socket (shutdown + join is not
    enough — only server_close() frees the fd), so the port can be
    rebound immediately."""
    import socket

    from fedml_tpu.serving.fedml_inference_runner import serve_ephemeral
    from fedml_tpu.serving.fedml_predictor import FedMLPredictor

    class Echo(FedMLPredictor):
        def predict(self, request):
            return {"echo": request}

    runner = serve_ephemeral(Echo())
    port = runner.port
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/ready",
                                timeout=5) as r:
        assert json.loads(r.read())["ready"] is True
    runner.stop()
    with socket.socket() as s:  # rebinding the exact port must succeed
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))
