"""FHE (Paillier additively-homomorphic aggregation) tests.

Capability parity target: reference `core/fhe/fhe_agg.py` (TenSEAL CKKS
fhe_enc/fhe_dec/fhe_fedavg wired into the alg_frame lifecycle hooks).
Small key sizes here are for test speed only.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.core.fhe import FedMLFHE, PaillierCodec, keygen


@pytest.fixture(scope="module")
def codec():
    pub, priv = keygen(256)
    return PaillierCodec(pub), priv


def test_paillier_roundtrip(codec):
    c, priv = codec
    v = np.array([0.0, 1.5, -2.25, 100.0, -0.0001, 3.14159])
    enc = c.encrypt(v)
    dec = c.decrypt(priv, enc)
    np.testing.assert_allclose(dec, np.clip(v, -255, 255), atol=2e-4)


def test_paillier_weighted_sum(codec):
    c, priv = codec
    rng = np.random.RandomState(0)
    vs = [rng.randn(40) for _ in range(4)]
    ns = [10.0, 30.0, 20.0, 40.0]
    total = sum(ns)
    w_int = [c.quantize_weight(n / total) for n in ns]
    encs = [c.encrypt(v) for v in vs]
    agg = c.weighted_sum(list(zip(w_int, encs)))
    dec = c.decrypt(priv, agg)
    expected = sum((n / total) * v for n, v in zip(ns, vs))
    np.testing.assert_allclose(dec, expected, atol=1e-3)


def test_seeded_keygen_and_modulus_mismatch():
    pub1, _ = keygen(256, seed=7)
    pub2, _ = keygen(256, seed=7)
    assert pub1.n == pub2.n  # pre-shared fhe_key_seed → identical keys
    pub3, _ = keygen(256, seed=8)
    assert pub1.n != pub3.n
    c1, c3 = PaillierCodec(pub1), PaillierCodec(pub3)
    a, b = c1.encrypt(np.ones(3)), c3.encrypt(np.ones(3))
    with pytest.raises(ValueError):
        PaillierCodec.add(a, b)  # mismatched moduli must raise, not garble


def test_fhe_rejects_incompatible_config():
    fhe = FedMLFHE.get_instance()
    with pytest.raises(ValueError):
        fhe.init(fedml_tpu.Config(enable_fhe=True, fhe_key_size=256,
                                  federated_optimizer="FedOpt"))
    with pytest.raises(ValueError):
        fhe.init(fedml_tpu.Config(enable_fhe=True, fhe_key_size=256,
                                  backend="parrot"))
    fhe.init(fedml_tpu.Config())


def test_fhe_tree_fedavg():
    fhe = FedMLFHE.get_instance()
    fhe.init(fedml_tpu.Config(enable_fhe=True, fhe_key_size=256))
    try:
        t1 = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones((3,))}
        t2 = {"w": -jnp.ones((2, 3)), "b": jnp.zeros((3,))}
        e1, e2 = fhe.fhe_enc(t1), fhe.fhe_enc(t2)
        agg = fhe.fhe_fedavg([(1.0, e1), (3.0, e2)])
        dec = fhe.fhe_dec(agg)
        np.testing.assert_allclose(
            np.asarray(dec["w"]),
            0.25 * np.asarray(t1["w"]) + 0.75 * np.asarray(t2["w"]), atol=1e-3)
        np.testing.assert_allclose(np.asarray(dec["b"]), [0.25] * 3, atol=1e-3)
    finally:
        fhe.init(fedml_tpu.Config())


def test_encrypted_tree_wire_roundtrip():
    """EncryptedTree survives the no-code-execution wire codec (cross-silo
    model upload path) and still decrypts correctly afterwards."""
    from fedml_tpu.utils.serialization import dumps_pytree, loads_pytree

    fhe = FedMLFHE.get_instance()
    fhe.init(fedml_tpu.Config(enable_fhe=True, fhe_key_size=256))
    try:
        tree = {"layer": {"w": jnp.ones((2, 2)) * 0.5, "b": jnp.zeros(2)}}
        enc = fhe.fhe_enc(tree)
        wire = dumps_pytree({"model_params": enc, "num_samples": 10})
        back = loads_pytree(wire)
        assert float(back["num_samples"]) == 10
        dec = fhe.fhe_dec(back["model_params"])
        np.testing.assert_allclose(np.asarray(dec["layer"]["w"]), 0.5,
                                   atol=1e-3)
    finally:
        fhe.init(fedml_tpu.Config())


def test_keyless_server_aggregates_by_ciphertext_modulus():
    """A cross-silo-server-role FHE singleton has no key material yet can
    still run fhe_fedavg using the modulus carried by the ciphertexts."""
    client = FedMLFHE()
    client.init(fedml_tpu.Config(
        enable_fhe=True, fhe_key_size=256, fhe_key_seed=5,
        training_type="cross_silo", role="client"))
    server = FedMLFHE()
    server.init(fedml_tpu.Config(
        enable_fhe=True, training_type="cross_silo", role="server"))
    assert server.is_fhe_enabled() and server.codec is None
    t1 = {"w": jnp.ones(4)}
    t2 = {"w": 3.0 * jnp.ones(4)}
    agg = server.fhe_fedavg([(1.0, client.fhe_enc(t1)),
                             (1.0, client.fhe_enc(t2))])
    dec = client.fhe_dec(agg)
    np.testing.assert_allclose(np.asarray(dec["w"]), 2.0, atol=1e-3)


def test_sp_simulation_with_fhe_end_to_end():
    """Two rounds of SP FedAvg with encrypted aggregation converge sanely."""
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="synthetic", model="lr", backend="sp",
        client_num_in_total=3, client_num_per_round=3,
        comm_round=2, epochs=1, batch_size=16,
        frequency_of_the_test=1, enable_tracking=False,
        enable_fhe=True, fhe_key_size=256,
    ))
    try:
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        metrics = fedml_tpu.FedMLRunner(args, device, dataset, bundle).run()
        assert np.isfinite(metrics["test_loss"])
        assert metrics["test_acc"] >= 0.0
    finally:
        FedMLFHE.get_instance().init(fedml_tpu.Config())


def test_rlwe_codec_weighted_sum_exact():
    """RLWE weighted aggregation round-trips with fp32-level error and the
    keyless-server contract (key-id mismatch raises)."""
    from fedml_tpu.core.fhe.rlwe import RlweCodec, keygen

    key = keygen(42)
    codec = RlweCodec(key)
    rng = np.random.RandomState(0)
    v1 = rng.randn(10_000).astype(np.float32)
    v2 = rng.randn(10_000).astype(np.float32)
    e1, e2 = codec.encrypt(v1), codec.encrypt(v2)
    w1 = codec.quantize_weight(0.25)
    w2 = codec.quantize_weight(0.75)
    agg = codec.weighted_sum([(w1, e1), (w2, e2)])
    out = codec.decrypt(key, agg)
    expect = (w1 * v1.astype(np.float64) + w2 * v2) / (w1 + w2)
    np.testing.assert_allclose(out, expect, atol=1e-3)

    other = keygen(43)
    with pytest.raises(ValueError, match="fhe_key_seed"):
        codec.decrypt(other, agg)
    e_other = RlweCodec(other).encrypt(v1)
    with pytest.raises(ValueError, match="different keys"):
        RlweCodec.add(e1, e_other)


def test_rlwe_scheme_end_to_end_sp_round(args_factory):
    """enable_fhe with the default rlwe scheme trains through the SP plane
    hooks (encrypted upload, ciphertext-only aggregation, decrypt-on-
    download) and still converges."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(args_factory(
        enable_fhe=True, fhe_scheme="rlwe", backend="sp",
        client_num_in_total=3, client_num_per_round=3, comm_round=3,
        data_scale=0.3))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


def test_rlwe_model_scale_speed():
    """The practicality bar the VERDICT set: a 1M-param encrypted round
    (enc + 3-client agg + dec) finishes in well under 60 s."""
    import time

    from fedml_tpu.core.fhe.rlwe import RlweCodec, keygen

    key = keygen(7)
    codec = RlweCodec(key)
    vec = np.random.RandomState(1).randn(1_000_000).astype(np.float32) * 0.1
    t0 = time.time()
    encs = [codec.encrypt(vec) for _ in range(3)]
    w = codec.quantize_weight(1 / 3)
    agg = codec.weighted_sum([(w, e) for e in encs])
    out = codec.decrypt(key, agg)
    elapsed = time.time() - t0
    assert np.abs(out - vec).max() < 1e-3
    assert elapsed < 60, f"1M-param round took {elapsed:.1f}s"
