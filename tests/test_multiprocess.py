"""Real multi-process integration (VERDICT round-1 item 5): server + 2
clients as OS subprocesses over gRPC (reference
`tests/cross-silo/run_cross_silo.sh` capability), a 2-process
jax.distributed mesh smoke, and the MPI comm manager's logic driven
through an injected communicator (mpi4py absent in this image — the
import gate stays)."""

import json
import os
import queue
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _spawn(script, extra, env_extra=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)   # single-device per process
    env.update(env_extra or {})
    return subprocess.Popen(
        [sys.executable, os.path.join(HERE, "multiproc", script)] + extra,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)


@pytest.mark.slow
def test_cross_silo_grpc_three_os_processes():
    port = 21890
    server = _spawn("cross_silo_node.py", ["--rank", "0",
                                           "--port", str(port)])
    time.sleep(2.0)  # server's gRPC endpoint up before clients dial
    clients = [_spawn("cross_silo_node.py", ["--rank", str(r),
                                             "--port", str(port)])
               for r in (1, 2)]
    outs = {}
    try:
        for name, proc in [("server", server), ("c1", clients[0]),
                           ("c2", clients[1])]:
            out, _ = proc.communicate(timeout=300)
            outs[name] = out
            assert proc.returncode == 0, f"{name} failed:\n{out[-3000:]}"
    finally:
        for proc in [server] + clients:
            if proc.poll() is None:
                proc.kill()
    final = [ln for ln in outs["server"].splitlines()
             if ln.startswith("FINAL_METRICS ")]
    assert final, outs["server"][-2000:]
    metrics = json.loads(final[-1].split(" ", 1)[1])
    assert np.isfinite(metrics["test_loss"])
    assert "CLIENT_DONE 1" in outs["c1"]
    assert "CLIENT_DONE 2" in outs["c2"]


@pytest.mark.slow
def test_jax_distributed_two_process_mesh():
    procs = [_spawn("jaxdist_node.py", ["--pid", str(i), "--nprocs", "2"])
             for i in range(2)]
    outs = []
    try:
        for proc in procs:
            out, _ = proc.communicate(timeout=300)
            outs.append(out)
            assert proc.returncode == 0, out[-3000:]
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
    assert all("JAXDIST_OK" in o for o in outs), outs


class _FakeComm:
    """mpi4py-communicator shim backed by per-rank queues (send/recv only,
    what MpiCommManager uses)."""

    def __init__(self, queues, rank):
        self.queues = queues
        self.rank = rank

    def Get_rank(self):
        return self.rank

    def Get_size(self):
        return len(self.queues)

    def send(self, obj, dest):
        self.queues[dest].put(obj)

    def recv(self):
        return self.queues[self.rank].get()


def test_mpi_comm_manager_logic_with_injected_comm(args_factory):
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.core.distributed.communication.mpi import MpiCommManager

    queues = {0: queue.Queue(), 1: queue.Queue()}
    args0 = args_factory()
    args0.comm = _FakeComm(queues, 0)
    args1 = args_factory()
    args1.comm = _FakeComm(queues, 1)
    m0 = MpiCommManager(args=args0, rank=0, size=2)
    m1 = MpiCommManager(args=args1, rank=1, size=2)

    got = []

    class Obs:
        def receive_message(self, msg_type, msg):
            got.append((msg_type, msg.get_sender_id(),
                        np.asarray(msg.get_params()["w"])))
            m1.stop_receive_message()

    m1.add_observer(Obs())
    t = threading.Thread(target=m1.handle_receive_message, daemon=True)
    t.start()

    msg = Message(type="sync", sender_id=0, receiver_id=1)
    msg.add_params("w", np.arange(4, dtype=np.float32))
    m0.send_message(msg)
    t.join(timeout=30)
    assert got and got[0][0] == "sync"
    np.testing.assert_array_equal(got[0][2], np.arange(4, dtype=np.float32))


def test_mpi_import_gate_without_mpi4py(args_factory):
    """Without an injected comm and without mpi4py, the gate names the
    alternatives instead of crashing deep in construction."""
    try:
        import mpi4py  # noqa: F401
        pytest.skip("mpi4py present; gate not reachable")
    except ImportError:
        pass
    from fedml_tpu.core.distributed.communication.mpi import MpiCommManager

    with pytest.raises(NotImplementedError, match="INPROC or GRPC"):
        MpiCommManager(args=args_factory(), rank=0, size=2)
