"""Elastic pod: live round-boundary mesh resize (docs/SCHEDULER.md
"Elastic resize") — jobs shrink instead of die.

Covers every layer of the resize ladder: elastic JobSpec ranges, queue
RESIZE control requests, the allocator's shrink-over-evict decision
table with cross-tick reservations and grow-back, the scheduler's
announce → ack → release orchestration with the fallback-to-preempt
rungs, the 8→4→8 chaos soak with a mid-resize death (zero lost rounds,
ledger-asserted), the parrot runtime's in-place re-mesh with trajectory
parity, the cross-silo server's round-boundary resize, and the resize
observability surface (CLI, control-plane route, SLO indicator)."""

import json
import os
import threading
import time

import numpy as np
import pytest
from click.testing import CliRunner

import fedml_tpu
from conftest import make_args
from fedml_tpu.core.mlops import ledger, metrics
from fedml_tpu.scheduler.pod import (
    PREEMPTED_EXIT_CODE,
    CallableJobRunner,
    GangAllocator,
    JobQueue,
    JobSpec,
    JobState,
    PodScheduler,
)
from fedml_tpu.scheduler.pod.runners import (
    clear_resize,
    read_resize_ack,
    signal_resize,
)
from fedml_tpu.scheduler.resource_db import ComputeResourceDB


# --------------------------------------------------------------- job specs
def test_jobspec_elastic_yaml_and_validation(tmp_path):
    y = tmp_path / "job.yaml"
    y.write_text(
        "job_name: elastic-sim\n"
        "kind: parrot\n"
        "slots: 4\n"
        "command: fedml run --cf cfg.yaml {resume}\n"
        "elastic:\n  min_slots: 2\n  max_slots: 8\n")
    spec = JobSpec.from_yaml(str(y))
    assert spec.elastic
    assert (spec.min_slots, spec.max_slots) == (2, 8)
    # one-sided range defaults the missing bound to the declared gang
    half = JobSpec.from_dict({"job_name": "h", "kind": "parrot",
                              "slots": 4, "elastic": {"min_slots": 2}})
    assert (half.min_slots, half.max_slots) == (2, 4)
    # a job without the block keeps the fixed-gang contract
    fixed = JobSpec.from_dict({"job_name": "f", "kind": "parrot",
                               "slots": 4})
    assert not fixed.elastic
    with pytest.raises(ValueError, match="min_slots"):
        JobSpec(name="x", kind="parrot", n_slots=4, min_slots=0,
                max_slots=8).validate()
    with pytest.raises(ValueError, match="max_slots"):
        JobSpec(name="x", kind="parrot", n_slots=4, min_slots=4,
                max_slots=2).validate()
    with pytest.raises(ValueError, match="outside the elastic range"):
        JobSpec(name="x", kind="parrot", n_slots=9, min_slots=2,
                max_slots=8).validate()
    with pytest.raises(ValueError, match="elastic must be a mapping"):
        JobSpec.from_dict({"job_name": "x", "kind": "parrot",
                           "slots": 4, "elastic": True})


# --------------------------------------------------------------- job queue
def test_queue_resize_request_clamp_and_record(tmp_path):
    q = JobQueue(str(tmp_path))
    jid = q.submit(JobSpec(name="el", kind="parrot", n_slots=4,
                           min_slots=2, max_slots=8, command="c"))
    # QUEUED: resize lands directly, clamped into the declared range
    assert q.request_resize(jid, 32) == 8
    assert q.get(jid)["n_slots"] == 8
    # RUNNING + elastic: the flag latches (clamped), scheduler performs
    q.mark_dispatched(jid, "run1", list(range(8)), "/tmp/l")
    assert q.request_resize(jid, 1) == 2
    row = q.get(jid)
    assert row["resize_requested"] == 2 and row["n_slots"] == 8
    # scheduler lands the completed attempt: new gang + audit blob
    q.record_resize(jid, 8, 2, "ok", downtime_s=0.02, slots=[0, 1])
    row = q.get(jid)
    assert row["n_slots"] == 2 and row["slots"] == [0, 1]
    assert row["resize_requested"] == 0
    assert row["last_resize"]["from"] == 8
    assert row["last_resize"]["to"] == 2
    assert row["last_resize"]["outcome"] == "ok"
    # a failed attempt records the audit blob but keeps the old gang
    assert q.request_resize(jid, 8) == 8
    q.record_resize(jid, 2, 8, "fallback_preempt")
    row = q.get(jid)
    assert row["n_slots"] == 2 and row["resize_requested"] == 0
    assert row["last_resize"]["outcome"] == "fallback_preempt"
    # RUNNING + inelastic: refused
    j2 = q.submit(JobSpec(name="fix", kind="parrot", n_slots=2,
                          command="c"))
    q.mark_dispatched(j2, "run2", [8, 9], "/tmp/l2")
    assert q.request_resize(j2, 4) is None
    # requeue clears any in-flight resize flag
    assert q.request_resize(jid, 4) == 4
    q.requeue_preempted(jid, PREEMPTED_EXIT_CODE)
    assert q.get(jid)["resize_requested"] == 0
    q.close()


# ------------------------------------------- allocator decision table
def _job(jid, slots, priority=0, tenant="t", state="RUNNING",
         preemptible=True, submitted=0.0, dispatched=0.0,
         min_slots=0, max_slots=0, resize_requested=0):
    return {"job_id": jid, "n_slots": slots, "priority": priority,
            "tenant": tenant, "state": state, "preemptible": preemptible,
            "submitted_ts": submitted, "dispatched_ts": dispatched,
            "min_slots": min_slots, "max_slots": max_slots,
            "resize_requested": resize_requested}


def test_allocator_shrinks_elastic_victim_instead_of_evicting():
    alloc = GangAllocator()
    running = [_job("el", 8, priority=0, min_slots=2, max_slots=8)]
    queued = [_job("hp", 6, priority=10, state="QUEUED")]
    plan = alloc.plan(queued, running, free_slots=0)
    # the elastic victim keeps running at its floor — no whole-job evict
    assert plan.shrink == [(running[0], 2)]
    assert not plan.evict
    assert plan.reserve == {"hp": 6} and plan.blocked == ["hp"]
    # partial pressure shrinks only as far as needed, not to the floor
    plan2 = alloc.plan(queued, running, free_slots=4)
    assert plan2.shrink == [(running[0], 6)]


def test_allocator_mixes_shrink_and_evict_never_below_floor():
    alloc = GangAllocator()
    el = _job("el", 4, priority=0, min_slots=2, max_slots=8)
    fixed = _job("fix", 4, priority=1, dispatched=1)
    queued = [_job("hp", 8, priority=10, state="QUEUED")]
    plan = alloc.plan(queued, [el, fixed], free_slots=2)
    # the elastic victim shrinks to exactly min_slots (never below);
    # the inelastic one covers the rest by draining whole
    assert plan.shrink == [(el, 2)]
    assert plan.evict == [fixed]
    assert plan.reserve == {"hp": 8}


def test_allocator_never_shrinks_equal_or_higher_priority():
    alloc = GangAllocator()
    running = [_job("el", 8, priority=5, min_slots=2, max_slots=8)]
    queued = [_job("hp", 6, priority=5, state="QUEUED")]
    plan = alloc.plan(queued, running, free_slots=0)
    assert not plan.shrink and not plan.evict
    assert plan.blocked == ["hp"]
    # a victim already mid-resize is spoken for — never picked again
    busy = [_job("el", 8, priority=0, min_slots=2, max_slots=8,
                 resize_requested=2)]
    plan2 = alloc.plan([_job("hp", 6, priority=10, state="QUEUED")],
                       busy, free_slots=0)
    assert not plan2.shrink and not plan2.evict


def test_allocator_shrink_reservation_survives_backfill():
    alloc = GangAllocator()
    queued = [_job("hp", 6, priority=10, state="QUEUED"),
              _job("bf", 4, priority=0, tenant="u", state="QUEUED",
                   submitted=1)]
    # while the shrink is in flight nothing fits and nothing re-pledges
    mid = [_job("el", 8, priority=0, min_slots=2, max_slots=8,
                resize_requested=2)]
    plan = alloc.plan(queued, mid, free_slots=0, reserved={"hp": 6})
    assert not plan.dispatch and not plan.shrink and not plan.evict
    # the re-mesh landed: 6 slots free, only the pledge owner spends them
    after = [_job("el", 2, priority=0, min_slots=2, max_slots=8)]
    plan2 = alloc.plan(queued, after, free_slots=6, reserved={"hp": 6})
    assert [j["job_id"] for j in plan2.dispatch] == ["hp"]
    assert "bf" in plan2.blocked


def test_allocator_grow_back_toward_ceiling_and_blocked_suppression():
    alloc = GangAllocator()
    a = _job("a", 2, priority=5, min_slots=2, max_slots=6)
    b = _job("b", 2, priority=0, tenant="u", min_slots=2, max_slots=8)
    # spare pool goes priority-first, each capped at its ceiling
    plan = alloc.plan([], [a, b], free_slots=6)
    assert plan.grow == [(a, 6), (b, 4)]
    # ANY blocked queued job suppresses grow-back entirely
    plan2 = alloc.plan([_job("big", 12, priority=5, state="QUEUED")],
                       [a, b], free_slots=6)
    assert not plan2.grow and plan2.blocked == ["big"]
    # a job mid-resize or at its ceiling is left alone
    c = _job("c", 4, priority=0, min_slots=2, max_slots=8,
             resize_requested=8)
    d = _job("d", 4, priority=0, min_slots=2, max_slots=4)
    plan3 = alloc.plan([], [c, d], free_slots=4)
    assert not plan3.grow


# ------------------------------------------- scheduler orchestration
def _mk_sched(tmp_path, workloads, total_slots=8, **kw):
    queue = JobQueue(str(tmp_path / "pod"))
    resources = ComputeResourceDB(str(tmp_path / "res"),
                                  total_slots=total_slots)
    sched = PodScheduler(queue, resources,
                         runner=CallableJobRunner(workloads), **kw)
    return sched, queue, resources


def _step_until(sched, pred, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        sched.step()
        if pred():
            return True
        time.sleep(0.02)
    return False


def _sim_workload(duration_s):
    def fn(ctx):
        t0 = time.monotonic()
        while time.monotonic() - t0 < duration_s:
            if ctx.drain_requested():
                return PREEMPTED_EXIT_CODE
            time.sleep(0.01)
        return 0
    return fn


def test_busy_integral_attributes_interval_to_held_slots(tmp_path):
    """Mid-job slot changes and the utilization integral: each interval
    is charged at the slot count actually held OVER it (sampled at the
    end of the previous pass), never retroactively at the count the
    current pass just switched to."""
    sched, q, _ = _mk_sched(tmp_path, {})
    sched._integrate_busy(0.0, 4)      # t0; nothing accrues yet
    sched._integrate_busy(10.0, 8)     # [0,10) ran at 4, not 8
    assert sched._busy_slot_seconds == pytest.approx(40.0)
    sched._integrate_busy(20.0, 2)     # [10,20) ran at 8
    assert sched._busy_slot_seconds == pytest.approx(120.0)
    sched._integrate_busy(30.0, 0)     # [20,30) ran at 2
    assert sched._busy_slot_seconds == pytest.approx(140.0)
    # 140 busy slot-seconds over 8 slots x 30 s
    assert sched.aggregate_utilization() == pytest.approx(140 / 240)
    q.close()


def _elastic_trainer(rounds, total, envs=None, resize_log=None,
                     chaos=None, round_s=0.02):
    """A round-loop workload honouring the full pod contract: drain at
    boundaries, latch + ack resizes, and (for the chaos soak) die without
    acking when `chaos` arms a mid-resize kill.  `rounds` is the
    persistent cross-dispatch cursor — the stand-in for the boundary
    checkpoint a real server resumes from."""
    def fn(ctx):
        if envs is not None:
            envs.append(dict(ctx.env))
        acked = None
        while len(rounds) < total:
            time.sleep(round_s)
            if ctx.drain_requested():
                return PREEMPTED_EXIT_CODE
            tgt = ctx.resize_requested()
            if tgt is None:
                acked = None             # scheduler cleared the last one
            elif tgt != acked:
                if chaos is not None and chaos.pop("die_on_resize", None):
                    return 1             # killed mid-re-mesh: no ack
                ctx.ack_resize("ok", tgt, downtime_s=0.004)
                acked = tgt
                if resize_log is not None:
                    resize_log.append(tgt)
            rounds.append(len(rounds))
        return 0
    return fn


def test_scheduler_shrink_over_evict_growback_e2e(tmp_path):
    """The headline elastic soak: a priority burst arrives on a full pod
    and the elastic trainer SHRINKS to seat it (no preemption, no lost
    warm state), then grows back to its ceiling when the burst passes.
    The pod stays ≥89% utilized across the whole episode."""
    rounds, resize_log, envs = [], [], []
    TOTAL = 120
    sched, q, res = _mk_sched(
        tmp_path,
        {"trainer": _elastic_trainer(rounds, TOTAL, envs=envs,
                                     resize_log=resize_log),
         "burst": _sim_workload(0.8)})
    jid = q.submit(JobSpec(name="trainer", kind="parrot",
                           tenant="research", n_slots=8, min_slots=2,
                           max_slots=8, command="t"))
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.RUNNING)
    # the dispatch env carries the resize channel next to the drain file
    assert envs[0]["FEDML_TPU_RESIZE_FILE"].endswith(".resize")
    assert _step_until(sched, lambda: len(rounds) >= 10)
    hp = q.submit(JobSpec(name="burst", kind="parrot", tenant="prod",
                          priority=10, preemptible=False, n_slots=6,
                          command="b"))
    # the allocator shrinks the trainer to its floor and seats the burst
    # on the freed slots — the trainer was never drained
    assert _step_until(
        sched, lambda: q.get(hp)["state"] == JobState.RUNNING,
        timeout_s=120.0)
    row = q.get(jid)
    assert row["state"] == JobState.RUNNING and row["n_slots"] == 2
    assert row["preempt_count"] == 0
    assert len(row["slots"]) == 2
    assert row["last_resize"]["outcome"] == "ok"
    assert row["last_resize"]["to"] == 2
    assert res.report()["free"] == 0          # 2 + 6: the pod is full
    # burst done → the spare pool grows the trainer back to its ceiling
    assert _step_until(
        sched, lambda: q.get(hp)["state"] == JobState.FINISHED,
        timeout_s=120.0)
    assert _step_until(sched, lambda: q.get(jid)["n_slots"] == 8,
                       timeout_s=120.0)
    assert q.get(jid)["last_resize"]["to"] == 8
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.FINISHED,
        timeout_s=120.0)
    final = q.get(jid)
    assert final["returncode"] == 0 and final["preempt_count"] == 0
    assert resize_log[:2] == [2, 8]           # shrink, then grow-back
    assert rounds == list(range(TOTAL))       # zero lost rounds
    util = sched.aggregate_utilization()
    assert util >= 0.89, f"pod utilization {util:.3f} < 0.89"
    expo = metrics.render_prometheus()
    assert 'fedml_pod_resizes_total{direction="shrink",outcome="ok"}' \
        in expo
    assert 'fedml_pod_resizes_total{direction="grow",outcome="ok"}' \
        in expo
    assert "fedml_resize_downtime_seconds_count" in expo
    q.close()


def test_scheduler_resize_grace_falls_back_to_preempt(tmp_path):
    """Fallback ladder rung 2: a workload that never acks the announce
    exceeds the resize grace and degrades to the PR-11 preempt path —
    drained at a boundary, requeued with resume, redispatched whole."""
    dispatches = []

    def stubborn(ctx):
        dispatches.append(ctx.resume)
        if ctx.resume:
            return 0
        while not ctx.drain_requested():
            time.sleep(0.02)             # ignores the resize announce
        return PREEMPTED_EXIT_CODE

    sched, q, res = _mk_sched(tmp_path, {"stubborn": stubborn},
                              resize_grace_s=0.3)
    jid = q.submit(JobSpec(name="stubborn", kind="parrot", n_slots=4,
                           min_slots=2, max_slots=4, command="s"))
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.RUNNING)
    assert q.request_resize(jid, 2) == 2
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.FINISHED,
        timeout_s=120.0)
    row = q.get(jid)
    assert row["preempt_count"] == 1 and row["resume"]
    assert row["last_resize"]["outcome"] == "fallback_preempt"
    assert dispatches == [False, True]
    assert res.report()["free"] == 8
    expo = metrics.render_prometheus()
    assert 'fedml_pod_resizes_total{direction="shrink",' \
        'outcome="fallback"}' in expo
    q.close()


def test_chaos_soak_midresize_death_zero_lost_rounds(tmp_path):
    """Acceptance chaos soak: 8→4→8 with a kill mid-resize.  The first
    shrink announce catches a workload that dies before acking; the
    scheduler degrades it to preempt/resume (the resize is never worse
    than a preemption), the resumed dispatch picks up at the boundary
    cursor, the retried shrink lands in place and the grow-back returns
    the pod to full width.  Every round runs exactly once — zero lost,
    zero duplicated — and the whole episode is ledger-auditable."""
    led_dir = str(tmp_path / "led")
    ledger.enable(True, log_dir=led_dir, run_id="chaos-soak")
    rounds, resize_log = [], []
    chaos = {"die_on_resize": True}
    TOTAL = 80
    try:
        sched, q, res = _mk_sched(
            tmp_path, {"trainer": _elastic_trainer(
                rounds, TOTAL, resize_log=resize_log, chaos=chaos)})
        jid = q.submit(JobSpec(name="trainer", kind="parrot",
                               tenant="research", n_slots=8, min_slots=2,
                               max_slots=8, command="t"))
        assert _step_until(sched, lambda: len(rounds) >= 5)
        # shrink #1: the workload dies mid-re-mesh (announce, no ack)
        assert q.request_resize(jid, 4) == 4
        assert _step_until(
            sched, lambda: q.get(jid)["preempt_count"] == 1,
            timeout_s=120.0)
        row = q.get(jid)
        assert row["resume"]
        assert row["last_resize"]["outcome"] == "fallback_preempt"
        # the requeued job redispatches whole and resumes at the cursor
        assert _step_until(
            sched, lambda: q.get(jid)["state"] == JobState.RUNNING,
            timeout_s=120.0)
        resumed_at = len(rounds)
        assert _step_until(sched,
                           lambda: len(rounds) >= resumed_at + 5)
        # shrink #2 lands in place; the idle spare then grows it back
        assert q.request_resize(jid, 4) == 4
        assert _step_until(
            sched,
            lambda: (q.get(jid)["last_resize"]["to"] == 8
                     and q.get(jid)["last_resize"]["outcome"] == "ok"),
            timeout_s=120.0)
        assert _step_until(
            sched, lambda: q.get(jid)["state"] == JobState.FINISHED,
            timeout_s=120.0)
        assert q.get(jid)["returncode"] == 0
    finally:
        ledger.reset()
    # zero lost rounds, zero duplicates, across the death and both resizes
    assert rounds == list(range(TOTAL))
    assert resize_log == [4, 8]
    recs = ledger.load_ledger(led_dir)
    resizes = [r for r in recs if r["actor"] == "scheduler"
               and r["event"] == "resize"]
    outcomes = [r["attrs"]["outcome"] for r in resizes]
    assert outcomes.count("fallback_preempt") == 1
    assert outcomes.count("ok") == 2
    spans = {(r["attrs"]["from"], r["attrs"]["to"]) for r in resizes}
    assert (8, 4) in spans and (4, 8) in spans
    assert sum(1 for r in recs if r["event"] == "requeue") == 1
    dispatches = [r for r in recs if r["event"] == "dispatch"]
    assert len(dispatches) == 2
    assert dispatches[-1]["attrs"]["resume"] is True


# ------------------------------------------- serving scaler (in place)
def test_serving_scaler_requests_inplace_resize_for_elastic_job(tmp_path):
    from fedml_tpu.scheduler.autoscaler import AutoscalePolicy
    from fedml_tpu.scheduler.pod.serving_scaler import (
        DECODE_METRIC,
        ServingReplicaScaler,
    )

    reg = metrics.MetricsRegistry()
    hist = reg.histogram(DECODE_METRIC, labels=("model",))
    q = JobQueue(str(tmp_path))
    jid = q.submit(JobSpec(name="svc", kind="serving", n_slots=2,
                           min_slots=1, max_slots=8, command="serve"))
    q.mark_dispatched(jid, "runS", [0, 1], "/tmp/l")
    clock = {"t": 0.0}
    scaler = ServingReplicaScaler(
        q, policy=AutoscalePolicy(min_replicas=1, max_replicas=8,
                                  target_latency_s=0.05,
                                  target_qps_per_replica=5.0),
        registry=reg, clock=lambda: clock["t"])
    assert scaler.tick() == {}               # baseline window
    for _ in range(200):
        hist.labels(model="m").observe(0.5)
    clock["t"] = 1.0
    decisions = scaler.tick()
    assert decisions[jid] == 8
    row = q.get(jid)
    # elastic + RUNNING → in-place resize request, NOT a drain
    assert row["state"] == JobState.RUNNING
    assert not row["preempt_requested"]
    assert row["resize_requested"] == 8
    # a request already in flight is left alone on the next breach
    for _ in range(200):
        hist.labels(model="m").observe(0.5)
    clock["t"] = 2.0
    scaler.tick()
    assert q.get(jid)["resize_requested"] == 8
    q.close()


# ------------------------------------------- parrot runtime (in place)
def _make_parrot(args):
    from fedml_tpu.simulation.parrot.parrot_api import ParrotAPI

    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return ParrotAPI(args, device, dataset, bundle, use_mesh=True)


def _parrot_kw(**kw):
    base = dict(backend="mesh", comm_round=4, client_num_in_total=8,
                client_num_per_round=4, data_scale=0.3,
                mesh_shape={"clients": 8})
    base.update(kw)
    return base


def test_parrot_inplace_resize_shrink_parity(tmp_path):
    """Acceptance: an in-place 8→4 re-mesh at a round boundary resumes
    from host-round-tripped state and reproduces the no-resize
    trajectory within tolerance (bit-identical on the CPU proxy — the
    re-mesh moves values, never math)."""
    rp = str(tmp_path / "job.resize")
    signal_resize(rp, 4, 8)                  # latches after round 0
    api = _make_parrot(make_args(
        checkpoint_dir=str(tmp_path / "ckpt"), resize_file=rp,
        **_parrot_kw()))
    m = api.train()
    ack = read_resize_ack(rp)
    assert ack and ack["outcome"] == "ok" and ack["to"] == 4, ack
    assert int(api.mesh.devices.size) == 4
    assert np.isfinite(m["test_loss"])
    # the boundary checkpoint exists (re-mesh failure falls back to it)
    assert os.listdir(str(tmp_path / "ckpt"))
    # trajectory parity vs the same seed without any resize
    api2 = _make_parrot(make_args(**_parrot_kw()))
    m2 = api2.train()
    np.testing.assert_allclose(m["test_loss"], m2["test_loss"],
                               atol=2e-4)
    np.testing.assert_allclose(m["test_acc"], m2["test_acc"], atol=1e-6)


def test_parrot_resize_grow_back_roundtrip(tmp_path):
    """Shrink 8→4 then grow back 4→8 across round boundaries (the
    scheduler clears the channel between announces), then train to
    completion on the re-grown mesh."""
    rp = str(tmp_path / "job.resize")
    api = _make_parrot(make_args(resize_file=rp,
                                 **_parrot_kw(comm_round=6)))
    signal_resize(rp, 4, 8)
    api._maybe_resize(None, 0)
    a1 = read_resize_ack(rp)
    assert a1["outcome"] == "ok" and int(api.mesh.devices.size) == 4
    clear_resize(rp)
    signal_resize(rp, 8, 4)
    api._maybe_resize(None, 2)
    a2 = read_resize_ack(rp)
    assert a2["outcome"] == "ok" and int(api.mesh.devices.size) == 8
    assert a2.get("downtime_s") is not None
    clear_resize(rp)
    m = api.train()
    assert np.isfinite(m["test_loss"])


# ------------------------------------------- cross-silo server (in place)
def _build_cross_silo(args):
    import jax

    from fedml_tpu.cross_silo.runner import init_client
    from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator
    from fedml_tpu.cross_silo.server.fedml_server_manager import (
        FedMLServerManager,
    )
    from fedml_tpu.ml.trainer.default_trainer import DefaultServerAggregator

    n = int(args.client_num_in_total)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    impl = DefaultServerAggregator(bundle, args)
    if impl.get_model_params() is None:
        impl.set_model_params(bundle.init_variables(jax.random.PRNGKey(0)))
    agg = FedMLAggregator(args, impl, dataset[3])
    server = FedMLServerManager(args, agg, rank=0, client_num=n,
                                backend="INPROC")
    clients = [init_client(args, dataset, bundle, rank, backend="INPROC")
               for rank in range(1, n + 1)]
    return server, clients


def _run_cross_silo(server, clients):
    threads = [threading.Thread(target=c.run, daemon=True)
               for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30)


def test_cross_silo_server_inplace_resize(tmp_path):
    """The server latches a resize at `_complete_round` AFTER the round
    state persisted, re-meshes in the same process, acks, and finishes
    every round — no preemption, no duplicate uploads, the resize
    audit-trailed in the run ledger."""
    N, ROUNDS = 3, 5
    rp = str(tmp_path / "job.resize")
    signal_resize(rp, 4, 8)                  # latches at the 1st boundary
    args = fedml_tpu.init(make_args(
        training_type="cross_silo", client_num_in_total=N,
        client_num_per_round=N, comm_round=ROUNDS, data_scale=0.3,
        frequency_of_the_test=1, run_id="resize_srv", resize_file=rp))
    server, clients = _build_cross_silo(args)
    ledger.enable(True, log_dir=str(tmp_path), run_id="resize_srv")
    try:
        _run_cross_silo(server, clients)
    finally:
        ledger.reset()
    ack = read_resize_ack(rp)
    assert ack and ack["outcome"] == "ok" and ack["to"] == 4, ack
    assert int(args.round_idx) == ROUNDS
    assert args.preempted_at_round is None
    assert len(server.aggregator.metrics_history) == ROUNDS
    assert server.aggregator.duplicate_uploads == 0
    assert np.isfinite(server.aggregator.metrics_history[-1]["test_loss"])
    recs = ledger.load_ledger(str(tmp_path))
    evs = [r for r in recs if r["actor"] == "server"
           and r["event"] == "resize"]
    assert evs and evs[0]["attrs"]["outcome"] == "ok"
    assert evs[0]["attrs"]["to"] == 4
    assert evs[0]["attrs"]["downtime_s"] is not None


def test_cross_silo_server_resize_failure_preempts_at_boundary(tmp_path):
    """Fallback ladder rung 1 inside the runtime: a re-mesh that raises
    acks `failed` and degrades to the boundary preempt — exit 75 with the
    checkpoint saved, never a crash."""
    N, ROUNDS = 2, 4
    rp = str(tmp_path / "job.resize")
    signal_resize(rp, 1, 2)
    args = fedml_tpu.init(make_args(
        training_type="cross_silo", client_num_in_total=N,
        client_num_per_round=N, comm_round=ROUNDS, data_scale=0.3,
        frequency_of_the_test=1, run_id="resize_fail",
        checkpoint_dir=str(tmp_path / "ckpt"), resize_file=rp))
    server, clients = _build_cross_silo(args)

    def _boom(n_slots):
        raise RuntimeError("re-mesh blew up")

    server.aggregator.remesh = _boom
    _run_cross_silo(server, clients)
    ack = read_resize_ack(rp)
    assert ack and ack["outcome"] == "failed", ack
    assert args.preempted_at_round is not None
    # completed rounds were checkpointed before the preempt
    assert os.listdir(str(tmp_path / "ckpt"))


# ------------------------------------------- observability surfaces
def test_resize_downtime_slo_indicator(tmp_path):
    from fedml_tpu.core.mlops import slo as slo_mod

    # ledger fallback: p95 over ok-resize downtimes only
    recs = [{"actor": "scheduler", "event": "resize", "ts_mono": float(i),
             "attrs": {"outcome": "ok", "downtime_s": 0.1 * (i + 1),
                       "from": 8, "to": 4}}
            for i in range(5)]
    recs.append({"actor": "scheduler", "event": "resize", "ts_mono": 9.0,
                 "attrs": {"outcome": "fallback_preempt",
                           "downtime_s": None, "from": 4, "to": 8}})
    (tmp_path / "ledger.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in recs))
    ctx = slo_mod.SLOContext.from_artifacts(log_dir=str(tmp_path))
    rule = slo_mod.SLORule(name="rd", indicator="resize_downtime_p95",
                           max=10.0)
    assert slo_mod.INDICATORS["resize_downtime_p95"](ctx, rule) \
        == pytest.approx(0.5)
    results = slo_mod.evaluate([rule], ctx)
    assert results[0]["ok"] is True
    # metrics-first: the live histogram wins when populated
    metrics.histogram(
        "fedml_resize_downtime_seconds",
        "Checkpoint -> re-mesh -> resume pause of an in-place resize"
    ).observe(0.2)
    live = slo_mod.INDICATORS["resize_downtime_p95"](
        slo_mod.SLOContext.live(), rule)
    assert live is not None and live > 0


def test_slo_pod_rules_gate_recorded_soak(tmp_path):
    """`fedml slo check --rules examples/slo_pod.yaml` gates a recorded
    elastic soak offline — the CI chaos-soak step's exact invocation."""
    from fedml_tpu.cli.cli import cli

    out = tmp_path / "soak"
    out.mkdir()
    (out / "ledger.jsonl").write_text(json.dumps(
        {"actor": "scheduler", "event": "resize", "ts_mono": 1.0,
         "attrs": {"outcome": "ok", "downtime_s": 0.02,
                   "from": 8, "to": 4}}) + "\n")
    rules = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "slo_pod.yaml")
    res = CliRunner().invoke(cli, ["slo", "check", "--rules", rules,
                                   "--log-dir", str(out)])
    assert res.exit_code == 0, res.output
    assert "resize_downtime_p95" in res.output


def test_cli_jobs_resize_and_elastic_projection(tmp_path):
    from fedml_tpu.cli.cli import cli

    pod = str(tmp_path / "pod")
    q = JobQueue(pod)
    jid = q.submit(JobSpec(name="el", kind="parrot", n_slots=4,
                           min_slots=2, max_slots=8, command="c"))
    # QUEUED: the CLI resize lands immediately, clamped to the range
    res = CliRunner().invoke(cli, ["jobs", "resize", jid, "32",
                                   "--pod-dir", pod])
    assert res.exit_code == 0, res.output
    payload = json.loads(res.output)
    assert payload["resize_requested"] and payload["target_slots"] == 8
    assert q.get(jid)["n_slots"] == 8
    # RUNNING elastic: flag latched for the scheduler, list/status
    # project the range + in-flight target + audit blob
    q.mark_dispatched(jid, "r1", list(range(8)), "/tmp/l")
    res2 = CliRunner().invoke(cli, ["jobs", "resize", jid, "4",
                                    "--pod-dir", pod])
    assert res2.exit_code == 0 and \
        json.loads(res2.output)["target_slots"] == 4
    rows = [json.loads(line) for line in CliRunner().invoke(
        cli, ["jobs", "list", "--pod-dir", pod]).output.splitlines()]
    brief = next(r for r in rows if r["job_id"] == jid)
    assert brief["elastic"] == {"min_slots": 2, "max_slots": 8}
    assert brief["resize_requested"] == 4
    q.record_resize(jid, 8, 4, "ok", downtime_s=0.02,
                    slots=[0, 1, 2, 3])
    res3 = CliRunner().invoke(cli, ["jobs", "status", jid,
                                    "--pod-dir", pod])
    row = json.loads(res3.output)
    assert row["n_slots"] == 4
    assert row["last_resize"]["outcome"] == "ok"
    # a RUNNING inelastic job refuses the resize (exit 1)
    j2 = q.submit(JobSpec(name="fix", kind="parrot", n_slots=2,
                          command="c"))
    q.mark_dispatched(j2, "r2", [8, 9], "/tmp/l2")
    res4 = CliRunner().invoke(cli, ["jobs", "resize", j2, "4",
                                    "--pod-dir", pod])
    assert res4.exit_code == 1
    assert json.loads(res4.output)["target_slots"] is None
    q.close()


def test_control_plane_resize_route(tmp_path):
    from fedml_tpu.scheduler.control_plane import (
        ControlPlaneClient,
        ControlPlaneServer,
    )

    q = JobQueue(str(tmp_path))
    jid = q.submit(JobSpec(name="el", kind="parrot", n_slots=4,
                           min_slots=2, max_slots=8, command="c"))
    q.mark_dispatched(jid, "r1", [0, 1, 2, 3], "/tmp/l")
    srv = ControlPlaneServer(master=None, pod_queue=q).start()
    try:
        client = ControlPlaneClient(srv.url)
        assert client.pod_resize(jid, 2) == 2
        assert q.get(jid)["resize_requested"] == 2
        # inelastic RUNNING job → 409 → None
        j2 = q.submit(JobSpec(name="fix", kind="parrot", n_slots=2,
                              command="c"))
        q.mark_dispatched(j2, "r2", [4, 5], "/tmp/l2")
        assert client.pod_resize(j2, 4) is None
    finally:
        srv.stop()
        q.close()
