"""Serving observatory (docs/OBSERVABILITY.md "Serving observatory"):
open-loop arrival processes, the per-request lifecycle ledger/metrics
telemetry on both engines, SLO-aware shedding end to end through the
OpenAI API, the degradation-curve knee, and the `fedml load` CLI."""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest
from click.testing import CliRunner

from fedml_tpu.core.mlops import ledger, metrics as metrics_mod


class _StubBundle:
    """Uniform logits — drives the batched decode loop with a trivial
    compile, so lifecycle tests don't pay a model forward."""

    input_shape = (16,)

    def apply(self, variables, x, train=False):
        import jax.numpy as jnp

        b, t = x.shape
        return jnp.zeros((b, t, 11)), None


def _stub_engine(max_batch=2, window=16, admission=None):
    from fedml_tpu.serving.llm_engine import BatchedLLMEngine

    return BatchedLLMEngine(_StubBundle(), {}, max_batch=max_batch,
                            window=window, admission=admission)


def _tiny_kv_engine(max_batch=2, tokens_per_dispatch=1, max_len=64,
                    admission=None):
    import jax

    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    lm = KVCacheLM.create(jax.random.PRNGKey(0), vocab=90, dim=16,
                          layers=1, heads=2, max_len=max_len)
    return KVCacheLLMEngine(lm, max_batch=max_batch,
                            tokens_per_dispatch=tokens_per_dispatch,
                            admission=admission)


# -- arrival processes -------------------------------------------------------

def test_poisson_schedule_statistics():
    from fedml_tpu.serving.loadgen import PoissonProcess

    sched = PoissonProcess(50.0, seed=3).schedule(20.0)
    assert np.all(np.diff(sched) >= 0)           # sorted
    assert sched[0] >= 0 and sched[-1] < 20.0
    # mean count 1000, sd ~32 — 5 sd tolerance
    assert 840 <= sched.size <= 1160
    gaps = np.diff(sched)
    assert abs(float(gaps.mean()) - 1 / 50.0) < 0.004


def test_mmpp_bursty_schedule():
    from fedml_tpu.serving.loadgen import MarkovModulatedProcess

    proc = MarkovModulatedProcess(5.0, 80.0, switch_p=0.02, seed=7)
    sched = proc.schedule(60.0)
    mean_qps = sched.size / 60.0
    assert 5.0 < mean_qps < 80.0                 # between the two states
    # burstiness: squared coeff of variation of gaps well above the
    # Poisson value of 1
    gaps = np.diff(sched)
    cv2 = float(gaps.var() / gaps.mean() ** 2)
    assert cv2 > 1.5


def test_trace_replay_and_scale(tmp_path):
    from fedml_tpu.serving.loadgen import TraceProcess

    trace = tmp_path / "arrivals.jsonl"
    trace.write_text("".join(
        json.dumps({"ts": 100.0 + t}) + "\n" for t in (0, 1, 2, 4, 8)))
    proc = TraceProcess.from_jsonl(str(trace))
    np.testing.assert_allclose(proc.schedule(100.0), [0, 1, 2, 4, 8])
    fast = TraceProcess.from_jsonl(str(trace), scale=2.0)
    np.testing.assert_allclose(fast.schedule(100.0), [0, 0.5, 1, 2, 4])
    # horizon clips
    assert TraceProcess.from_jsonl(str(trace)).schedule(3.0).size == 3


def test_trace_from_ledger_submit_events(tmp_path):
    from fedml_tpu.serving.loadgen import TraceProcess, parse_arrivals

    led = tmp_path / "ledger.jsonl"
    recs = ([{"actor": "serving", "event": "submit", "ts_mono": 50.0 + t}
             for t in (0, 0.5, 1.5)]
            + [{"actor": "serving", "event": "admit", "ts_mono": 51.0},
               {"actor": "server", "event": "solicit", "ts_mono": 50.2}])
    led.write_text("".join(json.dumps(r) + "\n" for r in recs))
    proc = TraceProcess.from_ledger(str(led))
    np.testing.assert_allclose(proc.schedule(10.0), [0, 0.5, 1.5])
    # the dir form of the spec resolves through the same loader
    proc2 = parse_arrivals(f"trace:{tmp_path}")
    assert proc2.schedule(10.0).size == 3


def test_parse_arrivals_specs():
    from fedml_tpu.serving.loadgen import (MarkovModulatedProcess,
                                           PoissonProcess, parse_arrivals)

    assert isinstance(parse_arrivals("poisson:8"), PoissonProcess)
    mm = parse_arrivals("mmpp:2:40:0.2")
    assert isinstance(mm, MarkovModulatedProcess)
    assert mm.switch_p == 0.2
    for bad in ("", "poisson", "poisson:0", "mmpp:1", "warp:9", "poisson:x"):
        with pytest.raises(ValueError):
            parse_arrivals(bad)


def test_length_sampler_committed_hist():
    from fedml_tpu.serving.loadgen import LengthSampler

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks",
        "serving_length_hist.json")
    sampler = LengthSampler.from_file(path, seed=5)
    with open(path) as f:
        payload = json.load(f)
    prompts = {v for v, _ in payload["prompt"]}
    outputs = {v for v, _ in payload["output"]}
    for _ in range(50):
        s = sampler.sample()
        assert s["prompt_tokens"] in prompts
        assert s["output_tokens"] in outputs
    fixed = LengthSampler.fixed(7, 3)
    assert fixed.sample() == {"prompt_tokens": 7, "output_tokens": 3}


# -- engine lifecycle telemetry ----------------------------------------------

def test_lifecycle_coverage_and_ttft_decomposition(tmp_path):
    """Every submitted request reaches exactly one terminal ledger event,
    and ttft == queue_wait + prefill + first_decode at every first_token
    (the decomposition holds by construction)."""
    from fedml_tpu.serving.loadgen import request_anatomy

    ledger.enable(True, log_dir=str(tmp_path), run_id="lifecycle")
    eng = _tiny_kv_engine(max_batch=2, tokens_per_dispatch=2)
    try:
        futs = [eng.submit(list(range(1, 5 + i)), max_new=4)
                for i in range(5)]        # 5 reqs > 2 slots → queueing
        for f in futs:
            f.result(120.0)
    finally:
        eng.stop()
        ledger.reset()
    anatomy = request_anatomy(ledger.load_ledger(str(tmp_path)))
    assert anatomy["submitted"] == 5
    assert anatomy["coverage"] == 1.0
    assert anatomy["outcomes"] == {"finish": 5}
    firsts = [e for r in anatomy["requests"].values()
              for e in r["events"] if e["event"] == "first_token"]
    assert len(firsts) == 5
    for e in firsts:
        a = e["attrs"]
        lhs = a["queue_wait_s"] + a["prefill_s"] + a["first_decode_s"]
        assert abs(lhs - a["ttft_s"]) < 2e-3
    # satellite: admit-time queue-wait histogram is populated
    qw = metrics_mod.REGISTRY.collect()[
        "fedml_llm_queue_wait_seconds"].labels(engine="kv")
    assert qw.count >= 5


def test_admission_sheds_with_reason_and_metrics(tmp_path):
    """Past the queue bound the engine sheds: the future raises
    ShedError, the ledger records the shed with its reason, and the
    shed/requests counters agree."""
    from fedml_tpu.serving.admission import (ServingAdmissionController,
                                             ShedError)
    from fedml_tpu.serving.loadgen import request_anatomy

    shed_c = metrics_mod.counter(
        "fedml_llm_shed_total", "Requests shed by admission control",
        labels=("engine", "reason")).labels(engine="batched",
                                            reason="queue_full")
    shed_before = shed_c.value
    ledger.enable(True, log_dir=str(tmp_path), run_id="shed")
    eng = _stub_engine(max_batch=1,
                       admission=ServingAdmissionController(
                           max_queue_depth=0))
    try:
        # depth >= 0 → every request sheds before entering the queue
        futs = [eng.submit([1, 2], max_new=3) for _ in range(4)]
        for f in futs:
            with pytest.raises(ShedError) as ei:
                f.result(30.0)
            assert ei.value.reason == "queue_full"
    finally:
        eng.stop()
        ledger.reset()
    anatomy = request_anatomy(ledger.load_ledger(str(tmp_path)))
    assert anatomy["outcomes"] == {"shed": 4}
    assert anatomy["coverage"] == 1.0
    sheds = [e for r in anatomy["requests"].values()
             for e in r["events"] if e["event"] == "shed"]
    assert all(e["attrs"]["reason"] == "queue_full" for e in sheds)
    assert shed_c.value == shed_before + 4


def test_stats_snapshot_matches_gauges():
    """stats() is the single source: the dict it returns and the
    Prometheus gauges it refreshes carry the same values."""
    eng = _stub_engine(max_batch=2)
    try:
        s = eng.stats()
        reg = metrics_mod.REGISTRY.collect()
        assert reg["fedml_llm_queue_depth"].labels(
            engine="batched").value == s["queue_depth"]
        assert reg["fedml_llm_active_requests"].labels(
            engine="batched").value == s["active"]
        assert reg["fedml_llm_batch_occupancy"].labels(
            engine="batched").value == pytest.approx(
                s["active"] / s["capacity"])
    finally:
        eng.stop()


# -- OpenAI API: shed → 429, client disconnect → cancel ----------------------

def test_openai_shed_returns_429():
    from fedml_tpu.serving.admission import ServingAdmissionController
    from fedml_tpu.serving.llm_engine import LLMEnginePredictor
    from fedml_tpu.serving.openai_api import OpenAIServer
    import urllib.error
    import urllib.request

    eng = _stub_engine(max_batch=1,
                       admission=ServingAdmissionController(
                           max_queue_depth=0))
    srv = OpenAIServer(LLMEnginePredictor(eng), model_name="tiny", port=0)
    try:
        srv.run(block=False)
        body = json.dumps({"model": "tiny", "max_tokens": 3,
                           "messages": [{"role": "user",
                                         "content": "hi"}]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/chat/completions",
            data=body, headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        assert ei.value.code == 429
        payload = json.loads(ei.value.read())
        assert payload["error"]["code"] == "queue_full"
        assert payload["error"]["type"] == "overloaded"
    finally:
        srv.stop()
        eng.stop()


def test_client_disconnect_mid_decode_emits_cancel(tmp_path):
    """A streaming client that drops its socket mid-decode frees the
    slot, lands a `cancel` (never `finish`) lifecycle event, and leaves
    the TBT percentiles untouched."""
    from fedml_tpu.serving.llm_engine import LLMEnginePredictor
    from fedml_tpu.serving.loadgen import request_anatomy
    from fedml_tpu.serving.openai_api import OpenAIServer

    ledger.enable(True, log_dir=str(tmp_path), run_id="disconnect")
    eng = _tiny_kv_engine(max_batch=2, tokens_per_dispatch=1, max_len=256)
    reg = metrics_mod.REGISTRY.collect()
    tbt = reg["fedml_llm_tbt_seconds"].labels(engine="kv")
    cancels = reg["fedml_llm_requests_total"].labels(engine="kv",
                                                     outcome="cancel")
    tbt_before, cancels_before = tbt.count, cancels.value
    srv = OpenAIServer(LLMEnginePredictor(eng), model_name="tiny", port=0)
    try:
        srv.run(block=False)
        body = json.dumps({"model": "tiny", "max_tokens": 200,
                           "stream": True,
                           "messages": [{"role": "user",
                                         "content": "hello"}]}).encode()
        raw = (b"POST /v1/chat/completions HTTP/1.1\r\n"
               b"Host: x\r\nContent-Type: application/json\r\n"
               + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=60)
        sock.sendall(raw)
        got = b""
        while b"data:" not in got:          # first token reached the wire
            got += sock.recv(4096)
        sock.close()                        # client vanishes mid-decode
        deadline = time.time() + 60
        while eng.active_count and time.time() < deadline:
            time.sleep(0.05)
        assert eng.active_count == 0        # slot freed
    finally:
        srv.stop()
        eng.stop()
        ledger.reset()
    anatomy = request_anatomy(ledger.load_ledger(str(tmp_path)))
    assert anatomy["outcomes"].get("cancel", 0) >= 1
    assert "finish" not in anatomy["outcomes"]
    assert cancels.value >= cancels_before + 1
    assert tbt.count == tbt_before          # cancels never observe TBT


# -- open-loop driver --------------------------------------------------------

def test_open_loop_driver_end_to_end(tmp_path):
    from fedml_tpu.serving.loadgen import (LengthSampler, OpenLoopDriver,
                                           PoissonProcess, request_anatomy,
                                           summarize_requests)

    ledger.enable(True, log_dir=str(tmp_path), run_id="driver")
    eng = _stub_engine(max_batch=2)
    try:
        driver = OpenLoopDriver(
            eng, PoissonProcess(30.0, seed=2),
            LengthSampler.fixed(4, 6), duration_s=1.5, vocab=10,
            cancel_fraction=0.3, cancel_after_tokens=2,
            gauge_period_s=0.1, seed=2)
        result = driver.run(drain_timeout_s=120.0)
    finally:
        eng.stop()
        ledger.reset()
    assert result.offered == len(result.rows) > 10
    outcomes = {r["outcome"] for r in result.rows}
    assert "finish" in outcomes and "cancel" in outcomes
    assert len(result.gauges) >= 5          # sampled during the soak
    assert all(g["queue_depth"] >= 0 for g in result.gauges)
    # full lifecycle coverage in the ledger
    anatomy = request_anatomy(ledger.load_ledger(str(tmp_path)))
    assert anatomy["submitted"] == result.offered
    assert anatomy["coverage"] == 1.0
    summary = summarize_requests(result.rows, result.duration_s,
                                 wall_s=result.wall_s,
                                 overhead_s=result.overhead_s)
    assert summary["finished"] + summary["cancelled"] == result.offered
    assert summary["ttft_p99"] is not None
    # cancelled streams are excluded from TBT rows
    assert all(r["tbt_s"] is None for r in result.rows
               if r["outcome"] == "cancel")
    # observability + driver bookkeeping stays a small fraction of wall
    # (the strict <2% budget is asserted on the longer CI soak)
    assert summary["overhead_frac"] < 0.2


# -- report / curve ----------------------------------------------------------

def _mk_rows(n_finish, n_shed=0, n_cancel=0, ttft=0.05, tbt=0.01):
    rows = []
    for i in range(n_finish):
        rows.append({"rid": i, "outcome": "finish", "tokens": 8,
                     "ttft_s": ttft, "queue_wait_s": ttft / 2,
                     "prefill_s": ttft / 4, "tbt_s": tbt})
    for i in range(n_shed):
        rows.append({"rid": 1000 + i, "outcome": "shed", "tokens": 0,
                     "ttft_s": None, "queue_wait_s": 0.0,
                     "prefill_s": 0.0, "tbt_s": None})
    for i in range(2000, 2000 + n_cancel):
        rows.append({"rid": i, "outcome": "cancel", "tokens": 2,
                     "ttft_s": ttft, "queue_wait_s": ttft / 2,
                     "prefill_s": ttft / 4, "tbt_s": None})
    return rows


def test_summarize_requests_partitions_outcomes():
    from fedml_tpu.serving.loadgen import summarize_requests

    s = summarize_requests(_mk_rows(8, n_shed=2, n_cancel=1), 10.0)
    assert s["offered"] == 11 and s["finished"] == 8
    assert s["shed"] == 2 and s["cancelled"] == 1
    assert s["shed_rate"] == pytest.approx(2 / 11)
    assert s["goodput_qps"] == pytest.approx(0.8)
    assert s["tbt_p99"] == pytest.approx(0.01)   # finish-only
    assert s["tokens"] == 8 * 8 + 2


def test_find_knee_and_graceful_verdict():
    from fedml_tpu.serving.loadgen import (find_knee, render_curve,
                                           summarize_requests)

    def point(qps, n_finish, n_shed, ttft):
        s = summarize_requests(
            _mk_rows(n_finish, n_shed=n_shed, ttft=ttft), 10.0)
        return s

    # graceful: past-knee point sheds, admitted p99 stays bounded
    graceful = [point(2, 20, 0, 0.02), point(8, 80, 0, 0.05),
                point(20, 150, 50, 0.2)]
    knee = find_knee(graceful, slo_ttft_p99_s=0.5)
    assert knee is graceful[1]        # last point fails goodput floor
    out = render_curve(graceful, 0.5)
    assert "<- knee" in out and "GRACEFUL" in out
    # collapsing: no shedding, p99 through the SLO
    collapsing = [point(2, 20, 0, 0.02), point(8, 80, 0, 0.05),
                  point(20, 190, 0, 3.0)]
    out2 = render_curve(collapsing, 0.5)
    assert "COLLAPSING" in out2 and "--admission" in out2
    # undersized: every point breaches
    assert find_knee([point(2, 20, 0, 3.0)], 0.5) is None


def test_request_anatomy_renders_exemplars():
    from fedml_tpu.serving.loadgen import (render_exemplars,
                                           render_request_timeline,
                                           request_anatomy)

    recs = [
        {"actor": "serving", "event": "submit", "ts_mono": 1.0,
         "attrs": {"rid": 1, "engine": "kv", "prompt_tokens": 4,
                   "max_new": 8}},
        {"actor": "serving", "event": "admit", "ts_mono": 1.01,
         "attrs": {"rid": 1, "slot": 0, "queue_wait_s": 0.01}},
        {"actor": "serving", "event": "first_token", "ts_mono": 1.02,
         "attrs": {"rid": 1, "ttft_s": 0.02, "queue_wait_s": 0.01,
                   "prefill_s": 0.005, "first_decode_s": 0.005}},
        {"actor": "serving", "event": "finish", "ts_mono": 1.05,
         "attrs": {"rid": 1, "tokens": 8, "service_s": 0.05,
                   "finish_reason": "stop"}},
        {"actor": "serving", "event": "submit", "ts_mono": 1.1,
         "attrs": {"rid": 2, "engine": "kv", "prompt_tokens": 4,
                   "max_new": 8}},
        {"actor": "serving", "event": "shed", "ts_mono": 1.1,
         "attrs": {"rid": 2, "reason": "queue_full", "queue_depth": 9}},
        {"actor": "serving", "event": "decode_batch", "ts_mono": 1.2,
         "attrs": {"active": 1}},        # aggregate event: no rid, skipped
    ]
    spans = [{"attrs": {"rid": 1}, "dur_s": 0.05, "status": None,
              "trace_id": "t1"}]
    anatomy = request_anatomy(recs, spans)
    assert anatomy["submitted"] == 2 and anatomy["coverage"] == 1.0
    assert anatomy["requests"][1]["span"]["dur_s"] == 0.05
    tl = render_request_timeline(anatomy, 1)
    assert "first_token" in tl and "ttft 20.0 ms" in tl
    ex = render_exemplars(anatomy)
    assert "lifecycle coverage 100.0%" in ex
    assert "a shed request" in ex and "queue_full" in ex


# -- SLO indicators ----------------------------------------------------------

def test_serving_slo_indicators_from_metrics():
    from fedml_tpu.core.mlops import slo as slo_mod

    metrics_mod.histogram(
        "fedml_llm_queue_wait_seconds", "Submit -> admit wait",
        labels=("engine",)).labels(engine="kv").observe(0.02)
    metrics_mod.histogram(
        "fedml_llm_tbt_seconds", "Mean inter-token gap",
        labels=("engine",)).labels(engine="kv").observe(0.004)
    metrics_mod.counter(
        "fedml_llm_shed_total", "Requests shed by admission control",
        labels=("engine", "reason")).labels(
            engine="kv", reason="queue_full").inc(2)
    metrics_mod.counter(
        "fedml_llm_requests_total", "Requests retired, by outcome",
        labels=("engine", "outcome")).labels(
            engine="kv", outcome="finish").inc(6)

    rules = [slo_mod.SLORule(name="qw", indicator="queue_wait_p99",
                             max=10.0),
             slo_mod.SLORule(name="tbt", indicator="decode_tbt_p99",
                             max=10.0)]
    results = slo_mod.evaluate(rules, slo_mod.SLOContext.live())
    by_name = {r["rule"]: r for r in results}
    assert by_name["qw"]["ok"] is True
    assert by_name["qw"]["value"] > 0
    assert by_name["tbt"]["ok"] is True
    # shed-rate over the live counters: shed / all requests
    rate = slo_mod.INDICATORS["serving_shed_rate"](
        slo_mod.SLOContext.live(),
        slo_mod.SLORule(name="s", indicator="serving_shed_rate",
                        max=1.0))
    assert rate is not None and 0.0 < rate <= 1.0


def test_serving_shed_rate_ledger_fallback(tmp_path):
    from fedml_tpu.core.mlops import slo as slo_mod

    recs = ([{"actor": "serving", "event": "submit", "ts_mono": t,
              "attrs": {"rid": t}} for t in range(10)]
            + [{"actor": "serving", "event": "shed", "ts_mono": 20 + t,
                "attrs": {"rid": t, "reason": "queue_full"}}
               for t in range(3)])
    (tmp_path / "ledger.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in recs))
    ctx = slo_mod.SLOContext.from_artifacts(log_dir=str(tmp_path))
    rule = slo_mod.SLORule(name="shed", indicator="serving_shed_rate",
                           max=0.5)
    assert slo_mod.INDICATORS["serving_shed_rate"](ctx, rule) \
        == pytest.approx(0.3)
    results = slo_mod.evaluate([rule], ctx)
    assert results[0]["ok"] is True


# -- perf history ------------------------------------------------------------

def test_perf_history_serving_headline_regression(tmp_path):
    from fedml_tpu.core.mlops import perf_history

    assert "serving_sustained_qps" in perf_history.HEADLINE_METRICS
    assert "serving_tokens_per_s" in perf_history.HEADLINE_METRICS
    path = str(tmp_path / "hist.jsonl")
    perf_history.append_entry(
        path, platform="cpu", source="fedml load run",
        metrics={"serving_sustained_qps": 10.0,
                 "serving_tokens_per_s": 100.0}, ts=1.0, rev="aaa")
    perf_history.append_entry(
        path, platform="cpu", source="fedml load run",
        metrics={"serving_sustained_qps": 4.0,
                 "serving_tokens_per_s": 99.0}, ts=2.0, rev="bbb")
    findings = perf_history.detect(perf_history.load_history(path))
    regressed = {r["metric"] for r in findings["regressions"]}
    assert "serving_sustained_qps" in regressed
    assert "serving_tokens_per_s" not in regressed     # 1% < threshold


# -- CLI ---------------------------------------------------------------------

def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_cli_load_run_report_and_slo_gate(tmp_path):
    from fedml_tpu.cli.cli import cli

    out = str(tmp_path / "soak")
    hist = str(tmp_path / "hist.jsonl")
    res = CliRunner().invoke(cli, [
        "load", "run", "--arrivals", "poisson:20", "--duration-s", "1.5",
        "--dim", "16", "--layers", "1", "--heads", "2", "--max-len", "48",
        "--max-batch", "2", "--lengths", "fixed:4:4",
        "--cancel-fraction", "0.2", "--out", out, "--history", hist,
        "--platform", "cpu-test"])
    assert res.exit_code == 0, res.output
    assert "lifecycle" not in res.output      # report, not anatomy
    for name in ("requests.jsonl", "gauges.jsonl", "summary.json",
                 "metrics.prom", "ledger.jsonl", "spans.jsonl"):
        assert os.path.exists(os.path.join(out, name)), name
    with open(os.path.join(out, "summary.json")) as f:
        summary = json.load(f)
    assert summary["finished"] > 0
    # provenance-stamped history row
    with open(hist) as f:
        entry = json.loads(f.readlines()[-1])
    assert entry["platform"] == "cpu-test" and entry["measured"]
    assert entry["metrics"]["serving_sustained_qps"] > 0
    assert "offered" in entry["notes"] and "ttft_p99" in entry["notes"]

    res2 = CliRunner().invoke(cli, ["load", "report", "--out", out,
                                    "--anatomy"])
    assert res2.exit_code == 0, res2.output
    assert "lifecycle coverage" in res2.output
    assert "slowest completed request" in res2.output
    assert "first_token" in res2.output

    res3 = CliRunner().invoke(cli, [
        "slo", "check",
        "--rules", os.path.join(_repo_root(), "examples",
                                "slo_serving.yaml"),
        "--log-dir", out, "--metrics", os.path.join(out, "metrics.prom")])
    assert res3.exit_code == 0, res3.output
    assert "decode_ttft_p99" in res3.output


@pytest.mark.slow
def test_cli_load_curve_finds_knee(tmp_path):
    """Acceptance: the CPU-proxy sweep locates a saturation knee and the
    engine degrades gracefully past it (shedding engaged, admitted p99
    bounded)."""
    from fedml_tpu.cli.cli import cli

    curve_path = str(tmp_path / "curve.json")
    res = CliRunner().invoke(cli, [
        "load", "curve", "--qps", "8,64,256", "--duration-s", "4",
        "--max-batch", "2", "--lengths", "fixed:16:32",
        "--admission", "queue:8", "--slo-ttft-p99", "1.0",
        "--out", curve_path])
    assert res.exit_code == 0, res.output
    assert "<- knee" in res.output
    with open(curve_path) as f:
        curve = json.load(f)
    assert curve["knee"] is not None
    past = [p for p in curve["points"]
            if p["offered_qps"] > curve["knee"]["offered_qps"]]
    assert past, "sweep never exceeded the knee"
    assert any(p["shed_rate"] > 0 for p in past)          # shedding engaged
    assert all(p["ttft_p99"] <= 1.0 for p in past)        # bounded p99


def test_serving_scaler_scales_up_under_open_loop_burst(tmp_path):
    """Policy loop under the load plane (docs/SCHEDULER.md "Elastic
    resize"): an MMPP burst through the open-loop driver feeds the real
    decode-step histogram the engine exports, and the replica scaler
    answers with an IN-PLACE resize request on the elastic RUNNING
    serving job — no drain, no preemption."""
    from fedml_tpu.scheduler.autoscaler import AutoscalePolicy
    from fedml_tpu.scheduler.pod import JobQueue, JobSpec, JobState
    from fedml_tpu.scheduler.pod.serving_scaler import ServingReplicaScaler
    from fedml_tpu.serving.loadgen import (LengthSampler,
                                           MarkovModulatedProcess,
                                           OpenLoopDriver)

    q = JobQueue(str(tmp_path / "pod"))
    jid = q.submit(JobSpec(name="svc", kind="serving", n_slots=2,
                           min_slots=1, max_slots=8, command="serve"))
    q.mark_dispatched(jid, "runS", [0, 1], "/tmp/l")
    scaler = ServingReplicaScaler(
        q, policy=AutoscalePolicy(min_replicas=1, max_replicas=8,
                                  target_latency_s=1e-6,
                                  target_qps_per_replica=1.0))
    assert scaler.tick() == {}               # baseline decode window
    eng = _stub_engine(max_batch=2)
    try:
        driver = OpenLoopDriver(
            eng, MarkovModulatedProcess(5.0, 80.0, switch_p=0.02, seed=7),
            LengthSampler.fixed(4, 6), duration_s=1.5, vocab=10,
            gauge_period_s=0.2, seed=7)
        result = driver.run(drain_timeout_s=120.0)
    finally:
        eng.stop()
    assert result.offered > 0
    decisions = scaler.tick()                # window saw the burst
    assert decisions.get(jid, 2) > 2
    row = q.get(jid)
    # elastic + RUNNING → the scaler latched an in-place resize
    assert row["state"] == JobState.RUNNING
    assert not row["preempt_requested"]
    assert row["resize_requested"] == decisions[jid]
    q.close()
