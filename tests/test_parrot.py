"""Parrot vectorized-simulation tests: parity with the SP loop and the mesh
(sharded clients axis) path on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


def test_parrot_fedavg_converges(args_factory):
    m = _run(args_factory(backend="parrot", comm_round=5, data_scale=0.3))
    assert m["test_acc"] > 0.3
    assert np.isfinite(m["test_loss"])


def test_parrot_partial_participation(args_factory):
    m = _run(args_factory(backend="parrot", client_num_in_total=8,
                          client_num_per_round=4, comm_round=6,
                          data_scale=0.3))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


@pytest.mark.parametrize("opt", ["FedProx", "FedOpt", "FedNova", "SCAFFOLD",
                                 "FedDyn", "Mime"])
def test_parrot_optimizers(args_factory, opt):
    m = _run(args_factory(backend="parrot", federated_optimizer=opt,
                          comm_round=5, data_scale=0.3, server_lr=0.3))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.15


def test_mesh_backend_shards_clients(args_factory):
    """Mesh (sharded clients axis) parity: the 8-device mesh path must
    reproduce the parrot trajectory — triage showed both backends produce
    the IDENTICAL trajectory here (acc 0.1333→0.2333 over 4 rounds; loss
    within 2e-7 from sharded reduction order), so the old absolute
    ``> 0.25`` bar was an over-tight progress threshold, not a mesh bug."""
    kw = dict(client_num_in_total=8, client_num_per_round=8, comm_round=4,
              data_scale=0.3)
    m = _run(args_factory(backend="mesh", **kw))
    ref = _run(args_factory(backend="parrot", **kw))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] == pytest.approx(ref["test_acc"], abs=1e-6)
    assert m["test_loss"] == pytest.approx(ref["test_loss"], rel=1e-4)
    # and the shared trajectory still makes real progress from 0.1 chance
    assert m["test_acc"] > 0.15


@pytest.mark.parametrize("optimizer", [
    "FedAvg", "FedProx", "FedOpt", "FedNova", "SCAFFOLD", "FedDyn", "Mime",
])
def test_parrot_matches_sp_exactly(args_factory, optimizer):
    """Convergence-parity audit (SURVEY §7 hard part f): the vectorized
    Parrot round (device-resident gather + vmapped local updates + fused
    aggregation) reproduces the sequential SP loop EXACTLY — same client
    sampling stream, same local SGD, same weighted averaging, same
    per-algorithm server state — so the TPU-first redesign provably changes
    the execution strategy, not the algorithm.  Parametrized over every
    shared-engine federated optimizer."""
    import jax

    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    def run(backend):
        args = fedml_tpu.init(args_factory(backend=backend, comm_round=3,
                                           federated_optimizer=optimizer,
                                           data_scale=0.1))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        runner = FedMLRunner(args, device, dataset, bundle)
        metrics = runner.run()
        return metrics, runner.runner.global_vars

    m_sp, gv_sp = run("sp")
    m_pr, gv_pr = run("parrot")
    np.testing.assert_allclose(m_sp["test_loss"], m_pr["test_loss"],
                               rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gv_sp),
                    jax.tree_util.tree_leaves(gv_pr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_run_rounds_fused_chunking_and_noop(args_factory):
    import jax
    import numpy as np

    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(args_factory(backend="parrot", dataset="mnist",
                                       model="lr", data_scale=0.1,
                                       client_num_in_total=8,
                                       client_num_per_round=8, comm_round=2))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    api = FedMLRunner(args, None, dataset, bundle).runner
    # no-op must not touch (donate) live state
    rms0 = api.run_rounds_fused(0)
    assert np.asarray(rms0["train_loss"]).shape == (0,)
    # full chunks + remainder; state stays usable across calls
    rms = api.run_rounds_fused(api.FUSED_CHUNK_ROUNDS * 2 + 3)
    tl = np.asarray(rms["train_loss"])
    assert tl.shape == (api.FUSED_CHUNK_ROUNDS * 2 + 3,)
    assert np.isfinite(tl).all() and tl[-1] < tl[0]
    jax.block_until_ready(api.run_rounds_fused(2))  # still alive


def test_train_fused_rounds_option(args_factory):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(args_factory(
        backend="parrot", dataset="mnist", model="lr", data_scale=0.1,
        client_num_in_total=8, client_num_per_round=8, comm_round=10,
        fused_rounds=True, frequency_of_the_test=5, learning_rate=0.1))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    m = FedMLRunner(args, None, dataset, bundle).run()
    assert m["round"] == 9
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.5


def test_train_fused_checkpoint_resume(args_factory, tmp_path):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    def build(rounds):
        args = fedml_tpu.init(args_factory(
            backend="parrot", dataset="mnist", model="lr", data_scale=0.1,
            client_num_in_total=8, client_num_per_round=8,
            comm_round=rounds, fused_rounds=True, frequency_of_the_test=4,
            checkpoint_dir=str(tmp_path / "ck"), learning_rate=0.1))
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        return FedMLRunner(args, None, dataset, bundle)

    m1 = build(8).run()
    assert m1["round"] == 7
    # a fresh runner resumes from the saved round instead of round 0
    runner2 = build(12)
    m2 = runner2.run()
    assert m2["round"] == 11
    rounds_run = [m["round"] for m in runner2.runner.metrics_history]
    assert min(rounds_run) > 7  # did NOT start over


def test_mesh_backend_with_dcn_shape(args_factory):
    """dcn_mesh_shape extends client sharding across a (simulated) DCN
    axis — the batch axis shards over clients x dp (8-way), not 4-way
    with a replicated dp; the round compiles and learns."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(args_factory(
        backend="mesh", dataset="mnist", model="lr", data_scale=0.1,
        client_num_in_total=8, client_num_per_round=8, comm_round=3,
        mesh_shape={"clients": 4}, dcn_mesh_shape={"dp": 2},
        learning_rate=0.1))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, None, dataset, bundle)
    assert runner.runner.mesh.axis_names == ("clients", "dp")
    # default mesh_shape must also respect the dcn product instead of
    # over-allocating (8 devices / dp=2 -> clients<=4)
    args2 = fedml_tpu.init(args_factory(
        backend="mesh", dataset="mnist", model="lr", data_scale=0.1,
        client_num_in_total=8, client_num_per_round=8, comm_round=1,
        dcn_mesh_shape={"dp": 2}, learning_rate=0.1))
    r2 = FedMLRunner(args2, None, fedml_tpu.data.load(args2),
                     fedml_tpu.model.create(args2, 10))
    assert dict(zip(r2.runner.mesh.axis_names,
                    r2.runner.mesh.devices.shape)) == {"clients": 4, "dp": 2}
    m = runner.run()
    assert np.isfinite(m["test_loss"]) and m["test_acc"] > 0.5


@pytest.mark.parametrize("opt", ["FedAvg", "SCAFFOLD"])
def test_bucketed_hetero_rounds_converge(args_factory, opt):
    """hetero_buckets>1: size-stratified rounds (per-bucket vmap widths)
    still converge, keep per-client state consistent, and report the
    per-round sampled-weight metric."""
    args = fedml_tpu.init(args_factory(
        backend="parrot", federated_optimizer=opt, comm_round=6,
        client_num_in_total=12, client_num_per_round=6, data_scale=0.4,
        partition_alpha=0.3, hetero_buckets=3))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, device, dataset, bundle)
    api = runner.runner
    assert api.buckets is not None and len(api.buckets) >= 2
    # quotas sum to k; bucket capacities are non-decreasing with size strata
    assert sum(b["k"] for b in api.buckets) == api.k
    nbs = [b["nb"] for b in api.buckets]
    assert nbs == sorted(nbs)
    m = runner.run()
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.15


def test_bucketed_fused_rounds_report_mean_tracking_compute(args_factory):
    """The fused path works with buckets and the padded-slot total per round
    is strictly below the uniform nb*k ceiling for a skewed partition."""
    args = fedml_tpu.init(args_factory(
        backend="parrot", comm_round=4, client_num_in_total=12,
        client_num_per_round=6, data_scale=0.4, partition_alpha=0.3,
        hetero_buckets=3, fused_rounds=True))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, device, dataset, bundle)
    api = runner.runner
    padded_bucketed = sum(b["k"] * b["nb"] for b in api.buckets) * api.bs
    padded_uniform = api.k * api.nb * api.bs
    assert padded_bucketed < padded_uniform
    m = runner.run()
    assert np.isfinite(m["test_loss"])
    rms = api.run_rounds_fused(2)
    assert rms["samples"].shape == (2,)
    assert float(rms["samples"].min()) > 0


def test_parrot_runs_are_bitwise_deterministic(args_factory):
    """Same seed → bitwise-identical params and metrics across two full
    runs (the determinism quality bar that replaces the reference's absent
    race detection, SURVEY §5)."""
    import jax

    def run_once():
        args = fedml_tpu.init(args_factory(
            backend="parrot", comm_round=3, client_num_in_total=6,
            client_num_per_round=3, data_scale=0.2, hetero_buckets=3,
            partition_alpha=0.3))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        runner = FedMLRunner(args, device, dataset, bundle)
        m = runner.run()
        leaves = jax.tree_util.tree_leaves(runner.runner.global_vars)
        return m, [np.asarray(x) for x in leaves]

    m1, p1 = run_once()
    m2, p2 = run_once()
    assert m1["test_loss"] == m2["test_loss"]
    assert m1["test_acc"] == m2["test_acc"]
    for a, b in zip(p1, p2):
        np.testing.assert_array_equal(a, b)


def test_parrot_bf16_data_storage_converges(args_factory):
    """data_dtype=bfloat16 (half the resident dataset) still converges."""
    args = fedml_tpu.init(args_factory(
        backend="parrot", comm_round=5, data_scale=0.3,
        data_dtype="bfloat16"))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    runner = FedMLRunner(args, device, dataset, bundle)
    import jax.numpy as jnp

    assert runner.runner.x_all.dtype == jnp.bfloat16
    m = runner.run()
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.3


def _make_parrot(args, use_mesh):
    from fedml_tpu.simulation.parrot.parrot_api import ParrotAPI

    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return ParrotAPI(args, device, dataset, bundle, use_mesh=use_mesh)


def test_bucketed_mesh_batch_axis_sharding_matches_unsharded(args_factory):
    """VERDICT r2 weak #1: the bench-winning bucketed path must shard over
    the mesh.  Quota k/B=2 < 4-device mesh → the INTRA-BATCH axis shards
    (data-parallel SGD per client).  Same on-device rng stream → sharded
    and unsharded runs must agree numerically."""
    kw = dict(backend="mesh", hetero_buckets=2, partition_method="hetero",
              partition_alpha=0.3, client_num_in_total=8,
              client_num_per_round=4, comm_round=3, data_scale=0.3,
              mesh_shape={"clients": 4})
    api_m = _make_parrot(args_factory(**kw), use_mesh=True)
    api_u = _make_parrot(args_factory(**kw), use_mesh=False)
    assert api_m.n_buckets == 2
    # quota (2) doesn't divide the mesh (4) but batch_size (16) does
    assert all(b["k"] == 2 for b in api_m.buckets)
    m = api_m.train()
    u = api_u.train()
    assert np.isfinite(m["test_loss"])
    np.testing.assert_allclose(m["test_loss"], u["test_loss"], atol=2e-4)
    np.testing.assert_allclose(m["test_acc"], u["test_acc"], atol=1e-6)


def test_bucketed_mesh_client_axis_sharding_matches_unsharded(args_factory):
    """Client-axis mode: quota k/B=2 divides a 2-device mesh → the client
    axis itself shards; aggregation lowers to a mesh all-reduce."""
    kw = dict(backend="mesh", hetero_buckets=2, partition_method="hetero",
              partition_alpha=0.3, client_num_in_total=8,
              client_num_per_round=4, comm_round=3, data_scale=0.3,
              mesh_shape={"clients": 2})
    api_m = _make_parrot(args_factory(**kw), use_mesh=True)
    api_u = _make_parrot(args_factory(**kw), use_mesh=False)
    m = api_m.train()
    u = api_u.train()
    np.testing.assert_allclose(m["test_loss"], u["test_loss"], atol=2e-4)
    np.testing.assert_allclose(m["test_acc"], u["test_acc"], atol=1e-6)


@pytest.mark.parametrize("mesh_clients,expect_mode", [
    (4, "batch"),    # quota 2 < mesh 4, bs 16 % 4 == 0 → intra-batch axis
    (2, "client"),   # quota 2 % mesh 2 == 0 → client axis
])
def test_bucketed_mesh_compiles_collectives(args_factory, mesh_clients,
                                            expect_mode):
    """The sharded bucketed step must actually PARTITION: the compiled
    HLO carries all-reduce collectives (grad psum in batch mode, weighted
    aggregation in client mode).  A constraint that silently replicates
    would compile collective-free."""
    import jax

    api = _make_parrot(args_factory(
        backend="mesh", hetero_buckets=2, partition_method="hetero",
        partition_alpha=0.3, client_num_in_total=8, client_num_per_round=4,
        comm_round=1, data_scale=0.3, mesh_shape={"clients": mesh_clients}),
        use_mesh=True)
    sh = api._grid_sharding(api.buckets[0]["k"])
    spec = sh.spec
    if expect_mode == "client":
        assert spec[0] is not None
    else:
        assert spec[0] is None and spec[2] is not None
    compiled = api.bucketed_round_step.lower(
        api.device_data, api.global_vars, api.server_state,
        jax.random.PRNGKey(0)).compile()
    assert "all-reduce" in compiled.as_text()


@pytest.mark.slow
def test_bucketed_vs_uniform_statistical_equivalence(args_factory):
    """VERDICT r3 item 9: size-bucketed hetero rounds are a SCHEDULING
    optimization, not an algorithm change — over >=3 seeds the final
    accuracy distribution must match the uniform path (same budget)."""
    def final_acc(buckets, seed):
        args = fedml_tpu.init(args_factory(
            backend="parrot", dataset="mnist", model="lr",
            partition_method="hetero", partition_alpha=0.3,
            client_num_in_total=12, client_num_per_round=6,
            comm_round=25, data_scale=0.3, batch_size=16,
            learning_rate=0.1, random_seed=seed,
            hetero_buckets=buckets, frequency_of_the_test=100))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        api = FedMLRunner(args, device, dataset, bundle).runner
        api.run_rounds_fused(25)
        tb = api._make_test_batches()
        out = api.eval_step(api.global_vars, tb)
        return float(out["correct"]) / max(float(out["n"]), 1.0)

    seeds = (0, 1, 2)
    uniform = [final_acc(1, s) for s in seeds]
    bucketed = [final_acc(3, s) for s in seeds]
    mu_u, mu_b = float(np.mean(uniform)), float(np.mean(bucketed))
    # same-convergence criterion: mean finals within 5pp and every run
    # lands in the learned regime (not chance)
    assert abs(mu_u - mu_b) < 0.05, (uniform, bucketed)
    assert min(uniform + bucketed) > 0.5, (uniform, bucketed)


def test_patches_conv_matches_lax_conv():
    """PatchesConv (im2col+matmul) must be numerically identical to
    nn.Conv for 3x3/1x1, strided and not — it's a lowering choice, not a
    model change."""
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    from fedml_tpu.models.cv import PatchesConv

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 8, 5), jnp.float32)
    for kernel, strides in (((3, 3), (1, 1)), ((3, 3), (2, 2)),
                            ((1, 1), (1, 1)), ((1, 1), (2, 2))):
        ref = nn.Conv(7, kernel, strides=strides, padding="SAME",
                      use_bias=False)
        mine = PatchesConv(7, kernel, strides)
        v = ref.init(jax.random.PRNGKey(0), x)
        out_ref = ref.apply(v, x)
        out_mine = mine.apply(v, x)          # same param name/shape
        np.testing.assert_allclose(np.asarray(out_mine),
                                   np.asarray(out_ref),
                                   atol=2e-5, rtol=1e-5,
                                   err_msg=f"{kernel} {strides}")


# -- size-bucket cap (rotating windows) ---------------------------------------

def test_bucket_plan_cap_reduces_padding_at_high_utilization():
    """The pure policy function: capping at cap·mean shrinks padded slots
    vs the uncapped plan while expected-real stays within a batch-size
    quantum of padded (utilization ≈ 1), quotas still sum to k, and
    nb never exceeds the full (uncapped) capacity."""
    from fedml_tpu.simulation.parrot.parrot_api import bucket_plan

    rng = np.random.RandomState(0)
    sizes = np.maximum(8, rng.lognormal(4.0, 0.8, size=60).astype(int))
    full = bucket_plan(sizes, k=12, bs=16, n_buckets=6)
    capped = bucket_plan(sizes, k=12, bs=16, n_buckets=6, cap_ratio=0.8)
    assert sum(b["q"] for b in capped) == 12
    assert all(c["nb"] <= f["nb_full"] == f["nb"]
               for c, f in zip(capped, full))
    p_full = sum(b["padded"] for b in full)
    p_cap = sum(b["padded"] for b in capped)
    assert p_cap < p_full
    real_cap = sum(b["real"] for b in capped)
    # every padded slot is (nearly) a real sample: waste only from
    # rounding the cap up to a batch multiple
    assert p_cap / real_cap - 1.0 < 0.10, (p_cap, real_cap)


def test_bucket_cap_rotating_window_converges(args_factory):
    """hetero_bucket_cap: over-cap clients train on per-round rotating
    windows instead of full epochs; convergence must match the uncapped
    policy on the same data (the bench's accuracy-guard contract)."""
    def final_acc(cap):
        args = fedml_tpu.init(args_factory(
            backend="parrot", comm_round=20, client_num_in_total=12,
            client_num_per_round=6, data_scale=0.4, partition_alpha=0.3,
            hetero_buckets=3, hetero_bucket_cap=cap))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        runner = FedMLRunner(args, device, dataset, bundle)
        api = runner.runner
        if cap:
            # the cap actually bites on this skewed partition …
            assert any(b["nb"] < b["nb_full"] for b in api.buckets)
            # … and the padded total shrinks accordingly
            stats = api.bucket_waste_stats()
            assert stats["padded_samples_per_round"] < sum(
                b["nb_full"] * api.bs * b["k"] for b in api.buckets)
        m = runner.run()
        return m["test_acc"]

    acc_full, acc_capped = final_acc(0.0), final_acc(0.75)
    assert acc_capped > 0.35, acc_capped          # learned, not chance
    assert abs(acc_full - acc_capped) < 0.1, (acc_full, acc_capped)


def test_bucket_cap_fused_scan_matches_per_round_path(args_factory):
    """The capped gather traces identically inside the fused scan: same
    config runs on both paths and stays finite/learned."""
    def run(fused):
        args = fedml_tpu.init(args_factory(
            backend="parrot", comm_round=16, client_num_in_total=8,
            client_num_per_round=4, data_scale=0.4, partition_alpha=0.3,
            hetero_buckets=2, hetero_bucket_cap=0.7, fused_rounds=fused,
            parrot_aot_cache=False))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        return FedMLRunner(args, device, dataset, bundle).run()

    m_round, m_fused = run(False), run(True)
    assert np.isfinite(m_round["test_loss"])
    assert np.isfinite(m_fused["test_loss"])
    assert m_round["test_acc"] > 0.3 and m_fused["test_acc"] > 0.3
