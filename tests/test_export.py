"""Portable served-model export (StableHLO — the reference deploy
pipeline's convert_model_to_onnx equivalent): export → load with NO model
code → identical logits → deploy through model cards + replica worker."""

import json
import os

import jax
import numpy as np

import fedml_tpu
from fedml_tpu.serving.export import ExportedPredictor, export_model


def _bundle():
    args = fedml_tpu.Config(model="cnn", dataset="mnist",
                            compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 10)
    variables = bundle.init_variables(jax.random.PRNGKey(0))
    return bundle, variables


def test_export_roundtrip_matches_live_model(tmp_path):
    bundle, variables = _bundle()
    out = export_model(bundle, variables, str(tmp_path / "art"),
                       batch_size=4)
    assert os.path.exists(os.path.join(out, "model.stablehlo"))
    meta = json.load(open(os.path.join(out, "export.json")))
    assert meta["input_shape"] == [28, 28, 1]

    pred = ExportedPredictor(out)
    x = np.random.RandomState(0).rand(6, 28, 28, 1).astype(np.float32)
    served = np.asarray(pred.predict({"inputs": x.tolist()})["logits"])
    live, _ = bundle.apply(variables, x, train=False)
    np.testing.assert_allclose(served, np.asarray(live), atol=1e-4)


def test_exported_artifact_deploys_via_model_card(tmp_path):
    from fedml_tpu.scheduler.model_cards import ModelCardRegistry

    bundle, variables = _bundle()
    art = export_model(bundle, variables, str(tmp_path / "art"),
                       batch_size=4)
    reg = ModelCardRegistry(root=str(tmp_path / "registry"))
    reg.create("exported-cnn", art)
    ep = reg.deploy("exported-cnn", port=0)
    try:
        import urllib.request

        x = np.zeros((2, 28, 28, 1), np.float32)
        req = urllib.request.Request(
            f"http://127.0.0.1:{ep.runner.port}/predict",
            data=json.dumps({"inputs": x.tolist()}).encode(),
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert len(out["predictions"]) == 2
    finally:
        ep.runner.stop()


def test_export_cli(tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    res = CliRunner().invoke(cli, [
        "model", "export", str(tmp_path / "art"), "--model", "lr",
        "--dataset", "mnist", "--batch-size", "4"])
    assert res.exit_code == 0, res.output
    info = json.loads(res.output.strip().splitlines()[-1])
    assert "model.stablehlo" in info["files"]
