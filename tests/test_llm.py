"""LLM fine-tune module: LoRA transform, packing, SFT loop reduces loss."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp


def _bundle():
    import fedml_tpu

    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            compute_dtype="float32")
    return fedml_tpu.model.create(args, 90)


def test_lora_targets_and_apply():
    from fedml_tpu.train.llm import apply_lora, init_lora

    bundle = _bundle()
    variables = bundle.init_variables(jax.random.PRNGKey(0))
    lora = init_lora(variables["params"], rank=4)
    assert len(lora) > 0
    eff = apply_lora(variables["params"], lora, alpha=16.0)
    # b init is zero → effective == base initially
    for (p1, l1), (p2, l2) in zip(
            jax.tree_util.tree_leaves_with_path(variables["params"]),
            jax.tree_util.tree_leaves_with_path(eff)):
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)
    # after perturbing A/B, targeted kernels must change
    lora2 = jax.tree_util.tree_map(lambda x: x + 0.1, lora)
    eff2 = apply_lora(variables["params"], lora2, alpha=16.0)
    diffs = [float(np.abs(np.asarray(a) - np.asarray(b)).max())
             for a, b in zip(jax.tree_util.tree_leaves(eff),
                             jax.tree_util.tree_leaves(eff2))]
    assert max(diffs) > 0.0


def test_pack_sequences_shapes():
    from fedml_tpu.train.llm import pack_sequences

    stream = np.arange(1000) % 90
    b = pack_sequences(stream, seq_len=32, batch_size=4)
    assert b["x"].shape[1:] == (4, 32)
    # next-token alignment
    np.testing.assert_array_equal(b["y"][0, 0, :-1], b["x"][0, 0, 1:])


def test_sft_lora_reduces_loss():
    from fedml_tpu.data.datasets import shakespeare_sequences
    from fedml_tpu.train.llm import LLMTrainConfig, LLMTrainer

    bundle = _bundle()
    xt, _, _, _ = shakespeare_sequences(seq_len=64, n_train=64, n_test=8)
    stream = np.concatenate([x for x in xt])
    cfg = LLMTrainConfig(seq_len=32, batch_size=4, epochs=3,
                         learning_rate=3e-3, lora_rank=4)
    trainer = LLMTrainer(bundle, cfg)
    out = trainer.train(stream)
    assert out["loss_history"][-1] < out["loss_history"][0]
    gen = trainer.generate(stream[:10], max_new=5)
    assert len(gen) == 15


def test_batched_llm_engine_continuous_batching(args_factory):
    import jax
    import numpy as np

    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.llm_engine import BatchedLLMEngine

    args = args_factory(model="transformer", dataset="shakespeare",
                        compute_dtype="float32")
    bundle = model_hub.create(args, 90)
    variables = bundle.init_variables(jax.random.PRNGKey(0), batch_size=2)
    engine = BatchedLLMEngine(bundle, variables, max_batch=4, window=16)
    try:
        # concurrent requests with different lengths — continuous batching
        futs = [engine.submit([1, 2, 3], max_new=4),
                engine.submit([5, 6], max_new=8),
                engine.submit([7], max_new=2, temperature=0.5)]
        outs = [f.result(timeout=120) for f in futs]
        assert outs[0].shape == (3 + 4,)
        assert outs[1].shape == (2 + 8,)
        assert outs[2].shape == (1 + 2,)
        assert np.array_equal(outs[0][:3], [1, 2, 3])  # prompt preserved
        # greedy decode is deterministic: same prompt → same continuation
        again = engine.generate([1, 2, 3], max_new=4)
        assert np.array_equal(again, outs[0])
    finally:
        engine.stop()


def test_llm_engine_behind_openai_api(args_factory):
    import json as _json
    import threading
    import urllib.request

    import jax

    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.llm_engine import (
        BatchedLLMEngine,
        LLMEnginePredictor,
    )
    from fedml_tpu.serving.openai_api import OpenAIServer

    args = args_factory(model="transformer", dataset="shakespeare",
                        compute_dtype="float32")
    bundle = model_hub.create(args, 90)
    variables = bundle.init_variables(jax.random.PRNGKey(0), batch_size=2)
    engine = BatchedLLMEngine(bundle, variables, max_batch=2, window=16)
    server = OpenAIServer(LLMEnginePredictor(engine), model_name="tiny",
                          port=0)
    try:
        server.run(block=False)
        port = server.port
        body = _json.dumps({"model": "tiny", "max_tokens": 4,
                            "messages": [{"role": "user",
                                          "content": "hi"}]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        resp = _json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert resp["object"] == "chat.completion"
        content = resp["choices"][0]["message"]["content"]
        assert isinstance(content, str) and len(content) == 4
    finally:
        server.stop()
        engine.stop()


@pytest.mark.parametrize("strategy", ["dp", "fsdp"])
def test_llm_trainer_sharded_strategies_match_unsharded(strategy):
    """ZeRO-equivalent path: fsdp/dp-sharded fine-tuning produces the same
    loss as the unsharded run (same data, same seeds)."""
    import fedml_tpu
    from fedml_tpu.train.llm.trainer import LLMTrainConfig, LLMTrainer

    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 90)
    tokens = np.random.RandomState(0).randint(0, 90, size=4000)

    base = LLMTrainer(bundle, LLMTrainConfig(
        seq_len=32, batch_size=8, epochs=1, use_lora=True))
    m0 = base.train(tokens)

    sharded = LLMTrainer(bundle, LLMTrainConfig(
        seq_len=32, batch_size=8, epochs=1, use_lora=True,
        strategy=strategy))
    m1 = sharded.train(tokens)
    np.testing.assert_allclose(m1["train_loss"], m0["train_loss"],
                               rtol=1e-4)


def test_kv_cache_decode_matches_full_forward():
    """Prefill + per-row cached decode reproduces the non-cached forward
    token-for-token (greedy), including rows at DIFFERENT positions."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM

    lm = KVCacheLM.create(jax.random.PRNGKey(0), vocab=50, dim=32,
                          layers=2, heads=4, max_len=64)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(0, 50, size=n)) for n in (5, 9, 3)]
    max_new = 8

    # reference: greedy with full re-forward each step
    ref_out = []
    for ids in prompts:
        ids = list(ids)
        for _ in range(max_new):
            logits = lm.full_logits(jnp.asarray([ids]))
            ids.append(int(jnp.argmax(logits[0, -1])))
        ref_out.append(ids)

    # cached: batched prefill (padded) + decode loop with per-row pos
    b = len(prompts)
    t0 = max(len(p) for p in prompts)
    toks = np.zeros((b, t0), np.int32)
    length = np.asarray([len(p) for p in prompts], np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p)] = p
    # prefill returns a full max_len-sized cache, so decode can continue
    # past the prompt width with no manual re-scatter.  Short rows carry
    # padding-token K/V between their length and t0 — harmless: decode
    # overwrites each position BEFORE the pos-mask ever admits it.
    cache, last = lm.prefill(jnp.asarray(toks), jnp.asarray(length))
    assert cache[0]["k"].shape[1] == lm.max_len

    out = [list(p) for p in prompts]
    pos = length.copy()
    nxt = np.asarray([int(jnp.argmax(last[i])) for i in range(b)])
    for i in range(b):
        out[i].append(int(nxt[i]))
    for _ in range(max_new - 1):
        cache, logits = lm.decode(cache, jnp.asarray(nxt),
                                  jnp.asarray(pos))
        pos = pos + 1
        nxt = np.asarray([int(jnp.argmax(logits[i])) for i in range(b)])
        for i in range(b):
            out[i].append(int(nxt[i]))
    assert out == ref_out


def test_kv_cache_engine_matches_uncached_generation():
    """KVCacheLLMEngine (chunked prefill + per-row cache, continuous
    batching) returns the same greedy continuations as full re-forward."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    lm = KVCacheLM.create(jax.random.PRNGKey(1), vocab=40, dim=32,
                          layers=2, heads=4, max_len=48)
    rng = np.random.RandomState(1)
    prompts = [list(rng.randint(0, 40, size=n)) for n in (4, 7, 2, 5)]

    expect = []
    for ids in prompts:
        ids = list(ids)
        for _ in range(6):
            logits = lm.full_logits(jnp.asarray([ids]))
            ids.append(int(jnp.argmax(logits[0, -1])))
        expect.append(ids)

    eng = KVCacheLLMEngine(lm, max_batch=3)  # < n prompts → queueing too
    try:
        futs = [eng.submit(p, max_new=6) for p in prompts]
        outs = [list(f.result(timeout=120)) for f in futs]
    finally:
        eng.stop()
    assert outs == expect


def test_kv_cache_engine_long_prompt_truncates_but_returns_full():
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    lm = KVCacheLM.create(jax.random.PRNGKey(1), vocab=40, dim=32,
                          layers=2, heads=4, max_len=16)
    prompt = list(np.random.RandomState(2).randint(0, 40, size=30))
    eng = KVCacheLLMEngine(lm, max_batch=2)
    try:
        out = list(eng.generate(prompt, max_new=4, timeout=120))
    finally:
        eng.stop()
    assert out[:30] == prompt           # full prompt comes back
    assert len(out) == 34               # plus the requested tokens


def test_quantized_kv_lm_close_to_full_precision():
    """Int8 per-channel weight quantization: decode logits track the
    full-precision model closely and the engine serves through it."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine
    from fedml_tpu.serving.quantization import QuantizedKVCacheLM

    lm = KVCacheLM.create(jax.random.PRNGKey(2), vocab=40, dim=32,
                          layers=2, heads=4, max_len=32)
    qlm = QuantizedKVCacheLM.from_lm(lm)

    toks = jnp.asarray(np.random.RandomState(3).randint(0, 40, size=(2, 10)))
    full = lm.full_logits(toks)
    quant = qlm.full_logits(toks)
    # int8 noise is small relative to the logit scale
    scale = float(jnp.std(full))
    assert float(jnp.max(jnp.abs(full - quant))) < 0.15 * max(scale, 1.0)

    # cached decode parity with ITSELF (prefill+decode vs full forward)
    length = jnp.asarray([10, 10], jnp.int32)
    cache_rows, last = qlm.prefill(toks, length)
    np.testing.assert_allclose(np.asarray(last), np.asarray(quant[:, 9]),
                               atol=1e-4, rtol=1e-4)

    eng = KVCacheLLMEngine(qlm, max_batch=2)
    try:
        out = eng.generate(list(range(5)), max_new=4, timeout=120)
    finally:
        eng.stop()
    assert len(out) == 9


def test_transformer_block_flash_path_matches_flax():
    """Deterministic passes through the flash attention_fn equal the
    stock flax dot-product attention (same params)."""
    from fedml_tpu.models.nlp import TinyTransformerLM

    x = jnp.asarray(np.random.RandomState(5).randint(0, 90, size=(2, 16)))
    flash_lm = TinyTransformerLM(vocab_size=90, dim=32, layers=2, heads=2)
    v = flash_lm.init(jax.random.PRNGKey(0), x)
    out_flash = flash_lm.apply(v, x, train=False)

    # rebuild with use_flash disabled in every block via module kwargs
    from fedml_tpu.models import nlp as _nlp

    orig = _nlp.TransformerBlock
    try:
        _nlp.TransformerBlock = lambda *a, **kw: orig(
            *a, **dict(kw, use_flash=False))
        plain_lm = TinyTransformerLM(vocab_size=90, dim=32, layers=2,
                                     heads=2)
        out_plain = plain_lm.apply(v, x, train=False)
    finally:
        _nlp.TransformerBlock = orig
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_plain),
                               atol=2e-5, rtol=2e-5)


def test_llm_trainer_grad_accum_and_cosine_schedule():
    """gradient_accumulation_steps + cosine LR run end to end and learn."""
    import fedml_tpu
    from fedml_tpu.train.llm.trainer import LLMTrainConfig, LLMTrainer

    args = fedml_tpu.Config(model="transformer", dataset="shakespeare",
                            compute_dtype="float32")
    bundle = fedml_tpu.model.create(args, 90)
    tokens = np.random.RandomState(0).randint(0, 90, size=6000)
    cfg = LLMTrainConfig(seq_len=32, batch_size=4, epochs=3,
                         learning_rate=3e-3, lora_rank=4,
                         grad_accum_steps=2, lr_schedule="cosine",
                         warmup_steps=5, lr_decay_steps=60)
    out = LLMTrainer(bundle, cfg).train(tokens)
    assert out["loss_history"][-1] < out["loss_history"][0]


def test_make_lr_schedules():
    from types import SimpleNamespace as NS

    from fedml_tpu.ml.engine.optimizers import make_lr

    const = make_lr(NS(learning_rate=0.1))
    assert const == 0.1
    cos = make_lr(NS(learning_rate=0.1, lr_schedule="cosine",
                     warmup_steps=10, lr_decay_steps=100))
    assert float(cos(0)) < 1e-6 and abs(float(cos(10)) - 0.1) < 1e-6
    assert float(cos(100)) < float(cos(50))
    lin = make_lr(NS(learning_rate=0.2, lr_schedule="linear",
                     warmup_steps=4, lr_decay_steps=20))
    assert abs(float(lin(4)) - 0.2) < 1e-6 and float(lin(20)) < 1e-6
    import pytest as _pytest

    with _pytest.raises(ValueError):
        make_lr(NS(learning_rate=0.1, lr_schedule="nope"))


def test_sampling_controls_top_k_top_p():
    """top-k / nucleus filtering restricts sampled tokens to the allowed
    set; greedy ignores them."""
    from fedml_tpu.serving.llm_engine import _Request, _sample_token

    rng = np.random.default_rng(0)
    row = np.asarray([5.0, 4.0, 3.0, -10.0, -10.0])
    greedy = _Request([0], 1, temperature=0.0)
    assert _sample_token(row, greedy, rng) == 0
    topk = _Request([0], 1, temperature=1.0, top_k=2)
    picks = {_sample_token(row, topk, rng) for _ in range(50)}
    assert picks <= {0, 1}
    nucleus = _Request([0], 1, temperature=1.0, top_p=0.6)
    picks = {_sample_token(row, nucleus, rng) for _ in range(50)}
    assert picks <= {0, 1}  # p(0)~0.70 covers the 0.6 nucleus with token 0+1


def test_kv_engine_multi_dispatch_equals_single_dispatch():
    """tokens_per_dispatch>1 (on-device sampling loop) produces the same
    greedy output as per-token dispatch, and temperature requests (which
    sample on-device in the multi path) still respect lengths."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    lm = KVCacheLM.create(jax.random.PRNGKey(3), vocab=40, dim=32,
                          layers=2, heads=4, max_len=64)
    prompts = [list(np.random.RandomState(4).randint(0, 40, size=n))
               for n in (4, 9)]

    outs = {}
    for k in (1, 8):
        eng = KVCacheLLMEngine(lm, max_batch=2, tokens_per_dispatch=k)
        try:
            outs[k] = [list(eng.generate(p, max_new=7, timeout=120))
                       for p in prompts]
        finally:
            eng.stop()
    assert outs[1] == outs[8]

    eng = KVCacheLLMEngine(lm, max_batch=2, tokens_per_dispatch=4)
    try:
        out = eng.generate(prompts[0], max_new=6, temperature=0.8,
                           timeout=120)
        # top-k filtering runs on-device inside the multi path
        out2 = eng.generate(prompts[1], max_new=5, temperature=0.8,
                            top_k=3, timeout=120)
    finally:
        eng.stop()
    assert len(out) == len(prompts[0]) + 6
    assert len(out2) == len(prompts[1]) + 5


def test_functional_lm_finetune_then_kv_serve():
    """One pytree end-to-end: fine-tune the functional LM (LoRA via the
    shared trainer), merge, then serve the SAME params through the
    KV-cache engine — greedy output equals the trained model's full
    forward."""
    import fedml_tpu
    from fedml_tpu.train.llm import apply_lora
    from fedml_tpu.train.llm.trainer import LLMTrainConfig, LLMTrainer
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    args = fedml_tpu.Config(model="functional_lm", dataset="shakespeare",
                            compute_dtype="float32", lm_dim=32, lm_layers=2,
                            lm_heads=4, lm_max_len=64)
    bundle = fedml_tpu.model.create(args, 90)
    tokens = np.random.RandomState(0).randint(0, 90, size=4000)
    cfg = LLMTrainConfig(seq_len=32, batch_size=4, epochs=2,
                         learning_rate=3e-3, lora_rank=4)
    trainer = LLMTrainer(bundle, cfg)
    out = trainer.train(tokens)
    assert out["loss_history"][-1] < out["loss_history"][0]

    merged = apply_lora(trainer.variables["params"], trainer.lora,
                        cfg.lora_alpha)
    lm = KVCacheLM(merged, heads=4, max_len=64)
    prompt = list(tokens[:8])
    ids = list(prompt)
    for _ in range(6):
        logits = lm.full_logits(jnp.asarray([ids]))
        ids.append(int(jnp.argmax(logits[0, -1])))

    eng = KVCacheLLMEngine(lm, max_batch=2)
    try:
        served = list(eng.generate(prompt, max_new=6, timeout=120))
    finally:
        eng.stop()
    assert served == ids


def test_on_device_sampler_top_p_zero_keeps_top_token():
    from fedml_tpu.serving.kv_cache_lm import _filter_sample

    logits = jnp.asarray([[1.0, 5.0, 3.0], [4.0, 0.0, 9.0]])
    out = _filter_sample(logits, jnp.asarray([1.0, 1.0]),
                         jnp.asarray([0, 0]), jnp.asarray([0.0, 0.0]),
                         jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), [1, 2])


def test_on_device_sampler_no_filters_reaches_full_vocab():
    """With top_k=0 and top_p=1 (both off), plain temperature sampling must
    cover the FULL vocab, not just the top-FILTER_CAP candidates — the
    capped fast path only applies when a filter is active."""
    from fedml_tpu.serving.kv_cache_lm import FILTER_CAP, _filter_sample

    v = FILTER_CAP + 72
    # DISTINCT near-uniform logits: the top-FILTER_CAP set is unambiguous
    # (uniform logits would let lax.top_k's first-occurrence tie-break
    # pick a different set than argsort and make this test vacuous), yet
    # every token keeps ~1/v sampling mass
    logits = (jnp.arange(v, dtype=jnp.float32) * 1e-4)[None]
    temps = jnp.asarray([1.0])
    off_k = jnp.asarray([0])
    off_p = jnp.asarray([1.0])
    top_cap = set(int(i) for i in
                  jax.lax.top_k(logits, FILTER_CAP)[1][0])
    assert top_cap == set(range(v - FILTER_CAP, v))  # sanity: unambiguous
    seen_outside = False
    for seed in range(64):
        tok = int(_filter_sample(logits, temps, off_k, off_p,
                                 jax.random.PRNGKey(seed))[0])
        assert 0 <= tok < v
        if tok not in top_cap:
            seen_outside = True
            break
    assert seen_outside  # P(miss 64x) ~ (128/200)^64 ~ 4e-13


def test_kv_engine_stats_feed_the_autoscaler():
    from fedml_tpu.scheduler.autoscaler import (
        AutoscalePolicy,
        ReplicaAutoscaler,
    )
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    lm = KVCacheLM.create(jax.random.PRNGKey(5), vocab=40, dim=32,
                          layers=1, heads=4, max_len=32)
    eng = KVCacheLLMEngine(lm, max_batch=2)
    try:
        eng.generate([1, 2, 3], max_new=4, timeout=120)
        st = eng.stats()
        assert st["tokens_per_s"] > 0 and st["queue_depth"] == 0
        scaler = ReplicaAutoscaler(AutoscalePolicy(max_replicas=4,
                                                   cooldown_s=0.0))
        n = scaler.observe(qps=st["tokens_per_s"], latency_s=0.01,
                           queue_depth=int(st["queue_depth"]))
        assert 1 <= n <= 4
    finally:
        eng.stop()


def test_openai_api_streams_tokens_incrementally():
    """stream=true yields one SSE delta PER TOKEN as the engine generates
    (not one final blob)."""
    import json as _json
    import urllib.request

    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import (
        KVCacheLLMEngine,
        LLMEnginePredictor,
    )
    from fedml_tpu.serving.openai_api import OpenAIServer

    lm = KVCacheLM.create(jax.random.PRNGKey(6), vocab=90, dim=32,
                          layers=1, heads=4, max_len=64)
    engine = KVCacheLLMEngine(lm, max_batch=2)
    server = OpenAIServer(LLMEnginePredictor(engine), model_name="tiny",
                          port=0)
    try:
        server.run(block=False)
        body = _json.dumps({"model": "tiny", "max_tokens": 6,
                            "stream": True,
                            "messages": [{"role": "user",
                                          "content": "hi"}]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/chat/completions",
            data=body, headers={"Content-Type": "application/json"})
        deltas = []
        with urllib.request.urlopen(req, timeout=300) as resp:
            for raw in resp:
                line = raw.decode().strip()
                if not line.startswith("data: ") or line == "data: [DONE]":
                    continue
                chunk = _json.loads(line[len("data: "):])
                d = chunk["choices"][0]["delta"].get("content")
                if d:
                    deltas.append(d)
        # 6 tokens → 6 one-char deltas (char-level codec)
        assert len(deltas) == 6
        assert all(len(d) == 1 for d in deltas)
    finally:
        server.stop()
        engine.stop()


def test_kv_engine_surfaces_length_finish_reason():
    """A request the cache cannot fully honor resolves with
    finish_reason='length' on future.request and through predict_full."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import (
        KVCacheLLMEngine,
        LLMEnginePredictor,
    )

    lm = KVCacheLM.create(jax.random.PRNGKey(7), vocab=90, dim=32,
                          layers=1, heads=4, max_len=16)
    eng = KVCacheLLMEngine(lm, max_batch=2)
    try:
        prompt = list(np.random.RandomState(3).randint(0, 90, size=6))
        fut = eng.submit(prompt, max_new=100)     # 6 + 100 > 16
        fut.result(timeout=120)
        assert fut.request.finish_reason == "length"
        # within budget → "stop"
        fut2 = eng.submit(prompt, max_new=3)
        fut2.result(timeout=120)
        assert fut2.request.finish_reason == "stop"

        pred = LLMEnginePredictor(eng)
        r = pred.predict_full({"prompt": "abcdef", "max_tokens": 100})
        assert r["finish_reason"] == "length"
        r2 = pred.predict_full({"prompt": "ab", "max_tokens": 2})
        assert r2["finish_reason"] == "stop"
    finally:
        eng.stop()


def test_stream_close_cancels_engine_request():
    """Closing the token stream mid-generation cancels the underlying
    request: its slot frees and the future resolves."""
    import time as _time

    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import (
        KVCacheLLMEngine,
        LLMEnginePredictor,
    )

    lm = KVCacheLM.create(jax.random.PRNGKey(8), vocab=90, dim=32,
                          layers=1, heads=4, max_len=256)
    # 1-token dispatch so cancellation lands between steps promptly
    eng = KVCacheLLMEngine(lm, max_batch=2, tokens_per_dispatch=1)
    pred = LLMEnginePredictor(eng)
    try:
        r = pred.predict_full({"prompt": "hello", "max_tokens": 200,
                               "stream": True})
        gen = r["stream"]
        next(gen)                      # at least one token flowed
        gen.close()                    # consumer disconnects
        deadline = _time.time() + 30
        while eng.active_count and _time.time() < deadline:
            _time.sleep(0.05)
        assert eng.active_count == 0   # slot was freed by cancellation
    finally:
        eng.stop()


def test_prefill_cache_supports_decode_past_prompt_width():
    """prefill returns a max_len cache: decode_step keeps matching the
    full forward well past the prompt width (the old prompt-width cache
    silently dropped those writes)."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM

    lm = KVCacheLM.create(jax.random.PRNGKey(9), vocab=50, dim=32,
                          layers=2, heads=4, max_len=32)
    prompt = list(np.random.RandomState(4).randint(0, 50, size=5))
    cache, last = lm.prefill(jnp.asarray([prompt]), jnp.asarray([5]))
    assert cache[0]["k"].shape[1] == lm.max_len
    ids = list(prompt)
    nxt = int(jnp.argmax(last[0]))
    ids.append(nxt)
    pos = 5
    for _ in range(12):                # 5 + 12 > prompt width by far
        cache, logits = lm.decode(cache, jnp.asarray([nxt]),
                                  jnp.asarray([pos]))
        pos += 1
        nxt = int(jnp.argmax(logits[0]))
        ids.append(nxt)

    ref = list(prompt)
    for _ in range(13):
        logits = lm.full_logits(jnp.asarray([ref]))
        ref.append(int(jnp.argmax(logits[0, -1])))
    assert ids == ref

def _numpy_nucleus_oracle(logits, temp, top_k, top_p):
    """Sorted sequential-warper reference (HF order): top-k first, then
    nucleus over the renormalized distribution, keep-the-crossing-token."""
    z = logits.astype(np.float64) / max(temp, 1e-6)
    p = np.exp(z - z.max())
    p /= p.sum()
    order = np.argsort(-p, kind="stable")
    keep = np.zeros(len(p), bool)
    kk = top_k if top_k > 0 else len(p)
    kept = order[:kk]
    if top_p < 1.0:
        pk = p[kept] / p[kept].sum()
        csum_before = np.cumsum(pk) - pk
        kept = kept[csum_before < max(top_p, 0.0)]
        if len(kept) == 0:
            kept = order[:1]
    keep[kept] = True
    return keep


@pytest.mark.parametrize("top_k,top_p,temp", [
    (0, 0.9, 1.0), (0, 0.5, 0.7), (0, 0.99, 1.3), (500, 0.95, 1.0),
    (500, 1.0, 1.0), (0, 0.1, 1.0), (40, 0.9, 0.8),
    # low temperature stretches the scaled-logit range the bisection
    # operates over; resolution (range/2^30) must stay below the kept/
    # dropped gap
    (0, 0.9, 0.3), (0, 0.9, 0.1),
])
def test_exact_topp_keep_set_matches_numpy_oracle_gpt2_vocab(
        top_k, top_p, temp):
    """VERDICT r4 item 7: the full-vocab bisection filter must reproduce
    the sorted nucleus SET exactly at vocab 50257 — including top_k above
    FILTER_CAP and nucleus-with-top-k-off, the two cases the capped
    sampler truncates."""
    from fedml_tpu.serving.kv_cache_lm import _exact_filter_keep

    v = 50257
    rng = np.random.default_rng(42)
    logits = rng.standard_normal((2, v)).astype(np.float32) * 3.0
    keep, _, _ = _exact_filter_keep(
        jnp.asarray(logits), jnp.asarray([temp, temp]),
        jnp.asarray([top_k, top_k]), jnp.asarray([top_p, top_p]))
    keep = np.asarray(keep)
    for b in range(2):
        oracle = _numpy_nucleus_oracle(logits[b], temp, top_k, top_p)
        assert (keep[b] == oracle).all(), (
            f"row {b}: keep {keep[b].sum()} vs oracle {oracle.sum()}, "
            f"symdiff {(keep[b] ^ oracle).sum()}")


def test_exact_sampler_matches_capped_sampler_small_vocab():
    """Where BOTH samplers are exact (vocab <= FILTER_CAP) they must emit
    the IDENTICAL token for the same key: the capped path's slot-space
    gumbel-argmax gathers the same per-vocab-position noise the exact
    path uses directly."""
    from fedml_tpu.serving.kv_cache_lm import (
        _exact_filter_sample,
        _filter_sample,
    )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((4, 96)).astype(np.float32))
    temps = jnp.asarray([1.0, 0.7, 0.0, 1.3])
    top_k = jnp.asarray([0, 10, 5, 0])
    top_p = jnp.asarray([0.9, 1.0, 0.5, 1.0])
    for seed in range(8):
        key = jax.random.PRNGKey(seed)
        a = np.asarray(_filter_sample(logits, temps, top_k, top_p, key))
        b = np.asarray(_exact_filter_sample(logits, temps, top_k, top_p,
                                            key))
        np.testing.assert_array_equal(a, b)


def test_exact_sampler_samples_inside_oracle_set_gpt2_vocab():
    from fedml_tpu.serving.kv_cache_lm import _exact_filter_sample

    v = 50257
    rng = np.random.default_rng(7)
    logits = rng.standard_normal((1, v)).astype(np.float32) * 2.0
    oracle = _numpy_nucleus_oracle(logits[0], 1.0, 0, 0.9)
    for seed in range(16):
        tok = int(_exact_filter_sample(
            jnp.asarray(logits), jnp.asarray([1.0]), jnp.asarray([0]),
            jnp.asarray([0.9]), jax.random.PRNGKey(seed))[0])
        assert oracle[tok]


def test_engine_routes_big_vocab_nucleus_through_exact_filters():
    """A >FILTER_CAP-vocab engine with a nucleus request must dispatch the
    exact sampler (and still produce valid tokens)."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    lm = KVCacheLM.create(jax.random.PRNGKey(0), vocab=200, dim=32,
                          layers=1, heads=2, max_len=64)
    calls = []
    orig = lm.decode_multi

    def spy(*a, **kw):
        calls.append(kw.get("exact_filters", False))
        return orig(*a, **kw)

    lm.decode_multi = spy
    eng = KVCacheLLMEngine(lm, max_batch=2, tokens_per_dispatch=4)
    try:
        out = eng.generate([3, 5], max_new=6, temperature=1.0, top_p=0.8)
        assert all(0 <= int(t) < 200 for t in out)
        assert any(calls), "no dispatch used exact_filters"
    finally:
        eng.stop()


def test_admission_prefill_edge_prompts_match_uncached():
    """Admission-prefill edge cases: prompt shorter than the dispatch
    chunk (skip path), prompt crossing a bucket boundary, and a prompt
    near max_len — greedy output must equal the non-cached forward."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    lm = KVCacheLM.create(jax.random.PRNGKey(2), vocab=60, dim=32,
                          layers=2, heads=4, max_len=72)
    rng = np.random.RandomState(1)
    prompts = [list(rng.randint(0, 60, n))
               for n in (3,      # < tokens_per_dispatch: skip prefill
                         33,     # crosses the 32-bucket boundary
                         65)]    # > biggest fitting bucket (64):
                                 # exercises the tp=max_len fallback
    eng = KVCacheLLMEngine(lm, max_batch=2, tokens_per_dispatch=8)
    try:
        for ids in prompts:
            out = eng.generate(ids, max_new=5, temperature=0.0,
                               timeout=300)
            ref = list(ids)
            for _ in range(len(out) - len(ids)):
                logits = lm.full_logits(jnp.asarray([ref]))
                ref.append(int(jnp.argmax(logits[0, -1])))
            np.testing.assert_array_equal(np.asarray(out), ref,
                                          err_msg=f"prompt len {len(ids)}")
    finally:
        eng.stop()


def test_openai_server_survives_concurrent_burst():
    """The DeepBacklogHTTPServer fix: a 50-client simultaneous burst must
    not get kernel-reset (stdlib default backlog is 5)."""
    import json as _json
    import threading
    import urllib.request

    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import (
        KVCacheLLMEngine,
        LLMEnginePredictor,
    )
    from fedml_tpu.serving.openai_api import OpenAIServer

    lm = KVCacheLM.create(jax.random.PRNGKey(0), vocab=90, dim=16,
                          layers=1, heads=2, max_len=48)
    eng = KVCacheLLMEngine(lm, max_batch=8, tokens_per_dispatch=4)
    srv = OpenAIServer(LLMEnginePredictor(eng), model_name="burst",
                       port=0)
    srv.run(block=False)
    ok, errs = [], []
    lock = threading.Lock()

    def client():
        body = _json.dumps({"model": "burst", "max_tokens": 3,
                            "temperature": 0,
                            "messages": [{"role": "user",
                                          "content": "x"}]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/chat/completions",
            data=body, headers={"Content-Type": "application/json"})
        try:
            r = _json.loads(urllib.request.urlopen(req, timeout=300)
                            .read())
            with lock:
                ok.append(r["choices"][0]["message"]["content"])
        except Exception as e:  # noqa: BLE001
            with lock:
                errs.append(repr(e))

    try:
        threads = [threading.Thread(target=client) for _ in range(50)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:5]
        assert len(ok) == 50
    finally:
        srv.stop()
        eng.stop()


def test_admission_turbo_short_first_dispatch():
    """After admitting an admission-prefilled prompt, the FIRST dispatch
    must be the short ADMIT_TURBO_K one (fast first token), then resume
    full-length dispatches; short prompts (chunk-prefill path) must NOT
    trigger turbo — it would delay their first token by a dispatch."""
    from fedml_tpu.serving.kv_cache_lm import KVCacheLM
    from fedml_tpu.serving.llm_engine import KVCacheLLMEngine

    lm = KVCacheLM.create(jax.random.PRNGKey(0), vocab=60, dim=16,
                          layers=1, heads=2, max_len=96)
    ks = []
    orig = lm.decode_multi

    def spy(cache, pb, pn, pos0, temps, tk, tp, rng, k, **kw):
        ks.append(k)
        return orig(cache, pb, pn, pos0, temps, tk, tp, rng, k, **kw)

    lm.decode_multi = spy
    eng = KVCacheLLMEngine(lm, max_batch=2, tokens_per_dispatch=8)
    try:
        long_prompt = list(np.random.RandomState(0).randint(0, 60, 40))
        out = eng.generate(long_prompt, max_new=12, temperature=0.0,
                           timeout=300)
        assert len(out) == 52
        assert ks[0] == eng.ADMIT_TURBO_K, ks   # turbo first dispatch
        assert eng.tokens_per_dispatch in ks[1:], ks  # then full length

        ks.clear()
        short = [1, 2, 3]                       # below-chunk: no prefill
        out = eng.generate(short, max_new=4, temperature=0.0, timeout=300)
        assert len(out) == 7
        assert ks and ks[0] == eng.tokens_per_dispatch, ks  # NO turbo
    finally:
        eng.stop()
