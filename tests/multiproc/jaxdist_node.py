"""One process of a 2-process jax.distributed mesh smoke (CPU backend).

Each process contributes its local CPU device(s) to a global mesh; the test
checks a cross-process psum sees every process's contribution — the
multi-host bring-up path `fedml_tpu.init` uses on real TPU pods.
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--pid", type=int, required=True)
    p.add_argument("--nprocs", type=int, default=2)
    p.add_argument("--coord", default="127.0.0.1:21977")
    cli = p.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=cli.coord,
                               num_processes=cli.nprocs,
                               process_id=cli.pid)

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    assert len(devs) >= cli.nprocs, devs
    mesh = Mesh(devs[:cli.nprocs], ("hosts",))
    sharding = NamedSharding(mesh, P("hosts"))

    # each process owns one shard carrying (pid+1); global sum must see both
    local = jnp.full((1,), float(cli.pid + 1))
    garr = jax.make_array_from_single_device_arrays(
        (cli.nprocs,), sharding,
        [jax.device_put(local, jax.local_devices()[0])])

    @jax.jit
    def total(x):
        return jnp.sum(x)

    out = total(garr)
    expect = sum(range(1, cli.nprocs + 1))
    assert float(out) == float(expect), (float(out), expect)
    print(f"JAXDIST_OK pid={cli.pid} sum={float(out)}", flush=True)


if __name__ == "__main__":
    main()
