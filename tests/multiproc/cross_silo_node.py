"""One OS process of a real cross-silo run over gRPC (server or client).

Mirrors the reference's multi-process smoke
(`/root/reference/python/tests/cross-silo/run_cross_silo.sh`: server + 2
clients as separate local processes).  tests/test_multiprocess.py spawns
``--rank 0`` (server) and ``--rank 1/2`` (clients); rank 0 prints
``FINAL_METRICS {...}`` on success.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rank", type=int, required=True)
    p.add_argument("--port", type=int, default=21890)
    p.add_argument("--rounds", type=int, default=2)
    cli = p.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")

    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    args = fedml_tpu.init(fedml_tpu.Config(
        training_type="cross_silo",
        backend="GRPC",
        dataset="mnist", model="lr",
        data_scale=0.1,
        client_num_in_total=2, client_num_per_round=2,
        comm_round=cli.rounds, epochs=1, batch_size=16,
        learning_rate=0.05, frequency_of_the_test=1,
        grpc_base_port=cli.port,
        run_id="multiproc_smoke",
        random_seed=0,
        enable_tracking=False,
        compute_dtype="float32",
    ))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])

    if cli.rank == 0:
        server = init_server(args, dataset, bundle, backend="GRPC")
        server.run()
        m = server.aggregator.metrics_history[-1]
        print("FINAL_METRICS " + json.dumps(
            {k: float(v) for k, v in m.items()}), flush=True)
    else:
        client = init_client(args, dataset, bundle, cli.rank,
                             backend="GRPC")
        client.run()
        print(f"CLIENT_DONE {cli.rank}", flush=True)


if __name__ == "__main__":
    main()
