"""fedml lint: rule engine, rules, suppressions, baseline ratchet, CLI."""

from __future__ import annotations

import json
import textwrap

import pytest

from fedml_tpu.analysis import run_cli, run_lint
from fedml_tpu.analysis.baseline import load_baseline, write_baseline
from fedml_tpu.analysis.engine import default_root
from fedml_tpu.analysis.findings import fingerprints


def _write(tmp_path, relpath: str, source: str):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def _lint(tmp_path, rules=None):
    return run_lint(root=tmp_path, rule_ids=rules).findings


def _ids(findings):
    return [f.rule_id for f in findings]


# -- JAX001: jit in loop / per-round function --------------------------------

def test_jax001_fires_on_jit_in_loop(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def train(fn, xs):
            for x in xs:
                f = jax.jit(fn)
                f(x)
    """)
    assert _ids(_lint(tmp_path)) == ["JAX001"]


def test_jax001_fires_in_round_function_not_builder(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def handle_round(fn):
            return jax.jit(fn)

        def build_round_step(fn):
            return jax.jit(fn)
    """)
    found = _lint(tmp_path)
    assert _ids(found) == ["JAX001"]
    assert found[0].line == 4


def test_jax001_silent_when_hoisted(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def train(fn, xs):
            f = jax.jit(fn)
            for x in xs:
                f(x)
    """)
    assert _lint(tmp_path) == []


def test_jax001_noqa_suppresses(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def train(fn, xs):
            for x in xs:
                f = jax.jit(fn)  # fedml: noqa[JAX001] — compile cache hit
                f(x)
    """)
    res = run_lint(root=tmp_path)
    assert res.findings == [] and res.suppressed == 1


# -- JAX002: PRNG key reuse ---------------------------------------------------

def test_jax002_fires_on_double_consume(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def f():
            k = jax.random.PRNGKey(0)
            a = jax.random.normal(k, (2,))
            b = jax.random.uniform(k, (2,))
            return a + b
    """)
    assert _ids(_lint(tmp_path)) == ["JAX002"]


def test_jax002_silent_with_split(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def f():
            k = jax.random.PRNGKey(0)
            k, sub = jax.random.split(k)
            a = jax.random.normal(sub, (2,))
            k, sub = jax.random.split(k)
            b = jax.random.uniform(sub, (2,))
            return a + b
    """)
    assert _lint(tmp_path) == []


def test_jax002_fires_on_loop_reuse(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def g(xs):
            k = jax.random.PRNGKey(0)
            out = []
            for x in xs:
                out.append(jax.random.normal(k, (2,)))
            return out
    """)
    assert "JAX002" in _ids(_lint(tmp_path))


def test_jax002_silent_when_resplit_in_loop(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def g(xs):
            k = jax.random.PRNGKey(0)
            out = []
            for x in xs:
                k, sub = jax.random.split(k)
                out.append(jax.random.normal(sub, (2,)))
            return out
    """)
    assert _lint(tmp_path) == []


def test_jax002_exclusive_branches_dont_compound(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def h(flag):
            k = jax.random.PRNGKey(0)
            if flag:
                return jax.random.normal(k, (2,))
            else:
                return jax.random.uniform(k, (2,))
    """)
    assert _lint(tmp_path) == []


# -- JAX003: host sync in hot-path loop --------------------------------------

def test_jax003_fires_only_on_hot_paths(tmp_path):
    src = """\
        def train(batches, step):
            losses = []
            for b in batches:
                losses.append(float(step(b)))
            return losses
    """
    _write(tmp_path, "fedml_tpu/ml/trainer/hot.py", src)
    _write(tmp_path, "fedml_tpu/data/cold.py", src)
    found = _lint(tmp_path)
    assert _ids(found) == ["JAX003"]
    assert found[0].path == "fedml_tpu/ml/trainer/hot.py"


def test_jax003_silent_when_hoisted_and_noqa(tmp_path):
    _write(tmp_path, "fedml_tpu/ml/trainer/hot.py", """\
        import jax

        def train(batches, step):
            losses = []
            for b in batches:
                losses.append(step(b))
            host = jax.device_get(losses)
            total = float(sum(host))  # fedml: noqa[JAX003] — host numpy
            return total
    """)
    assert _lint(tmp_path) == []


# -- JAX004: static/donate hazards --------------------------------------------

def test_jax004_fires_on_nonhashable_static_arg(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def f(fn, x):
            g = jax.jit(fn, static_argnums=(1,))
            return g(x, [1, 2])
    """)
    assert _ids(_lint(tmp_path)) == ["JAX004"]


def test_jax004_fires_on_donated_buffer_reuse(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def f(fn, x):
            g = jax.jit(fn, donate_argnums=(0,))
            y = g(x)
            return x + y
    """)
    assert _ids(_lint(tmp_path)) == ["JAX004"]


def test_jax004_silent_on_rebind_and_hashable_static(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import jax

        def f(fn, x):
            g = jax.jit(fn, static_argnums=(1,), donate_argnums=(0,))
            x = g(x, 4)
            return x
    """)
    assert _lint(tmp_path) == []


# -- PROTO001: message-key drift ----------------------------------------------

PROTO_DEFINE = """\
    class MyMessage:
        MSG_TYPE_S2C_GO = "S2C_GO"
        MSG_ARG_KEY_USED = "used"
        MSG_ARG_KEY_DROPPED = "dropped"
"""

PROTO_USER = """\
    from .message_define import MyMessage

    def send(Message, receiver):
        msg = Message(MyMessage.MSG_TYPE_S2C_GO, 0, receiver)
        msg.add_params(MyMessage.MSG_ARG_KEY_USED, 1)
        msg.add_params(MyMessage.MSG_ARG_KEY_DROPPED, 2)
        return msg

    def receive(comm, msg, handler):
        comm.register_message_receive_handler(
            MyMessage.MSG_TYPE_S2C_GO, handler)
        return msg.get(MyMessage.MSG_ARG_KEY_USED)
"""


def test_proto001_flags_write_only_key(tmp_path):
    _write(tmp_path, "fedml_tpu/proto/message_define.py", PROTO_DEFINE)
    _write(tmp_path, "fedml_tpu/proto/user.py", PROTO_USER)
    found = _lint(tmp_path)
    assert _ids(found) == ["PROTO001"]
    assert "MSG_ARG_KEY_DROPPED" in found[0].message
    assert found[0].path == "fedml_tpu/proto/message_define.py"


def test_proto001_flags_dead_and_read_only_constants(tmp_path):
    _write(tmp_path, "fedml_tpu/proto/message_define.py", """\
        class MyMessage:
            MSG_ARG_KEY_DEAD = "dead"
            MSG_ARG_KEY_EXPECTED = "expected"
    """)
    _write(tmp_path, "fedml_tpu/proto/user.py", """\
        from .message_define import MyMessage

        def receive(msg):
            return msg.get(MyMessage.MSG_ARG_KEY_EXPECTED)
    """)
    msgs = " | ".join(f.message for f in _lint(tmp_path))
    assert "never used" in msgs and "no sender ever emits" in msgs


def test_proto001_noqa_on_define_line(tmp_path):
    _write(tmp_path, "fedml_tpu/proto/message_define.py", """\
        class MyMessage:
            MSG_ARG_KEY_RESERVED = "rsv"  # fedml: noqa[PROTO001] — parity
    """)
    res = run_lint(root=tmp_path)
    assert res.findings == [] and res.suppressed == 1


# -- CONC001: unlocked shared mutation ---------------------------------------

CONC_SRC = """\
    import threading

    class Worker:
        def __init__(self):
            self.items = {}
            self._lock = threading.Lock()

        def start(self):
            threading.Thread(target=self.run, daemon=True).start()

        def run(self):
            self.items["a"] = 1

        def locked_update(self, k):
            with self._lock:
                self.items[k] = 2
"""


def test_conc001_fires_in_scheduler_not_elsewhere(tmp_path):
    _write(tmp_path, "fedml_tpu/scheduler/w.py", CONC_SRC)
    _write(tmp_path, "fedml_tpu/data/w.py", CONC_SRC)
    found = _lint(tmp_path)
    assert _ids(found) == ["CONC001"]
    assert found[0].path == "fedml_tpu/scheduler/w.py"
    assert found[0].line == 12  # the unlocked store, not the locked one


def test_conc001_silent_without_threads_or_with_lock(tmp_path):
    _write(tmp_path, "fedml_tpu/scheduler/w.py", """\
        import threading

        class Worker:
            def __init__(self):
                self.items = {}
                self._lock = threading.Lock()

            def start(self):
                threading.Thread(target=self.run, daemon=True).start()

            def run(self):
                with self._lock:
                    self.items["a"] = 1
    """)
    assert _lint(tmp_path) == []


# -- engine: output, baseline ratchet, exit codes, --paths --------------------

BAD_JAX = """\
    import jax

    def f():
        k = jax.random.PRNGKey(0)
        a = jax.random.normal(k, (2,))
        b = jax.random.uniform(k, (2,))
        return a + b
"""


def test_json_output_schema(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", BAD_JAX)
    lines = []
    code = run_cli(root=str(tmp_path), fmt="json", echo=lines.append)
    assert code == 1
    report = json.loads("\n".join(lines))
    assert report["version"] == 1 and report["tool"] == "fedml-lint"
    assert report["new_count"] == 1 and report["baselined_count"] == 0
    assert {"files_scanned", "duration_s", "suppressed_count",
            "findings"} <= set(report)
    (f,) = report["findings"]
    assert {"rule", "severity", "path", "line", "col", "message",
            "fingerprint", "baselined"} <= set(f)
    assert f["rule"] == "JAX002" and f["baselined"] is False


def test_baseline_ratchet_add_and_fail_on_new(tmp_path):
    _write(tmp_path, "fedml_tpu/old.py", BAD_JAX)
    lines = []
    assert run_cli(root=str(tmp_path), update_baseline=True,
                   echo=lines.append) == 0
    assert (tmp_path / ".fedml-lint-baseline.json").is_file()
    # baselined finding no longer fails the run
    assert run_cli(root=str(tmp_path), echo=lines.append) == 0
    # a NEW finding fails with exit 1 and only the new one is reported
    _write(tmp_path, "fedml_tpu/new.py", BAD_JAX)
    out = []
    assert run_cli(root=str(tmp_path), echo=out.append) == 1
    rendered = "\n".join(out)
    assert "fedml_tpu/new.py" in rendered
    assert "fedml_tpu/old.py" not in rendered


def test_fingerprints_stable_under_line_drift(tmp_path):
    f = _write(tmp_path, "fedml_tpu/mod.py", BAD_JAX)
    before = dict((fp, fi.rule_id)
                  for fi, fp in fingerprints(_lint(tmp_path)))
    f.write_text("# a new header comment\n\n" + f.read_text())
    after = dict((fp, fi.rule_id)
                 for fi, fp in fingerprints(_lint(tmp_path)))
    assert before == after


def test_exit_code_2_on_internal_error(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", "x = 1\n")
    bad = tmp_path / "broken-baseline.json"
    bad.write_text("{\"version\": 999}")
    assert run_cli(root=str(tmp_path), baseline=str(bad),
                   echo=lambda *_: None) == 2


def test_paths_filter_restricts_scan(tmp_path):
    _write(tmp_path, "fedml_tpu/a.py", BAD_JAX)
    _write(tmp_path, "fedml_tpu/b.py", BAD_JAX)
    res = run_lint(root=tmp_path, paths=["fedml_tpu/a.py"])
    assert res.files_scanned == 1
    assert [f.path for f in res.findings] == ["fedml_tpu/a.py"]


def test_nonexistent_path_is_an_error_not_a_clean_pass(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", BAD_JAX)
    assert run_cli(root=str(tmp_path), paths=["fedml_tpu/tariner"],
                   echo=lambda *_: None) == 2


def test_unknown_rule_id_is_an_error(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", "x = 1\n")
    assert run_cli(root=str(tmp_path), rule_ids=["NOPE999"],
                   echo=lambda *_: None) == 2


def test_whitespace_padded_rule_ids_still_select(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", BAD_JAX)
    res = run_lint(root=tmp_path, rule_ids=[" jax002 "])
    assert _ids(res.findings) == ["JAX002"]


def test_update_baseline_refused_on_partial_scan(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", BAD_JAX)
    assert run_cli(root=str(tmp_path), paths=["fedml_tpu/mod.py"],
                   update_baseline=True, echo=lambda *_: None) == 2
    assert not (tmp_path / ".fedml-lint-baseline.json").exists()


def test_unparseable_file_is_a_finding_not_a_crash(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", "def broken(:\n")
    assert _ids(_lint(tmp_path)) == ["LINT001"]


def test_write_and_load_baseline_roundtrip(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", BAD_JAX)
    findings = _lint(tmp_path)
    path = tmp_path / "bl.json"
    assert write_baseline(path, findings) == 1
    loaded = load_baseline(path)
    (fp,) = loaded
    assert loaded[fp]["rule"] == "JAX002"


# -- the repo itself is lint-clean against the committed baseline -------------

def test_repo_runs_clean_under_budget():
    root = default_root()
    assert (root / ".fedml-lint-baseline.json").is_file(), \
        "committed baseline missing"
    code = run_cli(root=str(root), echo=lambda *_: None)
    assert code == 0, "new unbaselined lint findings in the repo"
    res = run_lint(root=root)
    assert res.duration_s < 30.0
    assert res.files_scanned > 150
