"""The bench accuracy guard must be real evidence: on the HARD synthetic
image data (class mixing + jitter + label noise, the north-star bench
construction) a healthy run clears its target while a deliberately
sabotaged aggregator does not (VERDICT r3 item 4)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(monkeypatch=None, sabotage=False):
    if sabotage:
        import fedml_tpu.simulation.parrot.parrot_api as pa

        orig = pa.agg_stacked

        def broken(new_vars, weights):
            # sabotage: the aggregate comes out 20x too small (the
            # "aggregation output numerically wrong" failure class — e.g.
            # a mis-scaled weight normalization); learning stalls and the
            # run must miss the guard threshold
            out = orig(new_vars, weights)
            import jax

            return jax.tree_util.tree_map(
                lambda a: a * 0.05, out)

        monkeypatch.setattr(pa, "agg_stacked", broken)
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="mnist", model="lr", backend="parrot",
        partition_method="hetero", partition_alpha=0.5,
        synthetic_hard=True,
        client_num_in_total=12, client_num_per_round=6, comm_round=60,
        epochs=1, batch_size=16, learning_rate=0.1, data_scale=0.2,
        frequency_of_the_test=100, enable_tracking=False,
        compute_dtype="float32", hetero_buckets=1))
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    api = FedMLRunner(args, device, dataset, bundle).runner
    api.run_rounds_fused(60)
    tb = api._make_test_batches()
    out = api.eval_step(api.global_vars, tb)
    return float(out["correct"]) / max(float(out["n"]), 1.0)


@pytest.mark.slow
def test_guard_discriminates_broken_aggregation(monkeypatch):
    healthy = _run()
    broken = _run(monkeypatch, sabotage=True)
    # measured (CPU, deterministic, hard_v2 data): healthy 0.295 vs
    # sabotaged 0.13 — a guard threshold between them fails the sabotage
    assert healthy > 0.22, healthy
    assert broken < healthy - 0.10, (healthy, broken)
