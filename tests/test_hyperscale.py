"""Hyper-scale Parrot tests: streamed-cohort parity with the device-resident
path, double-buffer bitwise correctness, deterministic 100k-client cohort
sampling under crash-resume, sharded per-client state round-trips, and the
10k-client CPU-proxy streaming smoke (clients/sec + flight coverage)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.core.mlops import flight_recorder as fr
from fedml_tpu.data.population import (
    ClientPopulation,
    load_population,
    philox_generator,
    zipf_sizes,
)
from fedml_tpu.ml.engine.mesh import build_mesh
from fedml_tpu.simulation.parrot.hyperscale import (
    HierarchicalCohortSampler,
    StreamingParrotAPI,
    make_availability,
)
from fedml_tpu.simulation.parrot.parrot_api import (
    ParrotAPI,
    bucket_plan,
    stacked_client_sharding,
)


def _setup(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return args, device, dataset, bundle


def _params_np(api):
    return [np.asarray(x) for x in
            jax.tree_util.tree_leaves(api.global_vars["params"])]


# -- parity with the non-streamed path ----------------------------------------

def test_streamed_matches_parrot_trajectory(args_factory):
    """Acceptance: the streamed path's trajectory matches ParrotAPI on a
    small parity config — same sampling draws, same rng stream, same
    round arithmetic; only the data plane differs (host-assembled grids
    vs device-resident gather)."""
    kw = dict(client_num_in_total=8, client_num_per_round=4, comm_round=6,
              data_scale=0.3, random_seed=3)
    p = ParrotAPI(*_setup(args_factory(backend="parrot", **kw)))
    mp = p.train()
    s = StreamingParrotAPI(
        *_setup(args_factory(backend="hyperscale", **kw)))
    ms = s.train()
    assert ms["test_acc"] == pytest.approx(mp["test_acc"], abs=1e-6)
    assert ms["test_loss"] == pytest.approx(mp["test_loss"], rel=1e-4)
    for a, b in zip(_params_np(p), _params_np(s)):
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-6)


def test_double_buffer_bitwise_matches_sequential(args_factory):
    """The double buffer reorders WHEN grids upload, never WHAT computes:
    prefetch=2 and the sequential stage-then-compute baseline must be
    bit-identical (same jit, same inputs, same rng stream)."""
    kw = dict(client_num_in_total=8, client_num_per_round=4, comm_round=5,
              data_scale=0.2, random_seed=11, hetero_buckets=2)
    seq = StreamingParrotAPI(*_setup(
        args_factory(backend="hyperscale", stream_prefetch=1, **kw)))
    seq.train()
    dbl = StreamingParrotAPI(*_setup(
        args_factory(backend="hyperscale", stream_prefetch=2, **kw)))
    dbl.train()
    for a, b in zip(_params_np(seq), _params_np(dbl)):
        np.testing.assert_array_equal(a, b)


def test_scaffold_streamed_matches_parrot(args_factory):
    """Per-client state (SCAFFOLD variates) gathered/scattered from the
    stacked table must reproduce ParrotAPI's replicated-table result."""
    kw = dict(client_num_in_total=8, client_num_per_round=4, comm_round=5,
              data_scale=0.3, random_seed=5, federated_optimizer="SCAFFOLD")
    p = ParrotAPI(*_setup(args_factory(backend="parrot", **kw)))
    mp = p.train()
    s = StreamingParrotAPI(
        *_setup(args_factory(backend="hyperscale", **kw)))
    ms = s.train()
    assert ms["test_acc"] == pytest.approx(mp["test_acc"], abs=1e-6)
    assert ms["test_loss"] == pytest.approx(mp["test_loss"], rel=1e-4)
    for a, b in zip(
            jax.tree_util.tree_leaves(p.server_state["c_locals"]),
            jax.tree_util.tree_leaves(s.server_state["c_locals"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b)[:len(a)],
                                   rtol=0, atol=1e-6)


# -- hierarchical cohort sampling at 100k -------------------------------------

def test_cohort_sampler_deterministic_at_100k():
    """Crash-resume re-solicits the same cohort: a FRESH sampler (new
    process, no sequential RNG state) must reproduce any round's draw at
    a 100k-client population, without per-client index matrices."""
    sizes = zipf_sizes(100_000, seed=7)
    mk = lambda: HierarchicalCohortSampler(
        sizes, k=1024, bs=32, n_buckets=8, cap_ratio=0.8,
        run_id="run-a", seed=7)
    a, b = mk(), mk()
    for r in (0, 3, 41, 999):
        ca, cb = a.cohort(r), b.cohort(r)
        ids_a = np.concatenate([s["ids"] for s in ca])
        ids_b = np.concatenate([s["ids"] for s in cb])
        np.testing.assert_array_equal(ids_a, ids_b)
        np.testing.assert_array_equal(
            np.concatenate([s["starts"] for s in ca]),
            np.concatenate([s["starts"] for s in cb]))
        # quota: exactly k clients, no duplicates, all in range
        assert len(ids_a) == 1024
        assert len(np.unique(ids_a)) == 1024
        assert ids_a.min() >= 0 and ids_a.max() < 100_000
    # distinct rounds and distinct run_ids draw distinct cohorts
    r0 = np.concatenate([s["ids"] for s in a.cohort(0)])
    r1 = np.concatenate([s["ids"] for s in a.cohort(1)])
    assert not np.array_equal(np.sort(r0), np.sort(r1))
    other = HierarchicalCohortSampler(
        sizes, k=1024, bs=32, n_buckets=8, cap_ratio=0.8,
        run_id="run-b", seed=7)
    ro = np.concatenate([s["ids"] for s in other.cohort(0)])
    assert not np.array_equal(np.sort(r0), np.sort(ro))


def test_cohort_sampler_stratifies_by_size():
    """Each stratum's draw stays inside its own size band (the bucket
    members), so per-round compute tracks the size distribution."""
    sizes = zipf_sizes(50_000, seed=1)
    s = HierarchicalCohortSampler(sizes, k=256, bs=32, n_buckets=4,
                                  cap_ratio=0.8, run_id="x", seed=1)
    cohort = s.cohort(5)
    assert len(cohort) == len(s.strata) > 1
    for sl, stratum in zip(cohort, s.strata):
        assert np.isin(sl["ids"], stratum["members"]).all()


def test_availability_trace_respected():
    """With a diurnal trace, sampled clients are drawn from the round's
    available set (whenever the quota is satisfiable)."""
    n = 10_000
    sizes = zipf_sizes(n, seed=2)
    avail = make_availability("diurnal:0.5:4", n, seed=2)
    s = HierarchicalCohortSampler(sizes, k=128, bs=32, n_buckets=4,
                                  cap_ratio=0.8, run_id="t", seed=2,
                                  availability=avail)
    for r in range(6):
        ids = np.concatenate([sl["ids"] for sl in s.cohort(r)])
        assert avail(r, ids).all()
    # and the trace actually varies who is available across rounds
    all_ids = np.arange(n)
    m0, m2 = avail(0, all_ids), avail(2, all_ids)
    assert 0.3 < m0.mean() < 0.7 and not np.array_equal(m0, m2)


def test_virtual_population_lazy_rows_deterministic():
    """Virtual populations compute per-client rows positionally: the same
    (seed, cid) gives the same rows in any process, any order."""
    x = np.arange(400, dtype=np.float32).reshape(100, 4)
    y = np.arange(100) % 10
    sizes = zipf_sizes(100_000, seed=3, min_size=4, max_size=64)
    pop = ClientPopulation.virtual(x, y, sizes, (x[:10], y[:10]),
                                   class_num=10, seed=3)
    assert pop.n_clients == 100_000 and pop.virtual
    r1 = pop.rows(99_999)
    r2 = pop.rows(99_999)
    np.testing.assert_array_equal(r1, r2)
    assert len(r1) == sizes[99_999]
    assert (r1 >= 0).all() and (r1 < 100).all()
    assert not np.array_equal(pop.rows(0)[:4], r1[:4])


# -- sharded per-client state -------------------------------------------------

def test_sharded_state_gather_scatter_roundtrip():
    """The [N_pad, ...] client-state table laid out over the 8-device
    mesh must survive a cohort gather → update → scatter round-trip,
    including a non-divisible N (padding rows stay untouched)."""
    mesh = build_mesh({"clients": 8})
    n, n_pad = 20, 24  # ceil(20/8)*8
    sharding = stacked_client_sharding(mesh)
    assert sharding is not None
    table = jax.device_put(jnp.zeros((n_pad, 5)), sharding)
    ids = jnp.asarray([3, 7, 11, 19], jnp.int32)

    @jax.jit
    def roundtrip(t, ids):
        got = t[ids]                      # cohort gather
        new = got + jnp.arange(1.0, 5.0)[:, None]
        return t.at[ids].set(new), got    # cohort scatter

    with mesh:
        t2, got = roundtrip(table, ids)
    np.testing.assert_array_equal(np.asarray(got), np.zeros((4, 5)))
    t2 = np.asarray(t2)
    for j, cid in enumerate(np.asarray(ids)):
        np.testing.assert_array_equal(t2[cid], np.full(5, float(j + 1)))
    untouched = np.setdiff1d(np.arange(n_pad), np.asarray(ids))
    np.testing.assert_array_equal(t2[untouched],
                                  np.zeros((len(untouched), 5)))


# -- 10k-client CPU-proxy streaming smoke -------------------------------------

def test_hyperscale_streaming_smoke_10k(args_factory, tmp_path):
    """≥10k-client CPU-proxy run: virtual population, hierarchical
    sampling, double-buffered staging.  Asserts the clients/sec headline
    is reported and the flight recorder decomposes ≥95% of round wall
    time into named phases."""
    args, device, dataset, bundle = _setup(args_factory(
        backend="hyperscale", client_num_in_total=10_000,
        client_num_per_round=64, comm_round=4, data_scale=0.1,
        hetero_buckets=4, hetero_bucket_cap=0.8, random_seed=0,
        frequency_of_the_test=4))
    # arm AFTER init (fedml_tpu.init re-configures the recorder from args)
    fr.enable(True, log_dir=str(tmp_path), run_id="hyperscale-smoke")
    try:
        api = StreamingParrotAPI(args, device, dataset, bundle,
                                 use_mesh=True)
        assert api.pop.virtual and api.pop.n_clients == 10_000
        m = api.train()
        records = fr.load_flight_log(str(tmp_path))
    finally:
        fr.reset()
    stats = api.stream_stats()
    assert stats["clients_per_sec"] > 0
    assert stats["clients_simulated"] == 4 * 64
    assert np.isfinite(m["test_loss"])
    s = fr.summarize([r for r in records
                      if r.get("kind") == "hyperscale_round"])
    assert s["records"] == 4
    assert s["coverage"] >= 0.95, s


def test_streaming_overlap_beats_sequential(args_factory):
    """Acceptance: the h2d phase share under double-buffered streaming is
    strictly below the sequential-staging share on the same config —
    the upload hides behind the previous round's compute."""
    kw = dict(backend="hyperscale", client_num_in_total=4096,
              client_num_per_round=64, comm_round=6, data_scale=0.1,
              hetero_buckets=4, hetero_bucket_cap=0.8, random_seed=0,
              frequency_of_the_test=100)
    seq = StreamingParrotAPI(*_setup(
        args_factory(stream_prefetch=1, **kw)), use_mesh=True)
    seq.train()
    dbl = StreamingParrotAPI(*_setup(
        args_factory(stream_prefetch=2, **kw)), use_mesh=True)
    dbl.train()
    s_seq, s_dbl = seq.stream_stats(), dbl.stream_stats()
    assert s_dbl["h2d_share"] < s_seq["h2d_share"]
    assert s_dbl["overlap_frac"] > 0.5


# -- crash-resume -------------------------------------------------------------

def test_hyperscale_checkpoint_resume(args_factory, tmp_path):
    """A run killed mid-way and resumed from its checkpoint lands on the
    same final parameters as the unbroken run (deterministic cohorts +
    replayed rng stream)."""
    kw = dict(backend="hyperscale", client_num_in_total=8,
              client_num_per_round=4, data_scale=0.2, random_seed=9,
              checkpoint_frequency=1)
    full = StreamingParrotAPI(*_setup(args_factory(comm_round=6, **kw)))
    full.train()

    ck = str(tmp_path / "ck")
    broken = StreamingParrotAPI(*_setup(
        args_factory(comm_round=3, checkpoint_dir=ck, **kw)))
    broken.train()
    resumed = StreamingParrotAPI(*_setup(
        args_factory(comm_round=6, checkpoint_dir=ck, **kw)))
    resumed.train()
    for a, b in zip(_params_np(full), _params_np(resumed)):
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-6)


# -- scaled population histogram ----------------------------------------------

def test_zipf_100k_bucket_cap_utilization():
    """The bucket-cap policy holds ≥99% slot utilization on the scaled
    heavy-tailed histogram (satellite acceptance for the population
    generator)."""
    sizes = zipf_sizes(100_000, seed=0, min_size=64)
    assert len(sizes) == 100_000
    # heavy-tailed: the top 1% of clients hold a disproportionate share
    srt = np.sort(sizes)
    assert srt[-1000:].sum() > 5 * (sizes.sum() / 100)
    # the committed hyperscale policy (benchmarks/hyperscale_client_sizes
    # .json): 32 strata at cap 0.6 over a k=1024 cohort
    plan = bucket_plan(sizes, k=1024, bs=32, n_buckets=32, cap_ratio=0.6)
    padded = sum(b["padded"] for b in plan)
    real = sum(b["real"] for b in plan)
    assert real / padded >= 0.99, (real, padded)


def test_load_population_modes(args_factory):
    """load_population: parity wrap below the threshold, virtual above,
    explicit sizes file when given."""
    args, _, dataset, _ = _setup(args_factory(backend="hyperscale"))
    pop = load_population(args, dataset)
    assert not pop.virtual and pop.n_clients == 4
    np.testing.assert_array_equal(pop.rows(1), args.client_row_map[1])

    big = fedml_tpu.init(args_factory(backend="hyperscale",
                                      client_num_in_total=5000))
    pop2 = load_population(big)
    assert pop2.virtual and pop2.n_clients == 5000
    assert pop2.sizes.min() >= 1
