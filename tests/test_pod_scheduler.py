"""Multi-tenant pod scheduler: gang allocation, weighted fair-share,
priority eviction with reservations, round-boundary preemption with
crash-resume continuity, per-job isolation, and the 8-slot mixed-workload
soak (docs/SCHEDULER.md)."""

import json
import os
import threading
import time

import numpy as np
import pytest

import fedml_tpu
from conftest import make_args
from fedml_tpu.core import mlops
from fedml_tpu.core.mlops import metrics
from fedml_tpu.scheduler.pod import (
    PREEMPTED_EXIT_CODE,
    CallableJobRunner,
    GangAllocator,
    JobQueue,
    JobSpec,
    JobState,
    PodScheduler,
)
from fedml_tpu.scheduler.resource_db import ComputeResourceDB


# --------------------------------------------------------------- job specs
def test_jobspec_yaml_and_resume_placeholder(tmp_path):
    y = tmp_path / "job.yaml"
    y.write_text(
        "job_name: team-a-sim\n"
        "tenant: team-a\n"
        "kind: parrot\n"
        "priority: 7\n"
        "slots: 4\n"
        "command: fedml run --cf cfg.yaml {resume}\n"
        "workdir: sub\n"
        "preemptible: false\n"
        "fedml_env:\n  FEDML_TPU_FLIGHT_RECORDER: '1'\n")
    spec = JobSpec.from_yaml(str(y))
    assert (spec.name, spec.tenant, spec.kind) == ("team-a-sim", "team-a",
                                                   "parrot")
    assert (spec.priority, spec.n_slots, spec.preemptible) == (7, 4, False)
    assert spec.workdir == str(tmp_path / "sub")
    assert spec.env == {"FEDML_TPU_FLIGHT_RECORDER": "1"}
    # {resume} expands per dispatch, single job line either way
    assert spec.render_command(False) == "fedml run --cf cfg.yaml"
    assert spec.render_command(True) == \
        "fedml run --cf cfg.yaml --resume-from latest"


def test_jobspec_validation():
    with pytest.raises(ValueError, match="kind"):
        JobSpec(name="x", kind="mapreduce").validate()
    with pytest.raises(ValueError, match="slots"):
        JobSpec(name="x", n_slots=0).validate()


# --------------------------------------------------------------- job queue
def test_queue_lifecycle_and_control_requests(tmp_path):
    q = JobQueue(str(tmp_path))
    jid = q.submit(JobSpec(name="j", tenant="t", n_slots=2, command="c"))
    assert q.get(jid)["state"] == JobState.QUEUED
    # preempt only applies to RUNNING jobs
    assert not q.request_preempt(jid)
    q.mark_dispatched(jid, "run1", [0, 1], "/tmp/logs")
    job = q.get(jid)
    assert job["state"] == JobState.RUNNING and job["slots"] == [0, 1]
    assert q.request_preempt(jid)
    q.mark_preempting(jid)
    assert q.get(jid)["state"] == JobState.PREEMPTING
    q.requeue_preempted(jid, PREEMPTED_EXIT_CODE)
    job = q.get(jid)
    assert job["state"] == JobState.QUEUED
    assert job["resume"] and job["preempt_count"] == 1
    assert job["run_id"] is None
    # the serving scaler's knob works only while QUEUED
    assert q.update_slots(jid, 5)
    assert q.get(jid)["n_slots"] == 5
    # cancel of a QUEUED job is immediate
    assert q.request_cancel(jid)
    assert q.get(jid)["state"] == JobState.CANCELLED
    # cancel of a RUNNING job only flags it for the scheduler
    j2 = q.submit(JobSpec(name="j2", command="c"))
    q.mark_dispatched(j2, "run2", [3], "/tmp/l2")
    assert q.request_cancel(j2)
    job2 = q.get(j2)
    assert job2["state"] == JobState.RUNNING and job2["cancel_requested"]
    q.close()


# --------------------------------------------------------------- allocator
def _job(jid, slots, priority=0, tenant="t", state="RUNNING",
         preemptible=True, submitted=0.0, dispatched=0.0):
    return {"job_id": jid, "n_slots": slots, "priority": priority,
            "tenant": tenant, "state": state, "preemptible": preemptible,
            "submitted_ts": submitted, "dispatched_ts": dispatched}


def test_allocator_gang_fit_with_backfill():
    alloc = GangAllocator()
    queued = [_job("a", 6, state="QUEUED", submitted=1),
              _job("b", 4, state="QUEUED", submitted=2),
              _job("c", 2, state="QUEUED", submitted=3)]
    plan = alloc.plan(queued, [], free_slots=8)
    # a fits (6), b does NOT run on a partial gang, c backfills behind it
    assert [j["job_id"] for j in plan.dispatch] == ["a", "c"]
    assert plan.blocked == ["b"]
    assert not plan.evict and not plan.reserve


def test_allocator_weighted_fair_share_order():
    alloc = GangAllocator(tenant_weights={"big": 3.0, "small": 1.0})
    running = [_job("r1", 6, tenant="big")]
    queued = [_job("qb", 1, tenant="big", state="QUEUED", submitted=1),
              _job("qs", 1, tenant="small", state="QUEUED", submitted=2)]
    # deficits: big 6/3=2, small 0/1=0 → small first despite later submit
    assert [j["job_id"] for j in alloc.order(queued, running)] == \
        ["qs", "qb"]
    # ...but weight=3 means big is served before an equally-held tenant
    running2 = [_job("r1", 3, tenant="big"), _job("r2", 3, tenant="small")]
    assert [j["job_id"] for j in alloc.order(queued, running2)] == \
        ["qb", "qs"]


def test_allocator_priority_eviction_pledges_reservation():
    alloc = GangAllocator()
    running = [_job("low", 6, priority=0, dispatched=1)]
    queued = [_job("hp", 8, priority=10, state="QUEUED")]
    plan = alloc.plan(queued, running, free_slots=2)
    assert [j["job_id"] for j in plan.evict] == ["low"]
    assert plan.reserve == {"hp": 8}
    assert plan.dispatch == [] and plan.blocked == ["hp"]
    # while the drain is in flight the reservation must (a) not re-evict
    # and (b) starve backfill that would steal the pledged slots
    queued2 = [_job("hp", 8, priority=10, state="QUEUED"),
               _job("bf", 2, priority=0, tenant="u", state="QUEUED")]
    running2 = [dict(running[0], state="PREEMPTING")]
    plan2 = alloc.plan(queued2, running2, free_slots=2,
                       reserved={"hp": 8})
    assert not plan2.evict and not plan2.dispatch
    # victims drained and released → only the pledge owner spends them
    plan3 = alloc.plan(queued2, [], free_slots=8, reserved={"hp": 8})
    assert [j["job_id"] for j in plan3.dispatch] == ["hp"]
    assert "bf" in plan3.blocked


def test_allocator_never_evicts_equal_or_higher_priority():
    alloc = GangAllocator()
    running = [_job("same", 6, priority=5),
               _job("pinned", 2, priority=1, preemptible=False)]
    queued = [_job("hp", 8, priority=5, state="QUEUED")]
    plan = alloc.plan(queued, running, free_slots=0)
    assert not plan.evict and plan.blocked == ["hp"]


# ------------------------------------------------- scheduler + runners
def _sim_workload(duration_s, envs=None):
    """Stand-in compute kernel: jax matmuls until done, draining
    cooperatively like a real round loop."""
    def fn(ctx):
        import jax.numpy as jnp

        if envs is not None:
            envs.append(dict(ctx.env))
        x = jnp.full((32, 32), 1.0 / 32.0)
        t0 = time.monotonic()
        while time.monotonic() - t0 < duration_s:
            if ctx.drain_requested():
                return PREEMPTED_EXIT_CODE
            x = (x @ x) * 32.0
            x.block_until_ready()
            time.sleep(0.01)
        return 0
    return fn


def _mk_sched(tmp_path, workloads, total_slots=8, **kw):
    queue = JobQueue(str(tmp_path / "pod"))
    resources = ComputeResourceDB(str(tmp_path / "res"),
                                  total_slots=total_slots)
    sched = PodScheduler(queue, resources,
                         runner=CallableJobRunner(workloads), **kw)
    return sched, queue, resources


def _step_until(sched, pred, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        sched.step()
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_scheduler_dispatch_env_contract_and_finish(tmp_path):
    envs = []
    sched, q, res = _mk_sched(tmp_path, {"quick": _sim_workload(0.1, envs)})
    jid = q.submit(JobSpec(name="quick", tenant="t1", n_slots=3,
                           command="noop"))
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.FINISHED)
    job = q.get(jid)
    assert job["returncode"] == 0 and len(job["slots"]) == 3
    assert res.report()["free"] == 8          # slots released on reap
    env = envs[0]
    # the pod dispatch contract every runner sees
    assert env["FEDML_TPU_JOB_ID"] == jid
    assert env["FEDML_TPU_JOB_TENANT"] == "t1"
    assert env["FEDML_TPU_AOT_CACHE_DIR"] == os.path.join(q.root,
                                                          "aot_cache")
    assert env["FEDML_TPU_LOG_DIR"].startswith(
        os.path.join(q.root, "logs", jid))
    assert len(env["FEDML_TPU_SLOTS"].split(",")) == 3
    q.close()


def test_scheduler_preempt_requeues_with_resume_and_redispatches(tmp_path):
    resumes = []

    def long_job(ctx):
        resumes.append(ctx.resume)
        if ctx.resume:        # second dispatch completes immediately
            return 0
        while not ctx.drain_requested():
            time.sleep(0.02)
        return PREEMPTED_EXIT_CODE

    sched, q, _ = _mk_sched(tmp_path, {"long": long_job})
    jid = q.submit(JobSpec(name="long", tenant="team-x", n_slots=2,
                           command="noop"))
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.RUNNING)
    assert q.request_preempt(jid)
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.FINISHED)
    job = q.get(jid)
    assert job["preempt_count"] == 1 and job["resume"]
    assert resumes == [False, True]
    expo = metrics.render_prometheus()
    assert 'fedml_jobs_preempted_total{tenant="team-x"} 1' in expo
    q.close()


def test_scheduler_cancels_running_job(tmp_path):
    sched, q, res = _mk_sched(tmp_path, {"hang": _sim_workload(120.0)})
    jid = q.submit(JobSpec(name="hang", n_slots=1, command="noop"))
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.RUNNING)
    assert q.request_cancel(jid)
    # Callable kill is cooperative (drain flag); the workload obeys it
    assert _step_until(
        sched, lambda: q.get(jid)["state"] == JobState.CANCELLED)
    assert res.report()["free"] == 8
    q.close()


def test_queue_metrics_exported_on_prometheus_surface(tmp_path):
    sched, q, _ = _mk_sched(tmp_path, {"m": _sim_workload(0.05)})
    q.submit(JobSpec(name="m", tenant="mt", n_slots=1, command="noop"))
    assert _step_until(
        sched,
        lambda: (q.stats().get(JobState.FINISHED, 0) == 1))
    expo = metrics.render_prometheus()
    for name in ("fedml_job_queue_wait_seconds",
                 "fedml_pod_slot_utilization",
                 "fedml_jobs_preempted_total"):
        assert name in expo, name
    assert 'fedml_job_queue_wait_seconds_count{tenant="mt"} 1' in expo
    q.close()


# ------------------------------------------------- per-job isolation
def test_mlops_job_scope_isolates_log_dirs(tmp_path):
    d1, d2 = str(tmp_path / "job1"), str(tmp_path / "job2")
    with mlops.job_scope(d1, run_id="job-1"):
        assert mlops.log_dir() == d1
        mlops.log({"loss": 1.0})
        mlops.event("train", True)
    with mlops.job_scope(d2, run_id="job-2"):
        mlops.log({"acc": 0.5})
    m1 = open(os.path.join(d1, "metrics.jsonl")).read()
    m2 = open(os.path.join(d2, "metrics.jsonl")).read()
    assert "loss" in m1 and "acc" not in m1
    assert "acc" in m2 and "loss" not in m2
    assert json.loads(m1.splitlines()[0])["run_id"] == "job-1"
    assert os.path.exists(os.path.join(d1, "events.jsonl"))
    assert not os.path.exists(os.path.join(d2, "events.jsonl"))
    # scope exit fully shut the lifecycle down
    assert not mlops._state["enabled"] and not mlops._state["files"]


def test_job_scope_isolates_run_ledgers(tmp_path, monkeypatch):
    """Per-job ledger isolation: two pod jobs scoped with
    `mlops.job_scope` (the in-process dispatch contract) write DISJOINT
    ledger.jsonl files — every record carries its own job's run_id, and
    a job's ledger never leaks events from the other tenant."""
    from fedml_tpu.core.mlops import ledger

    monkeypatch.setenv("FEDML_TPU_RUN_LEDGER", "1")
    d1, d2 = str(tmp_path / "jobA"), str(tmp_path / "jobB")
    with mlops.job_scope(d1, run_id="tenant-a"):
        assert ledger.enabled()
        ledger.event("server", "round_start", round_idx=0, expected=2)
        ledger.event("aggregator", "admitted", round_idx=0, client=1)
    with mlops.job_scope(d2, run_id="tenant-b"):
        ledger.event("server", "round_start", round_idx=0, expected=5)
        ledger.event("server", "deadline_drop", round_idx=0, client=4)
    # scope exit disarmed the ledger; no stray file at either root
    assert not ledger.enabled()
    a = ledger.load_ledger(d1)
    b = ledger.load_ledger(d2)
    assert {r["run_id"] for r in a} == {"tenant-a"}
    assert {r["run_id"] for r in b} == {"tenant-b"}
    assert {r["event"] for r in a} == {"round_start", "admitted"}
    assert {r["event"] for r in b} == {"round_start", "deadline_drop"}
    # and the anatomies resolve independently
    assert ledger.load_anatomy(d1)["run_id"] == "tenant-a"
    assert ledger.load_anatomy(d2)["rounds"][0]["clients"][4][
        "deadline_dropped"] is True


def test_mlops_init_honors_pod_log_dir_env(tmp_path, monkeypatch):
    pod_dir = str(tmp_path / "podlogs")
    monkeypatch.setenv("FEDML_TPU_LOG_DIR", pod_dir)
    args = make_args(enable_tracking=True, run_id="envjob")
    args.log_file_dir = None
    mlops.init(args)
    try:
        assert mlops.log_dir() == pod_dir
    finally:
        mlops.shutdown()


# ------------------------------------------------- shared AOT cache
def test_parrot_aot_cache_shared_via_pod_env(args_factory, tmp_path,
                                             monkeypatch):
    """Two parrot jobs (think: two tenants on one pod) pointed at the
    pod's FEDML_TPU_AOT_CACHE_DIR share one compiled executable: the
    first writes the digest-keyed artifact, the second hits."""
    from fedml_tpu.runner import FedMLRunner

    shared = tmp_path / "aot_shared"
    monkeypatch.setenv("FEDML_TPU_AOT_CACHE_DIR", str(shared))

    def build_api():
        args = fedml_tpu.init(args_factory(
            backend="parrot", comm_round=2, client_num_in_total=4,
            client_num_per_round=4))
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        return FedMLRunner(args, None, dataset, bundle).runner

    cold = build_api()
    cold._ensure_multi_round_step()
    assert not cold.aot_cache_hit
    arts = [f for f in os.listdir(shared) if f.endswith(".jaxexp")]
    assert len(arts) == 1, arts

    warm = build_api()
    warm._ensure_multi_round_step()
    assert warm.aot_cache_hit
    rms = warm.run_rounds_fused(2)
    assert np.isfinite(np.asarray(rms["train_loss"])).all()


# ------------------------------------------------- serving scaler
def test_serving_scaler_resizes_from_decode_histogram(tmp_path):
    from fedml_tpu.scheduler.autoscaler import AutoscalePolicy
    from fedml_tpu.scheduler.pod.serving_scaler import (
        DECODE_METRIC,
        ServingReplicaScaler,
    )

    reg = metrics.MetricsRegistry()
    hist = reg.histogram(DECODE_METRIC, labels=("model",))
    q = JobQueue(str(tmp_path))
    jid = q.submit(JobSpec(name="svc", kind="serving", n_slots=1,
                           command="serve"))
    clock = {"t": 0.0}
    scaler = ServingReplicaScaler(
        q, policy=AutoscalePolicy(min_replicas=1, max_replicas=8,
                                  target_latency_s=0.05,
                                  target_qps_per_replica=5.0),
        registry=reg, clock=lambda: clock["t"])
    assert scaler.tick() == {}               # baseline window
    for _ in range(100):                     # 100 slow decode steps / s
        hist.labels(model="m").observe(0.2)
    clock["t"] = 1.0
    decisions = scaler.tick()
    assert decisions[jid] == 8               # latency+qps breach → max
    assert q.get(jid)["n_slots"] == 8

    # a RUNNING serving job resizes via the safe preempt→requeue path:
    # dispatch it undersized, breach again → drain request + pending size
    q.update_slots(jid, 2)
    q.mark_dispatched(jid, "runS", [0, 1], "/tmp/l")
    for _ in range(200):
        hist.labels(model="m").observe(0.5)
    clock["t"] = 2.0
    scaler.tick()
    assert q.get(jid)["preempt_requested"]
    q.requeue_preempted(jid, PREEMPTED_EXIT_CODE)
    clock["t"] = 3.0
    scaler.tick()                            # pending resize lands
    job = q.get(jid)
    assert job["state"] == JobState.QUEUED and job["n_slots"] == 8
    q.close()


# ------------------------------------------------- the mixed-workload soak
def test_soak_mixed_tenants_with_forced_preemption_and_resume(tmp_path):
    """Acceptance soak (ISSUE r8): ≥8 heterogeneous jobs from three
    tenants on a forced 8-slot pod.  A high-priority burst evicts the
    4-slot cross-silo job mid-run; it drains at the next round boundary,
    requeues with resume, and finishes ALL its rounds with zero lost
    rounds and zero duplicate-counted uploads.  Aggregate slot
    utilization ends strictly above the best any single job achieved."""
    from fedml_tpu.cross_silo.runner import init_client, init_server

    CS_ROUNDS, N_CLIENTS = 8, 2
    ckpt_dir = str(tmp_path / "cs_ckpt")
    dispatches = []      # (args, server, started_at_round) per dispatch
    sim_envs = []

    def cross_silo_workload(ctx):
        args = fedml_tpu.init(make_args(
            training_type="cross_silo", client_num_in_total=N_CLIENTS,
            client_num_per_round=N_CLIENTS, comm_round=CS_ROUNDS,
            data_scale=0.2, frequency_of_the_test=1,
            run_id=f"podsoak_{ctx.run_id}", checkpoint_dir=ckpt_dir,
            drain_file=ctx.drain_path,
            resume_from=("latest" if ctx.resume else None)))
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        server = init_server(args, dataset, bundle, backend="INPROC")
        clients = [init_client(args, dataset, bundle, rank,
                               backend="INPROC")
                   for rank in range(1, N_CLIENTS + 1)]
        started_at = int(args.round_idx)
        for c in clients:
            threading.Thread(target=c.run, daemon=True).start()
        server.run()
        dispatches.append((args, server, started_at))
        return (PREEMPTED_EXIT_CODE
                if args.preempted_at_round is not None else 0)

    workloads = {
        "cs-train": cross_silo_workload,
        "parrot": _sim_workload(1.2, sim_envs),
        "serving": _sim_workload(2.0, sim_envs),
    }
    sched, q, _res = _mk_sched(
        tmp_path, workloads, total_slots=8,
        tenant_weights={"research": 1.0, "product": 2.0})
    soak_t0 = time.monotonic()

    cs_id = q.submit(JobSpec(name="cs-train", kind="cross_silo",
                             tenant="research", n_slots=4, command="cs"))
    others = [
        q.submit(JobSpec(name="parrot", kind="parrot", tenant="research",
                         n_slots=1, command="p")),
        q.submit(JobSpec(name="parrot", kind="parrot", tenant="product",
                         n_slots=1, command="p")),
        q.submit(JobSpec(name="parrot", kind="parrot", tenant="product",
                         n_slots=2, command="p")),
        q.submit(JobSpec(name="serving", kind="serving", tenant="product",
                         n_slots=1, command="s")),
        q.submit(JobSpec(name="serving", kind="serving", tenant="research",
                         n_slots=1, command="s")),
        q.submit(JobSpec(name="parrot", kind="parrot", tenant="research",
                         n_slots=1, command="p")),
    ]

    def cs_rounds_completed():
        m = metrics.REGISTRY.collect().get("fedml_rounds_completed_total")
        if m is None:
            return 0.0
        return sum(c.value for key, c in m.children().items()
                   if key and key[0].startswith("podsoak_"))

    # phase 1: let the pod fill and the cross-silo job complete a round
    # (so its boundary checkpoint holds real progress)
    assert _step_until(
        sched,
        lambda: (cs_rounds_completed() >= 1
                 and q.get(cs_id)["state"] == JobState.RUNNING),
        timeout_s=240.0), "soak phase 1 stalled"

    # phase 2: high-priority 6-slot burst — every other job holds at most
    # 4 slots combined, so the allocator must evict the preemptible
    # 4-slot cross-silo job to seat the gang
    hp_id = q.submit(JobSpec(name="parrot", kind="parrot",
                             tenant="prod-hp", priority=10, n_slots=6,
                             preemptible=False, command="hp"))
    assert _step_until(
        sched, lambda: q.get(hp_id)["state"] == JobState.FINISHED,
        timeout_s=240.0), "high-priority burst never completed"

    # phase 3: the preempted job redispatches with resume; everything
    # (including the drained-and-requeued small jobs) runs to completion
    all_ids = [cs_id, hp_id] + others
    assert _step_until(
        sched,
        lambda: all(q.get(j)["state"] in JobState.TERMINAL
                    for j in all_ids),
        timeout_s=240.0), "soak never drained the queue"
    soak_elapsed = time.monotonic() - soak_t0
    assert q.get(cs_id)["state"] == JobState.FINISHED
    assert all(q.get(j)["state"] == JobState.FINISHED for j in others)

    cs = q.get(cs_id)
    assert cs["preempt_count"] >= 1 and cs["resume"]
    assert cs["returncode"] == 0

    # zero lost rounds: the resumed dispatch started exactly where the
    # preempted one drained, and together they cover every round once
    assert len(dispatches) >= 2
    first_args, first_server, first_start = dispatches[0]
    last_args, last_server, last_start = dispatches[-1]
    assert first_start == 0
    assert first_args.preempted_at_round is not None
    assert last_start == int(first_args.preempted_at_round)
    assert last_args.preempted_at_round is None
    assert int(last_args.round_idx) == CS_ROUNDS
    evals = sum(len(s.aggregator.metrics_history)
                for _, s, _ in dispatches)
    assert evals == CS_ROUNDS, "a round was lost or re-aggregated"
    # zero duplicate-counted uploads across every dispatch
    assert all(s.aggregator.duplicate_uploads == 0
               for _, s, _ in dispatches)

    # all jobs shared ONE pod AOT cache dir across tenants
    aot_dirs = {env["FEDML_TPU_AOT_CACHE_DIR"] for env in sim_envs}
    assert aot_dirs == {os.path.join(q.root, "aot_cache")}
    tenants_seen = {env["FEDML_TPU_JOB_TENANT"] for env in sim_envs}
    assert len(tenants_seen) >= 2

    # aggregate utilization strictly above the best single job's
    agg_util = sched.aggregate_utilization()
    best_single = 0.0
    for jid in [cs_id, hp_id] + others:
        row = q.get(jid)
        busy = max(0.0, (row["finished_ts"] or 0.0)
                   - (row["dispatched_ts"] or 0.0))
        best_single = max(best_single,
                          row["n_slots"] * busy / (8 * soak_elapsed))
    assert agg_util > best_single, (agg_util, best_single)

    # queue metrics are live on the exposition surface with real samples
    expo = metrics.render_prometheus()
    assert "fedml_pod_slot_utilization" in expo
    assert "fedml_job_queue_wait_seconds_count" in expo
    m = metrics.REGISTRY.collect()["fedml_jobs_preempted_total"]
    preempted = sum(c.value for c in m.children().values())
    assert preempted >= 1
    q.close()
