"""Cross-cloud plane with real substance (VERDICT round-1 item 7): each
cloud is a multi-device mesh slice training the LM with fsdp intra-cloud;
rounds ride the cross-silo message protocol inter-cloud.  On the virtual
8-device CPU mesh this is 2 clouds x 4-device fsdp."""

import jax
import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _build(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return args, device, dataset, bundle


def test_cloud_slices_partition_devices():
    from fedml_tpu.cross_cloud.cloud_trainer import cloud_device_slices

    slices = cloud_device_slices(2)
    assert len(slices) == 2
    assert len(slices[0]) == 4 and len(slices[1]) == 4
    assert not set(slices[0]) & set(slices[1])   # disjoint ICI slices


def test_two_clouds_four_device_fsdp_lm_converges(args_factory):
    """2 clouds x 4-device fsdp functional-LM federation converges and the
    per-cloud trainers really shard over their own slice."""
    args, device, dataset, bundle = _build(args_factory(
        training_type="cross_cloud", backend="INPROC",
        role="simulated",
        dataset="shakespeare", model="transformer",
        cloud_slices=True, cloud_strategy="fsdp", run_id="cc-fsdp",
        client_num_in_total=2, client_num_per_round=2,
        comm_round=3, epochs=1, batch_size=8, learning_rate=0.01,
        client_optimizer="adam", data_scale=0.2,
        frequency_of_the_test=1, compute_dtype="float32"))
    runner = FedMLRunner(args, device, dataset, bundle)
    from fedml_tpu.cross_cloud.runner import CloudFederationRunner

    assert isinstance(runner.runner, CloudFederationRunner)
    trainers = runner.runner.trainers
    assert len(trainers) == 2
    meshes = [t.mesh for t in trainers]
    assert all(len(m.devices.ravel()) == 4 for m in meshes)
    assert not (set(meshes[0].devices.ravel())
                & set(meshes[1].devices.ravel()))

    m = runner.run()
    assert np.isfinite(m["test_loss"])
    losses = [t.last_loss for t in trainers]
    assert all(np.isfinite(v) for v in losses)

    # fsdp really sharded: at least one param of each cloud's step is
    # partitioned over its 4-device data axis
    t0 = trainers[0]
    var0 = t0.init_shardings({"params": jax.tree_util.tree_map(
        lambda x: x, t0.params["params"])})
    specs = [s.spec for s in jax.tree_util.tree_leaves(var0["params"])]
    assert any(spec != () and any(a is not None for a in spec)
               for spec in specs)


def test_cross_cloud_defaults_to_hierarchical_delegation(args_factory):
    """Without cloud_slices the plane keeps the round-1 behavior
    (hierarchical cross-silo delegation) — no regression."""
    args, device, dataset, bundle = _build(args_factory(
        training_type="cross_cloud", backend="INPROC",
        role="simulated",
        dataset="mnist", model="lr", run_id="cc-deleg",
        client_num_in_total=2,
        client_num_per_round=2, comm_round=2, data_scale=0.2,
        frequency_of_the_test=1))
    m = FedMLRunner(args, device, dataset, bundle).run()
    assert np.isfinite(m["test_loss"])
    assert args.scenario == "hierarchical"
