"""Cross-silo plane over the in-process transport: full message protocol
(handshake → init → train → upload → aggregate → sync → finish), plus the
LightSecAgg secure-aggregation variant."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


def test_cross_silo_horizontal_full_protocol(args_factory):
    m = _run(args_factory(training_type="cross_silo", backend="INPROC",
                          role="simulated", client_num_in_total=3,
                          client_num_per_round=3, comm_round=3,
                          data_scale=0.3, run_id="cs1"))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


def test_cross_silo_partial_participation(args_factory):
    m = _run(args_factory(training_type="cross_silo", backend="INPROC",
                          role="simulated", client_num_in_total=6,
                          client_num_per_round=2, comm_round=3,
                          data_scale=0.3, run_id="cs2"))
    assert np.isfinite(m["test_loss"])


def test_cross_silo_lightsecagg_matches_plain(args_factory):
    """LSA must converge like plain FedAvg — masks cancel exactly in the
    field domain (up to quantization)."""
    plain = _run(args_factory(training_type="cross_silo", backend="INPROC",
                              role="simulated", client_num_in_total=3,
                              client_num_per_round=3, comm_round=2,
                              data_scale=0.3, run_id="cs3"))
    lsa = _run(args_factory(training_type="cross_silo", backend="INPROC",
                            role="simulated", client_num_in_total=3,
                            client_num_per_round=3, comm_round=2,
                            data_scale=0.3, run_id="cs4",
                            federated_optimizer="LSA"))
    assert np.isfinite(lsa["test_loss"])
    # quantization at 2^-10 slightly perturbs training; same ballpark
    assert abs(plain["test_acc"] - lsa["test_acc"]) < 0.3


def test_serialization_roundtrip():
    import jax.numpy as jnp

    from fedml_tpu.utils.serialization import dumps_pytree, loads_pytree

    tree = {
        "params": {"dense": {"kernel": jnp.ones((4, 3), jnp.bfloat16),
                             "bias": np.zeros(3, np.float32)}},
        "meta": {"round": 7, "name": "x", "flag": True, "none": None,
                 "lst": [1, 2.5, "s"]},
    }
    blob = dumps_pytree(tree)
    back = loads_pytree(blob)
    assert back["meta"] == tree["meta"]
    np.testing.assert_array_equal(
        np.asarray(back["params"]["dense"]["kernel"], np.float32),
        np.ones((4, 3), np.float32))
    assert str(back["params"]["dense"]["kernel"].dtype) == "bfloat16"
