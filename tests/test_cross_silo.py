"""Cross-silo plane over the in-process transport: full message protocol
(handshake → init → train → upload → aggregate → sync → finish), plus the
LightSecAgg secure-aggregation variant."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


def test_cross_silo_horizontal_full_protocol(args_factory):
    m = _run(args_factory(training_type="cross_silo", backend="INPROC",
                          role="simulated", client_num_in_total=3,
                          client_num_per_round=3, comm_round=3,
                          data_scale=0.3, run_id="cs1"))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


def test_cross_silo_partial_participation(args_factory):
    m = _run(args_factory(training_type="cross_silo", backend="INPROC",
                          role="simulated", client_num_in_total=6,
                          client_num_per_round=2, comm_round=3,
                          data_scale=0.3, run_id="cs2"))
    assert np.isfinite(m["test_loss"])


def test_cross_silo_lightsecagg_matches_plain(args_factory):
    """LSA must converge like plain FedAvg — masks cancel exactly in the
    field domain (up to quantization)."""
    plain = _run(args_factory(training_type="cross_silo", backend="INPROC",
                              role="simulated", client_num_in_total=3,
                              client_num_per_round=3, comm_round=2,
                              data_scale=0.3, run_id="cs3"))
    lsa = _run(args_factory(training_type="cross_silo", backend="INPROC",
                            role="simulated", client_num_in_total=3,
                            client_num_per_round=3, comm_round=2,
                            data_scale=0.3, run_id="cs4",
                            federated_optimizer="LSA"))
    assert np.isfinite(lsa["test_loss"])
    # quantization at 2^-10 slightly perturbs training; same ballpark
    assert abs(plain["test_acc"] - lsa["test_acc"]) < 0.3


def test_serialization_roundtrip():
    import jax.numpy as jnp

    from fedml_tpu.utils.serialization import dumps_pytree, loads_pytree

    tree = {
        "params": {"dense": {"kernel": jnp.ones((4, 3), jnp.bfloat16),
                             "bias": np.zeros(3, np.float32)}},
        "meta": {"round": 7, "name": "x", "flag": True, "none": None,
                 "lst": [1, 2.5, "s"]},
    }
    blob = dumps_pytree(tree)
    back = loads_pytree(blob)
    assert back["meta"] == tree["meta"]
    np.testing.assert_array_equal(
        np.asarray(back["params"]["dense"]["kernel"], np.float32),
        np.ones((4, 3), np.float32))
    assert str(back["params"]["dense"]["kernel"].dtype) == "bfloat16"


def test_cross_silo_secagg_matches_plain(args_factory):
    """Pairwise-mask SecAgg (SA): double masks (self + DH-pairwise) must
    cancel exactly in the field sum; convergence tracks plain FedAvg."""
    plain = _run(args_factory(training_type="cross_silo", backend="INPROC",
                              role="simulated", client_num_in_total=3,
                              client_num_per_round=3, comm_round=2,
                              data_scale=0.3, run_id="sa1"))
    sa = _run(args_factory(training_type="cross_silo", backend="INPROC",
                           role="simulated", client_num_in_total=3,
                           client_num_per_round=3, comm_round=2,
                           data_scale=0.3, run_id="sa2",
                           federated_optimizer="SA"))
    assert np.isfinite(sa["test_loss"])
    assert abs(plain["test_acc"] - sa["test_acc"]) < 0.3


def test_cross_silo_secagg_survives_dropout(args_factory):
    """A client dropping between upload and reconstruction must not poison
    the aggregate: survivors' sk-shares reconstruct the dropped client's
    pairwise masks (the core SecAgg dropout guarantee)."""
    m = _run(args_factory(training_type="cross_silo", backend="INPROC",
                          role="simulated", client_num_in_total=4,
                          client_num_per_round=4, comm_round=2,
                          data_scale=0.3, run_id="sa3",
                          federated_optimizer="SA",
                          sa_simulate_dropout_ranks=[2]))
    assert np.isfinite(m["test_loss"])
    assert m["test_loss"] < 50.0  # unmasked garbage would be huge


def test_secagg_mask_math_roundtrip():
    """Unit check of the field math: mask → sum → reconstruct → unmask
    recovers the exact field sum with and without dropout."""
    import numpy as np
    from fedml_tpu.core.mpc.secagg import FIELD_PRIME, shamir_reconstruct, shamir_share
    from fedml_tpu.cross_silo.secagg.sa_utils import (
        dh_keypair, dh_shared_seed, mask_upload, prg_field_vector,
        remove_dropped_pairwise_masks, remove_self_masks)

    rng = np.random.RandomState(0)
    n, d = 4, 32
    ranks = list(range(1, n + 1))
    keys = {r: dh_keypair(rng) for r in ranks}
    pks = {r: pk for r, (sk, pk) in keys.items()}
    seeds = {r: {p: dh_shared_seed(keys[r][0], pks[p])
                 for p in ranks if p != r} for r in ranks}
    # seeds agree pairwise
    assert seeds[1][2] == seeds[2][1]

    xs = {r: rng.randint(0, 1000, size=d).astype(np.int64) for r in ranks}
    bs = {r: int(rng.randint(1, 2**31 - 1)) for r in ranks}
    ys = {r: mask_upload(xs[r], bs[r], r, ranks, seeds[r]) for r in ranks}

    # no dropout: all pairwise masks cancel; subtract self masks
    qsum = np.zeros(d, np.int64)
    for r in ranks:
        qsum = (qsum + ys[r]) % FIELD_PRIME
    clear = remove_self_masks(qsum, bs)
    expect = sum(xs.values()) % FIELD_PRIME
    np.testing.assert_array_equal(clear, expect)

    # dropout of rank 2: orphaned pairwise masks removed via reconstructed sk
    active = [1, 3, 4]
    qsum2 = np.zeros(d, np.int64)
    for r in active:
        qsum2 = (qsum2 + ys[r]) % FIELD_PRIME
    clear2 = remove_self_masks(qsum2, {r: bs[r] for r in active})
    shares = shamir_share(np.array([keys[2][0]]), n, 2, rng)
    sk2 = int(shamir_reconstruct({0: shares[0], 1: shares[1], 3: shares[3]})[0])
    assert sk2 == keys[2][0]
    clear2 = remove_dropped_pairwise_masks(clear2, active, {2: sk2}, pks)
    expect2 = (xs[1] + xs[3] + xs[4]) % FIELD_PRIME
    np.testing.assert_array_equal(clear2, expect2)


def test_secagg_client_refuses_malicious_unmask():
    """A server asking for both b- and sk-shares of the same client (or
    asking twice) must be refused — the SecAgg privacy invariant is enforced
    client-side, not assumed."""
    from fedml_tpu.arguments import Config
    from fedml_tpu.cross_silo.secagg.sa_client_manager import SAClientManager
    from fedml_tpu.cross_silo.secagg.sa_message_define import SAMessage
    from fedml_tpu.core.distributed.communication.message import Message

    args = Config(random_seed=0, run_id="sa-mal", client_num_per_round=2,
                  comm_round=1)
    c = SAClientManager.__new__(SAClientManager)  # no transport needed
    c.args = args
    c.rank = 1
    c.round_idx = 0
    c._answered_unmask = set()
    c.held_b_shares = {0: {1: np.array([1]), 2: np.array([2])}}
    c.held_sk_shares = {0: {1: np.array([3]), 2: np.array([4])}}
    sent = []
    c.send_message = lambda m: sent.append(m)
    c.get_sender_id = lambda: 1

    # overlapping sets -> refused, nothing sent, shares retained
    bad = Message(SAMessage.MSG_TYPE_S2C_UNMASK_REQUEST, 0, 1)
    bad.add_params(SAMessage.ARG_ACTIVE_SET, [1, 2])
    bad.add_params(SAMessage.ARG_DROPPED_SET, [2])
    bad.add_params(SAMessage.ARG_ROUND, 0)
    c.handle_unmask_request(bad)
    assert not sent and 0 in c.held_b_shares

    # honest request answered once...
    ok = Message(SAMessage.MSG_TYPE_S2C_UNMASK_REQUEST, 0, 1)
    ok.add_params(SAMessage.ARG_ACTIVE_SET, [1])
    ok.add_params(SAMessage.ARG_DROPPED_SET, [2])
    ok.add_params(SAMessage.ARG_ROUND, 0)
    c.handle_unmask_request(ok)
    assert len(sent) == 1
    reply = sent[0]
    assert 1 in reply.get(SAMessage.ARG_B_SHARES)
    assert 2 in reply.get(SAMessage.ARG_SK_SHARES)
    # ...and never both shares for one client
    assert 2 not in reply.get(SAMessage.ARG_B_SHARES)
    assert 1 not in reply.get(SAMessage.ARG_SK_SHARES)

    # a second (replayed) request for the same round -> refused
    c.handle_unmask_request(ok)
    assert len(sent) == 1


def test_secagg_rejects_single_client():
    from fedml_tpu.arguments import Config
    from fedml_tpu.cross_silo.secagg.sa_server_manager import SAServerManager

    with pytest.raises(ValueError, match="at least 2 clients"):
        SAServerManager(Config(comm_round=1, run_id="sa-one"), None,
                        client_num=1)


def test_cross_silo_with_compressed_uploads(args_factory):
    """enable_compression: sparse EF-TopK delta uploads still converge."""
    import threading

    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=2,
        client_num_per_round=2, comm_round=3, data_scale=0.3,
        learning_rate=0.1, run_id="cs_comp", enable_compression=True,
        compression_type="eftopk", compress_ratio=0.3))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle)
    clients = [init_client(args, dataset, bundle, rank) for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.3  # sparse updates still learn


def test_elastic_round_timeout_drops_straggler(args_factory):
    """round_timeout_s: the server aggregates with the clients that
    reported and completes training even when one client goes silent after
    coming online (elastic membership / dropout tolerance)."""
    import threading

    import fedml_tpu
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.core.distributed.fedml_comm_manager import FedMLCommManager
    from fedml_tpu.cross_silo.message_define import MyMessage
    from fedml_tpu.cross_silo.runner import init_client, init_server

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=3,
        client_num_per_round=3, comm_round=3, data_scale=0.3,
        learning_rate=0.1, run_id="cs_elastic", round_timeout_s=2.0,
        min_clients_per_round=2))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle)
    clients = [init_client(args, dataset, bundle, rank) for rank in (1, 2)]

    # rank 3: comes ONLINE, then never trains or uploads (straggler)
    class Silent(FedMLCommManager):
        def register_message_receive_handlers(self):
            self.register_message_receive_handler(
                MyMessage.MSG_TYPE_S2C_FINISH, lambda m: self.finish())

        def go(self):
            self.register_message_receive_handlers()
            msg = Message(MyMessage.MSG_TYPE_C2S_CLIENT_STATUS, 3, 0)
            msg.add_params(MyMessage.MSG_ARG_KEY_CLIENT_STATUS,
                           MyMessage.CLIENT_STATUS_ONLINE)
            self.send_message(msg)
            self.com_manager.handle_receive_message()

    silent = Silent(args, rank=3, size=4)
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    threads.append(threading.Thread(target=silent.go, daemon=True))
    for t in threads:
        t.start()
    server.run()  # must terminate despite the straggler
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.3


def test_elastic_init_force_start_without_all_clients(args_factory):
    """A client that NEVER comes online must not block init forever when
    round_timeout_s is set: the server force-starts with min_clients."""
    import threading

    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=3,
        client_num_per_round=3, comm_round=2, data_scale=0.3,
        learning_rate=0.1, run_id="cs_forceinit", round_timeout_s=1.0,
        min_clients_per_round=2))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle)
    # only ranks 1 and 2 ever start; rank 3 is absent entirely
    clients = [init_client(args, dataset, bundle, rank) for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    done = threading.Event()
    t = threading.Thread(target=lambda: (server.run(), done.set()),
                         daemon=True)
    t.start()
    assert done.wait(60), "server never finished — init blocked"
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])


def _chaos_reliable_cross_silo(args_factory, backend_name, run_id, **kw):
    """Secure-aggregation run over CHAOS(INPROC) with the reliability
    runtime above it (reliability recovers what chaos loses — without it,
    SA/LSA stage gates that wait on the full cohort would stall forever
    on one dropped message)."""
    import threading

    import fedml_tpu
    from fedml_tpu.core.distributed.communication.chaos import (
        ChaosCommManager,
    )
    from fedml_tpu.core.distributed.communication.inprocess import (
        InProcCommManager,
    )
    from fedml_tpu.core.distributed.fedml_comm_manager import (
        register_comm_backend,
    )
    from fedml_tpu.cross_silo.runner import init_client, init_server

    chaos_instances = []

    def factory(args, rank=0, size=0):
        mgr = ChaosCommManager(
            InProcCommManager(rank, size, str(args.run_id)),
            drop_p=0.15, dup_p=0.1, delay_p=0.2, max_delay_s=0.05,
            seed=300 + rank)
        chaos_instances.append(mgr)
        return mgr

    register_comm_backend(backend_name, factory)
    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=4,
        client_num_per_round=4, comm_round=2, data_scale=0.3,
        learning_rate=0.1, run_id=run_id, reliable=True,
        reliable_retx_initial_s=0.05, reliable_retx_max_s=0.5, **kw))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle, backend=backend_name)
    clients = [init_client(args, dataset, bundle, rank,
                           backend=backend_name) for rank in range(1, 5)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    return server, threads, chaos_instances


def test_secagg_dropout_recovery_under_chaos(args_factory):
    """SecAgg with a client dying after its masking commitment, on a lossy
    link: survivors' sk-shares still reconstruct the dropped client's
    pairwise masks, and the reliable plane keeps every stage gate fed."""
    server, threads, chaos = _chaos_reliable_cross_silo(
        args_factory, "CHAOS_REL_SA", "sa_chaos",
        federated_optimizer="SA", sa_simulate_dropout_ranks=[2])
    server.run()
    for t in threads:
        t.join(timeout=30)
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
    assert m["test_loss"] < 50.0        # unmasked garbage would be huge
    assert sum(c.stats["dropped"] + c.stats["duplicated"]
               for c in chaos) > 0, "chaos never fired"


def test_lightsecagg_dropout_recovery_under_chaos(args_factory):
    """LightSecAgg counterpart: ≥u survivors reconstruct the aggregate
    mask after a post-commitment dropout, under seeded chaos."""
    server, threads, chaos = _chaos_reliable_cross_silo(
        args_factory, "CHAOS_REL_LSA", "lsa_chaos",
        federated_optimizer="LSA", lsa_simulate_dropout_ranks=[3])
    server.run()
    for t in threads:
        t.join(timeout=30)
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
    assert m["test_loss"] < 50.0
    assert sum(c.stats["dropped"] + c.stats["duplicated"]
               for c in chaos) > 0, "chaos never fired"


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_secagg_below_threshold_aborts_cleanly(args_factory):
    """Dropout beyond the Shamir threshold is unrecoverable: the server
    must abort via _abort_run — broadcast FINISH so every client exits —
    instead of stranding the cohort on a sync that never comes."""
    import threading

    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    # client_num=4 → t=2: dropping 2 clients leaves 2 survivors < t+1=3
    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=4,
        client_num_per_round=4, comm_round=2, data_scale=0.3,
        learning_rate=0.1, run_id="sa_abort", federated_optimizer="SA",
        sa_simulate_dropout_ranks=[2, 3]))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle, backend="INPROC")
    clients = [init_client(args, dataset, bundle, rank, backend="INPROC")
               for rank in range(1, 5)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    with pytest.raises(RuntimeError, match="cannot be opened"):
        server.run()
    # _abort_run released every client: all threads exit instead of
    # blocking on the next round's sync
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), \
        "abort left clients stranded"


def test_lsa_client_gives_up_on_permanently_lost_share(args_factory):
    """A survivor's C2C share lost for good (past the reliable plane's
    retransmit deadline) must NOT deadlock the client on the server's
    agg-mask request: after lsa_share_wait_s it replies 'unavailable'."""
    import queue
    import time

    from fedml_tpu.core.distributed.communication.inprocess import InProcHub
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.cross_silo.lightsecagg.lsa_client_manager import (
        LSAClientManager,
    )
    from fedml_tpu.cross_silo.lightsecagg.lsa_message_define import LSAMessage

    args = args_factory(run_id="lsa_giveup", lsa_share_wait_s=0.2)
    client = LSAClientManager(args, None, rank=1, size=4, backend="INPROC")
    # the client holds only its OWN share; survivor 2's share never comes
    client.received_shares = {0: {1: np.zeros(4, np.int64)}}
    req = Message(LSAMessage.MSG_TYPE_S2C_AGG_MASK_REQUEST, 0, 1)
    req.add_params(LSAMessage.ARG_SURVIVORS, [1, 2])
    req.add_params(LSAMessage.ARG_ROUND, 0)
    client.handle_agg_request(req)

    server_q = InProcHub.get("lsa_giveup").queue_for(0)
    deadline = time.time() + 5
    reply = None
    while time.time() < deadline:
        try:
            reply = server_q.get(timeout=0.1)
            break
        except queue.Empty:
            continue
    assert reply is not None, "client never gave up — cohort would deadlock"
    assert reply.get_type() == LSAMessage.MSG_TYPE_C2S_AGG_MASK_SHARE
    assert reply.get(LSAMessage.ARG_SHARE_UNAVAILABLE) is True
    assert int(reply.get(LSAMessage.ARG_ROUND)) == 0


def test_lsa_server_asks_next_holder_on_unavailable(args_factory):
    """On an 'unavailable' agg-share reply the server asks the next
    survivor; when none remain it aborts the run (FINISH to everyone)
    instead of waiting forever."""
    from fedml_tpu.core.distributed.communication.inprocess import InProcHub
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.cross_silo.lightsecagg.lsa_message_define import LSAMessage
    from fedml_tpu.cross_silo.lightsecagg.lsa_server_manager import (
        LSAServerManager,
    )

    class _DummyAgg:
        metrics_history = []

    args = args_factory(run_id="lsa_nextholder", comm_round=2)
    server = LSAServerManager(args, _DummyAgg(), rank=0, client_num=3,
                              backend="INPROC")
    server._share_survivors = [1, 2, 3]
    server._share_req_sent = {1, 2}
    hub = InProcHub.get("lsa_nextholder")

    bad = Message(LSAMessage.MSG_TYPE_C2S_AGG_MASK_SHARE, 1, 0)
    bad.add_params(LSAMessage.ARG_SHARE_UNAVAILABLE, True)
    bad.add_params(LSAMessage.ARG_ROUND, 0)
    server.handle_agg_share(bad)
    # the untried survivor (rank 3) got a fresh request
    nxt = hub.queue_for(3).get(timeout=2)
    assert nxt.get_type() == LSAMessage.MSG_TYPE_S2C_AGG_MASK_REQUEST
    assert 3 in server._share_req_sent

    # no survivors left → clean abort: FINISH broadcast to all ranks
    bad2 = Message(LSAMessage.MSG_TYPE_C2S_AGG_MASK_SHARE, 2, 0)
    bad2.add_params(LSAMessage.ARG_SHARE_UNAVAILABLE, True)
    bad2.add_params(LSAMessage.ARG_ROUND, 0)
    bad3 = Message(LSAMessage.MSG_TYPE_C2S_AGG_MASK_SHARE, 3, 0)
    bad3.add_params(LSAMessage.ARG_SHARE_UNAVAILABLE, True)
    bad3.add_params(LSAMessage.ARG_ROUND, 0)
    server.handle_agg_share(bad2)
    server.handle_agg_share(bad3)
    for rank in (1, 2, 3):
        q = hub.queue_for(rank)
        types = []
        while not q.empty():
            types.append(q.get().get_type())
        assert LSAMessage.MSG_TYPE_S2C_FINISH in types, \
            f"rank {rank} never released on abort"
