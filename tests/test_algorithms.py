"""Algorithm-structured planes: hierarchical, decentralized, async, vertical
FL, SplitNN — plus the heterogeneity-aware scheduler."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


def test_hierarchical_fl(args_factory):
    m = _run(args_factory(federated_optimizer="HierarchicalFL",
                          client_num_in_total=4, group_num=2,
                          group_comm_round=2, comm_round=2, data_scale=0.3))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


def test_decentralized_gossip(args_factory):
    m = _run(args_factory(federated_optimizer="Decentralized",
                          client_num_in_total=4, comm_round=3,
                          data_scale=0.3))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


def test_async_fedavg(args_factory):
    m = _run(args_factory(federated_optimizer="Async_FedAvg",
                          client_num_in_total=4, comm_round=4,
                          data_scale=0.3))
    assert m["server_steps"] >= 4  # every client completes at least once
    assert np.isfinite(m["test_loss"])


def test_vertical_fl_two_party(args_factory):
    m = _run(args_factory(federated_optimizer="VerticalFL", dataset="adult",
                          comm_round=4, batch_size=64, learning_rate=0.1,
                          data_scale=0.5))
    # synthetic adult is a logistic ground truth: both parties' features help
    assert m["test_acc"] > 0.6


def test_split_nn(args_factory):
    m = _run(args_factory(federated_optimizer="SplitNN", dataset="mnist",
                          client_num_in_total=3, comm_round=2,
                          batch_size=32, learning_rate=0.1, data_scale=0.1))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.15


def test_seq_train_scheduler_balances():
    from fedml_tpu.core.schedule.seq_train_scheduler import (
        SeqTrainScheduler,
        t_sample_fit,
    )

    workloads = [100, 90, 10, 10, 10, 10, 5, 5]
    scheduler = SeqTrainScheduler(workloads, constraints=[1.0, 1.0])
    assign, loads = scheduler.DP_schedule()
    assert sorted(sum(assign, [])) == list(range(8))
    # makespan must beat the trivial split (first half vs second half)
    assert max(loads) <= 130
    # runtime fit: t = 2n + 1 exactly recovered
    hist = {(0, c): [(n, 2.0 * n + 1.0)]
            for c, n in enumerate([10, 20, 40, 80])}
    fits = t_sample_fit(hist)
    a, b = fits[0]
    assert abs(a - 2.0) < 1e-6 and abs(b - 1.0) < 1e-6
