"""Run ledger + SLO engine + perf sentinel (docs/OBSERVABILITY.md "Run
ledger"): cross-plane round anatomy from a chaos federation, the
declarative SLO gate's exit codes, bounded-writer behaviour, and the
regression/stale detector over the perf history."""

import json
import os
import threading
import time

import numpy as np
import pytest
from click.testing import CliRunner

from fedml_tpu.core.distributed.communication.chaos import ChaosCommManager
from fedml_tpu.core.distributed.communication.inprocess import (
    InProcCommManager,
)
from fedml_tpu.core.mlops import (
    flight_recorder,
    ledger,
    metrics,
    perf_history,
    slo,
)


def _register_chaos_backend(name, *, drop_p=0.25, dup_p=0.1, delay_p=0.2,
                            max_delay_s=0.03, seed0=77):
    """Lossy seeded transport; args.reliable=True layers the reliability
    runtime ABOVE it so retransmits/dups cross the chaos link."""
    from fedml_tpu.core.distributed.fedml_comm_manager import (
        register_comm_backend,
    )

    def factory(args, rank=0, size=0):
        return ChaosCommManager(
            InProcCommManager(rank, size, str(args.run_id)),
            drop_p=drop_p, dup_p=dup_p, delay_p=delay_p,
            max_delay_s=max_delay_s, seed=seed0 + rank)

    register_comm_backend(name, factory)


def _run_federation(args_factory, run_id, log_dir, adversaries=None, n=3,
                    comm_round=2, backend="INPROC", **kw):
    """One INPROC cross-silo federation with the run ledger and flight
    recorder armed.  Returns (args, server, elapsed_s)."""
    import fedml_tpu
    from fedml_tpu.core.distributed.communication.chaos import chaos_trainer
    from fedml_tpu.cross_silo.runner import (
        fleet_size,
        init_client,
        init_server,
    )
    from fedml_tpu.ml.trainer.default_trainer import DefaultClientTrainer

    cfg = dict(training_type="cross_silo", client_num_in_total=n,
               client_num_per_round=n, comm_round=comm_round, data_scale=0.2,
               learning_rate=0.1, frequency_of_the_test=1, run_id=run_id,
               run_ledger=True, flight_recorder=True,
               log_file_dir=str(log_dir))
    cfg.update(kw)
    args = fedml_tpu.init(args_factory(**cfg))
    fleet = fleet_size(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle, backend=backend)
    clients = []
    for rank in range(1, fleet + 1):
        trainer = DefaultClientTrainer(bundle, args)
        if adversaries and rank in adversaries:
            trainer = chaos_trainer(trainer, adversaries[rank])
        clients.append(init_client(args, dataset, bundle, rank, trainer,
                                   backend=backend))
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    server.run()
    elapsed = time.monotonic() - t0
    for t in threads:
        t.join(timeout=15)
    return args, server, elapsed


# ------------------------------------------------- acceptance: anatomy
def test_chaos_round_anatomy_attributes_faults(args_factory, tmp_path):
    """ISSUE acceptance: a 3-client chaos run where client 2 uploads NaN
    (quarantined) and client 3 is a 4 s straggler against a 1 s deadline
    (dropped), over a lossy reliable link (retransmits) — `fedml rounds
    timeline` attributes each fault to the right client and round, and
    the combined ledger+recorder overhead stays under 2% of round wall."""
    from fedml_tpu.cli.cli import cli

    _register_chaos_backend("CHAOS_LEDGER")
    log_dir = tmp_path / "anat"
    args, server, _ = _run_federation(
        args_factory, "ledger_anat", log_dir,
        adversaries={2: "nan", 3: "slow:4.0"},
        backend="CHAOS_LEDGER", reliable=True,
        reliable_retx_initial_s=0.05, reliable_retx_max_s=0.5,
        admission_control=True, round_deadline_s=1.0,
        round_deadline_grace_s=0.5, min_aggregation_clients=1)
    assert int(args.round_idx) == 2

    # overhead guard BEFORE anything resets the recorders: the ledger's
    # self-measured write cost plus the flight recorder's, against the
    # summed round walls from the ledger itself
    led_overhead = ledger.overhead_s()
    anatomy = ledger.load_anatomy(str(log_dir))
    walls = [r["wall_s"] for r in anatomy["rounds"].values()
             if r.get("wall_s")]
    assert walls, anatomy
    fl_overhead = (anatomy["flight"] or {}).get("overhead_s", 0.0)
    budget = 0.02 * sum(walls)
    assert led_overhead + fl_overhead < budget, (
        f"ledger {led_overhead:.4f}s + flight {fl_overhead:.4f}s "
        f">= 2% of {sum(walls):.2f}s round wall")

    # round 0: the deadline round — the straggler was dropped there
    r0 = anatomy["rounds"][0]
    assert r0["closed"] == "deadline"
    assert r0["clients"][3]["deadline_dropped"] is True
    assert r0["clients"][3]["verdict"] is None  # never admitted
    # the NaN client's upload DID arrive and was quarantined non_finite
    quarantined = {(idx, rank): c["reason"]
                   for idx, r in anatomy["rounds"].items()
                   for rank, c in r["clients"].items()
                   if c["verdict"] == "quarantined"}
    assert quarantined, anatomy["rounds"]
    assert all(rank == 2 for _, rank in quarantined), quarantined
    assert set(quarantined.values()) == {"non_finite"}
    # client 1 is honest: admitted somewhere, never quarantined/dropped
    assert any(r["clients"].get(1, {}).get("verdict") == "admitted"
               for r in anatomy["rounds"].values())
    # the lossy link forced retransmits and they landed on real rounds
    assert sum(r["retransmits"] for r in anatomy["rounds"].values()) > 0

    # the CLI renders the same story.  Round 0 is always the deadline
    # round; the quarantine lands wherever client 2's delayed upload
    # actually arrived (under CPU contention it can slip past round 0's
    # deadline and be quarantined on re-solicit), so render that round.
    def _client_lines(round_idx):
        res = CliRunner().invoke(
            cli, ["rounds", "timeline", "--log-dir", str(log_dir),
                  "--round", str(round_idx)])
        assert res.exit_code == 0, res.output
        return {ln.strip().split(":")[0]: ln
                for ln in res.output.splitlines()
                if ln.strip().startswith("client ")}

    lines = _client_lines(0)
    assert "DROPPED at deadline" in lines["client 3"]
    assert "quarantined" not in lines["client 3"]
    quar_round = min(idx for idx, _ in quarantined)
    assert "quarantined non_finite" in _client_lines(quar_round)["client 2"]
    for sub in (["rounds", "report"], ["rounds", "stragglers"]):
        res = CliRunner().invoke(cli, sub + ["--log-dir", str(log_dir)])
        assert res.exit_code == 0, res.output
    res = CliRunner().invoke(
        cli, ["rounds", "stragglers", "--log-dir", str(log_dir)])
    # worst offender first: the deadline-dropped straggler tops the table
    assert res.output.splitlines()[1].split()[0] == "3"


# ---------------------------------------------------------- SLO engine
def _write_rules(path, body):
    path.write_text(body)
    return str(path)


def test_slo_check_exit_codes(args_factory, tmp_path):
    """`fedml slo check` exits 0 on a clean run (unknown indicators SKIP,
    never breach) and 1 when a bound is violated."""
    from fedml_tpu.cli.cli import cli

    log_dir = tmp_path / "slorun"
    _run_federation(args_factory, "slo_run", log_dir, n=2, comm_round=2)

    clean = _write_rules(tmp_path / "clean.yaml", """
slos:
  - name: round_time_p95
    indicator: round_time_p95
    max: 60
  - name: quarantine_rate
    indicator: quarantine_rate
    max: 0.5
  - name: retransmit_rate
    indicator: retransmit_rate
    max: 0.5
  - name: h2d_blocked_share
    indicator: h2d_blocked_share
    max: 0.9
  - name: mfu_floor
    indicator: measured_mfu
    min: 0.0001
  - name: decode_ttft_p99
    indicator: decode_ttft_p99
    max: 5
""")
    res = CliRunner().invoke(
        cli, ["slo", "check", "--rules", clean, "--log-dir", str(log_dir)])
    assert res.exit_code == 0, res.output
    assert "BREACH" not in res.output
    # indicators with no data on this tiny CPU run are SKIPPED, not failed
    assert "SKIP" in res.output

    tight = _write_rules(tmp_path / "tight.yaml", """
slos:
  - name: round_time_p95
    indicator: round_time_p95
    max: 0.000001
""")
    res = CliRunner().invoke(
        cli, ["slo", "check", "--rules", tight, "--log-dir", str(log_dir)])
    assert res.exit_code == 1
    assert "BREACH" in res.output


def test_slo_round_boundary_hook_emits_breach(args_factory, tmp_path):
    """A breached rule at the round boundary increments
    fedml_slo_breaches_total{rule} and lands a `breach` event in the
    ledger — attributable like any other round event."""
    ledger.enable(True, log_dir=str(tmp_path), run_id="slo_hook")
    slo.reset()
    rules = tmp_path / "r.yaml"
    rules.write_text("slos:\n  - name: rt\n    indicator: round_time_p95\n"
                     "    max: 0.000001\n")
    slo._state["rules"] = slo.load_rules(str(rules))
    slo._state["enabled"] = True
    metrics.histogram(
        "fedml_round_seconds", "round wall",
        ("run_id",)).labels(run_id="slo_hook").observe(3.0)

    slo.check_round_boundary(4)

    scrape = metrics.parse_prometheus(metrics.render_prometheus())
    total = sum(s["value"]
                for s in scrape["fedml_slo_breaches_total"]["samples"]
                if s["labels"].get("rule") == "rt")
    assert total >= 1
    ledger.reset()
    recs = ledger.load_ledger(str(tmp_path))
    breach = [r for r in recs if r["event"] == "breach"]
    assert breach and breach[0]["round_idx"] == 4
    assert breach[0]["attrs"]["rule"] == "rt"
    slo.reset()


def test_slo_rules_yaml_roundtrip(tmp_path):
    rules = tmp_path / "slo.yaml"
    rules.write_text("""
slos:
  - name: rt
    indicator: round_time_p95
    max: 30
  - name: ttft
    indicator: decode_ttft_p99
    max: 0.5
    quantile: 0.95
""")
    loaded = slo.load_rules(str(rules))
    assert [r.name for r in loaded] == ["rt", "ttft"]
    assert loaded[1].params["quantile"] == 0.95
    with pytest.raises(ValueError):
        slo.SLORule(name="x", indicator="nope", max=1)


# ------------------------------------------------------ bounded writer
def test_ledger_bounded_writes_and_dropped_counter(tmp_path):
    ledger.enable(True, log_dir=str(tmp_path), run_id="cap", max_records=10)
    for i in range(25):
        ledger.event("server", "tick", round_idx=i, i=i)
    assert ledger.dropped() == 15
    ledger.reset()
    recs = ledger.load_ledger(str(tmp_path))
    assert len(recs) == 10
    scrape = metrics.render_prometheus()
    assert "fedml_ledger_dropped_records_total" in scrape


def test_ledger_noop_when_disarmed(tmp_path):
    ledger.reset()
    assert not ledger.enabled()
    ledger.event("server", "tick", round_idx=0)  # must not raise or write
    assert ledger.load_ledger(str(tmp_path)) == []


# ---------------------------------------------------- span cap (sat 1)
def test_trace_span_cap_drops_and_counts(args_factory, tmp_path):
    """spans.jsonl is bounded by trace_max_spans; overflow increments
    fedml_trace_dropped_spans_total instead of growing the file."""
    from fedml_tpu.core import mlops
    from fedml_tpu.core.mlops import tracing

    mlops.init(args_factory(enable_tracking=True, run_id="spancap",
                            log_file_dir=str(tmp_path), trace_max_spans=5))
    for i in range(12):
        with tracing.Span("tiny", attrs={"i": i}):
            pass
    assert tracing.dropped_spans() == 7
    spans = tracing.load_spans(str(tmp_path))
    assert len(spans) == 5
    assert "fedml_trace_dropped_spans_total" in metrics.render_prometheus()
    mlops.shutdown()


# ----------------------------------------- exposition parser (sat 2)
def test_parse_prometheus_and_quantile():
    h = metrics.histogram("fedml_pp_test_seconds", "x", ("k",),
                          buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0):
        h.labels(k="a").observe(v)
    metrics.counter("fedml_pp_total", "y", ("k",)).labels(k='we"ird').inc(3)
    parsed = metrics.parse_prometheus(metrics.render_prometheus())
    assert parsed["fedml_pp_total"]["type"] == "counter"
    assert parsed["fedml_pp_total"]["samples"][0]["labels"]["k"] == 'we"ird'
    series = parsed["fedml_pp_test_seconds"]["series"]
    assert series and series[0]["count"] == 4
    q50 = metrics.histogram_quantile(0.5, series[0]["buckets"])
    assert 0.1 <= q50 <= 1.0
    # the CLI surfaces the same dict
    from fedml_tpu.cli.cli import cli

    res = CliRunner().invoke(cli, ["metrics", "--json"])
    assert res.exit_code == 0
    assert "fedml_pp_total" in json.loads(res.output)


# ------------------------------------------ flight dir locate (sat 3)
def test_flight_log_locate_accepts_directories(tmp_path):
    nested = tmp_path / "job1" / "flight"
    nested.mkdir(parents=True)
    (nested / "flight.jsonl").write_text(
        json.dumps({"kind": "phase", "phase": "h2d", "dur_s": 0.5}) + "\n")
    # file path, its dir, and an ancestor dir all resolve to the same log
    direct = flight_recorder.load_flight_log(str(nested / "flight.jsonl"))
    via_dir = flight_recorder.load_flight_log(str(nested))
    via_root = flight_recorder.load_flight_log(str(tmp_path))
    assert direct == via_dir == via_root
    assert direct[0]["phase"] == "h2d"


# ------------------------------------------------- perf sentinel
def test_perf_history_detects_regression_and_stale(tmp_path):
    h = str(tmp_path / "hist.jsonl")
    perf_history.append_entry(h, "cpu", "bench", {"rounds_per_s": 10.0},
                              ts=100.0, rev="aaa")
    perf_history.append_entry(h, "cpu", "bench", {"rounds_per_s": 7.0},
                              ts=200.0, rev="bbb")
    perf_history.append_entry(h, "tpu", "bench", {"rounds_per_s": 3.37},
                              ts=100.0, rev="r05")
    perf_history.append_entry(h, "tpu", "carried", {"rounds_per_s": 3.37},
                              ts=300.0, rev="r07", measured=False,
                              carried_from="r05")
    f = perf_history.detect(perf_history.load_history(h))
    assert [r["metric"] for r in f["regressions"]] == ["rounds_per_s"]
    reg = f["regressions"][0]
    assert reg["platform"] == "cpu" and reg["drop_frac"] == pytest.approx(0.3)
    assert [s["platform"] for s in f["stale"]] == ["tpu"]
    assert f["stale"][0]["carried_from"] == "r05"
    # cross-platform values never compared: tpu 3.37 vs cpu 10 is not a drop
    assert all(r["platform"] == "cpu" for r in f["regressions"])

    from fedml_tpu.cli.cli import cli

    res = CliRunner().invoke(cli, ["perf", "regress", "--history", h])
    assert res.exit_code == 1
    assert "REGRESSION [cpu]" in res.output and "STALE [tpu]" in res.output
    res = CliRunner().invoke(cli, ["perf", "regress", "--history", h,
                                   "--drop-threshold", "0.4",
                                   "--allow-stale"])
    assert res.exit_code == 0


def test_seeded_repo_history_flags_stale_tpu_headline():
    """The committed benchmarks/perf_history.jsonl encodes the ROADMAP
    caveat — the 3.3687 rounds/s TPU headline carried since BENCH_r05 —
    and the sentinel flags it until someone re-measures on a TPU."""
    entries = perf_history.load_history()  # default: benchmarks/…
    assert entries, "benchmarks/perf_history.jsonl missing"
    findings = perf_history.detect(entries)
    stale_platforms = {s["platform"] for s in findings["stale"]}
    assert "tpu" in stale_platforms
    assert findings["stale"][0]["carried_from"] == "bench_r05"
