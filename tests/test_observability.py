"""Observability plane: distributed tracing (span propagation across the
cross-silo hop), the typed metrics registry + Prometheus exposition, the
control-plane /metrics endpoint, mlops lifecycle isolation, perf-stats
monotonic timestamps, and log-daemon crash-resume."""

import json
import re
import threading
import time
import urllib.request

import pytest

from fedml_tpu.core.mlops import metrics as metrics_mod
from fedml_tpu.core.mlops import tracing


# -- tracing unit behavior ---------------------------------------------------

def test_span_nesting_and_ids():
    with tracing.span("outer", round=1) as outer:
        assert tracing.current() is outer.ctx
        with tracing.span("inner") as inner:
            assert inner.ctx.trace_id == outer.ctx.trace_id
            assert inner.parent_span_id == outer.ctx.span_id
    assert tracing.current() is None
    # fresh root gets a fresh trace
    with tracing.span("other") as other:
        assert other.ctx.trace_id != outer.ctx.trace_id
        assert other.parent_span_id is None


def test_trace_ctx_wire_roundtrip():
    with tracing.span("root") as sp:
        wire = tracing.inject()
        assert wire == {"trace_id": sp.ctx.trace_id,
                        "span_id": sp.ctx.span_id}
    ctx = tracing.extract(wire)
    assert ctx.trace_id == sp.ctx.trace_id
    # remote attachment parents new spans under the extracted context
    with tracing.use_ctx(ctx):
        with tracing.span("child") as child:
            assert child.ctx.trace_id == sp.ctx.trace_id
            assert child.parent_span_id == sp.ctx.span_id
    # tolerant of peers that predate tracing
    assert tracing.extract(None) is None
    assert tracing.extract("garbage") is None
    assert tracing.extract({"trace_id": ""}) is None
    assert tracing.inject(None) is not None or tracing.current() is None


def test_manual_span_end_idempotent():
    sp = tracing.start_span("held", phase="x")
    dur = sp.end()
    assert dur >= 0.0
    assert sp.end() == 0.0  # double end keeps the first record


# -- metrics registry --------------------------------------------------------

def test_histogram_bucketing_and_timer():
    r = metrics_mod.MetricsRegistry()
    h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    child = h.labels()
    cum = dict(child.cumulative())
    assert cum[0.1] == 1
    assert cum[1.0] == 3          # cumulative, not per-bucket
    assert cum[10.0] == 4
    assert cum[float("inf")] == 5
    assert child.count == 5
    assert child.sum == pytest.approx(56.05)
    with h.time():
        time.sleep(0.01)
    assert h.labels().count == 6

    c = r.counter("reqs_total", "requests", labels=("route",))
    c.labels(route="/a").inc()
    c.labels(route="/a").inc(2.5)
    with pytest.raises(ValueError):
        c.labels(route="/a").inc(-1)
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    g = r.gauge("depth", "queue depth")
    g.set(7)
    g.dec(3)
    assert g.labels().value == 4
    # type collision on an existing name is an error, same-type is get-or-create
    assert r.counter("reqs_total", labels=("route",)) is c
    with pytest.raises(ValueError):
        r.gauge("reqs_total")


_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? (\+Inf|-?[0-9.eE+-]+)$")


def test_prometheus_exposition_format():
    r = metrics_mod.MetricsRegistry()
    r.counter("c_total", "a counter").inc(3)
    r.gauge("g_now", "a gauge", labels=("node",)).labels(
        node='weird"\\name\n').set(1.5)
    r.histogram("h_seconds", "a histogram", buckets=(0.5,)).observe(0.2)
    text = r.render_prometheus()
    assert text.endswith("\n")
    lines = text.splitlines()
    for name, kind in (("c_total", "counter"), ("g_now", "gauge"),
                       ("h_seconds", "histogram")):
        assert f"# TYPE {name} {kind}" in lines
    for line in lines:
        if line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
    # histogram completeness: buckets are cumulative and end at +Inf
    assert 'h_seconds_bucket{le="0.5"} 1' in lines
    assert 'h_seconds_bucket{le="+Inf"} 1' in lines
    assert "h_seconds_sum 0.2" in lines
    assert "h_seconds_count 1" in lines
    # label values escaped, not mangled
    assert r'node="weird\"\\name\n"' in text


# -- the acceptance-criteria run: two clients, one stitched trace ------------

_RUN_SEQ = iter(range(10_000))


@pytest.fixture
def cross_silo_run(args_factory, tmp_path):
    """Run a 2-client, 2-round cross-silo federation with tracking on;
    returns (spans, run_id).  The run_id is unique per invocation so
    run-labelled series in the process-global registry stay exact."""
    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    run_id = f"obs-accept-{next(_RUN_SEQ)}"
    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=2,
        client_num_per_round=2, comm_round=2, data_scale=0.2,
        run_id=run_id, enable_tracking=True,
        log_file_dir=str(tmp_path)))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    server = init_server(args, dataset, bundle)
    clients = [init_client(args, dataset, bundle, rank) for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    spans = tracing.load_spans(str(tmp_path))
    return spans, run_id


def test_cross_silo_trace_stitching(cross_silo_run):
    spans, _ = cross_silo_run
    assert spans, "no spans emitted"
    # ONE trace id across server, clients and aggregator
    assert len({s["trace_id"] for s in spans}) == 1
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    rounds = {s["attrs"]["round"]: s for s in by_name["train_round"]}
    assert set(rounds) == {0, 1}
    root = by_name["fed_run"][0]
    round_ids = {s["span_id"] for s in by_name["train_round"]}
    for s in by_name["train_round"]:
        assert s["parent_span_id"] == root["span_id"]
    # every client training nests under ITS round's parent span
    assert len(by_name["client.train"]) == 4  # 2 clients x 2 rounds
    for s in by_name["client.train"]:
        assert s["parent_span_id"] == rounds[s["attrs"]["round"]]["span_id"]
    # aggregation and eval nest under the round parents too
    for s in by_name["server.aggregate"] + by_name["server.eval"]:
        assert s["parent_span_id"] in round_ids
    # trainer spans nest under the client spans (grandchildren of the round)
    client_ids = {s["span_id"] for s in by_name["client.train"]}
    for s in by_name["trainer.local_update"]:
        assert s["parent_span_id"] in client_ids

    summary = tracing.summarize(spans)
    assert "train_round" in summary and "client.train" in summary
    assert summary.count("trainer.local_update") == 4


def test_control_plane_metrics_endpoint(cross_silo_run):
    """GET /metrics returns valid Prometheus text with a Counter, Gauge and
    Histogram populated by the federated run."""
    from fedml_tpu.scheduler.control_plane import ControlPlaneServer

    _, run_id = cross_silo_run
    srv = ControlPlaneServer(master=None).start()
    try:
        with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
    finally:
        srv.stop()
    lines = text.splitlines()
    for line in lines:
        if line and not line.startswith("#"):
            assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"
    assert "# TYPE fedml_rounds_completed_total counter" in lines
    assert "# TYPE fedml_current_round gauge" in lines
    assert "# TYPE fedml_round_seconds histogram" in lines
    assert f'fedml_rounds_completed_total{{run_id="{run_id}"}} 2' in lines
    assert f'fedml_round_seconds_count{{run_id="{run_id}"}} 2' in lines
    # trainer histogram populated by the run's local updates (the model
    # label is shared across tests in this process, so >=, not ==)
    counts = [int(l.rsplit(" ", 1)[1]) for l in lines if l.startswith(
        'fedml_trainer_local_update_seconds_count{model="lr"}')]
    assert counts and counts[0] >= 4


# -- mlops lifecycle isolation ----------------------------------------------

def test_mlops_reset_isolation(tmp_path, args_factory):
    from fedml_tpu.core import mlops

    dir_a, dir_b = tmp_path / "a", tmp_path / "b"
    seen_a = []
    mlops.init(args_factory(enable_tracking=True, run_id="runA",
                            log_file_dir=str(dir_a)))
    mlops.add_sink(lambda kind, rec: seen_a.append(rec))
    mlops.log({"x": 1})
    handle_a = mlops._state["files"]["metrics"]
    assert not handle_a.closed

    # back-to-back init: files from run A are closed, sinks cleared
    mlops.init(args_factory(enable_tracking=True, run_id="runB",
                            log_file_dir=str(dir_b)))
    assert handle_a.closed, "init() must close the previous run's files"
    mlops.log({"y": 2})
    assert len(seen_a) == 1, "run A's sink must not see run B's records"
    recs_a = [json.loads(l) for l in open(dir_a / "metrics.jsonl")]
    recs_b = [json.loads(l) for l in open(dir_b / "metrics.jsonl")]
    assert [r["run_id"] for r in recs_a] == ["runA"]
    assert [r["run_id"] for r in recs_b] == ["runB"]

    # shutdown() disables emission and releases files; double call is safe
    mlops.shutdown()
    mlops.shutdown()
    mlops.log({"z": 3})
    assert len([json.loads(l) for l in open(dir_b / "metrics.jsonl")]) == 1
    assert mlops._state["files"] == {} and mlops._state["sinks"] == []


# -- perf stats --------------------------------------------------------------

def test_perf_stats_ts_mono_and_priming(monkeypatch):
    from fedml_tpu.core.mlops import perf_stats

    s1 = perf_stats.system_snapshot()
    s2 = perf_stats.system_snapshot()
    assert "ts_mono" in s1 and s2["ts_mono"] >= s1["ts_mono"]

    import psutil

    calls = []
    real = psutil.cpu_percent
    monkeypatch.setattr(psutil, "cpu_percent",
                        lambda interval=None: calls.append(1) or
                        real(interval=interval))
    d = perf_stats.PerfStatsDaemon(interval_s=0.05).start()
    time.sleep(0.4)
    d.stop()
    assert d.samples, "no samples collected"
    # the sampler primed the counter BEFORE the first snapshot: at least
    # one more cpu_percent call than samples taken
    assert len(calls) >= len(d.samples) + 1
    assert all("ts_mono" in s for s in d.samples)
    mono = [s["ts_mono"] for s in d.samples]
    assert mono == sorted(mono)


# -- log daemon crash-resume -------------------------------------------------

def test_log_daemon_killed_mid_file_resumes_exactly(tmp_path):
    """A daemon that dies between chunk uploads must resume at the first
    unshipped chunk: the consolidated upload ends up with every line
    exactly once — none duplicated, none dropped."""
    from fedml_tpu.core.mlops.log_daemon import MLOpsRuntimeLogDaemon

    src = tmp_path / "run.log"
    n = 23
    src.write_text("".join(f"line {i}\n" for i in range(n)))
    updir = tmp_path / "uploaded"
    updir.mkdir()

    def uploader_for(crash_after):
        state = {"chunks": 0}

        def upload(run_id, lines):
            if state["chunks"] == crash_after:
                raise RuntimeError("killed mid-file")
            state["chunks"] += 1
            with open(updir / f"{run_id}.log", "a") as f:
                f.writelines(lines)

        return upload

    d = MLOpsRuntimeLogDaemon("rx", str(src),
                              uploader=uploader_for(crash_after=2),
                              chunk_lines=4)
    with pytest.raises(RuntimeError):
        d.ship_once()  # dies after shipping 2 chunks (8 lines)
    shipped = (updir / "rx.log").read_text().splitlines()
    assert shipped == [f"line {i}" for i in range(8)]

    # a NEW daemon (fresh process) resumes from the persisted cursor
    d2 = MLOpsRuntimeLogDaemon("rx", str(src),
                               uploader=uploader_for(crash_after=99),
                               chunk_lines=4)
    assert d2.ship_once() == n - 8
    shipped = (updir / "rx.log").read_text().splitlines()
    assert shipped == [f"line {i}" for i in range(n)]


# -- llm engine metrics ------------------------------------------------------

class _StubBundle:
    """Minimal bundle: uniform logits — enough to drive the decode loop."""

    input_shape = (16,)

    def apply(self, variables, x, train=False):
        import jax.numpy as jnp

        b, t = x.shape
        return jnp.zeros((b, t, 11)), None


def test_llm_engine_populates_metrics():
    from fedml_tpu.serving.llm_engine import BatchedLLMEngine

    reg = metrics_mod.REGISTRY.collect()
    ttft = reg["fedml_llm_ttft_seconds"].labels(engine="batched")
    tokens = reg["fedml_llm_tokens_total"].labels(engine="batched")
    ttft_before, tokens_before = ttft.count, tokens.value

    eng = BatchedLLMEngine(_StubBundle(), {}, max_batch=2, window=16)
    try:
        out = eng.generate([1, 2, 3], max_new=5, timeout=60.0)
        assert len(out) == 8
    finally:
        eng.stop()
    assert ttft.count == ttft_before + 1
    assert tokens.value == tokens_before + 5
    steps = reg["fedml_llm_decode_step_seconds"].labels(engine="batched")
    assert steps.count >= 5


# -- trace summarize CLI -----------------------------------------------------

def test_trace_summarize_cli(tmp_path, cross_silo_run):
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    res = CliRunner().invoke(
        cli, ["trace", "summarize", "--log-dir", str(tmp_path)])
    assert res.exit_code == 0, res.output
    assert "train_round" in res.output and "fed_run" in res.output
    res = CliRunner().invoke(cli, ["metrics"])
    assert res.exit_code == 0, res.output
    assert "# TYPE fedml_rounds_completed_total counter" in res.output


# -- jax profiler hook -------------------------------------------------------

def test_trainer_jax_profile_capture(tmp_path):
    """profile_trace_dir: the first N local updates run inside
    jax.profiler.trace and land a capture on disk."""
    import os

    from fedml_tpu.ml.trainer.default_trainer import _maybe_jax_profile

    class _Args:
        profile_trace_dir = str(tmp_path / "prof")
        profile_trace_steps = 1

    import jax.numpy as jnp

    state = {}
    with _maybe_jax_profile(_Args(), state):
        jnp.ones(8).sum().block_until_ready()
    assert state["captured"] == 1
    captured = [f for r, _, fs in os.walk(_Args.profile_trace_dir)
                for f in fs]
    assert any(f.endswith(".xplane.pb") for f in captured), captured
    # budget exhausted: the next update is NOT captured
    with _maybe_jax_profile(_Args(), state):
        pass
    assert state["captured"] == 1


# -- metrics plane under concurrency (PR 9 satellite) ------------------------

def test_histogram_concurrent_observe_consistency():
    """A scrape racing multi-threaded observe() must stay internally
    consistent: bucket counts cumulative and monotone, and the implicit
    +Inf bucket exactly equal to the snapshot's count."""
    r = metrics_mod.MetricsRegistry()
    h = r.histogram("race_seconds", "x", buckets=(0.1, 1.0, 10.0))
    stop = threading.Event()
    errors = []

    def writer(seed):
        vals = (0.05, 0.5, 5.0, 50.0)
        i = seed
        while not stop.is_set():
            h.observe(vals[i % 4])
            i += 1

    def scraper():
        while not stop.is_set():
            try:
                pairs, _s, count = h.labels().snapshot()
                cums = [c for _b, c in pairs]
                assert cums == sorted(cums), f"non-monotone: {cums}"
                assert pairs[-1][0] == float("inf")
                assert pairs[-1][1] == count, \
                    f"+Inf {pairs[-1][1]} != count {count}"
                # exposition renders from one locked snapshot too
                text = r.render_prometheus()
                m = re.search(
                    r'race_seconds_bucket\{le="\+Inf"\} (\d+)', text)
                c = re.search(r"race_seconds_count (\d+)", text)
                assert m and c and m.group(1) == c.group(1)
            except AssertionError as e:  # noqa: PERF203
                errors.append(e)
                return
    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    threads += [threading.Thread(target=scraper) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors[0]
    # quiescent cross-check: totals add up after the race
    pairs, _s, count = h.labels().snapshot()
    assert pairs[-1][1] == count > 0


def test_counter_concurrent_increments_exact():
    r = metrics_mod.MetricsRegistry()
    c = r.counter("c_race_total", "x", labels=("w",))
    N, T = 2000, 8

    def worker(k):
        child = c.labels(w=str(k % 2))
        for _ in range(N):
            child.inc()
    threads = [threading.Thread(target=worker, args=(k,)) for k in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    total = sum(c.labels(w=str(i)).value for i in (0, 1))
    assert total == N * T


def test_registry_reset_mid_scrape_safe():
    """reset() racing scrapes and writers must never raise or wedge —
    cached handles keep working, fresh get-or-create re-registers."""
    r = metrics_mod.MetricsRegistry()
    stop = threading.Event()
    errors = []

    def writer():
        while not stop.is_set():
            try:
                r.counter("reset_race_total").inc()
                r.histogram("reset_race_seconds",
                            buckets=(1.0,)).observe(0.5)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    def scraper():
        while not stop.is_set():
            try:
                text = r.render_prometheus()
                assert text == "" or text.endswith("\n")
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    def resetter():
        while not stop.is_set():
            r.reset()
            time.sleep(0.005)
    threads = ([threading.Thread(target=writer) for _ in range(3)]
               + [threading.Thread(target=scraper) for _ in range(2)]
               + [threading.Thread(target=resetter)])
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors[0]
    # the registry still works after the churn
    r.counter("reset_race_total").inc()
    assert "reset_race_total" in r.render_prometheus()
