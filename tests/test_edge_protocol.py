"""Cross-device protocol test (mirrors reference
`tests/android_protocol_test/test_protocol.py`): a JAX-free native edge
client federates with the standard server over the MQTT+object-store
transport — proving the message schema is engine-agnostic."""

import threading

import numpy as np
import pytest


def test_native_edge_clients_over_mqtt(args_factory, tmp_path):
    import fedml_tpu
    from fedml_tpu.core.alg_frame.server_aggregator import ServerAggregator
    from fedml_tpu.cross_device.edge_client import EdgeClientManager
    from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator
    from fedml_tpu.cross_silo.server.fedml_server_manager import (
        FedMLServerManager,
    )
    from fedml_tpu.native.native_trainer import NativeClientTrainer

    n_clients = 2
    args = fedml_tpu.init(args_factory(
        training_type="cross_device", client_num_in_total=n_clients,
        client_num_per_round=n_clients, comm_round=2, data_scale=0.4,
        learning_rate=0.1, momentum=0.9, run_id="edge1",
        object_store_dir=str(tmp_path)))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])

    # server evaluates with the native weight layout too
    class EdgeServerAggregator(ServerAggregator):
        def __init__(self, bundle, args):
            super().__init__(bundle, args)
            self._t = NativeClientTrainer(bundle, args)

        def test(self, test_data, device=None, args=None):
            self._t.params = {k: np.asarray(v) for k, v in
                              self.params.items()}
            return self._t.test(test_data)

    # initial global model = zeros in the native layout
    d = int(np.prod(dataset[2][0].shape[1:]))
    classes = dataset[-1]
    init = {"w1": np.zeros(0, np.float32), "b1": np.zeros(0, np.float32),
            "w2": np.zeros((d, classes), np.float32),
            "b2": np.zeros(classes, np.float32)}
    agg_impl = EdgeServerAggregator(bundle, args)
    agg_impl.set_model_params(init)
    aggregator = FedMLAggregator(args, agg_impl, dataset[3])
    server = FedMLServerManager(args, aggregator, rank=0,
                                client_num=n_clients, backend="MQTT_S3")

    clients = [EdgeClientManager(args, bundle, dataset, rank, n_clients + 1,
                                 backend="MQTT_S3")
               for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=20)
    assert aggregator.metrics_history, "server never evaluated"
    m = aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.3  # native LR on synthetic logistic data learns


def test_native_conv_edge_clients_over_mqtt(args_factory, tmp_path):
    """The same wire schema carries CONV models: native C++ LeNet clients
    federate over MQTT+object-store (closes the round-1 gap where the
    cross-device plane was MLP-only)."""
    import fedml_tpu
    from fedml_tpu.core.alg_frame.server_aggregator import ServerAggregator
    from fedml_tpu.cross_device.edge_client import EdgeClientManager
    from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator
    from fedml_tpu.cross_silo.server.fedml_server_manager import (
        FedMLServerManager,
    )
    from fedml_tpu.native import bindings
    from fedml_tpu.native.native_trainer import NativeClientTrainer

    n_clients = 2
    args = fedml_tpu.init(args_factory(
        training_type="cross_device", dataset="mnist", model="cnn",
        native_model="lenet", client_num_in_total=n_clients,
        client_num_per_round=n_clients, comm_round=2, data_scale=0.1,
        learning_rate=0.05, momentum=0.9, run_id="edge-conv",
        object_store_dir=str(tmp_path)))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])

    class EdgeServerAggregator(ServerAggregator):
        def __init__(self, bundle, args):
            super().__init__(bundle, args)
            self._t = NativeClientTrainer(bundle, args)

        def test(self, test_data, device=None, args=None):
            self._t.params = {k: np.asarray(v)
                              for k, v in self.params.items()}
            return self._t.test(test_data)

    d = int(np.prod(dataset[2][0].shape[1:]))
    agg_impl = EdgeServerAggregator(bundle, args)
    agg_impl.set_model_params(
        bindings.init_lenet_weights(d, dataset[-1], seed=0))
    aggregator = FedMLAggregator(args, agg_impl, dataset[3])
    server = FedMLServerManager(args, aggregator, rank=0,
                                client_num=n_clients, backend="MQTT_S3")
    clients = [EdgeClientManager(args, bundle, dataset, rank, n_clients + 1,
                                 backend="MQTT_S3")
               for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    assert aggregator.metrics_history, "server never evaluated"
    m = aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
    # conv kernels really traveled the wire
    assert "k1" in agg_impl.params and agg_impl.params["k1"].size > 0
