"""Scheduler substrate under the pod: the local launcher's runs db and
the ComputeResourceDB the gang allocator spends (race-safe allocate/
release, dead-owner reclamation, legacy-schema migration)."""

import os
import signal
import sqlite3
import subprocess
import threading
import time

import pytest

from fedml_tpu.scheduler import local_launcher
from fedml_tpu.scheduler.resource_db import ComputeResourceDB


@pytest.fixture
def home(tmp_path, monkeypatch):
    """The launcher's runs db lives under ~/.fedml_tpu — isolate it."""
    monkeypatch.setenv("HOME", str(tmp_path))
    return tmp_path


# ------------------------------------------------------- local launcher
def test_runs_db_register_update_list_roundtrip(home):
    local_launcher.register_run("run_a", "job-a", "/tmp/a.log", pid=1234)
    run = local_launcher.get_run("run_a")
    assert run["status"] == "RUNNING" and run["pid"] == 1234
    assert run["job_name"] == "job-a" and run["finished"] is None

    local_launcher.update_run_status("run_a", "FINISHED", returncode=0)
    run = local_launcher.get_run("run_a")
    assert run["status"] == "FINISHED" and run["returncode"] == 0
    assert run["finished"] is not None

    local_launcher.register_run("run_b", "job-b", "/tmp/b.log")
    runs = local_launcher.list_runs()
    assert [r["run_id"] for r in runs[:2]] == ["run_b", "run_a"]
    assert local_launcher.get_run("nope") is None


def test_stop_run_kills_live_process_group(home):
    proc = subprocess.Popen(["sleep", "30"], start_new_session=True)
    try:
        local_launcher.register_run("run_s", "sleeper", "/tmp/s.log",
                                    pid=proc.pid)
        assert local_launcher.stop_run("run_s")
        assert proc.wait(timeout=10) == -signal.SIGTERM
        run = local_launcher.get_run("run_s")
        assert run["status"] == "KILLED" and run["returncode"] == -15
        # not RUNNING any more → refuses instead of re-signalling the pid
        assert not local_launcher.stop_run("run_s")
        assert not local_launcher.stop_run("missing")
    finally:
        if proc.poll() is None:
            proc.kill()


def test_launch_job_local_roundtrip(home, tmp_path):
    job = tmp_path / "job.yaml"
    job.write_text("workspace: .\njob_name: hello\n"
                   "job: echo launched-ok\n")
    res = local_launcher.launch_job_local(str(job))
    assert res.returncode == 0
    assert "launched-ok" in open(res.log_path).read()
    assert local_launcher.get_run(res.run_id)["status"] == "FINISHED"

    bad = tmp_path / "bad.yaml"
    bad.write_text("workspace: .\njob_name: broken\njob: exit 3\n")
    res2 = local_launcher.launch_job_local(str(bad))
    assert res2.returncode == 3
    assert local_launcher.get_run(res2.run_id)["status"] == "FAILED"


# ------------------------------------------------------- resource db
def test_allocate_release_symmetry(tmp_path):
    db = ComputeResourceDB(str(tmp_path), total_slots=4)
    slots = db.allocate("r1", 3)
    assert slots == [0, 1, 2]
    assert db.report() == dict(db.report(), total=4, free=1, in_use=3)
    # gang does not fit → nothing is claimed (no partial allocation)
    assert db.allocate("r2", 2) == []
    assert db.report()["free"] == 1
    assert db.release("r1") == 3
    assert db.available_slots() == [0, 1, 2, 3]
    assert db.release("r1") == 0   # idempotent
    db.close()


def test_allocate_is_race_safe_across_threads(tmp_path):
    db = ComputeResourceDB(str(tmp_path), total_slots=4)
    results = {}
    start = threading.Barrier(8)

    def worker(i):
        start.wait()
        results[i] = db.allocate(f"r{i}", 1, pid=os.getpid())

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    won = [s for s in results.values() if s]
    assert len(won) == 4 and len([s for s in results.values() if not s]) == 4
    claimed = [s for slots in won for s in slots]
    assert sorted(claimed) == [0, 1, 2, 3]  # no slot double-assigned
    db.close()


def test_reclaim_frees_dead_pid_but_keeps_live_owner(tmp_path):
    db = ComputeResourceDB(str(tmp_path), total_slots=4)
    proc = subprocess.Popen(["sleep", "30"], start_new_session=True)
    try:
        assert db.allocate("alive", 2, pid=proc.pid)
        dead = subprocess.Popen(["true"])
        assert db.allocate("dead", 2) and db.set_pid("dead", dead.pid) == 2
        dead.wait()               # reap — a zombie still answers kill(pid, 0)
        assert ComputeResourceDB._pid_alive(dead.pid) is False
        assert db.reclaim_stale() == 2
        report = db.report()
        assert report["free"] == 2 and report["in_use"] == 2
        assert {d["run_id"] for d in report["devices"]
                if d["run_id"]} == {"alive"}
        # owner dies → its slots come back too
        proc.terminate()
        proc.wait(timeout=10)
        assert db.reclaim_stale() == 2
        assert db.report()["free"] == 4
    finally:
        if proc.poll() is None:
            proc.kill()
    db.close()


def test_reclaim_age_cutoff_applies_without_pid(tmp_path):
    db = ComputeResourceDB(str(tmp_path), total_slots=2)
    assert db.allocate("old", 2)          # no pid → only the age cutoff
    assert db.reclaim_stale(max_age_s=3600) == 0
    db.conn.execute("UPDATE devices SET allocated_ts = allocated_ts - 7200")
    assert db.reclaim_stale(max_age_s=3600) == 2
    assert db.report()["free"] == 2
    db.close()


def test_legacy_schema_gains_pid_column(tmp_path):
    legacy = sqlite3.connect(os.path.join(str(tmp_path), "resources.db"))
    legacy.execute(
        "CREATE TABLE devices (slot INTEGER PRIMARY KEY, kind TEXT, "
        "hbm_gb REAL, run_id TEXT, allocated_ts REAL)")
    legacy.execute("INSERT INTO devices VALUES (0,'slot',0.0,NULL,NULL)")
    legacy.commit()
    legacy.close()
    db = ComputeResourceDB(str(tmp_path))
    assert [d["pid"] for d in db.list_devices()] == [None]
    assert db.allocate("r", 1, pid=os.getpid()) == [0]
    assert db.list_devices()[0]["pid"] == os.getpid()
    db.close()
