"""fedml lint --taint: the privacy-taint tier (PRIV001-PRIV006), its
noqa/fingerprint/baseline integration, and the wire-contract ratchet."""

from __future__ import annotations

import json
import textwrap
import time
from pathlib import Path

from fedml_tpu.analysis import run_cli, run_lint
from fedml_tpu.analysis.engine import parse_contexts
from fedml_tpu.analysis.taint import run_taint_pass
from fedml_tpu.analysis.taint.wirecontract import (
    derive_contract,
    legal_keys,
    load_contract,
    write_contract,
)
from fedml_tpu.analysis.wholeprogram import build_index

REPO_ROOT = Path(__file__).resolve().parent.parent


def _write(tmp_path, relpath: str, source: str):
    f = tmp_path / relpath
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return f


def _lint(tmp_path, rules):
    return run_lint(root=tmp_path, rule_ids=rules)


def _ids(result):
    return [f.rule_id for f in result.findings]


# -- PRIV001: raw example escape ----------------------------------------------

PRIV001_LEAK = """\
    import logging

    def debug_round(loader):
        batch = loader.next_batch()
        logging.info("first batch %s", batch){noqa}
"""


def test_priv001_fires_on_logged_batch(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", PRIV001_LEAK.format(noqa=""))
    res = _lint(tmp_path, ["PRIV001"])
    assert _ids(res) == ["PRIV001"]
    assert "raw client example" in res.findings[0].message
    assert "summarize_payload" in res.findings[0].message


def test_priv001_fixed_by_declassifier(tmp_path):
    # len()/summarize_payload() are declassifiers: shape-level facts out
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import logging

        def debug_round(loader):
            batch = loader.next_batch()
            logging.info("batch of %d", len(batch))
            logging.info("batch %s", summarize_payload(batch))
    """)
    assert _ids(_lint(tmp_path, ["PRIV001"])) == []


def test_priv001_noqa_suppresses(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py",
           PRIV001_LEAK.format(noqa="  # fedml: noqa[PRIV001]"))
    assert _ids(_lint(tmp_path, ["PRIV001"])) == []


def test_priv001_flows_through_unknown_helper(tmp_path):
    # taint survives an unknown call: pretty(batch) is NOT a declassifier
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import logging

        def debug_round(loader):
            batch = loader.next_batch()
            text = pretty(batch)
            logging.info("rows %s", text)
    """)
    assert _ids(_lint(tmp_path, ["PRIV001"])) == ["PRIV001"]


def test_priv001_wire_sink(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        def upload(msg, train_data):
            msg.add_params("debug_rows", train_data)
    """)
    res = _lint(tmp_path, ["PRIV001"])
    assert _ids(res) == ["PRIV001"]
    assert "Message payload" in res.findings[0].message


# -- PRIV002: client-id in metrics labels -------------------------------------

PRIV002_LEAK = """\
    from fedml_tpu.core.mlops import metrics

    def record(client_id, dt):
        h = metrics.histogram("t", "t", labels=("client",))
        h.labels(client=client_id).observe(dt)
"""


def test_priv002_fires_on_client_id_label(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", PRIV002_LEAK)
    res = _lint(tmp_path, ["PRIV002"])
    assert _ids(res) == ["PRIV002"]
    assert "cardinality" in res.findings[0].message


def test_priv002_fixed_by_bounded_label(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", PRIV002_LEAK.replace(
        "client=client_id", 'client="all"'))
    assert _ids(_lint(tmp_path, ["PRIV002"])) == []


def test_priv002_ledger_is_sanctioned(tmp_path):
    # the run ledger is the per-client surface — client_id is legal there
    _write(tmp_path, "fedml_tpu/mod.py", """\
        def record(ledger, client_id, dt):
            ledger.event("server", "train", client=client_id, dt=dt)
    """)
    assert _ids(_lint(tmp_path, ["PRIV002"])) == []


# -- PRIV003: secret escape ---------------------------------------------------


def test_priv003_fires_on_logged_seed(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import logging

        def keygen(rng):
            prng_key = rng.PRNGKey(0)
            logging.info("key %s", prng_key)
    """)
    res = _lint(tmp_path, ["PRIV003"])
    assert _ids(res) == ["PRIV003"]
    assert "secret material" in res.findings[0].message


def test_priv003_share_channel_keys_sanctioned(tmp_path):
    # Shamir shares MAY travel on the named share-channel wire keys —
    # any other key is an escape
    _write(tmp_path, "fedml_tpu/mod.py", """\
        def distribute(msg, b_shares):
            msg.add_params("b_shares", b_shares)

        def leak(msg, b_shares):
            msg.add_params("debug_blob", b_shares)
    """)
    res = _lint(tmp_path, ["PRIV003"])
    assert len(res.findings) == 1
    assert res.findings[0].rule_id == "PRIV003"
    assert "leak" in res.findings[0].message


def test_priv003_digest_fixes(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", """\
        import logging

        def keygen(rng):
            prng_key = rng.PRNGKey(0)
            logging.info("key fp %s", hash(prng_key))
    """)
    assert _ids(_lint(tmp_path, ["PRIV003"])) == []


# -- PRIV004: SecAgg bypass ---------------------------------------------------

PRIV004_BYPASS = """\
    class EagerClientManager:
        def upload(self, msg, adapter):
            weights, n = adapter.train(0)
            msg.add_params("model_params", weights)
            msg.add_params("num_samples", int(n))
"""


def test_priv004_fires_on_unmasked_upload(tmp_path):
    _write(tmp_path, "fedml_tpu/cross_silo/secagg/mgr.py", PRIV004_BYPASS)
    res = _lint(tmp_path, ["PRIV004"])
    assert _ids(res) == ["PRIV004"]
    assert "mask funnel" in res.findings[0].message


def test_priv004_mask_funnel_fixes(tmp_path):
    _write(tmp_path, "fedml_tpu/cross_silo/secagg/mgr.py", """\
        class MaskedClientManager:
            def upload(self, msg, adapter, peers, seeds):
                weights, n = adapter.train(0)
                y = mask_upload(weights, 7, 1, peers, seeds)
                msg.add_params("masked_vector", y)
                msg.add_params("num_samples", int(n))
    """)
    assert _ids(_lint(tmp_path, ["PRIV004"])) == []


def test_priv004_scoped_to_secagg_client_paths(tmp_path):
    # same code OUTSIDE secagg/ (plain FedAvg) is not a bypass, and the
    # secagg SERVER broadcasting the aggregate is sanctioned
    _write(tmp_path, "fedml_tpu/cross_silo/client/mgr.py", PRIV004_BYPASS)
    _write(tmp_path, "fedml_tpu/cross_silo/secagg/srv.py",
           PRIV004_BYPASS.replace("EagerClientManager",
                                  "AggServerManager"))
    assert _ids(_lint(tmp_path, ["PRIV004"])) == []


# -- PRIV005: tensor repr in wire-path logs -----------------------------------

PRIV005_LEAK = """\
    import logging

    def sync(weights):
        logging.debug("global model %s", weights)
"""


def test_priv005_fires_on_wire_path_only(tmp_path):
    _write(tmp_path, "fedml_tpu/cross_silo/mod.py", PRIV005_LEAK)
    _write(tmp_path, "fedml_tpu/train/mod.py", PRIV005_LEAK)
    res = _lint(tmp_path, ["PRIV005"])
    assert _ids(res) == ["PRIV005"]
    assert res.findings[0].path == "fedml_tpu/cross_silo/mod.py"
    assert "summarize_payload" in res.findings[0].message


def test_priv005_summary_fixes(tmp_path):
    _write(tmp_path, "fedml_tpu/cross_silo/mod.py", """\
        import logging

        def sync(weights):
            logging.debug("global model %s", summarize_payload(weights))
    """)
    assert _ids(_lint(tmp_path, ["PRIV005"])) == []


# -- PRIV006: the wire-contract ratchet ---------------------------------------

MANAGER = """\
    class FooManager:
        def send(self):
            msg = Message("SYNC", 0, 1)
            msg.add_params("custom_key", 1)
            return msg
"""


def _derived(tmp_path):
    contexts, errors = parse_contexts(Path(tmp_path), None)
    assert not errors
    return derive_contract(contexts, build_index(contexts))


def test_priv006_new_key_flagged_until_committed(tmp_path):
    _write(tmp_path, "fedml_tpu/mgr.py", MANAGER)
    res = _lint(tmp_path, ["PRIV006"])
    assert "PRIV006" in _ids(res)
    assert any("custom_key" in f.message and "[SYNC]" in f.message
               for f in res.findings)
    assert any("no committed wire contract" in n for n in res.notes)
    # commit the derived contract → the ratchet goes quiet
    write_contract(tmp_path, _derived(tmp_path))
    res = _lint(tmp_path, ["PRIV006"])
    assert _ids(res) == []
    assert res.notes == []


def test_priv006_unresolvable_key_always_reports(tmp_path):
    _write(tmp_path, "fedml_tpu/mgr.py", """\
        class FooManager:
            def send(self, key):
                msg = Message("SYNC", 0, 1)
                msg.add_params(key, 1)
                return msg
    """)
    write_contract(tmp_path, _derived(tmp_path))
    res = _lint(tmp_path, ["PRIV006"])
    assert _ids(res) == ["PRIV006"]
    assert "cannot be resolved" in res.findings[0].message


def test_priv006_stale_committed_entry_noted(tmp_path):
    _write(tmp_path, "fedml_tpu/mgr.py", MANAGER)
    contract = _derived(tmp_path)
    contract["managers"]["FooManager"]["SYNC"].append("gone_key")
    write_contract(tmp_path, contract)
    res = _lint(tmp_path, ["PRIV006"])
    assert _ids(res) == []
    assert any("no longer derived" in n and "gone_key" in n
               for n in res.notes)


def test_legal_keys_unknown_manager_falls_back_to_union(tmp_path):
    _write(tmp_path, "fedml_tpu/mgr.py", MANAGER)
    write_contract(tmp_path, _derived(tmp_path))
    contract = load_contract(tmp_path)
    assert "custom_key" in legal_keys(contract, "FooManager", "SYNC")
    assert "custom_key" not in legal_keys(contract, "FooManager", "OTHER")
    # subclass the static pass never saw: union fallback, no false alarm
    assert "custom_key" in legal_keys(contract, "SubFooManager", "SYNC")
    assert "nope" not in legal_keys(contract, "SubFooManager", "SYNC")


# -- tier integration ---------------------------------------------------------


def test_taint_flag_enables_the_tier(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", PRIV001_LEAK.format(noqa=""))
    lines = []
    code = run_cli(root=str(tmp_path), taint=True, fmt="json",
                   echo=lines.append)
    assert code == 1
    report = json.loads("\n".join(lines))
    assert "PRIV001" in {f["rule"] for f in report["findings"]}
    # without the flag (and no PRIV rule filter) the tier stays off
    assert run_cli(root=str(tmp_path), echo=lambda *_: None) == 0


def test_sarif_export_renders_findings(tmp_path):
    _write(tmp_path, "fedml_tpu/mod.py", PRIV001_LEAK.format(noqa=""))
    sarif_path = tmp_path / "lint.sarif"
    code = run_cli(root=str(tmp_path), taint=True, sarif=str(sarif_path),
                   echo=lambda *_: None)
    assert code == 1
    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "fedml-lint"
    results = run["results"]
    assert any(r["ruleId"] == "PRIV001" for r in results)
    (r,) = [r for r in results if r["ruleId"] == "PRIV001"]
    assert r["baselineState"] == "new"
    loc = r["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "fedml_tpu/mod.py"
    assert r["partialFingerprints"]["fedmlLint/v1"]


def test_priv000_on_parse_error(tmp_path):
    _write(tmp_path, "fedml_tpu/bad.py", "def broken(:\n")
    findings, notes = run_taint_pass(tmp_path)
    assert [f.rule_id for f in findings] == ["PRIV000"]
    assert any("skipped" in n for n in notes)


def test_repo_is_taint_clean():
    # the tier landed by FIXING its findings: the real package must scan
    # clean against the committed contract, and fast (<60s)
    t0 = time.monotonic()
    findings, notes = run_taint_pass(REPO_ROOT)
    dt = time.monotonic() - t0
    assert findings == [], [f"{f.rule_id} {f.path}:{f.line}"
                            for f in findings[:10]]
    assert not [n for n in notes if not n.startswith("hint:")]
    assert dt < 60, f"taint pass took {dt:.1f}s"
