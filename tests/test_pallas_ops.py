"""Pallas kernels (interpret mode on CPU): parity with the jnp math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_weighted_average_flat_matches_einsum():
    from fedml_tpu.ops.pallas_ops import weighted_average_flat

    rng = np.random.RandomState(0)
    stacked = jnp.asarray(rng.randn(10, 3000), jnp.float32)  # non-multiple D
    w = jnp.asarray(rng.rand(10), jnp.float32)
    out = weighted_average_flat(stacked, w, interpret=True)
    expect = (w / w.sum()) @ stacked
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_agg_stacked_pallas_matches_tree_version():
    from fedml_tpu.ml.aggregator.agg_operator import agg_stacked
    from fedml_tpu.ops.pallas_ops import agg_stacked_pallas

    rng = np.random.RandomState(1)
    tree = {"w": jnp.asarray(rng.randn(6, 17, 5), jnp.float32),
            "b": jnp.asarray(rng.randn(6, 9), jnp.float32)}
    w = jnp.asarray(rng.rand(6) * 10, jnp.float32)
    a = agg_stacked(tree, w)
    b = agg_stacked_pallas(tree, w, interpret=True)
    for k in tree:
        np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                   atol=1e-5, rtol=1e-5)


def test_quantize_mask_fused_matches_two_step():
    from fedml_tpu.core.mpc.secagg import mask_model, quantize
    from fedml_tpu.ops.pallas_ops import quantize_mask

    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(777), jnp.float32)
    mask = jnp.asarray(rng.randint(0, 2**32, size=777, dtype=np.uint32))
    fused = quantize_mask(x, mask, interpret=True)
    two_step = mask_model(quantize({"x": x})["x"], mask)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(two_step))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("t,d,bq,bk", [
    (32, 16, 8, 8),      # exact block fit
    (40, 16, 16, 8),     # T needs padding to block_q
    (17, 8, 8, 8),       # ragged T
])
def test_flash_attention_matches_reference(causal, t, d, bq, bk):
    from fedml_tpu.ops.pallas_attention import flash_attention
    from fedml_tpu.parallel.ring_attention import reference_attention

    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 3, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(2, 3, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(2, 3, t, d), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk,
                          interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_off_tpu_fallback_matches():
    """interpret=None off-TPU routes to the jnp fallback, same math."""
    from fedml_tpu.ops.pallas_attention import flash_attention
    from fedml_tpu.parallel.ring_attention import reference_attention

    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 2, 24, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 24, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 24, 8), jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_flash_residuals_merge_matches_full():
    """Splitting keys in two, computing partials, and merging equals full
    attention — the ring-attention combine."""
    from fedml_tpu.ops.pallas_attention import (
        flash_attention_residuals, merge_attention_partials)
    from fedml_tpu.parallel.ring_attention import reference_attention

    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(2, 2, 16, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 2, 32, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 2, 32, 8), jnp.float32)
    pa = flash_attention_residuals(q, k[:, :, :16], v[:, :, :16],
                                   causal=False, interpret=True)
    pb = flash_attention_residuals(q, k[:, :, 16:], v[:, :, 16:],
                                   causal=False, interpret=True)
    o, l, m = merge_attention_partials(pa, pb)
    ref = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_int8_matmul_matches_dequant_reference():
    from fedml_tpu.ops.pallas_ops import int8_matmul
    from fedml_tpu.serving.quantization import quantize_matrix_int8

    rng = np.random.RandomState(6)
    w = jnp.asarray(rng.randn(48, 700), jnp.float32)  # N not block-aligned
    x = jnp.asarray(rng.randn(4, 48), jnp.float32)
    qs = quantize_matrix_int8(w)
    out = int8_matmul(x, qs["q"], qs["s"], interpret=True)
    ref = (x @ (qs["q"].astype(jnp.float32) * qs["s"][None, :]))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
    # and the quantization itself tracks the dense matrix
    assert float(jnp.max(jnp.abs(w - qs["q"].astype(jnp.float32)
                                 * qs["s"][None, :]))) < 0.05
