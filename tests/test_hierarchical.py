"""Geo-distributed aggregation hierarchy: edge → region → global.

Covers the topology derivation (``hier_layout``), runner dispatch, the
two-plane INPROC federation end-to-end, the per-tier robustness
composition (regional trimmed-mean quarantining a sign-flip silo; global
median surviving a WHOLE byzantine region), the cross-tier
``(region, silo, round)`` fold dedup, WAN delta codecs, the SIGKILLed
regional aggregator's crash-resume, and the ISSUE acceptance chaos soak
(3 regions x 5 silos on a wan-lossy WAN with one region partitioned
mid-round and one regional aggregator hard-killed).
"""

import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.mlops import ledger, metrics


# --------------------------------------------------------------- helpers
def _launch_hier(args_factory, run_id, *, n, regions, comm_round=2,
                 adversaries=None, **kw):
    """Build (but do not start) a hierarchical federation runner.
    ``adversaries`` maps FLAT silo rank (global silo index + 1) → a
    chaos_trainer spec, independent of the region layout."""
    import fedml_tpu
    from fedml_tpu.core.distributed.communication.chaos import chaos_trainer
    from fedml_tpu.cross_silo.runner import build_cross_silo_runner
    from fedml_tpu.ml.trainer.default_trainer import DefaultClientTrainer

    cfg = dict(training_type="cross_silo", backend="INPROC",
               client_num_in_total=n, client_num_per_round=n,
               comm_round=comm_round, data_scale=0.3, learning_rate=0.1,
               frequency_of_the_test=1, run_id=run_id, hier_regions=regions)
    cfg.update(kw)
    args = fedml_tpu.init(args_factory(**cfg))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    trainer = None
    if adversaries:
        adv = dict(adversaries)

        def trainer(rank):
            t = DefaultClientTrainer(bundle, args)
            return chaos_trainer(t, adv[rank]) if rank in adv else t

    runner = build_cross_silo_runner(args, None, dataset, bundle,
                                     client_trainer=trainer)
    return args, runner


def _chaos_of(com_manager):
    """Walk a ReliableCommManager/ChaosCommManager ``.inner`` chain down
    to the chaos layer — the partition lever."""
    from fedml_tpu.core.distributed.communication.chaos import (
        ChaosCommManager,
    )

    m = com_manager
    while m is not None and not isinstance(m, ChaosCommManager):
        m = getattr(m, "inner", None)
    assert m is not None, "no ChaosCommManager in the chain"
    return m


def _register_hier_wan_backend(name, drop_p=0.0, dup_p=0.0,
                               base_latency_s=0.0):
    """A chaos WAN plane for the hierarchy: every WAN node (global rank 0
    and the region uplinks) sends through a ChaosCommManager over the
    base-run_id INPROC channel.  FINISH is protected — termination fate
    belongs to the reliability layer under test, not the link."""
    from fedml_tpu.core.distributed.communication.chaos import (
        ChaosCommManager,
    )
    from fedml_tpu.core.distributed.communication.inprocess import (
        InProcCommManager,
    )
    from fedml_tpu.core.distributed.fedml_comm_manager import (
        register_comm_backend,
    )
    from fedml_tpu.cross_silo.hierarchical.message_define import HierMessage

    def factory(args, rank=0, size=0):
        inner = InProcCommManager(rank, size, str(args.run_id))
        return ChaosCommManager(
            inner, drop_p=drop_p, dup_p=dup_p,
            base_latency_s=base_latency_s, seed=300 + rank,
            protect_types={HierMessage.MSG_TYPE_G2R_FINISH})

    register_comm_backend(name, factory)


def _counter(name, **labels):
    return metrics.REGISTRY.collect()[name].labels(**labels).value


# ------------------------------------------------- layout and dispatch
def test_hier_layout_and_dispatch(args_factory):
    from fedml_tpu.cross_silo.hierarchical.runner import (
        HierarchicalFederationRunner,
        hier_layout,
    )
    from fedml_tpu.cross_silo.runner import build_cross_silo_runner

    # contiguous slices, remainder spread over the FIRST regions
    layout = hier_layout(args_factory(client_num_in_total=7, hier_regions=3))
    assert [name for name, _ in layout] == ["r0", "r1", "r2"]
    assert [silos for _, silos in layout] == [[0, 1, 2], [3, 4], [5, 6]]
    named = hier_layout(args_factory(client_num_in_total=4, hier_regions=2,
                                     hier_region_names=["eu", "us"]))
    assert [name for name, _ in named] == ["eu", "us"]
    assert [silos for _, silos in named] == [[0, 1], [2, 3]]
    with pytest.raises(ValueError):
        hier_layout(args_factory(hier_regions=1))
    with pytest.raises(ValueError):
        hier_layout(args_factory(client_num_in_total=2, hier_regions=3))
    with pytest.raises(ValueError):
        hier_layout(args_factory(client_num_in_total=4, hier_regions=2,
                                 hier_region_names=["only_one"]))

    # hier_regions >= 2 dispatches to the hierarchy (INPROC only)
    runner = build_cross_silo_runner(
        args_factory(training_type="cross_silo", client_num_in_total=4,
                     hier_regions=2, backend="INPROC"),
        None, (None,) * 4, None)
    assert isinstance(runner, HierarchicalFederationRunner)
    assert runner.n_regions == 2
    with pytest.raises(NotImplementedError):
        build_cross_silo_runner(
            args_factory(training_type="cross_silo", client_num_in_total=4,
                         hier_regions=2, backend="GRPC"),
            None, (None,) * 4, None)


# ------------------------------------------- cross-tier fold dedup unit
def test_global_fold_dedup_keeps_first_and_audits_triples(args_factory):
    """The global ingest path's dedup domain: keep-first on
    ``(region, fold_round)``, PLUS the ``(region, silo, round)`` triple
    audit — a re-computed fold (post-crash regional re-fold under a NEW
    fold_round) overlapping ANY already-counted silo upload is rejected
    whole, so a silo upload is never double-counted into the global
    model."""
    import fedml_tpu
    from fedml_tpu.core.distributed.communication.message import Message
    from fedml_tpu.cross_silo.hierarchical.global_server_manager import (
        GlobalServerManager,
    )
    from fedml_tpu.cross_silo.hierarchical.message_define import HierMessage
    from fedml_tpu.cross_silo.message_define import MyMessage
    from fedml_tpu.cross_silo.server.fedml_aggregator import FedMLAggregator
    from fedml_tpu.ml.trainer.default_trainer import DefaultServerAggregator

    import jax

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=3,
        client_num_per_round=3, min_aggregation_clients=3,
        run_id="hier_dedup_unit"))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    impl = DefaultServerAggregator(bundle, args)
    impl.set_model_params(bundle.init_variables(jax.random.PRNGKey(0)))
    agg = FedMLAggregator(args, impl, dataset[3])
    gm = GlobalServerManager(args, agg, rank=0, client_num=3,
                             backend="INPROC")
    gm.is_initialized = True
    model = impl.get_model_params()

    def fold(sender, fold_round, pairs):
        msg = Message(HierMessage.MSG_TYPE_R2G_REGION_FOLD, sender, 0)
        msg.add_params(HierMessage.MSG_ARG_KEY_REGION, f"r{sender}")
        msg.add_params(MyMessage.MSG_ARG_KEY_MODEL_PARAMS, model)
        msg.add_params(MyMessage.MSG_ARG_KEY_ROUND, int(fold_round))
        msg.add_params(HierMessage.MSG_ARG_KEY_N_SILOS, len(pairs))
        msg.add_params(HierMessage.MSG_ARG_KEY_SILO_ROUNDS,
                       [[int(r), int(t)] for r, t in pairs])
        msg.add_params(MyMessage.MSG_ARG_KEY_NUM_SAMPLES, 8.0)
        return msg

    dup0 = _counter("fedml_region_folds_total", run_id="hier_dedup_unit",
                    outcome="duplicate")
    # first fold from region 1 folds
    gm.handle_message_region_fold(fold(1, 0, [(1, 0), (2, 0)]))
    assert agg.receive_count() == 1
    # exact retransmit: keep-first on (region, fold_round)
    gm.handle_message_region_fold(fold(1, 0, [(1, 0), (2, 0)]))
    assert agg.receive_count() == 1
    # re-computed fold under a NEW fold_round but overlapping the
    # already-counted (region 1, silo 1, round 0) triple: rejected whole
    args.round_idx = 1
    gm.handle_message_region_fold(fold(1, 1, [(1, 0), (2, 1)]))
    assert agg.receive_count() == 1
    assert _counter("fedml_region_folds_total", run_id="hier_dedup_unit",
                    outcome="duplicate") == dup0 + 2
    args.round_idx = 0
    # the same silo rounds from a DIFFERENT region are a different domain
    gm.handle_message_region_fold(fold(2, 0, [(1, 0), (2, 0)]))
    assert agg.receive_count() == 2
    # a fold claiming a FUTURE segment is dropped outright
    gm.handle_message_region_fold(fold(3, 7, [(1, 7)]))
    assert agg.receive_count() == 2
    # past the staleness cutoff: expired, never folded
    exp0 = _counter("fedml_region_folds_total", run_id="hier_dedup_unit",
                    outcome="expired")
    args.round_idx = gm._staleness_cutoff + 5
    gm.handle_message_region_fold(
        fold(3, 1, [(1, 1)]))
    assert agg.receive_count() == 2
    assert _counter("fedml_region_folds_total", run_id="hier_dedup_unit",
                    outcome="expired") == exp0 + 1
    gm.finish()


# ------------------------------------------------ end-to-end federation
def test_hier_two_tier_federation_converges(args_factory, tmp_path):
    """2 regions x 2 silos: every global round closes on one pre-reduced
    fold per region, the WAN byte plane (base run_id) is separate from
    the LAN planes, and the ledger's per-tier round anatomy renders the
    region tree."""
    from fedml_tpu.cli.cli import cli
    from click.testing import CliRunner

    args, runner = _launch_hier(
        args_factory, "hier_basic", n=4, regions=2, comm_round=2,
        run_ledger=True, log_file_dir=str(tmp_path))
    m = runner.train()
    assert np.isfinite(m["test_loss"])
    hist = runner.global_manager.aggregator.metrics_history
    assert len(hist) == 2
    assert all(np.isfinite(r["test_loss"]) for r in hist)

    # exactly one fold per region per round, none duplicate-counted
    assert _counter("fedml_region_folds_total", run_id="hier_basic",
                    outcome="folded") == 2 * 2
    assert _counter("fedml_region_folds_total", run_id="hier_basic",
                    outcome="duplicate") == 0
    assert runner.global_manager.aggregator.duplicate_uploads == 0

    # WAN accounting: the base run_id carries ONLY the WAN plane — one
    # fold per region per round up, one segment per region per round
    # down — while silo traffic lands on the per-region LAN run_ids
    wan_up = _counter("fedml_wan_bytes_total", run_id="hier_basic",
                      direction="up")
    wan_down = _counter("fedml_wan_bytes_total", run_id="hier_basic",
                        direction="down")
    assert wan_up > 0 and wan_down > 0
    lan_up = sum(
        _counter("fedml_wire_bytes_total", run_id=f"hier_basic/lan-r{i}",
                 direction="up", codec="raw")
        for i in range(2))
    assert lan_up > 0
    # the hierarchy's reason to exist: 2 pre-reduced folds cross the WAN
    # per round where 4 silo uploads crossed the LAN
    assert wan_up < lan_up

    # per-tier round anatomy: the regions sub-tree, and the timeline line
    anatomy = ledger.load_anatomy(str(tmp_path))
    r0 = anatomy["rounds"][0]
    assert set(r0["regions"]) == {"r0", "r1"}
    for g in r0["regions"].values():
        assert g["n_silos"] == 2
        assert g["expected"] == 2
        assert g["outcome"] == "folded"
        assert g["nbytes"] > 0
    res = CliRunner().invoke(
        cli, ["rounds", "timeline", "--log-dir", str(tmp_path),
              "--round", "0"])
    assert res.exit_code == 0, res.output
    assert "region r0: 2/2 silos" in res.output
    assert "WAN delta" in res.output

    # the per-tier SLO indicators evaluate from the same artifacts
    res = CliRunner().invoke(
        cli, ["slo", "check", "--rules", "examples/slo_hierarchy.yaml",
              "--log-dir", str(tmp_path)])
    assert res.exit_code == 0, res.output


def test_hier_wan_codec_folds_as_delta(args_factory):
    """--hier-wan-compression int8: segments broadcast encoded, folds
    ship as int8 deltas against the decoded segment reference, and the
    run still converges."""
    args, runner = _launch_hier(
        args_factory, "hier_codec", n=4, regions=2, comm_round=2,
        hier_wan_compression="int8")
    m = runner.train()
    assert np.isfinite(m["test_loss"])
    assert len(runner.global_manager.aggregator.metrics_history) == 2
    assert _counter("fedml_region_folds_total", run_id="hier_codec",
                    outcome="folded") == 2 * 2
    # WAN wire bytes on the base run_id carry the codec label both ways
    up = _counter("fedml_wire_bytes_total", run_id="hier_codec",
                  direction="up", codec="int8")
    down = _counter("fedml_wire_bytes_total", run_id="hier_codec",
                    direction="down", codec="int8")
    assert up > 0 and down > 0
    # int8 fold deltas are materially smaller than the raw folds the
    # uncompressed run ships (codec test reuses the raw run's geometry)
    raw_up = _counter("fedml_wan_bytes_total", run_id="hier_basic",
                      direction="up")
    if raw_up > 0:
        assert up < raw_up


# ------------------------------------- per-tier robustness composition
@pytest.mark.slow
def test_regional_trimmed_mean_quarantines_sign_flip_silo(args_factory):
    """Region tier: with 3 silos per region and trimmed_mean:0.34 (one
    trim per side), a sign-flipping silo is trimmed INSIDE its region —
    the fold that crosses the WAN is already clean, and the run lands
    within 10% of the clean hierarchical baseline."""
    _, clean = _launch_hier(args_factory, "hier_tm_clean", n=6, regions=2,
                            comm_round=4)
    clean_loss = clean.train()["test_loss"]
    assert np.isfinite(clean_loss)

    _, runner = _launch_hier(
        args_factory, "hier_tm_adv", n=6, regions=2, comm_round=4,
        adversaries={1: "sign_flip"},
        hier_region_robust_agg="trimmed_mean:0.34")
    loss = runner.train()["test_loss"]
    hist = runner.global_manager.aggregator.metrics_history
    assert len(hist) == 4
    assert all(np.isfinite(r["test_loss"]) for r in hist)
    assert loss <= 1.1 * clean_loss, (loss, clean_loss)


@pytest.mark.slow
def test_global_median_survives_whole_byzantine_region(args_factory):
    """Global tier: when EVERY silo of one region sign-flips, the
    regional robust op cannot help (the fold itself is poisoned) — but
    the poisoned region is one outlier among 3 at the global median, and
    the run stays within 10% of the clean hierarchical baseline."""
    _, clean = _launch_hier(args_factory, "hier_md_clean", n=6, regions=3,
                            comm_round=4)
    clean_loss = clean.train()["test_loss"]
    assert np.isfinite(clean_loss)

    # region r0 = flat silos 1 and 2 — the whole region is byzantine
    _, runner = _launch_hier(
        args_factory, "hier_md_adv", n=6, regions=3, comm_round=4,
        adversaries={1: "sign_flip", 2: "sign_flip"},
        hier_global_robust_agg="median")
    loss = runner.train()["test_loss"]
    hist = runner.global_manager.aggregator.metrics_history
    assert len(hist) == 4
    assert all(np.isfinite(r["test_loss"]) for r in hist)
    assert loss <= 1.1 * clean_loss, (loss, clean_loss)


# ----------------------------------------------- region fault domains
@pytest.mark.slow
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_regional_aggregator_crash_resume_no_double_count(args_factory,
                                                          tmp_path):
    """A SIGKILLed regional aggregator (hard_kill: no goodbye, timers and
    heartbeats die) resumes from its round-boundary checkpoint: its silos
    kept running, the restarted manager re-solicits only what is missing,
    the global round closes normally, and NO silo upload is ever counted
    twice."""
    args, runner = _launch_hier(
        args_factory, "hier_crash", n=4, regions=2, comm_round=3,
        adversaries={3: "slow:1.5"},  # r1's first silo delays r1's fold
        checkpoint_dir=str(tmp_path / "ckpt"),
        heartbeat_interval_s=0.2, heartbeat_miss_threshold=5)
    runner.launch()
    gm = runner.global_manager
    # wait for r0's round-0 fold — r1 is still mid-segment behind its
    # slow silo when the crash lands
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and gm.aggregator.receive_count() < 1:
        time.sleep(0.05)
    assert gm.aggregator.receive_count() >= 1, "r0 never folded"

    runner.regions["r1"].hard_kill()
    runner.restart_region("r1")

    m = runner.wait(timeout=120)
    assert not runner._global_thread.is_alive(), "global run did not finish"
    hist = gm.aggregator.metrics_history
    assert len(hist) == 3, f"lost rounds: {len(hist)}/3"
    assert all(np.isfinite(r["test_loss"]) for r in hist)
    assert np.isfinite(m["test_loss"])
    # the crash-resumed region never double-counted: at most one counted
    # fold per (region, round), and no fold reached the aggregator twice
    assert gm.aggregator.duplicate_uploads == 0
    assert _counter("fedml_region_folds_total", run_id="hier_crash",
                    outcome="folded") <= 2 * 3


@pytest.mark.slow
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_hier_chaos_soak_partition_and_crash(args_factory, tmp_path):
    """ISSUE acceptance soak: 3 regions x 5 silos over a wan-lossy WAN
    plane (drop + dup + latency, reliable retransmits on).  Mid-run one
    region is PARTITIONED (its uplink drops everything; the global
    failure detector declares it dead and rounds close on the
    --min-regions quorum), one regional aggregator is hard-killed and
    restarted from its checkpoint, and the partition heals (rejoin +
    frontier catch-up).  The run converges with zero lost rounds and
    zero duplicate-counted uploads."""
    _register_hier_wan_backend("HIER_WAN_LOSSY", drop_p=0.03, dup_p=0.01,
                               base_latency_s=0.02)
    ROUNDS = 4
    args, runner = _launch_hier(
        args_factory, "hier_soak", n=15, regions=3, comm_round=ROUNDS,
        data_scale=0.2,
        hier_wan_backend="HIER_WAN_LOSSY", hier_wan_reliable=True,
        reliable_retx_initial_s=0.1, reliable_retx_max_s=1.0,
        min_regions=2, hier_round_deadline_s=8.0,
        round_deadline_grace_s=1.0,
        heartbeat_interval_s=0.25, heartbeat_miss_threshold=4,
        checkpoint_dir=str(tmp_path / "ckpt"))
    runner.launch()
    gm = runner.global_manager

    # let round 0 complete so every region is known-good first
    deadline = time.monotonic() + 90
    while (time.monotonic() < deadline
           and len(gm.aggregator.metrics_history) < 1):
        time.sleep(0.1)
    assert gm.aggregator.metrics_history, "round 0 never closed"

    # partition r2: its uplink's chaos layer drops EVERYTHING (folds,
    # heartbeats, retransmits) — the global detector must declare the
    # region dead and close rounds on the 2-of-3 quorum
    chaos = _chaos_of(runner.regions["r2"].uplink.com_manager)
    chaos.drop_p, chaos.dup_p = 1.0, 0.0
    time.sleep(2.5)  # > miss_threshold * interval: verdict lands

    # crash r1's regional aggregator and restart it from its checkpoint
    runner.regions["r1"].hard_kill()
    runner.restart_region("r1")

    # heal the partition: r2 heartbeats again → rejoin + catch-up
    chaos.drop_p = 0.03

    m = runner.wait(timeout=240)
    assert not runner._global_thread.is_alive(), "global run did not finish"
    hist = gm.aggregator.metrics_history
    assert len(hist) == ROUNDS, f"lost rounds: {len(hist)}/{ROUNDS}"
    assert all(np.isfinite(r["test_loss"]) for r in hist)
    assert np.isfinite(m["test_loss"])
    # zero duplicate-counted uploads: the lossy/duplicating WAN plus the
    # crash-resumed region produced retransmits and possibly re-computed
    # folds, but none reached the aggregator twice
    assert gm.aggregator.duplicate_uploads == 0
    assert _counter("fedml_region_folds_total", run_id="hier_soak",
                    outcome="folded") <= 3 * ROUNDS
    # the partitioned region was dropped by a fault-domain verdict
    dropped = sum(
        _counter("fedml_region_dropouts_total", run_id="hier_soak",
                 cause=cause)
        for cause in ("heartbeat", "deadline"))
    assert dropped >= 1, "the partitioned region was never dropped"
