"""End-to-end SP simulation tests — the convergence smoke mirroring the
reference CI (`smoke_test_pip_cli_sp_linux.yml`: FedAvg LR/MNIST must learn)."""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


def test_fedavg_lr_synthetic_converges(args_factory):
    metrics = _run(args_factory(comm_round=5, data_scale=0.3))
    # synthetic logistic data is linearly separable-ish: LR must beat chance
    assert metrics["test_acc"] > 0.3
    assert np.isfinite(metrics["test_loss"])


def test_fedavg_partial_participation(args_factory):
    metrics = _run(args_factory(client_num_in_total=8, client_num_per_round=3,
                                comm_round=8, data_scale=0.3))
    assert metrics["test_acc"] > 0.2


@pytest.mark.parametrize("opt", ["FedProx", "FedOpt", "FedNova", "SCAFFOLD",
                                 "FedDyn", "Mime"])
def test_all_optimizers_run_and_learn(args_factory, opt):
    metrics = _run(args_factory(federated_optimizer=opt, comm_round=6,
                                data_scale=0.3, server_lr=0.3))
    assert np.isfinite(metrics["test_loss"])
    assert metrics["test_acc"] > 0.15


def test_cnn_on_synthetic_mnist(args_factory):
    metrics = _run(args_factory(dataset="mnist", model="cnn", comm_round=2,
                                data_scale=0.05, batch_size=8))
    assert np.isfinite(metrics["test_loss"])


def test_hetero_partition_reproducible(args_factory):
    a1 = fedml_tpu.init(args_factory())
    d1 = fedml_tpu.data.load(a1)
    a2 = fedml_tpu.init(args_factory())
    d2 = fedml_tpu.data.load(a2)
    for cid in range(4):
        np.testing.assert_array_equal(d1[5][cid][1], d2[5][cid][1])


def test_contribution_assessment_end_to_end(args_factory):
    """Shapley/LOO contribution assessment via the ServerAggregator hook
    (reference core/contribution + server_aggregator.py:105-134)."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    for alg in ("GTG-Shapley", "LOO"):
        args = fedml_tpu.init(args_factory(
            contribution_alg=alg, client_num_in_total=3,
            client_num_per_round=3, comm_round=2, data_scale=0.2))
        device = fedml_tpu.device.get_device(args)
        dataset = fedml_tpu.data.load(args)
        bundle = fedml_tpu.model.create(args, dataset[-1])
        m = FedMLRunner(args, device, dataset, bundle).run()
        contrib = m.get("contributions")
        assert contrib and len(contrib) == 3, (alg, m.keys())
        assert all(np.isfinite(v) for v in contrib.values())


def test_hierarchical_silo_dist_adapter(args_factory):
    """TrainerDistAdapter with scenario=hierarchical builds a data-parallel
    mesh over local devices (DDP-equivalent, SURVEY §7 step 6)."""
    import fedml_tpu
    from fedml_tpu.cross_silo.client.trainer_dist_adapter import (
        TrainerDistAdapter,
    )

    import jax

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", scenario="hierarchical",
        n_proc_per_node=4, client_num_in_total=2, client_num_per_round=2,
        comm_round=1, data_scale=0.2, batch_size=16))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    adapter = TrainerDistAdapter(args, bundle, dataset)
    assert adapter.mesh is not None  # data axis over 4 virtual devices
    adapter.update_dataset(0)
    adapter.update_model(bundle.init_variables(jax.random.PRNGKey(0),
                                                batch_size=8))
    weights, n = adapter.train(round_idx=0)
    assert n > 0
    leaves = jax.tree_util.tree_leaves(weights)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
