"""Process-level serving replicas (VERDICT round-1 item 8a): separate OS
processes per replica, autoscaler-driven resizing, and a monitor that
restarts a killed replica (reference `device_model_deployment.py` +
`job_monitor.py` capability, container-free)."""

import os
import time

import numpy as np
import pytest

from fedml_tpu.scheduler.model_cards import ModelCardRegistry
from fedml_tpu.scheduler.replica_manager import ReplicaProcessManager


@pytest.fixture()
def card(tmp_path):
    rng = np.random.RandomState(0)
    model_dir = tmp_path / "model"
    model_dir.mkdir()
    np.savez(model_dir / "model.npz",
             w2=rng.randn(6, 3).astype(np.float32),
             b2=np.zeros(3, np.float32))
    reg = ModelCardRegistry(root=str(tmp_path / "registry"))
    reg.create("lin", str(model_dir))
    return reg


@pytest.mark.slow
def test_replicas_scale_route_and_self_heal(card):
    mgr = ReplicaProcessManager("lin", registry_root=card.root,
                                monitor_interval_s=0.2)
    try:
        assert mgr.scale_to(2) == 2
        # gateway round-robins across both replicas
        x = np.zeros((2, 6), np.float32).tolist()
        out = [mgr.predict({"inputs": x}) for _ in range(4)]
        assert all("predictions" in o for o in out)

        # kill one replica process → monitor restarts it
        mgr.start_monitor()
        victim = mgr.replicas[0]
        victim.proc.kill()
        victim.proc.wait(timeout=10)
        deadline = time.time() + 60
        while time.time() < deadline:
            if (mgr.live_count() == 2
                    and mgr.replicas[0] is not victim
                    and mgr.replicas[0] is not None):
                break
            time.sleep(0.2)
        assert mgr.live_count() == 2, mgr.stats()
        assert mgr.stats()["restarts"] >= 1
        # the healed fleet still serves
        assert "predictions" in mgr.predict({"inputs": x})

        # scale down
        assert mgr.scale_to(1) == 1
    finally:
        mgr.shutdown()
    assert mgr.live_count() == 0


@pytest.mark.slow
def test_autoscaler_drives_replica_processes(card):
    from fedml_tpu.scheduler.autoscaler import (
        AutoscalePolicy,
        ReplicaAutoscaler,
    )

    mgr = ReplicaProcessManager("lin", registry_root=card.root)
    try:
        mgr.scale_to(1)
        scaler = ReplicaAutoscaler(
            AutoscalePolicy(min_replicas=1, max_replicas=3,
                            target_latency_s=0.5,
                            target_qps_per_replica=10.0, cooldown_s=0.0,
                            scale_down_idle_ticks=1),
            apply_fn=mgr.scale_to)
        # load breach → autoscaler grows the PROCESS fleet
        n = scaler.observe(qps=25.0, latency_s=2.0)
        assert n >= 2
        assert mgr.live_count() == n
        # sustained idle → shrink
        for _ in range(3):
            scaler.observe(qps=0.1, latency_s=0.01)
        assert mgr.live_count() == scaler.replicas < n
    finally:
        mgr.shutdown()


class _FakeProc:
    def __init__(self, rc=None):
        self.returncode = rc
        self.killed = False

    def poll(self):
        return self.returncode

    def terminate(self):
        self.returncode = -15
        self.killed = True

    def wait(self, timeout=None):
        return self.returncode

    def kill(self):
        self.returncode = -9
        self.killed = True


def test_monitor_restart_does_not_resurrect_retired_slot():
    """Race: monitor sees replica[0] dead, spawns a replacement; meanwhile
    scale_to shrink retires slot 0 (sets it None).  The replacement must be
    discarded (and killed), not installed over the retirement."""
    import threading as th

    from fedml_tpu.scheduler import replica_manager as rm

    mgr = rm.ReplicaProcessManager("x", monitor_interval_s=0.05)
    dead = rm._Replica(_FakeProc(rc=1), port=1)
    mgr.replicas = [dead]

    spawning = th.Event()
    retired = th.Event()
    replacement = rm._Replica(_FakeProc(rc=None), port=2)

    def slow_spawn(slot):
        spawning.set()
        assert retired.wait(timeout=10)
        return replacement

    mgr._spawn = slow_spawn
    mon = th.Thread(target=mgr._monitor_loop, daemon=True)
    mon.start()
    try:
        assert spawning.wait(timeout=10)
        with mgr._lock:                  # shrink retires the slot mid-spawn
            mgr.replicas[0] = None
        retired.set()
        deadline = time.time() + 10
        while not replacement.proc.killed and time.time() < deadline:
            time.sleep(0.02)
        assert mgr.replicas[0] is None          # NOT resurrected
        assert replacement.proc.killed          # replacement cleaned up
    finally:
        mgr._stop.set()
        mon.join(timeout=5)
