"""Runtime wire-contract audit: opt-in recorder, the comm-manager send
hook, the observed-vs-committed contract gate, and the soak's overhead
budget."""

from __future__ import annotations

import json
import time

import pytest

from fedml_tpu.core.distributed.communication.message import Message
from fedml_tpu.core.mlops import metrics, wire_audit


@pytest.fixture
def armed():
    wire_audit.arm(True)
    try:
        yield
    finally:
        wire_audit.arm(False)
        wire_audit._armed = None   # back to the env toggle


def _upload(extra_key=None):
    m = Message("C2S_SEND_MODEL_TO_SERVER", 1, 0)
    m.add_params("model_params", {"w": [1.0, 2.0]})
    m.add_params("num_samples", 10)
    if extra_key:
        m.add_params(extra_key, "x")
    return m


def test_disarmed_records_nothing():
    wire_audit.arm(False)
    try:
        assert not wire_audit.enabled()
    finally:
        wire_audit._armed = None


def test_armed_records_keys_and_counts_violations(armed):
    wire_audit.observe("ClientMasterManager", _upload())
    wire_audit.observe("ClientMasterManager", _upload("raw_rows"))
    snap = wire_audit.snapshot()
    assert snap["contract_loaded"]
    assert snap["messages"] == 2
    assert snap["violations"] == [
        ["ClientMasterManager", "C2S_SEND_MODEL_TO_SERVER", "raw_rows", 1]]
    (rec,) = snap["observed"]
    assert "model_params" in rec["keys"] and "msg_type" in rec["keys"]


def test_violation_counter_pushes_deltas(armed):
    # the registry is process-wide — gate on the DELTA this test adds
    key = ("ClientMasterManager", "C2S_SEND_MODEL_TO_SERVER", "raw_rows")

    def value():
        ctr = metrics.REGISTRY.collect().get(
            "fedml_wire_contract_violations_total")
        child = ctr.children().get(key) if ctr else None
        return child.value if child else 0.0

    before = value()
    wire_audit.observe("ClientMasterManager", _upload("raw_rows"))
    wire_audit.snapshot()
    wire_audit.observe("ClientMasterManager", _upload("raw_rows"))
    wire_audit.snapshot()   # second push must add 1, not re-add 2
    assert value() - before == 2.0


def test_unknown_manager_uses_union_fallback(armed):
    # a subclass the static pass never named must not false-positive on
    # keys some reviewed manager emits
    wire_audit.observe("TotallyNewManager", _upload())
    snap = wire_audit.snapshot()
    assert snap["violations"] == []


def test_comm_manager_send_hook_records(armed, tmp_path):
    from fedml_tpu.arguments import Config
    from fedml_tpu.core.distributed.fedml_comm_manager import (
        FedMLCommManager,
    )

    mgr = FedMLCommManager(Config(run_id="wa_hook"), rank=0, size=1,
                           backend="INPROC")
    try:
        mgr.send_message(_upload())
    finally:
        mgr.finish()
    snap = wire_audit.snapshot()
    assert [r["manager"] for r in snap["observed"]] == ["FedMLCommManager"]


def test_dump_roundtrip_and_report_render(armed, tmp_path):
    wire_audit.observe("ClientMasterManager", _upload())
    path = wire_audit.dump(str(tmp_path / "wire.json"))
    snap = json.loads(open(path).read())
    assert snap["messages"] == 1
    ok = wire_audit.render_report(snap, extras=[])
    assert "observed keys ⊆ committed wire contract: OK" in ok
    bad = wire_audit.render_report(
        snap, extras=[("X", "T", "rogue_key")])
    assert "OUTSIDE THE COMMITTED WIRE CONTRACT" in bad


def test_taint_report_cli_gates_on_contract_and_overhead(armed, tmp_path):
    from click.testing import CliRunner

    from fedml_tpu.cli.cli import cli

    wire_audit.observe("ClientMasterManager", _upload())
    path = wire_audit.dump(str(tmp_path / "wire.json"))
    res = CliRunner().invoke(cli, ["taint", "report", "--snapshot", path,
                                   "--check-contract",
                                   "--max-overhead", "0.02"])
    assert res.exit_code == 0, res.output
    assert "OK" in res.output
    # a key no reviewed surface emits fails the gate
    wire_audit.reset()
    wire_audit.observe("ClientMasterManager", _upload("raw_rows"))
    path = wire_audit.dump(str(tmp_path / "rogue.json"))
    res = CliRunner().invoke(cli, ["taint", "report", "--snapshot", path,
                                   "--check-contract"])
    assert res.exit_code == 1, res.output
    assert "raw_rows" in res.output


def test_soak_overhead_under_budget(armed):
    """The CI soak in miniature: a message-dense send loop must keep the
    recorder's self-measured bookkeeping under 2% of wall time."""
    msg = _upload()
    t_end = time.monotonic() + 0.3
    n = 0
    while time.monotonic() < t_end:
        wire_audit.observe("ClientMasterManager", msg)
        n += 1
        # a real control plane serializes/trains between sends; the
        # budget is against a round profile, not a send-spin micro
        sum(range(20000))
    snap = wire_audit.snapshot()
    assert n > 100
    assert snap["violations"] == []
    assert snap["overhead_frac"] < 0.02, snap["overhead_frac"]
