"""Real-TCP MQTT transport (closes round-1 weak item 5: "PahoBroker /
real-MQTT path untested"): a standard MQTT 3.1.1 broker + client over real
sockets — wire frames, QoS1 acks, last-will liveness — driving the full
cross-silo federation."""

import json
import threading
import time

import numpy as np
import pytest

from fedml_tpu.core.distributed.communication.mqtt_s3.mini_mqtt import (
    MiniMqttBroker,
    MiniMqttClient,
)


@pytest.fixture()
def broker():
    b = MiniMqttBroker()
    yield b
    b.stop()


def test_wire_pubsub_and_qos1(broker):
    got = []
    sub = MiniMqttClient(client_id="sub")
    sub.on_message = lambda c, u, m: got.append((m.topic, m.payload))
    sub.connect(broker.host, broker.port)
    sub.loop_start()
    sub.subscribe("a/b", qos=1)
    time.sleep(0.2)

    pub = MiniMqttClient(client_id="pub")
    pub.connect(broker.host, broker.port)
    pub.loop_start()
    pub.publish("a/b", b"hello", qos=1)     # QoS1: broker must PUBACK
    pub.publish("other", b"nope", qos=0)    # not subscribed

    deadline = time.time() + 10
    while not got and time.time() < deadline:
        time.sleep(0.05)
    assert got == [("a/b", b"hello")]
    sub.disconnect()
    pub.disconnect()


def test_last_will_fires_on_abnormal_disconnect(broker):
    got = []
    watcher = MiniMqttClient(client_id="watcher")
    watcher.on_message = lambda c, u, m: got.append(
        json.loads(m.payload.decode()))
    watcher.connect(broker.host, broker.port)
    watcher.loop_start()
    watcher.subscribe("status/1", qos=1)
    time.sleep(0.2)

    dying = MiniMqttClient(client_id="dying")
    dying.will_set("status/1", json.dumps({"status": "OFFLINE"}).encode())
    dying.connect(broker.host, broker.port)
    dying.loop_start()
    dying.kill()                             # no DISCONNECT → will fires

    deadline = time.time() + 10
    while not got and time.time() < deadline:
        time.sleep(0.05)
    assert got and got[0]["status"] == "OFFLINE"
    watcher.disconnect()


def test_graceful_disconnect_suppresses_will(broker):
    got = []
    watcher = MiniMqttClient(client_id="w2")
    watcher.on_message = lambda c, u, m: got.append(m.payload)
    watcher.connect(broker.host, broker.port)
    watcher.loop_start()
    watcher.subscribe("status/2", qos=1)
    time.sleep(0.2)

    polite = MiniMqttClient(client_id="polite")
    polite.will_set("status/2", b"OFFLINE")
    polite.connect(broker.host, broker.port)
    polite.loop_start()
    polite.disconnect()                      # graceful → no will
    time.sleep(0.5)
    assert got == []
    watcher.disconnect()


def test_cross_silo_federation_over_real_tcp_mqtt(broker, args_factory,
                                                  tmp_path):
    """The full cross-silo round protocol over REAL MQTT sockets (the
    production transport shape: MQTT control plane + object-store bulk)."""
    import fedml_tpu
    from fedml_tpu.cross_silo.runner import init_client, init_server

    args = fedml_tpu.init(args_factory(
        training_type="cross_silo", client_num_in_total=2,
        client_num_per_round=2, comm_round=2, data_scale=0.2,
        run_id="realmqtt1",
        mqtt_host=broker.host, mqtt_port=broker.port,
        object_store_dir=str(tmp_path)))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])

    server = init_server(args, dataset, bundle, backend="MQTT_S3")
    clients = [init_client(args, dataset, bundle, rank, backend="MQTT_S3")
               for rank in (1, 2)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server.run()
    for t in threads:
        t.join(timeout=30)
    m = server.aggregator.metrics_history[-1]
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.2


def test_broker_qos2_handshake_raw_frames(broker):
    """A real paho client publishes QoS2 — the broker must answer
    PUBREC/PUBCOMP (not a bare PUBACK) and route only after PUBREL."""
    import socket
    import struct

    from fedml_tpu.core.distributed.communication.mqtt_s3.mini_mqtt import (
        _mk_packet,
        _mqtt_str,
        _read_packet,
        CONNACK,
        CONNECT,
        PUBCOMP,
        PUBLISH,
        PUBREC,
        PUBREL,
    )

    got = []
    sub = MiniMqttClient(client_id="q2sub")
    sub.on_message = lambda c, u, m: got.append(m.payload)
    sub.connect(broker.host, broker.port)
    sub.loop_start()
    sub.subscribe("q2/topic", qos=1)
    time.sleep(0.2)

    s = socket.create_connection((broker.host, broker.port), timeout=10)
    vh = _mqtt_str("MQTT") + bytes([4, 0x02]) + struct.pack(">H", 60)
    s.sendall(_mk_packet(CONNECT, 0, vh + _mqtt_str("rawq2")))
    ptype, _, body = _read_packet(s)
    assert ptype == CONNACK and body[1] == 0

    # QoS2 PUBLISH, pid 7
    s.sendall(_mk_packet(PUBLISH, 2 << 1,
                         _mqtt_str("q2/topic") + struct.pack(">H", 7)
                         + b"exactly-once"))
    ptype, _, body = _read_packet(s)
    assert ptype == PUBREC and struct.unpack(">H", body)[0] == 7
    time.sleep(0.3)
    assert got == []                      # not routed before PUBREL
    s.sendall(_mk_packet(PUBREL, 0x02, struct.pack(">H", 7)))
    ptype, _, body = _read_packet(s)
    assert ptype == PUBCOMP and struct.unpack(">H", body)[0] == 7

    deadline = time.time() + 10
    while not got and time.time() < deadline:
        time.sleep(0.05)
    assert got == [b"exactly-once"]
    s.close()
    sub.disconnect()


def test_qos1_broker_retransmits_until_puback(broker, monkeypatch):
    """Broker→subscriber QoS1 is PUBACK-tracked: a subscriber that loses
    its first PUBACK gets the message redelivered with the DUP flag
    (at-least-once — the redelivery semantics EdgeService/SlaveAgent
    dup-guards are written against)."""
    from fedml_tpu.core.distributed.communication.mqtt_s3 import mini_mqtt

    monkeypatch.setattr(mini_mqtt, "RETRY_INTERVAL_S", 0.3)

    class _DropFirstPuback(MiniMqttClient):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.dropped = 0
            self.pubacks_sent = 0

        def _send(self, data):
            from fedml_tpu.core.distributed.communication.mqtt_s3.mini_mqtt import (  # noqa: E501
                PUBACK,
            )

            if (data[0] >> 4) == PUBACK:
                if self.dropped == 0:
                    self.dropped += 1
                    return                  # swallow the first PUBACK
                self.pubacks_sent += 1
            super()._send(data)

    got = []
    sub = _DropFirstPuback(client_id="flaky-sub")
    sub.on_message = lambda c, u, m: got.append(m.payload)
    sub.connect(broker.host, broker.port)
    sub.loop_start()
    sub.subscribe("rtx/a", qos=1)
    time.sleep(0.2)

    pub = MiniMqttClient(client_id="pub-rtx")
    pub.connect(broker.host, broker.port)
    pub.loop_start()
    pub.publish("rtx/a", b"must-arrive", qos=1)

    # broker must redeliver (DUP) until a PUBACK lands; the client's
    # receiver-side dedup suppresses the duplicate from on_message
    deadline = time.time() + 10
    while sub.pubacks_sent < 1 and time.time() < deadline:
        time.sleep(0.05)
    assert sub.dropped == 1
    assert sub.pubacks_sent >= 1            # a redelivery was acked
    assert got == [b"must-arrive"]          # delivered exactly once
    sub.disconnect()
    pub.disconnect()


def test_qos1_client_retransmits_until_puback(broker, monkeypatch):
    """Client→broker QoS1: a publish whose handling is lost at the broker
    is retransmitted (DUP) by the client until the broker PUBACKs."""
    from fedml_tpu.core.distributed.communication.mqtt_s3 import mini_mqtt

    monkeypatch.setattr(mini_mqtt, "RETRY_INTERVAL_S", 0.3)
    orig = mini_mqtt.MiniMqttBroker._on_publish
    state = {"dropped": 0}

    def flaky_on_publish(self, sess, flags, body):
        if ((flags >> 1) & 0x03) == 1 and state["dropped"] == 0:
            state["dropped"] += 1
            return                          # lose the first QoS1 publish
        orig(self, sess, flags, body)

    monkeypatch.setattr(mini_mqtt.MiniMqttBroker, "_on_publish",
                        flaky_on_publish)

    got = []
    sub = MiniMqttClient(client_id="sub-crtx")
    sub.on_message = lambda c, u, m: got.append(m.payload)
    sub.connect(broker.host, broker.port)
    sub.loop_start()
    sub.subscribe("crtx/a", qos=0)
    time.sleep(0.2)

    pub = MiniMqttClient(client_id="pub-crtx")
    pub.connect(broker.host, broker.port)
    pub.loop_start()
    pub.publish("crtx/a", b"retry-me", qos=1)

    deadline = time.time() + 10
    while not got and time.time() < deadline:
        time.sleep(0.05)
    assert got == [b"retry-me"]
    assert state["dropped"] == 1
    with pub._inflight_lock:
        assert not pub._inflight_pub       # PUBACK cleared the in-flight slot
    sub.disconnect()
    pub.disconnect()
