"""Parallelism layer on the virtual 8-device CPU mesh: ring attention parity,
TP/FSDP sharding rules, pipeline schedule, MoE routing."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedml_tpu.ml.engine.mesh import build_mesh


def test_ring_attention_matches_full_attention():
    from fedml_tpu.parallel.ring_attention import (
        make_ring_attention_fn,
        reference_attention,
    )

    mesh = build_mesh({"seq": 4})
    rng = np.random.RandomState(0)
    b, h, t, d = 2, 2, 32, 8
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    for causal in (True, False):
        ring = make_ring_attention_fn(mesh, causal=causal)
        with mesh:
            out = jax.jit(ring)(q, k, v)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)


def test_tp_and_fsdp_sharding_rules():
    from fedml_tpu.parallel.sharding import make_param_shardings

    mesh = build_mesh({"data": 2, "model": 4})
    params = {
        "attn": {"query": {"kernel": jnp.zeros((128, 128))},
                 "out": {"kernel": jnp.zeros((128, 128))}},
        "mlp": {"Dense_0": {"kernel": jnp.zeros((128, 512))},
                "Dense_1": {"kernel": jnp.zeros((512, 128))}},
        "norm": {"scale": jnp.zeros((128,))},
    }
    sh = make_param_shardings(params, mesh, "tp_fsdp")
    assert sh["attn"]["query"]["kernel"].spec == P(None, "model")
    assert sh["attn"]["out"]["kernel"].spec == P("model", None)
    assert sh["mlp"]["Dense_0"]["kernel"].spec == P(None, "model")
    assert sh["mlp"]["Dense_1"]["kernel"].spec == P("model", None)
    # small norm param stays replicated
    assert sh["norm"]["scale"].spec == P()
    # fsdp-only: large kernels shard over data on an even axis
    sh2 = make_param_shardings(params, mesh, "fsdp")
    assert sh2["mlp"]["Dense_0"]["kernel"].spec in (P("data", None),
                                                    P(None, "data"))


def test_sharded_train_step_runs_dp_and_fsdp():
    import fedml_tpu
    from fedml_tpu.parallel.sharding import (
        batch_sharding,
        build_sharded_train_step,
    )

    args = fedml_tpu.Config(model="cnn", dataset="mnist", batch_size=16,
                            compute_dtype="float32", learning_rate=0.05)
    bundle = fedml_tpu.model.create(args, 10)
    mesh = build_mesh({"data": 8})
    variables = bundle.init_variables(jax.random.PRNGKey(0))
    for strategy in ("dp", "fsdp"):
        train_step, init_shardings, tx = build_sharded_train_step(
            bundle, args, mesh, strategy)
        shardings = init_shardings(variables)
        v = jax.device_put(variables, shardings)
        opt_state = tx.init(v["params"])
        batch = {
            "x": jax.device_put(
                jnp.zeros((16, 28, 28, 1)), batch_sharding(mesh)),
            "y": jax.device_put(jnp.zeros((16,), jnp.int32),
                                batch_sharding(mesh)),
            "mask": None,
        }
        step = jax.jit(train_step)
        with mesh:
            v2, opt_state, metrics = step(v, opt_state, batch,
                                          jax.random.PRNGKey(1))
        assert np.isfinite(float(metrics["loss"]))


def test_pipeline_matches_sequential():
    from fedml_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params

    mesh = build_mesh({"pipe": 4})
    rng = np.random.RandomState(0)
    d = 16

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"] + params["b"])

    stages = [{"w": jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32),
               "b": jnp.asarray(rng.randn(d) * 0.1, jnp.float32)}
              for _ in range(4)]
    stacked = stack_stage_params(stages)
    x = jnp.asarray(rng.randn(8, 4, d), jnp.float32)  # [M=8 microbatches, mb=4]

    pipe = make_pipeline_fn(stage_fn, mesh, n_microbatches=8)
    with mesh:
        out = jax.jit(pipe)(stacked, x)

    expect = x
    for s in stages:
        expect = jnp.tanh(expect @ s["w"] + s["b"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               atol=1e-5, rtol=1e-5)


def test_switch_moe_forward_and_balance():
    from fedml_tpu.parallel.expert_parallel import SwitchMoE

    moe = SwitchMoE(n_experts=4, d_ff=32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 8), jnp.float32)
    variables = moe.init(jax.random.PRNGKey(0), x)
    out, state = moe.apply(variables, x, mutable=["intermediates"])
    assert out.shape == x.shape
    aux = state["intermediates"]["moe_aux_loss"][0]
    assert np.isfinite(float(aux)) and float(aux) > 0.5  # ~1 when balanced


def test_ulysses_attention_matches_full_attention():
    from fedml_tpu.parallel.ring_attention import reference_attention
    from fedml_tpu.parallel.ulysses import make_ulysses_attention_fn

    mesh = build_mesh({"seq": 4})
    rng = np.random.RandomState(1)
    b, h, t, d = 2, 8, 32, 8  # heads (8) divisible by axis size (4)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    for causal in (True, False):
        uly = make_ulysses_attention_fn(mesh, causal=causal)
        with mesh:
            out = jax.jit(uly)(q, k, v)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)


def test_hybrid_mesh_single_slice_fallback():
    """On one slice (CPU test devices) the DCN axes collapse to size 1 and
    the same sharding program runs; collectives still compile over both
    axis names."""
    from fedml_tpu.ml.engine.mesh import build_hybrid_mesh

    import pytest as _pytest

    with _pytest.raises(ValueError, match="BOTH"):
        build_hybrid_mesh({"data": 2}, {"data": 4})
    mesh = build_hybrid_mesh({"model": 4}, {"data": 2})
    assert mesh.axis_names == ("model", "data")
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "model": 4, "data": 2}

    # psum over BOTH axes (the dp-over-dcn + tp-over-ici layout)
    @partial(jax.shard_map, mesh=mesh, in_specs=P("data", "model"),
             out_specs=P(None, None), check_vma=False)
    def total(x):
        return jax.lax.psum(jax.lax.psum(x, "model"), "data")

    x = jnp.arange(8.0).reshape(2, 4)
    out = jax.jit(total)(x)
    np.testing.assert_allclose(np.asarray(out)[0, 0], x.sum())


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_gradients_match_reference(causal):
    """Sequence-parallel training path: grads through the custom second-ring
    backward equal grads of plain full attention."""
    from fedml_tpu.parallel.ring_attention import (
        make_ring_attention_fn,
        reference_attention,
    )

    mesh = build_mesh({"seq": 4})
    rng = np.random.RandomState(1)
    b, h, t, d = 1, 2, 32, 8
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    w = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)  # fixed cotangent

    ring = make_ring_attention_fn(mesh, causal=causal)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    with mesh:
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_gradients_match_reference(causal):
    """The flash custom VJP (blockwise backward) equals autodiff of the
    naive formulation — run through the interpret-mode kernel on CPU."""
    from fedml_tpu.ops.pallas_attention import flash_attention
    from fedml_tpu.parallel.ring_attention import reference_attention

    rng = np.random.RandomState(2)
    b, h, t, d = 1, 2, 24, 8
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)
    w = jnp.asarray(rng.randn(b, h, t, d), jnp.float32)

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                              interpret=True)
        return jnp.sum(out * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_seq_parallel_lm_train_step_matches_full(strategy):
    """End-to-end sequence-parallel LM training: one jitted step over a
    seq=4 mesh (tokens sharded [B, T/4]) produces the same loss and updated
    params as the unsharded model, and training reduces the loss."""
    from fedml_tpu.parallel.seq_parallel import (
        build_seq_parallel_train_step, init_lm_params)

    mesh = build_mesh({"seq": 4})
    vocab, heads, t = 37, 4, 32
    params = init_lm_params(jax.random.PRNGKey(0), vocab, dim=32, layers=2,
                            heads=heads, max_len=t)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, size=(4, t)), jnp.int32)

    step_sp, tok_shard = build_seq_parallel_train_step(
        mesh, heads, strategy=strategy)
    step_full, _ = build_seq_parallel_train_step(mesh, heads,
                                                 strategy="full")
    with mesh:
        p_sp, loss_sp = step_sp(params, jax.device_put(tokens, tok_shard))
        p_full, loss_full = step_full(params, tokens)
        np.testing.assert_allclose(float(loss_sp), float(loss_full),
                                   rtol=1e-4)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4),
            p_sp, p_full)
        # a few more steps: the sharded path actually trains
        p, losses = p_sp, [float(loss_sp)]
        toks = jax.device_put(tokens, tok_shard)
        for _ in range(5):
            p, l = step_sp(p, toks)
            losses.append(float(l))
        assert losses[-1] < losses[0]


def test_seq_parallel_remat_matches_no_remat():
    """jax.checkpoint over blocks changes memory, not math."""
    from fedml_tpu.parallel.seq_parallel import (
        build_seq_parallel_train_step, init_lm_params)

    mesh = build_mesh({"seq": 4})
    params = init_lm_params(jax.random.PRNGKey(0), 31, dim=32, layers=2,
                            heads=4, max_len=16)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 31, (2, 16)))
    outs = []
    for remat in (False, True):
        step, shard = build_seq_parallel_train_step(mesh, 4, strategy="ring",
                                                    remat=remat)
        with mesh:
            p, loss = step(params, jax.device_put(tokens, shard))
        outs.append((p, float(loss)))
    assert outs[0][1] == pytest.approx(outs[1][1], rel=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=1e-5, rtol=1e-5),
        outs[0][0], outs[1][0])
