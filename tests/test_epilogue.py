"""Parity suite for the fused round epilogue (PR 14): every funnel the
kernel family replaced must produce the same numbers it did before —
fused == unfused within 1e-6 (bitwise where dtypes allow), against a
float64 numpy reference, across weighted/masked/bf16 trees, every
robust-agg operator, staleness-weighted async folds, and the
momentum/adam server-optimizer channels round-tripped against optax.
The pallas kernels run here in interpret mode (no TPU in CI); the jnp
fallback is the bit-contract both paths are held to.

Plus the cross-process compile-ahead proof: the warm pool's per-round
executables must land in (and load from) the shared AOT cache so a
second process skips trace+compile entirely.
"""

import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import fedml_tpu
from fedml_tpu.ml.aggregator.agg_operator import (
    FedMLAggOperator,
    agg_stacked,
    fold_buffer,
    mix_global,
    weighted_average,
)
from fedml_tpu.ml.aggregator.robust import stack_grad_list
from fedml_tpu.ops import epilogue as ep

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- helpers

def _stacked(c=5, dtype=jnp.float32, seed=0, with_int=False):
    """Model-shaped stacked tree with a leading client axis: matrix +
    bias + scalar-ish leaf, odd sizes to exercise lane padding."""
    rng = np.random.default_rng(seed)

    def mk(*shape):
        return jnp.asarray(rng.normal(size=(c,) + shape), dtype)

    tree = {"w": mk(7, 130), "b": mk(9), "s": mk()}
    if with_int:
        tree["steps"] = jnp.asarray(rng.integers(0, 50, size=(c,)),
                                    jnp.int32)
    return tree


def _weights(c=5, seed=1):
    return jnp.asarray(np.random.default_rng(seed).uniform(0.5, 3.0, c),
                       jnp.float32)


def _np_mean(stacked, weights):
    """float64 reference weighted mean (normalized weights)."""
    w = np.asarray(weights, np.float64)
    w = w / w.sum()

    def _leaf(x):
        xf = np.asarray(x, np.float64)
        return np.tensordot(w, xf, axes=(0, 0))

    return jax.tree_util.tree_map(_leaf, stacked)


def _assert_close(got, ref, atol=1e-6, rtol=1e-6):
    for g, r in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(np.asarray(g, np.float64),
                                   np.asarray(r, np.float64),
                                   atol=atol, rtol=rtol)


def _assert_bitwise(got, ref):
    for g, r in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ref)):
        assert g.dtype == r.dtype
        assert np.array_equal(np.asarray(g), np.asarray(r)), (g, r)


# ------------------------------------------------- weighted_reduce contract

def test_weighted_reduce_matches_numpy_f32():
    stacked, w = _stacked(), _weights()
    out = ep.weighted_reduce(stacked, w, prefer_pallas=False)
    _assert_close(out, _np_mean(stacked, w))


def test_weighted_reduce_bf16_casts_back():
    stacked, w = _stacked(dtype=jnp.bfloat16, seed=3), _weights()
    out = ep.weighted_reduce(stacked, w, prefer_pallas=False)
    for leaf in jax.tree_util.tree_leaves(out):
        assert leaf.dtype == jnp.bfloat16
    # accumulation happened in f32: only the final cast loses precision
    _assert_close(out, _np_mean(stacked, w), atol=1e-2, rtol=1e-2)


def test_weighted_reduce_int_leaf_keeps_f32():
    stacked, w = _stacked(with_int=True), _weights()
    out = ep.weighted_reduce(stacked, w, prefer_pallas=False)
    assert out["steps"].dtype == jnp.float32
    _assert_close(out["steps"], _np_mean(stacked, w)["steps"])


def test_masked_weights_exclude_clients():
    """Zero-weight clients must not influence the mean — the masked
    cohort form every padded plane relies on."""
    stacked, w = _stacked(c=6, seed=5), _weights(6)
    mask = jnp.asarray([0.0, 1.0, 1.0, 0.0, 1.0, 0.0], jnp.float32)
    masked = ep.weighted_reduce(stacked, w * mask, prefer_pallas=False)
    keep = [1, 2, 4]
    sub = jax.tree_util.tree_map(lambda x: x[jnp.asarray(keep)], stacked)
    ref = ep.weighted_reduce(sub, w[jnp.asarray(keep)],
                             prefer_pallas=False)
    _assert_close(masked, ref)


# ---------------------------------------- fused == unfused (compose parity)

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_fused_none_bitwise_equals_reduce_then_mix(dtype):
    """spec=none is reduce + mix_global collapsed into one pass — the
    composition must be BITWISE identical (same ops, same order)."""
    stacked, w = _stacked(dtype=dtype, seed=7), _weights()
    g = jax.tree_util.tree_map(lambda x: x[0], _stacked(dtype=dtype,
                                                        seed=8))
    lr = 0.5
    fused, st = ep.fused_epilogue(g, stacked, w, lr, ep.NONE_SPEC,
                                  prefer_pallas=False)
    assert st is None
    acc = ep.weighted_reduce(stacked, w, prefer_pallas=False)

    def _mix(gl, al):
        gf = gl.astype(jnp.float32)
        af = al.astype(jnp.float32)
        return (gf + jnp.float32(lr) * (af - gf)).astype(gl.dtype)

    _assert_bitwise(fused, jax.tree_util.tree_map(_mix, g, acc))


def test_fused_server_lr_one_replaces_global():
    stacked, w = _stacked(seed=11), _weights()
    g = jax.tree_util.tree_map(lambda x: x[0] * 0 + 99.0, stacked)
    fused, _ = ep.fused_epilogue(g, stacked, w, 1.0, ep.NONE_SPEC,
                                 prefer_pallas=False)
    # f32 mix g + 1·(acc − g) cancels around the magnitude of g (99):
    # replacement up to |g|·eps_f32, not bitwise
    _assert_close(fused, _np_mean(stacked, w), atol=2e-5, rtol=1e-5)


# ------------------------------------------------ pallas interpret parity

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_pallas_reduce_matches_jnp(dtype):
    stacked, w = _stacked(dtype=dtype, seed=13), _weights()
    pl = ep.weighted_reduce(stacked, w, prefer_pallas=True,
                            interpret=True)
    ref = ep.weighted_reduce(stacked, w, prefer_pallas=False)
    _assert_close(pl, ref, atol=2e-6, rtol=2e-6)


@pytest.mark.parametrize("opt", ["none", "sgd", "momentum", "adam"])
def test_pallas_epilogue_matches_jnp(opt):
    stacked, w = _stacked(seed=17), _weights()
    g = jax.tree_util.tree_map(lambda x: x[0], _stacked(seed=18))
    spec = ep.EpilogueSpec(opt=opt, lr=0.1)
    st = ep.init_opt_state(g, spec)
    pl, pl_st = ep.fused_epilogue(g, stacked, w, 0.7, spec, st,
                                  prefer_pallas=True, interpret=True)
    jn, jn_st = ep.fused_epilogue(g, stacked, w, 0.7, spec, st,
                                  prefer_pallas=False)
    _assert_close(pl, jn, atol=2e-6, rtol=2e-6)
    if st is not None:
        _assert_close(
            [l for l in jax.tree_util.tree_leaves(pl_st)],
            [l for l in jax.tree_util.tree_leaves(jn_st)],
            atol=2e-6, rtol=2e-6)


def test_pallas_fold_delta_matches_jnp():
    g = jax.tree_util.tree_map(lambda x: x[0], _stacked(seed=19))
    d = jax.tree_util.tree_map(lambda x: x[1], _stacked(seed=20))
    pl = ep.fold_delta(g, d, 0.3, prefer_pallas=True, interpret=True)
    jn = ep.fold_delta(g, d, 0.3, prefer_pallas=False)
    _assert_close(pl, jn, atol=2e-6, rtol=2e-6)


# ------------------------------------- FedMLAggOperator routing equivalence

def _grad_list(c=5, dtype=jnp.float32, seed=0):
    stacked = _stacked(c=c, dtype=dtype, seed=seed)
    ns = np.random.default_rng(seed + 100).integers(10, 90, c)
    return [(float(ns[i]),
             jax.tree_util.tree_map(lambda x: x[i], stacked))
            for i in range(c)]


def _args(**kw):
    base = dict(federated_optimizer="FedAvg", fused_epilogue=True,
                client_num_in_total=5)
    base.update(kw)
    return fedml_tpu.Config(**base)


def test_agg_fused_matches_legacy_weighted_average_f32():
    gl = _grad_list()
    fused = FedMLAggOperator.agg(_args(), gl)
    legacy = FedMLAggOperator.agg(_args(fused_epilogue=False), gl)
    _assert_close(fused, legacy)
    # and the flag really flips the route: legacy == eager funnel exactly
    _assert_bitwise(legacy, weighted_average(gl))


def test_agg_fused_matches_legacy_weighted_average_bf16():
    gl = _grad_list(dtype=jnp.bfloat16, seed=2)
    fused = FedMLAggOperator.agg(_args(), gl)
    legacy = FedMLAggOperator.agg(_args(fused_epilogue=False), gl)
    # legacy accumulates eagerly in bf16; fused holds f32 until the final
    # cast — fused is the MORE accurate one, so compare both to f64
    ref = _np_mean([g for _, g in [(1, stack_grad_list(
        [g for _, g in gl]))]][0], jnp.asarray([n for n, _ in gl]))
    _assert_close(fused, ref, atol=3e-2, rtol=3e-2)
    _assert_close(legacy, ref, atol=3e-2, rtol=3e-2)


def test_agg_zero_total_uniform_fallback():
    gl = [(0.0, g) for _, g in _grad_list(seed=4)]
    fused = FedMLAggOperator.agg(_args(), gl)
    legacy = FedMLAggOperator.agg(_args(fused_epilogue=False), gl)
    _assert_close(fused, legacy)
    uni = _np_mean(stack_grad_list([g for _, g in gl]),
                   jnp.ones((len(gl),)))
    _assert_close(fused, uni)


@pytest.mark.parametrize("op", ["trimmed_mean:0.2", "median", "krum:1",
                                "multi_krum:1:3", "geo_median",
                                "norm_clip:1.0"])
def test_agg_robust_ops_unaffected_by_fused_flag(op):
    """Robust rounds bypass the fused channel entirely — both flag
    states must take the identical stacked-operator path."""
    gl = _grad_list(seed=6)
    center = jax.tree_util.tree_map(lambda x: x[0],
                                    _stacked(seed=9))
    on = FedMLAggOperator.agg(_args(robust_agg=op), gl, center=center)
    off = FedMLAggOperator.agg(_args(robust_agg=op, fused_epilogue=False),
                               gl, center=center)
    _assert_bitwise(on, off)


@pytest.mark.parametrize("opt", ["SCAFFOLD", "Mime"])
def test_agg_pair_payloads_fused_parity(opt):
    """(params, extra) pair payloads: fused flag must only change the
    reduction's accumulation path, never the pair plumbing."""
    c = 4
    ps = _stacked(c=c, seed=21)
    ex = _stacked(c=c, seed=22)
    ns = [17.0, 3.0, 40.0, 8.0]
    gl = [(ns[i], (jax.tree_util.tree_map(lambda x: x[i], ps),
                   jax.tree_util.tree_map(lambda x: x[i], ex)))
          for i in range(c)]
    a_on = _args(federated_optimizer=opt, client_num_in_total=c)
    a_off = _args(federated_optimizer=opt, client_num_in_total=c,
                  fused_epilogue=False)
    on_p, on_e = FedMLAggOperator.agg(a_on, gl)
    off_p, off_e = FedMLAggOperator.agg(a_off, gl)
    _assert_close(on_p, off_p)
    _assert_close(on_e, off_e)
    _assert_close(on_p, _np_mean(ps, jnp.asarray(ns)))


# ----------------------------------------------- async staleness-weighted

def test_fold_buffer_matches_legacy_reduce_mix_chain():
    """The buffered-async fold (one fused pass) against the pre-fusion
    chain: staleness-decayed weighted mean, then mix_global."""
    stacked, w = _stacked(c=6, seed=23), None
    staleness = jnp.asarray([1.0, 0.5, 0.25, 1.0, 0.125, 0.5],
                            jnp.float32)
    counts = jnp.asarray([30, 12, 44, 8, 20, 16], jnp.float32)
    w = staleness * counts
    g = jax.tree_util.tree_map(lambda x: x[0], _stacked(seed=24))
    for lr in (1.0, 0.5):
        fused = fold_buffer(g, stacked, w, lr)
        legacy = mix_global(
            g,
            jax.tree_util.tree_map(
                lambda x: jnp.asarray(x, jnp.float32),
                _np_mean(stacked, w)),
            lr)
        _assert_close(fused, legacy)


def test_agg_stacked_is_weighted_reduce():
    stacked, w = _stacked(seed=25), _weights()
    _assert_bitwise(agg_stacked(stacked, w),
                    ep.weighted_reduce(stacked, w))


# --------------------------------------- server-optimizer state roundtrips

def _optax_run(tx, g, grads_per_step):
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32), g)
    state = tx.init(params)
    for grad in grads_per_step:
        upd, state = tx.update(grad, state, params)
        params = optax.apply_updates(params, upd)
    return params


@pytest.mark.parametrize("opt,mk_tx", [
    ("sgd", lambda lr: optax.sgd(lr)),
    ("momentum", lambda lr: optax.sgd(lr, momentum=0.9)),
    ("adam", lambda lr: optax.adam(lr)),
])
def test_optimizer_channel_roundtrips_against_optax(opt, mk_tx):
    """Multi-step: the fused channel's threaded state must track optax
    exactly — pseudo-grad server_lr·(global − agg) into the standard
    update at spec.lr."""
    steps, lr, server_lr = 4, 0.05, 0.8
    g = jax.tree_util.tree_map(lambda x: x[0], _stacked(seed=30))
    spec = ep.EpilogueSpec(opt=opt, lr=lr)
    st = ep.init_opt_state(g, spec)
    cur = g
    grads = []
    for k in range(steps):
        stacked, w = _stacked(seed=40 + k), _weights(seed=50 + k)
        acc = ep.weighted_reduce(stacked, w, prefer_pallas=False)
        grads.append(jax.tree_util.tree_map(
            lambda gl, al: jnp.float32(server_lr)
            * (gl.astype(jnp.float32) - al.astype(jnp.float32)),
            cur, acc))
        cur, st = ep.fused_epilogue(cur, stacked, w, server_lr, spec, st,
                                    prefer_pallas=False)
    ref = _optax_run(mk_tx(lr), g, grads)
    # NOTE: grads were built from the FUSED trajectory's params, so this
    # only matches if every intermediate step matched too
    _assert_close(cur, ref)
    if opt == "adam":
        assert int(st["t"]) == steps
    if st is not None:
        for leaf in jax.tree_util.tree_leaves(st):
            assert np.isfinite(np.asarray(leaf, np.float64)).all()


def test_adam_state_threads_bias_correction():
    """First step from zero state: adam's bias-corrected update must be
    lr-scaled sign(grad)-ish, not the uncorrected tiny step."""
    g = {"w": jnp.ones((4, 4), jnp.float32)}
    stacked = {"w": jnp.zeros((3, 4, 4), jnp.float32)}
    spec = ep.EpilogueSpec(opt="adam", lr=0.1)
    st = ep.init_opt_state(g, spec)
    out, st2 = ep.fused_epilogue(g, stacked, jnp.ones((3,)), 1.0, spec,
                                 st, prefer_pallas=False)
    # grad = 1·(1 − 0) = 1 everywhere → first adam step ≈ −lr
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0 - 0.1,
                               atol=1e-5)
    assert int(st2["t"]) == 1


# ----------------------------------------------------------- spec_from_args

def test_spec_from_args_mapping():
    mk = fedml_tpu.Config
    assert ep.spec_from_args(mk(server_optimizer="adam",
                                server_lr=0.01)).opt == "adam"
    s = ep.spec_from_args(mk(server_optimizer="sgd", server_lr=0.5,
                             server_momentum=0.9))
    assert s.opt == "momentum" and s.momentum == 0.9 and s.lr == 0.5
    assert ep.spec_from_args(mk(server_optimizer="sgd", server_lr=0.5,
                                server_momentum=0.0)).opt == "sgd"
    assert ep.spec_from_args(mk(server_optimizer="yogi")) is None
    assert ep.spec_from_args(mk(server_optimizer="adam",
                                fused_epilogue=False)) is None


def test_unknown_epilogue_opt_raises():
    g = {"w": jnp.ones((2,), jnp.float32)}
    with pytest.raises(ValueError):
        ep.fused_epilogue(g, {"w": jnp.ones((2, 2))}, jnp.ones((2,)),
                          1.0, ep.EpilogueSpec(opt="rmsprop"))


# --------------------------------------------------- compile-ahead warm pool

def _make_api(args_factory, cache_dir, **kw):
    from fedml_tpu.runner import FedMLRunner

    args = fedml_tpu.init(args_factory(
        backend="parrot", dataset="mnist", model="lr", data_scale=0.05,
        client_num_in_total=4, client_num_per_round=4, comm_round=2,
        aot_cache_dir=str(cache_dir), parrot_compile_ahead=True, **kw))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, None, dataset, bundle).runner


def test_compile_ahead_warms_step_and_scan(args_factory, tmp_path):
    """The warm pool must precompile BOTH dispatchable programs (per-round
    step + fused scan), write their artifacts, and a second API instance
    must load every one of them (all hits)."""
    api = _make_api(args_factory, tmp_path)
    rep = api.start_compile_ahead(wait=True)
    assert "error" not in rep, rep
    assert set(rep) == {"rs", "mrs"} and not rep["rs"]["hit"]
    arts = sorted(f for f in os.listdir(tmp_path) if f.endswith(".jaxexp"))
    assert any(f.startswith("parrot_rs_") for f in arts), arts
    assert any(f.startswith("parrot_mrs_") for f in arts), arts
    # the warmed executables actually run and train
    rms = api.run_rounds_fused(2)
    assert np.isfinite(np.asarray(rms["train_loss"])).all()

    warm = _make_api(args_factory, tmp_path)
    rep2 = warm.start_compile_ahead(wait=True)
    assert "error" not in rep2, rep2
    assert rep2["rs"]["hit"] and rep2["mrs"]["hit"], rep2
    rms2 = warm.run_rounds_fused(2)
    np.testing.assert_allclose(np.asarray(rms2["train_loss"]),
                               np.asarray(rms["train_loss"]), atol=1e-6)


def test_compile_ahead_idempotent_and_joined_by_ensure(args_factory,
                                                      tmp_path):
    """start twice → one worker; _ensure_multi_round_step must JOIN the
    in-flight warm thread instead of racing a second compile."""
    api = _make_api(args_factory, tmp_path)
    api.start_compile_ahead()
    t = api._compile_ahead_thread
    api.start_compile_ahead()
    assert api._compile_ahead_thread is t
    api._ensure_multi_round_step()          # joins, never double-builds
    assert not t.is_alive()
    assert api.multi_round_step is not None


_CHILD = textwrap.dedent("""
    import json, os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["FEDML_TPU_AOT_CACHE_DIR"] = {cache!r}
    sys.path.insert(0, {repo!r})
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner
    import numpy as np
    args = fedml_tpu.init(fedml_tpu.Config(
        dataset="mnist", model="lr", backend="parrot", data_scale=0.05,
        client_num_in_total=4, client_num_per_round=4, comm_round=2,
        epochs=1, batch_size=16, learning_rate=0.1,
        enable_tracking=False, compute_dtype="float32",
        parrot_compile_ahead=True))
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    api = FedMLRunner(args, None, dataset, bundle).runner
    t0 = time.time()
    rep = api.start_compile_ahead(wait=True)
    ready_s = time.time() - t0
    rms = api.run_rounds_fused(2)
    print("WARMPROOF " + json.dumps({{
        "report": rep, "ready_s": ready_s,
        "loss0": float(np.asarray(rms["train_loss"])[0])}}))
""")


@pytest.mark.slow
def test_compile_ahead_shared_cache_across_processes(tmp_path):
    """The committed cross-process proof of compile-ahead: a SECOND
    process pointed at the same FEDML_TPU_AOT_CACHE_DIR must load every
    warm-pool executable (all hits), get ready several x faster, and
    train to the same first-round loss."""
    cache = str(tmp_path / "aot")
    script = _CHILD.format(repo=REPO, cache=cache)

    def run():
        out = subprocess.run([sys.executable, "-c", script],
                             capture_output=True, text=True, timeout=600,
                             cwd=REPO)
        for ln in out.stdout.splitlines():
            if ln.startswith("WARMPROOF "):
                return json.loads(ln[len("WARMPROOF "):])
        raise AssertionError(out.stderr[-3000:])

    cold = run()
    warm = run()
    assert "error" not in cold["report"], cold
    assert "error" not in warm["report"], warm
    assert not cold["report"]["rs"]["hit"]
    assert warm["report"]["rs"]["hit"] and warm["report"]["mrs"]["hit"]
    assert warm["ready_s"] < cold["ready_s"] * 0.6, (cold, warm)
    assert warm["loss0"] == pytest.approx(cold["loss0"], abs=1e-6)
