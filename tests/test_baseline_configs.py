"""The 5 BASELINE.json capability configs, scaled down for CI
(BASELINE.md "Targets for the new framework").  Each must run end-to-end
and learn; the full-size versions are the driver's bench configs.
"""

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.runner import FedMLRunner


def _run(args):
    args = fedml_tpu.init(args)
    device = fedml_tpu.device.get_device(args)
    dataset = fedml_tpu.data.load(args)
    bundle = fedml_tpu.model.create(args, dataset[-1])
    return FedMLRunner(args, device, dataset, bundle).run()


def test_config1_fedavg_lr_mnist_sp(args_factory):
    """#1: FedAvg LR on MNIST, SP backend, 10 clients."""
    m = _run(args_factory(dataset="mnist", model="lr",
                          client_num_in_total=10, client_num_per_round=10,
                          comm_round=4, learning_rate=0.1, data_scale=0.05))
    assert m["test_acc"] > 0.5


def test_config2_fedavg_resnet56_cifar10_parrot(args_factory):
    """#2: FedAvg ResNet-56 on CIFAR-10, 100 clients / 10 per round,
    Parrot (scaled: 20/5, 3 rounds)."""
    m = _run(args_factory(backend="parrot", dataset="cifar10",
                          model="resnet56", client_num_in_total=20,
                          client_num_per_round=5, comm_round=3,
                          batch_size=16, data_scale=0.05,
                          frequency_of_the_test=10))
    assert np.isfinite(m["test_loss"])


@pytest.mark.parametrize("optimizer", ["FedOpt", "FedProx"])
def test_config3_fedopt_bert_tiny_fed_shakespeare(args_factory, optimizer):
    """#3: FedOpt / FedProx BERT-tiny on Fed-Shakespeare (non-IID text)."""
    m = _run(args_factory(federated_optimizer=optimizer,
                          dataset="fed_shakespeare", model="bert_tiny",
                          client_num_in_total=4, client_num_per_round=4,
                          comm_round=3, batch_size=8, learning_rate=0.05,
                          server_lr=0.1, data_scale=0.05,
                          partition_method="hetero"))
    assert np.isfinite(m["test_loss"])
    assert 0.0 <= m["test_acc"] <= 1.0  # token accuracy


def test_config4_hierarchical_vit_fed_cifar100(args_factory):
    """#4: cross-silo hierarchical FL, ViT-Tiny on Fed-CIFAR100
    (scaled: 2 groups x 2 clients via the hierarchical SP plane)."""
    m = _run(args_factory(federated_optimizer="HierarchicalFL",
                          dataset="fed_cifar100", model="vit_tiny",
                          vit_layers=2, client_num_in_total=4,
                          client_num_per_round=4, group_num=2,
                          group_comm_round=1, comm_round=2, batch_size=8,
                          data_scale=0.02))
    assert np.isfinite(m["test_loss"])


def test_config5_vertical_fl_splitnn_adult(args_factory):
    """#5: vertical FL split-NN, two-party tabular, Adult."""
    m = _run(args_factory(federated_optimizer="VerticalFL", dataset="adult",
                          comm_round=4, batch_size=64, learning_rate=0.1,
                          data_scale=0.5))
    assert m["test_acc"] > 0.6


def test_vertical_fl_multiclass_nus_wide(args_factory):
    """VFL generalizes past the reference's binary-only formulation:
    5-class NUS-WIDE two-view features, per-class logit contributions."""
    m = _run(args_factory(federated_optimizer="VerticalFL",
                          dataset="nus_wide", comm_round=3, batch_size=64,
                          learning_rate=0.1, data_scale=0.2))
    assert np.isfinite(m["test_loss"])
    assert m["test_acc"] > 0.5
