"""NativeClientTrainer — a ClientTrainer backed by the C++ trainer.

Capability parity: the reference's edge path where local training happens in
native code while the host runtime only moves messages
(`android/fedmlsdk/.../TrainingExecutor.java` → JNI →
`FedMLMNNTrainer.cpp`).  This trainer plugs into the SAME planes
(SP simulation / cross-silo managers) as the JAX trainer, proving the
protocol is engine-agnostic.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..core.alg_frame.client_trainer import ClientTrainer
from . import bindings


class NativeClientTrainer(ClientTrainer):
    def __init__(self, bundle: Any, args: Any) -> None:
        super().__init__(bundle, args)
        self.classes = int(getattr(bundle, "num_classes", 10))
        self.hidden = int(getattr(args, "native_hidden", 0) or 0)
        self.batch_size = int(getattr(args, "batch_size", 32))
        self.epochs = int(getattr(args, "epochs", 1))
        self.lr = float(getattr(args, "learning_rate", 0.05))
        self.momentum = float(getattr(args, "momentum", 0.0) or 0.0)
        self.last_metrics: Dict[str, float] = {}
        self.algo_state: Dict[str, Any] = {}
        self.algo_out: Dict[str, Any] = {}

    def set_num_batches(self, nb: int) -> None:  # plane-compat no-op
        pass

    def train(self, train_data, device=None, args=None) -> Dict[str, float]:
        x, y = train_data
        self.params = bindings.train_classifier(
            np.asarray(x), np.asarray(y), self.classes, hidden=self.hidden,
            epochs=self.epochs, batch=min(self.batch_size, max(len(y), 1)),
            lr=self.lr, momentum=self.momentum,
            seed=int(self.rng_seed) + self.id,
            weights={k: np.array(v, np.float32, copy=True)
                     for k, v in self.params.items() if k != "loss"}
            if self.params else None)
        self.last_metrics = {"train_loss": self.params["loss"]}
        return self.last_metrics

    def test(self, test_data, device=None, args=None) -> Dict[str, float]:
        x, y = test_data
        acc, loss = bindings.eval_classifier(
            np.asarray(x), np.asarray(y), self.classes, self.params,
            hidden=self.hidden)
        return {"test_acc": acc, "test_loss": loss,
                "test_total": float(len(y))}
