"""NativeClientTrainer — a ClientTrainer backed by the C++ trainer.

Capability parity: the reference's edge path where local training happens in
native code while the host runtime only moves messages
(`android/fedmlsdk/.../TrainingExecutor.java` → JNI →
`FedMLMNNTrainer.cpp`).  This trainer plugs into the SAME planes
(SP simulation / cross-silo managers) as the JAX trainer, proving the
protocol is engine-agnostic.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..core.alg_frame.client_trainer import ClientTrainer
from . import bindings


class NativeClientTrainer(ClientTrainer):
    def __init__(self, bundle: Any, args: Any) -> None:
        super().__init__(bundle, args)
        self.classes = int(getattr(bundle, "num_classes", 10))
        self.hidden = int(getattr(args, "native_hidden", 0) or 0)
        #: "mlp" (linear / one-hidden-layer, trainer.cpp) or "lenet"
        #: (conv-pool-conv-pool-fc, conv_trainer.cpp — the reference's
        #: MNN CNN-on-device capability)
        self.arch = str(getattr(args, "native_model", "mlp")).lower()
        self.batch_size = int(getattr(args, "batch_size", 32))
        self.epochs = int(getattr(args, "epochs", 1))
        self.lr = float(getattr(args, "learning_rate", 0.05))
        # explicit momentum (including 0.0) is honored for both archs; the
        # DEFAULT differs (lenet wants 0.9 like the reference MNN trainer)
        mom = getattr(args, "momentum", None)
        self.momentum = (float(mom) if mom is not None
                         else (0.9 if self.arch == "lenet" else 0.0))
        self.last_metrics: Dict[str, float] = {}
        self.algo_state: Dict[str, Any] = {}
        self.algo_out: Dict[str, Any] = {}

    def set_num_batches(self, nb: int) -> None:  # plane-compat no-op
        pass

    def _carried_weights(self):
        if not self.params:
            return None
        return {k: np.array(v, np.float32, copy=True)
                for k, v in self.params.items() if k != "loss"}

    def train(self, train_data, device=None, args=None) -> Dict[str, float]:
        x, y = train_data
        kw = dict(epochs=self.epochs,
                  batch=min(self.batch_size, max(len(y), 1)),
                  lr=self.lr, seed=int(self.rng_seed) + self.id,
                  weights=self._carried_weights())
        if self.arch == "lenet":
            self.params = bindings.train_lenet(
                np.asarray(x), np.asarray(y), self.classes,
                momentum=self.momentum, **kw)
        else:
            self.params = bindings.train_classifier(
                np.asarray(x), np.asarray(y), self.classes,
                hidden=self.hidden, momentum=self.momentum, **kw)
        self.last_metrics = {"train_loss": self.params["loss"]}
        return self.last_metrics

    def test(self, test_data, device=None, args=None) -> Dict[str, float]:
        x, y = test_data
        if self.arch == "lenet":
            acc, loss = bindings.eval_lenet(
                np.asarray(x), np.asarray(y), self.classes, self.params)
        else:
            acc, loss = bindings.eval_classifier(
                np.asarray(x), np.asarray(y), self.classes, self.params,
                hidden=self.hidden)
        return {"test_acc": acc, "test_loss": loss,
                "test_total": float(len(y))}
