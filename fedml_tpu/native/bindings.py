"""ctypes bindings for the native C++ library.

Capability parity: the reference's JNI bridge
(`android/fedmlsdk/src/main/jni/JniFedMLClientManager.cpp`) binding the Java
service to the MobileNN C++ trainer — here the host runtime is Python and the
bridge is ctypes (pybind11 is not in this image).  Builds the library on
demand with the Makefile.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Callable, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "build", "libfedml_native.so")
_lib: Optional[ctypes.CDLL] = None

PROGRESS_CB = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_float,
                               ctypes.c_float)


def build_native(force: bool = False) -> str:
    # always invoke make: it is incremental (no-op when fresh) and a stale
    # .so from before a source change would be missing newer symbols
    subprocess.run(["make", "-C", _DIR] + (["-B"] if force else []),
                   check=True, capture_output=True)
    return _LIB_PATH


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    build_native()
    lib = ctypes.CDLL(_LIB_PATH)
    i64, f32, u32, u64 = (ctypes.c_int64, ctypes.c_float, ctypes.c_uint32,
                          ctypes.c_uint64)
    P = ctypes.POINTER
    lib.ft_train_classifier.restype = f32
    lib.ft_train_classifier.argtypes = [
        P(f32), P(ctypes.c_int32), i64, i64, i64, i64,
        P(f32), P(f32), P(f32), P(f32), i64, i64, f32, f32, u64, PROGRESS_CB]
    lib.ft_eval_classifier.restype = f32
    lib.ft_eval_classifier.argtypes = [
        P(f32), P(ctypes.c_int32), i64, i64, i64, i64,
        P(f32), P(f32), P(f32), P(f32), P(f32)]
    lib.ft_train_lenet.restype = f32
    lib.ft_train_lenet.argtypes = [
        P(f32), P(ctypes.c_int32), i64, i64, i64, i64, i64, i64, i64,
        P(f32), P(f32), P(f32), P(f32), P(f32), P(f32),
        i64, i64, f32, f32, u64, PROGRESS_CB]
    lib.ft_eval_lenet.restype = f32
    lib.ft_eval_lenet.argtypes = [
        P(f32), P(ctypes.c_int32), i64, i64, i64, i64, i64, i64, i64,
        P(f32), P(f32), P(f32), P(f32), P(f32), P(f32), P(f32)]
    lib.ft_lcc_encode.argtypes = [P(i64), i64, i64, P(i64), i64, P(i64), i64,
                                  P(i64)]
    lib.ft_lcc_decode.argtypes = [P(i64), i64, i64, P(i64), P(i64), i64,
                                  P(i64)]
    lib.ft_mask_encode.argtypes = [P(i64), i64, i64, i64, i64, u64, P(i64),
                                   P(i64)]
    lib.ft_aggregate_shares.argtypes = [P(i64), i64, i64, P(i64)]
    lib.ft_decode_aggregate_mask.argtypes = [P(i64), P(i64), i64, i64, i64,
                                             i64, i64, P(i64)]
    lib.ft_modular_inv.restype = i64
    lib.ft_modular_inv.argtypes = [i64]
    lib.ft_load_csv.restype = ctypes.c_int
    lib.ft_load_csv.argtypes = [ctypes.c_char_p, P(i64), P(i64), P(f32),
                                P(ctypes.c_int32), i64]
    lib.ft_load_idx.restype = ctypes.c_int
    lib.ft_load_idx.argtypes = [ctypes.c_char_p, ctypes.c_char_p, P(i64),
                                P(i64), P(f32), P(ctypes.c_int32), i64]
    _lib = lib
    return lib


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# -- numpy-friendly wrappers -------------------------------------------------

def lcc_encode(X: np.ndarray, interp_pts, eval_pts) -> np.ndarray:
    lib = load()
    X = np.ascontiguousarray(X, np.int64)
    m, blk = X.shape
    interp = np.ascontiguousarray(interp_pts, np.int64)
    ev = np.ascontiguousarray(eval_pts, np.int64)
    out = np.zeros((len(ev), blk), np.int64)
    lib.ft_lcc_encode(_ptr(X, ctypes.c_int64), m, blk,
                      _ptr(interp, ctypes.c_int64), len(interp),
                      _ptr(ev, ctypes.c_int64), len(ev),
                      _ptr(out, ctypes.c_int64))
    return out


def lcc_decode(F: np.ndarray, eval_pts_in, target_pts) -> np.ndarray:
    lib = load()
    F = np.ascontiguousarray(F, np.int64)
    n_in, blk = F.shape
    ev = np.ascontiguousarray(eval_pts_in, np.int64)
    tg = np.ascontiguousarray(target_pts, np.int64)
    out = np.zeros((len(tg), blk), np.int64)
    lib.ft_lcc_decode(_ptr(F, ctypes.c_int64), n_in, blk,
                      _ptr(ev, ctypes.c_int64), _ptr(tg, ctypes.c_int64),
                      len(tg), _ptr(out, ctypes.c_int64))
    return out


def train_classifier(x: np.ndarray, y: np.ndarray, classes: int,
                     hidden: int = 0, epochs: int = 1, batch: int = 32,
                     lr: float = 0.05, momentum: float = 0.0, seed: int = 0,
                     weights: Optional[dict] = None,
                     progress: Optional[Callable] = None) -> dict:
    """Train (in place) and return {'w1','b1','w2','b2','loss'}."""
    lib = load()
    x = np.ascontiguousarray(x, np.float32).reshape(len(y), -1)
    y = np.ascontiguousarray(y, np.int32)
    n, d = x.shape
    in2 = hidden if hidden > 0 else d
    if weights is None:
        rng = np.random.RandomState(seed)
        weights = {
            "w1": (0.1 * rng.randn(d, hidden)).astype(np.float32)
            if hidden else np.zeros(0, np.float32),
            "b1": np.zeros(hidden, np.float32),
            "w2": np.zeros((in2, classes), np.float32),
            "b2": np.zeros(classes, np.float32),
        }
    w1 = np.ascontiguousarray(weights["w1"], np.float32)
    b1 = np.ascontiguousarray(weights["b1"], np.float32)
    w2 = np.ascontiguousarray(weights["w2"], np.float32)
    b2 = np.ascontiguousarray(weights["b2"], np.float32)
    cb = PROGRESS_CB(progress) if progress else PROGRESS_CB(0)
    f32 = ctypes.c_float
    loss = lib.ft_train_classifier(
        _ptr(x, f32), _ptr(y, ctypes.c_int32), n, d, classes, hidden,
        _ptr(w1, f32) if hidden else None, _ptr(b1, f32) if hidden else None,
        _ptr(w2, f32), _ptr(b2, f32), epochs, batch, lr, momentum, seed, cb)
    return {"w1": w1, "b1": b1, "w2": w2, "b2": b2, "loss": float(loss)}


def _lenet_shapes(d: int, c1: int, c2: int, classes: int
                  ) -> Tuple[int, int, int, int]:
    """(H, W, Cin, fc_in) for a flat feature dim d: square single-channel
    (MNIST 784→28x28x1) or square 3-channel (CIFAR 3072→32x32x3)."""
    side = int(round(d ** 0.5))
    if side * side == d:
        H = W = side
        cin = 1
    else:
        side = int(round((d / 3) ** 0.5))
        if side * side * 3 != d:
            raise ValueError(f"cannot infer HxWxC from flat dim {d}")
        H = W = side
        cin = 3
    hp1 = (H - 4) // 2
    hp2 = (hp1 - 4) // 2
    return H, W, cin, c2 * hp2 * hp2


def init_lenet_weights(d: int, classes: int, c1: int = 8, c2: int = 16,
                       seed: int = 0) -> dict:
    """He-init conv kernels, zero fc — the canonical edge LeNet start."""
    H, W, cin, fc_in = _lenet_shapes(d, c1, c2, classes)
    rng = np.random.RandomState(seed)
    return {
        "k1": (rng.randn(c1, cin, 5, 5)
               * np.sqrt(2.0 / (cin * 25))).astype(np.float32),
        "bk1": np.zeros(c1, np.float32),
        "k2": (rng.randn(c2, c1, 5, 5)
               * np.sqrt(2.0 / (c1 * 25))).astype(np.float32),
        "bk2": np.zeros(c2, np.float32),
        "fw": np.zeros((fc_in, classes), np.float32),
        "fb": np.zeros(classes, np.float32),
    }


def _lenet_input(x: np.ndarray, y: np.ndarray) -> Tuple[np.ndarray, int,
                                                        int, int]:
    """→ contiguous [n, Cin, H, W] float32 regardless of NHWC/flat input."""
    x = np.asarray(x, np.float32)
    if x.ndim == 4:          # NHWC → NCHW
        x = np.transpose(x, (0, 3, 1, 2))
        n, cin, H, W = x.shape
    elif x.ndim == 3:        # NHW (single channel)
        x = x[:, None, :, :]
        n, cin, H, W = x.shape
    else:
        d = x.reshape(len(y), -1).shape[1]
        H, W, cin, _ = _lenet_shapes(d, 8, 16, 10)
        x = x.reshape(len(y), cin, H, W) if cin == 1 else \
            np.transpose(x.reshape(len(y), H, W, cin), (0, 3, 1, 2))
    return np.ascontiguousarray(x), int(x.shape[1]), int(x.shape[2]), \
        int(x.shape[3])


def _check_lenet_weights(ws: dict, cin: int, H: int, W: int, classes: int
                         ) -> None:
    """Shape-validate before handing raw pointers to C: a mismatched fc
    weight would make the C loops index past the numpy buffers (heap
    corruption instead of a Python error)."""
    c1, k1_cin = ws["k1"].shape[0], ws["k1"].shape[1]
    c2, k2_cin = ws["k2"].shape[0], ws["k2"].shape[1]
    hp1 = (H - 4) // 2
    hp2 = (hp1 - 4) // 2
    fc_in = c2 * hp2 * hp2
    if (k1_cin != cin or k2_cin != c1
            or ws["k1"].shape[2:] != (5, 5) or ws["k2"].shape[2:] != (5, 5)
            or ws["bk1"].shape != (c1,) or ws["bk2"].shape != (c2,)
            or ws["fw"].shape != (fc_in, classes)
            or ws["fb"].shape != (classes,)):
        raise ValueError(
            f"lenet weight shapes {({k: v.shape for k, v in ws.items()})} "
            f"do not match input {H}x{W}x{cin} / {classes} classes "
            f"(expected fw {(fc_in, classes)})")


def train_lenet(x: np.ndarray, y: np.ndarray, classes: int, c1: int = 8,
                c2: int = 16, epochs: int = 1, batch: int = 32,
                lr: float = 0.05, momentum: float = 0.9, seed: int = 0,
                weights: Optional[dict] = None,
                progress: Optional[Callable] = None) -> dict:
    """Train the native conv net in place; returns
    {'k1','bk1','k2','bk2','fw','fb','loss'}."""
    lib = load()
    y = np.ascontiguousarray(y, np.int32)
    x, cin, H, W = _lenet_input(x, y)
    if weights is None:
        weights = init_lenet_weights(cin * H * W, classes, c1, c2, seed)
    ws = {k: np.ascontiguousarray(weights[k], np.float32)
          for k in ("k1", "bk1", "k2", "bk2", "fw", "fb")}
    _check_lenet_weights(ws, cin, H, W, classes)
    c1 = ws["k1"].shape[0]
    c2 = ws["k2"].shape[0]
    cb = PROGRESS_CB(progress) if progress else PROGRESS_CB(0)
    f32 = ctypes.c_float
    loss = lib.ft_train_lenet(
        _ptr(x, f32), _ptr(y, ctypes.c_int32), len(y), H, W, cin, c1, c2,
        classes, _ptr(ws["k1"], f32), _ptr(ws["bk1"], f32),
        _ptr(ws["k2"], f32), _ptr(ws["bk2"], f32), _ptr(ws["fw"], f32),
        _ptr(ws["fb"], f32), epochs, batch, lr, momentum, seed, cb)
    return dict(ws, loss=float(loss))


def eval_lenet(x: np.ndarray, y: np.ndarray, classes: int, weights: dict
               ) -> Tuple[float, float]:
    lib = load()
    y = np.ascontiguousarray(y, np.int32)
    x, cin, H, W = _lenet_input(x, y)
    ws = {k: np.ascontiguousarray(weights[k], np.float32)
          for k in ("k1", "bk1", "k2", "bk2", "fw", "fb")}
    _check_lenet_weights(ws, cin, H, W, classes)
    f32 = ctypes.c_float
    loss = ctypes.c_float(0.0)
    acc = lib.ft_eval_lenet(
        _ptr(x, f32), _ptr(y, ctypes.c_int32), len(y), H, W, cin,
        ws["k1"].shape[0], ws["k2"].shape[0], classes,
        _ptr(ws["k1"], f32), _ptr(ws["bk1"], f32), _ptr(ws["k2"], f32),
        _ptr(ws["bk2"], f32), _ptr(ws["fw"], f32), _ptr(ws["fb"], f32),
        ctypes.byref(loss))
    return float(acc), float(loss.value)


def eval_classifier(x: np.ndarray, y: np.ndarray, classes: int,
                    weights: dict, hidden: int = 0) -> Tuple[float, float]:
    lib = load()
    x = np.ascontiguousarray(x, np.float32).reshape(len(y), -1)
    y = np.ascontiguousarray(y, np.int32)
    n, d = x.shape
    f32 = ctypes.c_float
    loss = ctypes.c_float(0.0)
    w1 = np.ascontiguousarray(weights["w1"], np.float32)
    b1 = np.ascontiguousarray(weights["b1"], np.float32)
    w2 = np.ascontiguousarray(weights["w2"], np.float32)
    b2 = np.ascontiguousarray(weights["b2"], np.float32)
    acc = lib.ft_eval_classifier(
        _ptr(x, f32), _ptr(y, ctypes.c_int32), n, d, classes, hidden,
        _ptr(w1, f32) if hidden else None, _ptr(b1, f32) if hidden else None,
        _ptr(w2, f32), _ptr(b2, f32), ctypes.byref(loss))
    return float(acc), float(loss.value)


def load_csv(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Native CSV loader (features..., label per line) → (x, y)."""
    lib = load()
    n = ctypes.c_int64(0)
    d = ctypes.c_int64(0)
    rc = lib.ft_load_csv(path.encode(), ctypes.byref(n), ctypes.byref(d),
                         None, None, 0)
    if rc != 0:
        raise IOError(f"ft_load_csv({path!r}) failed with code {rc}")
    cap = n.value
    x = np.zeros((cap, d.value), np.float32)
    y = np.zeros((cap,), np.int32)
    rc = lib.ft_load_csv(path.encode(), ctypes.byref(n), ctypes.byref(d),
                         _ptr(x, ctypes.c_float), _ptr(y, ctypes.c_int32),
                         cap)
    if rc != 0:
        raise IOError(f"ft_load_csv({path!r}) failed with code {rc}")
    return x[:n.value], y[:n.value]


def load_idx(images_path: str, labels_path: str
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Native MNIST-idx loader → (x in [0,1] shaped [n, rows*cols], y)."""
    lib = load()
    n = ctypes.c_int64(0)
    d = ctypes.c_int64(0)
    rc = lib.ft_load_idx(images_path.encode(), labels_path.encode(),
                         ctypes.byref(n), ctypes.byref(d), None, None, 0)
    if rc != 0:
        raise IOError(f"ft_load_idx failed with code {rc}")
    cap = n.value
    x = np.zeros((cap, d.value), np.float32)
    y = np.zeros((cap,), np.int32)
    rc = lib.ft_load_idx(images_path.encode(), labels_path.encode(),
                         ctypes.byref(n), ctypes.byref(d),
                         _ptr(x, ctypes.c_float), _ptr(y, ctypes.c_int32),
                         cap)
    if rc != 0:
        raise IOError(f"ft_load_idx failed with code {rc}")
    return x[:min(n.value, cap)], y[:min(n.value, cap)]
