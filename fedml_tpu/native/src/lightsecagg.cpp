// Native LightSecAgg mask codec — C API.
//
// Capability parity: the reference ships a C++ LightSecAgg for its Android
// client (android/fedmlsdk/MobileNN/src/security/LightSecAgg.cpp:134 LoC,
// LightSecAggForMNN.cpp): finite-field mask encode / share / aggregate-
// encoded-mask matching the Python protocol.  This codec speaks the SAME
// protocol as fedml_tpu/core/mpc/lightsecagg.py (verified by round-trip
// tests against the Python implementation).

#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

#include "field_math.h"

using fedml_native::kFieldPrime;
using fedml_native::lagrange_basis;
using fedml_native::mod_p;
using fedml_native::mul_mod;

extern "C" {

// y[i] = sum_j U[i,j] * X[j]  over the field; X: [m, blk], out: [ne, blk]
static void lcc_apply(const int64_t* U, const int64_t* X, int64_t* out,
                      int64_t ne, int64_t m, int64_t blk) {
  for (int64_t i = 0; i < ne; ++i) {
    for (int64_t c = 0; c < blk; ++c) out[i * blk + c] = 0;
    for (int64_t j = 0; j < m; ++j) {
      const int64_t u = U[i * m + j];
      if (u == 0) continue;
      const int64_t* xrow = X + j * blk;
      int64_t* orow = out + i * blk;
      for (int64_t c = 0; c < blk; ++c) {
        orow[c] = mod_p(orow[c] + mul_mod(u, xrow[c]));
      }
    }
  }
}

// Encode blocks X [m, blk] (nodes interp[0..m)) at eval points → out [ne, blk]
void ft_lcc_encode(const int64_t* X, int64_t m, int64_t blk,
                   const int64_t* interp_pts, int64_t n_interp,
                   const int64_t* eval_pts, int64_t n_eval, int64_t* out) {
  std::vector<int64_t> interp(interp_pts, interp_pts + n_interp);
  std::vector<int64_t> eval(eval_pts, eval_pts + n_eval);
  std::vector<int64_t> U = lagrange_basis(eval, interp);
  lcc_apply(U.data(), X, out, n_eval, m, blk);
}

// Decode: interpolate through (eval_in[i], F[i]) and evaluate at targets.
void ft_lcc_decode(const int64_t* F, int64_t n_in, int64_t blk,
                   const int64_t* eval_pts_in, const int64_t* target_pts,
                   int64_t n_target, int64_t* out) {
  std::vector<int64_t> nodes(eval_pts_in, eval_pts_in + n_in);
  std::vector<int64_t> targets(target_pts, target_pts + n_target);
  std::vector<int64_t> U = lagrange_basis(targets, nodes);
  lcc_apply(U.data(), F, out, n_target, n_in, blk);
}

// LightSecAgg mask encoding: mask [d] → n shares [n, blk]; any u reconstruct.
// blk = ceil(d / (u - t)); k = u - t data blocks + t noise blocks.
// Returns blk via out_blk. noise drawn from the given seed.
void ft_mask_encode(const int64_t* mask, int64_t d, int64_t n, int64_t u,
                    int64_t t, uint64_t seed, int64_t* out_shares,
                    int64_t* out_blk) {
  const int64_t k = u - t;
  const int64_t blk = (d + k - 1) / k;
  *out_blk = blk;
  std::vector<int64_t> X(static_cast<size_t>(u * blk), 0);
  for (int64_t i = 0; i < d; ++i) X[i] = mod_p(mask[i]);
  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<int64_t> dist(0, kFieldPrime - 1);
  for (int64_t j = k * blk; j < u * blk; ++j) X[j] = dist(rng);
  std::vector<int64_t> beta(u), alpha(n);
  for (int64_t j = 0; j < u; ++j) beta[j] = j + 1;
  for (int64_t j = 0; j < n; ++j) alpha[j] = u + 1 + j;
  ft_lcc_encode(X.data(), u, blk, beta.data(), u, alpha.data(), n,
                out_shares);
}

// Sum of held shares over the surviving set (mod p).
void ft_aggregate_shares(const int64_t* shares, int64_t n_shares, int64_t blk,
                         int64_t* out) {
  for (int64_t c = 0; c < blk; ++c) out[c] = 0;
  for (int64_t s = 0; s < n_shares; ++s) {
    const int64_t* row = shares + s * blk;
    for (int64_t c = 0; c < blk; ++c) out[c] = mod_p(out[c] + row[c]);
  }
}

// Decode the aggregate mask from u surviving clients' aggregated shares.
// share_owner_ids: 0-based share indices the survivors held.
void ft_decode_aggregate_mask(const int64_t* agg_shares,
                              const int64_t* share_owner_ids, int64_t n_have,
                              int64_t d, int64_t u, int64_t t, int64_t blk,
                              int64_t* out_mask) {
  std::vector<int64_t> alpha(n_have), beta(u - t);
  for (int64_t j = 0; j < n_have; ++j)
    alpha[j] = u + 1 + share_owner_ids[j];
  for (int64_t j = 0; j < u - t; ++j) beta[j] = j + 1;
  std::vector<int64_t> blocks(static_cast<size_t>((u - t) * blk));
  ft_lcc_decode(agg_shares, n_have, blk, alpha.data(), beta.data(), u - t,
                blocks.data());
  for (int64_t i = 0; i < d; ++i) out_mask[i] = blocks[i];
}

// mod-2^32 bulk mask application (device-free path for edge clients)
void ft_mask_add_u32(const uint32_t* x, const uint32_t* m, uint32_t* out,
                     int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = x[i] + m[i];
}

void ft_mask_sub_u32(const uint32_t* x, const uint32_t* m, uint32_t* out,
                     int64_t n) {
  for (int64_t i = 0; i < n; ++i) out[i] = x[i] - m[i];
}

int64_t ft_modular_inv(int64_t a) { return fedml_native::modular_inv(a); }

}  // extern "C"
