// Native on-device CONV trainer (LeNet-class) — C API.
//
// Capability parity: the reference's MobileNN trainer runs CNN-class models
// (LeNet / resnet20-mobile) on-device via MNN
// (android/fedmlsdk/MobileNN/src/train/FedMLMNNTrainer.cpp:3-179, mobile
// models at python/fedml/model/model_hub.py:78-84).  This dependency-free
// C++ implementation trains the same conv-pool-conv-pool-fc shape so the
// cross-device plane can carry conv models, not just MLPs:
//
//   conv 5x5 (Cin->c1, valid) + relu -> maxpool 2x2
//   conv 5x5 (c1->c2, valid) + relu -> maxpool 2x2
//   fc (c2*h2*w2 -> classes), softmax cross-entropy, SGD(momentum).
//
// x layout: [n, Cin, H, W] row-major.  All weight buffers are in/out, the
// federated round updates them in place (same contract as
// ft_train_classifier in trainer.cpp).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

namespace {

constexpr int64_t KS = 5;   // conv kernel
constexpr int64_t PS = 2;   // pool

struct Dims {
  int64_t H, W, Cin, c1, c2, classes;
  int64_t hc1, wc1;  // conv1 out
  int64_t hp1, wp1;  // pool1 out
  int64_t hc2, wc2;  // conv2 out
  int64_t hp2, wp2;  // pool2 out
  int64_t fc_in;
};

Dims make_dims(int64_t H, int64_t W, int64_t Cin, int64_t c1, int64_t c2,
               int64_t classes) {
  Dims d{H, W, Cin, c1, c2, classes, 0, 0, 0, 0, 0, 0, 0, 0, 0};
  d.hc1 = H - KS + 1;
  d.wc1 = W - KS + 1;
  d.hp1 = d.hc1 / PS;
  d.wp1 = d.wc1 / PS;
  d.hc2 = d.hp1 - KS + 1;
  d.wc2 = d.wp1 - KS + 1;
  d.hp2 = d.hc2 / PS;
  d.wp2 = d.wc2 / PS;
  d.fc_in = c2 * d.hp2 * d.wp2;
  return d;
}

// valid conv + relu: in [Ci, h, w] -> out [Co, ho, wo]; k [Co, Ci, KS, KS]
void conv_relu_fwd(const float* in, int64_t Ci, int64_t h, int64_t w,
                   const float* k, const float* bias, int64_t Co,
                   float* out, int64_t ho, int64_t wo) {
  for (int64_t co = 0; co < Co; ++co) {
    for (int64_t i = 0; i < ho; ++i) {
      for (int64_t j = 0; j < wo; ++j) {
        float acc = bias[co];
        for (int64_t ci = 0; ci < Ci; ++ci) {
          const float* inp = in + ci * h * w;
          const float* kp = k + ((co * Ci + ci) * KS) * KS;
          for (int64_t u = 0; u < KS; ++u)
            for (int64_t v = 0; v < KS; ++v)
              acc += inp[(i + u) * w + (j + v)] * kp[u * KS + v];
        }
        out[(co * ho + i) * wo + j] = acc > 0.f ? acc : 0.f;
      }
    }
  }
}

// maxpool 2x2 with argmax capture: in [C, h, w] -> out [C, h/2, w/2]
void pool_fwd(const float* in, int64_t C, int64_t h, int64_t w, float* out,
              int32_t* arg, int64_t ho, int64_t wo) {
  for (int64_t c = 0; c < C; ++c) {
    for (int64_t i = 0; i < ho; ++i) {
      for (int64_t j = 0; j < wo; ++j) {
        int64_t best = ((c * h + i * PS) * w + j * PS);
        float bv = in[best];
        for (int64_t u = 0; u < PS; ++u) {
          for (int64_t v = 0; v < PS; ++v) {
            int64_t idx = (c * h + i * PS + u) * w + (j * PS + v);
            if (in[idx] > bv) { bv = in[idx]; best = idx; }
          }
        }
        out[(c * ho + i) * wo + j] = bv;
        arg[(c * ho + i) * wo + j] = static_cast<int32_t>(best);
      }
    }
  }
}

// grad through pool: g_out [C, ho, wo] scattered to g_in via argmax
void pool_bwd(const float* g_out, const int32_t* arg, int64_t n_out,
              float* g_in, int64_t n_in) {
  std::memset(g_in, 0, sizeof(float) * n_in);
  for (int64_t i = 0; i < n_out; ++i) g_in[arg[i]] += g_out[i];
}

// grad through conv+relu: accumulates dk/db over the batch element and
// writes g_in (input gradient), given g_out already masked by relu.
void conv_bwd(const float* in, int64_t Ci, int64_t h, int64_t w,
              const float* k, int64_t Co, const float* g_out, int64_t ho,
              int64_t wo, float* dk, float* db, float* g_in) {
  if (g_in) std::memset(g_in, 0, sizeof(float) * Ci * h * w);
  for (int64_t co = 0; co < Co; ++co) {
    for (int64_t i = 0; i < ho; ++i) {
      for (int64_t j = 0; j < wo; ++j) {
        float g = g_out[(co * ho + i) * wo + j];
        if (g == 0.f) continue;
        db[co] += g;
        for (int64_t ci = 0; ci < Ci; ++ci) {
          const float* inp = in + ci * h * w;
          float* dkp = dk + ((co * Ci + ci) * KS) * KS;
          const float* kp = k + ((co * Ci + ci) * KS) * KS;
          float* gip = g_in ? g_in + ci * h * w : nullptr;
          for (int64_t u = 0; u < KS; ++u) {
            for (int64_t v = 0; v < KS; ++v) {
              dkp[u * KS + v] += inp[(i + u) * w + (j + v)] * g;
              if (gip) gip[(i + u) * w + (j + v)] += kp[u * KS + v] * g;
            }
          }
        }
      }
    }
  }
}

void forward_sample(const Dims& d, const float* xi, const float* k1,
                    const float* bk1, const float* k2, const float* bk2,
                    const float* fw, const float* fb, float* a1, float* p1,
                    int32_t* arg1, float* a2, float* p2, int32_t* arg2,
                    float* logits) {
  conv_relu_fwd(xi, d.Cin, d.H, d.W, k1, bk1, d.c1, a1, d.hc1, d.wc1);
  pool_fwd(a1, d.c1, d.hc1, d.wc1, p1, arg1, d.hp1, d.wp1);
  conv_relu_fwd(p1, d.c1, d.hp1, d.wp1, k2, bk2, d.c2, a2, d.hc2, d.wc2);
  pool_fwd(a2, d.c2, d.hc2, d.wc2, p2, arg2, d.hp2, d.wp2);
  for (int64_t c = 0; c < d.classes; ++c) {
    float acc = fb[c];
    for (int64_t k = 0; k < d.fc_in; ++k)
      acc += p2[k] * fw[k * d.classes + c];
    logits[c] = acc;
  }
}

}  // namespace

extern "C" {

typedef void (*ft_progress_cb)(int epoch, float loss, float acc);

float ft_train_lenet(const float* x, const int32_t* y, int64_t n, int64_t H,
                     int64_t W, int64_t Cin, int64_t c1, int64_t c2,
                     int64_t classes, float* k1, float* bk1, float* k2,
                     float* bk2, float* fw, float* fb, int64_t epochs,
                     int64_t batch, float lr, float momentum, uint64_t seed,
                     ft_progress_cb progress) {
  const Dims d = make_dims(H, W, Cin, c1, c2, classes);
  if (d.hp2 <= 0 || d.wp2 <= 0) return -1.f;

  // activations / grads for one sample at a time; grads accumulate over
  // the minibatch then one momentum-SGD step per batch
  std::vector<float> a1(d.c1 * d.hc1 * d.wc1), p1(d.c1 * d.hp1 * d.wp1);
  std::vector<float> a2(d.c2 * d.hc2 * d.wc2), p2(d.fc_in);
  std::vector<int32_t> arg1(d.c1 * d.hp1 * d.wp1), arg2(d.fc_in);
  std::vector<float> logits(classes), probs(classes);
  std::vector<float> g_p2(d.fc_in), g_a2(d.c2 * d.hc2 * d.wc2);
  std::vector<float> g_p1(d.c1 * d.hp1 * d.wp1),
      g_a1(d.c1 * d.hc1 * d.wc1);
  const int64_t nk1 = c1 * Cin * KS * KS, nk2 = c2 * c1 * KS * KS;
  const int64_t nfw = d.fc_in * classes;
  std::vector<float> dk1(nk1), dbk1(c1), dk2(nk2), dbk2(c2), dfw(nfw),
      dfb(classes);
  std::vector<float> vk1(nk1, 0.f), vbk1(c1, 0.f), vk2(nk2, 0.f),
      vbk2(c2, 0.f), vfw(nfw, 0.f), vfb(classes, 0.f);

  std::vector<int64_t> order(n);
  for (int64_t i = 0; i < n; ++i) order[i] = i;
  std::mt19937_64 rng(seed);
  const int64_t sample_sz = Cin * H * W;

  float epoch_loss = 0.f;
  for (int64_t ep = 0; ep < epochs; ++ep) {
    std::shuffle(order.begin(), order.end(), rng);
    epoch_loss = 0.f;
    int64_t correct = 0, seen = 0;
    for (int64_t s = 0; s + batch <= n; s += batch) {
      std::fill(dk1.begin(), dk1.end(), 0.f);
      std::fill(dbk1.begin(), dbk1.end(), 0.f);
      std::fill(dk2.begin(), dk2.end(), 0.f);
      std::fill(dbk2.begin(), dbk2.end(), 0.f);
      std::fill(dfw.begin(), dfw.end(), 0.f);
      std::fill(dfb.begin(), dfb.end(), 0.f);

      for (int64_t b = 0; b < batch; ++b) {
        const float* xi = x + order[s + b] * sample_sz;
        const int32_t yi = y[order[s + b]];
        forward_sample(d, xi, k1, bk1, k2, bk2, fw, fb, a1.data(),
                       p1.data(), arg1.data(), a2.data(), p2.data(),
                       arg2.data(), logits.data());
        float mx = logits[0];
        for (int64_t c = 1; c < classes; ++c) mx = std::max(mx, logits[c]);
        float z = 0.f;
        for (int64_t c = 0; c < classes; ++c) {
          probs[c] = std::exp(logits[c] - mx);
          z += probs[c];
        }
        int64_t am = 0;
        for (int64_t c = 0; c < classes; ++c) {
          probs[c] /= z;
          if (probs[c] > probs[am]) am = c;
        }
        epoch_loss += -std::log(std::max(probs[yi], 1e-12f));
        if (am == yi) ++correct;
        ++seen;

        // fc backward (grad scaled by 1/batch)
        for (int64_t k = 0; k < d.fc_in; ++k) g_p2[k] = 0.f;
        for (int64_t c = 0; c < classes; ++c) {
          float g = (probs[c] - (c == yi ? 1.f : 0.f)) / batch;
          dfb[c] += g;
          for (int64_t k = 0; k < d.fc_in; ++k) {
            dfw[k * classes + c] += p2[k] * g;
            g_p2[k] += fw[k * classes + c] * g;
          }
        }
        // pool2 -> conv2 (relu mask: a2 == 0 means pre-relu <= 0)
        pool_bwd(g_p2.data(), arg2.data(), d.fc_in, g_a2.data(),
                 d.c2 * d.hc2 * d.wc2);
        for (int64_t i = 0; i < d.c2 * d.hc2 * d.wc2; ++i)
          if (a2[i] <= 0.f) g_a2[i] = 0.f;
        conv_bwd(p1.data(), d.c1, d.hp1, d.wp1, k2, d.c2, g_a2.data(),
                 d.hc2, d.wc2, dk2.data(), dbk2.data(), g_p1.data());
        // pool1 -> conv1
        pool_bwd(g_p1.data(), arg1.data(), d.c1 * d.hp1 * d.wp1,
                 g_a1.data(), d.c1 * d.hc1 * d.wc1);
        for (int64_t i = 0; i < d.c1 * d.hc1 * d.wc1; ++i)
          if (a1[i] <= 0.f) g_a1[i] = 0.f;
        conv_bwd(xi, Cin, H, W, k1, d.c1, g_a1.data(), d.hc1, d.wc1,
                 dk1.data(), dbk1.data(), nullptr);
      }

      auto sgd = [lr, momentum](float* w, float* v, const float* g,
                                int64_t m) {
        for (int64_t i = 0; i < m; ++i) {
          v[i] = momentum * v[i] + g[i];
          w[i] -= lr * v[i];
        }
      };
      sgd(k1, vk1.data(), dk1.data(), nk1);
      sgd(bk1, vbk1.data(), dbk1.data(), c1);
      sgd(k2, vk2.data(), dk2.data(), nk2);
      sgd(bk2, vbk2.data(), dbk2.data(), c2);
      sgd(fw, vfw.data(), dfw.data(), nfw);
      sgd(fb, vfb.data(), dfb.data(), classes);
    }
    epoch_loss = seen > 0 ? epoch_loss / seen : 0.f;
    if (progress)
      progress(static_cast<int>(ep), epoch_loss,
               seen > 0 ? static_cast<float>(correct) / seen : 0.f);
  }
  return epoch_loss;
}

float ft_eval_lenet(const float* x, const int32_t* y, int64_t n, int64_t H,
                    int64_t W, int64_t Cin, int64_t c1, int64_t c2,
                    int64_t classes, const float* k1, const float* bk1,
                    const float* k2, const float* bk2, const float* fw,
                    const float* fb, float* loss_out) {
  const Dims d = make_dims(H, W, Cin, c1, c2, classes);
  std::vector<float> a1(d.c1 * d.hc1 * d.wc1), p1(d.c1 * d.hp1 * d.wp1);
  std::vector<float> a2(d.c2 * d.hc2 * d.wc2), p2(d.fc_in);
  std::vector<int32_t> arg1(d.c1 * d.hp1 * d.wp1), arg2(d.fc_in);
  std::vector<float> logits(classes);
  const int64_t sample_sz = Cin * H * W;
  int64_t correct = 0;
  float loss = 0.f;
  for (int64_t i = 0; i < n; ++i) {
    forward_sample(d, x + i * sample_sz, k1, bk1, k2, bk2, fw, fb,
                   a1.data(), p1.data(), arg1.data(), a2.data(), p2.data(),
                   arg2.data(), logits.data());
    float mx = logits[0];
    for (int64_t c = 1; c < classes; ++c) mx = std::max(mx, logits[c]);
    float z = 0.f;
    for (int64_t c = 0; c < classes; ++c) z += std::exp(logits[c] - mx);
    loss += -(logits[y[i]] - mx - std::log(z));
    int64_t am = 0;
    for (int64_t c = 1; c < classes; ++c)
      if (logits[c] > logits[am]) am = c;
    if (am == y[i]) ++correct;
  }
  if (loss_out) *loss_out = n > 0 ? loss / n : 0.f;
  return n > 0 ? static_cast<float>(correct) / n : 0.f;
}

}  // extern "C"
