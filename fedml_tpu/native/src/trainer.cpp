// Native on-device client trainer — C API.
//
// Capability parity: the reference's MobileNN C++ trainer
// (android/fedmlsdk/MobileNN/src/train/FedMLMNNTrainer.cpp:3-179 — loads a
// model, runs SGD with momentum over MNIST/CIFAR/tabular data, reports
// per-epoch progress/accuracy via callbacks).  This is the TPU-era edge
// counterpart: a dependency-free C++ SGD trainer for linear / one-hidden-
// layer MLP classifiers over float32 feature arrays, driven by the same
// Python client manager through ctypes, with an epoch-progress callback.
//
// It deliberately does NOT use JAX/XLA: it models the phone-class client
// that trains locally in native code and only speaks the message protocol.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <vector>

extern "C" {

typedef void (*ft_progress_cb)(int epoch, float loss, float acc);

// Softmax-regression (optional one hidden layer) SGD with momentum.
// x: [n, d] row-major; y: [n] int labels in [0, classes)
// w1: [d, hidden] or null if hidden == 0; b1: [hidden]
// w2: [in2, classes] where in2 = hidden>0 ? hidden : d; b2: [classes]
// All weight buffers are in/out (the federated round updates them in place).
// Returns final mean loss.
float ft_train_classifier(const float* x, const int32_t* y, int64_t n,
                          int64_t d, int64_t classes, int64_t hidden,
                          float* w1, float* b1, float* w2, float* b2,
                          int64_t epochs, int64_t batch, float lr,
                          float momentum, uint64_t seed,
                          ft_progress_cb progress) {
  const int64_t in2 = hidden > 0 ? hidden : d;
  std::vector<float> h(static_cast<size_t>(batch * (hidden > 0 ? hidden : 1)));
  std::vector<float> logits(static_cast<size_t>(batch * classes));
  std::vector<float> probs(static_cast<size_t>(batch * classes));
  std::vector<float> g_logits(static_cast<size_t>(batch * classes));
  std::vector<float> g_h(static_cast<size_t>(batch * (hidden > 0 ? hidden : 1)));
  std::vector<float> vw1(hidden > 0 ? static_cast<size_t>(d * hidden) : 0, 0.f);
  std::vector<float> vb1(hidden > 0 ? static_cast<size_t>(hidden) : 0, 0.f);
  std::vector<float> vw2(static_cast<size_t>(in2 * classes), 0.f);
  std::vector<float> vb2(static_cast<size_t>(classes), 0.f);
  std::vector<int64_t> order(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) order[i] = i;
  std::mt19937_64 rng(seed);

  float epoch_loss = 0.f;
  for (int64_t ep = 0; ep < epochs; ++ep) {
    std::shuffle(order.begin(), order.end(), rng);
    epoch_loss = 0.f;
    int64_t correct = 0, seen = 0;
    for (int64_t s = 0; s + batch <= n; s += batch) {
      // ---- forward ----
      for (int64_t b = 0; b < batch; ++b) {
        const float* xi = x + order[s + b] * d;
        const float* feat;
        if (hidden > 0) {
          float* hb = h.data() + b * hidden;
          for (int64_t j = 0; j < hidden; ++j) {
            float acc = b1[j];
            for (int64_t k = 0; k < d; ++k) acc += xi[k] * w1[k * hidden + j];
            hb[j] = acc > 0.f ? acc : 0.f;  // relu
          }
          feat = hb;
        } else {
          feat = xi;
        }
        float* lb = logits.data() + b * classes;
        for (int64_t c = 0; c < classes; ++c) {
          float acc = b2[c];
          for (int64_t k = 0; k < in2; ++k) acc += feat[k] * w2[k * classes + c];
          lb[c] = acc;
        }
      }
      // ---- softmax CE + grad ----
      for (int64_t b = 0; b < batch; ++b) {
        const float* lb = logits.data() + b * classes;
        float* pb = probs.data() + b * classes;
        float mx = lb[0];
        for (int64_t c = 1; c < classes; ++c) mx = std::max(mx, lb[c]);
        float z = 0.f;
        for (int64_t c = 0; c < classes; ++c) {
          pb[c] = std::exp(lb[c] - mx);
          z += pb[c];
        }
        int32_t yi = y[order[s + b]];
        int64_t argmax = 0;
        for (int64_t c = 0; c < classes; ++c) {
          pb[c] /= z;
          if (pb[c] > pb[argmax]) argmax = c;
        }
        epoch_loss += -std::log(std::max(pb[yi], 1e-12f));
        if (argmax == yi) ++correct;
        ++seen;
        float* gb = g_logits.data() + b * classes;
        for (int64_t c = 0; c < classes; ++c)
          gb[c] = (pb[c] - (c == yi ? 1.f : 0.f)) / batch;
      }
      // ---- backward + momentum SGD ----
      // w2 grad = feat^T @ g_logits ; g_h = g_logits @ w2^T (through relu)
      for (int64_t c = 0; c < classes; ++c) {
        float gb2 = 0.f;
        for (int64_t b = 0; b < batch; ++b)
          gb2 += g_logits[b * classes + c];
        vb2[c] = momentum * vb2[c] + gb2;
        b2[c] -= lr * vb2[c];
      }
      for (int64_t b = 0; b < batch; ++b) {
        const float* feat = hidden > 0 ? h.data() + b * hidden
                                       : x + order[s + b] * d;
        const float* gb = g_logits.data() + b * classes;
        if (hidden > 0) {
          float* ghb = g_h.data() + b * hidden;
          for (int64_t k = 0; k < hidden; ++k) {
            float acc = 0.f;
            for (int64_t c = 0; c < classes; ++c)
              acc += gb[c] * w2[k * classes + c];
            ghb[k] = feat[k] > 0.f ? acc : 0.f;
          }
        }
      }
      for (int64_t k = 0; k < in2; ++k) {
        for (int64_t c = 0; c < classes; ++c) {
          float g = 0.f;
          for (int64_t b = 0; b < batch; ++b) {
            const float* feat = hidden > 0 ? h.data() + b * hidden
                                           : x + order[s + b] * d;
            g += feat[k] * g_logits[b * classes + c];
          }
          float* vp = &vw2[k * classes + c];
          *vp = momentum * (*vp) + g;
          w2[k * classes + c] -= lr * (*vp);
        }
      }
      if (hidden > 0) {
        for (int64_t kk = 0; kk < d; ++kk) {
          for (int64_t j = 0; j < hidden; ++j) {
            float g = 0.f;
            for (int64_t b = 0; b < batch; ++b)
              g += x[order[s + b] * d + kk] * g_h[b * hidden + j];
            float* vp = &vw1[kk * hidden + j];
            *vp = momentum * (*vp) + g;
            w1[kk * hidden + j] -= lr * (*vp);
          }
        }
        for (int64_t j = 0; j < hidden; ++j) {
          float g = 0.f;
          for (int64_t b = 0; b < batch; ++b) g += g_h[b * hidden + j];
          vb1[j] = momentum * vb1[j] + g;
          b1[j] -= lr * vb1[j];
        }
      }
    }
    epoch_loss = seen > 0 ? epoch_loss / seen : 0.f;
    if (progress)
      progress(static_cast<int>(ep), epoch_loss,
               seen > 0 ? static_cast<float>(correct) / seen : 0.f);
  }
  return epoch_loss;
}

// Evaluate: returns accuracy, writes mean loss to *loss_out.
float ft_eval_classifier(const float* x, const int32_t* y, int64_t n,
                         int64_t d, int64_t classes, int64_t hidden,
                         const float* w1, const float* b1, const float* w2,
                         const float* b2, float* loss_out) {
  const int64_t in2 = hidden > 0 ? hidden : d;
  std::vector<float> h(static_cast<size_t>(hidden > 0 ? hidden : 1));
  int64_t correct = 0;
  float loss = 0.f;
  for (int64_t i = 0; i < n; ++i) {
    const float* xi = x + i * d;
    const float* feat;
    if (hidden > 0) {
      for (int64_t j = 0; j < hidden; ++j) {
        float acc = b1[j];
        for (int64_t k = 0; k < d; ++k) acc += xi[k] * w1[k * hidden + j];
        h[j] = acc > 0.f ? acc : 0.f;
      }
      feat = h.data();
    } else {
      feat = xi;
    }
    float mx = -1e30f;
    std::vector<float> lg(static_cast<size_t>(classes));
    for (int64_t c = 0; c < classes; ++c) {
      float acc = b2[c];
      for (int64_t k = 0; k < in2; ++k) acc += feat[k] * w2[k * classes + c];
      lg[c] = acc;
      mx = std::max(mx, acc);
    }
    float z = 0.f;
    for (int64_t c = 0; c < classes; ++c) z += std::exp(lg[c] - mx);
    loss += -(lg[y[i]] - mx - std::log(z));
    int64_t am = 0;
    for (int64_t c = 1; c < classes; ++c)
      if (lg[c] > lg[am]) am = c;
    if (am == y[i]) ++correct;
  }
  if (loss_out) *loss_out = n > 0 ? loss / n : 0.f;
  return n > 0 ? static_cast<float>(correct) / n : 0.f;
}

}  // extern "C"
